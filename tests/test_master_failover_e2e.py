"""Master-failover e2e — kill -9 the LEADER MASTER mid-pass under a live
4-worker fleet (ISSUE 7 acceptance).

The contract: the leader journals every queue transition (fsync'd, CRC
framed) before acking it; a hot standby tails snapshot + journal into a
live replica.  SIGKILL the leader mid-pass — via the ``kill_master`` chaos
point, which fires inside ``task_finished`` BEFORE the transition executes
— and the standby takes over WARM: bounded replay, task leases and result
payloads intact, the in-flight workers ride through the bounce on their
retry/re-discover loops, the pass completes with ZERO recomputed tasks,
and the final parameters are bit-for-bit identical to an uninterrupted
4-worker run and to an N=1 run.

All tests spawn multiple python processes => marked slow (tier-1 runs
`-m "not slow"`; `make chaos` runs this file directly)."""

import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from paddle_tpu.checkpoint import CheckpointManager
from paddle_tpu.io import recordio
from paddle_tpu.master_ha import HAMaster, discover_endpoint
from paddle_tpu.trainer.elastic import NumpyLinearModel

pytestmark = pytest.mark.slow

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DIM = 8
TASKS_PER_PASS = 12  # 96 records / 4 per chunk = 24 chunks at 2/task
PASSES = 2

# one service-kw set shared by every master candidate in a drill: the
# standby must replay the leader's journal into an identically-configured
# replica.  lease_timeout is WIDE on purpose: a scheduling stall on a
# loaded 2-core box must never let the standby steal leadership from a
# HEALTHY leader mid-drill (renew runs every lease_timeout/3) — the
# dual-leader window would re-serve tasks the deposed side already acked
# and break the zero-recompute accounting this drill exists to prove
MASTER_KW = dict(chunks_per_task=2, timeout_s=30.0, worker_timeout_s=10.0,
                 auto_rotate=False, lease_timeout=6.0)


def _write_dataset(path, n=96, seed=0):
    rng = np.random.RandomState(seed)
    w_true = rng.randn(DIM).astype(np.float32)
    recs = []
    for _ in range(n):
        x = rng.randn(DIM).astype(np.float32)
        recs.append(
            np.concatenate([x, [np.float32(x @ w_true)]])
            .astype(np.float32).tobytes()
        )
    recordio.write_records(path, iter(recs), max_chunk_records=4)


def _env():
    # one BLAS thread per spawned process: 6 processes on a small box must
    # not starve the leader's renew thread into a spurious lease expiry
    return dict(
        os.environ, JAX_PLATFORMS="cpu", OMP_NUM_THREADS="1",
        OPENBLAS_NUM_THREADS="1", MKL_NUM_THREADS="1",
        PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH", ""),
    )


def _spawn_workers(d, n):
    return [
        subprocess.Popen(
            [sys.executable, "-m", "paddle_tpu.trainer.elastic",
             "--dir", os.path.join(d, "ha"), "--worker-id", f"w{i}",
             "--num-passes", str(PASSES), "--model", "numpy",
             "--model-arg", f"dim={DIM}", "--model-arg", "lr=0.2",
             "--min-workers", str(n),
             "--checkpoint-dir", os.path.join(d, "ck"),
             "--stats-out", os.path.join(d, "stats-{worker}.json")],
            env=_env(), stdout=subprocess.DEVNULL, stderr=subprocess.PIPE,
        )
        for i in range(n)
    ]


def _collect(d, n, procs):
    # communicate() drains stderr WHILE waiting: a worker riding a long
    # bounce logs a retry line per backoff step, and a never-read PIPE
    # blocks it at ~64KB — wait()-then-read would deadlock the drill
    errs = {}
    rcs = []
    for i, p in enumerate(procs):
        _out, err = p.communicate(timeout=180)
        rcs.append(p.returncode)
        errs[i] = err.decode()[-2000:]
    stats = {}
    for i in range(n):
        p = os.path.join(d, f"stats-w{i}.json")
        if os.path.exists(p):
            with open(p) as f:
                stats[i] = json.load(f)
    restored = CheckpointManager(os.path.join(d, "ck")).restore_latest(
        NumpyLinearModel(DIM).state()
    )
    return rcs, errs, stats, restored


def _run_clean(d, n):
    """Uninterrupted reference fleet against an in-process HA master."""
    os.makedirs(d, exist_ok=True)
    data = os.path.join(d, "data.rio")
    _write_dataset(data)
    ha = HAMaster(os.path.join(d, "ha"), [data], owner_id="ref", **MASTER_KW)
    ha.start()
    assert ha.wait_leader(30)
    try:
        rcs, errs, stats, restored = _collect(d, n, _spawn_workers(d, n))
        master_stats = ha.service.stats() if ha.service else None
    finally:
        ha.stop()
    assert rcs == [0] * n, errs
    return stats, restored, master_stats


def test_kill_leader_mid_pass_warm_takeover_zero_recompute(tmp_path):
    """The headline acceptance drill."""
    # -- references: uninterrupted N=4 and N=1 ---------------------------
    stats4, res4, mst4 = _run_clean(str(tmp_path / "clean4"), 4)
    assert mst4["fail_events"] == 0 and res4 is not None
    stats1, res1, _ = _run_clean(str(tmp_path / "clean1"), 1)

    # -- the drill: subprocess leader armed to die at the 8th ack --------
    d = str(tmp_path / "killed")
    os.makedirs(d)
    data = os.path.join(d, "data.rio")
    _write_dataset(data)
    hadir = os.path.join(d, "ha")
    leader = subprocess.Popen(
        [sys.executable, "-m", "paddle_tpu", "master",
         "--dir", hadir, "--patterns", data,
         "--chunks-per-task", "2", "--timeout-s", "30",
         "--worker-timeout-s", "10", "--lease-timeout", "6",
         "--chaos", "kill_master@8"],
        env=_env(), stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True,
    )
    standby = HAMaster(hadir, [data], owner_id="standby", **MASTER_KW)
    procs = []
    try:
        deadline = time.time() + 60
        while discover_endpoint(hadir) is None:
            assert leader.poll() is None, leader.stdout.read()[-2000:]
            assert time.time() < deadline, "no leader endpoint appeared"
            time.sleep(0.1)
        standby.start()
        # the takeover must be WARM: wait until the standby's replica has
        # loaded the leader's journal-anchored snapshot before any worker
        # does real work
        deadline = time.time() + 20
        while standby._replica is None:
            assert time.time() < deadline, "standby never built a replica"
            time.sleep(0.05)

        procs = _spawn_workers(d, 4)
        t_kill = None
        deadline = time.time() + 120
        while leader.poll() is None:
            assert time.time() < deadline, "kill_master chaos never fired"
            time.sleep(0.01)
        t_kill = time.time()
        assert leader.returncode == -signal.SIGKILL  # chaos killed it hard

        rcs, errs, stats, restored = _collect(d, 4, procs)
        assert rcs == [0, 0, 0, 0], errs  # the fleet rode through the bounce
        assert standby.is_leader.is_set()
        takeover = standby.last_takeover
        t_takeover = takeover["t_leader"] - t_kill
        master_stats = standby.service.stats()
        jdir = os.path.dirname(standby.service.snapshot_path)
        snap = json.load(open(standby.service.snapshot_path))
        jpath = os.path.join(jdir, snap["journal_file"])
    finally:
        standby.stop()
        if leader.poll() is None:
            leader.kill()
        leader.communicate()
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.communicate()

    # -- warm takeover, bounded replay, zero recompute -------------------
    assert takeover["warm"] is True
    assert takeover["replayed_records"] > 0
    assert t_takeover < 30.0  # lease timeout + campaign + replay, not a hang
    # every task of every pass was computed EXACTLY once fleet-wide: the
    # journal preserved finished results AND in-flight leases, so nothing
    # recomputed (a recompute would add an extra accepted ack somewhere)
    total_acks = sum(s["tasks_done"] for s in stats.values())
    assert total_acks == TASKS_PER_PASS * PASSES
    assert master_stats["fail_events"] == 0  # no lease ever expired
    # every pass completed: the queue state matches the uninterrupted
    # run's (the final pass's boundary is deliberately never rotated)
    assert master_stats["pass_id"] == mst4["pass_id"]
    assert master_stats["n_done"] == TASKS_PER_PASS
    assert master_stats["n_todo"] == 0 and master_stats["n_pending"] == 0

    # -- bit-for-bit params vs uninterrupted N=4 and N=1 -----------------
    assert restored is not None
    for ref in (res4, res1):
        assert np.array_equal(restored[1]["w"], ref[1]["w"])
        assert np.array_equal(restored[1]["b"], ref[1]["b"])
    # cost trajectories agree wherever both logged them
    ref_costs = stats4[0]["pass_costs"]
    for i, s in stats.items():
        tail = s["pass_costs"]
        assert tail == ref_costs[len(ref_costs) - len(tail):], f"worker {i}"

    # -- and the surviving journal generation lints clean ----------------
    from paddle_tpu.cli import cmd_lint

    assert cmd_lint(["--journal", jpath]) == 0


def test_cli_master_stats_out_records_takeover(tmp_path):
    """`paddle-tpu master --stats-out`: each leadership assumption appends
    one JSON line with the warm/cold flag, replayed-record count and
    takeover span — the observables the failover bench commits."""
    d = str(tmp_path)
    data = os.path.join(d, "data.rio")
    _write_dataset(data)
    stats_path = os.path.join(d, "master-stats.jsonl")
    proc = subprocess.Popen(
        [sys.executable, "-m", "paddle_tpu", "master",
         "--dir", os.path.join(d, "ha"), "--patterns", data,
         "--chunks-per-task", "2", "--lease-timeout", "1",
         "--stats-out", stats_path],
        env=_env(), stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True,
    )
    try:
        deadline = time.time() + 60
        while not os.path.exists(stats_path):
            assert proc.poll() is None, proc.stdout.read()[-2000:]
            assert time.time() < deadline, "no takeover stats appeared"
            time.sleep(0.1)
        proc.send_signal(signal.SIGTERM)
        out, _ = proc.communicate(timeout=30)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.communicate()
    assert proc.returncode == 0, out[-2000:]
    rec = json.loads(open(stats_path).readline())
    assert rec["warm"] is False  # first leader of a fresh cluster: cold
    assert rec["replayed_records"] == 0
    assert rec["takeover_s"] >= 0 and "t_leader" in rec
