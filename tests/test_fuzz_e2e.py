"""End-to-end `paddle-tpu fuzz` + trace record/replay CLI (subprocess):
the make-chaos batch contract — a clean seeded composition batch, and
the full planted-canary loop: detect (exit 1), shrink to a replayable
spec file, replay from disk and reproduce (exit 0).  Plus the serve
CLI's record->replay loop: a recorded day replays through a fresh
process with the identical per-class status ledger.  Subprocess-level
so the exit-code contracts are what's tested."""

import json
import os
import subprocess
import sys

import pytest

pytestmark = pytest.mark.slow

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _cli(*argv, timeout=600):
    return subprocess.run(
        [sys.executable, "-m", "paddle_tpu", *argv],
        cwd=REPO, capture_output=True, text=True, timeout=timeout,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )


def test_fuzz_clean_seeded_batch():
    p = _cli("fuzz", "--count", "5", "--seed", "0")
    assert p.returncode == 0, (p.stdout, p.stderr)
    assert "clean: 5 compositions" in p.stdout


def test_fuzz_canary_detect_shrink_replay(tmp_path):
    spec_path = str(tmp_path / "ledger_skew.spec.json")
    p = _cli("fuzz", "--count", "10", "--seed", "7",
             "--plant", "ledger_skew", "--out", spec_path)
    assert p.returncode == 1, (p.stdout, p.stderr)
    assert "VIOLATION" in p.stdout
    assert "ledger_sum_mismatch" in p.stdout

    with open(spec_path, encoding="utf-8") as fh:
        spec = json.load(fh)
    assert spec["kind"] == "chaos-fuzz"
    assert spec["planted"] == "ledger_skew"
    # ddmin left only what the planted bug needs (arrival overload)
    assert len(spec["items"]) <= 2, spec["items"]

    r = _cli("fuzz", "--replay", spec_path)
    assert r.returncode == 0, (r.stdout, r.stderr)
    assert "reproduced" in r.stdout


def test_fuzz_replay_of_clean_spec_fails(tmp_path):
    """A spec whose composition no longer violates must exit 1 — the
    regression-test contract's other half."""
    spec_path = str(tmp_path / "clean.spec.json")
    with open(spec_path, "w", encoding="utf-8") as fh:
        json.dump({
            "version": 1, "kind": "chaos-fuzz", "seed": 0, "index": 0,
            "items": [{"axis": "arrival", "process": "uniform",
                       "rate_factor": 0.5}],
            "planted": None, "violations": ["stale"],
        }, fh)
    r = _cli("fuzz", "--replay", spec_path)
    assert r.returncode == 1, (r.stdout, r.stderr)
    assert "did NOT reproduce" in r.stderr


def _serve(*extra, timeout=300):
    return _cli(
        "serve", "--src-vocab", "60", "--trg-vocab", "60",
        "--word-dim", "32", "--hidden-dim", "64", "--max-length", "16",
        *extra, timeout=timeout,
    )


def test_serve_record_then_replay_reproduces_class_ledger(tmp_path):
    """The tentpole loop at the CLI: record a priority-stamped open-loop
    day, replay it through a FRESH process, and get the identical
    per-class status ledger (the recorded identity is authoritative —
    no live flag re-derives it)."""
    trace = str(tmp_path / "day.ptt")
    rec = _serve("--synthetic", "10", "--rate", "40", "--seed", "3",
                 "--priority-every", "4", "--sessions", "3",
                 "--deadline-s", "10", "--record-trace", trace)
    assert rec.returncode == 0, (rec.stdout, rec.stderr)
    rec_summary = json.loads(rec.stdout.strip().splitlines()[-1])
    assert rec_summary["recorded_trace"] == trace
    assert set(rec_summary["classes"]) == {"p0", "p2"}

    # the trace is a valid, byte-stable artifact
    from paddle_tpu.robustness.traces import read_trace

    t = read_trace(trace)
    assert len(t.requests()) == 10
    assert t.serialize().encode() == open(trace, "rb").read()

    rep = _serve("--replay", trace, "--seed", "99")
    assert rep.returncode == 0, (rep.stdout, rep.stderr)
    rep_summary = json.loads(rep.stdout.strip().splitlines()[-1])
    assert rep_summary["replayed_trace"] == trace
    assert rep_summary["classes"] == rec_summary["classes"]
    for k in ("served", "shed", "rejected", "timeout", "unfinished"):
        assert rep_summary[k] == rec_summary[k], (k, rep_summary)
    # replayed per-request ids are the RECORDED ids
    rep_ids = [json.loads(line)["req"]
               for line in rep.stdout.strip().splitlines()[:-1]
               if line.startswith("{")]
    assert sorted(rep_ids) == sorted(r["id"] for r in t.requests())


def test_serve_replay_rejects_torn_trace(tmp_path):
    """A truncated recording must fail loudly, not replay short."""
    trace = str(tmp_path / "torn.ptt")
    rec = _serve("--synthetic", "4", "--rate", "50", "--seed", "1",
                 "--record-trace", trace)
    assert rec.returncode == 0, (rec.stdout, rec.stderr)
    with open(trace) as f:
        lines = f.read().splitlines()
    with open(trace, "w") as f:
        f.write("\n".join(lines[:-1]) + "\n")  # drop the footer
    rep = _serve("--replay", trace)
    assert rep.returncode != 0
    assert "ptt-end" in (rep.stderr + rep.stdout)
