"""End-to-end `paddle-tpu explore` (CLI subprocess): the make-chaos
batch contract — clean seeded batches across every model, and the full
planted-canary loop: detect, shrink to a replayable spec file, replay
from disk and reproduce.  Subprocess-level so the exit-code contract
(0 clean / 1 violation, 0 reproduced / 1 not) is what's tested."""

import json
import os
import subprocess
import sys

import pytest

pytestmark = pytest.mark.slow

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _explore(*argv):
    return subprocess.run(
        [sys.executable, "-m", "paddle_tpu", "explore", *argv],
        cwd=REPO, capture_output=True, text=True, timeout=600,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )


def test_clean_batches_across_models():
    for model, schedules in (("router", "200"), ("ha", "200"),
                             ("master", "60")):
        p = _explore("--model", model, "--schedules", schedules,
                     "--seed", "0", "--dfs-depth", "3")
        assert p.returncode == 0, (model, p.stdout, p.stderr)
        assert "clean" in p.stdout


def test_canary_detect_shrink_replay(tmp_path):
    spec_path = str(tmp_path / "double_serve.spec.json")
    p = _explore("--model", "router", "--schedules", "200", "--seed", "7",
                 "--max-events", "12", "--plant", "double_serve",
                 "--out", spec_path)
    assert p.returncode == 1, (p.stdout, p.stderr)
    assert "VIOLATION" in p.stdout and "double-serve" in p.stdout

    with open(spec_path, encoding="utf-8") as fh:
        spec = json.load(fh)
    assert spec["model"] == "router" and spec["planted"] == "double_serve"
    assert len(spec["events"]) <= 6, spec["events"]

    r = _explore("--replay", spec_path)
    assert r.returncode == 0, (r.stdout, r.stderr)
    assert "reproduced" in r.stdout and "double-serve" in r.stdout


def test_replay_of_stale_spec_fails_loudly(tmp_path):
    # a spec whose bug has since been fixed must NOT silently pass: the
    # replay exits nonzero so a regression suite notices the spec rotted
    spec_path = str(tmp_path / "stale.spec.json")
    with open(spec_path, "w", encoding="utf-8") as fh:
        json.dump({
            "version": 1, "model": "router", "planted": None, "seed": 0,
            "events": [{"op": "submit", "req": "q1"}],
            "violations": ["(fixed long ago)"],
        }, fh)
    r = _explore("--replay", spec_path)
    assert r.returncode == 1, (r.stdout, r.stderr)
    assert "did NOT reproduce" in r.stderr
