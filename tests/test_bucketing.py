"""Length-bucketed batching + token-budget packing + shape-ladder contract.

Covers the feed→compile→scan chain of the bucketing subsystem:
reader.bucketing (bucket assignment, token-budget invariants, epoch
coverage), core.batch ladder rounding / canonicalization, jit-cache
boundedness over a length-skewed epoch (CompileShapeCache), and the pinned
numerics A/B — the same batch padded to two different ladder rungs trains
identically (masked positions contribute zero grad), with the
recurrent_group scan early-exit on and off.
"""

import numpy as np
import pytest

from paddle_tpu.core.batch import (
    DEFAULT_LADDER,
    batch_shape_key,
    canonicalize_batch,
    ladder_len,
    nested_seq,
    seq,
    shape_ladder,
)
from paddle_tpu.reader import bucketing


# ---------------------------------------------------------------------------
# ladder rounding
# ---------------------------------------------------------------------------


def test_ladder_rounding():
    assert shape_ladder(16, 4) == (16, 32, 64, 128)
    assert ladder_len(1) == 16
    assert ladder_len(16) == 16
    assert ladder_len(17) == 32
    assert ladder_len(50) == 64
    assert ladder_len(4096) == 4096
    # past the top rung: next multiple of it, never an error
    assert ladder_len(4097) == 8192
    assert ladder_len(9000, (16, 32)) == 9024


def test_sample_len_default_and_slots():
    s = ([1, 2, 3], [1] * 7, 0)
    assert bucketing.sample_len(s) == 7
    assert bucketing.sample_len(s, slots=(0,)) == 3
    assert bucketing.sample_len((np.zeros((5, 2)), 1)) == 5
    assert bucketing.sample_len(3) == 1


# ---------------------------------------------------------------------------
# token-budget batcher
# ---------------------------------------------------------------------------


def _corpus(n=600, lo=2, hi=120, seed=0):
    rng = np.random.RandomState(seed)
    return [
        ([1] * int(l), int(l) % 2) for l in rng.randint(lo, hi, size=n)
    ]


def test_bucket_assignment_and_budget_invariant():
    budget = 1024
    samples = _corpus()
    rd = bucketing.token_budget_batch(
        lambda: iter(samples), token_budget=budget
    )
    batches = list(rd())
    assert len(batches) > 4
    for b in batches:
        lens = [bucketing.sample_len(s) for s in b]
        rung = ladder_len(max(lens))
        # every sample sits in the bucket of its own rung: the batch's
        # padded extent IS the ladder rung of its longest member
        assert all(ladder_len(l) == rung for l in lens)
        # token budget: padded tokens per step never exceed the budget
        # (a batch of one oversized sample is the only allowed overflow)
        assert len(b) * rung <= budget or len(b) == 1
        cap = bucketing.bucket_batch_size(rung, budget)
        assert len(b) <= cap


def test_full_batches_keep_tokens_per_step_constant():
    budget = 2048
    samples = _corpus(n=2000)
    rd = bucketing.token_budget_batch(
        lambda: iter(samples), token_budget=budget, drop_last=True
    )
    for b in rd():
        rung = ladder_len(max(bucketing.sample_len(s) for s in b))
        # drop_last=True emits only canonical-size batches: padded tokens
        # per step fill at least half the budget at every rung
        assert len(b) == bucketing.bucket_batch_size(rung, budget)
        assert budget // 2 <= len(b) * rung <= budget


def test_epoch_coverage_and_drop_last():
    samples = _corpus(n=333, seed=3)
    key = lambda s: (tuple(s[0]), s[1])
    rd = bucketing.token_budget_batch(lambda: iter(samples), token_budget=512)
    got = sorted(key(s) for b in rd() for s in b)
    assert got == sorted(key(s) for s in samples)  # nothing lost or duplicated

    dropped = bucketing.token_budget_batch(
        lambda: iter(samples), token_budget=512, drop_last=True
    )
    n_dropped = sum(len(b) for b in dropped())
    assert n_dropped <= len(samples)
    for b in dropped():
        rung = ladder_len(max(bucketing.sample_len(s) for s in b))
        assert len(b) == bucketing.bucket_batch_size(rung, 512)


def test_budget_derived_from_batch_size():
    # budget=None derives batch_size x tallest first-window rung — the
    # padded token count the unbucketed feed would have spent per step
    samples = _corpus(n=400, lo=2, hi=100, seed=1)  # max rung = 128
    rd = bucketing.token_budget_batch(
        lambda: iter(samples), batch_size=4, window=400
    )
    batches = list(rd())
    budget = 4 * 128
    for b in batches:
        rung = ladder_len(max(bucketing.sample_len(s) for s in b))
        assert len(b) * rung <= budget or len(b) == 1


def test_derived_budget_pinned_across_passes():
    """The derived token budget is pinned on the first pass: a shuffled
    second pass whose first window happens to hold longer samples must NOT
    re-derive a bigger budget (that would change every rung's canonical
    batch size and recompile every bucket per pass)."""
    short = [([1] * 60, 0)] * 64   # rung 64 -> budget = 8 * 64 = 512
    longer = [([1] * 100, 0)] * 64  # rung 128
    calls = [0]

    def rd():
        calls[0] += 1
        return iter(short if calls[0] == 1 else longer)

    batched = bucketing.token_budget_batch(rd, batch_size=8, window=64)
    pass1 = list(batched())
    pass2 = list(batched())
    assert all(len(b) == 8 for b in pass1)  # 512 // 64
    # pass 2's rung-128 batches use the PINNED 512 budget: 512 // 128 = 4
    assert all(len(b) == 4 for b in pass2), [len(b) for b in pass2]


def test_feeder_ladders_nested_s_axis():
    """With a ladder, the nested-sequence S axis is a laddered compiled
    extent too (canonicalize_batch and the feeder must agree)."""
    from paddle_tpu.core.data_types import integer_value_sub_sequence
    from paddle_tpu.reader.feeder import DataFeeder

    f = DataFeeder(
        [("z", integer_value_sub_sequence(10))], ladder=DEFAULT_LADDER
    )
    out = f([([[1, 2], [3]] * 3,)])  # 6 subsequences, max sub len 2
    # S on the shallow sub-ladder (rung 8), T on the time ladder (rung 16)
    assert out["z"].data.shape == (1, 8, 16)
    assert out["z"].sub_lengths.shape == (1, 8)
    plain = DataFeeder([("z", integer_value_sub_sequence(10))])
    assert plain([([[1, 2], [3]] * 3,)])["z"].data.shape == (1, 8, 8)


def test_sort_within_window():
    samples = _corpus(n=64, seed=5)
    rd = bucketing.sort_within_window(lambda: iter(samples), window=32)
    out = list(rd())
    assert sorted(
        (tuple(s[0]), s[1]) for s in out
    ) == sorted((tuple(s[0]), s[1]) for s in samples)
    lens = [bucketing.sample_len(s) for s in out]
    assert lens[:32] == sorted(lens[:32])
    assert lens[32:] == sorted(lens[32:])


def test_batcher_requires_budget_or_batch_size():
    with pytest.raises(ValueError):
        bucketing.token_budget_batch(lambda: iter([]))


# ---------------------------------------------------------------------------
# canonicalization + shape keys
# ---------------------------------------------------------------------------


def test_canonicalize_batch_rounds_to_ladder():
    b = {
        "x": seq(np.zeros((4, 50, 3), np.float32), [3, 50, 20, 7]),
        "y": seq(np.zeros((4, 20), np.int32), [3, 20, 11, 7]),
        "z": nested_seq(
            np.zeros((4, 5, 9, 2), np.float32),
            [2, 5, 1, 3],
            np.ones((4, 5), np.int32),
        ),
    }
    c = canonicalize_batch(b)
    assert c["x"].data.shape == (4, 64, 3)
    assert c["y"].data.shape == (4, 32)
    # S rounds on the shallow 4-based sub-ladder, T on the time ladder
    assert c["z"].data.shape == (4, 8, 16, 2)
    # sub_lengths track the padded S axis so the nested SeqTensor stays
    # internally consistent — its joint mask must still evaluate
    assert c["z"].sub_lengths.shape == (4, 8)
    assert c["z"].sub_mask().shape == (4, 8, 16)
    np.testing.assert_array_equal(np.asarray(c["x"].lengths), b["x"].lengths)
    # already-canonical batches pass through shape-identical
    c2 = canonicalize_batch(c)
    assert batch_shape_key(c2) == batch_shape_key(c)


def test_batch_shape_key_ignores_values_tracks_shapes():
    a = {"x": seq(np.zeros((2, 16), np.int32), [3, 4])}
    b = {"x": seq(np.ones((2, 16), np.int32), [9, 1])}
    c = {"x": seq(np.zeros((2, 32), np.int32), [3, 4])}
    assert batch_shape_key(a) == batch_shape_key(b)
    assert batch_shape_key(a) != batch_shape_key(c)


def test_jit_cache_bounded_over_skewed_epoch():
    """A length-skewed epoch through bucketing + laddered feeder produces at
    most one distinct batch shape per ladder rung (the contract the compile
    counter enforces); full batches alone stay within the ladder size."""
    from paddle_tpu.core.compiler import CompileShapeCache
    from paddle_tpu.core.data_types import integer_value_sequence, integer_value
    from paddle_tpu.reader.feeder import DataFeeder
    from paddle_tpu.utils.timers import StatSet

    rng = np.random.RandomState(0)
    # heavily skewed: most samples short, a long tail (zipf-ish)
    lens = np.minimum(2 + (rng.zipf(1.5, size=1500) % 120), 120)
    samples = [([1] * int(l), int(l) % 2) for l in lens]
    budget = 512
    rd = bucketing.token_budget_batch(
        lambda: iter(samples), token_budget=budget, drop_last=True
    )
    feeder = DataFeeder(
        [("w", integer_value_sequence(10)), ("lbl", integer_value(2))],
        ladder=DEFAULT_LADDER,
    )
    stats = StatSet()
    cache = CompileShapeCache("test_step", stats=stats)
    n_batches = 0
    for raw in rd():
        cache.observe(feeder(raw))
        n_batches += 1
    assert n_batches > 10
    n_rungs = len([r for r in DEFAULT_LADDER if r <= 128])
    assert cache.misses <= n_rungs, cache.shapes
    assert cache.hits == n_batches - cache.misses
    assert stats.count("test_step/compile_miss") == cache.misses
    assert stats.count("test_step/compile_hit") == cache.hits


# ---------------------------------------------------------------------------
# numerics: pinned A/B across paddings + scan early-exit
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def small_seq2seq():
    import jax

    from paddle_tpu.core.compiler import CompiledNetwork
    from paddle_tpu.core.topology import Topology, reset_auto_names
    from paddle_tpu.models.seq2seq import seq2seq_cost

    reset_auto_names()
    cost, _ = seq2seq_cost(40, 40, word_dim=8, hidden_dim=8)
    net = CompiledNetwork(Topology([cost]))
    params, state = net.init(jax.random.PRNGKey(0))
    return net, params, state


def _nmt_batch(T, lens=(3, 9, 5, 7)):
    import jax.numpy as jnp

    from paddle_tpu.core.batch import SeqTensor

    lens = np.asarray(lens, np.int32)
    out = {}
    for k, name in enumerate(("src_word", "trg_word", "trg_next")):
        r = np.random.RandomState(42 + k)
        arr = np.zeros((len(lens), T), np.int32)
        for i, l in enumerate(lens):
            arr[i, :l] = r.randint(1, 40, size=l)
        out[name] = SeqTensor(jnp.asarray(arr), jnp.asarray(lens))
    return out


def _train_once(net, params, state, batch, *, key=11):
    import jax
    import jax.numpy as jnp

    import paddle_tpu as paddle
    from paddle_tpu.trainer.step import make_train_step

    opt = paddle.optimizer.Momentum(learning_rate=0.1, momentum=0.9)
    step = make_train_step(net, opt, mesh=None)
    p = jax.tree_util.tree_map(jnp.array, params)  # copies: step donates
    s = jax.tree_util.tree_map(jnp.array, state)
    p2, _, _, m = step(p, s, opt.init(p), batch, jax.random.PRNGKey(key))
    return float(m["cost"]), p2


def test_numerics_pinned_ab_bucketed_vs_unbucketed(small_seq2seq):
    """The SAME batch padded to two different ladder rungs (the bucketed
    shape vs the global-max shape) yields the same cost and the same updated
    parameters: masked positions contribute zero grad, so bucketing changes
    shapes, never numbers."""
    import jax

    net, params, state = small_seq2seq
    c16, p16 = _train_once(net, params, state, _nmt_batch(16))
    c32, p32 = _train_once(net, params, state, _nmt_batch(32))
    assert np.isfinite(c16)
    assert abs(c16 - c32) < 1e-5
    for a, b in zip(
        jax.tree_util.tree_leaves(p16), jax.tree_util.tree_leaves(p32)
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6
        )


def test_scan_early_exit_matches_full_scan(small_seq2seq):
    """Dead trailing steps skipped by the lax.cond early-exit produce the
    same training step as the full masked scan (flag off)."""
    import jax

    from paddle_tpu.utils.flags import reset_flags, set_flag

    net, params, state = small_seq2seq
    try:
        set_flag("scan_early_exit", True)
        c_on, p_on = _train_once(net, params, state, _nmt_batch(32))
        set_flag("scan_early_exit", False)
        c_off, p_off = _train_once(net, params, state, _nmt_batch(32))
    finally:
        reset_flags()
    assert abs(c_on - c_off) < 1e-5
    for a, b in zip(
        jax.tree_util.tree_leaves(p_on), jax.tree_util.tree_leaves(p_off)
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6
        )


def test_make_bucketed_train_step_canonicalizes_and_counts(small_seq2seq):
    """Two ragged paddings of the same rung dispatch ONE compiled shape
    through make_bucketed_train_step, and the cache says so."""
    import jax
    import jax.numpy as jnp

    import paddle_tpu as paddle
    from paddle_tpu.trainer.step import make_bucketed_train_step

    net, params, state = small_seq2seq
    opt = paddle.optimizer.Momentum(learning_rate=0.1, momentum=0.9)
    step, cache = make_bucketed_train_step(net, opt, mesh=None)
    costs = []
    for T in (20, 30, 25):  # all round to rung 32
        p = jax.tree_util.tree_map(jnp.array, params)
        s = jax.tree_util.tree_map(jnp.array, state)
        _, _, _, m = step(
            p, s, opt.init(p), _nmt_batch(T), jax.random.PRNGKey(0)
        )
        costs.append(float(m["cost"]))
    assert cache.n_shapes == 1
    assert cache.misses == 1 and cache.hits == 2
    assert abs(costs[0] - costs[1]) < 1e-5 and abs(costs[1] - costs[2]) < 1e-5


# ---------------------------------------------------------------------------
# flag plumbing
# ---------------------------------------------------------------------------


def test_make_batched_reader_flag_routing(monkeypatch):
    """v1 configs opt into bucketing via the use_bucketing flag alone: the
    CLI's batch reader routes through token_budget_batch, budget from the
    bucketing_token_budget flag."""
    import paddle_tpu.v1_compat as v1
    from paddle_tpu.utils.flags import reset_flags, set_flag

    samples = _corpus(n=100, seed=7)
    monkeypatch.setattr(
        v1, "make_config_reader",
        lambda parsed, d, train=True: lambda: iter(samples),
    )
    try:
        plain = list(v1.make_batched_reader(None, ".", 4)())
        assert all(len(b) == 4 for b in plain[:-1])  # paddle.batch semantics

        set_flag("use_bucketing", True)
        set_flag("bucketing_token_budget", 256)
        bucketed = list(v1.make_batched_reader(None, ".", 4)())
        assert sum(len(b) for b in bucketed) == len(samples)
        for b in bucketed:
            rung = ladder_len(max(bucketing.sample_len(s) for s in b))
            assert all(
                ladder_len(bucketing.sample_len(s)) == rung for s in b
            )
            assert len(b) * rung <= 256 or len(b) == 1
    finally:
        reset_flags()


def test_use_bucketing_flag_ladders_the_sgd_feeder():
    from paddle_tpu.utils.flags import reset_flags, set_flag

    try:
        set_flag("use_bucketing", True)
        from paddle_tpu.core.data_types import integer_value_sequence
        from paddle_tpu.reader.feeder import DataFeeder

        # the SGD feeder path reads the flag; check the feeder-level effect
        f = DataFeeder(
            [("w", integer_value_sequence(10))], ladder=DEFAULT_LADDER
        )
        out = f([([1] * 50,)])
        assert out["w"].data.shape == (1, 64)
    finally:
        reset_flags()
