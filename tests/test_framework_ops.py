"""Op-framework tests (reference models: python/paddle/v2/framework/tests/
op_test_util.py per-op numpy compare, test_net.py, backward_test.cc,
test_recurrent_op.py, gradient_checker.py)."""

import numpy as np
import pytest

from paddle_tpu import framework as fw


def _scope_with(**arrays):
    scope = fw.Scope()
    for k, v in arrays.items():
        scope.new_var(k).set(np.asarray(v, np.float32))
    return scope


# ---------------------------------------------------------------------------
# per-op numpy parity (op_test_util.OpTestMeta style)
# ---------------------------------------------------------------------------

def test_add_two_op():
    x = np.random.RandomState(0).randn(3, 4).astype(np.float32)
    y = np.random.RandomState(1).randn(3, 4).astype(np.float32)
    scope = _scope_with(x=x, y=y)
    op = fw.create_op("add", X="x", Y="y", Out="out")
    op.infer_shape(scope)
    assert scope.get_var("out").shape == (3, 4)
    op.run(scope)
    np.testing.assert_allclose(scope.get_var("out").get(), x + y, rtol=1e-6)


def test_mul_op():
    x = np.random.RandomState(2).randn(3, 5).astype(np.float32)
    y = np.random.RandomState(3).randn(5, 2).astype(np.float32)
    scope = _scope_with(x=x, y=y)
    fw.create_op("mul", X="x", Y="y", Out="out").run(scope)
    np.testing.assert_allclose(scope.get_var("out").get(), x @ y, rtol=1e-5)


def test_rowwise_add_sigmoid_softmax_mean_scale():
    x = np.random.RandomState(4).randn(4, 6).astype(np.float32)
    b = np.random.RandomState(5).randn(6).astype(np.float32)
    scope = _scope_with(x=x, b=b)
    fw.create_op("rowwise_add", X="x", b="b", Out="r").run(scope)
    np.testing.assert_allclose(scope.get_var("r").get(), x + b, rtol=1e-6)
    fw.create_op("sigmoid", X="r", Y="s").run(scope)
    np.testing.assert_allclose(
        scope.get_var("s").get(), 1 / (1 + np.exp(-(x + b))), rtol=1e-5
    )
    fw.create_op("softmax", X="x", Y="sm").run(scope)
    e = np.exp(x - x.max(1, keepdims=True))
    np.testing.assert_allclose(
        scope.get_var("sm").get(), e / e.sum(1, keepdims=True), rtol=1e-5
    )
    fw.create_op("mean", X="x", Out="m").run(scope)
    np.testing.assert_allclose(scope.get_var("m").get(), x.mean(), rtol=1e-6)
    fw.create_op("scale", X="x", Out="sc", scale=2.5).run(scope)
    np.testing.assert_allclose(scope.get_var("sc").get(), 2.5 * x, rtol=1e-6)


def test_cross_entropy_and_sgd():
    probs = np.array([[0.2, 0.8], [0.9, 0.1]], np.float32)
    labels = np.array([1, 0], np.int32)
    scope = _scope_with(x=probs)
    scope.new_var("lab").set(labels)
    fw.create_op("onehot_cross_entropy", X="x", label="lab", Y="ce").run(scope)
    np.testing.assert_allclose(
        scope.get_var("ce").get(), -np.log([0.8, 0.9]), rtol=1e-5
    )
    p = np.ones((2, 2), np.float32)
    g = np.full((2, 2), 0.5, np.float32)
    scope2 = _scope_with(p=p, g=g)
    fw.create_op(
        "sgd", param="p", grad="g", param_out="p2", learning_rate=0.1
    ).run(scope2)
    np.testing.assert_allclose(scope2.get_var("p2").get(), p - 0.05, rtol=1e-6)


# ---------------------------------------------------------------------------
# scope semantics (scope_test.cc)
# ---------------------------------------------------------------------------

def test_scope_hierarchy():
    parent = fw.Scope()
    parent.new_var("a").set(np.zeros(2))
    child = parent.new_scope()
    assert child.find_var("a") is parent.vars["a"]
    child.new_var("a").set(np.ones(2))  # shadowing
    np.testing.assert_allclose(child.find_var("a").get(), 1.0)
    np.testing.assert_allclose(parent.find_var("a").get(), 0.0)
    assert child.find_var("missing") is None
    with pytest.raises(KeyError):
        child.get_var("missing")


# ---------------------------------------------------------------------------
# NetOp: composition + single-program lowering (net_op_test.cc, fc_op.cc)
# ---------------------------------------------------------------------------

def test_net_external_io_dedup():
    net = fw.NetOp()
    net.add_op(fw.create_op("mul", X="x", Y="w", Out="h"))
    net.add_op(fw.create_op("add", X="h", Y="h", Out="h2"))
    net.complete_add_op()
    assert net.external_inputs == ["x", "w"]  # h is internal
    assert "h" in net.external_outputs and "h2" in net.external_outputs


def test_fc_net_matches_numpy():
    rng = np.random.RandomState(7)
    x = rng.randn(4, 3).astype(np.float32)
    w = rng.randn(3, 5).astype(np.float32)
    b = rng.randn(5).astype(np.float32)
    from paddle_tpu.framework.net import fc_net

    net = fc_net("x", "w", "b", "out")
    scope = _scope_with(x=x, w=w, b=b)
    net.run(scope)
    want = 1 / (1 + np.exp(-(x @ w + b)))
    np.testing.assert_allclose(scope.get_var("out").get(), want, rtol=1e-5)


def test_lowered_net_is_single_callable():
    from paddle_tpu.framework.net import fc_net

    net = fc_net("x", "w", None, "out")
    fn = net.lower()
    rng = np.random.RandomState(8)
    x, w = rng.randn(2, 3).astype(np.float32), rng.randn(3, 4).astype(np.float32)
    outs = fn(x, w)
    assert len(outs) == len(net.external_outputs)


# ---------------------------------------------------------------------------
# Backward (backward_test.cc: grad net with @GRAD names)
# ---------------------------------------------------------------------------

def test_backward_names_and_values():
    op = fw.create_op("mul", X="x", Y="w", Out="out")
    bwd = fw.Backward(op)
    assert bwd.output_names() == ["x@GRAD", "w@GRAD"]
    rng = np.random.RandomState(9)
    x = rng.randn(3, 4).astype(np.float32)
    w = rng.randn(4, 2).astype(np.float32)
    og = rng.randn(3, 2).astype(np.float32)
    scope = _scope_with(x=x, w=w)
    op.run(scope)
    scope.new_var("out@GRAD").set(og)
    bwd.run(scope)
    np.testing.assert_allclose(scope.get_var("x@GRAD").get(), og @ w.T, rtol=1e-4)
    np.testing.assert_allclose(scope.get_var("w@GRAD").get(), x.T @ og, rtol=1e-4)


def test_backward_no_grad_set():
    op = fw.create_op("mul", X="x", Y="w", Out="out")
    bwd = fw.Backward(op, no_grad_set={"w"})
    assert bwd.output_names() == ["x@GRAD"]


def test_backward_of_net():
    from paddle_tpu.framework.net import fc_net

    net = fc_net("x", "w", "b", "out")
    rng = np.random.RandomState(10)
    inputs = {
        "x": rng.randn(3, 4).astype(np.float32),
        "w": rng.randn(4, 2).astype(np.float32),
        "b": rng.randn(2).astype(np.float32),
    }
    fw.check_gradients(net, inputs)


# ---------------------------------------------------------------------------
# gradient checker on individual ops
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("op_type", ["sigmoid", "softmax"])
def test_unary_gradients(op_type):
    op = fw.create_op(op_type, X="x", Y="y")
    x = np.random.RandomState(11).randn(3, 5).astype(np.float32)
    fw.check_gradients(op, {"x": x})


def test_mul_gradients():
    op = fw.create_op("mul", X="x", Y="y", Out="o")
    rng = np.random.RandomState(12)
    fw.check_gradients(
        op,
        {"x": rng.randn(3, 4).astype(np.float32),
         "y": rng.randn(4, 2).astype(np.float32)},
    )


# ---------------------------------------------------------------------------
# RecurrentOp (test_recurrent_op.py)
# ---------------------------------------------------------------------------

def test_recurrent_op_matches_loop():
    """h_t = sigmoid(x_t @ W + h_{t-1} @ U) — compare against a python loop."""
    T, B, D = 5, 2, 3
    rng = np.random.RandomState(13)
    x = rng.randn(T, B, D).astype(np.float32)
    W = rng.randn(D, D).astype(np.float32)
    U = rng.randn(D, D).astype(np.float32)
    h0 = np.zeros((B, D), np.float32)

    step = fw.NetOp()
    step.add_op(fw.create_op("mul", X="x_t", Y="W", Out="xw"))
    step.add_op(fw.create_op("mul", X="h_pre", Y="U", Out="hu"))
    step.add_op(fw.create_op("add", X="xw", Y="hu", Out="pre_act"))
    step.add_op(fw.create_op("sigmoid", X="pre_act", Y="h"))
    step.complete_add_op()

    rnn = fw.RecurrentOp(
        step_net=step,
        inlinks={"x": "x_t"},
        outlinks=["h"],
        memories=[("h_pre", "h", "h0")],
    )
    assert set(rnn.input_names()) == {"x", "h0", "W", "U"}
    scope = _scope_with(x=x, W=W, U=U, h0=h0)
    rnn.run(scope)
    got = scope.get_var("h").get()
    assert got.shape == (T, B, D)

    h = h0
    for t in range(T):
        h = 1 / (1 + np.exp(-(x[t] @ W + h @ U)))
        np.testing.assert_allclose(got[t], h, rtol=1e-4, atol=1e-5)


def test_registry_lists_ops():
    types = fw.OpRegistry.op_types()
    for t in ("add", "mul", "softmax", "sgd", "onehot_cross_entropy",
              "fill_zeros_like", "rowwise_add", "mean", "sigmoid", "scale"):
        assert t in types
    with pytest.raises(KeyError):
        fw.OpRegistry.get("nope")
