"""Optimizer numeric goldens — the reference's test_TrainingAlgorithm.cpp
discipline (math/tests: every fused TrainingAlgorithmOp.cu kernel compared
against the straightforward OriginalOptimizerApi.h implementation).  Each
optimizer's multi-step trajectory is checked against an independent numpy
transcription of the v1 formulas (FirstOrderOptimizer.h:23-331), including
LR schedules, clipping, and L1/L2 regularization."""

import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu.optimizer as O

D = 6
STEPS = 5


def _run(opt, grads_seq, p0):
    params = {"layer": {"w": jnp.asarray(p0)}}
    state = opt.init(params)
    traj = []
    for g in grads_seq:
        params, state = opt.update({"layer": {"w": jnp.asarray(g)}}, state, params)
        traj.append(np.asarray(params["layer"]["w"]))
    return traj


def _data(seed=0):
    rng = np.random.RandomState(seed)
    p0 = rng.randn(D).astype(np.float32)
    grads = [rng.randn(D).astype(np.float32) for _ in range(STEPS)]
    return p0, grads


def test_momentum_matches_numpy():
    p0, grads = _data()
    lr, mom = 0.1, 0.9
    traj = _run(O.Momentum(learning_rate=lr, momentum=mom), grads, p0)
    p, m = p0.copy(), np.zeros(D, np.float32)
    for g, got in zip(grads, traj):
        m = mom * m - lr * g
        p = p + m
        np.testing.assert_allclose(got, p, rtol=1e-5, atol=1e-6)


def test_nesterov_momentum_matches_numpy():
    p0, grads = _data(1)
    lr, mom = 0.05, 0.8
    traj = _run(
        O.Momentum(learning_rate=lr, momentum=mom, nesterov=True), grads, p0
    )
    p, m = p0.copy(), np.zeros(D, np.float32)
    for g, got in zip(grads, traj):
        m = mom * m - lr * g
        p = p + mom * m - lr * g
        np.testing.assert_allclose(got, p, rtol=1e-5, atol=1e-6)


def test_adagrad_matches_numpy():
    p0, grads = _data(2)
    lr, eps = 0.1, 1e-6
    traj = _run(O.AdaGrad(learning_rate=lr, epsilon=eps), grads, p0)
    p, acc = p0.copy(), np.zeros(D, np.float32)
    for g, got in zip(grads, traj):
        acc = acc + g * g
        p = p - lr * g / (np.sqrt(acc) + eps)
        np.testing.assert_allclose(got, p, rtol=1e-5, atol=1e-6)


def test_decayed_adagrad_matches_numpy():
    p0, grads = _data(3)
    lr, rho, eps = 0.1, 0.9, 1e-6
    traj = _run(
        O.DecayedAdaGrad(learning_rate=lr, rho=rho, epsilon=eps), grads, p0
    )
    p, acc = p0.copy(), np.zeros(D, np.float32)
    for g, got in zip(grads, traj):
        acc = rho * acc + (1 - rho) * g * g
        p = p - lr * g / np.sqrt(acc + eps)
        np.testing.assert_allclose(got, p, rtol=1e-5, atol=1e-6)


def test_adadelta_matches_numpy():
    p0, grads = _data(4)
    lr, rho, eps = 1.0, 0.95, 1e-6
    traj = _run(O.AdaDelta(learning_rate=lr, rho=rho, epsilon=eps), grads, p0)
    p = p0.copy()
    eg = np.zeros(D, np.float32)
    ex = np.zeros(D, np.float32)
    for g, got in zip(grads, traj):
        eg = rho * eg + (1 - rho) * g * g
        dx = -np.sqrt((ex + eps) / (eg + eps)) * g
        ex = rho * ex + (1 - rho) * dx * dx
        p = p + lr * dx
        np.testing.assert_allclose(got, p, rtol=1e-5, atol=1e-6)


def test_rmsprop_centered_matches_numpy():
    p0, grads = _data(5)
    lr, rho, eps = 0.01, 0.9, 1e-6
    traj = _run(O.RMSProp(learning_rate=lr, rho=rho, epsilon=eps), grads, p0)
    p = p0.copy()
    ms = np.zeros(D, np.float32)
    mg = np.zeros(D, np.float32)
    for g, got in zip(grads, traj):
        ms = rho * ms + (1 - rho) * g * g
        mg = rho * mg + (1 - rho) * g
        p = p - lr * g / np.sqrt(ms - mg * mg + eps)
        np.testing.assert_allclose(got, p, rtol=1e-5, atol=1e-6)


def test_adam_matches_numpy():
    p0, grads = _data(6)
    lr, b1, b2, eps = 0.01, 0.9, 0.999, 1e-8
    traj = _run(
        O.Adam(learning_rate=lr, beta1=b1, beta2=b2, epsilon=eps), grads, p0
    )
    p = p0.copy()
    m = np.zeros(D, np.float32)
    v = np.zeros(D, np.float32)
    for t, (g, got) in enumerate(zip(grads, traj), start=1):
        m = b1 * m + (1 - b1) * g
        v = b2 * v + (1 - b2) * g * g
        mhat = m / (1 - b1**t)
        vhat = v / (1 - b2**t)
        p = p - lr * mhat / (np.sqrt(vhat) + eps)
        np.testing.assert_allclose(got, p, rtol=1e-5, atol=1e-6)


def test_adamax_matches_numpy():
    p0, grads = _data(7)
    lr, b1, b2 = 0.01, 0.9, 0.999
    traj = _run(O.AdaMax(learning_rate=lr, beta1=b1, beta2=b2), grads, p0)
    p = p0.copy()
    m = np.zeros(D, np.float32)
    u = np.zeros(D, np.float32)
    for t, (g, got) in enumerate(zip(grads, traj), start=1):
        m = b1 * m + (1 - b1) * g
        u = np.maximum(b2 * u, np.abs(g))
        p = p - (lr / (1 - b1**t)) * m / (u + 1e-12)
        np.testing.assert_allclose(got, p, rtol=1e-5, atol=1e-6)


def test_l2_clip_and_l1_composition():
    """Pipeline order (reference TrainerInternal update path): clip grad ->
    fold L2 into grad -> rule -> proximal L1 shrink."""
    p0, grads = _data(8)
    lr, clip, l2, l1 = 0.1, 0.5, 0.01, 0.02
    opt = O.Momentum(
        learning_rate=lr,
        gradient_clipping_threshold=clip,
        regularization=O.L2Regularization(l2),
    )
    traj = _run(opt, grads, p0)
    p = p0.copy()
    for g, got in zip(grads, traj):
        g = np.clip(g, -clip, clip) + l2 * p
        p = p - lr * g
        np.testing.assert_allclose(got, p, rtol=1e-5, atol=1e-6)

    opt = O.Momentum(learning_rate=lr, regularization=O.L1Regularization(l1))
    traj = _run(opt, grads, p0)
    p = p0.copy()
    for g, got in zip(grads, traj):
        p = p - lr * g
        p = np.sign(p) * np.maximum(np.abs(p) - lr * l1, 0.0)
        np.testing.assert_allclose(got, p, rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize(
    "schedule,a,b,expect",
    [
        ("poly", 0.1, 0.5, lambda t: (1 + 0.1 * t) ** -0.5),
        ("exp", 0.5, 2.0, lambda t: 0.5 ** (t / 2.0)),
        ("discexp", 0.5, 2.0, lambda t: 0.5 ** np.floor(t / 2.0)),
        ("linear", 0.1, 0.2, lambda t: max(1.0 - 0.1 * t, 0.2)),
    ],
)
def test_lr_schedules_scale_plain_sgd(schedule, a, b, expect):
    p0, grads = _data(9)
    lr = 0.1
    opt = O.Momentum(
        learning_rate=lr,
        learning_rate_schedule=schedule,
        learning_rate_decay_a=a,
        learning_rate_decay_b=b,
    )
    traj = _run(opt, grads, p0)
    p = p0.copy()
    for t, (g, got) in enumerate(zip(grads, traj)):
        p = p - lr * expect(t) * g
        np.testing.assert_allclose(got, p, rtol=1e-5, atol=1e-6)


def _manual_golden(num, segments, rates):
    """ManualLRS::calc transcription (LearningRateScheduler.cpp): first
    segment with num <= segments[i] selects rates[i]; past the last
    boundary the last rate holds."""
    for seg, rate in zip(segments, rates):
        if num <= seg:
            return rate
    return rates[-1]


def test_manual_schedule_matches_reference_formula():
    """'manual': learning_rate_args boundaries count SAMPLES processed
    (numSamplesProcessed = step * batch); samples_per_step converts."""
    p0, grads = _data(11)
    lr, batch = 0.1, 100
    opt = O.Momentum(
        learning_rate=lr,
        learning_rate_schedule="manual",
        learning_rate_args="150:1.0,250:0.5,300:0.25",
        samples_per_step=batch,
    )
    traj = _run(opt, grads, p0)
    p = p0.copy()
    segs, rates = [150, 250, 300], [1.0, 0.5, 0.25]
    for t, (g, got) in enumerate(zip(grads, traj)):
        # the reference bumps numSamplesProcessed before the rate lookup, so
        # update t sees (t+1)*batch samples (ParameterUpdater.h)
        mult = _manual_golden((t + 1) * batch, segs, rates)
        p = p - lr * mult * g
        np.testing.assert_allclose(got, p, rtol=1e-5, atol=1e-6)
    # boundary semantics: num == segment stays in that segment (ManualLRS
    # uses num <= segments_[i])
    assert _manual_golden(150, segs, rates) == 1.0
    assert _manual_golden(151, segs, rates) == 0.5


def test_pass_manual_schedule_reads_pass_counter():
    """'pass_manual': boundaries count PASSES (PassManualLRS::calc(pass));
    the trainer publishes the pass index into opt_state['pass']."""
    import jax.numpy as jnp

    p0, grads = _data(12)
    lr = 0.1
    opt = O.Momentum(
        learning_rate=lr,
        learning_rate_schedule="pass_manual",
        learning_rate_args="1:1.0,3:0.1",
    )
    params = {"layer": {"w": jnp.asarray(p0)}}
    state = opt.init(params)
    assert "pass" in state  # the trainer's publication point
    p = p0.copy()
    for pass_id, g in enumerate(grads):
        state = {**state, "pass": jnp.asarray(pass_id, jnp.int32)}
        params, state = opt.update(
            {"layer": {"w": jnp.asarray(g)}}, state, params
        )
        mult = _manual_golden(pass_id, [1, 3], [1.0, 0.1])
        p = p - lr * mult * g
        np.testing.assert_allclose(
            np.asarray(params["layer"]["w"]), p, rtol=1e-5, atol=1e-6
        )


def test_v1_config_with_manual_schedules_trains(tmp_path):
    """A v1 config file using learning_rate_schedule='pass_manual' parses
    and trains through the v2 trainer, with the LR actually dropping at the
    declared pass boundary."""
    import paddle_tpu as paddle
    from paddle_tpu.v1_compat import make_optimizer, parse_config

    cfg = tmp_path / "conf.py"
    cfg.write_text(
        "from paddle.trainer_config_helpers import *\n"
        "settings(batch_size=4, learning_rate=0.5,\n"
        "         learning_rate_schedule='pass_manual',\n"
        "         learning_rate_args='0:1.0,1:0.01',\n"
        "         learning_method=MomentumOptimizer(momentum=0.0))\n"
        "x = data_layer(name='x', size=4)\n"
        "y = fc_layer(input=x, size=1, act=LinearActivation())\n"
        "lbl = data_layer(name='lbl', size=1)\n"
        "outputs(regression_cost(input=y, label=lbl))\n"
    )
    p = parse_config(str(cfg))
    assert p.settings.learning_rate_schedule == "pass_manual"
    opt = make_optimizer(p.settings)
    assert opt.schedule_unit == "pass"

    rng = np.random.RandomState(0)
    xs = rng.randn(16, 4).astype(np.float32)
    ys = (xs @ np.array([1.0, -1.0, 0.5, 0.0], np.float32))[:, None]
    reader = lambda: iter([(x, y) for x, y in zip(xs, ys)])

    params = paddle.parameters.create(p.topology)
    trainer = paddle.trainer.SGD(
        cost=p.topology, parameters=params, update_equation=opt
    )
    before = {}
    deltas = {}

    def handler(e):
        if isinstance(e, paddle.event.BeginPass):
            before[e.pass_id] = np.array(
                trainer.parameters.params["__fc_layer_0__"]["w0"]
            )
        elif isinstance(e, paddle.event.EndPass):
            after = np.array(trainer.parameters.params["__fc_layer_0__"]["w0"])
            deltas[e.pass_id] = float(np.abs(after - before[e.pass_id]).max())

    trainer.train(
        reader=paddle.batch(reader, 4), num_passes=3, event_handler=handler,
        async_load_data=False,
    )
    # passes 0 and 1 run at multiplier 1.0; pass 2 is past the last boundary
    # (pass_manual '0:1.0,1:0.01' => pass>=2 uses 0.01): updates shrink ~100x
    assert deltas[2] < 0.2 * deltas[0], deltas
