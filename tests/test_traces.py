"""Request-trace record/replay (robustness/traces.py) + the chaos-fuzz
spec plumbing (robustness/fuzz.py pure logic — no engine).

The load-bearing guarantees pinned here:

* a recorded trace round-trips BYTE-IDENTICALLY (serialize == the file),
  so a committed trace is a stable artifact, not a moving target;
* torn / truncated / corrupted trace files are REJECTED with a
  structured ``TraceError`` naming the defect — a crashed recorder can
  never feed a silently-short workload to a drift gate;
* arrival statistics survive the round-trip: a trace recorded from each
  arrival process reconstructs that process's rate and CV signature;
* replay reproduces the recorded identity — ids, deadlines, sessions,
  priority classes — and therefore the router's rendezvous affinity
  targets, even when the replayed requests pass through a LIVE loadgen
  configured differently (the stamp-if-absent contract);
* fuzz composition sampling is seed-deterministic and ddmin shrinking
  minimizes (the `paddle-tpu fuzz` replay contract's foundations).
"""

import random

import pytest

from paddle_tpu.reader.loadgen import OpenLoopLoadGen, PrefixMixer
from paddle_tpu.robustness.traces import (
    TraceError,
    TraceReplayLoadGen,
    TraceWriter,
    arrival_stats,
    read_trace,
    serialize_trace,
)
from paddle_tpu.serving import Request
from paddle_tpu.serving.router import affinity_key, rendezvous_pick


def _virtual_clock():
    now = [0.0]
    return (lambda: now[0]), (lambda s: now.__setitem__(0, now[0] + s))


def _write_trace(path, reqs_with_offsets, meta=None, cancels=()):
    clock, _ = _virtual_clock()
    with TraceWriter(str(path), meta=meta or {"test": 1},
                     clock=clock) as w:
        for off, r in reqs_with_offsets:
            w.record_request(r, offset_s=off)
        for off, rid, reason in cancels:
            w.record_cancel(rid, offset_s=off, reason=reason)
    return str(path)


# ---------------------------------------------------------------------------
# round-trip + rejection
# ---------------------------------------------------------------------------


def test_trace_roundtrip_byte_identity(tmp_path):
    reqs = [
        Request([2, 3, 4], 6, req_id="a", deadline_s=0.25,
                session_id="sess0", priority=0),
        Request([5, 6], req_id="b"),
    ]
    p = _write_trace(tmp_path / "t.ptt",
                     [(0.0, reqs[0]), (0.125, reqs[1])],
                     cancels=[(0.5, "a", "client gave up")])
    raw = open(p, "rb").read()
    trace = read_trace(p)
    # byte identity: what the reader validated re-serializes to EXACTLY
    # the recorded file — a committed trace artifact is stable
    assert trace.serialize().encode() == raw
    assert len(trace) == 3
    (r0, r1), (c0,) = trace.requests(), trace.cancels()
    assert r0["id"] == "a" and r0["src"] == [2, 3, 4] and r0["mnt"] == 6
    assert r0["dl"] == 0.25 and r0["sess"] == "sess0" and r0["prio"] == 0
    assert r1["prio"] == 1 and r1["sess"] is None  # defaults recorded
    assert c0["id"] == "a" and c0["reason"] == "client gave up"
    # meta survives
    assert trace.meta == {"test": 1}


def test_trace_rejects_torn_truncated_and_corrupt(tmp_path):
    reqs = [(0.0, Request([2, 3], req_id="a")),
            (0.1, Request([4], req_id="b"))]
    p = _write_trace(tmp_path / "ok.ptt", reqs)
    lines = open(p).read().splitlines()

    def _variant(name, content):
        q = tmp_path / name
        q.write_text(content)
        with pytest.raises(TraceError) as ei:
            read_trace(str(q))
        return str(ei.value)

    # writer never closed (crash mid-run): no footer
    assert "footer" in _variant("nofoot.ptt",
                                "\n".join(lines[:-1]) + "\n")
    # crash mid-record: last line has no newline
    assert "newline" in _variant("torn.ptt", "\n".join(lines))
    # one flipped record byte: per-line crc catches it
    bad = lines[1][:10] + ("0" if lines[1][10] != "0" else "1") + lines[1][11:]
    assert "crc" in _variant(
        "flip.ptt", "\n".join([lines[0], bad, *lines[2:]]) + "\n")
    # a dropped record: footer count catches it
    assert "truncated" in _variant(
        "short.ptt", "\n".join([lines[0], lines[1], lines[-1]]) + "\n")
    # not a trace at all / wrong version
    assert "header" in _variant("junk.ptt", "hello\n")
    assert "version" in _variant(
        "vers.ptt",
        '#ptt1 {"meta":{},"version":999}\n' + "\n".join(lines[1:]) + "\n")


def test_trace_rejects_nonmonotonic_offsets(tmp_path):
    text = serialize_trace(
        [{"ev": "req", "o": 0.5, "id": "a", "src": [2]},
         {"ev": "req", "o": 0.1, "id": "b", "src": [3]}], {})
    q = tmp_path / "mono.ptt"
    q.write_text(text)
    with pytest.raises(TraceError, match="monotonic"):
        read_trace(str(q))


def test_writer_refuses_after_close(tmp_path):
    w = TraceWriter(str(tmp_path / "c.ptt"))
    w.record_request(Request([2]))
    w.close()
    with pytest.raises(TraceError, match="closed"):
        w.record_request(Request([3]))


# ---------------------------------------------------------------------------
# arrival-process reconstruction
# ---------------------------------------------------------------------------


def _stats_for(tmp_path, process, n=600, rate=50.0):
    gen = OpenLoopLoadGen(rate, n, lambda i: i, process=process, seed=9)
    recs = [{"ev": "req", "o": round(a, 6), "id": f"r{i}", "src": [2]}
            for i, a in enumerate(gen.arrivals)]
    q = tmp_path / f"{process}.ptt"
    q.write_text(serialize_trace(recs, {"process": process}))
    return arrival_stats(read_trace(str(q)))


def test_arrival_stats_reconstruct_each_process(tmp_path):
    """A recorded day carries its arrival process's signature: the rate
    comes back near nominal and the gap CV separates uniform (~0),
    poisson (~1) and burst (overdispersed) — the realism evidence that a
    replayed trace offers the RECORDED process, not a relabeled one."""
    uni = _stats_for(tmp_path, "uniform")
    poi = _stats_for(tmp_path, "poisson")
    bur = _stats_for(tmp_path, "burst")
    for s in (uni, poi, bur):
        assert s["n"] == 600
        assert 0.6 * 50.0 < s["rate_rps"] < 1.6 * 50.0, s
    assert uni["cv"] == pytest.approx(0.0, abs=1e-6)
    assert 0.7 < poi["cv"] < 1.3, poi
    assert bur["cv"] > poi["cv"] * 1.1, (bur, poi)


# ---------------------------------------------------------------------------
# replay fidelity: identity, affinity pinning, stamp-if-absent
# ---------------------------------------------------------------------------


def _record_live_window(tmp_path, n=12):
    """Drive a live loadgen window (virtual clock) and record it."""
    mixer = PrefixMixer(50, pool_size=3, prefix_frac=0.6, seed=4,
                        sessions=4)
    live = [Request(mixer.source(i), req_id=f"live{i}") for i in range(n)]
    clock, sleep = _virtual_clock()
    gen = OpenLoopLoadGen(
        100.0, n, lambda i: live[i], process="poisson", seed=2,
        deadline_s=0.4, session_of=mixer.session_of,
        priority_of=lambda i: 0 if i % 3 == 0 else 2,
        clock=clock, sleep=sleep,
    )
    w = TraceWriter(str(tmp_path / "live.ptt"), clock=clock)
    gen.run(lambda r: (w.record_request(r), r)[-1])
    w.close()
    return live, read_trace(str(tmp_path / "live.ptt"))


def test_replay_reproduces_recorded_identity_and_rendezvous(tmp_path):
    live, trace = _record_live_window(tmp_path)
    clock, sleep = _virtual_clock()
    replayed = TraceReplayLoadGen(trace, clock=clock, sleep=sleep).run(
        lambda r: r)
    assert len(replayed) == len(live)
    engines = ["engine-a", "engine-b", "engine-c"]
    for a, b in zip(live, replayed):
        assert b.req_id == a.req_id
        assert b.src_ids == a.src_ids
        assert b.deadline_s == a.deadline_s
        assert b.session_id == a.session_id
        assert b.priority == a.priority
        # the affinity key and the rendezvous target both pin: the
        # replayed day lands on the SAME engines the recorded day did
        ka = affinity_key(a.src_ids, a.session_id)
        kb = affinity_key(b.src_ids, b.session_id)
        assert ka == kb
        if ka is not None:
            assert (rendezvous_pick(ka, engines)
                    == rendezvous_pick(kb, engines))


def test_live_loadgen_never_clobbers_replayed_identity(tmp_path):
    """The stamp-if-absent regression (PR 20 satellite): replay-built
    requests passed through a DIFFERENTLY-configured live loadgen keep
    their recorded deadline and session — the live RNG must not
    re-derive affinity keys a recorded day already fixed."""
    live, trace = _record_live_window(tmp_path)
    clock, sleep = _virtual_clock()
    replayed = TraceReplayLoadGen(trace, clock=clock, sleep=sleep).run(
        lambda r: r)
    clock2, sleep2 = _virtual_clock()
    out = OpenLoopLoadGen(
        100.0, len(replayed), lambda i: replayed[i], process="uniform",
        deadline_s=99.0, session_of=lambda i: "sessCLOBBER",
        clock=clock2, sleep=sleep2,
    ).run(lambda r: r)
    assert [r.session_id for r in out] == [a.session_id for a in live]
    assert [r.deadline_s for r in out] == [a.deadline_s for a in live]


def test_replay_fires_cancels_at_recorded_offsets(tmp_path):
    p = _write_trace(
        tmp_path / "c.ptt",
        [(0.0, Request([2, 3], req_id="a")),
         (0.1, Request([4, 5], req_id="b"))],
        cancels=[(0.2, "a", "deadline blown")])
    clock, sleep = _virtual_clock()
    submitted, canceled = [], []
    TraceReplayLoadGen(read_trace(p), clock=clock, sleep=sleep).run(
        submitted.append,
        cancel=lambda rid, reason: canceled.append((rid, reason, clock())))
    assert [r.req_id for r in submitted] == ["a", "b"]
    assert canceled == [("a", "deadline blown", pytest.approx(0.2))]


def test_replay_speedup_compresses_the_clock(tmp_path):
    _, trace = _record_live_window(tmp_path)
    clock, sleep = _virtual_clock()
    gen = TraceReplayLoadGen(trace, speedup=4.0, clock=clock, sleep=sleep)
    gen.run(lambda r: r)
    span = float(trace.records[-1]["o"])
    assert clock() == pytest.approx(span / 4.0, rel=1e-3)
    assert gen.offered_duration_s == pytest.approx(span / 4.0)


# ---------------------------------------------------------------------------
# chaos-fuzz spec plumbing (pure logic; the engine-driving path is
# tests/test_fuzz_e2e.py, slow tier)
# ---------------------------------------------------------------------------


def test_fuzz_composition_sampling_deterministic():
    from paddle_tpu.robustness import fuzz

    a = fuzz.sample_composition(random.Random("7:3"))
    b = fuzz.sample_composition(random.Random("7:3"))
    assert a == b
    axes = [it["axis"] for it in a]
    assert axes[0] == "arrival"          # arrival is always present
    assert len(axes) == len(set(axes))   # one item per axis
    known = {"arrival", "serve_chaos", "netem", "train_chaos",
             "checkpoint"}
    assert set(axes) <= known
    # different seeds eventually sample different cocktails
    assert any(
        fuzz.sample_composition(random.Random(f"8:{i}")) != a
        for i in range(8)
    )


def test_fuzz_shrink_items_minimizes_and_keeps_irreproducible():
    from paddle_tpu.robustness.fuzz import shrink_items

    items = [{"axis": c} for c in "abcdef"]
    shrunk = shrink_items(
        items, lambda cand: any(it["axis"] == "d" for it in cand))
    assert shrunk == [{"axis": "d"}]
    # two-item violation shrinks to exactly the pair
    pair = shrink_items(
        items,
        lambda cand: ({"axis": "b"} in cand and {"axis": "e"} in cand))
    assert sorted(it["axis"] for it in pair) == ["b", "e"]
    # a non-reproducible violation comes back untouched (caller decides)
    assert shrink_items(items, lambda cand: False) == items


def test_fuzz_spec_roundtrip_and_replay_validation(tmp_path):
    from paddle_tpu.robustness import fuzz

    spec = fuzz._spec(
        7, 3, [{"axis": "arrival", "process": "burst",
                "rate_factor": 2.0}],
        "ledger_skew", ["ledger_sum_mismatch:offered=16:sum=17"])
    assert spec["kind"] == "chaos-fuzz"
    assert spec["version"] == fuzz.FUZZ_SPEC_VERSION
    p = tmp_path / "spec.json"
    fuzz.save_spec(spec, str(p))
    assert fuzz.load_spec(str(p)) == spec
    with pytest.raises(ValueError, match="chaos-fuzz"):
        fuzz.replay_fuzz_spec({"kind": "nope", "version": 1})
    with pytest.raises(ValueError, match="version"):
        fuzz.replay_fuzz_spec({"kind": "chaos-fuzz", "version": 999})
