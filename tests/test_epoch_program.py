"""Whole-pass on-device epoch program (trainer/step.py make_epoch_program +
SGD's ``whole_pass_program`` feed switch): cached epochs >= 2 run as ONE
lax.scan dispatch over the stacked pass cache, bit-exact against the
stepwise path — params, costs, events, the RNG chain, and the divergence
sentinel's skip decisions (a NaN-injected step) all match — with O(1) host
dispatches per epoch counter-asserted, and every unsupported configuration
falling back to stepwise replay."""

import logging

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core.batch import SeqTensor
from paddle_tpu.core.topology import reset_auto_names
from paddle_tpu.utils.flags import reset_flags, set_flag
from paddle_tpu.utils.timers import global_stats


@pytest.fixture(autouse=True)
def _clean():
    global_stats.reset()
    yield
    reset_flags()
    global_stats.reset()


def _model():
    reset_auto_names()
    x = paddle.layer.data("x", paddle.data_type.dense_vector(6))
    h = paddle.layer.fc(x, size=8, act=paddle.activation.Relu())
    pred = paddle.layer.fc(h, size=3, act=paddle.activation.Softmax())
    y = paddle.layer.data("y", paddle.data_type.integer_value(3))
    return paddle.layer.classification_cost(input=pred, label=y)


def _samples(n=16, seed=0, nan_at=None):
    rng = np.random.RandomState(seed)
    out = []
    for i in range(n):
        v = rng.randn(6).astype(np.float32)
        if nan_at is not None and i == nan_at:
            v[2] = np.nan
        out.append((v, int(rng.randint(3))))
    return out


def _train(whole_pass, num_passes=3, samples=None, collect=None,
           batch_size=4):
    set_flag("cache_pass_in_mem", True)
    if whole_pass:
        set_flag("whole_pass_program", True)
    cost = _model()
    params = paddle.parameters.create(cost, seed=0)
    tr = paddle.trainer.SGD(
        cost=cost, parameters=params, seed=0,
        update_equation=paddle.optimizer.Adam(learning_rate=1e-2),
    )
    s = samples if samples is not None else _samples()

    def reader():
        yield from s

    tr.train(
        reader=paddle.batch(reader, batch_size), num_passes=num_passes,
        event_handler=collect or (lambda e: None), async_load_data=False,
    )
    return tr


def _params_equal(a, b):
    for name in a.parameters.params:
        for k, v in a.parameters.params[name].items():
            np.testing.assert_array_equal(
                np.asarray(v), np.asarray(b.parameters.params[name][k]),
                err_msg=f"{name}.{k} diverged",
            )


def _end_iterations(events):
    return [
        (e.pass_id, e.batch_id, e.cost)
        for e in events if isinstance(e, paddle.event.EndIteration)
    ]


# ---------------------------------------------------------------------------
# bit-exact parity vs the stepwise path
# ---------------------------------------------------------------------------


def test_whole_pass_bit_exact_params_and_events():
    ev_a, ev_b = [], []
    a = _train(False, collect=lambda e: ev_a.append(e))
    reset_flags()
    global_stats.reset()
    b = _train(True, collect=lambda e: ev_b.append(e))
    _params_equal(a, b)
    ia, ib = _end_iterations(ev_a), _end_iterations(ev_b)
    assert ia == ib and len(ia) == 12  # 4 batches x 3 passes
    assert global_stats.count("epoch_program/dispatches") == 2
    # the carried RNG chain matched the host-side split sequence
    np.testing.assert_array_equal(np.asarray(a._rng), np.asarray(b._rng))
    assert a._step_count == b._step_count == 12


def test_whole_pass_end_pass_metrics_match():
    evs = {}
    for whole in (False, True):
        ev = []
        _train(whole, collect=lambda e: ev.append(e))
        evs[whole] = [
            e.evaluator for e in ev if isinstance(e, paddle.event.EndPass)
        ]
        reset_flags()
        global_stats.reset()
    assert len(evs[False]) == 3
    for ma, mb in zip(evs[False], evs[True]):
        assert set(ma) == set(mb)
        for k in ma:
            assert float(ma[k]) == float(mb[k]), k


def test_sentinel_skipped_step_parity():
    """Acceptance: a NaN batch inside the cached pass is SKIPPED on device
    by both paths — identical params, identical skip decisions, and the
    unhealthy step's cost excluded from the pass report in both."""
    samples = _samples(nan_at=5)  # lands in batch 1 of the pass
    ev_a, ev_b = [], []
    a = _train(False, samples=samples, collect=lambda e: ev_a.append(e))
    reset_flags()
    global_stats.reset()
    b = _train(True, samples=samples, collect=lambda e: ev_b.append(e))
    _params_equal(a, b)
    ia, ib = _end_iterations(ev_a), _end_iterations(ev_b)
    assert len(ia) == len(ib) == 12
    for (pa, ba, ca), (pb, bb, cb) in zip(ia, ib):
        assert (pa, ba) == (pb, bb)
        assert (ca == cb) or (np.isnan(ca) and np.isnan(cb))
    # the poisoned batch replays every pass; every replay skips
    assert sum(np.isnan(c) for _, _, c in ib) == 3
    ep_a = [e for e in ev_a if isinstance(e, paddle.event.EndPass)]
    ep_b = [e for e in ev_b if isinstance(e, paddle.event.EndPass)]
    for ma, mb in zip(ep_a, ep_b):
        assert float(ma.evaluator["mean_cost"]) == float(
            mb.evaluator["mean_cost"]
        )
        assert np.isfinite(ma.evaluator["mean_cost"])


def test_whole_pass_composes_with_aot_cache(tmp_path):
    from paddle_tpu.core.aot_cache import serialization_available

    set_flag("aot_cache_dir", str(tmp_path))
    tr = _train(True)
    assert global_stats.count("epoch_program/dispatches") == 2
    if serialization_available():
        kinds = {e["key"]["kind"] for e in tr._aot_cache.entries()}
        assert kinds == {"train_step", "epoch_program"}


# ---------------------------------------------------------------------------
# dispatch accounting + fallbacks
# ---------------------------------------------------------------------------


def test_o1_dispatches_per_cached_epoch():
    _train(True, num_passes=5)
    # pass 1 streams + captures; passes 2-5 are ONE dispatch each
    assert global_stats.count("epoch_program/dispatches") == 4
    assert global_stats.count("epoch_program/steps") == 16


def test_multi_bucket_pass_falls_back_stepwise(caplog):
    """Two batch shapes (ragged tail) can't stack — the stepwise cached
    replay runs instead, with a warning naming why."""
    with caplog.at_level("WARNING", logger="paddle_tpu.trainer"):
        a = _train(True, samples=_samples(18))  # 4+4+4+4+2 rows
    assert global_stats.count("epoch_program/dispatches") == 0
    assert any("shape buckets" in r.getMessage() for r in caplog.records)
    # and the run still trains correctly vs plain stepwise caching
    reset_flags()
    global_stats.reset()
    b = _train(False, samples=_samples(18))
    _params_equal(a, b)


def test_checkpoint_plane_falls_back_stepwise(tmp_path, caplog):
    set_flag("cache_pass_in_mem", True)
    set_flag("whole_pass_program", True)
    cost = _model()
    params = paddle.parameters.create(cost, seed=0)
    tr = paddle.trainer.SGD(
        cost=cost, parameters=params, seed=0,
        update_equation=paddle.optimizer.Adam(learning_rate=1e-2),
    )
    s = _samples()

    def reader():
        yield from s

    with caplog.at_level("WARNING", logger="paddle_tpu.trainer"):
        tr.train(
            reader=paddle.batch(reader, 4), num_passes=3,
            async_load_data=False, checkpoint_dir=str(tmp_path),
        )
    assert global_stats.count("epoch_program/dispatches") == 0
    assert any(
        "checkpoint/rollback" in r.getMessage() for r in caplog.records
    )


def test_flag_off_never_uses_program():
    _train(False)
    assert global_stats.count("epoch_program/dispatches") == 0


def test_stacked_copy_over_budget_falls_back_stepwise(caplog):
    """The whole-pass program needs a SECOND copy of the pass in HBM; a
    pass captured just under pass_cache_hbm_budget_mb must replay stepwise
    (with the reason named) instead of silently doubling past the budget."""
    set_flag("cache_pass_in_mem", True)
    set_flag("whole_pass_program", True)
    cost = _model()
    params = paddle.parameters.create(cost, seed=0)
    tr = paddle.trainer.SGD(
        cost=cost, parameters=params, seed=0,
        update_equation=paddle.optimizer.Adam(learning_rate=1e-2),
    )
    s = _samples()

    def reader():
        yield from s

    def shrink_budget(e):
        # after pass 1 sealed the capture, leave room for the pass once
        # but not for the stacked second copy
        if isinstance(e, paddle.event.EndPass) and e.pass_id == 0:
            cache = tr._pass_cache
            assert cache is not None and cache.ready
            cache.budget = cache.nbytes * 2 - 1
            assert not cache.fits_stacked()

    with caplog.at_level("WARNING", logger="paddle_tpu.trainer"):
        tr.train(reader=paddle.batch(reader, 4), num_passes=3,
                 event_handler=shrink_budget, async_load_data=False)
    assert global_stats.count("epoch_program/dispatches") == 0
    assert any(
        "stacked copy would exceed" in r.getMessage()
        for r in caplog.records
    )


def test_flag_without_pass_cache_warns(caplog):
    """whole_pass_program without cache_pass_in_mem can never engage — the
    run must say so instead of silently training stepwise forever."""
    set_flag("whole_pass_program", True)
    cost = _model()
    params = paddle.parameters.create(cost, seed=0)
    tr = paddle.trainer.SGD(
        cost=cost, parameters=params, seed=0,
        update_equation=paddle.optimizer.Adam(learning_rate=1e-2),
    )
    s = _samples()

    def reader():
        yield from s

    with caplog.at_level("WARNING", logger="paddle_tpu.trainer"):
        tr.train(reader=paddle.batch(reader, 4), num_passes=2,
                 async_load_data=False)
    assert global_stats.count("epoch_program/dispatches") == 0
    assert any(
        "no device-resident pass cache" in r.getMessage()
        for r in caplog.records
    )


# ---------------------------------------------------------------------------
# make_epoch_program unit behavior (carry fold semantics)
# ---------------------------------------------------------------------------


def test_carry_accumulators_fold_health_and_cost():
    import jax
    import jax.numpy as jnp

    from paddle_tpu.core.compiler import CompiledNetwork
    from paddle_tpu.core.topology import Topology
    from paddle_tpu.trainer.step import (
        make_epoch_program,
        make_train_carry,
    )

    cost = _model()
    net = CompiledNetwork(Topology([cost]))
    opt = paddle.optimizer.Adam(learning_rate=1e-2)
    params, state = net.init(jax.random.PRNGKey(0))
    opt_state = opt.init(params)
    rng = np.random.RandomState(0)
    batches = []
    for i in range(4):
        xs = rng.randn(4, 6).astype(np.float32)
        if i == 2:
            xs[0, 0] = np.nan
        batches.append({
            "x": SeqTensor(jnp.asarray(xs)),
            "y": SeqTensor(jnp.asarray(
                rng.randint(0, 3, 4).astype(np.int32)
            )),
        })
    stacked = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *batches)
    prog = make_epoch_program(net, opt, mesh=None)
    carry = make_train_carry(params, state, opt_state, jax.random.PRNGKey(7))
    carry, ms = prog(carry, stacked, jnp.arange(4))
    assert float(carry["skipped"]) == 1.0
    assert float(carry["health_min"]) == 0.0
    assert float(carry["ok_steps"]) == 3.0
    healthy_costs = [
        float(c) for c, h in zip(np.asarray(ms["cost"]),
                                 np.asarray(ms["health"])) if h >= 0.5
    ]
    np.testing.assert_allclose(
        float(carry["cost_sum"]), sum(healthy_costs), rtol=1e-6
    )
    # the skipped step's params passed through inside the scan: replaying
    # with the NaN batch REMOVED from the healthy steps' view would differ,
    # but health semantics are already pinned by the parity tests above
    assert np.isnan(np.asarray(ms["cost"])[2])
