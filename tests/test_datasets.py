"""Dataset suite + @provider surface tests (reference test model:
python/paddle/v2/dataset/tests/*, gserver/tests/test_PyDataProvider2.py)."""

import itertools

import numpy as np
import pytest

from paddle_tpu import data_provider as dp2
from paddle_tpu.dataset import (
    cifar,
    conll05,
    flowers,
    imdb,
    imikolov,
    mnist,
    movielens,
    mq2007,
    sentiment,
    voc2012,
    wmt14,
)


def take(reader, n):
    return list(itertools.islice(reader(), n))


def test_cifar_shapes():
    for rd, classes in [(cifar.train10, 10), (cifar.test10, 10),
                        (cifar.train100, 100), (cifar.test100, 100)]:
        samples = take(rd(), 5)
        assert len(samples) == 5
        for img, label in samples:
            assert img.shape == (3072,) and img.dtype == np.float32
            assert 0 <= label < classes


def test_imdb():
    word_idx = imdb.word_dict()
    assert "<unk>" in word_idx
    for ids, label in take(imdb.train(word_idx), 10):
        assert label in (0, 1)
        assert all(0 <= i < len(word_idx) for i in ids)


def test_imikolov_ngram_and_seq():
    word_idx = imikolov.build_dict()
    n = 5
    for gram in take(imikolov.train(word_idx, n), 10):
        assert len(gram) == n
        assert all(0 <= i < len(word_idx) for i in gram)
    for src, trg in take(imikolov.train(word_idx, -1, imikolov.DataType.SEQ), 5):
        assert len(src) == len(trg)
        assert src[0] == word_idx["<s>"] and trg[-1] == word_idx["<e>"]


def test_wmt14():
    dict_size = 100
    src_d, trg_d = wmt14.get_dict(dict_size, reverse=False)
    assert src_d["<s>"] == 0 and src_d["<e>"] == 1 and src_d["<unk>"] == 2
    for src, trg, trg_next in take(wmt14.train(dict_size), 8):
        assert len(trg) == len(trg_next)
        assert trg[0] == 0 and trg_next[-1] == 1
        assert trg[1:] == trg_next[:-1]


def test_conll05():
    word_d, verb_d, label_d = conll05.get_dict()
    emb = conll05.get_embedding()
    assert emb.shape[0] == len(word_d)
    for sample in take(conll05.test(), 5):
        assert len(sample) == 9
        length = len(sample[0])
        assert all(len(s) == length for s in sample)
        assert sum(sample[7]) == 1  # exactly one predicate mark


def test_movielens():
    samples = take(movielens.train(), 10)
    for s in samples:
        # [uid, gender, age, job, mid, [cats], [title], score]
        assert len(s) == 8
        uid, gender, age, job, mid, cats, title, score = s
        assert gender in (0, 1)
        assert 1.0 <= score <= 5.0
        assert isinstance(cats, list) and isinstance(title, list)
    assert movielens.max_user_id() > 0
    assert movielens.max_movie_id() > 0


def test_mq2007_formats():
    for score, feat in take(mq2007.train("pointwise"), 5):
        assert feat.shape == (mq2007.FEATURE_DIM,)
    for label, hi, lo in take(mq2007.train("pairwise"), 5):
        assert hi.shape == lo.shape == (mq2007.FEATURE_DIM,)
    for labels, feats in take(mq2007.train("listwise"), 2):
        assert len(labels) == len(feats)
        assert labels == sorted(labels, reverse=True)


def test_sentiment():
    wd = sentiment.get_word_dict()
    train = take(sentiment.train(), 10)
    for ids, label in train:
        assert label in (0, 1)
        assert all(0 <= i < len(wd) for i in ids)
    assert len(list(sentiment.test()())) == (
        sentiment.NUM_TOTAL_INSTANCES - sentiment.NUM_TRAINING_INSTANCES
    )


def test_flowers_voc():
    img, label = next(flowers.train()())
    assert img.shape == (flowers.DIM,) and 0 <= label < flowers.CLASSES
    img, seg = next(voc2012.train()())
    assert img.shape == (3, voc2012.SIZE, voc2012.SIZE)
    assert seg.shape == (voc2012.SIZE, voc2012.SIZE)
    assert seg.max() < voc2012.CLASSES


def test_provider_decorator():
    @dp2.provider(
        input_types=[dp2.dense_vector(4), dp2.integer_value(3)],
        should_shuffle=False,
        cache=dp2.CacheType.CACHE_PASS_IN_MEM,
        check=True,
    )
    def process(settings, filename):
        assert settings.input_types is not None
        rng = np.random.RandomState(0)
        for _ in range(20):
            yield rng.randn(4).astype(np.float32), int(rng.randint(3))

    reader = process()
    first = list(reader())
    second = list(reader())  # served from the pass cache
    assert len(first) == len(second) == 20
    np.testing.assert_allclose(first[0][0], second[0][0])


def test_provider_check_rejects_bad_dim():
    @dp2.provider(
        input_types=[dp2.dense_vector(4)], should_shuffle=False, check=True
    )
    def bad(settings, filename):
        yield (np.zeros(3, np.float32),)

    with pytest.raises(ValueError):
        list(bad()())


def test_provider_converter_batches():
    conv = dp2.DataProviderConverter(
        [dp2.dense_vector(4), dp2.integer_value(3)]
    )
    batch = conv([(np.zeros(4, np.float32), 1) for _ in range(6)])
    assert batch["slot_0"].data.shape[0] == 6
