"""Beam-search generation parity against the reference's CHECKED-IN golden
model (paddle/trainer/tests/test_recurrent_machine_generation.cpp): load the
shipped trained parameters (rnn_gen_test_model_dir/t1, v1 binary format),
run sample_trainer_rnn_gen.conf unmodified, and reproduce the golden output
files r1.test.nobeam / r1.test.beam token for token and score for score."""

import os
import struct

import numpy as np
import pytest

import paddle_tpu as paddle

REF = "/root/reference/paddle"
MODEL = f"{REF}/trainer/tests/rnn_gen_test_model_dir"

pytestmark = pytest.mark.skipif(
    not os.path.isdir(MODEL), reason="reference tree not present"
)


def _load_v1_param(path: str) -> np.ndarray:
    """Reference Parameter::save format (Parameter.cpp ~250-340): int32
    version, uint32 value_size, uint64 count, then raw float32."""
    with open(path, "rb") as f:
        buf = f.read()
    version, value_size, count = struct.unpack("<iIQ", buf[:16])
    assert version == 0 and value_size == 4
    arr = np.frombuffer(buf[16:], "<f4").copy()
    assert arr.size == count
    return arr


def _gen(beam_flag: bool):
    import jax

    from paddle_tpu.core.batch import SeqTensor
    from paddle_tpu.core.compiler import CompiledNetwork
    from paddle_tpu.core.topology import reset_auto_names
    from paddle_tpu.v1_compat import parse_config

    reset_auto_names()
    cwd = os.getcwd()
    os.chdir(REF)  # the conf's evaluator dict path is run-dir relative
    try:
        p = parse_config(
            f"{REF}/trainer/tests/sample_trainer_rnn_gen.conf",
            f"beam_search={int(beam_flag)}",
        )
    finally:
        os.chdir(cwd)
    net = CompiledNetwork(p.topology)
    params, state = net.init(jax.random.PRNGKey(0))

    wordvec = _load_v1_param(f"{MODEL}/t1/wordvec").reshape(5, 5)
    transtable = _load_v1_param(f"{MODEL}/t1/transtable").reshape(5, 5)
    # shared-by-name parameters of the conf: the GeneratedInput embedding
    # and the output trans-projection both name "wordvec"
    gp = params["rnn_gen"]
    gp["@gen_emb"]["w"] = np.asarray(wordvec)
    gp["__mixed_0__"]["p0_w"] = np.asarray(transtable)
    gp["__mixed_1__"]["p0_w"] = np.asarray(wordvec)

    batch = {
        "dummy_data_input": SeqTensor(np.zeros((15, 2), np.float32))
    }
    outs, _ = net.apply(params, batch, state=state, train=False)
    seqs = np.asarray(outs["rnn_gen"].data)  # [B, K, T]
    scores = np.asarray(outs["rnn_gen@scores"].data)  # [B, K]
    return seqs, scores


def _trim(seq, eos=4):
    out = []
    for t in seq:
        out.append(int(t))
        if t == eos:
            break
    return out


def test_generation_matches_golden_nobeam():
    """r1.test.nobeam: every one of the 15 samples generates `1 2 3 4`."""
    golden = [
        [int(t) for t in line.split("\t")[1].split()]
        for line in open(f"{MODEL}/r1.test.nobeam")
        if line.strip()
    ]
    seqs, _ = _gen(beam_flag=False)
    assert seqs.shape[0] == 15
    for i, want in enumerate(golden):
        assert _trim(seqs[i, 0]) == want, (i, seqs[i, 0], want)


def test_generation_matches_golden_beam():
    """r1.test.beam: for every sample, hypothesis 0 = `1 2 3 4` at score 0,
    hypothesis 1 = `0 1 2 3 4` at score -0.2 (the exact numbers the
    reference's beamSearch prints for this model)."""
    seqs, scores = _gen(beam_flag=True)
    assert seqs.shape[0] == 15 and seqs.shape[1] >= 2
    for i in range(15):
        assert _trim(seqs[i, 0]) == [1, 2, 3, 4], seqs[i, 0]
        assert _trim(seqs[i, 1]) == [0, 1, 2, 3, 4], seqs[i, 1]
        np.testing.assert_allclose(scores[i, 0], 0.0, atol=1e-5)
        np.testing.assert_allclose(scores[i, 1], -0.2, atol=1e-5)


def test_generation_matches_golden_nested():
    """r1.test.nest (sample_trainer_nest_rnn_gen.conf): a beam generator
    INSIDE a recurrent_group over subsequences — one sample with 15
    subsequences, each generating `1 2 3 4` (the reference concatenates the
    per-subsequence beam results through the outer group)."""
    import jax

    from paddle_tpu.core.batch import nested_seq
    from paddle_tpu.core.compiler import CompiledNetwork
    from paddle_tpu.core.topology import reset_auto_names
    from paddle_tpu.v1_compat import parse_config

    reset_auto_names()
    cwd = os.getcwd()
    os.chdir(REF)
    try:
        p = parse_config(
            f"{REF}/trainer/tests/sample_trainer_nest_rnn_gen.conf",
            "beam_search=0",
        )
    finally:
        os.chdir(cwd)
    assert p.output_layers[0] == "rnn_gen_concat"  # the outer group
    net = CompiledNetwork(p.topology)
    params, state = net.init(jax.random.PRNGKey(0))
    gp = params["rnn_gen_concat"]["rnn_gen"]
    gp["@gen_emb"]["w"] = np.asarray(
        _load_v1_param(f"{MODEL}/t1/wordvec").reshape(5, 5)
    )
    gp["__mixed_0__"]["p0_w"] = np.asarray(
        _load_v1_param(f"{MODEL}/t1/transtable").reshape(5, 5)
    )
    gp["__mixed_1__"]["p0_w"] = np.asarray(
        _load_v1_param(f"{MODEL}/t1/wordvec").reshape(5, 5)
    )
    # golden: ONE sample, 15 subsequences (dummy data decides the count)
    batch = {
        "dummy_data_input": nested_seq(
            np.zeros((1, 15, 1, 2), np.float32), [15], [[1] * 15]
        )
    }
    outs, _ = net.apply(params, batch, state=state, train=False)
    seqs = np.asarray(outs["rnn_gen_concat"].data)  # [1, 15, K, T]
    golden = [
        [int(t) for t in line.split("\t")[-1].split()]
        for line in open(f"{MODEL}/r1.test.nest")
        if line.strip()
    ]
    assert len(golden) == 15
    for s in range(15):
        assert _trim(seqs[0, s, 0]) == golden[s], (s, seqs[0, s, 0])


def test_num_results_per_sample_limits_output():
    """num_results_per_sample keeps only the best N of K beams in the
    layer output (reference beam_search arg) — built with beam_size=2 and
    num_results_per_sample=1 so the trim path actually executes."""
    import jax

    import paddle_tpu as paddle
    from paddle_tpu import layers as L
    from paddle_tpu.core.batch import SeqTensor
    from paddle_tpu.core.compiler import CompiledNetwork
    from paddle_tpu.core.topology import Topology, reset_auto_names

    reset_auto_names()
    dummy = L.data("d", paddle.data_type.dense_vector(2))

    def step(static_in, prev_word):
        return L.fc(prev_word, size=6, act=paddle.activation.Softmax())

    beam = L.beam_search(
        step=step,
        input=[
            L.StaticInput(input=dummy, size=2),
            L.GeneratedInput(size=6, embedding_size=4),
        ],
        bos_id=0,
        eos_id=5,
        beam_size=2,
        num_results_per_sample=1,
        max_length=5,
        name="trimmed",
    )
    net = CompiledNetwork(Topology([beam]))
    params, state = net.init(jax.random.PRNGKey(0))
    outs, _ = net.apply(
        params,
        {"d": SeqTensor(np.zeros((4, 2), np.float32))},
        state=state,
        train=False,
    )
    # searched with K=2, reports only the best 1
    assert np.asarray(outs["trimmed"].data).shape == (4, 1, 5)
    assert np.asarray(outs["trimmed@scores"].data).shape == (4, 1)
