"""Device-resident pass cache + data echo (reader/pass_cache.py — the
TPU-native CACHE_PASS_IN_MEM, reference PyDataProvider2.cpp:69).

Covers: cached-vs-streamed training parity (identical trained parameters for
the same batch order), HBM-budget overflow falling back to streaming with a
warning, per-bucket composition with ``use_bucketing``, data echo, shuffle
reproducibility from the pass seed, and the v1 zero-edit face — a reference-
style config whose ``@provider(cache=CacheType.CACHE_PASS_IN_MEM)`` rides
through ``parse_config``/``make_batched_reader`` into the trainer's device
cache.
"""

import logging

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core.batch import SeqTensor, batch_shape_key
from paddle_tpu.core.topology import reset_auto_names
from paddle_tpu.reader.pass_cache import PassCache, batch_nbytes
from paddle_tpu.utils.flags import reset_flags, set_flag


@pytest.fixture(autouse=True)
def _clean_flags():
    yield
    reset_flags()


def _device_batch(seed=0, b=4, d=6):
    import jax

    rng = np.random.RandomState(seed)
    return {
        "x": SeqTensor(jax.device_put(rng.randn(b, d).astype(np.float32))),
        "y": SeqTensor(jax.device_put(rng.randint(0, 3, b).astype(np.int32))),
    }


# ---------------------------------------------------------------------------
# PassCache unit behavior
# ---------------------------------------------------------------------------


def test_capture_seal_replay_roundtrip():
    cache = PassCache(seed=3)
    batches = [_device_batch(i) for i in range(5)]
    consumed = list(cache.capture(iter(batches)))
    assert consumed == batches  # echo off: pass-through
    assert cache.ready and cache.n_batches == 5
    assert cache.nbytes == sum(batch_nbytes(b) for b in batches)
    replay = list(cache.epoch(1))
    # a permutation of the SAME device batches (by identity, no copies)
    assert sorted(map(id, replay)) == sorted(map(id, batches))


def test_epoch_order_reproducible_from_pass_seed():
    batches = [_device_batch(i) for i in range(8)]
    a, b = PassCache(seed=7), PassCache(seed=7)
    for x in batches:
        a.observe(x)
        b.observe(x)
    a.seal(), b.seal()
    assert a.epoch_order(1) == b.epoch_order(1)  # same seed+pass = same order
    assert a.epoch_order(1) == a.epoch_order(1)  # stable across calls
    assert a.epoch_order(1) != a.epoch_order(2)  # passes decorrelate
    c = PassCache(seed=7, shuffle=False)
    for x in batches:
        c.observe(x)
    c.seal()
    assert c.epoch_order(1) == list(range(8))


def test_hbm_budget_overflow_falls_back_to_streaming(caplog):
    batches = [_device_batch(i) for i in range(4)]
    per = batch_nbytes(batches[0])
    cache = PassCache(hbm_budget_bytes=2 * per + per // 2, echo_factor=1)
    with caplog.at_level(logging.WARNING, logger="paddle_tpu.pass_cache"):
        consumed = list(cache.capture(iter(batches)))
    assert consumed == batches  # training itself is untouched
    assert not cache.active and not cache.ready
    assert cache.n_batches == 0 and cache.nbytes == 0  # references released
    assert any("falling back to streaming" in r.message for r in caplog.records)


def test_data_echo_repeats_first_epoch_batches():
    batches = [_device_batch(i) for i in range(3)]
    cache = PassCache(echo_factor=3)
    consumed = list(cache.capture(iter(batches)))
    assert len(consumed) == 9
    for i, b in enumerate(batches):
        assert all(x is b for x in consumed[3 * i : 3 * i + 3])
    assert cache.ready and cache.n_batches == 3  # cached once, trained 3x


def test_sample_shuffle_permutes_rows_consistently_across_slots():
    import jax

    b, d = 8, 4
    data = np.arange(b * d, dtype=np.float32).reshape(b, d)
    lens = np.arange(b, dtype=np.int32) + 1
    batch = {
        "w": SeqTensor(
            jax.device_put(data), jax.device_put(lens)
        ),
        "y": SeqTensor(jax.device_put(np.arange(b, dtype=np.int32))),
    }
    cache = PassCache(seed=5, sample_shuffle=True)
    cache.observe(batch)
    cache.seal()
    (out,) = list(cache.epoch(2))
    w, y = np.asarray(out["w"].data), np.asarray(out["y"].data)
    wl = np.asarray(out["w"].lengths)
    assert sorted(y.tolist()) == list(range(b))  # a permutation, no loss
    assert y.tolist() != list(range(b))  # and it actually shuffled
    for row, sample_id in enumerate(y):
        # every slot (data, lengths, label) moved together
        np.testing.assert_array_equal(w[row], data[sample_id])
        assert wl[row] == lens[sample_id]
    # reproducibility: a fresh cache with the same seed replays identically
    c2 = PassCache(seed=5, sample_shuffle=True)
    c2.observe(batch)
    c2.seal()
    (rep,) = list(c2.epoch(2))
    np.testing.assert_array_equal(np.asarray(rep["y"].data), y)


def test_abandoned_capture_restarts_clean():
    cache = PassCache()
    gen = cache.capture(iter([_device_batch(0), _device_batch(1)]))
    next(gen)  # abandon mid-pass
    gen.close()
    assert not cache.ready and cache.n_batches == 1
    list(cache.capture(iter([_device_batch(2), _device_batch(3)])))
    assert cache.ready and cache.n_batches == 2  # no mixed partial pass


# ---------------------------------------------------------------------------
# trainer integration
# ---------------------------------------------------------------------------


def _dense_model():
    reset_auto_names()
    x = paddle.layer.data("x", paddle.data_type.dense_vector(6))
    h = paddle.layer.fc(x, size=8, act=paddle.activation.Relu())
    pred = paddle.layer.fc(h, size=3, act=paddle.activation.Softmax())
    y = paddle.layer.data("y", paddle.data_type.integer_value(3))
    return paddle.layer.classification_cost(input=pred, label=y)


def _dense_samples(n=16, seed=0):
    rng = np.random.RandomState(seed)
    return [
        (rng.randn(6).astype(np.float32), int(rng.randint(3)))
        for _ in range(n)
    ]


def _train(reader, num_passes, collect=None, seed=0):
    cost = _dense_model()
    params = paddle.parameters.create(cost, seed=0)
    tr = paddle.trainer.SGD(
        cost=cost, parameters=params, seed=seed,
        update_equation=paddle.optimizer.Adam(learning_rate=1e-2),
    )
    tr.train(
        reader=reader, num_passes=num_passes,
        event_handler=collect or (lambda e: None), async_load_data=False,
    )
    return tr


def test_cached_vs_streamed_training_parity():
    """Same batches via the device cache vs plain streaming produce
    IDENTICAL trained parameters (acceptance criterion): run the cached
    trainer, read back the replay order its cache actually used, then
    stream exactly that order through an uncached trainer."""
    import jax

    samples = _dense_samples(16)
    batches = [samples[i : i + 4] for i in range(0, 16, 4)]

    def reader():
        yield from samples

    set_flag("cache_pass_in_mem", True)
    cached = _train(paddle.batch(reader, 4), num_passes=3)
    cache = cached._pass_cache
    assert cache is not None and cache.ready and cache.n_batches == 4
    orders = [cache.epoch_order(p) for p in (1, 2)]

    reset_flags()
    calls = {"n": 0}

    def replay_reader():
        i = calls["n"]
        calls["n"] += 1
        order = list(range(4)) if i == 0 else orders[i - 1]
        for bi in order:
            yield from batches[bi]

    streamed = _train(paddle.batch(replay_reader, 4), num_passes=3)
    assert streamed._pass_cache is None
    for name in cached.parameters.params:
        for k, a in cached.parameters.params[name].items():
            np.testing.assert_array_equal(
                np.asarray(a),
                np.asarray(streamed.parameters.params[name][k]),
                err_msg=f"{name}.{k} diverged between cached and streamed",
            )


def test_trainer_overflow_streams_every_pass(caplog):
    samples = _dense_samples(16)

    def reader():
        yield from samples

    set_flag("cache_pass_in_mem", True)
    set_flag("pass_cache_hbm_budget_mb", 0)  # nothing fits
    events = []
    with caplog.at_level(logging.WARNING, logger="paddle_tpu.pass_cache"):
        tr = _train(
            paddle.batch(reader, 4), num_passes=2,
            collect=lambda e: events.append(e)
            if isinstance(e, paddle.event.EndIteration) else None,
        )
    assert any("falling back to streaming" in r.message for r in caplog.records)
    assert tr._pass_cache is not None and not tr._pass_cache.active
    assert len(events) == 8  # both passes trained, streamed


def test_trainer_data_echo_first_pass_only():
    samples = _dense_samples(16)

    def reader():
        yield from samples

    set_flag("cache_pass_in_mem", True)
    set_flag("data_echo_factor", 2)
    events = []
    tr = _train(
        paddle.batch(reader, 4), num_passes=2,
        collect=lambda e: events.append(e.batch_id)
        if isinstance(e, paddle.event.EndIteration) else None,
    )
    # pass 0: 4 batches x echo 2 = 8 iterations; pass 1: cached replay, 4
    assert len(events) == 12
    assert tr._pass_cache.ready and tr._pass_cache.n_batches == 4


def test_single_pass_run_retains_nothing_but_still_echoes():
    """num_passes=1 can never replay, so the trainer must NOT pin the pass
    in HBM — and data echo (which needs only the batch in hand) still
    applies to the one pass."""
    samples = _dense_samples(16)

    def reader():
        yield from samples

    set_flag("cache_pass_in_mem", True)
    tr = _train(paddle.batch(reader, 4), num_passes=1)
    assert tr._pass_cache is None  # no retention for a single-pass run

    set_flag("data_echo_factor", 2)
    events = []
    tr2 = _train(
        paddle.batch(reader, 4), num_passes=1,
        collect=lambda e: events.append(e)
        if isinstance(e, paddle.event.EndIteration) else None,
    )
    assert tr2._pass_cache is None
    assert len(events) == 8  # 4 batches x echo 2, zero batches retained


def test_cache_reused_across_train_calls_same_reader():
    """The cache lives with its data source (reference CACHE_PASS_IN_MEM
    semantics): a second train() with the SAME reader object replays
    immediately — even its first pass pays zero H2D; a different reader
    frees the stale pass."""
    samples = _dense_samples(16)

    def reader():
        yield from samples

    rd = paddle.batch(reader, 4)
    set_flag("cache_pass_in_mem", True)
    cost = _dense_model()
    tr = paddle.trainer.SGD(
        cost=cost, parameters=paddle.parameters.create(cost, seed=0), seed=0,
        update_equation=paddle.optimizer.Adam(learning_rate=1e-2),
    )
    tr.train(reader=rd, num_passes=2, async_load_data=False)
    first = tr._pass_cache
    assert first.ready and first.n_batches == 4
    # same reader object, even a single pass: replayed from the held cache
    tr.train(reader=rd, num_passes=1, async_load_data=False)
    assert tr._pass_cache is first and first.ready
    # a different reader: the stale pass is freed before re-capture
    rd2 = paddle.batch(reader, 4)
    tr.train(reader=rd2, num_passes=2, async_load_data=False)
    assert tr._pass_cache is not first
    assert not first.active and first.n_batches == 0  # dropped
    assert tr._pass_cache.ready and tr._pass_cache.n_batches == 4


def test_pass_cache_composes_with_use_bucketing():
    """Variable-length corpus under use_bucketing: the cache captures the
    per-rung batch shapes as-is (per-bucket caching) and the cached epoch
    replays the same shape multiset interleaved across rungs."""
    reset_auto_names()
    w = paddle.layer.data(
        "w", paddle.data_type.integer_value_sequence(30)
    )
    emb = paddle.layer.embedding(w, size=4)
    pooled = paddle.layer.last_seq(emb)
    pred = paddle.layer.fc(pooled, size=2, act=paddle.activation.Softmax())
    y = paddle.layer.data("y", paddle.data_type.integer_value(2))
    cost = paddle.layer.classification_cost(input=pred, label=y)

    rng = np.random.RandomState(1)
    samples = [
        ([int(t) for t in rng.randint(1, 30, size=l)], int(l % 2))
        for l in rng.randint(2, 60, size=64)
    ]

    from paddle_tpu.reader.bucketing import token_budget_batch

    set_flag("cache_pass_in_mem", True)
    set_flag("use_bucketing", True)
    params = paddle.parameters.create(cost, seed=0)
    tr = paddle.trainer.SGD(
        cost=cost, parameters=params,
        update_equation=paddle.optimizer.Adam(learning_rate=1e-2),
    )
    tr.train(
        reader=token_budget_batch(
            lambda: iter(samples), token_budget=128, drop_last=True
        ),
        num_passes=3,
        async_load_data=False,
    )
    cache = tr._pass_cache
    assert cache is not None and cache.ready
    assert cache.n_buckets > 1, "corpus should span several ladder rungs"
    captured = sorted(
        batch_shape_key(b) for b in cache._batches
    )
    replayed = sorted(batch_shape_key(b) for b in cache.epoch(2))
    assert captured == replayed


# ---------------------------------------------------------------------------
# v1 zero-edit face
# ---------------------------------------------------------------------------


def test_provider_cache_tag_propagates_through_batchers():
    from paddle_tpu.data_provider import CacheType, integer_value, provider
    from paddle_tpu.reader.bucketing import token_budget_batch

    @provider(
        input_types=[integer_value(4)], cache=CacheType.CACHE_PASS_IN_MEM,
        should_shuffle=False,
    )
    def proc(settings, f):
        for i in range(8):
            yield (i % 4,)

    rd = proc()
    assert getattr(rd, "cache_pass_in_mem", False)
    assert getattr(paddle.batch(rd, 2), "cache_pass_in_mem", False)
    assert getattr(
        token_budget_batch(rd, token_budget=8), "cache_pass_in_mem", False
    )

    @provider(input_types=[integer_value(4)], should_shuffle=False)
    def proc_nocache(settings, f):
        yield (0,)

    assert not getattr(proc_nocache(), "cache_pass_in_mem", False)
    assert not getattr(
        paddle.batch(proc_nocache(), 2), "cache_pass_in_mem", False
    )


def test_should_shuffle_false_replays_in_capture_order():
    """A should_shuffle=False provider (ordered/curriculum data) must replay
    cached epochs in capture order — the shuffle intent rides the reader tag
    into the trainer's PassCache."""
    from paddle_tpu.data_provider import CacheType, integer_value, provider
    from paddle_tpu.reader.bucketing import token_budget_batch

    def make(should_shuffle):
        @provider(
            input_types=[integer_value(4)],
            cache=CacheType.CACHE_PASS_IN_MEM,
            should_shuffle=should_shuffle,
        )
        def proc(settings, f):
            yield (0,)

        return proc()

    ordered = make(False)
    assert ordered.cache_pass_shuffle is False
    assert paddle.batch(ordered, 2).cache_pass_shuffle is False
    assert token_budget_batch(ordered, token_budget=8).cache_pass_shuffle is False
    assert make(True).cache_pass_shuffle is True

    # end-to-end: the trainer's cache honors it
    samples = _dense_samples(16)

    def reader():
        yield from samples

    rd = paddle.batch(reader, 4)
    rd.cache_pass_in_mem = True
    rd.cache_pass_shuffle = False
    tr = _train(rd, num_passes=3)
    cache = tr._pass_cache
    assert cache.ready and not cache.shuffle
    assert cache.epoch_order(1) == [0, 1, 2, 3]
    assert cache.epoch_order(2) == [0, 1, 2, 3]


def test_v1_config_cache_pass_in_mem_run_sweep(tmp_path):
    """A reference-style config whose provider declares
    cache=CacheType.CACHE_PASS_IN_MEM trains through the v1 face with ZERO
    edits and lands in the device cache: parse_config -> make_batched_reader
    (tag propagated) -> SGD.train captures pass 1 and replays pass 2 from
    HBM."""
    from paddle_tpu.v1_compat import (
        make_batched_reader,
        make_optimizer,
        parse_config,
    )

    cfg = tmp_path / "conf.py"
    cfg.write_text(
        "from paddle.trainer_config_helpers import *\n"
        "define_py_data_sources2(train_list='t', test_list=None,\n"
        "                        module='cache_prov', obj='process')\n"
        "settings(batch_size=4, learning_rate=1e-3,\n"
        "         learning_method=MomentumOptimizer())\n"
        "img = data_layer(name='pixel', size=12)\n"
        "lbl = data_layer(name='label', size=3)\n"
        "fc1 = fc_layer(input=img, size=3, act=SoftmaxActivation())\n"
        "outputs(classification_cost(input=fc1, label=lbl))\n"
    )
    (tmp_path / "cache_prov.py").write_text(
        "from paddle.trainer.PyDataProvider2 import *\n"
        "@provider(input_types=[dense_vector(12), integer_value(3)],\n"
        "          cache=CacheType.CACHE_PASS_IN_MEM, should_shuffle=False)\n"
        "def process(settings, f):\n"
        "    for i in range(16):\n"
        "        yield [0.125 * (i % 8)] * 12, i % 3\n"
    )
    (tmp_path / "t").write_text("dummy\n")
    p = parse_config(str(cfg))
    reader = make_batched_reader(
        p, str(tmp_path), p.settings.batch_size, train=True
    )
    assert getattr(reader, "cache_pass_in_mem", False), (
        "CACHE_PASS_IN_MEM must survive the v1 reader pipeline untagged-free"
    )
    params = paddle.parameters.create(p.topology, seed=0)
    tr = paddle.trainer.SGD(
        cost=p.topology, parameters=params,
        update_equation=make_optimizer(p.settings),
    )
    events = []
    tr.train(
        reader=reader, num_passes=2, feeding=p.feeding,
        event_handler=lambda e: events.append(e)
        if isinstance(e, paddle.event.EndIteration) else None,
        async_load_data=False,
    )
    cache = tr._pass_cache
    assert cache is not None and cache.ready and cache.n_batches == 4
    assert len(events) == 8  # pass 1 streamed+captured, pass 2 replayed
