"""Persistent AOT executable cache (core/aot_cache.py): roundtrip + warm
hit, stale/corrupt robustness (truncated blob, mismatched jax-version key,
foreign-topology key — each falls back to retrace, warns once, bumps its
counter, never crashes or loads wrong code), maintenance surface
(ls/prune/clear + the CLI), and the subprocess warm-boot e2e: a second
process boots from the first's cache with ZERO full retraces,
compile-counter-asserted."""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core import aot_cache as ac
from paddle_tpu.core.topology import reset_auto_names
from paddle_tpu.utils.flags import reset_flags, set_flag
from paddle_tpu.utils.timers import StatSet, global_stats


@pytest.fixture(autouse=True)
def _clean_flags():
    yield
    reset_flags()


# the version-compat shim path (no executable serialization) degrades to
# retracing — everything that asserts on real disk entries skips there
needs_ser = pytest.mark.skipif(
    not ac.serialization_available(),
    reason="jax build has no executable serialization (shim no-op path)",
)


def _jitted():
    import jax
    import jax.numpy as jnp

    return jax.jit(
        lambda p, x: {k: v + x.mean() for k, v in p.items()},
        donate_argnums=(0,),
    ), ({"w": jnp.ones((16, 16)), "b": jnp.ones((16,))}, jnp.ones((4, 16)))


def _identity(n=None):
    return {"kind": "test_step", "n_steps": n, "topology": "t0",
            "batch": "b0", "mesh": "none", "donation": "(0,)"}


# ---------------------------------------------------------------------------
# store/load roundtrip + counters
# ---------------------------------------------------------------------------


@needs_ser
def test_miss_then_hit_roundtrip(tmp_path):
    import jax.numpy as jnp

    stats = StatSet()
    cache = ac.AOTCache(str(tmp_path), stats=stats)
    fn, args = _jitted()
    exe = cache.get_or_compile(fn, args, _identity())
    assert cache.compiles == 1 and stats.count("aot_cache/miss") == 1
    out = exe(*_jitted()[1])
    np.testing.assert_allclose(np.asarray(out["b"]), 2.0)

    # a second cache object (fresh process stand-in) loads, no compile
    stats2 = StatSet()
    cache2 = ac.AOTCache(str(tmp_path), stats=stats2)
    exe2 = cache2.get_or_compile(fn, _jitted()[1], _identity())
    assert cache2.compiles == 0 and cache2.loads == 1
    assert stats2.count("aot_cache/hit") == 1
    out2 = exe2(*_jitted()[1])
    np.testing.assert_array_equal(np.asarray(out2["w"]), np.asarray(out["w"]))


@needs_ser
def test_distinct_identities_are_distinct_entries(tmp_path):
    cache = ac.AOTCache(str(tmp_path), stats=StatSet())
    fn, args = _jitted()
    cache.get_or_compile(fn, args, _identity())
    cache.get_or_compile(fn, _jitted()[1], _identity(n=8))
    assert len(cache.entries()) == 2
    assert cache.compiles == 2


@needs_ser
def test_serialization_writes_real_entries(tmp_path):
    cache = ac.AOTCache(str(tmp_path), stats=StatSet())
    fn, args = _jitted()
    cache.get_or_compile(fn, args, _identity())
    ents = cache.entries()
    assert len(ents) == 1 and ents[0]["bytes"] > 0
    assert ents[0]["key"]["kind"] == "test_step"
    assert ents[0]["key"]["jax"]  # env fields in the header provenance


# ---------------------------------------------------------------------------
# robustness: truncated / version-stale / foreign-topology entries
# ---------------------------------------------------------------------------


def _entry_paths(tmp_path):
    return [
        os.path.join(str(tmp_path), f)
        for f in sorted(os.listdir(str(tmp_path))) if f.endswith(".aotx")
    ]


@needs_ser
def test_truncated_entry_falls_back_to_retrace(tmp_path, caplog):
    stats = StatSet()
    cache = ac.AOTCache(str(tmp_path), stats=stats)
    fn, args = _jitted()
    cache.get_or_compile(fn, args, _identity())
    (path,) = _entry_paths(tmp_path)
    data = open(path, "rb").read()
    with open(path, "wb") as f:
        f.write(data[: len(data) // 2])  # torn write / partial copy

    stats2 = StatSet()
    cache2 = ac.AOTCache(str(tmp_path), stats=stats2)
    with caplog.at_level("WARNING", logger="paddle_tpu.aot_cache"):
        exe = cache2.get_or_compile(fn, _jitted()[1], _identity())
        # warn once, not per load
        cache2.load(_identity())
    assert cache2.compiles == 1  # retraced, not crashed
    assert stats2.count("aot_cache/corrupt") >= 1
    assert sum("damaged" in r.getMessage() for r in caplog.records) == 1
    out = exe(*_jitted()[1])
    np.testing.assert_allclose(np.asarray(out["b"]), 2.0)


@needs_ser
def test_header_level_truncation_falls_back_to_retrace(tmp_path):
    """Truncation INSIDE the fixed-size framing fields (magic + partial
    length u32, or cut before the CRC) must be a corrupt entry, not an
    unhandled struct.error — regression test for the length-checked
    header reads."""
    cache = ac.AOTCache(str(tmp_path), stats=StatSet())
    fn, args = _jitted()
    cache.get_or_compile(fn, args, _identity())
    (path,) = _entry_paths(tmp_path)
    data = open(path, "rb").read()
    for cut in (9, len(ac._MAGIC) + 2, len(ac._MAGIC) + 4 + 10):
        with open(path, "wb") as f:
            f.write(data[:cut])
        stats = StatSet()
        cache2 = ac.AOTCache(str(tmp_path), stats=stats)
        assert cache2.load(_identity()) is None  # never raises
        assert stats.count("aot_cache/corrupt") == 1
        ents = cache2.entries()  # ls lists it as corrupt, no crash
        assert len(ents) == 1 and "corrupt" in ents[0]
    exe = cache2.get_or_compile(fn, _jitted()[1], _identity())
    assert cache2.compiles == 1
    out = exe(*_jitted()[1])
    np.testing.assert_allclose(np.asarray(out["b"]), 2.0)


@needs_ser
def test_mismatched_jax_version_key_is_stale(tmp_path, caplog, monkeypatch):
    """An entry written by a different jax (or backend) must be detected
    and retraced — simulated by rewriting the header's env fields, the
    exact bytes a version upgrade leaves behind."""
    stats = StatSet()
    cache = ac.AOTCache(str(tmp_path), stats=stats)
    fn, args = _jitted()
    cache.get_or_compile(fn, args, _identity())
    (path,) = _entry_paths(tmp_path)
    header, blob = ac._read_entry(path)
    header["key"]["jax"] = "0.0.1-foreign"
    ac._write_entry(path, header, blob)

    stats2 = StatSet()
    cache2 = ac.AOTCache(str(tmp_path), stats=stats2)
    with caplog.at_level("WARNING", logger="paddle_tpu.aot_cache"):
        exe = cache2.get_or_compile(fn, _jitted()[1], _identity())
    assert cache2.compiles == 1 and cache2.loads == 0
    assert stats2.count("aot_cache/stale") == 1
    assert any("jax" in r.getMessage() for r in caplog.records)
    # the retrace OVERWROTE the stale entry: next boot is warm again
    cache3 = ac.AOTCache(str(tmp_path), stats=StatSet())
    assert cache3.load(_identity()) is not None
    out = exe(*_jitted()[1])
    np.testing.assert_allclose(np.asarray(out["b"]), 2.0)


@needs_ser
def test_foreign_topology_entry_never_loads(tmp_path):
    """A valid entry for a DIFFERENT program renamed into this identity's
    path (hash collision stand-in): the full-key comparison rejects it —
    wrong code can never load."""
    stats = StatSet()
    cache = ac.AOTCache(str(tmp_path), stats=stats)
    fn, args = _jitted()
    foreign = dict(_identity(), topology="OTHER-NET")
    cache.get_or_compile(fn, args, foreign)
    os.rename(cache.entry_path(foreign), cache.entry_path(_identity()))

    stats2 = StatSet()
    cache2 = ac.AOTCache(str(tmp_path), stats=stats2)
    assert cache2.load(_identity()) is None
    assert stats2.count("aot_cache/stale") == 1
    exe = cache2.get_or_compile(fn, _jitted()[1], _identity())
    assert cache2.compiles == 1
    out = exe(*_jitted()[1])
    np.testing.assert_allclose(np.asarray(out["b"]), 2.0)


@needs_ser
def test_meta_mismatch_is_stale(tmp_path):
    """Same program identity, different hyperparameters (the optimizer
    fingerprint): the old executable bakes the old constants — stale."""
    cache = ac.AOTCache(str(tmp_path), stats=StatSet())
    fn, args = _jitted()
    cache.get_or_compile(fn, args, _identity(), {"optimizer": "lr=0.1"})
    stats2 = StatSet()
    cache2 = ac.AOTCache(str(tmp_path), stats=stats2)
    assert cache2.load(_identity(), {"optimizer": "lr=0.01"}) is None
    assert stats2.count("aot_cache/stale") == 1


def test_optimizer_fingerprint_distinguishes_hyperparams():
    a = ac.optimizer_fingerprint(paddle.optimizer.Adam(learning_rate=1e-2))
    b = ac.optimizer_fingerprint(paddle.optimizer.Adam(learning_rate=1e-3))
    c = ac.optimizer_fingerprint(
        paddle.optimizer.Momentum(momentum=0.9, learning_rate=1e-2)
    )
    d = ac.optimizer_fingerprint(
        paddle.optimizer.Adam(
            learning_rate=1e-2, learning_rate_schedule="exp",
            learning_rate_decay_a=0.5, learning_rate_decay_b=100.0,
        )
    )
    assert len({a, b, c, d}) == 4


# ---------------------------------------------------------------------------
# maintenance: ls / prune / clear
# ---------------------------------------------------------------------------


@needs_ser
def test_prune_drops_oldest_until_fit(tmp_path):
    cache = ac.AOTCache(str(tmp_path), stats=StatSet())
    fn, args = _jitted()
    for i in range(3):
        cache.get_or_compile(fn, args, _identity(n=i))
        path = cache.entry_path(_identity(n=i))
        os.utime(path, (i + 1, i + 1))  # deterministic age order
    sizes = {e["file"]: e["bytes"] for e in cache.entries()}
    keep_newest = cache.entry_path(_identity(n=2))
    removed = cache.prune(max_bytes=sizes[os.path.basename(keep_newest)])
    assert len(removed) == 2
    assert os.path.exists(keep_newest)
    assert cache.load(_identity(n=2)) is not None or not (
        ac.serialization_available()
    )


@needs_ser
def test_prune_and_clear_sweep_orphaned_tmp_files(tmp_path):
    """A writer SIGKILLed mid-_write_entry leaves <hash>.aotx.tmp.<pid>;
    the maintenance commands must reclaim it even though it is not a
    listable entry."""
    cache = ac.AOTCache(str(tmp_path), stats=StatSet())
    fn, args = _jitted()
    cache.get_or_compile(fn, args, _identity())
    orphan = os.path.join(str(tmp_path), "deadbeef.aotx.tmp.12345")
    with open(orphan, "wb") as f:
        f.write(b"x" * 1024)
    assert all("tmp" not in e["file"] for e in cache.entries())
    removed = cache.prune(max_bytes=1 << 30)  # fits: only the tmp goes
    assert os.path.basename(orphan) in removed
    assert not os.path.exists(orphan)
    with open(orphan, "wb") as f:
        f.write(b"x")
    assert cache.clear() == 2  # the entry + the orphan
    assert os.listdir(str(tmp_path)) == []


@needs_ser
def test_clear_empties_store(tmp_path):
    cache = ac.AOTCache(str(tmp_path), stats=StatSet())
    fn, args = _jitted()
    cache.get_or_compile(fn, args, _identity())
    assert len(cache.entries()) == 1
    assert cache.clear() == 1
    assert cache.entries() == []


# ---------------------------------------------------------------------------
# SGD integration: dispatch table + warm_compile
# ---------------------------------------------------------------------------


def _model():
    reset_auto_names()
    x = paddle.layer.data("x", paddle.data_type.dense_vector(6))
    h = paddle.layer.fc(x, size=8, act=paddle.activation.Relu())
    pred = paddle.layer.fc(h, size=3, act=paddle.activation.Softmax())
    y = paddle.layer.data("y", paddle.data_type.integer_value(3))
    return paddle.layer.classification_cost(input=pred, label=y)


def _samples(n=16, seed=0):
    rng = np.random.RandomState(seed)
    return [
        (rng.randn(6).astype(np.float32), int(rng.randint(3)))
        for _ in range(n)
    ]


def _train(num_passes=2, seed=0):
    cost = _model()
    params = paddle.parameters.create(cost, seed=0)
    tr = paddle.trainer.SGD(
        cost=cost, parameters=params, seed=seed,
        update_equation=paddle.optimizer.Adam(learning_rate=1e-2),
    )
    s = _samples()

    def reader():
        yield from s

    tr.train(reader=paddle.batch(reader, 4), num_passes=num_passes,
             async_load_data=False)
    return tr


@needs_ser
def test_sgd_aot_dispatch_cold_then_warm_trainer(tmp_path):
    """Two trainers sharing one cache dir: the second resolves every shape
    by deserializing — zero compiles — and trains to bit-identical
    params."""
    set_flag("aot_cache_dir", str(tmp_path))
    t1 = _train()
    assert t1._aot_cache.compiles >= 1
    global_stats.reset()
    t2 = _train()
    assert t2._aot_cache.compiles == 0
    assert t2._aot_cache.loads >= 1
    for name in t1.parameters.params:
        for k, v in t1.parameters.params[name].items():
            np.testing.assert_array_equal(
                np.asarray(v), np.asarray(t2.parameters.params[name][k]),
                err_msg=f"{name}.{k} diverged cold vs warm",
            )


def test_sgd_without_flag_has_no_cache(tmp_path):
    t = _train(num_passes=1)
    assert t._aot_cache is None
    assert os.listdir(str(tmp_path)) == []


def test_warm_compile_populates_without_stepping(tmp_path):
    import jax

    set_flag("aot_cache_dir", str(tmp_path))
    cost = _model()
    params = paddle.parameters.create(cost, seed=0)
    tr = paddle.trainer.SGD(
        cost=cost, parameters=params,
        update_equation=paddle.optimizer.Adam(learning_rate=1e-2),
    )
    before = jax.tree_util.tree_map(np.asarray, tr.parameters.params)
    from paddle_tpu.core.batch import SeqTensor

    batch = {
        "x": SeqTensor(np.zeros((4, 6), np.float32)),
        "y": SeqTensor(np.zeros((4,), np.int32)),
    }
    assert tr.warm_compile(batch) is True
    assert tr.warm_compile(batch) is False  # shape already resolved
    assert tr._aot_cache.compiles == 1
    after = jax.tree_util.tree_map(np.asarray, tr.parameters.params)
    for name in before:
        for k in before[name]:
            np.testing.assert_array_equal(before[name][k], after[name][k])


# ---------------------------------------------------------------------------
# subprocess e2e: a second PROCESS warm-boots from the first's cache
# ---------------------------------------------------------------------------

_CHILD = """
import json, sys
import numpy as np
import paddle_tpu as paddle
from paddle_tpu.core.topology import reset_auto_names
from paddle_tpu.utils.flags import set_flag
from paddle_tpu.utils.timers import global_stats

set_flag("aot_cache_dir", sys.argv[1])
set_flag("cache_pass_in_mem", True)
set_flag("whole_pass_program", True)

def model():
    reset_auto_names()
    x = paddle.layer.data("x", paddle.data_type.dense_vector(6))
    h = paddle.layer.fc(x, size=8, act=paddle.activation.Relu())
    pred = paddle.layer.fc(h, size=3, act=paddle.activation.Softmax())
    y = paddle.layer.data("y", paddle.data_type.integer_value(3))
    return paddle.layer.classification_cost(input=pred, label=y)

def train(batch_size, passes):
    cost = model()
    params = paddle.parameters.create(cost, seed=0)
    tr = paddle.trainer.SGD(cost=cost, parameters=params, seed=0,
                            update_equation=paddle.optimizer.Adam(
                                learning_rate=1e-2))
    rng = np.random.RandomState(0)
    s = [(rng.randn(6).astype(np.float32), int(rng.randint(3)))
         for _ in range(16)]
    def reader():
        yield from s
    tr.train(reader=paddle.batch(reader, batch_size), num_passes=passes,
             async_load_data=False)
    return tr

# run A: two ladder rungs (full 6-row batches + the ragged 4-row tail),
# stepwise; run B: single rung, whole-pass epoch program for passes >= 2
t1 = train(6, 1)
t2 = train(4, 3)
leaf = np.asarray(
    next(iter(t2.parameters.params["__fc_layer_0__"].values()))
)
print(json.dumps({
    "compiles": t1._aot_cache.compiles + t2._aot_cache.compiles,
    "loads": t1._aot_cache.loads + t2._aot_cache.loads,
    "hit": global_stats.count("aot_cache/hit"),
    "miss": global_stats.count("aot_cache/miss"),
    "stale": global_stats.count("aot_cache/stale"),
    "corrupt": global_stats.count("aot_cache/corrupt"),
    "epoch_dispatches": global_stats.count("epoch_program/dispatches"),
    "fingerprint": float(np.abs(leaf).sum()),
}))
"""


def _boot(tmp_path, cache_dir):
    script = os.path.join(str(tmp_path), "child.py")
    with open(script, "w") as f:
        f.write(_CHILD)
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    r = subprocess.run(
        [sys.executable, script, cache_dir],
        capture_output=True, text=True, env=env, timeout=600,
    )
    assert r.returncode == 0, (r.stdout[-1000:], r.stderr[-3000:])
    return json.loads(r.stdout.strip().splitlines()[-1])


@needs_ser
def test_subprocess_warm_boot_zero_retraces(tmp_path):
    """Acceptance: a second process against a populated cache performs
    ZERO full retraces for the rungs (train-step shapes + the whole-pass
    epoch program) the first process compiled — compile-counter-asserted —
    and trains to the identical trajectory."""
    cache_dir = os.path.join(str(tmp_path), "cache")
    cold = _boot(tmp_path, cache_dir)
    # 2 train-step rungs (6-row + 4-row: run A's ragged tail IS run B's
    # full rung, so run B hits run A's entry in-process) + the whole-pass
    # epoch program
    assert cold["compiles"] == 3, cold
    assert cold["miss"] == cold["compiles"]
    assert cold["hit"] == 1  # the cross-run 4-row reuse above
    assert cold["epoch_dispatches"] == 2  # passes 2 and 3: one each

    warm = _boot(tmp_path, cache_dir)
    assert warm["compiles"] == 0, warm  # the headline: zero retraces
    # 4 deserializations: run A loads its 2 rungs, run B its rung (its own
    # trainer-local executable table) + the epoch program
    assert warm["loads"] == 4 and warm["hit"] == 4
    assert warm["miss"] == 0
    assert warm["stale"] == 0 and warm["corrupt"] == 0
    assert warm["fingerprint"] == cold["fingerprint"]


# ---------------------------------------------------------------------------
# CLI face
# ---------------------------------------------------------------------------


def _run_cli(args, cwd=None):
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    return subprocess.run(
        [sys.executable, "-m", "paddle_tpu", *args],
        capture_output=True, text=True, cwd=cwd, env=env, timeout=600,
    )


def _write_v1_config(tmp_path):
    (tmp_path / "conf.py").write_text(
        "from paddle.trainer_config_helpers import *\n"
        "define_py_data_sources2(train_list='t', test_list=None,\n"
        "                        module='prov', obj='process')\n"
        "settings(batch_size=4, learning_rate=1e-3,\n"
        "         learning_method=AdamOptimizer())\n"
        "img = data_layer(name='pixel', size=12)\n"
        "lbl = data_layer(name='label', size=3)\n"
        "fc1 = fc_layer(input=img, size=3, act=SoftmaxActivation())\n"
        "outputs(classification_cost(input=fc1, label=lbl))\n"
    )
    (tmp_path / "prov.py").write_text(
        "from paddle.trainer.PyDataProvider2 import *\n"
        "@provider(input_types=[dense_vector(12), integer_value(3)],\n"
        "          should_shuffle=False)\n"
        "def process(settings, f):\n"
        "    for i in range(16):\n"
        "        yield [0.125 * (i % 8)] * 12, i % 3\n"
    )
    (tmp_path / "t").write_text("dummy\n")
    return str(tmp_path / "conf.py")


@pytest.mark.slow
@needs_ser
def test_cache_cli_warm_ls_prune_clear(tmp_path):
    cfg = _write_v1_config(tmp_path)
    d = str(tmp_path / "cache")
    r = _run_cli(["cache", "warm", "--dir", d, "--config", cfg])
    assert r.returncode == 0, r.stderr[-2000:]
    cold = json.loads(r.stdout.strip().splitlines()[-1])
    assert cold["compiles"] >= 1 and cold["entries"] >= 1

    r = _run_cli(["cache", "warm", "--dir", d, "--config", cfg])
    warm = json.loads(r.stdout.strip().splitlines()[-1])
    assert warm["compiles"] == 0 and warm["loads"] == cold["compiles"]
    assert warm["warm_s"] < cold["warm_s"]

    r = _run_cli(["cache", "ls", "--dir", d])
    assert r.returncode == 0
    assert "kind=train_step" in r.stdout  # key provenance listed

    r = _run_cli(["cache", "prune", "--dir", d, "--max-mb", "0"])
    assert r.returncode == 0
    assert json.loads(r.stdout.strip().splitlines()[-1])["entries"] == 0

    _run_cli(["cache", "warm", "--dir", d, "--config", cfg])
    r = _run_cli(["cache", "clear", "--dir", d])
    assert json.loads(r.stdout.strip().splitlines()[-1])["entries"] == 0
