"""Image preprocessing utility (reference python/paddle/utils/
preprocess_img.py): dir tree -> batch files + lists + meta -> reader."""

import os

import numpy as np
import pytest

from paddle_tpu.utils import preprocess_img as pp


@pytest.fixture()
def image_tree(tmp_path):
    from PIL import Image

    rng = np.random.RandomState(0)
    for split, n in (("train", 6), ("test", 2)):
        for label in ("cat", "dog"):
            d = tmp_path / split / label
            d.mkdir(parents=True)
            for i in range(n):
                arr = rng.randint(0, 255, size=(12, 10, 3), dtype=np.uint8)
                Image.fromarray(arr).save(d / f"im{i}.png")
    return tmp_path


def test_create_batches_and_reader(image_tree):
    creater = pp.ImageClassificationDatasetCreater(
        str(image_tree), target_size=8, num_per_batch=5
    )
    meta = creater.create_batches()
    assert meta["label_names"] == ["cat", "dog"]
    assert meta["img_size"] == 8 * 8 * 3
    assert meta["mean_image"].shape == (8 * 8 * 3,)

    meta2 = pp.load_meta(str(image_tree))
    assert meta2["label_names"] == meta["label_names"]

    # 12 train images, 5 per batch -> 3 batch files
    with open(image_tree / "train.list") as f:
        assert len(f.read().split()) == 3

    reader = pp.batch_reader(str(image_tree / "train.list"), meta)
    rows = list(reader())
    assert len(rows) == 12
    xs = np.stack([r[0] for r in rows])
    labels = sorted(r[1] for r in rows)
    assert xs.shape == (12, 8 * 8 * 3)
    assert labels == [0] * 6 + [1] * 6
    # mean-subtracted training set has ~zero mean
    np.testing.assert_allclose(xs.mean(axis=0), 0.0, atol=1e-3)


def test_disk_image_npy_and_png_agree(tmp_path):
    from PIL import Image

    rng = np.random.RandomState(1)
    arr = rng.randint(0, 255, size=(8, 8, 3), dtype=np.uint8)
    Image.fromarray(arr).save(tmp_path / "a.png")
    np.save(tmp_path / "a.npy", arr)
    png = pp.DiskImage(str(tmp_path / "a.png"), 8).convert_to_paddle_format()
    npy = pp.DiskImage(str(tmp_path / "a.npy"), 8).convert_to_paddle_format()
    np.testing.assert_allclose(png, npy)
    assert png.shape == (8 * 8 * 3,)
