"""Image preprocessing utility (reference python/paddle/utils/
preprocess_img.py): dir tree -> batch files + lists + meta -> reader."""

import os

import numpy as np
import pytest

from paddle_tpu.utils import preprocess_img as pp


@pytest.fixture()
def image_tree(tmp_path):
    from PIL import Image

    rng = np.random.RandomState(0)
    for split, n in (("train", 6), ("test", 2)):
        for label in ("cat", "dog"):
            d = tmp_path / split / label
            d.mkdir(parents=True)
            for i in range(n):
                arr = rng.randint(0, 255, size=(12, 10, 3), dtype=np.uint8)
                Image.fromarray(arr).save(d / f"im{i}.png")
    return tmp_path


def test_create_batches_and_reader(image_tree):
    creater = pp.ImageClassificationDatasetCreater(
        str(image_tree), target_size=8, num_per_batch=5
    )
    meta = creater.create_batches()
    assert meta["label_names"] == ["cat", "dog"]
    assert meta["img_size"] == 8 * 8 * 3
    assert meta["mean_image"].shape == (8 * 8 * 3,)

    meta2 = pp.load_meta(str(image_tree))
    assert meta2["label_names"] == meta["label_names"]

    # 12 train images, 5 per batch -> 3 batch files
    with open(image_tree / "train.list") as f:
        assert len(f.read().split()) == 3

    reader = pp.batch_reader(str(image_tree / "train.list"), meta)
    rows = list(reader())
    assert len(rows) == 12
    xs = np.stack([r[0] for r in rows])
    labels = sorted(r[1] for r in rows)
    assert xs.shape == (12, 8 * 8 * 3)
    assert labels == [0] * 6 + [1] * 6
    # mean-subtracted training set has ~zero mean
    np.testing.assert_allclose(xs.mean(axis=0), 0.0, atol=1e-3)


def test_disk_image_npy_and_png_agree(tmp_path):
    from PIL import Image

    rng = np.random.RandomState(1)
    arr = rng.randint(0, 255, size=(8, 8, 3), dtype=np.uint8)
    Image.fromarray(arr).save(tmp_path / "a.png")
    np.save(tmp_path / "a.npy", arr)
    png = pp.DiskImage(str(tmp_path / "a.png"), 8).convert_to_paddle_format()
    npy = pp.DiskImage(str(tmp_path / "a.npy"), 8).convert_to_paddle_format()
    np.testing.assert_allclose(png, npy)
    assert png.shape == (8 * 8 * 3,)


def test_test_split_labels_map_to_training_label_set(tmp_path):
    """Test-split ids must follow the TRAINING label set even when the test
    dir is missing a label."""
    from PIL import Image

    rng = np.random.RandomState(2)
    for label in ("ant", "bee", "cow"):
        d = tmp_path / "train" / label
        d.mkdir(parents=True)
        Image.fromarray(
            rng.randint(0, 255, size=(8, 8, 3), dtype=np.uint8)
        ).save(d / "x.png")
    # test split only has the LAST two labels
    for label in ("bee", "cow"):
        d = tmp_path / "test" / label
        d.mkdir(parents=True)
        Image.fromarray(
            rng.randint(0, 255, size=(8, 8, 3), dtype=np.uint8)
        ).save(d / "y.png")
    creater = pp.ImageClassificationDatasetCreater(str(tmp_path), target_size=8)
    meta = creater.create_batches()
    rows = list(pp.batch_reader(str(tmp_path / "test.list"))())
    assert sorted(r[1] for r in rows) == [1, 2]  # bee=1, cow=2 per training set
    assert meta["label_names"] == ["ant", "bee", "cow"]


def test_unknown_test_label_rejected(tmp_path):
    from PIL import Image
    import pytest

    rng = np.random.RandomState(3)
    for split, labels in (("train", ["a"]), ("test", ["a", "zz"])):
        for label in labels:
            d = tmp_path / split / label
            d.mkdir(parents=True)
            Image.fromarray(
                rng.randint(0, 255, size=(8, 8, 3), dtype=np.uint8)
            ).save(d / "x.png")
    creater = pp.ImageClassificationDatasetCreater(str(tmp_path), target_size=8)
    with pytest.raises(ValueError, match="zz"):
        creater.create_batches()


def test_small_npy_resized_like_png(tmp_path):
    rng = np.random.RandomState(4)
    arr = rng.randint(0, 255, size=(6, 6, 3), dtype=np.uint8)
    np.save(tmp_path / "small.npy", arr)
    vec = pp.DiskImage(str(tmp_path / "small.npy"), 8).convert_to_paddle_format()
    assert vec.shape == (8 * 8 * 3,)
