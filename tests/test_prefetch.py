"""Async data plane (reader/prefetch.py) — the reference DataProvider.h
double-buffer queue equivalent: ordering, error propagation, teardown,
measured feed/compute overlap, and trainer equivalence sync vs async."""

import threading
import time

import numpy as np
import pytest

from paddle_tpu.reader.prefetch import DevicePrefetcher, prefetch


def test_prefetch_preserves_order_and_terminates():
    out = list(prefetch(range(100), lambda x: x * 2))
    assert out == [2 * i for i in range(100)]


def test_prefetch_propagates_reader_exception():
    def bad():
        yield 1
        yield 2
        raise RuntimeError("reader boom")

    it = prefetch(bad())
    assert next(it) == 1 and next(it) == 2
    with pytest.raises(RuntimeError, match="reader boom"):
        next(it)


def test_prefetch_propagates_prepare_exception():
    def prepare(x):
        if x == 3:
            raise ValueError("prepare boom")
        return x

    got = []
    with pytest.raises(ValueError, match="prepare boom"):
        for v in prefetch(range(10), prepare):
            got.append(v)
    assert got == [0, 1, 2]


def test_prefetch_close_unblocks_stuck_worker():
    """Early consumer exit must not leave the worker thread alive feeding a
    full queue."""
    n_before = threading.active_count()
    pf = DevicePrefetcher(iter(range(10_000)), depth=2)
    assert next(pf) == 0
    pf.close()
    deadline = time.time() + 5
    while threading.active_count() > n_before and time.time() < deadline:
        time.sleep(0.01)
    assert threading.active_count() <= n_before


def test_prefetch_overlaps_feed_with_consumer_work():
    """With feed cost F per batch and consumer cost C per batch, the wall
    time must approach max(F, C) * n, not (F + C) * n (double buffering)."""
    n, f, c = 10, 0.03, 0.03

    def slow_reader():
        for i in range(n):
            time.sleep(f)
            yield i

    # sync lower bound for comparison: every batch pays F + C serially
    t0 = time.perf_counter()
    for _ in range(n):
        time.sleep(f)
        time.sleep(c)
    sync_wall = time.perf_counter() - t0

    t0 = time.perf_counter()
    got = []
    for v in prefetch(slow_reader()):
        time.sleep(c)  # the "train step"
        got.append(v)
    async_wall = time.perf_counter() - t0

    assert got == list(range(n))
    # generous margin: overlap should reclaim a large part of min(F, C) * n
    assert async_wall < sync_wall - 0.4 * n * min(f, c), (
        f"no overlap: async {async_wall:.3f}s vs sync {sync_wall:.3f}s"
    )


def test_trainer_async_feed_matches_sync_feed():
    """SGD.train(async_load_data=True) computes exactly the same costs as
    the inline feed — the background thread changes timing, not math."""
    import paddle_tpu as paddle
    from paddle_tpu import activation as A
    from paddle_tpu.core.topology import reset_auto_names

    def run(async_load):
        reset_auto_names()
        paddle.init(seed=11)
        x = paddle.layer.data("x", paddle.data_type.dense_vector(8))
        y = paddle.layer.data("y", paddle.data_type.dense_vector(1))
        pred = paddle.layer.fc(x, size=1, act=A.Identity())
        cost = paddle.layer.square_error_cost(input=pred, label=y)
        params = paddle.parameters.create(cost)
        trainer = paddle.trainer.SGD(
            cost=cost, parameters=params,
            update_equation=paddle.optimizer.Momentum(learning_rate=1e-3),
        )
        rng = np.random.RandomState(5)
        data = [
            (rng.randn(8).tolist(), [float(rng.randn())]) for _ in range(24)
        ]
        costs = []
        trainer.train(
            paddle.batch(lambda: iter(data), 8),
            num_passes=2,
            event_handler=lambda e: costs.append(e.cost)
            if isinstance(e, paddle.event.EndIteration) else None,
            async_load_data=async_load,
        )
        return costs

    np.testing.assert_allclose(run(True), run(False), rtol=1e-6)


def test_prefetch_terminal_states_are_sticky():
    """After exhaustion or a propagated error, further next() calls must
    keep raising instead of blocking on the dead worker's queue."""
    pf = DevicePrefetcher(iter([1, 2]))
    assert list(pf) == [1, 2]
    with pytest.raises(StopIteration):
        next(pf)  # second call after exhaustion: no hang
    pf.close()

    def bad():
        yield 1
        raise RuntimeError("boom")

    pf2 = DevicePrefetcher(bad())
    assert next(pf2) == 1
    for _ in range(3):  # retry loop keeps seeing the error, never hangs
        with pytest.raises(RuntimeError, match="boom"):
            next(pf2)
    pf2.close()


def test_narrow_dtype_feed_trains():
    """data_layer(feed_dtype="uint8"): the wire batch stays uint8 (4x fewer
    host->device bytes) and the jitted step casts + normalizes on device
    (feed_scale/feed_shift) — reference DataProvider ships bytes the same
    way (mnist_bin_part is uint8 on disk).  Training must still converge."""
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu.core.topology import reset_auto_names

    reset_auto_names()
    x = paddle.layer.data(
        "img", paddle.data_type.dense_vector(12),
        feed_dtype="uint8", feed_scale=1 / 255.0, feed_shift=-0.5,
    )
    lbl = paddle.layer.data("lbl", paddle.data_type.integer_value(3))
    fc = paddle.layer.fc(input=x, size=3, act=paddle.activation.Softmax())
    cost = paddle.layer.classification_cost(input=fc, label=lbl)
    params = paddle.parameters.create(cost)
    tr = paddle.trainer.SGD(
        cost=cost, parameters=params,
        update_equation=paddle.optimizer.Momentum(
            learning_rate=0.5, momentum=0.9
        ),
    )
    feeder = tr._make_feeder(None)
    rng = np.random.RandomState(0)
    pix = rng.randint(0, 256, (24, 12), dtype=np.uint8)
    rows = [(pix[i], int(pix[i, 0]) % 3) for i in range(24)]  # learnable
    batch = feeder(rows)
    assert batch["img"].data.dtype == np.uint8  # narrow on the wire
    assert batch["lbl"].data.dtype == np.int32

    costs = []
    tr.train(
        reader=paddle.batch(lambda: iter(rows), 8), num_passes=30,
        event_handler=lambda e: costs.append(e.cost)
        if isinstance(e, paddle.event.EndIteration) else None,
        async_load_data=False,
    )
    assert costs[-1] < 0.9 * costs[0], (costs[0], costs[-1])

    # the device-side values are the normalized floats, not raw bytes
    import jax

    net = tr.network
    outs, _ = net.apply(
        tr.parameters.params, batch, state=tr.parameters.state, train=False,
        rng=jax.random.PRNGKey(0),
    )
    got = np.asarray(outs["img"].data)
    want = np.asarray(batch["img"].data, np.float32) / 255.0 - 0.5
    np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-6)


def test_narrow_dtype_infer_matches_train_path():
    """paddle.infer must feed the same wire dtype + on-device normalize as
    training (r5 review finding: a float-fed infer batch skipped the
    normalize and skewed predictions)."""
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu.core.topology import reset_auto_names

    reset_auto_names()
    x = paddle.layer.data(
        "img", paddle.data_type.dense_vector(8),
        feed_dtype="uint8", feed_scale=1 / 255.0, feed_shift=-0.5,
    )
    out = paddle.layer.fc(x, size=2, act=paddle.activation.Softmax())
    params = paddle.parameters.create(out)
    rows = [(np.arange(8, dtype=np.uint8) * 30,)]
    probs = paddle.infer(output_layer=out, parameters=params, input=rows)
    # manual reference through the train-path math
    xf = (np.arange(8) * 30).astype(np.float32) / 255.0 - 0.5
    w = np.asarray(params.params["__fc_layer_0__"]["w0"])
    b = np.asarray(params.params["__fc_layer_0__"]["b"])
    logits = xf @ w + b
    want = np.exp(logits - logits.max())
    want /= want.sum()
    np.testing.assert_allclose(np.asarray(probs)[0], want, rtol=2e-3, atol=2e-3)


def test_buffered_reader_error_reraises_on_consumer():
    from paddle_tpu.reader.decorator import buffered

    def bad_reader():
        yield 1
        raise ValueError("bad sample")

    it = buffered(bad_reader, size=2)()
    assert next(it) == 1
    import pytest

    with pytest.raises(ValueError, match="bad sample"):
        list(it)


def test_xmap_mapper_error_reraises_instead_of_hanging():
    from paddle_tpu.reader.decorator import xmap_readers

    def src():
        for i in range(100):
            yield i

    def mapper(x):
        if x == 5:
            raise ValueError("poison sample")
        return x

    import pytest

    with pytest.raises(ValueError, match="poison sample"):
        list(xmap_readers(mapper, src, process_num=2, buffer_size=4)())


def test_xmap_source_error_reraises_instead_of_hanging():
    from paddle_tpu.reader.decorator import xmap_readers

    def bad_src():
        yield 0
        raise IOError("truncated input")

    import pytest

    with pytest.raises(IOError, match="truncated input"):
        list(xmap_readers(lambda x: x, bad_src, process_num=3,
                          buffer_size=2, order=True)())
