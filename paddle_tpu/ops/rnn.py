"""Recurrent cells as lax.scan loops — the TPU-native replacement for the
reference's fused CUDA LSTM/GRU kernels (reference: paddle/cuda/src/
hl_cuda_lstm.cu, hl_gpu_gru.cuh, consumed by paddle/gserver/layers/
{LstmLayer,GatedRecurrentLayer}.cpp via SequenceToBatch reordering).

Instead of reordering variable-length sequences into shrinking per-timestep
batches (SequenceToBatch.h), we keep a fixed [B, T, ...] padded layout and
scan over T with a carry-through mask: padded steps propagate the previous
state unchanged.  XLA unrolls the per-step gate math into fused HLO while the
big input projections (x @ W) stay *outside* the scan as one [B*T] matmul on
the MXU.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from paddle_tpu.ops.activations import get_activation

# Step-body unroll factor for the recurrence scans: amortizes per-iteration
# scan overhead across MXU-bound small matmuls (measured on v5e, GRU
# B=128/T=50/H=512 fwd+bwd: unroll 1 -> 5.6 ms, 4 -> 4.1 ms; 8 is no
# better).  lax.scan handles non-divisible lengths itself.
_UNROLL = 4


def _time_major(x):
    """[B, T, D] -> [T, B, D] for scan."""
    return jnp.swapaxes(x, 0, 1)


def _mask_seq(lengths: Optional[jnp.ndarray], max_len: int, reverse: bool):
    """[T, B, 1] carry mask; for reverse scans the *flipped* positions are
    valid when t >= T - len."""
    if lengths is None:
        return None
    t = jnp.arange(max_len, dtype=jnp.int32)[:, None]
    if reverse:
        valid = t >= (max_len - lengths[None, :])
    else:
        valid = t < lengths[None, :]
    return valid[..., None]


def lstm_scan(
    gates: jnp.ndarray,  # [B, T, 4H] pre-computed input projections (i,f,g,o)
    w_h: jnp.ndarray,  # [H, 4H] recurrent weight
    bias: Optional[jnp.ndarray],  # [4H]
    w_ci: Optional[jnp.ndarray],  # [H] peephole input-gate
    w_cf: Optional[jnp.ndarray],  # [H] peephole forget-gate
    w_co: Optional[jnp.ndarray],  # [H] peephole output-gate
    lengths: Optional[jnp.ndarray] = None,
    *,
    gate_act: str = "sigmoid",
    act: str = "tanh",
    state_act: str = "tanh",
    reverse: bool = False,
    h0: Optional[jnp.ndarray] = None,
    c0: Optional[jnp.ndarray] = None,
) -> Tuple[jnp.ndarray, Tuple[jnp.ndarray, jnp.ndarray]]:
    """Paddle-v1 LSTM with peepholes (LstmLayer.cpp forwardSequence):
        i = σ(a_i + w_ci∘c₋)   f = σ(a_f + w_cf∘c₋)
        c = f∘c₋ + i∘act(a_g)  o = σ(a_o + w_co∘c)   h = o∘state_act(c)
    Returns ([B, T, H] hidden sequence, (h_last, c_last))."""
    b, t, g4 = gates.shape
    h = g4 // 4
    f_gate = get_activation(gate_act)
    f_act = get_activation(act)
    f_state = get_activation(state_act)

    xs = _time_major(gates)
    if reverse:
        xs = jnp.flip(xs, axis=0)
    mask = _mask_seq(lengths, t, reverse)

    h_prev = h0 if h0 is not None else jnp.zeros((b, h), gates.dtype)
    c_prev = c0 if c0 is not None else jnp.zeros((b, h), gates.dtype)

    def step(carry, inp):
        h_p, c_p = carry
        if mask is None:
            x_t, m = inp, None
        else:
            x_t, m = inp
        a = x_t + h_p @ w_h
        if bias is not None:
            a = a + bias
        a_i, a_f, a_g, a_o = jnp.split(a, 4, axis=-1)
        if w_ci is not None:
            a_i = a_i + w_ci * c_p
            a_f = a_f + w_cf * c_p
        i_t = f_gate(a_i)
        f_t = f_gate(a_f)
        c_t = f_t * c_p + i_t * f_act(a_g)
        a_o = a_o + (w_co * c_t if w_co is not None else 0.0)
        o_t = f_gate(a_o)
        h_t = o_t * f_state(c_t)
        if m is not None:
            h_t = jnp.where(m, h_t, h_p)
            c_t = jnp.where(m, c_t, c_p)
        return (h_t, c_t), h_t

    inputs = xs if mask is None else (xs, mask)
    (h_last, c_last), hs = lax.scan(
        step, (h_prev, c_prev), inputs, unroll=_UNROLL
    )
    if reverse:
        hs = jnp.flip(hs, axis=0)
    return jnp.swapaxes(hs, 0, 1), (h_last, c_last)


def gru_scan(
    gates: jnp.ndarray,  # [B, T, 3H] input projections (u, r, c)
    w_h: jnp.ndarray,  # [H, 2H] recurrent weight for update+reset
    w_c: jnp.ndarray,  # [H, H] recurrent weight for candidate
    bias: Optional[jnp.ndarray],  # [3H]
    lengths: Optional[jnp.ndarray] = None,
    *,
    gate_act: str = "sigmoid",
    act: str = "tanh",
    reverse: bool = False,
    h0: Optional[jnp.ndarray] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Paddle-v1 GRU (GatedRecurrentLayer.cpp / hl_cpu_gru.cuh:238-253,
    hl_gru_ops.cuh gru_resetOutput/gru_finalOutput):
        u = σ(x_u + U_u h₋)   r = σ(x_r + U_r h₋)
        c = act(x_c + (r∘h₋) U_c)        # resetOutput = prevOut*r, THEN gemm
        h = (1-u)∘h₋ + u∘c               # prevOut - u*prevOut + u*frameState
    Returns ([B, T, H], h_last)."""
    b, t, g3 = gates.shape
    h = g3 // 3
    f_gate = get_activation(gate_act)
    f_act = get_activation(act)

    xs = _time_major(gates)
    if reverse:
        xs = jnp.flip(xs, axis=0)
    mask = _mask_seq(lengths, t, reverse)

    h_prev = h0 if h0 is not None else jnp.zeros((b, h), gates.dtype)

    def step(h_p, inp):
        if mask is None:
            x_t, m = inp, None
        else:
            x_t, m = inp
        if bias is not None:
            x_t = x_t + bias
        x_u, x_r, x_c = jnp.split(x_t, 3, axis=-1)
        ur = h_p @ w_h
        u_t = f_gate(x_u + ur[:, :h])
        r_t = f_gate(x_r + ur[:, h:])
        c_t = f_act(x_c + (r_t * h_p) @ w_c)
        h_t = (1.0 - u_t) * h_p + u_t * c_t
        if m is not None:
            h_t = jnp.where(m, h_t, h_p)
        return h_t, h_t

    inputs = xs if mask is None else (xs, mask)
    h_last, hs = lax.scan(step, h_prev, inputs, unroll=_UNROLL)
    if reverse:
        hs = jnp.flip(hs, axis=0)
    return jnp.swapaxes(hs, 0, 1), h_last


def simple_rnn_scan(
    x: jnp.ndarray,  # [B, T, H] input projections
    w_h: jnp.ndarray,  # [H, H]
    bias: Optional[jnp.ndarray],
    lengths: Optional[jnp.ndarray] = None,
    *,
    act: str = "tanh",
    reverse: bool = False,
    h0: Optional[jnp.ndarray] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Plain recurrence h_t = act(x_t + h₋ W) (RecurrentLayer.cpp)."""
    b, t, h = x.shape
    f_act = get_activation(act)
    xs = _time_major(x)
    if reverse:
        xs = jnp.flip(xs, axis=0)
    mask = _mask_seq(lengths, t, reverse)
    h_prev = h0 if h0 is not None else jnp.zeros((b, h), x.dtype)

    def step(h_p, inp):
        if mask is None:
            x_t, m = inp, None
        else:
            x_t, m = inp
        a = x_t + h_p @ w_h
        if bias is not None:
            a = a + bias
        h_t = f_act(a)
        if m is not None:
            h_t = jnp.where(m, h_t, h_p)
        return h_t, h_t

    inputs = xs if mask is None else (xs, mask)
    h_last, hs = lax.scan(step, h_prev, inputs, unroll=_UNROLL)
    if reverse:
        hs = jnp.flip(hs, axis=0)
    return jnp.swapaxes(hs, 0, 1), h_last
