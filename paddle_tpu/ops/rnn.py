"""Recurrent cells as lax.scan loops — the TPU-native replacement for the
reference's fused CUDA LSTM/GRU kernels (reference: paddle/cuda/src/
hl_cuda_lstm.cu, hl_gpu_gru.cuh, consumed by paddle/gserver/layers/
{LstmLayer,GatedRecurrentLayer}.cpp via SequenceToBatch reordering).

Instead of reordering variable-length sequences into shrinking per-timestep
batches (SequenceToBatch.h), we keep a fixed [B, T, ...] padded layout and
scan over T with a carry-through mask: padded steps propagate the previous
state unchanged.  XLA unrolls the per-step gate math into fused HLO while the
big input projections (x @ W) stay *outside* the scan as one [B*T] matmul on
the MXU.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from paddle_tpu.ops import acc_einsum, acc_matmul
from paddle_tpu.ops.activations import get_activation

# Step-body unroll factor.  All three cells use custom-VJP cores (chain
# GEMMs only inside the scans, weight grads deferred to post-scan einsums),
# whose light bodies are latency-bound on the chained [B,H]x[H,*] matmul:
# unroll=1 measures fastest on v5e (LSTM text-cls B=128/T=100/H=512
# fwd+bwd: unroll 1 -> 5.9 ms, 4 -> 6.9 ms; a bare 200-GEMM chain
# microbench shows the same 13.4 vs 25.5 us/link shape).
_UNROLL_FUSED = 1


def _time_major(x):
    """[B, T, D] -> [T, B, D] for scan."""
    return jnp.swapaxes(x, 0, 1)


def _mask_seq(lengths: Optional[jnp.ndarray], max_len: int, reverse: bool):
    """[T, B, 1] carry mask; for reverse scans the *flipped* positions are
    valid when t >= T - len."""
    if lengths is None:
        return None
    t = jnp.arange(max_len, dtype=jnp.int32)[:, None]
    if reverse:
        valid = t >= (max_len - lengths[None, :])
    else:
        valid = t < lengths[None, :]
    return valid[..., None]


def _lstm_elem(acts, a, c_p, h_p, m, w_ci, w_cf, w_co):
    """The per-step ELEMENTWISE LSTM cell math (everything except the
    recurrent GEMM): a = x_t + h₋W (+bias) already combined.  Shared by the
    forward scan and the backward pass (which re-derives its local VJP from
    this closure, so peepholes/masking/activation choices stay exact)."""
    f_gate = get_activation(acts[0])
    f_act = get_activation(acts[1])
    f_state = get_activation(acts[2])
    a_i, a_f, a_g, a_o = jnp.split(a, 4, axis=-1)
    a_i = a_i + w_ci * c_p
    a_f = a_f + w_cf * c_p
    i_t = f_gate(a_i)
    f_t = f_gate(a_f)
    c_t = f_t * c_p + i_t * f_act(a_g)
    o_t = f_gate(a_o + w_co * c_t)
    h_t = o_t * f_state(c_t)
    h_t = jnp.where(m, h_t, h_p)
    c_t = jnp.where(m, c_t, c_p)
    return h_t, c_t


@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def _lstm_core(acts, xs, w_h, w_ci, w_cf, w_co, h0, c0, mask):
    """Time-major LSTM recurrence with a hand-written VJP.

    Autodiff of the naive scan accumulates dW_h with an extra [H,4H]
    carry + a second [H,B]x[B,4H] GEMM in EVERY backward step — for
    B=128/T=100/H=512 that is ~100 extra chained GEMMs and ~800 MB of f32
    accumulator traffic.  Here the backward scan computes only the gate
    cotangents (one [B,4H]x[4H,H] GEMM per step) and the weight gradient
    is ONE batched einsum over the saved sequences afterwards — the same
    restructuring the reference's fused CUDA kernels do by hand
    (hl_cuda_lstm.cu backwardOneSequence vs its weight-grad GEMM pass).

    xs: [T,B,4H] input projections (+bias), mask: [T,B,1] bool.
    Returns (hs [T,B,H], h_last, c_last)."""
    hs, _as, _cs, h_last, c_last = _lstm_fwd_scan(
        acts, xs, w_h, w_ci, w_cf, w_co, h0, c0, mask
    )
    return hs, h_last, c_last


def _lstm_fwd_scan(acts, xs, w_h, w_ci, w_cf, w_co, h0, c0, mask):
    def step(carry, inp):
        h_p, c_p = carry
        x_t, m = inp
        a = x_t + acc_matmul(h_p, w_h)
        h_t, c_t = _lstm_elem(acts, a, c_p, h_p, m, w_ci, w_cf, w_co)
        return (h_t, c_t), (h_t, a, c_t)

    (h_last, c_last), (hs, a_seq, c_seq) = lax.scan(
        step, (h0, c0), (xs, mask), unroll=_UNROLL_FUSED
    )
    return hs, a_seq, c_seq, h_last, c_last


def _lstm_core_fwd(acts, xs, w_h, w_ci, w_cf, w_co, h0, c0, mask):
    hs, a_seq, c_seq, h_last, c_last = _lstm_fwd_scan(
        acts, xs, w_h, w_ci, w_cf, w_co, h0, c0, mask
    )
    res = (a_seq, c_seq, hs, w_h, w_ci, w_cf, w_co, h0, c0, mask)
    return (hs, h_last, c_last), res


def _lstm_core_bwd(acts, res, cts):
    a_seq, c_seq, hs, w_h, w_ci, w_cf, w_co, h0, c0, mask = res
    dhs, dh_last, dc_last = cts
    # previous-step state sequences aligned with step t
    h_prev_seq = jnp.concatenate([h0[None], hs[:-1]], axis=0)
    c_prev_seq = jnp.concatenate([c0[None], c_seq[:-1]], axis=0)
    w_h_t = w_h.T
    # peephole-grad carries accumulate across all T steps: keep them at
    # >= f32 like the deferred weight einsums (bf16 += bf16 over 100 steps
    # loses low bits)
    acc_w = jnp.promote_types(w_ci.dtype, jnp.float32)
    zeros_w = (
        jnp.zeros(w_ci.shape, acc_w),
        jnp.zeros(w_cf.shape, acc_w),
        jnp.zeros(w_co.shape, acc_w),
    )

    def step(carry, inp):
        dh, dc, dwci, dwcf, dwco = carry
        a_t, c_p, h_p, m, dh_out = inp
        dh = dh + dh_out
        _, vjp_fn = jax.vjp(
            lambda a, cp, hp, wci, wcf, wco: _lstm_elem(
                acts, a, cp, hp, m, wci, wcf, wco
            ),
            a_t, c_p, h_p, w_ci, w_cf, w_co,
        )
        da, dc_p, dh_p_elem, dwci_t, dwcf_t, dwco_t = vjp_fn((dh, dc))
        dh_p = acc_matmul(da, w_h_t) + dh_p_elem  # the ONE backward-chain GEMM
        return (
            (
                dh_p,
                dc_p,
                dwci + dwci_t.astype(dwci.dtype),
                dwcf + dwcf_t.astype(dwcf.dtype),
                dwco + dwco_t.astype(dwco.dtype),
            ),
            da,
        )

    (dh0, dc0, dwci, dwcf, dwco), da_seq = lax.scan(
        step,
        (dh_last, dc_last, *zeros_w),
        (a_seq, c_prev_seq, h_prev_seq, mask, dhs),
        reverse=True,
        unroll=_UNROLL_FUSED,
    )
    # weight grad as ONE big GEMM over the whole sequence, accumulated at
    # >= f32 (bf16 inputs accumulate f32; f64 tests stay f64)
    acc = jnp.promote_types(w_h.dtype, jnp.float32)
    dw_h = jnp.einsum(
        "tbh,tbg->hg", h_prev_seq, da_seq,
        preferred_element_type=acc,
    ).astype(w_h.dtype)
    d_mask = np.zeros(mask.shape, dtype=jax.dtypes.float0)
    return (
        da_seq,
        dw_h,
        dwci.astype(w_ci.dtype),
        dwcf.astype(w_cf.dtype),
        dwco.astype(w_co.dtype),
        dh0,
        dc0,
        d_mask,
    )


_lstm_core.defvjp(_lstm_core_fwd, _lstm_core_bwd)


def lstm_scan(
    gates: jnp.ndarray,  # [B, T, 4H] pre-computed input projections (i,f,g,o)
    w_h: jnp.ndarray,  # [H, 4H] recurrent weight
    bias: Optional[jnp.ndarray],  # [4H]
    w_ci: Optional[jnp.ndarray],  # [H] peephole input-gate
    w_cf: Optional[jnp.ndarray],  # [H] peephole forget-gate
    w_co: Optional[jnp.ndarray],  # [H] peephole output-gate
    lengths: Optional[jnp.ndarray] = None,
    *,
    gate_act: str = "sigmoid",
    act: str = "tanh",
    state_act: str = "tanh",
    reverse: bool = False,
    h0: Optional[jnp.ndarray] = None,
    c0: Optional[jnp.ndarray] = None,
) -> Tuple[jnp.ndarray, Tuple[jnp.ndarray, jnp.ndarray]]:
    """Paddle-v1 LSTM with peepholes (LstmLayer.cpp forwardSequence):
        i = σ(a_i + w_ci∘c₋)   f = σ(a_f + w_cf∘c₋)
        c = f∘c₋ + i∘act(a_g)  o = σ(a_o + w_co∘c)   h = o∘state_act(c)
    Returns ([B, T, H] hidden sequence, (h_last, c_last))."""
    b, t, g4 = gates.shape
    h = g4 // 4

    xs = _time_major(gates)
    if bias is not None:
        xs = xs + bias  # num: allow[N401] LSTM gate-bias grad reduce rides the compute dtype (folds into the projection GEMM's epilogue); weight grads accumulate f32 post-scan
    if reverse:
        xs = jnp.flip(xs, axis=0)
    mask = _mask_seq(lengths, t, reverse)
    if mask is None:
        mask = jnp.ones((t, b, 1), bool)

    h_prev = h0 if h0 is not None else jnp.zeros((b, h), gates.dtype)
    c_prev = c0 if c0 is not None else jnp.zeros((b, h), gates.dtype)
    zeros_h = jnp.zeros((h,), gates.dtype)
    hs, h_last, c_last = _lstm_core(
        (gate_act, act, state_act),
        xs,
        w_h,
        w_ci if w_ci is not None else zeros_h,
        w_cf if w_cf is not None else zeros_h,
        w_co if w_co is not None else zeros_h,
        h_prev,
        c_prev,
        mask,
    )
    if reverse:
        hs = jnp.flip(hs, axis=0)
    return jnp.swapaxes(hs, 0, 1), (h_last, c_last)


def gru_scan(
    gates: jnp.ndarray,  # [B, T, 3H] input projections (u, r, c)
    w_h: jnp.ndarray,  # [H, 2H] recurrent weight for update+reset
    w_c: jnp.ndarray,  # [H, H] recurrent weight for candidate
    bias: Optional[jnp.ndarray],  # [3H]
    lengths: Optional[jnp.ndarray] = None,
    *,
    gate_act: str = "sigmoid",
    act: str = "tanh",
    reverse: bool = False,
    h0: Optional[jnp.ndarray] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Paddle-v1 GRU (GatedRecurrentLayer.cpp / hl_cpu_gru.cuh:238-253,
    hl_gru_ops.cuh gru_resetOutput/gru_finalOutput):
        u = σ(x_u + U_u h₋)   r = σ(x_r + U_r h₋)
        c = act(x_c + (r∘h₋) U_c)        # resetOutput = prevOut*r, THEN gemm
        h = (1-u)∘h₋ + u∘c               # prevOut - u*prevOut + u*frameState
    Returns ([B, T, H], h_last)."""
    b, t, g3 = gates.shape
    h = g3 // 3

    xs = _time_major(gates)
    if bias is not None:
        xs = xs + bias  # num: allow[N401] GRU gate-bias grad reduce rides the compute dtype; weight grads accumulate f32 post-scan
    if reverse:
        xs = jnp.flip(xs, axis=0)
    mask = _mask_seq(lengths, t, reverse)
    if mask is None:
        mask = jnp.ones((t, b, 1), bool)

    h_prev = h0 if h0 is not None else jnp.zeros((b, h), gates.dtype)
    hs, h_last = _gru_core((gate_act, act), xs, w_h, w_c, h_prev, mask)
    if reverse:
        hs = jnp.flip(hs, axis=0)
    return jnp.swapaxes(hs, 0, 1), h_last


def _gru_reset(acts, p_r, h_p):
    """rh = σ(p_r) ∘ h₋ — the reference's gru_resetOutput (hl_gru_ops.cuh),
    separated out because the candidate GEMM consumes its result."""
    return get_activation(acts[0])(p_r) * h_p


def _gru_final(acts, p_u, p_c, h_p, m):
    """h = (1-u)∘h₋ + u∘c with carry-through masking (gru_finalOutput)."""
    u = get_activation(acts[0])(p_u)
    c = get_activation(acts[1])(p_c)
    h_t = (1.0 - u) * h_p + u * c
    return jnp.where(m, h_t, h_p)


@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def _gru_core(acts, xs, w_h, w_c, h0, mask):
    """Time-major GRU recurrence with a hand-written VJP (same deferment
    as _lstm_core: the backward scan runs only the two transposed chain
    GEMMs per step; dW_h / dW_c become two post-scan einsums over the
    saved sequences instead of per-step accumulator carries).

    xs: [T,B,3H] input projections (+bias) in (u, r, c) slot order.
    Returns (hs [T,B,H], h_last)."""
    hs, _p, _q, h_last = _gru_fwd_scan(acts, xs, w_h, w_c, h0, mask)
    return hs, h_last


def _gru_fwd_scan(acts, xs, w_h, w_c, h0, mask):
    h = h0.shape[-1]

    def step(h_p, inp):
        x_t, m = inp
        ur = acc_matmul(h_p, w_h)
        p_ur = x_t[:, : 2 * h] + ur
        rh = _gru_reset(acts, p_ur[:, h:], h_p)
        p_c = x_t[:, 2 * h :] + acc_matmul(rh, w_c)
        h_t = _gru_final(acts, p_ur[:, :h], p_c, h_p, m)
        return h_t, (h_t, p_ur, p_c)

    h_last, (hs, p_ur_seq, p_c_seq) = lax.scan(
        step, h0, (xs, mask), unroll=_UNROLL_FUSED
    )
    return hs, p_ur_seq, p_c_seq, h_last


def _gru_core_fwd(acts, xs, w_h, w_c, h0, mask):
    hs, p_ur_seq, p_c_seq, h_last = _gru_fwd_scan(acts, xs, w_h, w_c, h0, mask)
    return (hs, h_last), (p_ur_seq, p_c_seq, hs, w_h, w_c, h0, mask)


def _gru_core_bwd(acts, res, cts):
    p_ur_seq, p_c_seq, hs, w_h, w_c, h0, mask = res
    dhs, dh_last = cts
    h = h0.shape[-1]
    h_prev_seq = jnp.concatenate([h0[None], hs[:-1]], axis=0)
    w_h_t = w_h.T
    w_c_t = w_c.T

    def step(dh, inp):
        p_ur, p_c, h_p, m, dh_out = inp
        dh = dh + dh_out
        _, vjp_final = jax.vjp(
            lambda pu, pc, hp: _gru_final(acts, pu, pc, hp, m),
            p_ur[:, :h], p_c, h_p,
        )
        dp_u, dp_c, dh_p = vjp_final(dh)
        drh = acc_matmul(dp_c, w_c_t)
        rh, vjp_reset = jax.vjp(
            lambda pr, hp: _gru_reset(acts, pr, hp), p_ur[:, h:], h_p
        )
        dp_r, dh_p_r = vjp_reset(drh)
        dp_ur = jnp.concatenate([dp_u, dp_r], axis=-1)
        dh_p = dh_p + dh_p_r + acc_matmul(dp_ur, w_h_t)
        return dh_p, (dp_ur, dp_c, rh)

    dh0, (dp_ur_seq, dp_c_seq, rh_seq) = lax.scan(
        step,
        dh_last,
        (p_ur_seq, p_c_seq, h_prev_seq, mask, dhs),
        reverse=True,
        unroll=_UNROLL_FUSED,
    )
    dxs = jnp.concatenate([dp_ur_seq, dp_c_seq], axis=-1)
    acc = jnp.promote_types(w_h.dtype, jnp.float32)
    dw_h = jnp.einsum(
        "tbh,tbg->hg", h_prev_seq, dp_ur_seq,
        preferred_element_type=acc,
    ).astype(w_h.dtype)
    dw_c = jnp.einsum(
        "tbh,tbg->hg", rh_seq, dp_c_seq,
        preferred_element_type=acc,
    ).astype(w_c.dtype)
    d_mask = np.zeros(mask.shape, dtype=jax.dtypes.float0)
    return (dxs, dw_h, dw_c, dh0, d_mask)


_gru_core.defvjp(_gru_core_fwd, _gru_core_bwd)


# ---------------------------------------------------------------------------
# Fused attention-GRU decoder step — the NMT decoder recurrence
# ---------------------------------------------------------------------------
#
# The v1 attention decoder (networks.py simple_attention + gru_step inside a
# recurrent_group) lowers, layer by layer, to a per-step chain of SIX
# dependent GEMMs — expand+fc state projection (computed on [B*S] rows, S×
# redundant), score fc, context reduce, input fc, GRU gate GEMM, GRU
# candidate GEMM — which is exactly the per-timestep launch/latency overhead
# the reference's fused decoder kernels exist to kill (reference:
# paddle/cuda/src/hl_cuda_lstm.cu, 872 LoC of hand-fused per-step math).
#
# The fused core below collapses the step to the MINIMAL dependent chain:
#
#   a1    = h₋ @ [W_sp | U_ur]            one [B,H]x[H,P+2H] GEMM (state
#                                         projection + GRU update/reset
#                                         gates share the h₋ operand)
#   α     = softmax_S(act(ep + sp) · v)   score matvec (+ static enc mask)
#   ctx   = α · enc                       context reduce
#   p     = xg_t + ctx @ W_ctx            one [B,E]x[E,3H] GEMM (the
#                                         target-embedding half of the v1
#                                         "input fc" is precomputed for the
#                                         WHOLE sequence outside the scan)
#   c̃    = act(p_c + (r∘h₋) @ W_c)       the one unavoidable second link
#   h     = (1-u)∘h₋ + u∘c̃
#
# i.e. 2 chained [B,H]-class GEMMs + the attention matvec/reduce per step,
# with the same custom-VJP discipline as the cells above: the backward scan
# runs only transposed chain GEMMs; every weight gradient (dW1, dW_ctx,
# dW_c, dv) and the static-input gradients (d_enc, d_ep) are post-scan
# einsums over the saved sequences.


def _att_scores(att_act: str, ep, sp, v):
    """[B, S] unnormalized attention scores: act(ep + sp[:,None,:]) · v."""
    return jnp.einsum(
        "bsp,p->bs", get_activation(att_act)(ep + sp[:, None, :]), v
    )


def _att_softmax(score, emask):
    """Masked softmax over S, replicating the sequence_softmax activation
    (ops/activations.py): -1e9 fill, softmax, then zero the padding."""
    if emask is not None:
        score = jnp.where(emask, score, -1e9)
    alpha = jax.nn.softmax(score, axis=-1)
    if emask is not None:
        alpha = alpha * emask.astype(alpha.dtype)
    return alpha


def _attgru_step(acts, xg_t, h_p, enc, ep, emask, w1, v, w_ctx, w_c, m):
    """One fused decoder step.  Returns (h_t, saved) where saved carries the
    residuals the hand-written backward needs."""
    p_dim = ep.shape[-1]
    h = h_p.shape[-1]
    a1 = acc_matmul(h_p, w1)  # [B, P+2H]: state projection + GRU u/r gates fused
    sp, ur = a1[:, :p_dim], a1[:, p_dim:]
    alpha = _att_softmax(_att_scores(acts[2], ep, sp, v), emask)
    ctxv = acc_einsum("bs,bse->be", alpha, enc)
    p = xg_t + acc_matmul(ctxv, w_ctx)  # [B, 3H] in (u, r, c) slot order
    pu = p[:, :h] + ur[:, :h]
    pr = p[:, h : 2 * h] + ur[:, h:]
    rh = _gru_reset(acts, pr, h_p)
    cpre = p[:, 2 * h :] + acc_matmul(rh, w_c)
    h_t = _gru_final(acts, pu, cpre, h_p, m)
    return h_t, (sp, alpha, ctxv, pu, pr, cpre)


@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def _attgru_core(opts, xg, enc, ep, emask, w1, v, w_ctx, w_c, h0, mask):
    """Time-major fused attention-GRU recurrence with a hand-written VJP.

    opts: (gate_act, act, att_act, early_exit).
    xg: [T,B,3H] precomputed target-side gate projections (+ biases);
    enc: [B,S,E] context values; ep: [B,S,P] score keys (+ biases);
    emask: [B,S] bool encoder validity or None; w1: [H,P+2H] fused
    state weight [W_state_proj | U_ur]; v: [P] score vector; w_ctx:
    [E,3H]; w_c: [H,H]; mask: [T,B,1] bool decoder-step validity.
    Returns (hs [T,B,H], h_last)."""
    hs, *_rest, h_last = _attgru_fwd_scan(
        opts, xg, enc, ep, emask, w1, v, w_ctx, w_c, h0, mask
    )
    return hs, h_last


def _cond_step(active, live_fn, carry, ys_struct):
    """Shared early-exit step wrapper for the fused scans: run the live
    body when any batch row is live at this step, else pass the carry
    through emitting zeros in the live branch's exact output structure."""

    def dead(c):
        return c, jax.tree_util.tree_map(
            lambda st: jnp.zeros(st.shape, st.dtype), ys_struct
        )

    return lax.cond(active, live_fn, dead, carry)


def _attgru_fwd_scan(opts, xg, enc, ep, emask, w1, v, w_ctx, w_c, h0, mask):
    acts, early = opts[:3], opts[3]

    def live(h_p, x_t, m):
        h_t, saved = _attgru_step(
            acts, x_t, h_p, enc, ep, emask, w1, v, w_ctx, w_c, m
        )
        return h_t, (h_t,) + saved

    if early:
        # bucketed feeds pad T up to a ladder rung: steps past every row's
        # true length are dead for the WHOLE batch — skip their FLOPs, keep
        # the compiled shape (same contract as the generic group scan)
        active_seq = jnp.any(mask[:, :, 0], axis=1)  # [T]
        ys_struct = jax.eval_shape(
            lambda h, x, m: live(h, x, m)[1],
            h0, jax.tree_util.tree_map(lambda u: u[0], xg), mask[0],
        )

        def step(h_p, inp):
            x_t, m, a = inp
            h_t, ys = _cond_step(
                a, lambda h: live(h, x_t, m), h_p, ys_struct
            )
            # dead steps must still emit the CARRY as the step output so
            # hs stays the masked carry-through sequence
            ys = (jnp.where(a, ys[0], h_p),) + ys[1:]
            return h_t, ys

        h_last, seqs = lax.scan(
            step, h0, (xg, mask, active_seq), unroll=_UNROLL_FUSED
        )
    else:
        h_last, seqs = lax.scan(
            lambda h_p, inp: live(h_p, *inp), h0, (xg, mask),
            unroll=_UNROLL_FUSED,
        )
    hs, sp_seq, alpha_seq, ctx_seq, pu_seq, pr_seq, cpre_seq = seqs
    return hs, sp_seq, alpha_seq, ctx_seq, pu_seq, pr_seq, cpre_seq, h_last


def _attgru_core_fwd(opts, xg, enc, ep, emask, w1, v, w_ctx, w_c, h0, mask):
    hs, sp_seq, alpha_seq, ctx_seq, pu_seq, pr_seq, cpre_seq, h_last = (
        _attgru_fwd_scan(opts, xg, enc, ep, emask, w1, v, w_ctx, w_c, h0, mask)
    )
    res = (
        sp_seq, alpha_seq, ctx_seq, pu_seq, pr_seq, cpre_seq, hs,
        enc, ep, emask, w1, v, w_ctx, w_c, h0, mask,
    )
    return (hs, h_last), res


def _attgru_core_bwd(opts, res, cts):
    acts, early = opts[:3], opts[3]
    (sp_seq, alpha_seq, ctx_seq, pu_seq, pr_seq, cpre_seq, hs,
     enc, ep, emask, w1, v, w_ctx, w_c, h0, mask) = res
    dhs, dh_last = cts
    h = h0.shape[-1]
    h_prev_seq = jnp.concatenate([h0[None], hs[:-1]], axis=0)
    w1_t, w_ctx_t, w_c_t = w1.T, w_ctx.T, w_c.T
    f_att = get_activation(acts[2])

    def live(dh, sp, alpha, pu, pr, cpre, h_p, m):
        # GRU tail (same structure as _gru_core_bwd, via the elementwise
        # closures so activation choices stay exact)
        _, vjp_final = jax.vjp(
            lambda a, c, hp: _gru_final(acts, a, c, hp, m), pu, cpre, h_p
        )
        dpu, dcpre, dh_p = vjp_final(dh)
        drh = acc_matmul(dcpre, w_c_t)  # chain GEMM 1
        rh, vjp_reset = jax.vjp(
            lambda p_r, hp: _gru_reset(acts, p_r, hp), pr, h_p
        )
        dpr, dh_p_r = vjp_reset(drh)
        dxg = jnp.concatenate([dpu, dpr, dcpre], axis=-1)  # == dp
        dctx = acc_matmul(dxg, w_ctx_t)  # chain GEMM 2
        dalpha = acc_einsum("be,bse->bs", dctx, enc)
        # masked-softmax VJP: padding has alpha == 0, so it drops out
        dpre = alpha * (
            dalpha - jnp.sum(alpha * dalpha, axis=-1, keepdims=True)
        )
        # score backward: dsp[b,p] = v[p] * Σ_s dpre·act'(ep+sp); act' via
        # jvp so any registered activation works (elementwise, fuses)
        x_s = ep + sp[:, None, :]
        _, fp = jax.jvp(f_att, (x_s,), (jnp.ones_like(x_s),))
        dsp = acc_einsum("bs,bsp->bp", dpre, fp) * v
        da1 = jnp.concatenate([dsp, dpu, dpr], axis=-1)
        dh_p = dh_p + dh_p_r + acc_matmul(da1, w1_t)  # chain GEMM 3 (the h₋ link)
        return dh_p, (da1, dxg, dctx, dpre, rh)

    if early:
        active_seq = jnp.any(mask[:, :, 0], axis=1)
        ys_struct = jax.eval_shape(
            lambda *a: live(*a)[1],
            dhs[0], sp_seq[0], alpha_seq[0], pu_seq[0], pr_seq[0],
            cpre_seq[0], h_prev_seq[0], mask[0],
        )

        def step(dh, inp):
            sp, alpha, pu, pr, cpre, h_p, m, dh_out, a = inp
            dh = dh + dh_out
            return _cond_step(
                a, lambda d: live(d, sp, alpha, pu, pr, cpre, h_p, m),
                dh, ys_struct,
            )

        xs_bwd = (
            sp_seq, alpha_seq, pu_seq, pr_seq, cpre_seq, h_prev_seq, mask,
            dhs, active_seq,
        )
    else:
        def step(dh, inp):
            sp, alpha, pu, pr, cpre, h_p, m, dh_out = inp
            return live(dh + dh_out, sp, alpha, pu, pr, cpre, h_p, m)

        xs_bwd = (
            sp_seq, alpha_seq, pu_seq, pr_seq, cpre_seq, h_prev_seq, mask,
            dhs,
        )

    dh0, (da1_seq, dxg_seq, dctx_seq, dpre_seq, rh_seq) = lax.scan(
        step, dh_last, xs_bwd, reverse=True, unroll=_UNROLL_FUSED
    )

    # every weight gradient is ONE post-scan einsum at >= f32 accumulation
    acc = jnp.promote_types(w1.dtype, jnp.float32)
    dw1 = jnp.einsum(
        "tbh,tbg->hg", h_prev_seq, da1_seq, preferred_element_type=acc
    ).astype(w1.dtype)
    dw_ctx = jnp.einsum(
        "tbe,tbg->eg", ctx_seq, dxg_seq, preferred_element_type=acc
    ).astype(w_ctx.dtype)
    dw_c = jnp.einsum(
        "tbh,tbg->hg", rh_seq, dxg_seq[..., 2 * h :],
        preferred_element_type=acc,
    ).astype(w_c.dtype)
    d_enc = jnp.einsum(
        "tbs,tbe->bse", alpha_seq, dctx_seq, preferred_element_type=acc
    ).astype(enc.dtype)
    # static score-key gradients: the [T,B,S,P] act/act' tensors are traced
    # broadcasts that XLA fuses straight into the t-reduction
    x_big = ep[None] + sp_seq[:, :, None, :]
    th_big = f_att(x_big)
    _, fp_big = jax.jvp(f_att, (x_big,), (jnp.ones_like(x_big),))
    dv = jnp.einsum(
        "tbs,tbsp->p", dpre_seq, th_big, preferred_element_type=acc
    ).astype(v.dtype)
    d_ep = (
        jnp.einsum(
            "tbs,tbsp->bsp", dpre_seq, fp_big, preferred_element_type=acc
        )
        * v.astype(acc)
    ).astype(ep.dtype)
    d_emask = (
        None if emask is None else np.zeros(emask.shape, jax.dtypes.float0)
    )
    d_mask = np.zeros(mask.shape, jax.dtypes.float0)
    return (
        dxg_seq, d_enc, d_ep, d_emask, dw1, dv, dw_ctx, dw_c, dh0, d_mask
    )


_attgru_core.defvjp(_attgru_core_fwd, _attgru_core_bwd)


def attention_gru_step(
    xg_t, h_p, enc, enc_proj, enc_mask, w1, v, w_ctx, w_c,
    *, gate_act: str = "sigmoid", act: str = "tanh", att_act: str = "tanh",
):
    """One fused decoder step for GENERATION (beam/greedy stepping): same
    math as the scan core's step, no mask (every generated step is live).
    xg_t: [B, 3H] this step's target-side gate projections (+ biases)."""
    m = jnp.ones((h_p.shape[0], 1), bool)
    h_t, _ = _attgru_step(
        (gate_act, act, att_act), xg_t, h_p, enc, enc_proj, enc_mask,
        w1, v, w_ctx, w_c, m,
    )
    return h_t


def attention_gru_scan(
    gates: jnp.ndarray,  # [B, T, 3H] target-side input projections (+bias)
    enc: jnp.ndarray,  # [B, S, E] encoded sequence (context values)
    enc_proj: jnp.ndarray,  # [B, S, P] projected keys (+ any biases folded)
    w1: jnp.ndarray,  # [H, P+2H] fused [W_state_proj | U_ur]
    v: jnp.ndarray,  # [P] attention score vector
    w_ctx: jnp.ndarray,  # [E, 3H] context -> gates projection
    w_c: jnp.ndarray,  # [H, H] GRU candidate recurrent weight
    enc_lengths: Optional[jnp.ndarray] = None,
    lengths: Optional[jnp.ndarray] = None,
    *,
    gate_act: str = "sigmoid",
    act: str = "tanh",
    att_act: str = "tanh",
    reverse: bool = False,
    h0: Optional[jnp.ndarray] = None,
    early_exit: bool = False,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Fused Bahdanau-attention GRU decoder over a padded batch.

    Semantically identical to the unfused v1 lowering (simple_attention +
    gru_step in a recurrent_group) — pinned by tests/test_attention_gru_fused
    against naive autodiff in f64.  Returns ([B, T, H], h_last)."""
    b, t, _g3 = gates.shape
    h = w_c.shape[0]
    xs = _time_major(gates)
    if reverse:
        xs = jnp.flip(xs, axis=0)
    mask = _mask_seq(lengths, t, reverse)
    if mask is None:
        mask = jnp.ones((t, b, 1), bool)
    emask = None
    if enc_lengths is not None:
        s = enc.shape[1]
        emask = jnp.arange(s, dtype=jnp.int32)[None, :] < enc_lengths[:, None]
    h_prev = h0 if h0 is not None else jnp.zeros((b, h), gates.dtype)
    hs, h_last = _attgru_core(
        (gate_act, act, att_act, bool(early_exit)),
        xs, enc, enc_proj, emask, w1, v, w_ctx, w_c, h_prev, mask,
    )
    if reverse:
        hs = jnp.flip(hs, axis=0)
    return jnp.swapaxes(hs, 0, 1), h_last


def simple_rnn_scan(
    x: jnp.ndarray,  # [B, T, H] input projections
    w_h: jnp.ndarray,  # [H, H]
    bias: Optional[jnp.ndarray],
    lengths: Optional[jnp.ndarray] = None,
    *,
    act: str = "tanh",
    reverse: bool = False,
    h0: Optional[jnp.ndarray] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Plain recurrence h_t = act(x_t + h₋ W) (RecurrentLayer.cpp)."""
    b, t, h = x.shape
    xs = _time_major(x)
    if bias is not None:
        xs = xs + bias
    if reverse:
        xs = jnp.flip(xs, axis=0)
    mask = _mask_seq(lengths, t, reverse)
    if mask is None:
        mask = jnp.ones((t, b, 1), bool)
    h_prev = h0 if h0 is not None else jnp.zeros((b, h), x.dtype)
    hs, h_last = _rnn_core((act,), xs, w_h, h_prev, mask)
    if reverse:
        hs = jnp.flip(hs, axis=0)
    return jnp.swapaxes(hs, 0, 1), h_last


def _rnn_act(acts, a, h_p, m):
    return jnp.where(m, get_activation(acts[0])(a), h_p)


@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def _rnn_core(acts, xs, w_h, h0, mask):
    """Plain recurrence with the same deferred-weight-grad VJP as
    _lstm_core / _gru_core."""
    hs, _a, h_last = _rnn_fwd_scan(acts, xs, w_h, h0, mask)
    return hs, h_last


def _rnn_fwd_scan(acts, xs, w_h, h0, mask):
    def step(h_p, inp):
        x_t, m = inp
        a = x_t + acc_matmul(h_p, w_h)
        h_t = _rnn_act(acts, a, h_p, m)
        return h_t, (h_t, a)

    h_last, (hs, a_seq) = lax.scan(step, h0, (xs, mask), unroll=_UNROLL_FUSED)
    return hs, a_seq, h_last


def _rnn_core_fwd(acts, xs, w_h, h0, mask):
    hs, a_seq, h_last = _rnn_fwd_scan(acts, xs, w_h, h0, mask)
    return (hs, h_last), (a_seq, hs, w_h, h0, mask)


def _rnn_core_bwd(acts, res, cts):
    a_seq, hs, w_h, h0, mask = res
    dhs, dh_last = cts
    h_prev_seq = jnp.concatenate([h0[None], hs[:-1]], axis=0)
    w_h_t = w_h.T

    def step(dh, inp):
        a_t, h_p, m, dh_out = inp
        dh = dh + dh_out
        _, vjp_fn = jax.vjp(lambda a, hp: _rnn_act(acts, a, hp, m), a_t, h_p)
        da, dh_p_elem = vjp_fn(dh)
        return acc_matmul(da, w_h_t) + dh_p_elem, da

    dh0, da_seq = lax.scan(
        step,
        dh_last,
        (a_seq, h_prev_seq, mask, dhs),
        reverse=True,
        unroll=_UNROLL_FUSED,
    )
    dw_h = jnp.einsum(
        "tbh,tbg->hg", h_prev_seq, da_seq,
        preferred_element_type=jnp.promote_types(w_h.dtype, jnp.float32),
    ).astype(w_h.dtype)
    d_mask = np.zeros(mask.shape, dtype=jax.dtypes.float0)
    return (da_seq, dw_h, dh0, d_mask)


_rnn_core.defvjp(_rnn_core_fwd, _rnn_core_bwd)
