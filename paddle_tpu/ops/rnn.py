"""Recurrent cells as lax.scan loops — the TPU-native replacement for the
reference's fused CUDA LSTM/GRU kernels (reference: paddle/cuda/src/
hl_cuda_lstm.cu, hl_gpu_gru.cuh, consumed by paddle/gserver/layers/
{LstmLayer,GatedRecurrentLayer}.cpp via SequenceToBatch reordering).

Instead of reordering variable-length sequences into shrinking per-timestep
batches (SequenceToBatch.h), we keep a fixed [B, T, ...] padded layout and
scan over T with a carry-through mask: padded steps propagate the previous
state unchanged.  XLA unrolls the per-step gate math into fused HLO while the
big input projections (x @ W) stay *outside* the scan as one [B*T] matmul on
the MXU.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from paddle_tpu.ops.activations import get_activation

# Step-body unroll factors.  The custom-VJP LSTM core (one GEMM per step
# in BOTH directions, weight grads deferred to a single post-scan GEMM) is
# latency-bound on the chained [B,H]x[H,4H] matmul and unroll=1 measures
# fastest on v5e (LSTM text-cls B=128/T=100/H=512 fwd+bwd: unroll 1 ->
# 5.9 ms, 4 -> 6.9 ms; a bare 200-GEMM chain microbench shows the same
# 13.4 vs 25.5 us/link shape).  The GRU/simple-RNN scans still use naive
# autodiff whose heavier backward bodies (per-step weight-grad GEMM +
# accumulator) amortize best at the previously measured unroll=4 (GRU
# B=128/T=50/H=512 fwd+bwd: unroll 1 -> 5.6 ms, 4 -> 4.1 ms).
_UNROLL_FUSED = 1
_UNROLL = 4


def _time_major(x):
    """[B, T, D] -> [T, B, D] for scan."""
    return jnp.swapaxes(x, 0, 1)


def _mask_seq(lengths: Optional[jnp.ndarray], max_len: int, reverse: bool):
    """[T, B, 1] carry mask; for reverse scans the *flipped* positions are
    valid when t >= T - len."""
    if lengths is None:
        return None
    t = jnp.arange(max_len, dtype=jnp.int32)[:, None]
    if reverse:
        valid = t >= (max_len - lengths[None, :])
    else:
        valid = t < lengths[None, :]
    return valid[..., None]


def _lstm_elem(acts, a, c_p, h_p, m, w_ci, w_cf, w_co):
    """The per-step ELEMENTWISE LSTM cell math (everything except the
    recurrent GEMM): a = x_t + h₋W (+bias) already combined.  Shared by the
    forward scan and the backward pass (which re-derives its local VJP from
    this closure, so peepholes/masking/activation choices stay exact)."""
    f_gate = get_activation(acts[0])
    f_act = get_activation(acts[1])
    f_state = get_activation(acts[2])
    a_i, a_f, a_g, a_o = jnp.split(a, 4, axis=-1)
    a_i = a_i + w_ci * c_p
    a_f = a_f + w_cf * c_p
    i_t = f_gate(a_i)
    f_t = f_gate(a_f)
    c_t = f_t * c_p + i_t * f_act(a_g)
    o_t = f_gate(a_o + w_co * c_t)
    h_t = o_t * f_state(c_t)
    h_t = jnp.where(m, h_t, h_p)
    c_t = jnp.where(m, c_t, c_p)
    return h_t, c_t


@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def _lstm_core(acts, xs, w_h, w_ci, w_cf, w_co, h0, c0, mask):
    """Time-major LSTM recurrence with a hand-written VJP.

    Autodiff of the naive scan accumulates dW_h with an extra [H,4H]
    carry + a second [H,B]x[B,4H] GEMM in EVERY backward step — for
    B=128/T=100/H=512 that is ~100 extra chained GEMMs and ~800 MB of f32
    accumulator traffic.  Here the backward scan computes only the gate
    cotangents (one [B,4H]x[4H,H] GEMM per step) and the weight gradient
    is ONE batched einsum over the saved sequences afterwards — the same
    restructuring the reference's fused CUDA kernels do by hand
    (hl_cuda_lstm.cu backwardOneSequence vs its weight-grad GEMM pass).

    xs: [T,B,4H] input projections (+bias), mask: [T,B,1] bool.
    Returns (hs [T,B,H], h_last, c_last)."""
    hs, _as, _cs, h_last, c_last = _lstm_fwd_scan(
        acts, xs, w_h, w_ci, w_cf, w_co, h0, c0, mask
    )
    return hs, h_last, c_last


def _lstm_fwd_scan(acts, xs, w_h, w_ci, w_cf, w_co, h0, c0, mask):
    def step(carry, inp):
        h_p, c_p = carry
        x_t, m = inp
        a = x_t + h_p @ w_h
        h_t, c_t = _lstm_elem(acts, a, c_p, h_p, m, w_ci, w_cf, w_co)
        return (h_t, c_t), (h_t, a, c_t)

    (h_last, c_last), (hs, a_seq, c_seq) = lax.scan(
        step, (h0, c0), (xs, mask), unroll=_UNROLL_FUSED
    )
    return hs, a_seq, c_seq, h_last, c_last


def _lstm_core_fwd(acts, xs, w_h, w_ci, w_cf, w_co, h0, c0, mask):
    hs, a_seq, c_seq, h_last, c_last = _lstm_fwd_scan(
        acts, xs, w_h, w_ci, w_cf, w_co, h0, c0, mask
    )
    res = (a_seq, c_seq, hs, w_h, w_ci, w_cf, w_co, h0, c0, mask)
    return (hs, h_last, c_last), res


def _lstm_core_bwd(acts, res, cts):
    a_seq, c_seq, hs, w_h, w_ci, w_cf, w_co, h0, c0, mask = res
    dhs, dh_last, dc_last = cts
    t = a_seq.shape[0]
    # previous-step state sequences aligned with step t
    h_prev_seq = jnp.concatenate([h0[None], hs[:-1]], axis=0)
    c_prev_seq = jnp.concatenate([c0[None], c_seq[:-1]], axis=0)
    w_h_t = w_h.T
    zeros_w = (
        jnp.zeros_like(w_ci),
        jnp.zeros_like(w_cf),
        jnp.zeros_like(w_co),
    )

    def step(carry, inp):
        dh, dc, dwci, dwcf, dwco = carry
        a_t, c_p, h_p, m, dh_out = inp
        dh = dh + dh_out
        _, vjp_fn = jax.vjp(
            lambda a, cp, hp, wci, wcf, wco: _lstm_elem(
                acts, a, cp, hp, m, wci, wcf, wco
            ),
            a_t, c_p, h_p, w_ci, w_cf, w_co,
        )
        da, dc_p, dh_p_elem, dwci_t, dwcf_t, dwco_t = vjp_fn((dh, dc))
        dh_p = da @ w_h_t + dh_p_elem  # the ONE backward-chain GEMM
        return (
            (dh_p, dc_p, dwci + dwci_t, dwcf + dwcf_t, dwco + dwco_t),
            da,
        )

    (dh0, dc0, dwci, dwcf, dwco), da_seq = lax.scan(
        step,
        (dh_last, dc_last, *zeros_w),
        (a_seq, c_prev_seq, h_prev_seq, mask, dhs),
        reverse=True,
        unroll=_UNROLL_FUSED,
    )
    # weight grad as ONE big GEMM over the whole sequence (f32 accumulate)
    dw_h = jnp.einsum(
        "tbh,tbg->hg", h_prev_seq, da_seq,
        preferred_element_type=jnp.float32,
    ).astype(w_h.dtype)
    d_mask = np.zeros(mask.shape, dtype=jax.dtypes.float0)
    return (da_seq, dw_h, dwci, dwcf, dwco, dh0, dc0, d_mask)


_lstm_core.defvjp(_lstm_core_fwd, _lstm_core_bwd)


def lstm_scan(
    gates: jnp.ndarray,  # [B, T, 4H] pre-computed input projections (i,f,g,o)
    w_h: jnp.ndarray,  # [H, 4H] recurrent weight
    bias: Optional[jnp.ndarray],  # [4H]
    w_ci: Optional[jnp.ndarray],  # [H] peephole input-gate
    w_cf: Optional[jnp.ndarray],  # [H] peephole forget-gate
    w_co: Optional[jnp.ndarray],  # [H] peephole output-gate
    lengths: Optional[jnp.ndarray] = None,
    *,
    gate_act: str = "sigmoid",
    act: str = "tanh",
    state_act: str = "tanh",
    reverse: bool = False,
    h0: Optional[jnp.ndarray] = None,
    c0: Optional[jnp.ndarray] = None,
) -> Tuple[jnp.ndarray, Tuple[jnp.ndarray, jnp.ndarray]]:
    """Paddle-v1 LSTM with peepholes (LstmLayer.cpp forwardSequence):
        i = σ(a_i + w_ci∘c₋)   f = σ(a_f + w_cf∘c₋)
        c = f∘c₋ + i∘act(a_g)  o = σ(a_o + w_co∘c)   h = o∘state_act(c)
    Returns ([B, T, H] hidden sequence, (h_last, c_last))."""
    b, t, g4 = gates.shape
    h = g4 // 4

    xs = _time_major(gates)
    if bias is not None:
        xs = xs + bias  # folds into the producing projection GEMM's epilogue
    if reverse:
        xs = jnp.flip(xs, axis=0)
    mask = _mask_seq(lengths, t, reverse)
    if mask is None:
        mask = jnp.ones((t, b, 1), bool)

    h_prev = h0 if h0 is not None else jnp.zeros((b, h), gates.dtype)
    c_prev = c0 if c0 is not None else jnp.zeros((b, h), gates.dtype)
    zeros_h = jnp.zeros((h,), gates.dtype)
    hs, h_last, c_last = _lstm_core(
        (gate_act, act, state_act),
        xs,
        w_h,
        w_ci if w_ci is not None else zeros_h,
        w_cf if w_cf is not None else zeros_h,
        w_co if w_co is not None else zeros_h,
        h_prev,
        c_prev,
        mask,
    )
    if reverse:
        hs = jnp.flip(hs, axis=0)
    return jnp.swapaxes(hs, 0, 1), (h_last, c_last)


def gru_scan(
    gates: jnp.ndarray,  # [B, T, 3H] input projections (u, r, c)
    w_h: jnp.ndarray,  # [H, 2H] recurrent weight for update+reset
    w_c: jnp.ndarray,  # [H, H] recurrent weight for candidate
    bias: Optional[jnp.ndarray],  # [3H]
    lengths: Optional[jnp.ndarray] = None,
    *,
    gate_act: str = "sigmoid",
    act: str = "tanh",
    reverse: bool = False,
    h0: Optional[jnp.ndarray] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Paddle-v1 GRU (GatedRecurrentLayer.cpp / hl_cpu_gru.cuh:238-253,
    hl_gru_ops.cuh gru_resetOutput/gru_finalOutput):
        u = σ(x_u + U_u h₋)   r = σ(x_r + U_r h₋)
        c = act(x_c + (r∘h₋) U_c)        # resetOutput = prevOut*r, THEN gemm
        h = (1-u)∘h₋ + u∘c               # prevOut - u*prevOut + u*frameState
    Returns ([B, T, H], h_last)."""
    b, t, g3 = gates.shape
    h = g3 // 3
    f_gate = get_activation(gate_act)
    f_act = get_activation(act)

    xs = _time_major(gates)
    if reverse:
        xs = jnp.flip(xs, axis=0)
    mask = _mask_seq(lengths, t, reverse)

    h_prev = h0 if h0 is not None else jnp.zeros((b, h), gates.dtype)

    def step(h_p, inp):
        if mask is None:
            x_t, m = inp, None
        else:
            x_t, m = inp
        if bias is not None:
            x_t = x_t + bias
        x_u, x_r, x_c = jnp.split(x_t, 3, axis=-1)
        ur = h_p @ w_h
        u_t = f_gate(x_u + ur[:, :h])
        r_t = f_gate(x_r + ur[:, h:])
        c_t = f_act(x_c + (r_t * h_p) @ w_c)
        h_t = (1.0 - u_t) * h_p + u_t * c_t
        if m is not None:
            h_t = jnp.where(m, h_t, h_p)
        return h_t, h_t

    inputs = xs if mask is None else (xs, mask)
    h_last, hs = lax.scan(step, h_prev, inputs, unroll=_UNROLL)
    if reverse:
        hs = jnp.flip(hs, axis=0)
    return jnp.swapaxes(hs, 0, 1), h_last


def simple_rnn_scan(
    x: jnp.ndarray,  # [B, T, H] input projections
    w_h: jnp.ndarray,  # [H, H]
    bias: Optional[jnp.ndarray],
    lengths: Optional[jnp.ndarray] = None,
    *,
    act: str = "tanh",
    reverse: bool = False,
    h0: Optional[jnp.ndarray] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Plain recurrence h_t = act(x_t + h₋ W) (RecurrentLayer.cpp)."""
    b, t, h = x.shape
    f_act = get_activation(act)
    xs = _time_major(x)
    if reverse:
        xs = jnp.flip(xs, axis=0)
    mask = _mask_seq(lengths, t, reverse)
    h_prev = h0 if h0 is not None else jnp.zeros((b, h), x.dtype)

    def step(h_p, inp):
        if mask is None:
            x_t, m = inp, None
        else:
            x_t, m = inp
        a = x_t + h_p @ w_h
        if bias is not None:
            a = a + bias
        h_t = f_act(a)
        if m is not None:
            h_t = jnp.where(m, h_t, h_p)
        return h_t, h_t

    inputs = xs if mask is None else (xs, mask)
    h_last, hs = lax.scan(step, h_prev, inputs, unroll=_UNROLL)
    if reverse:
        hs = jnp.flip(hs, axis=0)
    return jnp.swapaxes(hs, 0, 1), h_last
