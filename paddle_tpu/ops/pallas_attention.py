"""Fused (flash) attention as a Pallas TPU kernel.

The dense MHA path materializes the [T, T] score matrix in HBM; this kernel
streams key/value blocks through VMEM with an online softmax, so attention
memory is O(T·dh) and the scores never leave the core — the standard
flash-attention recipe, written for the MXU:

  * grid = (B, H, T/bq); each program owns one [bq, dh] query block,
  * the k-loop walks [bk, dh] key/value blocks with jnp.dot at
    preferred_element_type=f32 (MXU-native bf16 in, f32 accumulate),
  * causal masking + key-padding fold into the streaming max/normalizer.

Used by multi_head_attention for self-attention on the TPU backend when the
`use_pallas_attention` flag is on (opt-in: the win is MEMORY — no [T, T]
scores in HBM, enabling context lengths the dense path cannot hold; for
short sequences XLA's fused dense attention is faster because the kernel
pays full-precision MXU passes).  `interpret=True` runs the same kernel on
CPU for tests.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def flash_attention(
    q: jnp.ndarray,  # [B, T, H, dh]
    k: jnp.ndarray,
    v: jnp.ndarray,
    lengths: Optional[jnp.ndarray] = None,  # [B] valid key counts
    causal: bool = False,
    block_q: int = 128,
    block_k: int = 128,
    interpret: bool = False,
) -> jnp.ndarray:
    """[B, T, H, dh] -> [B, T, H, dh]; exact softmax attention (one kernel
    shared with the differentiable path; the LSE residual is simply
    dropped here)."""
    out, _ = _flash_fwd(q, k, v, lengths, causal, block_q, block_k, interpret)
    return out


def supported(t: int, dh: int) -> bool:
    """Shapes the kernel handles well: T a multiple of a block, lane-friendly
    head dim."""
    return t % min(128, t) == 0 and dh % 8 == 0 and t >= 128


def auto_blocks(t: int) -> tuple:
    """v5e-tuned (block_q, block_k) for sequence length t, from an on-chip
    sweep of the fwd+bwd train path (bq in {128..2048} x bk in {128..1024},
    B=8/T=1024 and B=4/T=2048, bf16): large query blocks win — fewer grid
    steps and better MXU pipelining — with bk=512 the sweet spot:
      T=1024: 128/128 6.03 ms -> 512/512 4.28 ms
      T=2048: 128/128 8.51 ms -> 1024/512 3.66 ms"""
    bq = min(max(t // 2, 128), 1024)
    while t % bq:
        bq //= 2
    bk = min(512, t)
    while t % bk:
        bk //= 2
    return bq, bk


# ---------------------------------------------------------------------------
# backward kernels — the standard two-pass flash backward:
#   forward additionally emits LSE (log-sum-exp per query row) so p = exp(s -
#   lse) is recomputable blockwise; delta = rowsum(do * o) folds the softmax
#   jacobian.  dq loops k-blocks per q-block; dk/dv loop q-blocks per k-block.
# ---------------------------------------------------------------------------


def _fa_fwd_kernel(
    len_ref, q_ref, k_ref, v_ref, o_ref, lse_ref, *, bk, t, causal, scale, bq
):
    qi = pl.program_id(2)
    # Keep MXU operands in the INPUT dtype (bf16 on the bench path): the MXU
    # is bf16-native, and f32 operands with Precision.HIGHEST cost multiple
    # passes — accumulation stays f32 via preferred_element_type (the
    # standard TPU flash recipe; softmax statistics are always f32).
    q = q_ref[...]
    dh = q.shape[-1]
    q_pos = qi * bq + jax.lax.iota(jnp.int32, bq)
    valid_len = len_ref[pl.program_id(0)]
    nk = t // bk

    def body(j, carry):
        m, l, acc = carry
        k = k_ref[pl.ds(j * bk, bk), :]
        v = v_ref[pl.ds(j * bk, bk), :]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale
        k_pos = j * bk + jax.lax.iota(jnp.int32, bk)
        mask = k_pos[None, :] < valid_len
        if causal:
            mask &= k_pos[None, :] <= q_pos[:, None]
        s = jnp.where(mask, s, NEG_INF)
        blk_max = jnp.max(s, axis=-1)
        m_new = jnp.maximum(m, blk_max)
        p = jnp.where(mask, jnp.exp(s - m_new[:, None]), 0.0)
        corr = jnp.exp(m - m_new)
        acc = acc * corr[:, None] + jnp.dot(
            p.astype(v.dtype), v, preferred_element_type=jnp.float32,
        )
        l = l * corr + jnp.sum(p, axis=-1)
        return m_new, l, acc

    m0 = jnp.full((bq,), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((bq,), jnp.float32)
    acc0 = jnp.zeros((bq, q.shape[-1]), jnp.float32)
    # causal: key blocks past this query block's diagonal are fully masked —
    # skip them (standard flash practice, ~2x on long causal sequences)
    upper = ((qi + 1) * bq + bk - 1) // bk if causal else nk
    m, l, acc = jax.lax.fori_loop(0, upper, body, (m0, l0, acc0))
    l_safe = jnp.maximum(l, 1e-20)
    o_ref[...] = (acc / l_safe[:, None]).astype(o_ref.dtype)
    # lse block spans the FULL T row (rank-1 bq blocks are not tileable);
    # consecutive qi iterations revisit it, each writing its own slice
    lse_ref[pl.ds(qi * bq, bq), :] = (m + jnp.log(l_safe))[:, None]


def _fa_bwd_dq_kernel(
    len_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
    *, bk, t, causal, scale, bq
):
    qi = pl.program_id(2)
    q = q_ref[...]
    do = do_ref[...]
    lse = lse_ref[pl.ds(qi * bq, bq), 0]
    delta = delta_ref[pl.ds(qi * bq, bq), 0]
    q_pos = qi * bq + jax.lax.iota(jnp.int32, bq)
    valid_len = len_ref[pl.program_id(0)]
    nk = t // bk

    def body(j, dq):
        k = k_ref[pl.ds(j * bk, bk), :]
        v = v_ref[pl.ds(j * bk, bk), :]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale
        k_pos = j * bk + jax.lax.iota(jnp.int32, bk)
        mask = k_pos[None, :] < valid_len
        if causal:
            mask &= k_pos[None, :] <= q_pos[:, None]
        p = jnp.where(mask, jnp.exp(s - lse[:, None]), 0.0)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = (p * (dp - delta[:, None]) * scale).astype(k.dtype)
        return dq + jnp.dot(
            ds, k, preferred_element_type=jnp.float32,
        )

    dq0 = jnp.zeros(q.shape, jnp.float32)  # f32 accumulator (q may be bf16)
    upper = ((qi + 1) * bq + bk - 1) // bk if causal else nk
    dq = jax.lax.fori_loop(0, upper, body, dq0)
    dq_ref[...] = dq.astype(dq_ref.dtype)


def _fa_bwd_dkv_kernel(
    len_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref,
    *, bq_loop, t, causal, scale, bk
):
    ki = pl.program_id(2)
    k = k_ref[...]
    v = v_ref[...]
    k_pos = ki * bk + jax.lax.iota(jnp.int32, bk)
    valid_len = len_ref[pl.program_id(0)]
    nq = t // bq_loop

    def body(j, carry):
        dk, dv = carry
        q = q_ref[pl.ds(j * bq_loop, bq_loop), :]
        do = do_ref[pl.ds(j * bq_loop, bq_loop), :]
        lse = lse_ref[pl.ds(j * bq_loop, bq_loop), 0]
        delta = delta_ref[pl.ds(j * bq_loop, bq_loop), 0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # [bq, bk]
        q_pos = j * bq_loop + jax.lax.iota(jnp.int32, bq_loop)
        mask = k_pos[None, :] < valid_len
        if causal:
            mask &= k_pos[None, :] <= q_pos[:, None]
        p = jnp.where(mask, jnp.exp(s - lse[:, None]), 0.0)  # [bq, bk]
        p_b = p.astype(do.dtype)
        dv = dv + jax.lax.dot_general(
            p_b, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # p^T @ do: [bk, dh]
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [bq, bk]
        ds = (p * (dp - delta[:, None]) * scale).astype(q.dtype)
        dk = dk + jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # ds^T @ q: [bk, dh]
        return dk, dv

    dk0 = jnp.zeros(k.shape, jnp.float32)  # f32 accumulators (k/v may be bf16)
    dv0 = jnp.zeros(v.shape, jnp.float32)
    # causal: query blocks strictly before this key block's diagonal see
    # none of these keys — start at the diagonal
    lower = (ki * bk) // bq_loop if causal else 0
    dk, dv = jax.lax.fori_loop(lower, nq, body, (dk0, dv0))
    dk_ref[...] = dk.astype(dk_ref.dtype)
    dv_ref[...] = dv.astype(dv_ref.dtype)


@functools.partial(
    jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7)
)
def flash_attention_diff(q, k, v, lengths, causal, block_q, block_k, interpret):
    out, _ = _flash_fwd(q, k, v, lengths, causal, block_q, block_k, interpret)
    return out


def _flash_fwd(q, k, v, lengths, causal, block_q, block_k, interpret):
    b, t, h, dh = q.shape
    bq = min(block_q, t)
    bk = min(block_k, t)
    if t % bq or t % bk:
        raise ValueError(
            f"T={t} must be divisible by block sizes ({bq}, {bk}) — rows "
            f"beyond the last full block would be silently dropped"
        )
    scale = 1.0 / math.sqrt(dh)
    if lengths is None:
        lengths = jnp.full((b,), t, jnp.int32)
    qt, kt, vt = (jnp.swapaxes(x, 1, 2) for x in (q, k, v))
    kernel = functools.partial(
        _fa_fwd_kernel, bk=bk, t=t, causal=causal, scale=scale, bq=bq
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(b, h, t // bq),
        in_specs=[
            pl.BlockSpec((None, None, bq, dh), lambda bi, hi, qi, _: (bi, hi, qi, 0)),
            pl.BlockSpec((None, None, t, dh), lambda bi, hi, qi, _: (bi, hi, 0, 0)),
            pl.BlockSpec((None, None, t, dh), lambda bi, hi, qi, _: (bi, hi, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, None, bq, dh), lambda bi, hi, qi, _: (bi, hi, qi, 0)),
            pl.BlockSpec((None, None, t, 1), lambda bi, hi, qi, _: (bi, hi, 0, 0)),
        ],
    )
    out, lse = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((b, h, t, dh), q.dtype),
            jax.ShapeDtypeStruct((b, h, t, 1), jnp.float32),
        ],
        interpret=interpret,
    )(lengths, qt, kt, vt)
    return jnp.swapaxes(out, 1, 2), (q, k, v, lengths, out, lse)


def _flash_fwd_vjp(q, k, v, lengths, causal, block_q, block_k, interpret):
    out, res = _flash_fwd(q, k, v, lengths, causal, block_q, block_k, interpret)
    return out, res


def _flash_bwd(causal, block_q, block_k, interpret, res, g):
    q, k, v, lengths, out_bhtd, lse = res
    b, t, h, dh = q.shape
    bq = min(block_q, t)
    bk = min(block_k, t)
    scale = 1.0 / math.sqrt(dh)
    qt, kt, vt = (jnp.swapaxes(x, 1, 2) for x in (q, k, v))
    do = jnp.swapaxes(g, 1, 2)  # [B, H, T, dh]
    delta = jnp.sum(
        do.astype(jnp.float32) * out_bhtd.astype(jnp.float32), axis=-1
    )[..., None]  # [B, H, T, 1] (rank-2 tileable blocks)

    dq_kernel = functools.partial(
        _fa_bwd_dq_kernel, bk=bk, t=t, causal=causal, scale=scale, bq=bq
    )
    dq_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(b, h, t // bq),
        in_specs=[
            pl.BlockSpec((None, None, bq, dh), lambda bi, hi, qi, _: (bi, hi, qi, 0)),
            pl.BlockSpec((None, None, t, dh), lambda bi, hi, qi, _: (bi, hi, 0, 0)),
            pl.BlockSpec((None, None, t, dh), lambda bi, hi, qi, _: (bi, hi, 0, 0)),
            pl.BlockSpec((None, None, bq, dh), lambda bi, hi, qi, _: (bi, hi, qi, 0)),
            pl.BlockSpec((None, None, t, 1), lambda bi, hi, qi, _: (bi, hi, 0, 0)),
            pl.BlockSpec((None, None, t, 1), lambda bi, hi, qi, _: (bi, hi, 0, 0)),
        ],
        out_specs=pl.BlockSpec(
            (None, None, bq, dh), lambda bi, hi, qi, _: (bi, hi, qi, 0)
        ),
    )
    dq = pl.pallas_call(
        dq_kernel,
        grid_spec=dq_spec,
        out_shape=jax.ShapeDtypeStruct((b, h, t, dh), q.dtype),
        interpret=interpret,
    )(lengths, qt, kt, vt, do, lse, delta)

    dkv_kernel = functools.partial(
        _fa_bwd_dkv_kernel, bq_loop=bq, t=t, causal=causal, scale=scale, bk=bk
    )
    dkv_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(b, h, t // bk),
        in_specs=[
            pl.BlockSpec((None, None, t, dh), lambda bi, hi, ki, _: (bi, hi, 0, 0)),
            pl.BlockSpec((None, None, bk, dh), lambda bi, hi, ki, _: (bi, hi, ki, 0)),
            pl.BlockSpec((None, None, bk, dh), lambda bi, hi, ki, _: (bi, hi, ki, 0)),
            pl.BlockSpec((None, None, t, dh), lambda bi, hi, ki, _: (bi, hi, 0, 0)),
            pl.BlockSpec((None, None, t, 1), lambda bi, hi, ki, _: (bi, hi, 0, 0)),
            pl.BlockSpec((None, None, t, 1), lambda bi, hi, ki, _: (bi, hi, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, None, bk, dh), lambda bi, hi, ki, _: (bi, hi, ki, 0)),
            pl.BlockSpec((None, None, bk, dh), lambda bi, hi, ki, _: (bi, hi, ki, 0)),
        ],
    )
    dk, dv = pl.pallas_call(
        dkv_kernel,
        grid_spec=dkv_spec,
        out_shape=[
            jax.ShapeDtypeStruct((b, h, t, dh), k.dtype),
            jax.ShapeDtypeStruct((b, h, t, dh), v.dtype),
        ],
        interpret=interpret,
    )(lengths, qt, kt, vt, do, lse, delta)

    return (
        jnp.swapaxes(dq, 1, 2),
        jnp.swapaxes(dk, 1, 2),
        jnp.swapaxes(dv, 1, 2),
        None,
    )


flash_attention_diff.defvjp(_flash_fwd_vjp, _flash_bwd)
