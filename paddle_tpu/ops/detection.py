"""SSD detection ops: prior boxes, IoU matching, box codec, NMS.

Reference behavior: paddle/gserver/layers/PriorBox.cpp,
MultiBoxLossLayer.cpp, DetectionOutputLayer.cpp and DetectionUtil.cpp
(encodeBBox/decodeBBox/matchBBox/applyNMSFast).

TPU-native design: everything is static-shape.  Ground truth arrives as a
padded [G, 4] block with a validity mask instead of the reference's
variable-length CSR label argument; NMS runs as a fixed-length lax.scan
(max_out iterations of select-and-suppress) instead of data-dependent list
manipulation; matching is one [P, G] IoU matrix plus argmax/scatter instead
of per-box loops.
"""

from __future__ import annotations

import math
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np


# ---------------------------------------------------------------------------
# priors
# ---------------------------------------------------------------------------


def make_priors(
    h: int,
    w: int,
    min_sizes: Sequence[float],
    max_sizes: Sequence[float],
    aspect_ratios: Sequence[float],
    img_h: int,
    img_w: int,
    clip: bool = True,
) -> np.ndarray:
    """[P, 4] corner-form (xmin,ymin,xmax,ymax) normalized priors for an
    h×w feature map over an img_h×img_w image; cell-major (row-major cells,
    prior variants fastest) to match NHWC conv predictions.  Per-cell order
    mirrors PriorBox.cpp: min box, sqrt(min*max) box, then r and 1/r boxes
    per aspect ratio."""
    step_x, step_y = img_w / w, img_h / h
    variants: List[Tuple[float, float]] = []  # (bw, bh) in pixels
    for k, s in enumerate(min_sizes):
        variants.append((s, s))
        if k < len(max_sizes):
            m = math.sqrt(s * max_sizes[k])
            variants.append((m, m))
        for r in aspect_ratios:
            if abs(r - 1.0) < 1e-6:
                continue
            sr = math.sqrt(r)
            variants.append((s * sr, s / sr))
            variants.append((s / sr, s * sr))
    out = np.zeros((h, w, len(variants), 4), np.float32)
    for i in range(h):
        cy = (i + 0.5) * step_y
        for j in range(w):
            cx = (j + 0.5) * step_x
            for k, (bw, bh) in enumerate(variants):
                out[i, j, k] = [
                    (cx - bw / 2) / img_w,
                    (cy - bh / 2) / img_h,
                    (cx + bw / 2) / img_w,
                    (cy + bh / 2) / img_h,
                ]
    out = out.reshape(-1, 4)
    if clip:
        out = np.clip(out, 0.0, 1.0)
    return out


def priors_per_cell(n_min: int, n_max: int, aspect_ratios: Sequence[float]) -> int:
    n_ar = sum(1 for r in aspect_ratios if abs(r - 1.0) >= 1e-6)
    return n_min * (1 + 2 * n_ar) + n_max


# ---------------------------------------------------------------------------
# geometry
# ---------------------------------------------------------------------------


def box_area(b):
    return jnp.maximum(b[..., 2] - b[..., 0], 0.0) * jnp.maximum(
        b[..., 3] - b[..., 1], 0.0
    )


def iou_matrix(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """[N, 4] × [M, 4] corner-form → [N, M] IoU."""
    lt = jnp.maximum(a[:, None, :2], b[None, :, :2])
    rb = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
    wh = jnp.maximum(rb - lt, 0.0)
    inter = wh[..., 0] * wh[..., 1]
    union = box_area(a)[:, None] + box_area(b)[None, :] - inter
    return jnp.where(union > 0, inter / jnp.maximum(union, 1e-10), 0.0)


def _center_form(b):
    wh = b[..., 2:] - b[..., :2]
    c = (b[..., 2:] + b[..., :2]) * 0.5
    return c, jnp.maximum(wh, 1e-8)


def encode_boxes(gt: jnp.ndarray, priors: jnp.ndarray, variances) -> jnp.ndarray:
    """SSD codec (DetectionUtil.cpp encodeBBox): center/size offsets scaled
    by the 4 variances.  gt/priors [..., 4] corner form."""
    v = jnp.asarray(variances, jnp.float32)
    gc, gwh = _center_form(gt)
    pc, pwh = _center_form(priors)
    d_c = (gc - pc) / pwh / v[:2]
    d_wh = jnp.log(gwh / pwh) / v[2:]
    return jnp.concatenate([d_c, d_wh], axis=-1)


def decode_boxes(loc: jnp.ndarray, priors: jnp.ndarray, variances) -> jnp.ndarray:
    v = jnp.asarray(variances, jnp.float32)
    pc, pwh = _center_form(priors)
    c = loc[..., :2] * v[:2] * pwh + pc
    wh = jnp.exp(loc[..., 2:] * v[2:]) * pwh
    return jnp.concatenate([c - wh / 2, c + wh / 2], axis=-1)


# ---------------------------------------------------------------------------
# matching (MultiBoxLossLayer::forward matching phase / matchBBox)
# ---------------------------------------------------------------------------


def match_priors(
    priors: jnp.ndarray,  # [P, 4]
    gt: jnp.ndarray,  # [G, 4]
    gt_valid: jnp.ndarray,  # [G] bool
    overlap_threshold: float,
):
    """Returns (matched_gt [P] int32, pos_mask [P] bool, max_iou [P]).

    Per-prior: best gt with IoU > threshold.  Bipartite pass: valid gts
    claim distinct priors by globally-best IoU regardless of threshold (so
    no gt goes unmatched — DetectionUtil matchBBox does the same two
    phases, excluding already-claimed priors and gts each round)."""
    g = gt.shape[0]
    iou = iou_matrix(priors, gt) * gt_valid[None, :].astype(jnp.float32)
    max_iou = jnp.max(iou, axis=1)
    matched = jnp.argmax(iou, axis=1).astype(jnp.int32)
    pos = max_iou > overlap_threshold
    # Bipartite phase (reference matchBBox): iteratively claim the globally
    # best remaining (prior, gt) pair, excluding claimed priors AND gts, so
    # every valid gt gets its own prior even when two gts share a best
    # prior.  G iterations of a masked global argmax — static shape.

    def body(carry, _):
        live, m, p_ = carry
        flat = jnp.argmax(live)
        pi, gi = flat // g, flat % g
        ok = live.reshape(-1)[flat] > 0.0
        m = jnp.where(ok, m.at[pi].set(gi.astype(jnp.int32)), m)
        p_ = jnp.where(ok, p_.at[pi].set(True), p_)
        live = jnp.where(ok, live.at[pi, :].set(-1.0).at[:, gi].set(-1.0), live)
        return (live, m, p_), None

    (_, matched, pos), _ = jax.lax.scan(
        body, (iou, matched, pos), None, length=g
    )
    return matched, pos, max_iou


def smooth_l1(x: jnp.ndarray) -> jnp.ndarray:
    ax = jnp.abs(x)
    return jnp.where(ax < 1.0, 0.5 * x * x, ax - 0.5)


def hard_negative_ranks(neg_score: jnp.ndarray, neg_mask: jnp.ndarray) -> jnp.ndarray:
    """[P] rank of each negative prior by descending score (invalid -> P);
    keep the top floor(neg_pos_ratio*npos) by comparing rank < n_neg."""
    masked = jnp.where(neg_mask, neg_score, -jnp.inf)
    order = jnp.argsort(-masked)  # best negatives first
    ranks = jnp.zeros_like(order).at[order].set(jnp.arange(order.shape[0]))
    return jnp.where(neg_mask, ranks, neg_score.shape[0])


# ---------------------------------------------------------------------------
# NMS (DetectionUtil applyNMSFast) — fixed-iteration select-and-suppress
# ---------------------------------------------------------------------------


def nms(
    boxes: jnp.ndarray,  # [N, 4]
    scores: jnp.ndarray,  # [N]
    iou_threshold: float,
    max_out: int,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Greedy NMS: returns (idx [max_out] int32, keep_scores [max_out]);
    unused slots have score 0 and idx 0.  A lax.scan of max_out
    select-argmax-then-suppress steps — static shape, no host loop."""

    def body(state, _):
        live = state
        i = jnp.argmax(live)
        s = live[i]
        overlapping = iou_matrix(boxes[i][None, :], boxes)[0] > iou_threshold
        live = jnp.where(overlapping, -jnp.inf, live)
        live = live.at[i].set(-jnp.inf)
        return live, (i.astype(jnp.int32), s)

    _, (idx, kept) = jax.lax.scan(body, scores, None, length=max_out)
    valid = kept > -jnp.inf
    return jnp.where(valid, idx, 0), jnp.where(valid, kept, 0.0)
