"""Activation registry — full parity with the reference activation set
(reference: paddle/gserver/activations/ActivationFunction.cpp:69-443).

Each activation is a pure elementwise jnp function; XLA fuses it into the
producing matmul so there is no separate kernel launch (unlike the
reference's separate forward/backward activation kernels).  ``softmax`` and
``sequence_softmax`` are the two non-elementwise members, handled with
explicit axis/mask semantics.
"""

from __future__ import annotations

from typing import Callable, Dict, Optional

import jax
import jax.numpy as jnp

Activation = Callable[..., jnp.ndarray]

_ACTIVATIONS: Dict[str, Activation] = {}


def register_activation(*names: str):
    def deco(fn: Activation) -> Activation:
        for n in names:
            _ACTIVATIONS[n] = fn
        return fn

    return deco


def get_activation(name: str) -> Activation:
    try:
        return _ACTIVATIONS[name]
    except KeyError:
        raise KeyError(
            f"unknown activation {name!r}; known: {sorted(_ACTIVATIONS)}"
        ) from None


def registered_activations():
    """Sorted registered activation names (the graph linter's G013 domain)."""
    return sorted(_ACTIVATIONS)


def apply_activation(name: str, x: jnp.ndarray, mask: Optional[jnp.ndarray] = None):
    if name in ("sequence_softmax",):
        return _ACTIVATIONS[name](x, mask)
    return _ACTIVATIONS[name](x)


@register_activation("identity", "linear", "")
def _identity(x):
    return x


@register_activation("sigmoid")
def _sigmoid(x):
    return jax.nn.sigmoid(x)


@register_activation("softmax")
def _softmax(x):
    return jax.nn.softmax(x, axis=-1)  # num: allow[N401] softmax fwd sums in f32 inside jax.nn; the bwd [S]-sum rides the compute dtype (S bounded by the shape ladder)


@register_activation("sequence_softmax")
def _sequence_softmax(x, mask=None):
    """Softmax over the time axis of a [B, T, 1] / [B, T] sequence score,
    masking padding (reference ActivationFunction.cpp SequenceSoftmax)."""
    squeeze = x.ndim == 3 and x.shape[-1] == 1
    logits = x[..., 0] if squeeze else x
    if mask is not None:
        logits = jnp.where(mask > 0, logits, -1e9)
    out = jax.nn.softmax(logits, axis=-1)
    if mask is not None:
        out = out * mask
    return out[..., None] if squeeze else out


@register_activation("relu")
def _relu(x):
    return jax.nn.relu(x)


@register_activation("brelu")
def _brelu(x):
    # Reference clips to [0, 24] (BReluActivation, ActivationFunction.cpp).
    return jnp.clip(x, 0.0, 24.0)


@register_activation("tanh")
def _tanh(x):
    return jnp.tanh(x)


@register_activation("stanh")
def _stanh(x):
    # Scaled tanh: 1.7159 * tanh(2/3 x) (STanhActivation).
    return 1.7159 * jnp.tanh((2.0 / 3.0) * x)


@register_activation("softrelu")
def _softrelu(x):
    # log(1 + exp(x)), input clipped to [-40, 40] like the reference.
    return jax.nn.softplus(jnp.clip(x, -40.0, 40.0))


@register_activation("abs")
def _abs(x):
    return jnp.abs(x)


@register_activation("square")
def _square(x):
    return jnp.square(x)


@register_activation("exponential", "exp")
def _exp(x):
    return jnp.exp(x)


@register_activation("reciprocal")
def _reciprocal(x):
    return 1.0 / x


@register_activation("sqrt")
def _sqrt(x):
    return jnp.sqrt(x)


@register_activation("log")
def _log(x):
    return jnp.log(x)
