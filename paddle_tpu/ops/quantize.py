"""Block-scaled quantization — the shared core of every quantized surface.

One format, three planes (EQuARX, arXiv:2506.17615: block-scaled int8
recovers near-f32 allreduce accuracy at a fraction of the interconnect
bytes):

  * **in-graph collectives** (:func:`quantized_psum`, used by
    trainer/step.py behind the ``quantized_allreduce`` flag): the gradient
    psum rides as an int8 (or bf16) payload psum with its f32 block-scale
    psum emitted side-by-side in the SAME region — the structure rule N405
    (analysis/numerics_lint.py) statically requires of any sub-f32 psum;
  * **elastic RPC results** (:func:`quantize_tree` /
    :func:`dequantize_tree`, numpy-only — no jax import — so the wire
    plane and the numpy elastic workers stay light): a per-task gradient
    contribution rides master_wire as (int8 blocks, f32 scales) typed
    arrays and is dequantized BEFORE the sorted-order reduction, keeping
    the deterministic-trajectory contract of reduce_results;
  * **serving weight-only int8** (:func:`quantize_weight_bundle` /
    :func:`dequantize_weight_bundle`, serving/engine.py behind
    ``serving_int8_weights``): decode weights live as int8 blocks + f32
    scales and dequantize in-graph per dispatch, shrinking resident
    weight bytes under ``serving_hbm_budget_mb``.

Format: an array is flattened C-order, zero-padded to a multiple of
``block``, and reshaped to ``[n_blocks, block]``; each block stores a
payload (int8 in ``[-127, 127]``, or bf16 in ``[-1, 1]``) plus one f32
scale (max-abs over the block, divided by 127 for int8).  Dequantize is
``payload * scale`` truncated back to the original shape.  A zero block
quantizes against scale 1.0 (the zero-guard applies ONLY at exact amax 0:
a scale that underflows a narrow ``scale_dtype`` saturates LOUDLY — the
division produces inf and the numerics sanitizer names the eqn — instead
of being silently absorbed; tests/test_num_sanitizer.py drills this).
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import numpy as np

__all__ = [
    "DEFAULT_BLOCK",
    "INT8_MAX",
    "quantize_block_scaled",
    "dequantize_block_scaled",
    "quantized_psum",
    "quantize_array",
    "dequantize_array",
    "is_quantized_array",
    "quantize_tree",
    "dequantize_tree",
    "tree_wire_bytes",
    "quantize_weight_bundle",
    "dequantize_weight_bundle",
    "weight_bundle_bytes",
]

DEFAULT_BLOCK = 256
INT8_MAX = 127.0

# the wire marker key of a quantized-leaf dict (a plain string key so the
# restricted master_wire codec carries it without any new type)
QUANT_KEY = "__bsq__"


def _resolve_block(block: Optional[int]) -> int:
    if block is not None:
        return int(block)
    try:
        from paddle_tpu.utils.flags import get_flag

        return int(get_flag("quantize_block_size"))
    except Exception:  # noqa: BLE001 — flag plane not loaded (stripped use)
        return DEFAULT_BLOCK


# ---------------------------------------------------------------------------
# numpy core — the wire plane (NO jax import: elastic's numpy workers and
# master_wire stay jax-free)
# ---------------------------------------------------------------------------

def quantize_array(a: np.ndarray, block: Optional[int] = None
                   ) -> Dict[str, Any]:
    """One float ndarray -> the wire-ready quantized-leaf dict
    ``{QUANT_KEY: 1, "q": int8 [nb, block], "s": f32 [nb], "shape": [...],
    "dtype": "<name>"}`` (every value inside the restricted master_wire
    type set).  Deterministic round-half-even — the producing worker's
    bytes are the contribution; every reducer dequantizes the SAME bytes,
    so the sorted-order reduction stays bit-identical fleet-wide."""
    block = _resolve_block(block)
    a = np.asarray(a)
    if not np.issubdtype(a.dtype, np.floating):
        raise TypeError(f"quantize_array wants a float array, got {a.dtype}")
    n = a.size
    nb = max((n + block - 1) // block, 1)
    flat = np.zeros((nb * block,), np.float32)
    flat[:n] = a.astype(np.float32, copy=False).reshape(-1)
    blocks = flat.reshape(nb, block)
    amax = np.max(np.abs(blocks), axis=1)
    scale = amax / np.float32(INT8_MAX)
    safe = np.where(amax == 0.0, np.float32(1.0), scale)
    q = np.clip(np.rint(blocks / safe[:, None]), -INT8_MAX, INT8_MAX)
    return {
        QUANT_KEY: 1,
        "q": q.astype(np.int8),
        "s": scale.astype(np.float32),
        "shape": [int(d) for d in a.shape],
        "dtype": str(a.dtype),
    }


def is_quantized_array(obj: Any) -> bool:
    return isinstance(obj, dict) and obj.get(QUANT_KEY) == 1


def dequantize_array(d: Dict[str, Any]) -> np.ndarray:
    """Inverse of :func:`quantize_array` (up to the one rounding)."""
    q = np.asarray(d["q"], np.float32)
    s = np.asarray(d["s"], np.float32)
    flat = (q * s[:, None]).reshape(-1)
    shape = tuple(int(x) for x in d["shape"])
    n = int(np.prod(shape)) if shape else 1
    out = flat[:n].reshape(shape)
    return out.astype(np.dtype(str(d["dtype"])), copy=False)


def quantize_tree(tree: Any, block: Optional[int] = None) -> Any:
    """Recursively quantize every float ndarray leaf of a nested-dict
    gradient tree (the elastic contribution payload); non-float leaves
    and scalars pass through untouched."""
    if isinstance(tree, dict):
        return {k: quantize_tree(v, block) for k, v in tree.items()}
    arr = np.asarray(tree)
    if arr.ndim >= 1 and np.issubdtype(arr.dtype, np.floating):
        return quantize_array(arr, block)
    return tree


def dequantize_tree(tree: Any) -> Any:
    """Recursively undo :func:`quantize_tree`; a mixed tree (some tasks
    quantized, some not — a fleet mid-flag-flip) dequantizes only the
    marked leaves."""
    if is_quantized_array(tree):
        return dequantize_array(tree)
    if isinstance(tree, dict):
        return {k: dequantize_tree(v) for k, v in tree.items()}
    return tree


def tree_wire_bytes(tree: Any) -> int:
    """Approximate payload bytes of a contribution tree (array bytes only
    — framing/tag overhead excluded), for the wire-reduction arithmetic
    the bench records check against the measured counters."""
    if isinstance(tree, dict):
        return sum(tree_wire_bytes(v) for v in tree.values())
    arr = np.asarray(tree)
    return int(arr.nbytes)


# ---------------------------------------------------------------------------
# jax core — in-graph quantize/dequantize + the quantized collective
# (jax imported lazily so this module stays importable on the wire plane)
# ---------------------------------------------------------------------------

def quantize_block_scaled(
    x,
    block: Optional[int] = None,
    payload_dtype=None,
    stochastic: bool = False,
    rng=None,
    scale_dtype=None,
):
    """In-graph block-scaled quantize: ``x`` (any shape, float) ->
    ``(payload [nb, block], scales [nb])``.

    ``payload_dtype``: int8 (default) or bfloat16.  int8 payloads round to
    ``[-127, 127]`` with per-block scale ``amax/127``; bf16 payloads store
    ``block/amax`` in ``[-1, 1]`` with scale ``amax``.  ``stochastic``
    (int8 only) rounds ``floor(v + u)``, ``u ~ U[0, 1)`` from ``rng`` —
    unbiased in expectation, the EQuARX recipe for gradient traffic.

    ``scale_dtype`` narrows the STORED scale (default f32).  The zero
    guard applies only at exact amax 0; a scale that underflows a narrow
    scale_dtype divides to inf — a saturating config fails loudly under
    the numerics sanitizer instead of silently zeroing blocks."""
    import jax
    import jax.numpy as jnp

    block = _resolve_block(block)
    if payload_dtype is None:
        payload_dtype = jnp.int8
    if scale_dtype is None:
        scale_dtype = jnp.float32
    x = jnp.asarray(x)
    n = x.size
    nb = max((n + block - 1) // block, 1)
    flat = jnp.zeros((nb * block,), jnp.float32)
    flat = flat.at[:n].set(x.astype(jnp.float32).reshape(-1))
    blocks = flat.reshape(nb, block)
    amax = jnp.max(jnp.abs(blocks), axis=1)
    if jnp.dtype(payload_dtype) == jnp.dtype(jnp.int8):
        scale = (amax / jnp.float32(INT8_MAX)).astype(scale_dtype)
        safe = jnp.where(amax == 0.0, jnp.float32(1.0),
                         scale.astype(jnp.float32))
        v = blocks / safe[:, None]
        if stochastic:
            if rng is None:
                raise ValueError("stochastic rounding needs an rng key")
            v = jnp.floor(v + jax.random.uniform(rng, v.shape, jnp.float32))
        else:
            v = jnp.round(v)
        payload = jnp.clip(v, -INT8_MAX, INT8_MAX).astype(jnp.int8)
    else:
        scale = amax.astype(scale_dtype)
        safe = jnp.where(amax == 0.0, jnp.float32(1.0),
                         scale.astype(jnp.float32))
        payload = (blocks / safe[:, None]).astype(payload_dtype)
    return payload, scale


def dequantize_block_scaled(payload, scales, shape, dtype=None):
    """Inverse of :func:`quantize_block_scaled`: ``payload * scale``,
    truncated back to ``shape``/``dtype``."""
    import jax.numpy as jnp

    if dtype is None:
        dtype = jnp.float32
    flat = payload.astype(jnp.float32) * scales.astype(jnp.float32)[:, None]
    n = 1
    for d in shape:
        n *= int(d)
    return flat.reshape(-1)[:n].reshape(shape).astype(dtype)


def quantized_psum(
    tree,
    axis_name: str,
    block: Optional[int] = None,
    payload_dtype=None,
    stochastic: bool = False,
    rng=None,
    mean: bool = False,
):
    """Block-scaled quantized allreduce of a gradient pytree over a mesh
    axis (inside shard_map/pmap) — the in-graph half of the tentpole.

    Per leaf, per block of ``block`` elements:

      1. ``amax_i = max|x_i|`` locally (f32);
      2. ``S = psum(amax, axis)`` — the **f32 scale psum** (the N405
         block-scale anchor), and the shared quantization bound:
         every shard quantizes against ``scale = S/127``, so
         ``|q_i| <= 127 * amax_i / S`` and the payload psum is
         **overflow-free by construction** (``sum_i |q_i| <= 127``) with
         adaptive headroom — a shard holding most of the magnitude keeps
         most of the int8 range;
      3. ``Q = psum(q, axis)`` at the payload dtype — the bandwidth win:
         1 byte/element (+ 4/block for the scales) instead of 4;
      4. dequantize ``Q * scale`` back to the leaf dtype.

    ``mean=True`` divides by the axis size (the gradient-mean contract of
    the data-parallel step).  ``stochastic`` decorrelates per-shard
    rounding by folding the axis index into ``rng``."""
    import jax
    import jax.numpy as jnp

    block = _resolve_block(block)
    if payload_dtype is None:
        payload_dtype = jnp.int8
    int8 = jnp.dtype(payload_dtype) == jnp.dtype(jnp.int8)
    if stochastic and rng is not None:
        rng = jax.random.fold_in(rng, jax.lax.axis_index(axis_name))
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    keys = (
        jax.random.split(rng, max(len(leaves), 1))
        if (stochastic and rng is not None) else [None] * len(leaves)
    )

    def leaf_psum(g, key):
        shape, dt = g.shape, g.dtype
        n = g.size
        nb = max((n + block - 1) // block, 1)
        flat = jnp.zeros((nb * block,), jnp.float32)
        flat = flat.at[:n].set(g.astype(jnp.float32).reshape(-1))
        blocks = flat.reshape(nb, block)
        amax = jnp.max(jnp.abs(blocks), axis=1)
        # the f32 scale psum — the shared bound AND the N405 anchor
        total = jax.lax.psum(amax, axis_name)
        if int8:
            scale = total / jnp.float32(INT8_MAX)
        else:
            scale = total
        safe = jnp.where(total == 0.0, jnp.float32(1.0), scale)
        v = blocks / safe[:, None]
        if int8:
            if key is not None:
                v = jnp.floor(
                    v + jax.random.uniform(key, v.shape, jnp.float32)
                )
            else:
                v = jnp.round(v)
            payload = jnp.clip(v, -INT8_MAX, INT8_MAX).astype(jnp.int8)
        else:
            payload = v.astype(payload_dtype)
        summed = jax.lax.psum(payload, axis_name)
        out = summed.astype(jnp.float32) * safe[:, None]
        if mean:
            out = out / jnp.float32(jax.lax.psum(1, axis_name))
        return out.reshape(-1)[:n].reshape(shape).astype(dt)

    return jax.tree_util.tree_unflatten(
        treedef, [leaf_psum(g, k) for g, k in zip(leaves, keys)]
    )


# ---------------------------------------------------------------------------
# serving weight bundles — int8 weight-only decode
# ---------------------------------------------------------------------------

def quantize_weight_bundle(
    w: Dict[str, Any],
    block: Optional[int] = None,
    min_size: int = 512,
) -> Tuple[Dict[str, Any], Dict[str, Tuple[Tuple[int, ...], Any]]]:
    """Quantize the DENSE MATRICES of a fused decode-weight bundle
    (serving/engine.py's jit argument): every float leaf with ndim >= 2
    and >= ``min_size`` elements becomes ``{"q": int8 blocks, "s": f32
    scales}``; biases / vectors / None ride through at full precision
    (weight-ONLY quantization — the certify_precision_plan ACCEPT case).

    Returns ``(bundle, meta)`` where ``meta`` maps quantized keys to
    ``(shape, dtype)`` — the static half the in-graph dequantize needs
    (the bundle itself stays a pure array pytree for jit)."""
    import jax.numpy as jnp

    block = _resolve_block(block)
    out: Dict[str, Any] = {}
    meta: Dict[str, Tuple[Tuple[int, ...], Any]] = {}
    for k, v in w.items():
        if (
            v is not None
            and hasattr(v, "dtype")
            and jnp.issubdtype(v.dtype, jnp.floating)
            and getattr(v, "ndim", 0) >= 2
            and v.size >= min_size
        ):
            q, s = quantize_block_scaled(v, block=block)
            out[k] = {"q": q, "s": s}
            meta[k] = (tuple(int(d) for d in v.shape), v.dtype)
        else:
            out[k] = v
    return out, meta


def dequantize_weight_bundle(
    w: Dict[str, Any],
    meta: Dict[str, Tuple[Tuple[int, ...], Any]],
) -> Dict[str, Any]:
    """In-graph inverse of :func:`quantize_weight_bundle` — runs at the
    top of every decode dispatch, so resident HBM holds the int8 blocks
    and only the dispatch working set pays the f32 materialization."""
    return {
        k: (
            dequantize_block_scaled(v["q"], v["s"], *meta[k])
            if k in meta else v
        )
        for k, v in w.items()
    }


def weight_bundle_bytes(w: Dict[str, Any]) -> int:
    """Resident bytes of a (possibly quantized) weight bundle."""
    total = 0
    for v in w.values():
        if v is None:
            continue
        if isinstance(v, dict):
            total += sum(int(x.nbytes) for x in v.values())
        else:
            total += int(v.nbytes)
    return total
