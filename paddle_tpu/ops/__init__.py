"""TPU-native op helpers shared by the layer impls and the fused cores.

``acc_matmul``/``acc_einsum`` are the one sanctioned spelling of a GEMM
under mixed precision: sub-f32 operands contract with an f32 accumulator
(``preferred_element_type`` — the MXU gives f32 accumulation for free)
and round ONCE to the compute dtype on the way out, instead of
truncating every partial sum.  At f32 they are byte-for-byte
``jnp.matmul``/``jnp.einsum`` — no behavior change on the default path.
The numerics lint (analysis/numerics_lint.py, rule N401) flags any
low-precision contraction that bypasses this discipline.

``ops.quantize`` is the block-scaled quantization plane (the quantized
allreduce, the elastic wire contributions, int8 weight-only serving);
jax is imported lazily here so that plane's numpy half stays importable
from jax-free processes (elastic's numpy workers, master_wire).
"""

from __future__ import annotations

__all__ = ["acc_matmul", "acc_einsum", "needs_f32_acc"]


def needs_f32_acc(dtype) -> bool:
    """True for sub-f32 float dtypes (bf16/f16/f8) — the dtypes whose
    contractions must accumulate upward."""
    import jax.numpy as jnp

    return (
        jnp.issubdtype(dtype, jnp.floating)
        and jnp.finfo(dtype).bits < 32
    )


def acc_matmul(x, w):
    """``x @ w`` accumulating in f32 for sub-f32 operands, result cast
    back to the operand dtype; the plain matmul (bit-identical) at f32+."""
    import jax.numpy as jnp

    if needs_f32_acc(x.dtype):
        y = jnp.matmul(x, w, preferred_element_type=jnp.float32)
        return y.astype(x.dtype)  # num: allow[N406] intentional single rounding: the f32-accumulated GEMM result quantizes ONCE to the compute dtype at the op boundary (a full-precision consumer may immediately re-promote)
    return jnp.matmul(x, w)


def acc_einsum(subscripts: str, *operands):
    """``jnp.einsum`` with the same f32-accumulation discipline as
    :func:`acc_matmul` (keyed on the first operand's dtype)."""
    import jax.numpy as jnp

    if operands and needs_f32_acc(operands[0].dtype):
        y = jnp.einsum(subscripts, *operands,
                       preferred_element_type=jnp.float32)
        return y.astype(operands[0].dtype)
    return jnp.einsum(subscripts, *operands)
