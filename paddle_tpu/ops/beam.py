"""Beam search as a jitted lax.scan — the TPU-native replacement for the
reference's host-side beam search (reference: paddle/gserver/
gradientmachines/RecurrentGradientMachine.cpp:1393 beamSearch, .cpp:964
generateSequence): fixed beam width K and max length T, padded beams, eos
handling via finished masks — no data-dependent control flow.

User hook surface (reference BeamSearchControlCallbacks,
RecurrentGradientMachine.h:70-120, and the ``diy_beam_search_prob_so``
user-.so probability hook, .cpp:27): the reference invokes host std::function
callbacks between steps; here the hooks are restricted IN-GRAPH functions
traced into the same jitted scan — they must be jax-traceable (no
data-dependent Python control flow).  A hook that genuinely needs host code
can wrap it in ``jax.pure_callback`` itself.

  candidate_adjust_fn(logp [B*K, V], seqs [B*K, T], t) -> logp
      BeamSearchCandidatesAdjustCallback + diy prob .so: restrict/adjust
      the candidate distribution given the formed prefixes and step number.
  drop_fn(seqs [B*K, T], ids [B*K], scores [B*K], t) -> bool [B*K]
      DropCallback: True drops the expanded path (score pinned to -inf).
  norm_fn(scores [B, K], seqs [B, K, T], lengths [B, K]) -> scores
      NormOrDropNodeCallback on completed paths: rescore (e.g. length
      normalization) before the final best-first sort; return -inf to drop.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp

NEG_INF = -1e9


def greedy_token_chain(logits):
    """``(logp [.., V], nxt [..])`` from raw logits via THE greedy argmax
    chain — softmax, floor at 1e-9, log, argmax — the exact op sequence
    the one-shot generator emits (its head produces probabilities and
    :func:`greedy_search` consumes ``log(max(prob, 1e-9))``).  The serving
    plane's fused decode AND speculative-verify programs call this so
    every token they emit rode bit-for-bit the same chain: speculative
    rejection "falls back to greedy" by construction, not by tolerance."""
    prob = jax.nn.softmax(logits, axis=-1)
    logp = jnp.log(jnp.maximum(prob, 1e-9))
    return logp, jnp.argmax(logp, axis=-1).astype(jnp.int32)


def beam_search(
    step_fn: Callable[[jnp.ndarray, Any], Tuple[jnp.ndarray, Any]],
    init_carry: Any,
    batch_size: int,
    beam_size: int,
    vocab_size: int,
    bos_id: int,
    eos_id: int,
    max_len: int,
    candidate_adjust_fn: Optional[Callable] = None,
    drop_fn: Optional[Callable] = None,
    norm_fn: Optional[Callable] = None,
):
    """Generic beam search.

    step_fn(ids[B*K] int32, carry) -> (log_probs [B*K, V], new_carry); carry
    leaves must have leading dim B*K.  Returns (sequences [B, K, T] int32,
    scores [B, K]) sorted best-first.  Finished beams propagate only via the
    eos column so shorter hypotheses stay comparable (the reference's
    eosFrameLine_ bookkeeping).  See module docstring for the hook surface.
    """
    bk = batch_size * beam_size

    def expand_first(x):
        # [B, ...] -> [B*K, ...] by repeat
        return jnp.repeat(x, beam_size, axis=0)

    carry0 = jax.tree_util.tree_map(expand_first, init_carry)
    ids0 = jnp.full((bk,), bos_id, jnp.int32)
    # Only beam 0 of each batch starts alive; others -inf so the first step
    # picks K distinct tokens rather than K copies.
    scores0 = jnp.tile(
        jnp.asarray([0.0] + [NEG_INF] * (beam_size - 1), jnp.float32),
        (batch_size,),
    )
    finished0 = jnp.zeros((bk,), bool)

    def body(state, _):
        ids, scores, finished, carry, seqs, t = state
        logp, new_carry = step_fn(ids, carry)  # [B*K, V]
        # Finished beams: only the eos continuation at score-delta 0, so
        # their total stays frozen and they remain comparable.
        eos_row = jnp.where(
            jnp.arange(vocab_size) == eos_id, 0.0, NEG_INF
        ).astype(logp.dtype)
        logp = jnp.where(finished[:, None], eos_row[None, :], logp)
        if candidate_adjust_fn is not None:
            # adjusted distribution must keep finished beams frozen on eos
            adj = candidate_adjust_fn(logp, seqs, t)
            logp = jnp.where(finished[:, None], eos_row[None, :], adj)
        cand = scores[:, None] + logp  # [B*K, V]
        cand = cand.reshape(batch_size, beam_size * vocab_size)
        top_scores, top_idx = jax.lax.top_k(cand, beam_size)  # [B, K]
        beam_idx = top_idx // vocab_size  # which parent beam
        tok_idx = (top_idx % vocab_size).astype(jnp.int32)  # which token

        # flat parent indices into [B*K]
        parent = (
            beam_idx + jnp.arange(batch_size, dtype=beam_idx.dtype)[:, None] * beam_size
        ).reshape(-1)
        new_scores = top_scores.reshape(-1)
        new_ids = tok_idx.reshape(-1)
        new_finished = jnp.take(finished, parent) | (new_ids == eos_id)
        new_carry = jax.tree_util.tree_map(
            lambda x: jnp.take(x, parent, axis=0), new_carry
        )
        new_seqs = jnp.take(seqs, parent, axis=0)  # reorder histories
        new_seqs = new_seqs.at[:, t].set(new_ids)
        if drop_fn is not None:
            # never re-judge an already-finished hypothesis: its tail is
            # forced eos padding the user hook should not see (the reference
            # applies DropCallback to live expansion candidates only)
            drop = drop_fn(new_seqs, new_ids, new_scores, t)
            drop &= ~jnp.take(finished, parent)
            new_scores = jnp.where(drop, NEG_INF, new_scores)
        return (new_ids, new_scores, new_finished, new_carry, new_seqs, t + 1), None

    seqs0 = jnp.zeros((bk, max_len), jnp.int32)
    state0 = (ids0, scores0, finished0, carry0, seqs0, jnp.asarray(0, jnp.int32))
    (ids, scores, finished, carry, seqs, _), _ = jax.lax.scan(
        body, state0, None, length=max_len
    )
    seqs = seqs.reshape(batch_size, beam_size, max_len)
    scores = scores.reshape(batch_size, beam_size)
    if norm_fn is not None:
        is_eos = seqs == eos_id
        any_eos = jnp.any(is_eos, axis=-1)
        first_eos = jnp.argmax(is_eos.astype(jnp.int32), axis=-1)
        lengths = jnp.where(any_eos, first_eos, max_len).astype(jnp.int32)
        scores = norm_fn(scores, seqs, lengths)
    order = jnp.argsort(-scores, axis=1)
    seqs = jnp.take_along_axis(seqs, order[:, :, None], axis=1)
    scores = jnp.take_along_axis(scores, order, axis=1)
    return seqs, scores


def greedy_search(
    step_fn: Callable[[jnp.ndarray, Any], Tuple[jnp.ndarray, Any]],
    init_carry: Any,
    batch_size: int,
    bos_id: int,
    eos_id: int,
    max_len: int,
    max_new_tokens: Optional[int] = None,
    early_exit: bool = False,
):
    """Greedy decode: argmax each step; returns ([B, L] ids, [B] lengths)
    with ``L = min(max_len, max_new_tokens)``.

    ``early_exit`` replaces the fixed-trip scan with a ``lax.while_loop``
    that stops once every row has emitted EOS.  The output is BIT-IDENTICAL
    to the full unroll: a finished row only ever re-emits EOS (the
    ``where(finished, eos, ...)`` clamp), and the early-exit token buffer
    is pre-filled with EOS, so the steps the loop skips would have written
    exactly what the buffer already holds."""
    length = max_len if max_new_tokens is None else max(
        0, min(int(max_new_tokens), max_len)
    )
    if length == 0:
        return (
            jnp.zeros((batch_size, 0), jnp.int32),
            jnp.zeros((batch_size,), jnp.int32),
        )
    ids0 = jnp.full((batch_size,), bos_id, jnp.int32)
    finished0 = jnp.zeros((batch_size,), bool)

    def step(ids, finished, carry):
        logp, new_carry = step_fn(ids, carry)
        nxt = jnp.argmax(logp, axis=-1).astype(jnp.int32)
        nxt = jnp.where(finished, eos_id, nxt)
        return nxt, finished | (nxt == eos_id), new_carry

    if early_exit:
        toks0 = jnp.full((batch_size, length), eos_id, jnp.int32)

        def cond(state):
            t, _, finished, _, _ = state
            return (t < length) & ~jnp.all(finished)

        def body(state):
            t, ids, finished, carry, toks = state
            nxt, new_finished, new_carry = step(ids, finished, carry)
            return (
                t + 1, nxt, new_finished, new_carry,
                toks.at[:, t].set(nxt),
            )

        _, _, finished, _, toks = jax.lax.while_loop(
            cond,
            body,
            (jnp.asarray(0, jnp.int32), ids0, finished0, init_carry, toks0),
        )
    else:

        def scan_body(state, _):
            ids, finished, carry = state
            nxt, new_finished, new_carry = step(ids, finished, carry)
            return (nxt, new_finished, new_carry), nxt

        (_, finished, _), toks = jax.lax.scan(
            scan_body, (ids0, finished0, init_carry), None, length=length
        )
        toks = jnp.swapaxes(toks, 0, 1)  # [B, L]
    is_eos = toks == eos_id
    any_eos = jnp.any(is_eos, axis=1)
    first_eos = jnp.argmax(is_eos.astype(jnp.int32), axis=1)
    lengths = jnp.where(any_eos, first_eos, length).astype(jnp.int32)
    return toks, lengths
