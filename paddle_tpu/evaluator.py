"""Evaluator framework — the reference's ``paddle/gserver/evaluators``
(Evaluator.cpp: classification_error:995, sum:996, column_sum, rankauc:503,
precision_recall:584, pnpair:862; ChunkEvaluator.cpp:288;
CTCErrorEvaluator.cpp:277; printers :1009-1346) exposed with the
trainer_config_helpers/evaluators.py surface.

TPU-native split: each evaluator contributes
  * an **in-graph update** — pure jnp over the step's layer outputs producing
    fixed-shape accumulator arrays (no host sync, fuses into the step), and
  * a **host finalize** — turns summed accumulators into scalar results.
The trainer sums accumulators across batches (per-batch for iteration events,
per-pass for pass events) and calls finalize for display — replacing the
reference's start()/eval()/finish() object protocol with pure data.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp

from paddle_tpu.core.batch import SeqTensor
from paddle_tpu.core.topology import LayerOutput, auto_name

Accums = Dict[str, jnp.ndarray]


@dataclasses.dataclass
class Evaluator:
    name: str
    layers: List[LayerOutput]  # outputs the in-graph update needs
    update: Callable[[Dict[str, SeqTensor]], Accums]
    finalize: Callable[[Dict[str, object]], Dict[str, float]]


def _ids_of(t: SeqTensor) -> jnp.ndarray:
    ids = t.data.astype(jnp.int32)
    if ids.ndim >= 2 and ids.shape[-1] == 1:
        ids = ids[..., 0]
    return ids


def _flat_valid(pred: SeqTensor, label: SeqTensor):
    """(pred2d [N, C], ids [N], weight [N]) flattening sequence time."""
    p = pred.data
    ids = _ids_of(label)
    if pred.is_seq and p.ndim == 3:
        w = pred.mask().reshape(-1)
        return p.reshape(-1, p.shape[-1]), ids.reshape(-1), w
    return p, ids.reshape(-1), jnp.ones((p.shape[0],), jnp.float32)


# ---------------------------------------------------------------------------
# classification_error
# ---------------------------------------------------------------------------


def classification_error_evaluator(
    input: LayerOutput, label: LayerOutput, name: Optional[str] = None
) -> Evaluator:
    nm = name or auto_name("classification_error")

    def update(outs):
        # argmax(softmax(x)) == argmax(x): prefer the producer's
        # pre-activation aux so the error never forces a big softmax to
        # materialize (the fused CE path reads logits directly)
        pred = outs.get(input.name + "@logits")
        if pred is None:
            pred = outs[input.name]
        p, ids, w = _flat_valid(pred, outs[label.name])
        err = (jnp.argmax(p, axis=-1) != ids).astype(jnp.float32)
        return {"err": jnp.sum(err * w), "total": jnp.sum(w)}

    def finalize(acc):
        return {nm: float(acc["err"]) / max(float(acc["total"]), 1.0)}

    return Evaluator(nm, [input, label], update, finalize)


# ---------------------------------------------------------------------------
# sum / column_sum
# ---------------------------------------------------------------------------


def sum_evaluator(input: LayerOutput, name: Optional[str] = None) -> Evaluator:
    nm = name or auto_name("sum")

    def update(outs):
        t = outs[input.name]
        return {"sum": jnp.sum(t.masked_data() if t.is_seq else t.data)}

    return Evaluator(nm, [input], update, lambda a: {nm: float(a["sum"])})


def column_sum_evaluator(
    input: LayerOutput, name: Optional[str] = None
) -> Evaluator:
    nm = name or auto_name("column_sum")

    def update(outs):
        t = outs[input.name]
        d = t.masked_data() if t.is_seq else t.data
        return {"colsum": jnp.sum(d.reshape(-1, d.shape[-1]), axis=0),
                "n": jnp.asarray(d.reshape(-1, d.shape[-1]).shape[0], jnp.float32)}

    def finalize(acc):
        import numpy as np

        col = np.asarray(acc["colsum"]) / max(float(acc["n"]), 1.0)
        return {f"{nm}[{i}]": float(v) for i, v in enumerate(col)}

    return Evaluator(nm, [input], update, finalize)


# ---------------------------------------------------------------------------
# auc — histogram-based rank AUC (reference AucEvaluator sorts on host; a
# fixed-bin histogram gives the same statistic with static shapes on device)
# ---------------------------------------------------------------------------


def auc_evaluator(
    input: LayerOutput, label: LayerOutput, name: Optional[str] = None,
    num_bins: int = 4096,
) -> Evaluator:
    nm = name or auto_name("auc")

    def update(outs):
        p, ids, w = _flat_valid(outs[input.name], outs[label.name])
        # positive-class score: column 1 of a 2-col softmax, else column 0
        score = p[:, 1] if p.shape[-1] >= 2 else p[:, 0]
        bin_ = jnp.clip((score * num_bins).astype(jnp.int32), 0, num_bins - 1)
        pos = jnp.zeros((num_bins,)).at[bin_].add(w * (ids == 1))
        neg = jnp.zeros((num_bins,)).at[bin_].add(w * (ids != 1))
        return {"pos": pos, "neg": neg}

    def finalize(acc):
        import numpy as np

        pos = np.asarray(acc["pos"], np.float64)
        neg = np.asarray(acc["neg"], np.float64)
        # walk bins from high score to low, trapezoid on the ROC curve
        tp = np.cumsum(pos[::-1])
        fp = np.cumsum(neg[::-1])
        tot_p, tot_n = tp[-1], fp[-1]
        if tot_p == 0 or tot_n == 0:
            return {nm: 0.0}
        tpr = np.concatenate([[0.0], tp / tot_p])
        fpr = np.concatenate([[0.0], fp / tot_n])
        return {nm: float(np.trapezoid(tpr, fpr))}

    return Evaluator(nm, [input, label], update, finalize)


# ---------------------------------------------------------------------------
# precision_recall
# ---------------------------------------------------------------------------


def precision_recall_evaluator(
    input: LayerOutput, label: LayerOutput,
    positive_label: int = -1, name: Optional[str] = None,
) -> Evaluator:
    nm = name or auto_name("precision_recall")
    c = input.size

    def update(outs):
        p, ids, w = _flat_valid(outs[input.name], outs[label.name])
        pred = jnp.argmax(p, axis=-1)
        onehot_pred = jax.nn.one_hot(pred, c) * w[:, None]
        onehot_gold = jax.nn.one_hot(ids, c) * w[:, None]
        tp = jnp.sum(onehot_pred * onehot_gold, axis=0)
        return {
            "tp": tp,
            "pred": jnp.sum(onehot_pred, axis=0),
            "gold": jnp.sum(onehot_gold, axis=0),
        }

    def finalize(acc):
        import numpy as np

        tp = np.asarray(acc["tp"], np.float64)
        pred = np.asarray(acc["pred"], np.float64)
        gold = np.asarray(acc["gold"], np.float64)
        if positive_label >= 0:
            sel = [positive_label]
        else:
            sel = list(range(c))
        precs = [tp[i] / pred[i] if pred[i] else 0.0 for i in sel]
        recs = [tp[i] / gold[i] if gold[i] else 0.0 for i in sel]
        prec, rec = float(np.mean(precs)), float(np.mean(recs))
        f1 = 2 * prec * rec / (prec + rec) if prec + rec else 0.0
        return {f"{nm}.precision": prec, f"{nm}.recall": rec, f"{nm}.F1": f1}

    return Evaluator(nm, [input, label], update, finalize)


# ---------------------------------------------------------------------------
# pnpair — positive-negative pair ratio within query groups
# ---------------------------------------------------------------------------


def pnpair_evaluator(
    input: LayerOutput, label: LayerOutput, query_id: LayerOutput,
    name: Optional[str] = None,
) -> Evaluator:
    nm = name or auto_name("pnpair")

    def update(outs):
        score_t = outs[input.name]
        score = score_t.data.reshape(-1)
        y = _ids_of(outs[label.name]).reshape(-1).astype(jnp.float32)
        q = _ids_of(outs[query_id.name]).reshape(-1)
        if score_t.is_seq:
            w = score_t.mask(bool).reshape(-1)
        else:
            w = jnp.ones(score.shape, bool)
        same_q = q[:, None] == q[None, :]
        better = y[:, None] > y[None, :]
        mask = same_q & better & w[:, None] & w[None, :]
        sdiff = score[:, None] - score[None, :]
        pos = jnp.sum(mask & (sdiff > 0))
        neg = jnp.sum(mask & (sdiff < 0))
        spe = jnp.sum(mask & (sdiff == 0))
        return {"pos": pos.astype(jnp.float32),
                "neg": neg.astype(jnp.float32),
                "spe": spe.astype(jnp.float32)}

    def finalize(acc):
        pos, neg, spe = (float(acc[k]) for k in ("pos", "neg", "spe"))
        return {nm: (pos + 0.5 * spe) / max(neg + 0.5 * spe, 1e-12)}

    return Evaluator(nm, [input, label, query_id], update, finalize)


# ---------------------------------------------------------------------------
# ctc_error — edit distance between best-path CTC decode and the label
# ---------------------------------------------------------------------------


def _ctc_best_path(logits: jnp.ndarray, lengths: jnp.ndarray, blank: int):
    """Greedy decode + collapse → (padded ids [B, T], lens [B])."""
    b_, t_ = logits.shape[0], logits.shape[1]
    am = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # [B, T]
    prev = jnp.pad(am, ((0, 0), (1, 0)), constant_values=-1)[:, :t_]
    tpos = jnp.arange(t_)[None, :]
    keep = (am != blank) & (am != prev) & (tpos < lengths[:, None])
    # stable-compact kept symbols to the front
    order = jnp.argsort(~keep, axis=1, stable=True)
    out = jnp.take_along_axis(am, order, axis=1)
    return out, jnp.sum(keep, axis=1).astype(jnp.int32)


def _edit_distance(a, alen, b, blen):
    """Batched Levenshtein via scan over a's positions. a: [B, Ta], b: [B, Tb]."""
    b_, ta = a.shape
    tb = b.shape[1]
    # row[j] = distance(a[:i], b[:j]); freeze once i > alen
    init = jnp.broadcast_to(jnp.arange(tb + 1, dtype=jnp.float32), (b_, tb + 1))

    def step(row, inp):
        ai, i = inp  # [B], scalar
        sub = (a[:, i][:, None] != b).astype(jnp.float32)  # [B, Tb]
        new = jnp.zeros_like(row).at[:, 0].set(i + 1.0)

        def inner(left, j):
            val = jnp.minimum(
                jnp.minimum(row[:, j + 1] + 1.0, left + 1.0),
                row[:, j] + sub[:, j],
            )
            return val, val

        _, cols = jax.lax.scan(inner, new[:, 0], jnp.arange(tb))
        new = jnp.concatenate([new[:, :1], jnp.moveaxis(cols, 0, 1)], axis=1)
        active = (i < alen)[:, None]
        return jnp.where(active, new, row), None

    row, _ = jax.lax.scan(step, init, (jnp.moveaxis(a, 1, 0), jnp.arange(ta)))
    return jnp.take_along_axis(row, blen[:, None], axis=1)[:, 0]


def ctc_error_evaluator(
    input: LayerOutput, label: LayerOutput, blank: int = 0,
    name: Optional[str] = None,
) -> Evaluator:
    """Sequence error = edit_distance(best-path decode, label) / label_len
    (reference CTCErrorEvaluator.cpp:277)."""
    nm = name or auto_name("ctc_error")

    def update(outs):
        pred_t, lab_t = outs[input.name], outs[label.name]
        dec, dec_len = _ctc_best_path(pred_t.data, pred_t.lengths, blank)
        lab = _ids_of(lab_t)
        dist = _edit_distance(dec, dec_len, lab, lab_t.lengths)
        return {
            "dist": jnp.sum(dist),
            "ref": jnp.sum(lab_t.lengths).astype(jnp.float32),
            "seqs": jnp.asarray(dec.shape[0], jnp.float32),
        }

    def finalize(acc):
        return {nm: float(acc["dist"]) / max(float(acc["ref"]), 1.0)}

    return Evaluator(nm, [input, label], update, finalize)


# ---------------------------------------------------------------------------
# chunk — F1 over chunk segmentations (reference ChunkEvaluator.cpp:288)
# label encoding: id = chunk_type * tag_num + tag, O = num_chunk_types*tag_num
# ---------------------------------------------------------------------------

_SCHEMES = {
    # tag ids within a type
    "plain": {"num": 1},
    "IOB": {"num": 2, "B": 0, "I": 1},
    "IOE": {"num": 2, "I": 0, "E": 1},
    "IOBES": {"num": 4, "B": 0, "I": 1, "E": 2, "S": 3},
}


def _chunk_bounds(ids, lengths, scheme: str, num_types: int):
    """(start [B,T] bool, end [B,T] bool, type [B,T]) per position."""
    sc = _SCHEMES[scheme]
    tag_num = sc["num"]
    o_id = num_types * tag_num
    is_o = ids >= o_id
    typ = jnp.where(is_o, -1, ids // tag_num)
    tag = jnp.where(is_o, -1, ids % tag_num)

    t_ = ids.shape[1]
    tpos = jnp.arange(t_)[None, :]
    valid = tpos < lengths[:, None]
    prev_typ = jnp.pad(typ, ((0, 0), (1, 0)), constant_values=-1)[:, :t_]
    prev_tag = jnp.pad(tag, ((0, 0), (1, 0)), constant_values=-1)[:, :t_]
    next_typ = jnp.pad(typ, ((0, 0), (0, 1)), constant_values=-1)[:, 1:]
    next_tag = jnp.pad(tag, ((0, 0), (0, 1)), constant_values=-1)[:, 1:]
    last_pos = tpos == (lengths[:, None] - 1)
    next_typ = jnp.where(last_pos, -1, next_typ)
    next_tag = jnp.where(last_pos, -1, next_tag)
    first_pos = tpos == 0
    in_chunk = (~is_o) & valid

    if scheme == "plain":
        start = in_chunk & (typ != prev_typ)
        end = in_chunk & (typ != next_typ)
    elif scheme == "IOB":
        start = in_chunk & (
            (tag == sc["B"])
            | ((tag == sc["I"]) & ((prev_typ != typ) | first_pos))
        )
        end = in_chunk & (
            (next_typ != typ) | (next_tag == sc["B"]) | last_pos
        )
    elif scheme == "IOE":
        start = in_chunk & ((prev_typ != typ) | (prev_tag == sc["E"]) | first_pos)
        end = in_chunk & ((tag == sc["E"]) | (next_typ != typ) | last_pos)
    else:  # IOBES
        start = in_chunk & ((tag == sc["B"]) | (tag == sc["S"]))
        end = in_chunk & ((tag == sc["E"]) | (tag == sc["S"]))
    return start & valid, end & valid, typ


def _next_end_pos(end):
    """[B, T] int: for each position, index of the next end >= it (T if none)."""
    b_, t_ = end.shape
    idx = jnp.where(end, jnp.arange(t_)[None, :], t_)
    # reverse cumulative min
    return jnp.flip(jax.lax.cummin(jnp.flip(idx, axis=1), axis=1), axis=1)


def chunk_evaluator(
    input: LayerOutput, label: LayerOutput,
    chunk_scheme: str = "IOB", num_chunk_types: int = 1,
    name: Optional[str] = None,
) -> Evaluator:
    nm = name or auto_name("chunk")

    def update(outs):
        pred_t, lab_t = outs[input.name], outs[label.name]
        lengths = lab_t.lengths
        pred = _ids_of(pred_t)
        gold = _ids_of(lab_t)
        ps, pe, pt = _chunk_bounds(pred, lengths, chunk_scheme, num_chunk_types)
        gs, ge, gt = _chunk_bounds(gold, lengths, chunk_scheme, num_chunk_types)
        p_end = _next_end_pos(pe)
        g_end = _next_end_pos(ge)
        correct = ps & gs & (pt == gt) & (p_end == g_end)
        return {
            "correct": jnp.sum(correct).astype(jnp.float32),
            "pred": jnp.sum(ps).astype(jnp.float32),
            "gold": jnp.sum(gs).astype(jnp.float32),
        }

    def finalize(acc):
        c, p, g = float(acc["correct"]), float(acc["pred"]), float(acc["gold"])
        prec = c / p if p else 0.0
        rec = c / g if g else 0.0
        f1 = 2 * prec * rec / (prec + rec) if prec + rec else 0.0
        return {f"{nm}.precision": prec, f"{nm}.recall": rec, f"{nm}.F1": f1}

    return Evaluator(nm, [input, label], update, finalize)


# ---------------------------------------------------------------------------
# printers — side-effect evaluators (reference value/maxid/seqtext printers)
# ---------------------------------------------------------------------------


def value_printer_evaluator(input: LayerOutput, name: Optional[str] = None) -> Evaluator:
    nm = name or auto_name("value_printer")

    def update(outs):
        jax.debug.print(nm + " {v}", v=outs[input.name].data)
        return {}

    return Evaluator(nm, [input], update, lambda a: {})


def maxid_printer_evaluator(input: LayerOutput, name: Optional[str] = None) -> Evaluator:
    nm = name or auto_name("maxid_printer")

    def update(outs):
        jax.debug.print(nm + " {v}", v=jnp.argmax(outs[input.name].data, axis=-1))
        return {}

    return Evaluator(nm, [input], update, lambda a: {})


def seq_text_printer_evaluator(
    input: LayerOutput,
    id_to_word=None,
    result_file: Optional[str] = None,
    id_input: Optional[LayerOutput] = None,
    dict_file: Optional[str] = None,
    name: Optional[str] = None,
) -> Evaluator:
    """Print id sequences as text (reference seqtext_printer_evaluator,
    trainer_config_helpers/evaluators.py: dict_file + result_file).
    `id_to_word` maps id→token (dict/list/callable); None prints raw ids.
    `dict_file` loads that mapping one token per line (the reference's
    surface); `id_input` (reference: separate id stream alongside the text
    stream) is accepted — the ids printed are the input layer's.
    The print runs host-side via io_callback so it works under jit."""
    nm = name or auto_name("seq_text_printer")
    if id_to_word is None and dict_file:
        with open(dict_file) as f:
            id_to_word = [ln.rstrip("\n").split("\t")[0] for ln in f]

    def to_text(ids, lengths):
        import numpy as np

        lines = []
        ids = np.asarray(ids)
        lengths = None if lengths is None else np.asarray(lengths)
        for i in range(ids.shape[0]):
            row = ids[i][: int(lengths[i])] if lengths is not None else ids[i]
            if id_to_word is None:
                toks = [str(int(t)) for t in row.reshape(-1)]
            elif callable(id_to_word):
                toks = [str(id_to_word(int(t))) for t in row.reshape(-1)]
            else:
                toks = [str(id_to_word[int(t)]) for t in row.reshape(-1)]
            lines.append(" ".join(toks))
        text = "\n".join(lines)
        if result_file:
            with open(result_file, "a") as f:
                f.write(text + "\n")
        else:
            print(f"{nm}:\n{text}")

    def update(outs):
        t = outs[input.name]
        if t.is_seq:
            jax.experimental.io_callback(
                to_text, None, t.data, t.lengths, ordered=True
            )
        else:
            jax.experimental.io_callback(to_text, None, t.data, None, ordered=True)
        return {}

    return Evaluator(nm, [input], update, lambda a: {})


def maxframe_printer_evaluator(
    input: LayerOutput, name: Optional[str] = None
) -> Evaluator:
    """Print, per sample, the FRAME (timestep) holding the maximum
    activation and that value (reference max_frame_printer,
    Evaluator.cpp:1061 MaxFramePrinter — prints the max-value frame of
    each sequence).  Non-sequence inputs degenerate to the per-sample max
    feature.  Runs host-side via io_callback so it works under jit."""
    nm = name or auto_name("maxframe_printer")

    def to_text(data, lengths):
        import numpy as np

        data = np.asarray(data)
        lengths = None if lengths is None else np.asarray(lengths)
        lines = []
        for i in range(data.shape[0]):
            row = data[i]
            if lengths is not None:
                row = row[: max(int(lengths[i]), 1)]
            flat = row.reshape(row.shape[0], -1) if row.ndim > 1 else (
                row.reshape(-1, 1)
            )
            per_frame = flat.max(axis=-1)
            frame = int(np.argmax(per_frame))
            lines.append(
                f"sample {i}: frame {frame} value {float(per_frame[frame]):.6g}"
            )
        print(f"{nm}:\n" + "\n".join(lines))

    def update(outs):
        t = outs[input.name]
        jax.experimental.io_callback(
            to_text, None, t.data,
            t.lengths if t.is_seq else None, ordered=True,
        )
        return {}

    return Evaluator(nm, [input], update, lambda a: {})


def classification_error_printer_evaluator(
    input: LayerOutput, label: LayerOutput, name: Optional[str] = None
) -> Evaluator:
    """Print the PER-INSTANCE classification error indicators (reference
    classification_error_printer, Evaluator.cpp:1337
    ClassificationErrorPrinter — the per-sample view of
    classification_error, printed instead of aggregated).  Sequence inputs
    print one 0/1 per valid timestep."""
    nm = name or auto_name("classification_error_printer")

    def to_text(err, w):
        import numpy as np

        err = np.asarray(err)
        w = np.asarray(w)
        vals = [
            str(int(e)) for e, ww in zip(err.reshape(-1), w.reshape(-1))
            if ww > 0
        ]
        print(f"{nm}: [" + " ".join(vals) + "]")

    def update(outs):
        pred = outs.get(input.name + "@logits")
        if pred is None:
            pred = outs[input.name]
        p, ids, w = _flat_valid(pred, outs[label.name])
        err = (jnp.argmax(p, axis=-1) != ids).astype(jnp.float32)
        jax.experimental.io_callback(to_text, None, err, w, ordered=True)
        return {}

    return Evaluator(nm, [input, label], update, lambda a: {})


def gradient_printer_evaluator(
    input: LayerOutput, name: Optional[str] = None
) -> Evaluator:
    """reference gradient_printer_evaluator prints a layer's output
    gradient mid-backward.  Backward here is one jax.grad over the whole
    step, so the per-layer output gradient is not materialized in the
    evaluator's (forward) view — the equivalent diagnostic is
    utils.debug.gradient_stats, which computes per-parameter gradient norms
    with a dedicated jax.grad.  This evaluator prints the layer's forward
    VALUE norm so v1 configs still run, and points at gradient_stats."""
    nm = name or auto_name("gradient_printer")

    def update(outs):
        v = outs[input.name].data
        jax.debug.print(
            nm + " forward-norm {n} (use utils.debug.gradient_stats for "
            "gradient norms)", n=jnp.linalg.norm(v.astype(jnp.float32)),
        )
        return {}

    return Evaluator(nm, [input], update, lambda a: {})


# ---------------------------------------------------------------------------
# detection mAP (reference DetectionMAPEvaluator.cpp:306)
# ---------------------------------------------------------------------------

_MAP_BINS = 1000


def detection_map_evaluator(
    input: LayerOutput,  # detection_output layer: [B, K, 6]
    label: LayerOutput,  # gt slot: [B, G, 6] (label,x1,y1,x2,y2,difficult)
    num_classes: int,
    overlap_threshold: float = 0.5,
    background_id: int = 0,
    evaluate_difficult: bool = False,
    ap_type: str = "11point",
    name: Optional[str] = None,
) -> Evaluator:
    """Streaming mAP: the in-graph update greedily matches each image's
    detections to ground truth (sorted by score, one gt per detection,
    IoU >= threshold) and accumulates TP/FP counts into per-class score-bin
    histograms; finalize integrates the binned PR curve on the host
    (11-point interpolation or trapezoid 'Integral', matching the
    reference's two ap_type modes).  The reference buffers every
    (score, tp/fp) pair on the host instead — binning keeps the accumulator
    static-shape for jit, at <=1/NBINS score resolution."""
    import jax

    from paddle_tpu.ops.detection import iou_matrix

    nm = name or auto_name("detection_map")

    def update(outs):
        det_t, gt_t = outs[input.name], outs[label.name]
        det = det_t.data  # [B, K, 6]
        gt = gt_t.data  # [B, G, 6]
        gt_valid = gt_t.mask(jnp.float32) > 0 if gt_t.is_seq else (
            jnp.ones(gt.shape[:2], bool)
        )

        def per_image(det_i, gt_i, valid_i):
            g_lab = gt_i[:, 0].astype(jnp.int32)
            g_box = gt_i[:, 1:5]
            g_diff = gt_i[:, 5] > 0
            counted = valid_i & (evaluate_difficult | ~g_diff)
            n_gt = jnp.zeros((num_classes,), jnp.float32).at[g_lab].add(
                counted.astype(jnp.float32)
            )
            # sort detections by score desc (detection_output emits top-k
            # globally sorted, but per-class order must be by score)
            order = jnp.argsort(-det_i[:, 1])
            det_i = det_i[order]
            d_lab = det_i[:, 0].astype(jnp.int32)
            d_score = det_i[:, 1]
            d_box = det_i[:, 2:6]
            ious = iou_matrix(d_box, g_box)  # [K, G]

            def body(used, k):
                lab, score, iou_k = d_lab[k], d_score[k], ious[k]
                # Reference calcTFPos: best-overlap gt over ALL same-class
                # gts (visited or not); a hit on a visited gt is an FP, a
                # hit on a skipped difficult gt is ignored and does NOT mark
                # the gt visited.
                cand = valid_i & (g_lab == lab)
                masked = jnp.where(cand, iou_k, -1.0)
                best = jnp.argmax(masked)
                hit = masked[best] >= overlap_threshold
                live = (lab >= 0) & (lab != background_id) & (score > 0)
                ignore = hit & g_diff[best] & (not evaluate_difficult)
                already = used[best]
                tp = live & hit & ~ignore & ~already
                fp = live & ((~hit) | (hit & ~ignore & already))
                used = used.at[best].set(already | (hit & live & ~ignore))
                bin_ = jnp.clip(
                    (score * _MAP_BINS).astype(jnp.int32), 0, _MAP_BINS - 1
                )
                return used, (lab, bin_, tp, fp)

            used0 = jnp.zeros(g_lab.shape, bool)
            _, (labs, bins, tps, fps) = jax.lax.scan(
                body, used0, jnp.arange(det_i.shape[0])
            )
            safe_lab = jnp.clip(labs, 0, num_classes - 1)
            tp_h = jnp.zeros((num_classes, _MAP_BINS), jnp.float32).at[
                safe_lab, bins
            ].add(tps.astype(jnp.float32))
            fp_h = jnp.zeros((num_classes, _MAP_BINS), jnp.float32).at[
                safe_lab, bins
            ].add(fps.astype(jnp.float32))
            return n_gt, tp_h, fp_h

        n_gt, tp_h, fp_h = jax.vmap(per_image)(det, gt, gt_valid)
        return {
            "n_gt": jnp.sum(n_gt, 0),
            "tp": jnp.sum(tp_h, 0),
            "fp": jnp.sum(fp_h, 0),
        }

    def finalize(acc):
        import numpy as np

        n_gt = np.asarray(acc["n_gt"])
        tp = np.asarray(acc["tp"])[:, ::-1]  # high-score bins first
        fp = np.asarray(acc["fp"])[:, ::-1]
        aps = []
        for c in range(num_classes):
            if c == background_id or n_gt[c] <= 0:
                continue
            ctp, cfp = np.cumsum(tp[c]), np.cumsum(fp[c])
            recall = ctp / n_gt[c]
            precision = ctp / np.maximum(ctp + cfp, 1e-10)
            if ap_type == "11point":
                ap = 0.0
                for t in np.linspace(0, 1, 11):
                    mask = recall >= t
                    ap += (precision[mask].max() if mask.any() else 0.0) / 11.0
            else:  # Integral: sum precision deltas over recall steps
                prev_r = 0.0
                ap = 0.0
                for r, p in zip(recall, precision):
                    ap += (r - prev_r) * p
                    prev_r = r
            aps.append(ap)
        return {nm: float(np.mean(aps)) if aps else 0.0}

    return Evaluator(nm, [input, label], update, finalize)


# ---------------------------------------------------------------------------
# combination helpers (used by the trainer)
# ---------------------------------------------------------------------------


def combined_update(evaluators: Sequence[Evaluator]):
    """One in-graph fn emitting all accumulators, namespaced per evaluator."""

    def update(outs) -> Accums:
        acc: Accums = {}
        for ev in evaluators:
            for k, v in ev.update(outs).items():
                acc[f"ev:{ev.name}:{k}"] = v
        return acc

    return update


def finalize_all(evaluators: Sequence[Evaluator], sums: Dict[str, object]) -> Dict[str, float]:
    out: Dict[str, float] = {}
    for ev in evaluators:
        prefix = f"ev:{ev.name}:"
        acc = {k[len(prefix):]: v for k, v in sums.items() if k.startswith(prefix)}
        if acc or not ev.layers:
            out.update(ev.finalize(acc))
    return out
