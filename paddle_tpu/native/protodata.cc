// Native decoder for the DataFormat.proto binary stream — the data-loader
// hot path (dense image + index label files like the reference's
// mnist_bin_part).  Mirrors the wire-format rules of io/protodata.py:
// varint32-framed proto2 messages (ProtoReader.h:53), DataHeader then
// DataSamples.  Scope: the DENSE+INDEX fast path, decoded in one pass into
// contiguous buffers the Python side wraps as numpy arrays; sparse /
// sequence / gzip files take the pure-Python decoder instead.
//
// C ABI (ctypes):
//   pdx_scan(path, &n_samples, &n_slots, types[], dims[], max_slots)
//   pdx_decode_dense_index(path, dense_ptrs[], index_ptrs[], expected)
// Buffers are allocated by the CALLER (numpy) at the sizes pdx_scan
// reports; decode fills them and refuses files whose sample count no
// longer matches `expected`.  Returns 0 on success, negative error codes.

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <vector>

namespace {

struct Buf {
  const uint8_t* p;
  size_t n;
  size_t pos = 0;
  bool ok = true;

  uint64_t varint() {
    uint64_t out = 0;
    int shift = 0;
    while (pos < n) {
      uint8_t b = p[pos++];
      out |= static_cast<uint64_t>(b & 0x7F) << shift;
      if (!(b & 0x80)) return out;
      shift += 7;
      if (shift > 63) break;
    }
    ok = false;
    return 0;
  }

  bool read_file(const char* path, std::string* store) {
    FILE* f = std::fopen(path, "rb");
    if (!f) return false;
    std::fseek(f, 0, SEEK_END);
    long sz = std::ftell(f);
    std::fseek(f, 0, SEEK_SET);
    store->resize(static_cast<size_t>(sz));
    size_t got = sz ? std::fread(&(*store)[0], 1, sz, f) : 0;
    std::fclose(f);
    if (got != static_cast<size_t>(sz)) return false;
    p = reinterpret_cast<const uint8_t*>(store->data());
    n = store->size();
    pos = 0;
    return true;
  }
};

constexpr int kDense = 0;  // SlotDef::VECTOR_DENSE
constexpr int kIndex = 3;  // SlotDef::INDEX

struct SlotDef {
  int type = -1;
  uint32_t dim = 0;
};

bool parse_header(const uint8_t* msg, size_t len, std::vector<SlotDef>* defs) {
  Buf b{msg, len};
  while (b.pos < b.n && b.ok) {
    uint64_t key = b.varint();
    int field = static_cast<int>(key >> 3), wt = static_cast<int>(key & 7);
    if (field == 1 && wt == 2) {  // SlotDef submessage
      uint64_t sz = b.varint();
      if (!b.ok || sz > b.n - b.pos) return false;
      Buf s{b.p + b.pos, static_cast<size_t>(sz)};
      SlotDef d;
      while (s.pos < s.n && s.ok) {
        uint64_t k2 = s.varint();
        int f2 = static_cast<int>(k2 >> 3), w2 = static_cast<int>(k2 & 7);
        uint64_t v = (w2 == 0) ? s.varint() : 0;
        if (w2 != 0) return false;  // SlotDef only has varint fields
        if (f2 == 1) d.type = static_cast<int>(v);
        if (f2 == 2) d.dim = static_cast<uint32_t>(v);
      }
      if (!s.ok) return false;
      defs->push_back(d);
      b.pos += sz;
    } else {
      return false;  // unexpected field in DataHeader
    }
  }
  return b.ok && !defs->empty();
}

// Walk one DataSample; when fill buffers are given, copy dense floats /
// index ids into them (per-kind running offsets).  Returns false on any
// wire-format surprise or a non-fast-path feature (sparse ids, strings,
// subseq slots).
bool walk_sample(const uint8_t* msg, size_t len,
                 const std::vector<SlotDef>& defs,
                 float** dense_fill, int32_t** index_fill,
                 size_t sample_idx) {
  Buf b{msg, len};
  size_t vec_i = 0;   // which dense slot (in slot order of kind)
  size_t idx_i = 0;   // which index value
  // Declared slot counts, computed up front: every write below must be
  // bounded by these.  The caller allocates exactly want_vec/want_idx
  // pointers, and the file content is re-read after pdx_scan (whose result
  // may come from a cache), so a sample with more slots than declared must
  // fail cleanly here rather than index past the pointer arrays.
  size_t want_vec = 0, want_idx = 0;
  for (const auto& d : defs) {
    if (d.type == kDense) ++want_vec;
    else if (d.type == kIndex) ++want_idx;
    else return false;
  }
  while (b.pos < b.n && b.ok) {
    uint64_t key = b.varint();
    int field = static_cast<int>(key >> 3), wt = static_cast<int>(key & 7);
    if (field == 1 && wt == 0) {        // is_beginning
      b.varint();
    } else if (field == 2 && wt == 2) { // VectorSlot
      uint64_t sz = b.varint();
      if (!b.ok || sz > b.n - b.pos) return false;
      Buf s{b.p + b.pos, static_cast<size_t>(sz)};
      bool saw_values = false;
      while (s.pos < s.n && s.ok) {
        uint64_t k2 = s.varint();
        int f2 = static_cast<int>(k2 >> 3), w2 = static_cast<int>(k2 & 7);
        if (f2 == 1 && w2 == 2) {  // packed float values
          uint64_t bytes = s.varint();
          if (!s.ok || bytes > s.n - s.pos || bytes % 4) return false;
          if (vec_i >= want_vec) return false;
          if (dense_fill) {
            // find the vec_i-th DENSE slot's dim for bounds checking
            size_t seen = 0;
            uint32_t dim = 0;
            for (const auto& d : defs) {
              if (d.type == kDense) {
                if (seen == vec_i) { dim = d.dim; break; }
                ++seen;
              }
            }
            if (bytes / 4 != dim) return false;
            std::memcpy(dense_fill[vec_i] + sample_idx * dim,
                        s.p + s.pos, bytes);
          }
          s.pos += bytes;
          saw_values = true;
        } else if (f2 == 1 && w2 == 5) {  // unpacked single float
          return false;  // rare; let Python handle it
        } else {
          return false;  // ids/dims/strs => not the fast path
        }
      }
      if (!s.ok || !saw_values) return false;
      ++vec_i;
      b.pos += sz;
    } else if (field == 3 && (wt == 2 || wt == 0)) {  // id_slots
      if (wt == 2) {
        uint64_t bytes = b.varint();
        if (!b.ok || bytes > b.n - b.pos) return false;
        Buf s{b.p + b.pos, static_cast<size_t>(bytes)};
        while (s.pos < s.n && s.ok) {
          uint64_t v = s.varint();
          if (idx_i >= want_idx) return false;
          if (index_fill) index_fill[idx_i][sample_idx] = static_cast<int32_t>(v);
          ++idx_i;
        }
        if (!s.ok) return false;
        b.pos += bytes;
      } else {
        uint64_t v = b.varint();
        if (idx_i >= want_idx) return false;
        if (index_fill) index_fill[idx_i][sample_idx] = static_cast<int32_t>(v);
        ++idx_i;
      }
    } else {
      return false;  // var_id_slots / subseq_slots => not the fast path
    }
  }
  if (!b.ok) return false;
  // every declared slot must have appeared (exactly once / exactly dim ids)
  return vec_i == want_vec && idx_i == want_idx;
}

}  // namespace

extern "C" {

// Scan: header + sample count; verifies every sample is dense/index-only.
// Returns 0 ok, -1 io, -2 wire format, -3 not fast path, -4 too many slots.
int pdx_scan(const char* path, long long* n_samples, int* n_slots,
             int* types, unsigned int* dims, int max_slots) {
  std::string store;
  Buf b{};
  if (!b.read_file(path, &store)) return -1;
  uint64_t hlen = b.varint();
  if (!b.ok || hlen > b.n - b.pos) return -2;
  std::vector<SlotDef> defs;
  if (!parse_header(b.p + b.pos, hlen, &defs)) return -2;
  b.pos += hlen;
  if (static_cast<int>(defs.size()) > max_slots) return -4;
  for (const auto& d : defs)
    if (d.type != kDense && d.type != kIndex) return -3;
  long long count = 0;
  while (b.pos < b.n) {
    uint64_t mlen = b.varint();
    if (!b.ok || mlen > b.n - b.pos) return -2;
    if (!walk_sample(b.p + b.pos, mlen, defs, nullptr, nullptr, 0)) return -3;
    b.pos += mlen;
    ++count;
  }
  *n_samples = count;
  *n_slots = static_cast<int>(defs.size());
  for (size_t i = 0; i < defs.size(); ++i) {
    types[i] = defs[i].type;
    dims[i] = defs[i].dim;
  }
  return 0;
}

// Decode into caller-allocated buffers: dense_ptrs[i] -> [expected, dim_i]
// f32 (slot order among DENSE slots), index_ptrs[j] -> [expected] int32.
// `expected` is the sample count pdx_scan reported — a file that changed
// size since the scan returns -5 instead of overflowing the buffers.
int pdx_decode_dense_index(const char* path, float** dense_ptrs,
                           int32_t** index_ptrs, long long expected) {
  std::string store;
  Buf b{};
  if (!b.read_file(path, &store)) return -1;
  uint64_t hlen = b.varint();
  if (!b.ok || hlen > b.n - b.pos) return -2;
  std::vector<SlotDef> defs;
  if (!parse_header(b.p + b.pos, hlen, &defs)) return -2;
  b.pos += hlen;
  size_t i = 0;
  while (b.pos < b.n) {
    if (static_cast<long long>(i) >= expected) return -5;  // file grew since scan
    uint64_t mlen = b.varint();
    if (!b.ok || mlen > b.n - b.pos) return -2;
    if (!walk_sample(b.p + b.pos, mlen, defs, dense_ptrs, index_ptrs, i))
      return -3;
    b.pos += mlen;
    ++i;
  }
  return (static_cast<long long>(i) == expected) ? 0 : -5;
}

}  // extern "C"
