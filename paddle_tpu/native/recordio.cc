// TPU-native data-IO runtime: recordio chunk format + background prefetcher.
//
// Replaces the reference's native data plane — the Go recordio chunks the
// master partitions into tasks (reference: go/master/service.go:105 uses
// recordio.Chunk offsets) and the C++ DataProvider's async double-buffer
// thread (reference: paddle/gserver/dataproviders/DataProvider.h DoubleBuffer)
// — as one small C library the Python framework loads via ctypes.
//
// File layout: a sequence of chunks.
//   chunk   := magic:u32 | crc32:u32 | body_len:u32 | n_records:u32 | body
//   body    := len_0:u32 ... len_{n-1}:u32 | payload_0 ... payload_{n-1}
// crc32 covers the body only.  All integers little-endian.  No compression
// (XLA hosts are never CPU-bound on raw record IO; gzip would serialize the
// prefetch threads).
//
// C ABI (ctypes-friendly): see the extern "C" block at the bottom.

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <condition_variable>
#include <deque>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace {

constexpr uint32_t kMagic = 0x7061646c;  // "padl"

// -- crc32 (standard polynomial, table-driven) ------------------------------
uint32_t crc_table[256];
bool crc_init_done = false;

void crc_init() {
  if (crc_init_done) return;
  for (uint32_t i = 0; i < 256; i++) {
    uint32_t c = i;
    for (int k = 0; k < 8; k++) c = (c & 1) ? 0xedb88320u ^ (c >> 1) : c >> 1;
    crc_table[i] = c;
  }
  crc_init_done = true;
}

uint32_t crc32_buf(const uint8_t* buf, size_t len) {
  crc_init();
  uint32_t c = 0xffffffffu;
  for (size_t i = 0; i < len; i++) c = crc_table[(c ^ buf[i]) & 0xff] ^ (c >> 8);
  return c ^ 0xffffffffu;
}

void put_u32(std::vector<uint8_t>* out, uint32_t v) {
  out->push_back(v & 0xff);
  out->push_back((v >> 8) & 0xff);
  out->push_back((v >> 16) & 0xff);
  out->push_back((v >> 24) & 0xff);
}

uint32_t get_u32(const uint8_t* p) {
  return (uint32_t)p[0] | ((uint32_t)p[1] << 8) | ((uint32_t)p[2] << 16) |
         ((uint32_t)p[3] << 24);
}

// -- writer -----------------------------------------------------------------
struct Writer {
  FILE* f = nullptr;
  std::vector<std::vector<uint8_t>> pending;
  size_t pending_bytes = 0;
  size_t max_chunk_bytes;
  uint32_t max_chunk_records;

  int flush() {
    if (pending.empty()) return 0;
    std::vector<uint8_t> body;
    body.reserve(pending_bytes + 4 * pending.size());
    for (auto& r : pending) put_u32(&body, (uint32_t)r.size());
    for (auto& r : pending) body.insert(body.end(), r.begin(), r.end());
    std::vector<uint8_t> head;
    put_u32(&head, kMagic);
    put_u32(&head, crc32_buf(body.data(), body.size()));
    put_u32(&head, (uint32_t)body.size());
    put_u32(&head, (uint32_t)pending.size());
    if (fwrite(head.data(), 1, head.size(), f) != head.size()) return -1;
    if (fwrite(body.data(), 1, body.size(), f) != body.size()) return -1;
    pending.clear();
    pending_bytes = 0;
    return 0;
  }
};

// -- reader -----------------------------------------------------------------
struct Reader {
  FILE* f = nullptr;
  std::deque<std::vector<uint8_t>> records;  // decoded from current chunk
  std::vector<uint8_t> current;              // last record handed out
  bool corrupt = false;

  // Reads the next chunk into `records`; false on EOF or error.
  bool load_chunk() {
    uint8_t head[16];
    if (fread(head, 1, 16, f) != 16) return false;
    if (get_u32(head) != kMagic) {
      corrupt = true;
      return false;
    }
    uint32_t crc = get_u32(head + 4);
    uint32_t body_len = get_u32(head + 8);
    uint32_t n = get_u32(head + 12);
    // The CRC covers the body only, so header fields are untrusted: the
    // length table must fit inside the body, and each record must stay in
    // bounds, or the chunk is treated as corrupt rather than read OOB.
    if (4ull * n > body_len) {
      corrupt = true;
      return false;
    }
    std::vector<uint8_t> body;
    try {
      body.resize(body_len);
    } catch (const std::bad_alloc&) {
      corrupt = true;
      return false;
    }
    if (fread(body.data(), 1, body_len, f) != body_len) {
      corrupt = true;
      return false;
    }
    if (crc32_buf(body.data(), body_len) != crc) {
      corrupt = true;
      return false;
    }
    size_t off = 4ul * n;
    const uint8_t* p = body.data();
    for (uint32_t i = 0; i < n; i++) {
      uint32_t len = get_u32(p + 4ul * i);
      if ((uint64_t)len > (uint64_t)body_len - off) {
        corrupt = true;
        records.clear();
        return false;
      }
      records.emplace_back(body.begin() + off, body.begin() + off + len);
      off += len;
    }
    return true;
  }
};

// -- prefetcher -------------------------------------------------------------
// N worker threads each own a disjoint set of files and push records into a
// bounded queue; the consumer pops.  This is the double-buffer thread of the
// reference DataProvider generalized to a pool.
struct Prefetcher {
  std::vector<std::string> paths;
  size_t capacity;
  std::deque<std::vector<uint8_t>> queue;
  std::mutex mu;
  std::condition_variable cv_push, cv_pop;
  std::vector<std::thread> workers;
  int active_workers = 0;
  bool stop = false;
  bool error = false;  // IO/corruption seen by any worker
  std::vector<uint8_t> current;

  void set_error() {
    std::unique_lock<std::mutex> lk(mu);
    error = true;
  }

  void worker(size_t begin, size_t end) {
    for (size_t i = begin; i < end && !stopped(); i++) {
      FILE* f = fopen(paths[i].c_str(), "rb");
      if (!f) {
        set_error();
        continue;
      }
      Reader r;
      r.f = f;
      while (!stopped() && (!r.records.empty() || r.load_chunk())) {
        while (!r.records.empty()) {
          std::vector<uint8_t> rec = std::move(r.records.front());
          r.records.pop_front();
          std::unique_lock<std::mutex> lk(mu);
          cv_push.wait(lk, [&] { return queue.size() < capacity || stop; });
          if (stop) break;
          queue.push_back(std::move(rec));
          cv_pop.notify_one();
        }
      }
      if (r.corrupt) set_error();
      fclose(f);
    }
    std::unique_lock<std::mutex> lk(mu);
    if (--active_workers == 0) cv_pop.notify_all();
  }

  bool stopped() {
    std::unique_lock<std::mutex> lk(mu);
    return stop;
  }
};

}  // namespace

extern "C" {

// ---- writer ----
void* rio_writer_create(const char* path, uint32_t max_chunk_records,
                        uint32_t max_chunk_bytes) {
  FILE* f = fopen(path, "wb");
  if (!f) return nullptr;
  Writer* w = new Writer();
  w->f = f;
  w->max_chunk_records = max_chunk_records ? max_chunk_records : 1000;
  w->max_chunk_bytes = max_chunk_bytes ? max_chunk_bytes : (1u << 20);
  return w;
}

int rio_writer_write(void* handle, const uint8_t* data, uint32_t len) {
  Writer* w = (Writer*)handle;
  w->pending.emplace_back(data, data + len);
  w->pending_bytes += len;
  if (w->pending.size() >= w->max_chunk_records ||
      w->pending_bytes >= w->max_chunk_bytes)
    return w->flush();
  return 0;
}

int rio_writer_close(void* handle) {
  Writer* w = (Writer*)handle;
  int rc = w->flush();
  if (fclose(w->f) != 0) rc = -1;
  delete w;
  return rc;
}

// ---- reader ----
void* rio_reader_open(const char* path) {
  FILE* f = fopen(path, "rb");
  if (!f) return nullptr;
  Reader* r = new Reader();
  r->f = f;
  return r;
}

// Seek to a chunk's byte offset (for master task partitioning).
int rio_reader_seek(void* handle, uint64_t offset) {
  Reader* r = (Reader*)handle;
  r->records.clear();
  return fseek(r->f, (long)offset, SEEK_SET);
}

// Returns record length and sets *out to an internal buffer valid until the
// next call; -1 at EOF, -2 on corruption.
int64_t rio_reader_next(void* handle, const uint8_t** out) {
  Reader* r = (Reader*)handle;
  if (r->records.empty() && !r->load_chunk())
    return r->corrupt ? -2 : -1;
  r->current = std::move(r->records.front());
  r->records.pop_front();
  *out = r->current.data();
  return (int64_t)r->current.size();
}

void rio_reader_close(void* handle) {
  Reader* r = (Reader*)handle;
  fclose(r->f);
  delete r;
}

// ---- chunk index scan (master task partitioning) ----
// Fills offsets[]/counts[] with each chunk's byte offset and record count.
// Returns number of chunks, or -1 on malformed file.
int64_t rio_scan_chunks(const char* path, uint64_t* offsets, uint32_t* counts,
                        int64_t cap) {
  FILE* f = fopen(path, "rb");
  if (!f) return -1;
  if (fseek(f, 0, SEEK_END) != 0) {
    fclose(f);
    return -1;
  }
  long fsize_l = ftell(f);
  if (fsize_l < 0 || fseek(f, 0, SEEK_SET) != 0) {
    fclose(f);
    return -1;
  }
  uint64_t fsize = (uint64_t)fsize_l;
  int64_t n = 0;
  uint8_t head[16];
  uint64_t pos = 0;
  while (fread(head, 1, 16, f) == 16) {
    if (get_u32(head) != kMagic) {
      fclose(f);
      return -1;
    }
    uint32_t body_len = get_u32(head + 8);
    uint32_t n_rec = get_u32(head + 12);
    // Header fields are not covered by the CRC: a chunk whose claimed body
    // overruns the file, or whose length table alone exceeds the body, marks
    // the file malformed instead of producing a phantom chunk index.
    if (4ull * n_rec > body_len || pos + 16 + (uint64_t)body_len > fsize) {
      fclose(f);
      return -1;
    }
    if (n < cap) {
      offsets[n] = pos;
      counts[n] = n_rec;
    }
    n++;
    pos += 16 + body_len;
    if (fseek(f, (long)pos, SEEK_SET) != 0) break;
  }
  fclose(f);
  return n;
}

// ---- prefetcher ----
void* rio_prefetcher_create(const char** paths, int32_t n_paths,
                            int32_t n_threads, int32_t capacity) {
  Prefetcher* p = new Prefetcher();
  for (int32_t i = 0; i < n_paths; i++) p->paths.emplace_back(paths[i]);
  p->capacity = capacity > 0 ? capacity : 1024;
  if (n_threads <= 0) n_threads = 2;
  if (n_threads > n_paths) n_threads = n_paths > 0 ? n_paths : 1;
  p->active_workers = n_threads;
  size_t per = (p->paths.size() + n_threads - 1) / n_threads;
  for (int32_t t = 0; t < n_threads; t++) {
    size_t b = t * per, e = std::min(p->paths.size(), (t + 1) * per);
    p->workers.emplace_back([p, b, e] { p->worker(b, e); });
  }
  return p;
}

// Blocks until a record is available or all workers finished.
// Returns length (with *out set), -1 at clean end of stream, or -2 when a
// worker hit an unopenable/corrupt file (after serving what it could).
int64_t rio_prefetcher_next(void* handle, const uint8_t** out) {
  Prefetcher* p = (Prefetcher*)handle;
  std::unique_lock<std::mutex> lk(p->mu);
  p->cv_pop.wait(lk, [&] { return !p->queue.empty() || p->active_workers == 0; });
  if (p->queue.empty()) return p->error ? -2 : -1;
  p->current = std::move(p->queue.front());
  p->queue.pop_front();
  p->cv_push.notify_one();
  *out = p->current.data();
  return (int64_t)p->current.size();
}

void rio_prefetcher_destroy(void* handle) {
  Prefetcher* p = (Prefetcher*)handle;
  {
    std::unique_lock<std::mutex> lk(p->mu);
    p->stop = true;
    p->cv_push.notify_all();
    p->cv_pop.notify_all();
  }
  for (auto& t : p->workers) t.join();
  delete p;
}

}  // extern "C"
