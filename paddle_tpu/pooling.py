"""Pooling type objects — the ``paddle.v2.pooling`` surface (reference:
python/paddle/trainer_config_helpers/poolings.py)."""

from __future__ import annotations


class BasePoolingType:
    name = "max"


class Max(BasePoolingType):
    name = "max"

    def __init__(self, output_max_index: bool = False):
        # reference MaxPooling(output_max_index=True): the sequence pool
        # emits per-feature argmax timestep indices instead of values
        self.output_max_index = output_max_index


class Avg(BasePoolingType):
    name = "average"


class Sum(BasePoolingType):
    name = "sum"


class SquareRootN(BasePoolingType):
    name = "sqrt_n"  # AverageLevel.kSqrtN sequence pooling


class CudnnMax(Max):
    pass


class CudnnAvg(Avg):
    pass


def pool_name(p) -> str:
    if p is None:
        return "max"
    if isinstance(p, str):
        return p
    if isinstance(p, BasePoolingType) or hasattr(p, "name"):
        return p.name
    if isinstance(p, type) and issubclass(p, BasePoolingType):
        return p.name
    raise TypeError(f"bad pooling type: {p!r}")
