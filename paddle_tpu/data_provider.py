"""PyDataProvider2-compatible ``@provider`` surface (reference:
python/paddle/trainer/PyDataProvider2.py:329-497 and the C++ host
paddle/gserver/dataproviders/PyDataProvider2.cpp:665).

The reference embeds CPython inside the C++ trainer and pulls samples from a
user generator decorated with ``@provider``; slot declarations
(dense/sparse/index × seq/sub-seq) tell the C++ side how to pack Arguments.
Here the roles flip — the framework *is* Python — so ``@provider`` wraps the
generator into a standard reader-creator that plugs straight into the v2
trainer's DataFeeder, with the same decorator knobs:

* ``input_types`` — list or dict of slot declarations (core.data_types)
* ``should_shuffle`` / ``pool_size`` — buffered shuffle (PyDataProvider2.cpp
  pool semantics)
* ``cache`` — CacheType.CACHE_PASS_IN_MEM keeps pass 1's samples in host RAM
* ``init_hook`` — called with a settings object (settings.input_types, slots,
  plus any kwargs) before reading
* ``check`` — validate each sample against the declared input_types
* ``calc_batch_size`` — custom per-sample weight (honored by the feeder's
  batching when provided)
"""

from __future__ import annotations

import collections.abc
import functools
from typing import Any, Callable, Dict, List, Optional, Sequence, Union

import numpy as np


def _is_oneshot_iter(v) -> bool:
    """py2-era providers yield ``map(int, row)``-style fields — and just as
    legally, generator expressions; under py3 all of these are one-shot
    iterators the feeder can't len()/index.  Any Iterator counts (str/bytes/
    ndarray are Iterables, not Iterators — excluded for clarity)."""
    return isinstance(v, collections.abc.Iterator) and not isinstance(
        v, (str, bytes, np.ndarray)
    )

from paddle_tpu.core import data_types as dt
from paddle_tpu.reader import decorator as reader_dec

__all__ = [
    "provider",
    "CacheType",
    "DataProviderConverter",
    # re-exported slot declarations (reference PyDataProvider2.py:73-215)
    "dense_slot",
    "dense_vector",
    "dense_vector_sequence",
    "sparse_non_value_slot",
    "sparse_binary_vector",
    "sparse_binary_vector_sequence",
    "sparse_value_slot",
    "sparse_vector",
    "sparse_vector_sequence",
    "index_slot",
    "integer_value",
    "integer_value_sequence",
    "integer_value_sub_sequence",
    "dense_vector_sub_sequence",
    "sparse_binary_vector_sub_sequence",
    "sparse_vector_sub_sequence",
]

dense_slot = dt.dense_vector
dense_vector = dt.dense_vector
dense_vector_sequence = dt.dense_vector_sequence
sparse_non_value_slot = dt.sparse_binary_vector
sparse_binary_vector = dt.sparse_binary_vector
sparse_binary_vector_sequence = dt.sparse_binary_vector_sequence
sparse_value_slot = dt.sparse_float_vector
sparse_vector = dt.sparse_float_vector
sparse_vector_sequence = dt.sparse_float_vector_sequence
index_slot = dt.integer_value
integer_value = dt.integer_value
integer_value_sequence = dt.integer_value_sequence
integer_value_sub_sequence = dt.integer_value_sub_sequence
dense_vector_sub_sequence = dt.dense_vector_sub_sequence
sparse_binary_vector_sub_sequence = dt.sparse_binary_vector_sub_sequence
sparse_vector_sub_sequence = dt.sparse_float_vector_sub_sequence


class CacheType:
    NO_CACHE = 0
    CACHE_PASS_IN_MEM = 1


class _Settings:
    """The object handed to init_hook (reference PyDataProvider2.py:356-377:
    'settings' carries input_types plus user args).  Reference hooks declare
    types by assigning EITHER ``settings.input_types`` (PyDataProvider2.py
    initializer pattern) OR ``settings.slots`` (the benchmark image provider,
    benchmark/paddle/image/provider.py initHook) — ``declared_types`` reads
    whichever was set."""

    def __init__(self, **kwargs):
        import logging

        self.input_types: Optional[Sequence[dt.InputType]] = None
        self.slots: Optional[Sequence[dt.InputType]] = None
        self.should_shuffle: Optional[bool] = None
        # reference hooks log through settings.logger (sequenceGen.py hook)
        self.logger = logging.getLogger("paddle_tpu.provider")
        for k, v in kwargs.items():
            setattr(self, k, v)

    def set_input_types(self, input_types):
        self.input_types = input_types
        self.slots = input_types

    def declared_types(self):
        return self.input_types if self.input_types is not None else self.slots


def _normalize_types(
    input_types: Union[Sequence[dt.InputType], Dict[str, dt.InputType], None]
):
    if input_types is None:
        return None, None
    if isinstance(input_types, dict):
        names = list(input_types.keys())
        return [input_types[n] for n in names], names
    return list(input_types), None


def _check_sample(sample, types: Sequence[dt.InputType]):
    items = sample if isinstance(sample, (list, tuple)) else (sample,)
    if len(items) != len(types):
        raise ValueError(
            f"sample has {len(items)} slots, provider declares {len(types)}"
        )
    for value, t in zip(items, types):
        if t.kind == dt.SlotKind.INDEX and t.seq == dt.SeqLevel.NONE:
            if not np.issubdtype(np.asarray(value).dtype, np.integer):
                raise ValueError(f"index slot got non-integer {value!r}")
        if t.kind == dt.SlotKind.DENSE and t.seq == dt.SeqLevel.NONE:
            arr = np.asarray(value, dtype=np.float32)
            if arr.size != t.dim:
                raise ValueError(
                    f"dense slot dim mismatch: got {arr.size}, want {t.dim}"
                )


def provider(
    input_types=None,
    should_shuffle=None,
    pool_size=1024,
    min_pool_size=-1,
    can_over_batch_size=True,
    calc_batch_size=None,
    cache=CacheType.NO_CACHE,
    check=False,
    check_fail_continue=False,
    init_hook: Optional[Callable[..., None]] = None,
    **outter_kwargs,
):
    """Decorate ``def process(settings, filename): yield sample``.

    The decorated symbol becomes a factory: calling it with the file list (or
    any objects the process function understands) plus init_hook kwargs
    returns a reader-creator compatible with ``trainer.SGD.train``.
    """

    types, names = _normalize_types(input_types)

    def __wrapper__(generator):
        @functools.wraps(generator)
        def factory(*files, **hook_kwargs):
            # is_train resolves should_shuffle=None the way the reference
            # trainer context does (shuffle for train, stable order for
            # test/predict); it stays in hook_kwargs so init_hook sees it
            # too, matching the reference PyDataProvider2 hook contract.
            is_train = bool(hook_kwargs.get("is_train", True))
            settings = _Settings(**outter_kwargs)
            if types is not None:
                settings.set_input_types(types)
            settings.should_shuffle = should_shuffle
            if init_hook is not None:
                init_hook(settings, file_list=list(files), **hook_kwargs)
            # init_hook may (re)declare input_types — or settings.slots —
            # (the reference initializer pattern); re-normalize so dict
            # samples and checks use the hook's declaration.
            eff_types, eff_names = types, names
            declared = settings.declared_types()
            if declared is not None and declared is not types:
                eff_types, eff_names = _normalize_types(declared)

            def base_reader():
                file_list = files if files else (None,)
                for f in file_list:
                    for sample in generator(settings, f):
                        if isinstance(sample, dict):
                            if eff_names is None:
                                raise ValueError(
                                    "generator yields dict samples but "
                                    "input_types was not a dict"
                                )
                            sample = tuple(sample[n] for n in eff_names)
                        elif _is_oneshot_iter(sample):
                            # a whole-sample iterator (map/filter/zip or a
                            # generator expression, reference benchmark/
                            # paddle/rnn/provider.py:72); materialize so the
                            # feeder can len()/index it
                            sample = tuple(sample)
                        if isinstance(sample, tuple):
                            sample = tuple(
                                list(fld) if _is_oneshot_iter(fld) else fld
                                for fld in sample
                            )
                        if check and eff_types:
                            try:
                                _check_sample(sample, eff_types)
                            except ValueError:
                                if check_fail_continue:
                                    continue
                                raise
                        yield sample

            rd = base_reader
            if cache == CacheType.CACHE_PASS_IN_MEM:
                rd = reader_dec.cache(rd)
            # init_hook may override the decorator's should_shuffle (the
            # reference's test/predict readers do exactly this); None falls
            # back to the trainer context: shuffle only when training.
            shuffle_flag = settings.should_shuffle
            if shuffle_flag is None:
                shuffle_flag = is_train
            if shuffle_flag:
                rd = reader_dec.shuffle(rd, pool_size)
            if cache == CacheType.CACHE_PASS_IN_MEM:
                # the TPU-native half of CACHE_PASS_IN_MEM: tag the reader
                # so the trainer keeps the DECODED pass device-resident and
                # replays it for epochs >= 2 (reader/pass_cache.py); the
                # host-RAM cache above still spares the generator re-run for
                # the capture epoch's own restarts.  paddle.batch and
                # token_budget_batch propagate the tags.  Replay shuffling
                # follows the provider's own shuffle intent: a
                # should_shuffle=False provider (ordered/curriculum data)
                # must replay in capture order, like the reference's
                # host-RAM cache did.
                rd.cache_pass_in_mem = True
                rd.cache_pass_shuffle = bool(shuffle_flag)
            return rd

        def resolve_input_types(file_list=(), **hook_kwargs):
            """Run init_hook (if any) on a fresh settings object and return
            (types, slot_names) — parse_config uses this to learn slot types
            that the provider only declares inside its hook (reference
            PyDataProvider2 initializer pattern, run with the config's real
            args + file list like PyDataProvider2.cpp:665 does)."""
            settings = _Settings(**outter_kwargs)
            if types is not None:
                settings.set_input_types(types)
            if init_hook is not None:
                init_hook(settings, file_list=list(file_list), **hook_kwargs)
            return _normalize_types(settings.declared_types())

        factory.input_types = types
        factory.slot_names = names
        factory.resolve_input_types = resolve_input_types
        factory.calc_batch_size = calc_batch_size
        return factory

    return __wrapper__


class DataProviderConverter:
    """numpy/py-list samples → padded Batch (reference:
    paddle/py_paddle/dataprovider_converter.py:247 built swig Arguments; here
    the target is the static-shape Batch consumed by the jitted step)."""

    def __init__(self, input_types: Sequence[dt.InputType]):
        from paddle_tpu.reader.feeder import DataFeeder

        if isinstance(input_types, dict):
            named = list(input_types.items())
        else:
            named = [(f"slot_{i}", t) for i, t in enumerate(input_types)]
        self._feeder = DataFeeder(named)

    def convert(self, dat, argument=None):
        return self._feeder(dat)

    __call__ = convert
