"""paddle_tpu — a TPU-native deep-learning framework with the capabilities of
early PaddlePaddle (reference: zhoudaqing/Paddle, v1 gserver engine + v2 API),
re-architected on JAX/XLA: topologies compile to single jitted XLA programs,
distribution is a jax.sharding Mesh with ICI collectives (no parameter
server), sequences are padded lax.scan loops.

User surface mirrors ``paddle.v2``::

    import paddle_tpu as paddle
    paddle.init()
    img = paddle.layer.data("pixel", paddle.data_type.dense_vector(784))
    ...
    trainer = paddle.trainer.SGD(cost, parameters, paddle.optimizer.Momentum(...))
    trainer.train(paddle.batch(paddle.dataset.mnist.train(), 128), ...)
"""

from __future__ import annotations

from paddle_tpu import activation  # noqa: F401
from paddle_tpu import attr  # noqa: F401
from paddle_tpu import dataset  # noqa: F401
from paddle_tpu import evaluator  # noqa: F401
from paddle_tpu import event  # noqa: F401
from paddle_tpu import layers as layer  # noqa: F401
from paddle_tpu.layers import networks  # noqa: F401
from paddle_tpu import optimizer  # noqa: F401
from paddle_tpu import parallel  # noqa: F401
from paddle_tpu import parameters  # noqa: F401
from paddle_tpu import pooling  # noqa: F401
from paddle_tpu import reader  # noqa: F401
from paddle_tpu import trainer  # noqa: F401
from paddle_tpu.core import data_types as data_type  # noqa: F401
from paddle_tpu.core.compiler import CompiledNetwork  # noqa: F401
from paddle_tpu.core.topology import Topology  # noqa: F401
from paddle_tpu.minibatch import batch  # noqa: F401
from paddle_tpu import inference  # noqa: F401
from paddle_tpu.inference import Inference, infer  # noqa: F401
from paddle_tpu import v1_compat  # noqa: F401

__version__ = "0.1.0"


def init(
    use_tpu: bool = True,
    trainer_count: int = 1,
    seed: int = 0,
    compute_dtype=None,
    **kwargs,
) -> None:
    """paddle.init equivalent (reference: paddle/utils/Util.h initMain via
    swig initPaddle).  JAX needs no global init; `use_tpu`/`trainer_count`
    are accepted for config compatibility — device selection and parallelism
    come from the jax platform and the mesh instead.

    compute_dtype: 'bfloat16' enables mixed precision for networks built
    after this call (master params stay float32; see core.compiler).
    """
    import random

    import numpy as np

    random.seed(seed)
    np.random.seed(seed)
    if compute_dtype is not None:
        from paddle_tpu.core.compiler import set_default_compute_dtype

        set_default_compute_dtype(compute_dtype)
