"""paddle_tpu — a TPU-native deep-learning framework with the capabilities of
early PaddlePaddle (reference: zhoudaqing/Paddle, v1 gserver engine + v2 API),
re-architected on JAX/XLA: topologies compile to single jitted XLA programs,
distribution is a jax.sharding Mesh with ICI collectives (no parameter
server), sequences are padded lax.scan loops.

User surface mirrors ``paddle.v2``::

    import paddle_tpu as paddle
    paddle.init()
    img = paddle.layer.data("pixel", paddle.data_type.dense_vector(784))
    ...
    trainer = paddle.trainer.SGD(cost, parameters, paddle.optimizer.Momentum(...))
    trainer.train(paddle.batch(paddle.dataset.mnist.train(), 128), ...)
"""

from __future__ import annotations

from paddle_tpu import activation  # noqa: F401
from paddle_tpu import attr  # noqa: F401
from paddle_tpu import dataset  # noqa: F401
from paddle_tpu import evaluator  # noqa: F401
from paddle_tpu import event  # noqa: F401
from paddle_tpu import layers as layer  # noqa: F401
from paddle_tpu.layers import networks  # noqa: F401
from paddle_tpu import optimizer  # noqa: F401
from paddle_tpu import parallel  # noqa: F401
from paddle_tpu import parameters  # noqa: F401
from paddle_tpu import pooling  # noqa: F401
from paddle_tpu import reader  # noqa: F401
from paddle_tpu import trainer  # noqa: F401
from paddle_tpu.core import data_types as data_type  # noqa: F401
from paddle_tpu.core import topology  # noqa: F401
from paddle_tpu.core.compiler import CompiledNetwork  # noqa: F401
from paddle_tpu.core.topology import Topology  # noqa: F401
from paddle_tpu import master  # noqa: F401
from paddle_tpu.minibatch import batch  # noqa: F401
from paddle_tpu import inference  # noqa: F401
from paddle_tpu import model  # noqa: F401
from paddle_tpu.inference import Inference, infer  # noqa: F401
from paddle_tpu import v1_compat  # noqa: F401
from paddle_tpu import plot  # noqa: F401
from paddle_tpu import image  # noqa: F401
from paddle_tpu import launcher  # noqa: F401
from paddle_tpu.utils import flags  # noqa: F401
from paddle_tpu.utils import profiler  # noqa: F401

__version__ = "0.1.0"


def init(
    use_tpu=None,
    trainer_count=None,
    seed=None,
    compute_dtype=None,
    **kwargs,
) -> None:
    """paddle.init equivalent (reference: paddle/utils/Util.h initMain via
    swig initPaddle).  JAX needs no global init; `use_tpu`/`trainer_count`
    are accepted for config compatibility — device selection and parallelism
    come from the jax platform and the mesh instead.

    compute_dtype: 'bfloat16' enables mixed precision for networks built
    after this call (master params stay float32; see core.compiler).

    Remaining keyword arguments set flags from the global flags plane
    (utils/flags.py — the gflags surface, e.g. check_nans=True,
    log_period=50); unknown names are accepted-and-ignored like the
    reference's tolerant command-line init.
    """
    import random

    import numpy as np

    from paddle_tpu.utils import flags as _flags

    # Only arguments the caller actually passed enter the explicit layer —
    # otherwise init()'s python defaults would mask PADDLE_TPU_* env
    # overrides (the documented defaults < env < explicit precedence).
    explicit = {
        k: v
        for k, v in dict(
            use_tpu=use_tpu, trainer_count=trainer_count, seed=seed
        ).items()
        if v is not None
    }
    if "use_tpu" in explicit:
        explicit["use_tpu"] = bool(explicit["use_tpu"])
    _flags.set_flags(**explicit)
    seed_val = _flags.get_flag("seed")
    random.seed(seed_val)
    np.random.seed(seed_val)
    for k, v in kwargs.items():
        try:
            _flags.set_flag(k, v)
        except KeyError:
            pass  # v1 configs pass gpu-era flags; accept silently
    # compute_dtype comes from THIS call's argument, else the flag plane
    # (env PADDLE_TPU_COMPUTE_DTYPE or an explicit flags.set_flag).  init
    # never WRITES the flag: the argument is per-call configuration, so a
    # later bare init() (or set_default_compute_dtype(None)) is not
    # silently overridden by an earlier call's choice.
    dtype_src = (
        compute_dtype
        if compute_dtype is not None
        else _flags.get_flag("compute_dtype")
    )
    if dtype_src:
        from paddle_tpu.core.compiler import set_default_compute_dtype

        set_default_compute_dtype(dtype_src)
    if _flags.get_flag("check_nans"):
        from paddle_tpu.utils.profiler import enable_nan_checks

        enable_nan_checks(True)
