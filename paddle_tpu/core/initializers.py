"""Parameter initialization, matching the reference defaults
(reference: paddle/parameter/Parameter.cpp randomize + config_parser.py
default initial_std = 1/sqrt(fan_in) gaussian, initial_mean = 0)."""

from __future__ import annotations

import math
from typing import Optional, Sequence

import jax
import jax.numpy as jnp


def default_std(fan_in: int) -> float:
    return 1.0 / math.sqrt(max(fan_in, 1))


def normal(rng, shape: Sequence[int], std: Optional[float] = None, dtype=jnp.float32):
    if std is None:
        fan_in = shape[0] if len(shape) >= 2 else shape[-1]
        std = default_std(int(fan_in))
    return std * jax.random.normal(rng, tuple(shape), dtype)


def uniform(rng, shape: Sequence[int], scale: float, dtype=jnp.float32):
    return jax.random.uniform(rng, tuple(shape), dtype, -scale, scale)


def zeros(shape: Sequence[int], dtype=jnp.float32):
    return jnp.zeros(tuple(shape), dtype)


def ones(shape: Sequence[int], dtype=jnp.float32):
    return jnp.ones(tuple(shape), dtype)


def conv_normal(rng, shape: Sequence[int], dtype=jnp.float32):
    """For conv kernels laid out [kh, kw, cin, cout]: std over receptive field."""
    kh, kw, cin, _ = shape
    return normal(rng, shape, default_std(kh * kw * cin), dtype)
