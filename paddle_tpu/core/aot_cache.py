"""Persistent AOT executable cache — warm boot = deserialize, not retrace.

The in-process :class:`~paddle_tpu.core.compiler.CompileShapeCache` accounts
jit keys per batch-shape rung; this module extends that contract onto disk:
every (step kind, topology, ladder rung, mesh, dtype/donation) variant the
shape ladder realizes is serialized once — ``jit(...).lower(...).compile()``
+ ``jax.experimental.serialize_executable`` — and every later process boot
deserializes instead of paying the full XLA retrace.  This is the
Julia-to-TPU paper's full-compilation argument (arXiv:1810.09868) applied to
boot cost: the whole train step is ONE offloadable XLA computation, so its
compiled form is a cacheable artifact, multiplied across the bucketing
ladder's rung set and across every worker of a fleet.

Safety model — a wrong executable must be impossible to load:

* **Identity key** (hashed into the filename): step kind + n_steps,
  topology fingerprint (``Topology.serialize()`` hash + compute dtype),
  batch shape-ladder key, mesh/sharding spec, donation signature.
* **Environment key** (stored in the entry header, compared on load):
  jax version, backend platform, device kind + count, optimizer
  fingerprint, package version.  A mismatch is a **stale** entry — counted,
  warned once, retraced, and overwritten with a fresh entry.  An entry
  whose header names a different identity (hash collision, a foreign file
  renamed into place) is detected the same way: the FULL key is compared,
  never trusted from the filename.
* **Integrity**: the pickled executable blob carries a CRC32 and its byte
  length in the header; truncation or corruption is a **corrupt** entry —
  counted, warned once, retraced, overwritten.  Loads never raise.
* **Version shim**: jax builds without ``serialize_executable`` (or
  backends whose executables refuse to serialize) degrade to today's
  retrace path — ``available()`` is False, every ``get_or_compile`` is a
  plain ``lower().compile()`` and nothing touches disk (the
  ``parallel/mesh.py`` shard_map-shim pattern).

Counters ride the StatSet plane (``aot_cache/{hit,miss,stale,corrupt}``) so
the per-pass stats table says whether a boot was warm.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import pickle
import struct
import time
import zlib
from typing import Any, Dict, List, Optional, Tuple

_log = logging.getLogger("paddle_tpu.aot_cache")

__all__ = [
    "AOTCache",
    "serialization_available",
    "optimizer_fingerprint",
    "topology_fingerprint",
    "mesh_fingerprint",
]

_MAGIC = b"PTAOT1\n"
_SUFFIX = ".aotx"


def serialization_available() -> bool:
    """True when this jax build can serialize compiled executables (the
    version-compat shim: older/newer jax without the module simply keeps
    the retrace path — behavior degrades, never breaks)."""
    try:
        from jax.experimental import serialize_executable as se

        return hasattr(se, "serialize") and hasattr(se, "deserialize_and_load")
    except Exception:  # pragma: no cover - import-time variance across jax
        return False


def topology_fingerprint(network) -> str:
    """Identity of the compiled program's graph: the serialized topology
    (types/sizes/attrs — the same structural comparison SGD uses to decide
    network reuse) plus the compute dtype it lowers at."""
    text = network.topology.serialize() + f"|compute={network.compute_dtype}"
    return hashlib.sha256(text.encode()).hexdigest()[:16]


def mesh_fingerprint(mesh) -> str:
    if mesh is None:
        return "none"
    try:
        shape = dict(mesh.shape)
    except Exception:
        shape = {}
    return f"axes={sorted(shape.items())}"


def optimizer_fingerprint(opt) -> str:
    """Stable identity of an optimizer's baked-in constants (learning rate,
    schedule args, slot hyperparameters): two optimizers that would compile
    different update programs must fingerprint differently — an executable
    cached for lr=0.1 silently reused at lr=0.01 is exactly the wrong-code
    load this cache must never do."""
    parts: Dict[str, Any] = {"class": type(opt).__name__}
    for k, v in sorted(vars(opt).items()):
        if isinstance(v, (int, float, str, bool, tuple, type(None))):
            parts[k] = v
        elif k in ("regularization", "model_average"):
            parts[k] = repr(v)
    return repr(sorted(parts.items()))


def _env_key() -> Dict[str, Any]:
    import jax

    try:
        devs = jax.devices()
        kind, count = devs[0].device_kind, len(devs)
        platform = devs[0].platform
    except Exception:  # pragma: no cover - backendless build
        kind, count, platform = "unknown", 0, "unknown"
    import paddle_tpu

    return {
        "jax": jax.__version__,
        "backend": platform,
        "device_kind": kind,
        "n_devices": count,
        "paddle_tpu": paddle_tpu.__version__,
    }


def _key_hash(identity: Dict[str, Any]) -> str:
    return hashlib.sha256(
        json.dumps(identity, sort_keys=True).encode()
    ).hexdigest()[:24]


def _write_entry(path: str, header: Dict[str, Any], blob: bytes) -> None:
    """MAGIC | header_len:u32 | header json | crc32:u32 | blob — written
    tmp+rename so a concurrent reader never sees a torn entry."""
    hdr = json.dumps(header, sort_keys=True).encode()
    tmp = path + f".tmp.{os.getpid()}"
    with open(tmp, "wb") as f:
        f.write(_MAGIC)
        f.write(struct.pack(">I", len(hdr)))
        f.write(hdr)
        f.write(struct.pack(">I", zlib.crc32(blob) & 0xFFFFFFFF))
        f.write(blob)
    os.replace(tmp, path)


def _read_header(path: str) -> Tuple[Dict[str, Any], int, int]:
    """(header, blob offset, blob crc) — framing-validated WITHOUT reading
    the blob (``cache ls`` lists hundreds of MB of executables by header
    alone).  Raises ValueError on any damage, including truncation inside
    the fixed-size fields: every read is length-checked before unpacking,
    so a torn file can never leak a struct.error past the caller's
    ValueError handling."""
    with open(path, "rb") as f:
        if f.read(len(_MAGIC)) != _MAGIC:
            raise ValueError("bad magic")
        raw = f.read(4)
        if len(raw) != 4:
            raise ValueError("truncated header length")
        (hlen,) = struct.unpack(">I", raw)
        hraw = f.read(hlen)
        if len(hraw) != hlen:
            raise ValueError(f"truncated header: {len(hraw)} != {hlen} bytes")
        try:
            header = json.loads(hraw.decode())
        except Exception as e:
            raise ValueError(f"bad header: {e}")
        raw = f.read(4)
        if len(raw) != 4:
            raise ValueError("truncated CRC")
        (crc,) = struct.unpack(">I", raw)
    return header, len(_MAGIC) + 4 + hlen + 4, crc


def _read_entry(path: str) -> Tuple[Dict[str, Any], bytes]:
    """(header, blob) — raises ValueError on any framing/CRC damage (the
    caller maps that to the `corrupt` counter; this never loads a damaged
    blob)."""
    header, offset, crc = _read_header(path)
    with open(path, "rb") as f:
        f.seek(offset)
        blob = f.read()
    want = int(header.get("blob_bytes", -1))
    if want >= 0 and len(blob) != want:
        raise ValueError(f"truncated blob: {len(blob)} != {want} bytes")
    if zlib.crc32(blob) & 0xFFFFFFFF != crc:
        raise ValueError("blob CRC mismatch")
    return header, blob


class AOTCache:
    """On-disk serialized-executable store keyed by the ladder contract.

    ``get_or_compile(jitted, args, identity, meta)`` is the whole surface a
    dispatch loop needs: a valid entry deserializes (**hit**), anything
    else compiles via ``jitted.lower(*args).compile()`` (**miss**; stale /
    corrupt entries also bump their own counter) and — when this jax can
    serialize — writes the fresh executable back for the next boot.

    ``identity`` names what program this is (hashed into the filename);
    ``meta`` names what must ALSO match for the entry to be loadable
    (jax/backend versions are merged in automatically).  ``compiles`` and
    ``loads`` count what actually happened in-process — the warm-boot
    assertion (`compiles == 0` on a populated cache) reads them directly.
    """

    def __init__(self, cache_dir: str, stats=None):
        from paddle_tpu.utils.timers import global_stats

        self.dir = os.path.abspath(cache_dir)
        os.makedirs(self.dir, exist_ok=True)
        self._stats = stats if stats is not None else global_stats
        self.compiles = 0  # full XLA compiles this process performed
        self.loads = 0  # executables deserialized from disk
        self._warned: set = set()

    # -- key plumbing ----------------------------------------------------
    def entry_path(self, identity: Dict[str, Any]) -> str:
        return os.path.join(self.dir, _key_hash(identity) + _SUFFIX)

    def full_key(self, identity: Dict[str, Any], meta: Optional[Dict] = None
                 ) -> Dict[str, Any]:
        return {**identity, **(meta or {}), **_env_key()}

    def _warn_once(self, category: str, msg: str, *args) -> None:
        if category not in self._warned:
            self._warned.add(category)
            _log.warning(msg + " (warning once; counters keep counting)",
                         *args)

    # -- load / store ----------------------------------------------------
    def load(self, identity: Dict[str, Any], meta: Optional[Dict] = None):
        """The cached executable for this full key, or None (miss / stale /
        corrupt — counted; never raises, never loads a mismatched entry)."""
        path = self.entry_path(identity)
        if not os.path.exists(path):
            return None
        try:
            header, blob = _read_entry(path)
        except (OSError, ValueError) as e:
            self._stats.incr("aot_cache/corrupt")
            self._warn_once(
                "corrupt",
                "aot cache entry %s is damaged (%s); retracing", path, e,
            )
            return None
        want = self.full_key(identity, meta)
        have = header.get("key", {})
        if have != want:
            diff = sorted(
                k for k in set(want) | set(have)
                if want.get(k) != have.get(k)
            )
            self._stats.incr("aot_cache/stale")
            self._warn_once(
                "stale",
                "aot cache entry %s is stale (mismatched fields: %s); "
                "retracing", path, diff,
            )
            return None
        try:
            from jax.experimental import serialize_executable as se

            payload, in_tree, out_tree = pickle.loads(blob)  # wire: allow[A206] local CRC32-verified AOT cache blob under the operator's cache_dir, never network input; serialized XLA executables are not expressible in the restricted wire codec
            exe = se.deserialize_and_load(payload, in_tree, out_tree)
        except Exception as e:
            self._stats.incr("aot_cache/corrupt")
            self._warn_once(
                "corrupt",
                "aot cache entry %s failed to deserialize (%s); retracing",
                path, e,
            )
            return None
        self._stats.incr("aot_cache/hit")
        self.loads += 1
        return exe

    def store(self, identity: Dict[str, Any], compiled,
              meta: Optional[Dict] = None) -> bool:
        """Serialize one compiled executable; False (warn once) when this
        jax/backend cannot serialize it — the retrace path stays correct."""
        if not serialization_available():
            self._warn_once(
                "unsupported",
                "this jax build has no executable serialization; aot cache "
                "%s stays empty (warm boots will retrace)", self.dir,
            )
            return False
        try:
            from jax.experimental import serialize_executable as se

            payload, in_tree, out_tree = se.serialize(compiled)
            blob = pickle.dumps((payload, in_tree, out_tree))
        except Exception as e:
            self._warn_once(
                "unsupported",
                "executable refused to serialize (%s); aot cache entry "
                "skipped", e,
            )
            return False
        header = {
            "key": self.full_key(identity, meta),
            "created": time.time(),
            "blob_bytes": len(blob),
        }
        try:
            _write_entry(self.entry_path(identity), header, blob)
        except OSError as e:
            self._warn_once(
                "unwritable", "aot cache dir %s unwritable (%s)", self.dir, e
            )
            return False
        return True

    def get_or_compile(self, jitted, args, identity: Dict[str, Any],
                       meta: Optional[Dict] = None):
        """One dispatch-boundary call: cached executable when the full key
        matches, else compile (counted as a miss — the warm-boot metric is
        exactly these), store for the next boot, and return the compiled
        executable so the caller never pays the trace twice."""
        exe = self.load(identity, meta)
        if exe is not None:
            return exe
        self._stats.incr("aot_cache/miss")
        compiled = jitted.lower(*args).compile()
        self.compiles += 1
        self.store(identity, compiled, meta)
        return compiled

    # -- maintenance (the `paddle-tpu cache` CLI surface) ----------------
    def entries(self) -> List[Dict[str, Any]]:
        """Per-entry metadata for ``cache ls``: size, age, and the full key
        provenance out of the header (damaged headers list as corrupt).
        Header-only reads — blob integrity is the load path's job, so
        listing a store of hundreds of MB stays cheap."""
        out: List[Dict[str, Any]] = []
        for name in sorted(os.listdir(self.dir)):
            if not name.endswith(_SUFFIX):
                continue
            path = os.path.join(self.dir, name)
            ent: Dict[str, Any] = {
                "file": name,
                "bytes": os.path.getsize(path),
                "mtime": os.path.getmtime(path),
            }
            try:
                header, _, _ = _read_header(path)
                ent["key"] = header.get("key", {})
                ent["created"] = header.get("created")
            except (OSError, ValueError) as e:
                ent["corrupt"] = str(e)
            out.append(ent)
        return out

    def total_bytes(self) -> int:
        return sum(e["bytes"] for e in self.entries())

    def _sweep_tmp(self) -> List[str]:
        """Remove orphaned ``*.tmp.<pid>`` files a killed writer left
        behind (the chaos/preemption drills SIGKILL mid-write by design).
        Only run from the explicit maintenance commands — a tmp file
        belonging to a LIVE concurrent writer swept at boot would fail its
        rename."""
        removed = []
        for name in os.listdir(self.dir):
            if ".tmp." not in name:
                continue
            try:
                os.remove(os.path.join(self.dir, name))
                removed.append(name)
            except OSError:
                pass
        return removed

    def prune(self, max_bytes: int) -> List[str]:
        """Drop oldest-first (mtime) until the store fits; orphaned tmp
        files and corrupt entries go first.  Returns the removed
        filenames."""
        removed_tmp = self._sweep_tmp()
        ents = self.entries()
        ents.sort(key=lambda e: (0 if "corrupt" in e else 1, e["mtime"]))
        total = sum(e["bytes"] for e in ents)
        removed = list(removed_tmp)
        for e in ents:
            if total <= max_bytes and "corrupt" not in e:
                break
            try:
                os.remove(os.path.join(self.dir, e["file"]))
            except OSError:
                continue
            total -= e["bytes"]
            removed.append(e["file"])
        return removed

    def clear(self) -> int:
        n = len(self._sweep_tmp())
        for name in os.listdir(self.dir):
            if name.endswith(_SUFFIX):
                try:
                    os.remove(os.path.join(self.dir, name))
                    n += 1
                except OSError:
                    pass
        return n

    def summary(self) -> Dict[str, Any]:
        ents = self.entries()  # one directory scan, header-only reads
        return {
            "dir": self.dir,
            "entries": len(ents),
            "mb": round(sum(e["bytes"] for e in ents) / 1e6, 2),
            "compiles": self.compiles,
            "loads": self.loads,
            "hit": self._stats.count("aot_cache/hit"),
            "miss": self._stats.count("aot_cache/miss"),
            "stale": self._stats.count("aot_cache/stale"),
            "corrupt": self._stats.count("aot_cache/corrupt"),
            "serialization": serialization_available(),
        }
