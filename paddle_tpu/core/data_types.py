"""Input slot type declarations — the user-facing equivalent of the reference's
``paddle.trainer.PyDataProvider2`` input_types (reference:
python/paddle/trainer/PyDataProvider2.py:140-260).

The reference expresses variable-length data as CSR-packed rows plus
``sequenceStartPositions`` (reference: paddle/parameter/Argument.h:84-93).  On
TPU we instead declare a static-shape contract up front: every sequence slot is
padded to a bucketed max length and carried as ``[B, T, ...]`` plus a
``lengths[B]`` vector, so the whole step stays jit-compilable with static
shapes.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Optional


class SlotKind(enum.Enum):
    DENSE = "dense"
    SPARSE_BINARY = "sparse_binary"
    SPARSE_FLOAT = "sparse_float"
    INDEX = "index"


class SeqLevel(enum.IntEnum):
    NONE = 0  # one value per sample
    SEQ = 1  # a sequence of values per sample
    SUB_SEQ = 2  # a nested sequence (sequence of sequences)


@dataclasses.dataclass(frozen=True)
class InputType:
    """Declares shape/semantics of one data slot."""

    dim: int
    kind: SlotKind
    seq: SeqLevel = SeqLevel.NONE
    # Number of non-zero entries to keep per timestep for sparse slots when
    # densified into gather-friendly id/value buffers.
    max_nnz: Optional[int] = None

    @property
    def is_seq(self) -> bool:
        return self.seq != SeqLevel.NONE


def dense_vector(dim: int) -> InputType:
    return InputType(dim, SlotKind.DENSE)


def dense_vector_sequence(dim: int) -> InputType:
    return InputType(dim, SlotKind.DENSE, SeqLevel.SEQ)


def integer_value(value_range: int) -> InputType:
    return InputType(value_range, SlotKind.INDEX)


def integer_value_sequence(value_range: int) -> InputType:
    return InputType(value_range, SlotKind.INDEX, SeqLevel.SEQ)


def integer_value_sub_sequence(value_range: int) -> InputType:
    return InputType(value_range, SlotKind.INDEX, SeqLevel.SUB_SEQ)


def dense_vector_sub_sequence(dim: int) -> InputType:
    return InputType(dim, SlotKind.DENSE, SeqLevel.SUB_SEQ)


def sparse_binary_vector_sub_sequence(dim: int, max_nnz: int = 64) -> InputType:
    return InputType(dim, SlotKind.SPARSE_BINARY, SeqLevel.SUB_SEQ, max_nnz)


def sparse_float_vector_sub_sequence(dim: int, max_nnz: int = 64) -> InputType:
    return InputType(dim, SlotKind.SPARSE_FLOAT, SeqLevel.SUB_SEQ, max_nnz)


def sparse_binary_vector(dim: int, max_nnz: int = 64) -> InputType:
    return InputType(dim, SlotKind.SPARSE_BINARY, SeqLevel.NONE, max_nnz)


def sparse_binary_vector_sequence(dim: int, max_nnz: int = 64) -> InputType:
    return InputType(dim, SlotKind.SPARSE_BINARY, SeqLevel.SEQ, max_nnz)


def sparse_float_vector(dim: int, max_nnz: int = 64) -> InputType:
    return InputType(dim, SlotKind.SPARSE_FLOAT, SeqLevel.NONE, max_nnz)


def sparse_float_vector_sequence(dim: int, max_nnz: int = 64) -> InputType:
    return InputType(dim, SlotKind.SPARSE_FLOAT, SeqLevel.SEQ, max_nnz)


# Aliases matching the reference naming.
dense_array = dense_vector
sparse_vector = sparse_float_vector
sparse_non_value_slot = sparse_binary_vector
index_slot = integer_value
