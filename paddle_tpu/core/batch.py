"""Batch value types — the TPU-native replacement for the reference's
``Argument`` (reference: paddle/parameter/Argument.h:29-157).

The reference packs variable-length sequences into CSR form (`value` rows +
`sequenceStartPositions`).  Static XLA shapes want padded tensors, so the
in-graph value type is :class:`SeqTensor`: a padded array plus optional
per-sample lengths (and sub-sequence segment ids for nested sequences).
All layer implementations consume and produce SeqTensors.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp


@jax.tree_util.register_pytree_node_class
class SeqTensor:
    """A (possibly sequential) batch value.

    data:        [B, ...] for plain samples; [B, T, ...] padded when seq;
                 [B, S, T, ...] doubly padded for nested sequences (a sequence
                 of subsequences — the reference's SUB_SEQUENCE slots).
    lengths:     [B] int32 — valid timesteps (plain seq) or valid subsequence
                 count (nested), or None for non-sequence.
    sub_lengths: [B, S] int32 valid-timestep counts of each subsequence, or
                 None.  Replaces the reference's CSR
                 subSequenceStartPositions (Argument.h:84-93) — static doubly
                 padded shapes instead of two-level offset vectors, so nested
                 recurrence lowers to a lax.scan over S whose body sees an
                 ordinary [B, T, ...] sequence.
    """

    def __init__(self, data, lengths=None, sub_lengths=None, sparse_ids=False):
        self.data = data
        self.lengths = lengths
        self.sub_lengths = sub_lengths
        # True when `data` is the PADDED-ID form of a big-vocab sparse
        # slot ([..., max_nnz] int32 ids, sentinel == vocab) — set by the
        # feeder, consumed by fc/mixed projections via
        # layers.base.is_sparse_ids (exact dispatch, no shape heuristics)
        self.sparse_ids = sparse_ids

    # -- pytree protocol ----------------------------------------------------
    def tree_flatten(self):
        children = (self.data, self.lengths, self.sub_lengths)
        return children, self.sparse_ids

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children, sparse_ids=bool(aux))

    # -- helpers ------------------------------------------------------------
    @property
    def is_seq(self) -> bool:
        return self.lengths is not None

    @property
    def is_nested(self) -> bool:
        return self.sub_lengths is not None

    @property
    def batch_size(self) -> int:
        return self.data.shape[0]

    @property
    def max_len(self) -> int:
        """Extent of the outer padded axis: T (plain seq) or S (nested)."""
        assert self.is_seq
        return self.data.shape[1]

    @property
    def max_sub_len(self) -> int:
        assert self.is_nested
        return self.data.shape[2]

    def mask(self, dtype=jnp.float32):
        """[B, T] (or [B, S] for nested) 1/0 validity of the outer axis."""
        assert self.is_seq
        t = jnp.arange(self.max_len, dtype=jnp.int32)
        return (t[None, :] < self.lengths[:, None]).astype(dtype)

    def sub_mask(self, dtype=jnp.float32):
        """[B, S, T] joint validity: subsequence s valid AND timestep t valid
        within it."""
        assert self.is_nested
        outer = self.mask(dtype)  # [B, S]
        t = jnp.arange(self.max_sub_len, dtype=jnp.int32)
        inner = (t[None, None, :] < self.sub_lengths[:, :, None]).astype(dtype)
        return outer[:, :, None] * inner

    def masked_data(self):
        """data with padding timesteps zeroed."""
        if not self.is_seq:
            return self.data
        m = self.sub_mask(self.data.dtype) if self.is_nested else self.mask(self.data.dtype)
        return self.data * m.reshape(m.shape + (1,) * (self.data.ndim - m.ndim))

    def with_data(self, data) -> "SeqTensor":
        return SeqTensor(data, self.lengths, self.sub_lengths)

    def __repr__(self) -> str:  # pragma: no cover
        shp = getattr(self.data, "shape", None)
        return f"SeqTensor(shape={shp}, seq={self.is_seq}, nested={self.is_nested})"


Batch = Dict[str, SeqTensor]  # slot name -> value, the feeder's output


def non_seq(data) -> SeqTensor:
    return SeqTensor(jnp.asarray(data))


def seq(data, lengths) -> SeqTensor:
    return SeqTensor(jnp.asarray(data), jnp.asarray(lengths, dtype=jnp.int32))


def nested_seq(data, n_sub, sub_lengths) -> SeqTensor:
    """[B, S, T, ...] doubly-padded nested sequence: n_sub[B] valid
    subsequences, sub_lengths[B, S] valid timesteps per subsequence."""
    return SeqTensor(
        jnp.asarray(data),
        jnp.asarray(n_sub, dtype=jnp.int32),
        jnp.asarray(sub_lengths, dtype=jnp.int32),
    )
