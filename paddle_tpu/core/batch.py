"""Batch value types — the TPU-native replacement for the reference's
``Argument`` (reference: paddle/parameter/Argument.h:29-157).

The reference packs variable-length sequences into CSR form (`value` rows +
`sequenceStartPositions`).  Static XLA shapes want padded tensors, so the
in-graph value type is :class:`SeqTensor`: a padded array plus optional
per-sample lengths (and sub-sequence segment ids for nested sequences).
All layer implementations consume and produce SeqTensors.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp


@jax.tree_util.register_pytree_node_class
class SeqTensor:
    """A (possibly sequential) batch value.

    data:        [B, ...] for plain samples; [B, T, ...] padded when seq;
                 [B, S, T, ...] doubly padded for nested sequences (a sequence
                 of subsequences — the reference's SUB_SEQUENCE slots).
    lengths:     [B] int32 — valid timesteps (plain seq) or valid subsequence
                 count (nested), or None for non-sequence.
    sub_lengths: [B, S] int32 valid-timestep counts of each subsequence, or
                 None.  Replaces the reference's CSR
                 subSequenceStartPositions (Argument.h:84-93) — static doubly
                 padded shapes instead of two-level offset vectors, so nested
                 recurrence lowers to a lax.scan over S whose body sees an
                 ordinary [B, T, ...] sequence.
    """

    def __init__(self, data, lengths=None, sub_lengths=None, sparse_ids=False):
        self.data = data
        self.lengths = lengths
        self.sub_lengths = sub_lengths
        # True when `data` is the PADDED-ID form of a big-vocab sparse
        # slot ([..., max_nnz] int32 ids, sentinel == vocab) — set by the
        # feeder, consumed by fc/mixed projections via
        # layers.base.is_sparse_ids (exact dispatch, no shape heuristics)
        self.sparse_ids = sparse_ids

    # -- pytree protocol ----------------------------------------------------
    def tree_flatten(self):
        children = (self.data, self.lengths, self.sub_lengths)
        return children, self.sparse_ids

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children, sparse_ids=bool(aux))

    # -- helpers ------------------------------------------------------------
    @property
    def is_seq(self) -> bool:
        return self.lengths is not None

    @property
    def is_nested(self) -> bool:
        return self.sub_lengths is not None

    @property
    def batch_size(self) -> int:
        return self.data.shape[0]

    @property
    def max_len(self) -> int:
        """Extent of the outer padded axis: T (plain seq) or S (nested)."""
        assert self.is_seq
        return self.data.shape[1]

    @property
    def max_sub_len(self) -> int:
        assert self.is_nested
        return self.data.shape[2]

    def mask(self, dtype=jnp.float32):
        """[B, T] (or [B, S] for nested) 1/0 validity of the outer axis."""
        assert self.is_seq
        t = jnp.arange(self.max_len, dtype=jnp.int32)
        return (t[None, :] < self.lengths[:, None]).astype(dtype)

    def sub_mask(self, dtype=jnp.float32):
        """[B, S, T] joint validity: subsequence s valid AND timestep t valid
        within it."""
        assert self.is_nested
        outer = self.mask(dtype)  # [B, S]
        t = jnp.arange(self.max_sub_len, dtype=jnp.int32)
        inner = (t[None, None, :] < self.sub_lengths[:, :, None]).astype(dtype)
        return outer[:, :, None] * inner

    def masked_data(self):
        """data with padding timesteps zeroed."""
        if not self.is_seq:
            return self.data
        m = self.sub_mask(self.data.dtype) if self.is_nested else self.mask(self.data.dtype)
        return self.data * m.reshape(m.shape + (1,) * (self.data.ndim - m.ndim))

    def with_data(self, data) -> "SeqTensor":
        return SeqTensor(data, self.lengths, self.sub_lengths)

    def __repr__(self) -> str:  # pragma: no cover
        shp = getattr(self.data, "shape", None)
        return f"SeqTensor(shape={shp}, seq={self.is_seq}, nested={self.is_nested})"


Batch = Dict[str, SeqTensor]  # slot name -> value, the feeder's output


# ---------------------------------------------------------------------------
# Bucket-shape canonicalization — the feed→compile→scan shape-ladder contract
# ---------------------------------------------------------------------------
# Variable-length workloads recompile the jitted step once per distinct batch
# shape.  The contract threaded through reader.bucketing → DataFeeder →
# trainer.step → layers.recurrent_group is: every padded sequence extent is a
# rung of one small geometric ladder (16·2^k), so the jit cache sees a
# bounded shape set no matter how lengths are distributed, and the token-
# budget batcher (reader/bucketing.py) keeps tokens/step ~constant by scaling
# batch size inversely with the rung.

DEFAULT_LADDER: Tuple[int, ...] = (16, 32, 64, 128, 256, 512, 1024, 2048, 4096)

# Nested sequences' S axis (subsequence count) is typically small (2-8);
# rounding it on the 16-based time ladder would pad the common case 4-8x.
# A 4-based ladder bounds the shape set just as well without the blowup.
DEFAULT_SUB_LADDER: Tuple[int, ...] = (4, 8, 16, 32, 64, 128, 256, 512, 1024)

# The BATCH axis is a compiled extent exactly like the padded time axis: a
# caller feeding ragged batch sizes (inference.Inference, the serving plane's
# ragged live-slot set) retraces per distinct B unless B rides a ladder too.
# Batches start at 1 (single-request decode) so the ladder is 2^k, not 16·2^k.
DEFAULT_BATCH_LADDER: Tuple[int, ...] = (
    1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024,
)


def shape_ladder(base: int = 16, rungs: int = 9) -> Tuple[int, ...]:
    """Geometric shape ladder base·2^k, k in [0, rungs)."""
    return tuple(base << k for k in range(rungs))


def ladder_len(n: int, ladder: Sequence[int] = DEFAULT_LADDER) -> int:
    """Smallest ladder rung >= n; past the top rung, the next multiple of it
    (so absurdly long outliers still get a canonical — if coarse — shape)."""
    n = max(int(n), 1)
    for r in ladder:
        if n <= r:
            return r
    top = ladder[-1]
    return ((n + top - 1) // top) * top


def batch_shape_key(batch: Batch) -> tuple:
    """Hashable shape signature of a feeder batch — exactly the part of the
    jit cache key the feed controls (slot names, data shapes, dtypes).  Two
    batches with equal keys dispatch to the same compiled executable."""
    key = []
    for name in sorted(batch):
        t = batch[name]
        data = t.data if hasattr(t, "data") else t
        key.append((name, tuple(int(d) for d in data.shape), str(data.dtype)))
    return tuple(key)


def _pad_axis(data, axis: int, to: int):
    """Zero-pad one axis of a host/device array up to `to` (no-op if equal).
    Works on numpy and jax arrays alike (the feeder hands numpy; bench and
    tests may hand staged device arrays)."""
    cur = data.shape[axis]
    if cur >= to:
        return data
    import numpy as np

    pad = [(0, 0)] * data.ndim
    pad[axis] = (0, to - cur)
    mod = jnp if isinstance(data, jax.Array) else np
    return mod.pad(data, pad)


def canonicalize_batch(
    batch: Batch, ladder: Sequence[int] = DEFAULT_LADDER
) -> Batch:
    """Round every sequence slot's padded extents up to the shape ladder.

    Plain sequences pad T (axis 1) to ``ladder_len(T)``; nested sequences pad
    both S (axis 1) and T (axis 2).  Lengths are untouched — the new
    positions are beyond every sample's valid range, so masks, cost sums and
    the scan early-exit (layers/recurrent_group.py) all treat them as dead
    padding.  Zero-pad is correct for every slot kind here: the added
    positions are whole masked-out timesteps, not intra-step nnz slots (the
    feeder's sparse-ids sentinel concern)."""
    out: Batch = {}
    for name, t in batch.items():
        if not hasattr(t, "data") or not t.is_seq:
            out[name] = t
            continue
        sub_lengths = t.sub_lengths
        if t.is_nested:
            # S axis (outer, axis 1) rounds on the shallow sub-ladder; T
            # (axis 2) on the time ladder
            data = _pad_axis(
                t.data, 1, ladder_len(t.data.shape[1], DEFAULT_SUB_LADDER)
            )
            data = _pad_axis(data, 2, ladder_len(data.shape[2], ladder))
            # sub_lengths must track the padded S axis (new subsequences
            # are empty: zero valid timesteps) or mask consumers see an
            # internally inconsistent SeqTensor
            sub_lengths = _pad_axis(sub_lengths, 1, data.shape[1])
        else:
            data = _pad_axis(t.data, 1, ladder_len(t.data.shape[1], ladder))
        out[name] = SeqTensor(
            data, t.lengths, sub_lengths, sparse_ids=t.sparse_ids
        )
    return out


def pad_batch_rows(batch: Batch, to_b: int) -> Batch:
    """Pad every slot's BATCH axis (axis 0) up to ``to_b`` dead rows.

    The batch-axis half of the ladder contract (DEFAULT_BATCH_LADDER):
    callers with ragged batch sizes pad B to a rung, dispatch ONE compiled
    program, and slice the leading ``b`` rows back out of every output
    (:func:`slice_batch_rows`).  Padded rows are all-zero data; sequence
    lengths pad with 1 — one valid zero timestep — so per-row normalizers
    (mean pooling's divide-by-length) never see 0/0 in the dead rows.  Row
    independence of the forward pass keeps the live rows bit-identical."""
    out: Batch = {}
    for name, t in batch.items():
        if not hasattr(t, "data"):
            out[name] = t
            continue
        b = t.data.shape[0]
        if b >= to_b:
            out[name] = t
            continue
        data = _pad_axis(t.data, 0, to_b)
        lengths = t.lengths
        sub_lengths = t.sub_lengths
        import numpy as np

        mod = jnp if isinstance(t.data, jax.Array) else np
        if lengths is not None:
            pad_len = mod.ones((to_b - b,), dtype=lengths.dtype)
            lengths = mod.concatenate([lengths, pad_len], axis=0)
        if sub_lengths is not None:
            pad_sub = mod.ones(
                (to_b - b,) + tuple(sub_lengths.shape[1:]), dtype=sub_lengths.dtype
            )
            sub_lengths = mod.concatenate([sub_lengths, pad_sub], axis=0)
        out[name] = SeqTensor(data, lengths, sub_lengths, sparse_ids=t.sparse_ids)
    return out


def slice_batch_rows(outs: Dict[str, SeqTensor], b: int) -> Dict[str, SeqTensor]:
    """Undo :func:`pad_batch_rows` on a dict of output SeqTensors: keep the
    first ``b`` rows of data/lengths/sub_lengths."""
    sliced: Dict[str, SeqTensor] = {}
    for name, t in outs.items():
        if not hasattr(t, "data"):
            sliced[name] = t
            continue
        sliced[name] = SeqTensor(
            t.data[:b],
            None if t.lengths is None else t.lengths[:b],
            None if t.sub_lengths is None else t.sub_lengths[:b],
            sparse_ids=t.sparse_ids,
        )
    return sliced


def non_seq(data) -> SeqTensor:
    return SeqTensor(jnp.asarray(data))


def seq(data, lengths) -> SeqTensor:
    return SeqTensor(jnp.asarray(data), jnp.asarray(lengths, dtype=jnp.int32))


def nested_seq(data, n_sub, sub_lengths) -> SeqTensor:
    """[B, S, T, ...] doubly-padded nested sequence: n_sub[B] valid
    subsequences, sub_lengths[B, S] valid timesteps per subsequence."""
    return SeqTensor(
        jnp.asarray(data),
        jnp.asarray(n_sub, dtype=jnp.int32),
        jnp.asarray(sub_lengths, dtype=jnp.int32),
    )
