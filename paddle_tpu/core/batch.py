"""Batch value types — the TPU-native replacement for the reference's
``Argument`` (reference: paddle/parameter/Argument.h:29-157).

The reference packs variable-length sequences into CSR form (`value` rows +
`sequenceStartPositions`).  Static XLA shapes want padded tensors, so the
in-graph value type is :class:`SeqTensor`: a padded array plus optional
per-sample lengths (and sub-sequence segment ids for nested sequences).
All layer implementations consume and produce SeqTensors.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp


@jax.tree_util.register_pytree_node_class
class SeqTensor:
    """A (possibly sequential) batch value.

    data:      [B, ...] for plain samples, or [B, T, ...] padded when seq.
    lengths:   [B] int32 valid-timestep counts, or None for non-sequence.
    sub_starts:[B, S] int32 start offsets of nested subsequences (padded with
               `lengths`), or None — replaces subSequenceStartPositions
               (reference Argument.h:88).
    """

    def __init__(self, data, lengths=None, sub_starts=None):
        self.data = data
        self.lengths = lengths
        self.sub_starts = sub_starts

    # -- pytree protocol ----------------------------------------------------
    def tree_flatten(self):
        children = (self.data, self.lengths, self.sub_starts)
        return children, None

    @classmethod
    def tree_unflatten(cls, _aux, children):
        return cls(*children)

    # -- helpers ------------------------------------------------------------
    @property
    def is_seq(self) -> bool:
        return self.lengths is not None

    @property
    def batch_size(self) -> int:
        return self.data.shape[0]

    @property
    def max_len(self) -> int:
        assert self.is_seq
        return self.data.shape[1]

    def mask(self, dtype=jnp.float32):
        """[B, T] 1/0 validity mask from lengths."""
        assert self.is_seq
        t = jnp.arange(self.max_len, dtype=jnp.int32)
        return (t[None, :] < self.lengths[:, None]).astype(dtype)

    def masked_data(self):
        """data with padding timesteps zeroed."""
        if not self.is_seq:
            return self.data
        m = self.mask(self.data.dtype)
        return self.data * m.reshape(m.shape + (1,) * (self.data.ndim - 2))

    def with_data(self, data) -> "SeqTensor":
        return SeqTensor(data, self.lengths, self.sub_starts)

    def __repr__(self) -> str:  # pragma: no cover
        shp = getattr(self.data, "shape", None)
        return f"SeqTensor(shape={shp}, seq={self.is_seq})"


Batch = Dict[str, SeqTensor]  # slot name -> value, the feeder's output


def non_seq(data) -> SeqTensor:
    return SeqTensor(jnp.asarray(data))


def seq(data, lengths) -> SeqTensor:
    return SeqTensor(jnp.asarray(data), jnp.asarray(lengths, dtype=jnp.int32))
