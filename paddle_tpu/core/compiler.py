"""Topology → pure JAX function compiler.

This replaces the reference's runtime layer-graph interpreter
(``NeuralNetwork::forward`` looping over C++ Layer objects, reference:
paddle/gserver/gradientmachines/NeuralNetwork.cpp:235-292) with a trace-time
loop: :meth:`CompiledNetwork.apply` walks the topology **while being traced by
jax.jit**, so the emitted program is one fused XLA computation per step —
the OpDesc→HLO lowering the north star asks for.  Gradients come from
``jax.grad`` over the whole step instead of per-layer ``backward``.

State handling: trainable parameters and non-trainable state (batch-norm
moving stats — the reference mutates these inside forward,
paddle/gserver/layers/BatchNormBaseLayer.h) are separate pytrees; ``apply``
returns updated state functionally.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from paddle_tpu.core.batch import Batch, SeqTensor, batch_shape_key
from paddle_tpu.core.topology import Topology
from paddle_tpu.layers.base import ApplyContext, get_layer_impl, stable_hash
from paddle_tpu.ops.activations import apply_activation

Params = Dict[str, Dict[str, Any]]
NetState = Dict[str, Dict[str, Any]]

# Global default compute dtype for newly-built networks.  Master parameters
# always live in float32; when this is bfloat16 the forward/backward compute
# runs in bf16 on the MXU (mixed precision — the cast's transpose upcasts
# gradients back to f32 for the optimizer).  Set via paddle.init or
# settings(), queried at CompiledNetwork construction.
_default_compute_dtype = None


def set_default_compute_dtype(dtype) -> None:
    global _default_compute_dtype
    _default_compute_dtype = None if dtype is None else jnp.dtype(dtype)


def get_default_compute_dtype():
    return _default_compute_dtype


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def _error_clip(x, t):
    """Identity forward; the backward clips the layer-output cotangent to
    [-t, t] (reference ExtraLayerAttribute.error_clipping_threshold,
    Layer.cpp backwardActivation)."""
    return x


def _error_clip_fwd(x, t):
    return x, None


def _error_clip_bwd(t, _res, g):
    return (jnp.clip(g, -t, t),)


_error_clip.defvjp(_error_clip_fwd, _error_clip_bwd)


def _cast_floats(tree, dtype):
    """Cast every floating leaf of a pytree to `dtype` (ints/bools pass)."""
    def cast(x):
        if hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating):
            return x.astype(dtype)  # num: allow[N406] the mixed contract quantizes EVERY non-full-precision layer output at its boundary, even when an f32 consumer follows — downstream must see the same values a fully-bf16 pipeline produces
        return x

    return jax.tree_util.tree_map(cast, tree)


def _feed_transform(conf, t):
    """On-device narrow-dtype feed (DataProvider.h double-buffer parity —
    the reference never ships float32 pixels either; mnist_bin_part stores
    uint8).  A DENSE slot arriving at an integer dtype (the DataFeeder's
    ``feed_dtypes`` wire form) is cast to float32 here, INSIDE the jitted
    step, and normalized with the data layer's feed_scale/feed_shift attrs
    — XLA fuses the cast+scale into the first consumer, so the host->device
    transfer is 1/4 the bytes with zero extra kernels."""
    from paddle_tpu.core.data_types import SlotKind

    it = conf.input_type
    data = t.data if hasattr(t, "data") else t
    if (
        it is None
        or it.kind != SlotKind.DENSE
        or not jnp.issubdtype(data.dtype, jnp.integer)
    ):
        return t
    x = data.astype(jnp.float32)
    scale = conf.attr("feed_scale") or 0.0
    shift = conf.attr("feed_shift") or 0.0
    if scale:
        x = x * scale
    if shift:
        x = x + shift
    return SeqTensor(x, getattr(t, "lengths", None),
                     getattr(t, "sub_lengths", None))


def _walk_layers(topology, prefix=()):
    """(path, conf) over a topology INCLUDING recurrent_group sub-topologies
    (path = (top_layer, inner..., layer)) — the traversal behind the global
    parameter table: named parameters share storage wherever they live, like
    the reference's per-name Parameter map (config_parser.py Parameters /
    gserver's global parameter table), including inside recurrent groups."""
    for name in topology.order:
        conf = topology.layers[name]
        yield prefix + (name,), conf
        sub = conf.attrs.get("_sub_topology")
        if sub is not None:
            yield from _walk_layers(sub, prefix + (name,))


def _get_path(d, path):
    for k in path:
        d = d[k]
    return d


def _set_path(d, path, v):
    """Set d[path] with copy-on-write of every intermediate dict (the caller
    has already shallow-copied `d` itself), so grafting shared values never
    mutates the canonical params tree."""
    cur = d
    for k in path[:-1]:
        nxt = dict(cur.get(k, {}))
        cur[k] = nxt
        cur = nxt
    cur[path[-1]] = v


def _share_conflict_error(message: str, layer: str):
    """Parameter-sharing conflict in the shared diagnostic format (rule
    G006 — same id the graph linter reports for these, so config-time and
    build-time findings read identically).  DiagnosticError subclasses
    ValueError: every existing except/raises site keeps working."""
    from paddle_tpu.analysis.diagnostics import (
        Diagnostic,
        DiagnosticError,
        Severity,
    )

    return DiagnosticError(Diagnostic(
        rule="G006",
        severity=Severity.ERROR,
        layer=layer,
        message=message,
        hint="give the parameters distinct ParamAttr names, or align the "
        "declaring layers' shapes/forms",
    ))


def _mixed_forms_error(key_owners, g, path, decl) -> ValueError:
    """Mixed whole-layer/per-key declaration of one global parameter name."""
    ol, ok, owhole = key_owners[g]
    kind = "whole-layer inside a recurrent_group" if owhole else "per-key"
    return _share_conflict_error(
        f"parameter name {g!r} is declared {decl} by {'.'.join(path)!r} but "
        f"{kind} by {ol!r}.{'.'.join(ok)!r}; sharing across the two forms "
        "is not supported — use distinct names",
        ".".join(path),
    )


def _del_path(d, path):
    """Delete d[path], pruning dicts emptied by the deletion."""
    stack = []
    cur = d
    for k in path[:-1]:
        stack.append((cur, k))
        cur = cur[k]
    del cur[path[-1]]
    for parent, k in reversed(stack):
        if not parent[k]:
            del parent[k]


class CompileShapeCache:
    """Host-side mirror of the jit executable cache, keyed per bucket shape.

    jax.jit keys its cache by abstract argument shapes; the feed layer
    controls exactly one slice of that key — the batch's slot shapes
    (core.batch.batch_shape_key).  Observing every batch here makes the
    compile behaviour of a variable-length feed visible and testable:

    * hit/miss counters export through the StatSet plane (``<name>/
      compile_hit`` / ``compile_miss`` in utils.timers.global_stats — the
      same table REGISTER_TIMER stats print in), so a feed that recompiles
      per batch shows up in the stats instead of as mystery latency;
    * ``n_shapes`` asserts the shape-ladder contract: with a laddered feed
      (reader.bucketing + DataFeeder(ladder=...)), every padded extent is a
      ladder rung, so distinct shapes across an epoch are bounded by the
      combinations of slot rungs the data actually realizes — one per rung
      when slot lengths correlate, and never a shape per batch, instead of
      growing with the length distribution.  (Multiple sequence slots with
      UNcorrelated lengths multiply rung combinations; pass the batcher a
      ``key``/``slots`` tied to the dominant slot if that bites.)
    """

    def __init__(self, name: str = "train_step", stats=None):
        from paddle_tpu.utils.timers import global_stats

        self.name = name
        self._stats = stats if stats is not None else global_stats
        self.shapes: Dict[tuple, int] = {}  # shape key -> dispatch count

    def observe(self, batch: Batch) -> bool:
        """Record one dispatch; True when this shape is new (a compile)."""
        key = batch_shape_key(batch)
        miss = key not in self.shapes
        self.shapes[key] = self.shapes.get(key, 0) + 1
        self._stats.incr(
            f"{self.name}/compile_{'miss' if miss else 'hit'}"
        )
        return miss

    @property
    def n_shapes(self) -> int:
        return len(self.shapes)

    @property
    def misses(self) -> int:
        # by construction every distinct shape missed exactly once
        return self.n_shapes

    @property
    def hits(self) -> int:
        return sum(self.shapes.values()) - len(self.shapes)

    def summary(self) -> Dict[str, int]:
        return {
            "shapes": self.n_shapes,
            "hits": self.hits,
            "misses": self.misses,
        }


class CompiledNetwork:
    """init/apply view over a Topology."""

    def __init__(self, topology: Topology, dtype=jnp.float32, compute_dtype=None):
        self.topology = topology
        self.dtype = dtype
        # Mesh handed to mesh-aware layers via ApplyContext; the trainer
        # sets this so ring attention traces against ITS mesh instead of a
        # process-global (two trainers with different meshes stay isolated).
        self.mesh = None
        if compute_dtype is None:
            compute_dtype = _default_compute_dtype or dtype
        self.compute_dtype = jnp.dtype(compute_dtype)
        # Resolve implementations eagerly so unknown types fail at build.
        self._impls = {
            name: get_layer_impl(conf.type)
            for name, conf in topology.layers.items()
        }
        # Cross-layer parameter sharing by ParamAttr name (the reference's
        # global parameter table: two layers declaring the same parameter
        # name share storage — e.g. crf + crf_decoding sharing "crfw",
        # tied embeddings).  First declarer in topology order owns the
        # params; later declarers read the owner's slot.  Two granularities:
        #   attr("param_name")  — the whole layer param dict (legacy layers
        #                         with one logical parameter);
        #   attr("param_names") — {param_key: global_name} per-key sharing
        #                         (fc per-input weights, mixed projections,
        #                         named bias attrs) — including intra-layer
        #                         duplicates like fc param_attr=[p, p].
        # _shared_keys: sharer top-level layer -> {relpath: (owner top-level
        # layer, owner relpath)}.  relpath is a tuple of dict keys into the
        # layer's param subtree — one element for a flat layer key, longer
        # for parameters inside a recurrent_group's nested params (and a
        # whole inner-layer dict for legacy one-parameter layers inside a
        # group).  Sharing WITHIN one group's subtree is handled by that
        # group's own sub-CompiledNetwork running this same scan.
        self._param_owner: Dict[str, str] = {}
        self._shared_keys: Dict[str, Dict[tuple, tuple]] = {}
        # global parameter table: reference parameters are NAMED objects
        # (Parameter.h:46; v2 parameters.get("embedding.w0")) — map each
        # declared global name to its owning storage path (top layer,
        # relpath-into-its-param-subtree)
        self._named_params: Dict[str, tuple] = {}
        owners: Dict[str, str] = {}
        key_owners: Dict[str, tuple] = {}
        inner_seen: set = set()  # (global name, top layer) with an inner decl
        for path, conf in _walk_layers(topology):
            name, rel = path[0], tuple(path[1:])
            pmap = conf.attr("param_names") or {}
            pname = conf.attr("param_name")
            if pname and not pmap:
                if not rel:
                    if pname in key_owners:
                        raise _mixed_forms_error(
                            key_owners, pname, path, "whole-layer"
                        )
                    if pname in owners:
                        self._param_owner[name] = owners[pname]
                    else:
                        owners[pname] = name
                        self._named_params[pname] = (name, ())
                else:
                    # legacy one-parameter layer inside a group: share its
                    # whole inner dict at `rel`
                    if pname in owners:
                        raise _share_conflict_error(
                            f"parameter name {pname!r} is declared whole-layer "
                            f"both at top level ({owners[pname]!r}) and inside "
                            f"a recurrent_group ({'.'.join(path)!r}); use "
                            "distinct names",
                            ".".join(path),
                        )
                    if pname in key_owners and not key_owners[pname][2]:
                        raise _mixed_forms_error(
                            key_owners, pname, path,
                            "whole-layer inside a recurrent_group",
                        )
                    owner = self._inner_key_owner(
                        key_owners, inner_seen, pname, name, rel,
                        inner=True, whole=True,
                    )
                    if owner is not None:
                        self._shared_keys.setdefault(name, {})[rel] = owner
                    else:
                        self._named_params.setdefault(pname, (name, rel))
            for key, gname in pmap.items():
                if not gname:
                    continue
                if gname in owners:
                    raise _share_conflict_error(
                        f"parameter name {gname!r} is declared per-key by "
                        f"{'.'.join(path)!r}.{key!r} but whole-layer by "
                        f"{owners[gname]!r}; sharing across the two layer "
                        "kinds is not supported — use distinct names",
                        ".".join(path),
                    )
                if gname in key_owners and key_owners[gname][2]:
                    raise _mixed_forms_error(key_owners, gname, path, "per-key")
                kp = rel + (key,)
                owner = self._inner_key_owner(
                    key_owners, inner_seen, gname, name, kp,
                    inner=bool(rel), whole=False,
                )
                if owner is not None:
                    self._shared_keys.setdefault(name, {})[kp] = owner
                else:
                    self._named_params.setdefault(gname, (name, kp))

    @staticmethod
    def _inner_key_owner(key_owners, inner_seen, gname, top, relpath, inner,
                         whole):
        """First declarer of `gname` wins ownership; a later declarer gets
        the owner's address back — except a second declaration INSIDE the
        same top-level layer's subtree, where the group's own sub-network
        scan already chains it to the subtree's first declarer (returning
        None avoids double handling — and that first declarer is itself
        grafted from the global owner, so the chain stays correct even when
        the global owner lives outside the subtree)."""
        if inner:
            if (gname, top) in inner_seen:
                return None  # sub-CompiledNetwork chains this to the first
            inner_seen.add((gname, top))
        if gname not in key_owners:
            key_owners[gname] = (top, relpath, whole)
            return None
        otop, orel, _ = key_owners[gname]
        return (otop, orel)

    # ------------------------------------------------------------------
    def init_params(self, rng: jax.Array) -> Params:
        params: Params = {}
        for name in self.topology.order:
            conf = self.topology.layers[name]
            impl = self._impls[name]
            in_confs = [self.topology.layers[i] for i in conf.inputs]
            layer_rng = jax.random.fold_in(rng, stable_hash(name))
            p = impl.init(conf, in_confs, layer_rng)
            owner = self._param_owner.get(name)
            if owner is not None:
                # sharer: storage lives at the owner — validate the shapes
                # agree NOW so a name collision between differently-sized
                # layers fails at build, not deep inside a matmul
                want = jax.tree_util.tree_map(jnp.shape, p)
                have = jax.tree_util.tree_map(jnp.shape, params.get(owner, {}))
                if want != have:
                    raise _share_conflict_error(
                        f"shares parameter {conf.attr('param_name')!r} with "
                        f"{owner!r} but expects shapes {want} != owner's "
                        f"{have}",
                        name,
                    )
                continue
            for relpath, (ol, orel) in self._shared_keys.get(name, {}).items():
                owner_val = (
                    _get_path(p, orel) if ol == name
                    else _get_path(params[ol], orel)
                )
                mine = _get_path(p, relpath)
                want = jax.tree_util.tree_map(jnp.shape, mine)
                have = jax.tree_util.tree_map(jnp.shape, owner_val)
                if want != have:
                    raise _share_conflict_error(
                        f"parameter {'.'.join(relpath)!r} shares storage "
                        f"with {ol!r}.{'.'.join(orel)!r} but expects shapes "
                        f"{want} != owner's {have}",
                        name,
                    )
                _del_path(p, relpath)
            if p:
                params[name] = p
        return params

    # ------------------------------------------------------------------
    @property
    def has_dynamic_widths(self) -> bool:
        """Any fc / matrix projection stacked on a dynamic-width input
        (whole-minibatch trans, TransLayer.cpp) — their true weight height
        is the runtime batch size."""
        for conf in self.topology.layers.values():
            if conf.attr("dynamic_width_in"):
                return True
            for s in conf.attrs.get("projections", ()):
                if s.get("dynamic_width"):
                    return True
        return False

    def resolve_dynamic_widths(
        self, params: Params, batch: Batch, seed: int = 0
    ) -> Tuple[Params, bool]:
        """Re-initialize weights whose height depends on the runtime batch
        size, now that a batch exists.

        A whole-minibatch ``trans`` (reference TransLayer.cpp) outputs
        [D, B]: a consuming fc/matrix-projection weight must be [B, size],
        but B is unknowable at init, so init builds the declared static
        size (matching the reference's parameter dims — which can then only
        RUN at batch == size, protostr test_fc dims 100x100).  The trainer
        calls this with its first batch; weights whose height mismatches
        the actual B are re-drawn (deterministically from ``seed``) at the
        right shape and the optimizer state must be rebuilt by the caller
        when ``changed`` comes back True.  Note the inherent semantics of
        batch-wide transpose: weights trained at one batch size cannot be
        reused at another (true of the op, not this implementation) — feed
        with drop_last=True so a ragged final batch doesn't change B.
        Weights restored at a shape matching neither the static init nor
        this batch raise (they trained at another B); the one blind spot
        is a checkpoint trained at exactly B == the declared static size,
        which is indistinguishable from a fresh init by shape."""
        import dataclasses

        b = 0
        for t in batch.values():
            data = t.data if hasattr(t, "data") else t
            b = int(data.shape[0])
            break
        if not b:
            return params, False
        rng = jax.random.PRNGKey(seed)
        out = dict(params)
        changed = False
        for name in self.topology.order:
            conf = self.topology.layers[name]
            dyn_fc = conf.attr("dynamic_width_in") or ()
            dyn_proj = [
                j for j, s in enumerate(conf.attrs.get("projections", ()))
                if s.get("dynamic_width")
            ]
            if not dyn_fc and not dyn_proj:
                continue
            in_confs = [self.topology.layers[i] for i in conf.inputs]
            patched = list(in_confs)
            targets = set(dyn_fc) | {
                conf.attrs["projections"][j]["in"] for j in dyn_proj
            }
            for i in targets:
                # the dynamic input's runtime width is the batch size B
                # (trans swaps [B, D] -> [D, B]); width-preserving unaries
                # in between keep it
                patched[i] = dataclasses.replace(in_confs[i], size=b)
            impl = self._impls[name]
            layer_rng = jax.random.fold_in(rng, stable_hash(name))
            fresh = impl.init(conf, patched, layer_rng)
            # what a FRESH (untrained) init looks like at the declared
            # static sizes — only weights still in that state may be
            # re-drawn; anything else was trained/restored at some other
            # batch size and re-drawing it would silently destroy it
            static_init = impl.init(conf, in_confs, layer_rng)
            cur = dict(out.get(name, {}))
            layer_changed = False
            for k, v in fresh.items():
                if k not in cur or jnp.shape(cur[k]) == jnp.shape(v):
                    continue
                if jnp.shape(cur[k]) != jnp.shape(static_init.get(k)):
                    raise ValueError(
                        f"layer {name!r} parameter {k!r} has shape "
                        f"{jnp.shape(cur[k])} — neither the declared static "
                        f"shape {jnp.shape(static_init.get(k))} nor this "
                        f"batch's resolved shape {jnp.shape(v)}.  It was "
                        "trained/restored at a different batch size; "
                        "batch-wide-trans weights are only usable at the "
                        "batch size they trained at."
                    )
                cur[k] = v
                layer_changed = True
            if layer_changed:
                out[name] = cur
                changed = True
        return out, changed

    def init_state(self) -> NetState:
        state: NetState = {}
        for name in self.topology.order:
            conf = self.topology.layers[name]
            impl = self._impls[name]
            if impl.init_state is not None:
                in_confs = [self.topology.layers[i] for i in conf.inputs]
                s = impl.init_state(conf, in_confs)
                if s:
                    state[name] = s
        return state

    def init(self, rng: jax.Array) -> Tuple[Params, NetState]:
        return self.init_params(rng), self.init_state()

    # ------------------------------------------------------------------
    def make_context(self, *, train: bool, rng=None, state=None) -> ApplyContext:
        """ApplyContext exactly as apply() would build it (mesh fallback
        included) — shared with utils.debug so diagnostics trace the same
        computation as training."""
        from paddle_tpu.parallel.mesh import get_default_mesh

        return ApplyContext(
            train=train,
            rng=rng,
            state=state or {},
            dtype=self.compute_dtype,
            mesh=self.mesh if self.mesh is not None else get_default_mesh(),
        )

    # ------------------------------------------------------------------
    def layer_params(self, params: Params, name: str):
        """This layer's effective param dict: owner lookup for whole-layer
        sharing plus per-key grafts of shared storage (copy-on-write — the
        canonical params tree is never mutated)."""
        p = params.get(self._param_owner.get(name, name), {})
        shared = self._shared_keys.get(name)
        if shared:
            p = dict(p)
            for relpath, (ol, orel) in shared.items():
                src = (
                    _get_path(p, orel) if ol == name
                    else _get_path(params[ol], orel)
                )
                _set_path(p, relpath, src)
        return p

    def named_parameters(self) -> Dict[str, str]:
        """Global parameter table: {declared parameter name: dotted storage
        path into the params tree} (reference Parameter.h:46 named buffers /
        v2 parameters surface — the reference addresses every parameter by
        its config-declared name)."""
        return {
            gname: ".".join((top,) + tuple(rel))
            for gname, (top, rel) in self._named_params.items()
        }

    def materialize_shared(self, params: Params) -> Params:
        """Params with every shared key grafted back in place, per top-level
        layer.  For feeding a sub-network or pruned subgraph that was
        compiled WITHOUT this network's sharing maps (e.g. generation-time
        decoder stepping reads params['decoder'] directly)."""
        out: Params = {}
        for name in self.topology.order:
            p = self.layer_params(params, name)
            if p:
                out[name] = p
        return out

    def resolve_layer_call(self, name: str, params: Params, ins):
        """(layer params, inputs) as the apply loop would hand them to the
        impl: shared-parameter owner lookup + mixed-precision casts.  Used
        by apply() and by utils.debug.profile_layers so the profiler times
        exactly what training runs."""
        impl = self._impls[name]
        p = self.layer_params(params, name)
        if self.compute_dtype != jnp.dtype(jnp.float32):
            if impl.full_precision:
                ins = [_cast_floats(x, jnp.float32) for x in ins]
            else:
                p = _cast_floats(p, self.compute_dtype)
                ins = [_cast_floats(x, self.compute_dtype) for x in ins]
        return p, ins

    # ------------------------------------------------------------------
    def apply(
        self,
        params: Params,
        batch: Batch,
        *,
        state: Optional[NetState] = None,
        train: bool = True,
        rng: Optional[jax.Array] = None,
        only: Optional[set] = None,
        preset: Optional[Dict[str, SeqTensor]] = None,
    ) -> Tuple[Dict[str, SeqTensor], NetState]:
        """Run the whole graph; returns every layer's output by name plus the
        functionally-updated state.

        `only` restricts execution to the named layers (everything else is
        skipped — its output must then come from `preset` if a survivor
        needs it); `preset` seeds layer outputs directly.  Both exist for
        recurrent_group's epilogue hoisting: the scan body executes the
        loop partition, the stacked epilogue partition runs once outside
        with the loop's outputs preset."""
        mixed = self.compute_dtype != jnp.dtype(jnp.float32)
        # Mixed precision: master params and the raw batch stay f32; each
        # non-full_precision layer casts its own params/inputs to the compute
        # dtype below.  Casting the whole batch up front would quantize float
        # regression targets / soft labels before the full_precision cost
        # layers ever see them.
        ctx = self.make_context(train=train, rng=rng, state=state)
        if preset:
            ctx.outputs.update(preset)
        for name in self.topology.order:
            if preset and name in preset:
                continue
            if only is not None and name not in only:
                continue
            conf = self.topology.layers[name]
            impl = self._impls[name]
            if conf.type in ("data", "step_input", "memory"):
                # data: user slots; step_input/memory: placeholders fed by an
                # enclosing recurrent_group's scan body.
                if name not in batch:
                    raise KeyError(f"batch is missing data slot {name!r}")
                ctx.outputs[name] = _feed_transform(conf, batch[name])
                continue
            ins = [ctx.outputs[i] for i in conf.inputs]
            pre_keys = set(ctx.outputs) if mixed else ()
            p, ins = self.resolve_layer_call(name, params, ins)
            # named_scope labels this layer's ops in profiler traces; the
            # except-note is the CustomStackTrace equivalent (reference
            # utils/CustomStackTrace.h:51 pushes layer names so a fatal
            # error reports which layer it happened in).
            try:
                with jax.named_scope(f"{conf.type}:{name}"):
                    out = impl.apply(conf, p, ins, ctx)
            except Exception as e:
                # layer-provenance note in the shared diagnostic format
                # (analysis.diagnostics) — trace-time shape errors read like
                # the graph linter's config-time findings, naming the layer
                from paddle_tpu.analysis.diagnostics import (
                    Diagnostic,
                    Severity,
                )

                shapes = [getattr(t.data, "shape", None) for t in ins]
                note = Diagnostic(
                    rule="T100",
                    severity=Severity.ERROR,
                    layer=name,
                    message=(
                        f"failed while applying this layer (type={conf.type}, "
                        f"size={conf.size}, inputs={list(conf.inputs)} with "
                        f"shapes {shapes})"
                    ),
                    hint="run analysis.graph_lint.lint_topology on this "
                    "topology — most shape/arity mistakes are caught "
                    "before tracing",
                ).format()
                if hasattr(e, "add_note"):  # py3.11+
                    e.add_note(note)
                else:
                    # py3.10: emulate PEP 678 — populate __notes__ for
                    # introspection AND splice into args for display
                    try:
                        notes = list(getattr(e, "__notes__", ()) or ())
                        notes.append(note)
                        e.__notes__ = notes
                    except (AttributeError, TypeError):  # pragma: no cover
                        pass
                    if e.args and isinstance(e.args[0], str):
                        e.args = (f"{e.args[0]}\n{note}",) + e.args[1:]
                raise
            if mixed and not impl.full_precision:
                # Enforce the compute dtype at every layer boundary —
                # f32 constants/masks inside an impl would otherwise promote
                # and leak float32 downstream (breaking e.g. scan carries).
                out = _cast_floats(out, self.compute_dtype)
                for k in set(ctx.outputs) - pre_keys:  # side outputs (@cell, …)
                    ctx.outputs[k] = _cast_floats(
                        ctx.outputs[k], self.compute_dtype
                    )
            if impl.auto_activation and conf.act not in ("identity", "linear", ""):
                if conf.act == "softmax":
                    # Stash pre-activation logits so downstream cross_entropy
                    # fuses into log-softmax CE (numerically stable); XLA
                    # dead-code-eliminates this when unused.
                    ctx.outputs[name + "@logits"] = out
                mask = out.mask() if (out.is_seq and conf.act == "sequence_softmax") else None
                out = out.with_data(apply_activation(conf.act, out.data, mask))
            if impl.auto_dropout and conf.drop_rate > 0.0 and train:
                drop_rng = ctx.layer_rng(name + "/dropout")
                if drop_rng is not None:
                    keep = 1.0 - conf.drop_rate
                    m = jax.random.bernoulli(drop_rng, keep, out.data.shape)
                    out = out.with_data(
                        jnp.where(m, out.data / keep, jnp.zeros_like(out.data))
                    )
            eclip = conf.attr("error_clip", 0.0)
            if eclip and train:
                out = out.with_data(_error_clip(out.data, eclip))
            ctx.outputs[name] = out
        new_state = dict(ctx.state)
        new_state.update(ctx.new_state)
        return ctx.outputs, new_state

    # ------------------------------------------------------------------
    def forward(
        self,
        params: Params,
        batch: Batch,
        *,
        state: Optional[NetState] = None,
        train: bool = True,
        rng: Optional[jax.Array] = None,
    ) -> Tuple[SeqTensor, Dict[str, SeqTensor], NetState]:
        """First declared output, the full output dict, and updated state."""
        outs, new_state = self.apply(params, batch, state=state, train=train, rng=rng)
        return outs[self.topology.output_names[0]], outs, new_state

    def cost(
        self,
        params: Params,
        batch: Batch,
        *,
        state: Optional[NetState] = None,
        rng: Optional[jax.Array] = None,
        train: bool = True,
    ):
        """(scalar mean cost, (outputs, new_state)) — the differentiable
        quantity (replaces GradientMachine::backward's sum-of-cost seeding,
        reference: paddle/gserver/gradientmachines/GradientMachine.h:72)."""
        out, outs, new_state = self.forward(
            params, batch, state=state, train=train, rng=rng
        )
        return jnp.mean(out.data), (outs, new_state)


def count_params(params: Params) -> int:
    return sum(int(jnp.size(x)) for x in jax.tree_util.tree_leaves(params))
