"""Typed topology IR — the TPU-native replacement for the reference's
``ModelConfig`` protobuf graph (reference: proto/ModelConfig.proto:608,
LayerConfig:326) and the config_parser that builds it (reference:
python/paddle/trainer/config_parser.py:3669).

Design: instead of a proto compiled by a global-state parser and then
interpreted layer-by-layer at runtime (reference:
paddle/gserver/gradientmachines/NeuralNetwork.cpp:235), the DSL builds an
immutable dataclass graph.  ``paddle_tpu.core.compiler`` traces it **once**
into a pure JAX function, so the whole model becomes a single XLA computation
— the graph exists only at trace time.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Sequence, Tuple

from paddle_tpu.core.data_types import InputType


@dataclasses.dataclass(frozen=True)
class LayerConf:
    """One node of the model graph (reference LayerConfig,
    proto/ModelConfig.proto:326).  ``attrs`` carries per-type configuration
    (kernel sizes, dropout rate, ...) keeping this class closed."""

    name: str
    type: str
    size: int  # output feature dimension (last-axis width)
    inputs: Tuple[str, ...] = ()  # parent layer names, ordered
    act: str = "identity"
    bias: bool = True
    # Static per-type attributes; must be hashable-friendly plain data.
    attrs: Dict[str, Any] = dataclasses.field(default_factory=dict)
    # Input slot type for data layers.
    input_type: Optional[InputType] = None
    # Dropout applied to the layer output during training (reference
    # attrs.py ExtraAttr drop_rate).
    drop_rate: float = 0.0
    # Mesh-axis hint for model-parallel sharding of this layer's parameters
    # (replaces the reference's per-layer `device` attribute,
    # ParallelNeuralNetwork.h:34).
    shard_axis: Optional[str] = None

    def attr(self, key: str, default: Any = None) -> Any:
        return self.attrs.get(key, default)


_layer_sink = None  # optional observer of every LayerOutput creation


def set_layer_sink(fn):
    """Install/remove (None) a callback invoked with each new LayerOutput —
    used by v1_compat.parse_config to resolve name-based Outputs()
    declarations without global config state.  Returns the PREVIOUS sink so
    nested installations restore rather than clear (parse_config can
    re-enter via configs that parse other configs)."""
    global _layer_sink
    prev = _layer_sink
    _layer_sink = fn
    return prev


class LayerOutput:
    """Functional DSL handle returned by every layer function — mirrors
    trainer_config_helpers.layers.LayerOutput (reference:
    python/paddle/trainer_config_helpers/layers.py:320-400) but carries the
    actual conf + parents so the graph is collected by traversal instead of
    mutable global state."""

    def __init__(self, conf: LayerConf, parents: Sequence["LayerOutput"] = ()):
        self.conf = conf
        self.parents: Tuple[LayerOutput, ...] = tuple(parents)
        if _layer_sink is not None:
            _layer_sink(self)

    @property
    def name(self) -> str:
        return self.conf.name

    @property
    def size(self) -> int:
        return self.conf.size

    def __repr__(self) -> str:  # pragma: no cover
        return f"LayerOutput({self.conf.type}:{self.conf.name}, size={self.conf.size})"


class Topology:
    """Whole-model graph in topological order.

    Equivalent of the v2 Topology (reference: python/paddle/v2/topology.py:25)
    that serializes to ModelConfig; here it *is* the model description handed
    to the compiler.
    """

    def __init__(self, outputs: Sequence[LayerOutput]):
        if isinstance(outputs, LayerOutput):
            outputs = [outputs]
        self.outputs: Tuple[LayerOutput, ...] = tuple(outputs)
        self.layers: Dict[str, LayerConf] = {}
        order: List[str] = []
        # name -> conf at FIRST sighting (before recursing into parents):
        # duplicate detection must compare against this, not self.layers —
        # a duplicate on an ancestor path is met while its descendant's
        # conf is seen but not yet stored in self.layers, and comparing
        # against the incomplete dict would silently drop the ancestor
        seen: Dict[str, LayerConf] = {}

        def visit(lo: LayerOutput) -> None:
            if lo.conf.name in seen:
                existing = seen.get(lo.conf.name)
                if existing is not None and existing != lo.conf:
                    from paddle_tpu.analysis.diagnostics import (
                        Diagnostic,
                        DiagnosticError,
                        Severity,
                    )

                    raise DiagnosticError(Diagnostic(
                        rule="G016",
                        severity=Severity.ERROR,
                        layer=lo.conf.name,
                        message=(
                            f"two different layers share the name "
                            f"{lo.conf.name!r} (types "
                            f"{existing.type!r} vs {lo.conf.type!r})"
                        ),
                        hint="give one of them an explicit distinct name= "
                        "(auto_name counters reset per config; see "
                        "reset_auto_names)",
                    ))
                return
            seen[lo.conf.name] = lo.conf
            for p in lo.parents:
                visit(p)
            self.layers[lo.conf.name] = lo.conf
            order.append(lo.conf.name)

        for out in self.outputs:
            visit(out)
        self.order: Tuple[str, ...] = tuple(order)
        # Explicit feeding order (set when a config declared Inputs(...));
        # None → DFS traversal order below.
        self.input_order: Optional[Tuple[str, ...]] = None

    @property
    def output_names(self) -> Tuple[str, ...]:
        return tuple(o.conf.name for o in self.outputs)

    def data_layers(self) -> Dict[str, LayerConf]:
        """Data layers in FEEDING order — explicit ``Inputs(...)`` order when
        the config declared one, else DFS-traversal order from the outputs
        (parents first, left to right).  The reference computes exactly this
        in trainer_config_helpers/networks.py:1412 ``outputs()``:
        ``__dfs_travel__`` collects data layers in LRV order and passes them
        to ``Inputs()``, and "the data streams from DataProvider must have
        the same order" (config_parser.py:205-222).  Declaration order is NOT
        the contract — googlenet.py declares label before input yet the
        provider yields (image, label)."""
        if self.input_order is not None:
            return {n: self.layers[n] for n in self.input_order}
        return {
            n: self.layers[n] for n in self.order if self.layers[n].type == "data"
        }

    def data_types(self) -> List[Tuple[str, InputType]]:
        """[(name, InputType)] — same contract as v2 Topology.data_type()
        (reference: python/paddle/v2/topology.py:84-100).  Raises for v1
        slots whose provider types could not be resolved: feeding those with
        the parse-time dense placeholder would be silently wrong for
        index/sequence slots, so it is a hard error here at the feed
        boundary (the topology itself stays buildable/inspectable)."""
        out = []
        for name, conf in self.data_layers().items():
            why = conf.attrs.get("_v1_unresolved")
            if why:
                from paddle_tpu.analysis.diagnostics import (
                    Diagnostic,
                    DiagnosticError,
                    Severity,
                )

                raise DiagnosticError(Diagnostic(
                    rule="G011",
                    severity=Severity.ERROR,
                    layer=name,
                    message=f"cannot feed data layer {name!r}: {why}",
                    hint="fix the provider (declare input_types, or make "
                    "its init_hook runnable — e.g. fetch the dataset it "
                    "reads), or feed through an explicit DataFeeder with "
                    "the true types",
                ))
            assert conf.input_type is not None, f"data layer {name} missing input_type"
            out.append((name, conf.input_type))
        return out

    def get(self, name: str) -> LayerConf:
        return self.layers[name]

    def serialize(self, indent: str = "") -> str:
        """Deterministic text form used for golden-snapshot tests (the
        protostr-equality tests of the reference,
        python/paddle/trainer_config_helpers/tests/configs/).  Attr keys
        starting with '_' hold non-scalar build artifacts (e.g. a group's
        sub-topology) and are serialized specially."""
        lines = []
        for name in self.order:
            c = self.layers[name]
            # None-valued attrs are absent options (param_std, param_name,
            # prune_sparsity, ...) — skipping them keeps golden snapshots
            # stable when new optional attributes are introduced.
            attrs = ", ".join(
                f"{k}={c.attrs[k]!r}"
                for k in sorted(c.attrs)
                if not k.startswith("_") and c.attrs[k] is not None
            )
            lines.append(
                indent
                + f"{c.type} {name} size={c.size} act={c.act} bias={c.bias}"
                f" inputs={list(c.inputs)}"
                + (f" drop={c.drop_rate}" if c.drop_rate else "")
                + (f" [{attrs}]" if attrs else "")
            )
            sub = c.attrs.get("_sub_topology")
            if sub is not None:
                lines.append(indent + "  {")
                lines.append(sub.serialize(indent + "    "))
                lines.append(indent + "  }")
        lines.append(indent + f"outputs={list(self.output_names)}")
        return "\n".join(lines)


_AUTO_NAMES: Dict[str, int] = {}


def auto_name(prefix: str) -> str:
    """Deterministic unique layer names, mirroring the reference DSL's
    `__fc_layer_0__` style counters (trainer_config_helpers/default_decorators
    wrap_name_default)."""
    idx = _AUTO_NAMES.get(prefix, 0)
    _AUTO_NAMES[prefix] = idx + 1
    return f"__{prefix}_{idx}__"


def reset_auto_names() -> None:
    """Reset the name counters (call between independently-built models in
    tests so golden snapshots are stable)."""
    _AUTO_NAMES.clear()
