from paddle_tpu.trainer.sgd import SGD  # noqa: F401
from paddle_tpu.trainer.step import (  # noqa: F401
    make_eval_step,
    make_forward_fn,
    make_grad_step,
    make_train_step,
)
