"""Elastic multi-process training — the pass-synchronous worker driver over
the master's cluster plane (worker registry, shard leases, fences, results).

This completes the reference's fault-tolerance story end-to-end (the Go
master's lease-based dispatch, go/master/service.go, in the TF-paper model
of arXiv:1605.08695 §4.4): N trainer processes lease data-shard tasks from
the master, each computes a DETERMINISTIC per-task gradient contribution
(trainer/step.py make_grad_step, or any model honoring the protocol below),
and submits it with the epoch-guarded ``task_finished`` ack.  At the pass
boundary every live worker arrives at a fence; on release each worker
fetches the full ``{task_id: contribution}`` map and reduces it in sorted
task-id order, so the applied update — and therefore the whole parameter
trajectory — is bit-identical no matter which worker computed which task.

That invariant is the elasticity mechanism, not a nicety:

  * kill -9 one of N mid-pass → its registry lease expires, the master
    requeues its held shard leases to survivors (``failure_max`` epoch
    discipline), the pass completes, and final params match an
    uninterrupted run bit-for-bit;
  * a hung worker (GC pause, NFS stall) is pruned the same way; when it
    wakes, its stale acks are rejected by epoch and it rejoins as a late
    worker;
  * a joining worker just registers, restores the latest committed
    checkpoint manifest, and starts leasing.

Checkpoints are **sharded + asynchronous**: after applying a pass, worker
rank r of the fence membership writes shard r of the full state off the hot
path (checkpoint.CheckpointManager.save_shard), and the step commits at the
NEXT fence — once every writer has joined its background write — by
publishing ``MANIFEST.json`` atomically.  A worker that died mid-write
strands an uncommitted shard set that ``restore_latest`` walks straight
past.

Model protocol (duck-typed; see :class:`NumpyLinearModel` and
:class:`TrainerTaskModel`):

    task_grad(records, pass_id, task_id) -> (mean_grad_tree, cost_sum, rows)
        deterministic per (records, pass_id, task_id) — NOT per worker
    apply(mean_grad_tree) -> None        deterministic state transition
    state() -> pytree                    full state for checkpointing
    load(tree, extra) -> None            restore from a checkpoint
"""

from __future__ import annotations

import argparse
import logging
import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from paddle_tpu import master_wire as _wire
from paddle_tpu import obs as _obs
from paddle_tpu.io import recordio
from paddle_tpu.ops import quantize as _bsq
from paddle_tpu.robustness import chaos as _chaos
from paddle_tpu.utils.timers import global_stats

__all__ = [
    "ElasticWorker",
    "NumpyLinearModel",
    "TrainerTaskModel",
    "reduce_results",
    "main",
]

_log = logging.getLogger("paddle_tpu.trainer.elastic")


class _PassSuperseded(Exception):
    """The pass we were reducing closed under us (a force-rotation while
    we were briefly pruned, or a rotation we slept through): its retained
    map can no longer be reduced here — the worker must catch up to the
    master's pass instead.  ``target`` is the master's current pass."""

    def __init__(self, target: int):
        super().__init__(f"pass superseded; master is at pass {target}")
        self.target = target


# ---------------------------------------------------------------------------
# Deterministic reduction over nested-dict gradient trees (numpy, no jax)
# ---------------------------------------------------------------------------

def _tree_axpy(acc, tree, w: float):
    """acc += tree * w, recursively over nested dicts; None acc allocates.
    The scale stays in each leaf's own dtype so every worker runs the exact
    same float ops in the exact same order."""
    if isinstance(tree, dict):
        return {
            k: _tree_axpy(None if acc is None else acc[k], v, w)
            for k, v in tree.items()
        }
    arr = np.asarray(tree)
    if np.issubdtype(arr.dtype, np.floating):
        term = arr * arr.dtype.type(w)
    else:
        term = arr * w
    return term if acc is None else acc + term


def _tree_scale(tree, s: float):
    if isinstance(tree, dict):
        return {k: _tree_scale(v, s) for k, v in tree.items()}
    arr = np.asarray(tree)
    if np.issubdtype(arr.dtype, np.floating):
        return arr * arr.dtype.type(s)
    return arr * s


def reduce_results(results: Dict[int, Any]) -> Tuple[Any, float, int]:
    """(mean_grads, mean_cost, total_rows) from a pass's ``{task_id:
    {"grads", "cost", "rows"}}`` map, reduced in sorted task-id order —
    the canonical order every worker uses, so the reduction is
    bit-identical fleet-wide.

    Contributions may arrive block-scale quantized (the producing worker
    ran with ``elastic_quantized_grads``): dequantize-THEN-reduce keeps
    the determinism contract, because every reducer dequantizes the SAME
    producer bytes before the same sorted-order float ops — which worker
    quantized which task still cannot change the trajectory."""
    order = sorted(results)
    if not order:
        raise ValueError("empty result map: nothing to reduce")
    total_rows = sum(int(results[t]["rows"]) for t in order)
    acc = None
    for t in order:
        grads = _bsq.dequantize_tree(results[t]["grads"])
        acc = _tree_axpy(acc, grads, float(results[t]["rows"]))
    mean = _tree_scale(acc, 1.0 / total_rows)
    mean_cost = sum(float(results[t]["cost"]) for t in order) / total_rows
    return mean, mean_cost, total_rows


def _read_task_records(task_json: Dict[str, Any]) -> List[bytes]:
    recs: List[bytes] = []
    for c in task_json["chunks"]:
        with recordio.Reader(c["path"], offset=c["offset"]) as r:
            for _ in range(c["n_records"]):
                rec = r.next()
                if rec is None:
                    break
                recs.append(rec)
    return recs


# ---------------------------------------------------------------------------
# Worker driver
# ---------------------------------------------------------------------------

class ElasticWorker:
    """One trainer process of an elastic fleet.

    ``client`` is a master surface (master.Client or master_ha.HAClient)
    exposing the cluster plane; ``heartbeat_client`` (optional but
    recommended — the CLI always wires one) renews the registry lease from
    a side thread so a long jitted compile can't get this worker pruned."""

    def __init__(
        self,
        client,
        worker_id: str,
        model,
        manager=None,
        resume: bool = False,
        heartbeat_client=None,
        heartbeat_interval: Optional[float] = None,
        poll_s: float = 0.02,
        min_workers: int = 1,
        rpc_retry_window_s: float = 60.0,
        quantized_grads: Optional[bool] = None,
        clock=time.time,
        sleep=time.sleep,
    ):
        self.client = client
        self.worker_id = worker_id
        self.model = model
        self.manager = manager
        self.resume = resume
        self.poll_s = poll_s
        # bounded ride-through for a master bounce: transport/timeout
        # failures on the cluster-plane RPCs retry with backoff inside this
        # window (every master method is idempotent-or-epoch-guarded, so
        # the at-least-once re-send is absorbed server-side); past it the
        # worker exits nonzero for its supervisor
        self.rpc_retry_window_s = float(rpc_retry_window_s)
        # gang-start hint: hold the first lease until this many workers
        # have registered, so a fast-booting worker doesn't race through
        # whole (small) passes alone while its peers are still starting —
        # purely a START gate; membership stays fully elastic afterwards
        self.min_workers = max(int(min_workers), 1)
        self._clock = clock
        self._sleep = sleep
        self._hb = heartbeat_client
        self._hb_interval = heartbeat_interval
        self._hb_thread: Optional[threading.Thread] = None
        self._hb_pause = threading.Event()
        self._stop = threading.Event()
        # a pass whose shards this worker wrote but whose manifest is not
        # yet published: (step, num_shards, extra)
        self._pending_commit: Optional[Tuple[int, int, Dict[str, Any]]] = None
        # block-scale quantize this worker's gradient contributions before
        # they ride the wire (reduce_results dequantizes EVERY contribution,
        # so a mixed fleet mid-flag-flip still reduces deterministically);
        # default from the elastic_quantized_grads flag, whose
        # PADDLE_TPU_ELASTIC_QUANTIZED_GRADS env spelling reaches launcher-
        # spawned worker processes
        if quantized_grads is None:
            try:
                from paddle_tpu.utils.flags import get_flag

                quantized_grads = bool(get_flag("elastic_quantized_grads"))
            except Exception:  # noqa: BLE001 — flag plane not loaded
                quantized_grads = False
        self.quantized_grads = bool(quantized_grads)
        # observability
        self.pass_costs: List[float] = []
        self.tasks_done = 0
        self.rejected_acks = 0
        self.busy_s = 0.0
        self.grad_payload_bytes = 0
        self.wire_bytes_per_pass: List[int] = []
        self.t_work0: Optional[float] = None
        self.t_work1: Optional[float] = None

    # -- master RPC with bounded ride-through -----------------------------
    def _rpc(self, method: str, *args):
        """One cluster-plane call, retried through a master bounce.

        The client layer already absorbs brief blips (master.Client's
        reconnect-retry, master_ha.HAClient's re-discover loop); what
        surfaces here — MasterTransportError / MasterTimeoutError /
        HAClient's discovery TimeoutError — means the master stayed gone
        for the client's whole window.  A failover can legitimately take
        longer (lease timeout + campaign + replay), so retry with backoff
        until ``rpc_retry_window_s`` elapses, then give up: a supervisor
        restart + startup recovery is the heal path past that.

        The window is checked BETWEEN attempts — a single in-flight call
        blocks for at most the client's own per-call deadline, so wire
        the client's ``call_timeout_s``/discovery timeout to fractions of
        the window (as ``main()`` does) to keep the total overshoot
        bounded.

        A SEND-SIDE wire-codec refusal (``MasterWireError``: the
        contribution payload is unencodable or exceeds
        ``rpc_max_message_mb``) is deterministic — retrying re-encodes the
        same bytes — so it surfaces immediately as a configuration error
        naming the flag, never as a wedged worker burning the window."""
        deadline = self._clock() + self.rpc_retry_window_s
        delay = 0.2
        while True:
            try:
                return getattr(self.client, method)(*args)
            except _wire.MasterWireError as exc:
                raise RuntimeError(
                    f"worker {self.worker_id}: RPC {method} payload "
                    f"refused by the wire codec ({exc}); raise the "
                    f"rpc_max_message_mb flag or shrink the per-task "
                    f"contribution (smaller chunks_per_task)"
                ) from exc
            except (ConnectionError, TimeoutError) as exc:
                if self._clock() >= deadline:
                    raise
                _log.warning(
                    "worker %s: master RPC %s failed (%r); retrying "
                    "through the bounce", self.worker_id, method, exc,
                )
                self._sleep(min(delay, 2.0))
                delay *= 2

    # -- registry ---------------------------------------------------------
    def _hb_loop(self, interval: float) -> None:
        while not self._stop.wait(interval):
            if self._hb_pause.is_set():
                continue  # simulated full-process freeze: no heartbeats
            try:
                if not self._hb.heartbeat(self.worker_id):
                    # expired (we were pruned) or the master failed over:
                    # rejoin — the registry is runtime state, not snapshot
                    self._hb.register_worker(self.worker_id)
            except Exception:  # noqa: BLE001 — transient; next beat retries
                pass

    def _start_heartbeat(self, worker_timeout_s: float) -> None:
        if self._hb is None:
            return
        interval = self._hb_interval or max(worker_timeout_s / 3.0, 0.05)
        self._hb_thread = threading.Thread(
            target=self._hb_loop, args=(interval,),
            name="paddle-elastic-heartbeat", daemon=True,
        )
        self._hb_thread.start()

    # -- fence ------------------------------------------------------------
    def _fence(self, fence_id: str) -> Dict[str, Any]:
        """Arrive and poll until released.  Polling re-arrives: arrival is
        idempotent, doubles as a liveness signal, and re-registers the
        barrier after a master failover dropped its fences.  The arrival
        meta declares whether this worker checkpoints, so the released
        view's ``writers`` roster covers exactly the shard writers."""
        meta = {"ckpt": self.manager is not None}
        with _obs.span("fence", cat="trainer", fence=fence_id):
            view = self._rpc("fence_arrive", fence_id, self.worker_id, meta)
            while not view.get("released"):
                self._sleep(self.poll_s)
                view = self._rpc(
                    "fence_arrive", fence_id, self.worker_id, meta
                )
        return view

    # -- checkpoints ------------------------------------------------------
    def _write_shard(self, pass_id: int, ranks: List[str]) -> None:
        if self.manager is None or self.worker_id not in ranks:
            return  # not a writer, or we missed the membership cut
        rank, n = ranks.index(self.worker_id), len(ranks)
        step = pass_id + 1
        extra = {"pass_id": pass_id, "step_count": step}
        # off the hot path: the next pass's task compute overlaps this write
        self.manager.save_shard(
            step, rank, n, self.model.state(), async_=True
        )
        self._pending_commit = (step, n, extra)

    def _commit_pending(self) -> None:
        """Publish the previous pass's manifest.  Called right after a
        fence release: every surviving writer joined its async write before
        arriving, so all shards that will ever land have landed.  Any
        worker may commit (idempotent); False just means a writer died
        mid-write and the step stays unrestorable — by design."""
        if self._pending_commit is None:
            return
        step, n, extra = self._pending_commit
        self._pending_commit = None
        if not self.manager.commit(step, n, extra=extra):
            _log.warning(
                "worker %s: checkpoint step %d left uncommitted (a shard "
                "writer died mid-write); restore will use the previous "
                "complete manifest", self.worker_id, step,
            )

    # -- the pass loop ----------------------------------------------------
    def _apply_retained_pass(self, pass_id: int) -> None:
        """Catch up one missed pass from the master's retained result map —
        how a late joiner (or a worker that detected pass skew) reaches the
        exact parameter state the fleet computed without re-leasing any
        task.  Refuses loudly when the retained map is incomplete: applying
        a partial reduction would silently fork the trajectory."""
        pr = self._rpc("pass_results", pass_id)
        results, n_done = pr["results"], pr["n_done"]
        if not results or n_done is None or len(results) != n_done:
            raise RuntimeError(
                f"worker {self.worker_id}: cannot catch up pass {pass_id} "
                f"({len(results)}/{n_done} contributions retained) — joined "
                f"too many passes late with no committed checkpoint "
                f"covering it"
            )
        mean_grads, mean_cost, _ = reduce_results(results)
        self.model.apply(mean_grads)
        self.pass_costs.append(mean_cost)
        _log.info(
            "worker %s caught up pass %d from retained results",
            self.worker_id, pass_id,
        )

    def _catch_up(self, pass_id: int, target: int) -> int:
        """Reach the exact state "after pass target-1" when the fleet moved
        on without us (late join, or a hang long enough to be pruned):
        replay retained result maps; when the gap outruns result retention
        — or a pass was force-rotated and its map POISONED — restore the
        latest committed manifest and replay the remainder.  A manifest
        that does not yet bridge the gap is WAITED for (bounded by the
        RPC retry window): the fleet commits one every pass, so a
        rejoiner stranded behind an unreplayable pass heals as soon as
        the next manifest lands instead of crash-looping."""
        deadline = self._clock() + self.rpc_retry_window_s
        while True:
            try:
                for p in range(pass_id, target):
                    self._apply_retained_pass(p)
                    # advance PER applied pass: a later retry of this loop
                    # (after a partial failure + wait) must never re-apply
                    # a pass these params already include
                    pass_id = p + 1
                return target
            except RuntimeError:
                if self.manager is None:
                    raise
                restored = self.manager.restore_latest(self.model.state())
                if restored is not None:
                    _, tree, extra = restored
                    completed = int(extra.get("pass_id", -1))
                    if completed + 1 > pass_id:
                        # the manifest moves us FORWARD: load and retry
                        # the (now shorter) retained replay
                        self.model.load(tree, extra)
                        pass_id = completed + 1
                        _log.info(
                            "worker %s rejoining via manifest (pass %d "
                            "applied)", self.worker_id, completed,
                        )
                        continue
                if self._clock() >= deadline:
                    raise
                self._sleep(max(self.poll_s, 0.2))
                # the fleet may have moved further on while we waited
                target = max(target, int(self._rpc("stats")["pass_id"]))

    def _run_pass_tasks(self, pass_id: int) -> Optional[int]:
        """Lease and compute this pass's tasks.  Returns None when the pass
        drained, or the MASTER's pass id when it is ahead of ours (the
        fleet fenced and rotated in the gap between our registration and
        our first lease) — the caller must catch up before computing."""
        while True:
            got = self._rpc("get_task", self.worker_id)
            if got is None:
                return None  # pass drained: the master holds the barrier
            if got == "wait":  # remaining leases held by other workers
                self._sleep(self.poll_s)
                continue
            task, epoch = got["task"], got["epoch"]
            tid = task["task_id"]
            # the elastic task lifecycle: lease → compute → ack, correlated
            # by task id so `trace merge` lines this worker's span up with
            # the master's rpc:get_task / rpc:task_finished handling
            _obs.instant(
                "elastic/lease", cat="trainer", task=tid, epoch=epoch,
                p=pass_id,
            )
            master_pass = int(got.get("pass_id", pass_id))
            if master_pass != pass_id:
                # our params lag the fleet (it fenced and rotated between
                # our registration and this lease): hand the task back
                # untouched — no failure event — and replay the gap first
                self._rpc("task_returned", tid, epoch)
                return master_pass
            if _chaos.fire("kill_worker"):
                # die HOLDING the shard lease — the kill-one-of-N drill
                _chaos.kill_self()
            if _chaos.fire("worker_hang"):
                # full-process freeze: heartbeats stop too, so both the
                # registry lease and this shard lease expire underneath us
                self._hb_pause.set()
                _chaos.hang()
                self._hb_pause.clear()
            try:
                records = _read_task_records(task)
            except IOError:
                self._rpc("task_failed", tid, epoch)
                continue
            t0 = self._clock()
            with _obs.span(
                "elastic/compute", cat="trainer", task=tid, p=pass_id,
            ):
                grads, cost_sum, rows = self.model.task_grad(
                    records, pass_id, tid
                )
            self.busy_s += self._clock() - t0
            if self.quantized_grads:
                grads = _bsq.quantize_tree(grads)
            nbytes = _bsq.tree_wire_bytes(grads)
            self.grad_payload_bytes += nbytes
            global_stats.incr("elastic_grad_payload_bytes", nbytes)
            payload = {
                "grads": grads, "cost": float(cost_sum), "rows": int(rows)
            }
            # the ack carries the lease's pass tag: a retry delayed past a
            # rotation is rejected instead of landing in the wrong pass
            with _obs.span("elastic/ack", cat="trainer", task=tid):
                acked = self._rpc("task_finished", tid, epoch, payload,
                                  pass_id)
            if acked:
                self.tasks_done += 1
            else:
                # zombie ack: the lease expired (we hung) and the task was
                # re-served — the surviving recomputation's bits win
                self.rejected_acks += 1

    def _heal_pass_results(self, pass_id: int, view: Dict[str, Any],
                           n_have: int):
        """The fence's frozen done-count disagrees with the retained
        result map: a master failover landed inside the fence window.
        Requeue any done-without-result orphans, recompute whatever the
        queue re-serves (bit-identical: our params have NOT applied this
        pass yet), and return the map only once it provably covers the
        whole pass — rotated-and-frozen-complete, or drained with every
        done task resulted.  Bounded by the RPC retry window; a heal that
        cannot converge surfaces the original refusal."""
        _log.warning(
            "worker %s: pass %d fence froze %s done tasks but the result "
            "map holds %d — master failover mid-fence; healing in place",
            self.worker_id, pass_id, view.get("n_done"), n_have,
        )
        deadline = self._clock() + self.rpc_retry_window_s
        while True:
            st = self._rpc("stats")
            if int(st["pass_id"]) < pass_id:
                # the failover regressed the master to an EARLIER pass
                # than the one we are reducing: that pass must re-drain
                # first.  We already applied it (we are a pass ahead), so
                # attest it forward rather than recompute it with
                # post-apply params.
                self._await_master_repass(int(st["pass_id"]), pass_id)
            self._rpc("requeue_unresulted")
            self._run_pass_tasks(pass_id)
            st = self._rpc("stats")
            pr = self._rpc("pass_results", pass_id)
            results, n_done = pr["results"], pr["n_done"]
            if n_done is not None and results and len(results) == n_done:
                return results  # pass rotated meanwhile: frozen-complete
            if int(st["pass_id"]) > pass_id:
                # rotated but NOT frozen-complete (a force-rotation
                # poisoned the map, or retention dropped it): nothing
                # reducible remains for this pass here
                raise _PassSuperseded(int(st["pass_id"]))
            if (int(st["pass_id"]) == pass_id and st["n_todo"] == 0
                    and st["n_pending"] == 0 and results
                    and len(results) == st["n_done"]):
                return results  # drained: the map covers the whole pass
            if self._clock() >= deadline:
                raise RuntimeError(
                    f"pass {pass_id}: fence froze {view.get('n_done')} "
                    f"done tasks but only {len(results)} contributions "
                    f"exist and in-place recompute did not converge — "
                    f"refusing to apply a partial reduction"
                )
            self._sleep(max(self.poll_s, 0.05))

    def _await_master_repass(self, master_pass: int, pass_id: int) -> None:
        """The master rotated BACKWARD relative to us: a failover replica
        lost rotations/acks that died with the deposed leader, and the
        fleet is re-draining a pass our params already applied.  We must
        neither recompute (our contributions would carry post-apply bits —
        the workers still AT that pass recompute them bit-identically)
        nor re-apply.  While waiting the re-drain out we: re-arrive at
        the re-opened pass's fence (our original arrival may have died
        with the old leader, and an absent live member would wedge the
        healers' barrier forever) and ATTEST our target pass through
        ``start_new_pass(target, worker_id)`` — when every live worker
        attests (nobody is left who could recompute the pass with
        pre-apply params), the master force-rotates past the
        unrecoverable queue state and the fleet recomputes the NEXT pass
        from its common post-apply params, bit-identically."""
        _log.warning(
            "worker %s: master regressed to pass %d (we are at %d) — a "
            "failover lost rotations; waiting for the fleet to re-drain",
            self.worker_id, master_pass, pass_id,
        )
        deadline = self._clock() + self.rpc_retry_window_s
        meta = {"ckpt": self.manager is not None}
        cur = master_pass
        while cur < pass_id:
            if self._clock() >= deadline:
                raise RuntimeError(
                    f"worker {self.worker_id}: master stuck at pass {cur} "
                    f"while we already applied pass {pass_id - 1} — the "
                    f"re-drain never converged"
                )
            self._rpc("fence_arrive", f"pass-{cur}", self.worker_id, meta)
            cur = int(self._rpc(
                "start_new_pass", pass_id, self.worker_id
            ))
            if cur < pass_id:
                self._sleep(max(self.poll_s, 0.05))

    def run(self, num_passes: int) -> Dict[str, Any]:
        info = self._rpc("register_worker", self.worker_id)
        if info.get("auto_rotate"):
            raise RuntimeError(
                "elastic training needs a master with auto_rotate=False: "
                "pass boundaries are fence-synchronized, not free-running"
            )
        self._start_heartbeat(float(info.get("timeout_s", 10.0)))
        try:
            # gang-start wait polls by RE-REGISTERING: registration renews
            # our own lease (and returns the roster), so a worker waiting
            # out a peer's slow boot can never expire into a livelock even
            # with no heartbeat thread wired
            while len(info.get("workers", ())) < self.min_workers:
                self._sleep(max(self.poll_s, 0.05))
                info = self._rpc("register_worker", self.worker_id)
            return self._run(num_passes, info)
        finally:
            self._stop.set()
            if self._hb_thread is not None:
                self._hb_thread.join(timeout=5)
            try:
                self.client.deregister_worker(self.worker_id)
            except Exception:  # noqa: BLE001 — the registry lease will expire
                pass

    def _run(self, num_passes: int, info: Dict[str, Any]) -> Dict[str, Any]:
        current = int(info.get("pass_id", 0))
        completed = None
        # restore when explicitly resuming, OR when joining a cluster that
        # is already past pass 0 — a joiner MUST reach the fleet's exact
        # parameter state before contributing (checkpoint manifest first,
        # retained result maps for the trailing gap)
        if self.manager is not None and (self.resume or current > 0):
            restored = self.manager.restore_latest(self.model.state())
            if restored is not None:
                _, tree, extra = restored
                self.model.load(tree, extra)
                completed = int(extra.get("pass_id", -1))
                _log.info(
                    "worker %s restored committed manifest: pass %d applied",
                    self.worker_id, completed,
                )
        if completed is not None:
            if current < completed:
                raise RuntimeError(
                    f"master is at pass {current} but the checkpoint "
                    f"already applied pass {completed}: the master state "
                    f"dir is stale relative to the checkpoint dir"
                )
            if current == completed:
                if completed + 1 >= num_passes:
                    # the job is already complete (we joined after the last
                    # pass): do NOT rotate the queue past the end — that
                    # would refill todo for a pass nobody asked for
                    current = completed + 1
                else:
                    current = self._rpc("start_new_pass", completed + 1)
            if current == completed:
                raise RuntimeError(
                    f"master cannot rotate past pass {completed} (queue "
                    f"not drained) yet the checkpoint applied it — "
                    f"mismatched master/checkpoint state"
                )
        # late join: replay the passes between the checkpoint (or scratch)
        # and the master's current pass from the retained result maps
        for p in range((completed + 1) if completed is not None else 0,
                       current):
            self._apply_retained_pass(p)
        # a restarted master recovered its queues from the snapshot but the
        # in-memory result payloads died with it: requeue done-but-
        # unresulted tasks so this pass's contributions are recomputed
        # (deterministic, so recomputation cannot move the trajectory)
        requeued = self._rpc("requeue_unresulted")
        if requeued:
            _log.warning(
                "worker %s: recomputing %d task contributions lost with a "
                "restarted master", self.worker_id, requeued,
            )
        self.t_work0 = self._clock()
        pass_id = current
        while pass_id < num_passes:
            wb0 = _wire.counters.snapshot()
            behind = self._run_pass_tasks(pass_id)
            if behind is None:
                # drained — but a pruned-then-rejoined worker (hang) may
                # have slept through whole passes without ever seeing a
                # skewed lease; one stats probe per pass catches that
                actual = int(self._rpc("stats")["pass_id"])
                if actual > pass_id:
                    behind = actual
            if behind is not None:
                if behind > pass_id:
                    # the fleet fenced + rotated without us: replay the
                    # missed passes, then continue at the master's pass
                    pass_id = self._catch_up(pass_id, behind)
                else:
                    # the MASTER is behind us: a failover replica lost the
                    # rotation (and possibly acks) that died with the
                    # deposed leader — heal without recomputing (our
                    # params already include that pass, so our bits would
                    # be wrong) and WITHOUT walking pass_id backwards
                    self._await_master_repass(behind, pass_id)
                continue
            if self.manager is not None:
                self.manager.wait()  # join the async shard write pre-fence
            view = self._fence(f"pass-{pass_id}")
            self._commit_pending()
            results = self._rpc("pass_results", pass_id)["results"]
            if not results or len(results) != int(
                view.get("n_done", len(results))
            ):
                # a master failover landed inside the fence window: the
                # new leader's replica is missing acks that died with the
                # deposed leader (they survive as warm leases / todo), or
                # the frozen fence view predates them — or the pass was
                # force-rotated to nothing under us.  Correctness-first
                # still means NEVER applying a partial (or empty)
                # reduction — but the heal no longer needs a process
                # restart: recompute the missing contributions in place
                # and reduce only a map that covers the WHOLE pass.
                try:
                    results = self._heal_pass_results(
                        pass_id, view, len(results)
                    )
                except _PassSuperseded as sup:
                    # the pass closed under us while we were (briefly)
                    # pruned: nothing left to reduce here — catch up to
                    # the fleet's pass (manifest-bridged if the retained
                    # map was poisoned) and continue there
                    pass_id = self._catch_up(pass_id, sup.target)
                    continue
            mean_grads, mean_cost, _rows = reduce_results(results)
            self.model.apply(mean_grads)
            self.pass_costs.append(mean_cost)
            # this worker's RPC traffic for the whole pass (lease/ack/
            # fence/result-fetch frames) — the per-pass counter the
            # quantized-vs-f32 fleet bench gates its >= 3x reduction on
            wb1 = _wire.counters.snapshot()
            wb = sum(
                wb1.get(k, 0) - wb0.get(k, 0)
                for k in ("wire_bytes_sent", "wire_bytes_recv")
            )
            self.wire_bytes_per_pass.append(wb)
            global_stats.incr("elastic_wire_bytes_pass", wb)
            self._write_shard(pass_id, view.get("writers", []))
            if pass_id + 1 < num_passes:
                self._rpc("start_new_pass", pass_id + 1)
            pass_id += 1
        if self.manager is not None:
            self.manager.wait()
            if self._pending_commit is not None:
                # final pass: every writer joins at one last barrier, then
                # anyone publishes the manifest
                self._fence(f"final-{num_passes - 1}")
                self._commit_pending()
        self.t_work1 = self._clock()
        return {
            "worker_id": self.worker_id,
            "pass_costs": self.pass_costs,
            "tasks_done": self.tasks_done,
            "rejected_acks": self.rejected_acks,
            "busy_s": self.busy_s,
            "t_work0": self.t_work0,
            "t_work1": self.t_work1,
            "quantized_grads": self.quantized_grads,
            "grad_payload_bytes": self.grad_payload_bytes,
            "wire_bytes_per_pass": self.wire_bytes_per_pass,
        }


# ---------------------------------------------------------------------------
# Built-in models
# ---------------------------------------------------------------------------

class NumpyLinearModel:
    """Least-squares regression in pure numpy — the jax-free reference
    model for cluster-plane tests and the scaling bench (worker startup is
    then import-light, so the curve measures coordination + compute, not
    interpreter boot).  Records are float32 vectors ``[x..., y]``.

    ``hidden=0`` (default) is plain linear regression; ``hidden>0`` adds a
    tanh hidden layer (deterministically seeded init) so the per-task
    gradient has real arithmetic weight — what the 1→N scaling bench needs
    to expose coordination overhead honestly."""

    def __init__(self, dim: int, lr: float = 0.1, hidden: int = 0,
                 seed: int = 0):
        self.dim = int(dim)
        self.hidden = int(hidden)
        self.lr = np.float32(lr)
        if self.hidden:
            rng = np.random.RandomState(seed)
            scale = np.float32(1.0 / np.sqrt(self.dim))
            self.w1 = (rng.randn(self.dim, self.hidden)
                       .astype(np.float32) * scale)
            self.b1 = np.zeros((self.hidden,), np.float32)
            self.w = np.zeros((self.hidden,), np.float32)
        else:
            self.w = np.zeros((self.dim,), np.float32)
        self.b = np.zeros((), np.float32)

    def task_grad(self, records, pass_id: int, task_id: int):
        arr = np.stack([np.frombuffer(r, np.float32) for r in records])
        if arr.shape[1] != self.dim + 1:
            raise ValueError(
                f"record width {arr.shape[1]} != dim+1 ({self.dim + 1})"
            )
        x, y = arr[:, :-1], arr[:, -1]
        n = np.float32(len(records))
        if self.hidden:
            h = np.tanh(x @ self.w1 + self.b1)
            err = h @ self.w + self.b - y
            dh = err[:, None] * self.w[None, :] * (1.0 - h * h)
            grads = {
                "w1": x.T @ dh / n,
                "b1": dh.sum(axis=0, dtype=np.float32) / n,
                "w": h.T @ err / n,
                "b": err.mean(dtype=np.float32),
            }
        else:
            err = x @ self.w + self.b - y
            grads = {"w": x.T @ err / n, "b": err.mean(dtype=np.float32)}
        cost_sum = float(0.5 * np.sum(err.astype(np.float64) ** 2))
        return grads, cost_sum, len(records)

    def apply(self, grads) -> None:
        for name, g in grads.items():
            setattr(
                self, name,
                getattr(self, name) - self.lr * np.asarray(g, np.float32),
            )

    def state(self):
        out = {"w": self.w, "b": self.b}
        if self.hidden:
            out.update({"w1": self.w1, "b1": self.b1})
        return out

    def load(self, tree, extra) -> None:
        for name in self.state():
            setattr(self, name, np.asarray(tree[name], np.float32))


class TrainerTaskModel:
    """Adapts a :class:`paddle_tpu.trainer.SGD` trainer to the elastic
    protocol: per-task gradients come from the jitted
    :func:`~paddle_tpu.trainer.step.make_grad_step`, the reduced update
    goes through the trainer's own optimizer, and the checkpointed state is
    the trainer's full state (params + layer state + optimizer state +
    RNG) — so an elastic fleet trains the same networks, with the same
    optimizers, as a single-process ``trainer.train`` run.

    ``decode(record) -> sample`` turns one stored record into one feed
    sample for the trainer's DataFeeder.  The per-task RNG folds in
    (pass_id, task_id) only — NOT the worker or the task epoch — so a
    requeued task recomputes bit-identical contributions on any survivor."""

    def __init__(self, trainer, decode):
        import jax

        from paddle_tpu.trainer.step import make_grad_step

        self._t = trainer
        self._decode = decode
        self._feeder = trainer._make_feeder(None)
        self._gstep = make_grad_step(trainer.network, trainer.mesh)
        self._apply = jax.jit(
            lambda g, o, p: trainer.optimizer.update(g, o, p)
        )
        self._base_rng = jax.random.PRNGKey(trainer._seed)

    def task_grad(self, records, pass_id: int, task_id: int):
        import jax

        from paddle_tpu.parallel.mesh import shard_batch

        samples = [self._decode(r) for r in records]
        batch = shard_batch(self._feeder(samples), self._t.mesh)
        rng = jax.random.fold_in(
            jax.random.fold_in(self._base_rng, pass_id), task_id
        )
        grads, cost = self._gstep(
            self._t.parameters.params, self._t.parameters.state, batch, rng
        )
        grads = jax.tree_util.tree_map(
            lambda g: np.asarray(jax.device_get(g)), grads
        )
        return grads, float(cost) * len(samples), len(samples)

    def apply(self, grads) -> None:
        t = self._t
        t.parameters.params, t._opt_state = self._apply(
            grads, t._opt_state, t.parameters.params
        )
        t._step_count += 1

    def state(self):
        return self._t._full_state()

    def load(self, tree, extra) -> None:
        self._t._apply_restored(tree, extra)


# ---------------------------------------------------------------------------
# CLI — the per-process entry point the launcher/bench/chaos tests spawn
# ---------------------------------------------------------------------------

def _parse_model_args(pairs: List[str]) -> Dict[str, str]:
    out: Dict[str, str] = {}
    for p in pairs:
        k, _, v = p.partition("=")
        out[k.strip()] = v.strip()
    return out


def _build_model(name: str, margs: Dict[str, str], seed: int):
    if name == "numpy":
        return NumpyLinearModel(
            dim=int(margs.get("dim", "8")),
            lr=float(margs.get("lr", "0.1")),
            hidden=int(margs.get("hidden", "0")),
            seed=seed,
        )
    if name == "mlp":
        import paddle_tpu as paddle
        from paddle_tpu.core.topology import reset_auto_names

        dim = int(margs.get("dim", "8"))
        classes = int(margs.get("classes", "4"))
        hidden = int(margs.get("hidden", "16"))
        lr = float(margs.get("lr", "0.1"))
        reset_auto_names()
        paddle.init(seed=seed)
        x = paddle.layer.data("x", paddle.data_type.dense_vector(dim))
        h = paddle.layer.fc(x, size=hidden, act=paddle.activation.Tanh())
        pred = paddle.layer.fc(
            h, size=classes, act=paddle.activation.Softmax()
        )
        label = paddle.layer.data(
            "label", paddle.data_type.integer_value(classes)
        )
        cost = paddle.layer.classification_cost(input=pred, label=label)
        trainer = paddle.trainer.SGD(
            cost=cost,
            parameters=paddle.parameters.create(cost, seed=seed),
            update_equation=paddle.optimizer.Momentum(
                learning_rate=lr, momentum=0.9
            ),
        )

        def decode(rec: bytes):
            vec = np.frombuffer(rec, np.float32)
            return vec[:-1].tolist(), int(vec[-1])

        return trainer.elastic_model(decode)
    raise ValueError(f"unknown --model {name!r} (numpy, mlp)")


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="paddle-tpu worker",
        description="One elastic trainer process: leases data-shard tasks "
        "from the master plane, contributes deterministic per-task "
        "gradients, reduces at pass fences, writes its checkpoint shard.",
    )
    ap.add_argument("--dir", required=True,
                    help="the HA master discovery directory (master_ha)")
    ap.add_argument("--worker-id", default=None,
                    help="default: w<PADDLE_TPU_PROCESS_ID> under the "
                    "launcher, else host:pid")
    ap.add_argument("--num-passes", type=int, default=1)
    ap.add_argument("--model", default="numpy", help="numpy | mlp")
    ap.add_argument("--model-arg", action="append", default=[],
                    help="k=v model hyperparameter (repeatable), e.g. dim=8")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--checkpoint-dir", default=None,
                    help="sharded-manifest checkpoint directory (shared)")
    ap.add_argument("--resume", action="store_true",
                    help="restore the latest committed manifest first")
    ap.add_argument("--stats-out", default=None,
                    help="write a JSON work summary here on success; a "
                    "'{worker}' placeholder expands to the worker id, so "
                    "one launcher argv serves the whole fleet")
    ap.add_argument("--poll-s", type=float, default=0.02)
    ap.add_argument("--min-workers", type=int, default=1,
                    help="hold the first lease until this many workers "
                    "registered (gang-start hint; membership stays elastic "
                    "afterwards)")
    ap.add_argument("--client-timeout", type=float, default=None,
                    help="leader-discovery timeout; default derives from "
                    "--rpc-retry-window-s, an explicit value is used as-is")
    ap.add_argument("--rpc-retry-window-s", type=float, default=60.0,
                    help="ride through a master bounce for this long "
                    "before exiting nonzero for the supervisor")
    ap.add_argument("--chaos", default=None,
                    help="arm chaos points in THIS worker, e.g. "
                    "'kill_worker@2' (env PADDLE_TPU_CHAOS also works)")
    ap.add_argument("--quantized-grads", action="store_true", default=None,
                    help="block-scale quantize gradient contributions "
                    "(int8 blocks + f32 scales) before they ride the wire; "
                    "default from the elastic_quantized_grads flag / "
                    "PADDLE_TPU_ELASTIC_QUANTIZED_GRADS env")
    args = ap.parse_args(argv)

    logging.basicConfig(
        level=logging.INFO, format="%(asctime)s %(name)s %(message)s"
    )
    # obs trace context: one elastic trainer process of the fleet (export
    # armed by the trace_dir flag / PADDLE_TPU_TRACE_DIR from the launcher)
    _obs.tracer.configure(role="worker")
    if args.chaos:
        _chaos.arm(args.chaos)
    from paddle_tpu.master_ha import HAClient

    worker_id = args.worker_id
    if worker_id is None:
        proc_id = os.environ.get("PADDLE_TPU_PROCESS_ID")
        worker_id = (
            f"w{proc_id}" if proc_id is not None
            else f"{os.uname().nodename}:{os.getpid()}"
        )
    manager = None
    if args.checkpoint_dir:
        from paddle_tpu.checkpoint import CheckpointManager

        manager = CheckpointManager(args.checkpoint_dir)
    model = _build_model(
        args.model, _parse_model_args(args.model_arg), args.seed
    )
    # the retry window is only checked BETWEEN calls, so one blocked call
    # must not be able to eat the whole window: cap the per-call deadline
    # and the leader re-discovery timeout at fractions of it.  An explicit
    # --client-timeout is the operator's call and is used as-is.
    window = args.rpc_retry_window_s
    client_kw = dict(
        timeout=(args.client_timeout if args.client_timeout is not None
                 else max(window / 2.0, 5.0)),
        call_timeout_s=max(min(15.0, window / 4.0), 2.0),
    )
    worker = ElasticWorker(
        HAClient(args.dir, **client_kw),
        worker_id,
        model,
        manager=manager,
        resume=args.resume,
        heartbeat_client=HAClient(args.dir, **client_kw),
        poll_s=args.poll_s,
        min_workers=args.min_workers,
        rpc_retry_window_s=window,
        quantized_grads=args.quantized_grads,
    )
    summary = worker.run(args.num_passes)
    if args.stats_out:
        _obs.write_stats_json(
            args.stats_out.replace("{worker}", worker_id), summary
        )
    _obs.tracer.dump()  # per-process trace file (no-op without trace_dir)
    for i, c in enumerate(summary["pass_costs"]):
        print(f"worker {worker_id} pass cost {c:.6f} (#{i})", flush=True)
    return 0


if __name__ == "__main__":  # pragma: no cover
    import sys

    sys.exit(main())
