"""Metric builders attached to the train/eval step — the in-graph half of the
reference evaluator framework (reference: paddle/gserver/evaluators/
Evaluator.cpp classification_error:995, sum:996, precision_recall:584).

Metrics here are computed *inside* the jitted step from layer outputs (no
host sync), then averaged across batches on the host by the trainer.
"""

from __future__ import annotations

from typing import Callable, Dict, Optional

import jax.numpy as jnp

from paddle_tpu.core.batch import SeqTensor
from paddle_tpu.core.topology import Topology

_CLS_COST_TYPES = {"softmax_with_cost", "cross_entropy"}


def default_metrics_fn(topology: Topology) -> Optional[Callable]:
    """Build an extra_metrics fn: for classification costs in the topology,
    emit classification_error (argmax(pred) != label), masked over sequences
    — reference ClassificationErrorEvaluator (Evaluator.cpp:70-160)."""
    cls = [
        conf
        for conf in topology.layers.values()
        if conf.type in _CLS_COST_TYPES
    ]
    if not cls:
        return None

    def metrics(outs: Dict[str, SeqTensor]) -> Dict[str, jnp.ndarray]:
        m: Dict[str, jnp.ndarray] = {}
        for conf in cls:
            pred_name, label_name = conf.inputs[0], conf.inputs[1]
            pred, label = outs[pred_name], outs[label_name]
            ids = label.data.astype(jnp.int32)
            if ids.ndim >= 2 and ids.shape[-1] == 1:
                ids = ids[..., 0]
            # argmax(softmax(x)) == argmax(x): read the pre-activation aux
            # when the producer exposed one, so the error metric never
            # forces the [N, V] softmax to materialize (at a 32k MT vocab
            # that softmax is ~1 GB per step and exists ONLY for this
            # metric — the fused CE reads logits)
            lg = outs.get(pred_name + "@logits")
            scores = lg.data if lg is not None else pred.data
            err = (jnp.argmax(scores, axis=-1) != ids).astype(jnp.float32)
            if pred.is_seq and err.ndim == 2:
                mask = pred.mask()
                err = jnp.sum(err * mask) / jnp.maximum(jnp.sum(mask), 1.0)
            else:
                err = jnp.mean(err)
            key = (
                "classification_error"
                if len(cls) == 1
                else f"classification_error/{conf.name}"
            )
            m[key] = err
        return m

    return metrics
