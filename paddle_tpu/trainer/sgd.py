"""The training driver — ``paddle.v2.trainer.SGD`` surface (reference:
python/paddle/v2/trainer.py:24-177) over the jitted step.

Differences from the reference by design: one fused XLA step replaces the
forwardBackward + per-parameter updater loop; data parallelism is the mesh
`data` axis (gradients psum over ICI) instead of MultiGradientMachine threads
or remote parameter servers — `is_local` is accepted for API compatibility
but there is nothing remote to talk to.
"""

from __future__ import annotations

import os
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

import jax
import numpy as np

from paddle_tpu import event as v2_event
from paddle_tpu.core.compiler import CompiledNetwork
from paddle_tpu.core.topology import LayerOutput, Topology
from paddle_tpu.optimizer import Optimizer
from paddle_tpu.parameters import Parameters, create_from_network
from paddle_tpu.parallel.mesh import get_default_mesh, shard_batch
from paddle_tpu.reader.feeder import DataFeeder
from paddle_tpu.trainer.evaluators import default_metrics_fn
from paddle_tpu.trainer.step import make_eval_step, make_train_step
from paddle_tpu.utils.timers import stat_timer


class SGD:
    """paddle.v2.trainer.SGD(cost, parameters, update_equation, ...)"""

    def __init__(
        self,
        cost,
        parameters: Optional[Parameters] = None,
        update_equation: Optional[Optimizer] = None,
        extra_layers: Optional[Sequence[LayerOutput]] = None,
        is_local: bool = True,  # kept for surface compat; always "local"
        mesh=None,
        seed: int = 0,
    ):
        outputs: List[LayerOutput] = [cost] if isinstance(cost, LayerOutput) else list(cost)
        if extra_layers:
            outputs += list(extra_layers)
        self.topology = Topology(outputs)
        if parameters is not None and parameters.network.topology.order == self.topology.order:
            self.network = parameters.network
            self.parameters = parameters
        else:
            self.network = CompiledNetwork(self.topology)
            self.parameters = parameters or create_from_network(self.network, seed)
        assert update_equation is not None, "update_equation (an Optimizer) is required"
        self.optimizer = update_equation
        self.mesh = mesh if mesh is not None else get_default_mesh()
        self._metrics_fn = default_metrics_fn(self.topology)
        self._train_step = make_train_step(
            self.network, self.optimizer, self.mesh, self._metrics_fn
        )
        self._eval_step = make_eval_step(self.network, self.mesh, self._metrics_fn)
        self._opt_state = self.optimizer.init(self.parameters.params)
        self._rng = jax.random.PRNGKey(seed + 1)
        self._step_count = 0

    # ------------------------------------------------------------------
    def _make_feeder(self, feeding) -> DataFeeder:
        return DataFeeder(self.topology.data_types(), feeding)

    def train(
        self,
        reader: Callable,
        num_passes: int = 1,
        event_handler: Optional[Callable] = None,
        feeding=None,
    ) -> None:
        if event_handler is None:
            event_handler = lambda e: None
        feeder = self._make_feeder(feeding)
        params, state = self.parameters.params, self.parameters.state
        opt_state = self._opt_state
        for pass_id in range(num_passes):
            event_handler(v2_event.BeginPass(pass_id))
            pass_costs: List[float] = []
            for batch_id, data_batch in enumerate(reader()):
                event_handler(v2_event.BeginIteration(pass_id, batch_id))
                with stat_timer("feed"):
                    batch = feeder(data_batch)
                    batch = shard_batch(batch, self.mesh)
                with stat_timer("train_step"):
                    self._rng, step_rng = jax.random.split(self._rng)
                    params, state, opt_state, metrics = self._train_step(
                        params, state, opt_state, batch, step_rng
                    )
                self._step_count += 1
                cost = float(metrics["cost"])
                pass_costs.append(cost)
                evaluator = {
                    k: float(v) for k, v in metrics.items() if k != "cost"
                }
                event_handler(
                    v2_event.EndIteration(pass_id, batch_id, cost, evaluator)
                )
            # persist latest values so checkpoints/test see them
            self.parameters.params, self.parameters.state = params, state
            self._opt_state = opt_state
            event_handler(
                v2_event.EndPass(
                    pass_id,
                    {"mean_cost": float(np.mean(pass_costs)) if pass_costs else 0.0},
                )
            )
        self.parameters.params, self.parameters.state = params, state
        self._opt_state = opt_state

    # ------------------------------------------------------------------
    def test(self, reader: Callable, feeding=None) -> v2_event.TestResult:
        feeder = self._make_feeder(feeding)
        costs: List[float] = []
        sums: Dict[str, float] = {}
        n = 0
        for data_batch in reader():
            batch = shard_batch(feeder(data_batch), self.mesh)
            metrics = self._eval_step(
                self.parameters.params, self.parameters.state, batch
            )
            costs.append(float(metrics["cost"]))
            for k, v in metrics.items():
                if k != "cost":
                    sums[k] = sums.get(k, 0.0) + float(v)
            n += 1
        avg = {k: v / max(n, 1) for k, v in sums.items()}
        return v2_event.TestResult(avg, float(np.mean(costs)) if costs else 0.0)

    # ------------------------------------------------------------------
    def save_parameter_to_tar(self, f) -> None:
        self.parameters.to_tar(f)

    def save_pass(self, save_dir: str, pass_id: int) -> str:
        """Write pass-%05d/params.tar (reference pass-%05d dirs,
        paddle/trainer/ParamUtil.cpp)."""
        d = os.path.join(save_dir, f"pass-{pass_id:05d}")
        os.makedirs(d, exist_ok=True)
        with open(os.path.join(d, "params.tar"), "wb") as f:
            self.parameters.to_tar(f)
        return d
