"""The training driver — ``paddle.v2.trainer.SGD`` surface (reference:
python/paddle/v2/trainer.py:24-177) over the jitted step.

Differences from the reference by design: one fused XLA step replaces the
forwardBackward + per-parameter updater loop; data parallelism is the mesh
`data` axis (gradients psum over ICI) instead of MultiGradientMachine threads
or remote parameter servers — `is_local` is accepted for API compatibility
but there is nothing remote to talk to.
"""

from __future__ import annotations

import logging
import os
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

import jax
import numpy as np

from paddle_tpu import event as v2_event
from paddle_tpu.core.compiler import CompiledNetwork
from paddle_tpu.core.topology import LayerOutput, Topology
from paddle_tpu.optimizer import Optimizer
from paddle_tpu.parameters import Parameters, create_from_network
from paddle_tpu.parallel.mesh import get_default_mesh, shard_batch
from paddle_tpu.reader.feeder import DataFeeder
from paddle_tpu.trainer.evaluators import default_metrics_fn
from paddle_tpu.trainer.step import make_eval_step, make_train_step

_log = logging.getLogger("paddle_tpu.trainer")
from paddle_tpu import obs as _obs
from paddle_tpu.utils.timers import global_stats, stat_timer


def _batch_rows(batch) -> int:
    """Sample count of a staged batch (any slot's leading dim).  Cost and
    metric aggregation weight by this: with the bucketed feed, batch sizes
    vary ~32x across length rungs, and an unweighted mean-over-batches would
    give a long-sequence sample many times the weight of a short one."""
    for t in batch.values():
        data = t.data if hasattr(t, "data") else t
        return int(data.shape[0])
    return 1


class SGD:
    """paddle.v2.trainer.SGD(cost, parameters, update_equation, ...)"""

    def __init__(
        self,
        cost,
        parameters: Optional[Parameters] = None,
        update_equation: Optional[Optimizer] = None,
        extra_layers: Optional[Sequence[LayerOutput]] = None,
        is_local: bool = True,  # kept for surface compat; always "local"
        mesh=None,
        seed: int = 0,
        evaluators: Optional[Sequence] = None,
    ):
        self.evaluators = list(evaluators or [])
        self._seed = seed  # also keys the pass-cache replay shuffle
        if isinstance(cost, Topology) and not extra_layers and not self.evaluators:
            # e.g. a v1_compat parse_config result's topology
            self.topology = cost
        else:
            if isinstance(cost, Topology):
                outputs: List[LayerOutput] = list(cost.outputs)
            elif isinstance(cost, LayerOutput):
                outputs = [cost]
            else:
                outputs = list(cost)
            if extra_layers:
                outputs += list(extra_layers)
            for ev in self.evaluators:
                outputs += list(ev.layers)
            self.topology = Topology(outputs)
        if parameters is not None and not hasattr(parameters, "network"):
            # the reference's static Parameters.from_tar(f) returns a
            # topology-free bag (DetachedParameters); build real params
            # for THIS topology and merge the values in by name
            detached = parameters
            parameters = create_from_network(
                CompiledNetwork(self.topology), seed
            )
            detached.merge_into(parameters)
        # Structural comparison (serialize covers types/sizes/attrs) — name
        # tuples alone would wrongly reuse a different network whose layers
        # happen to share auto-names.
        if parameters is not None and (
            parameters.network.topology.serialize() == self.topology.serialize()
            # a shared network must not have its mesh clobbered: reuse only
            # when the meshes agree (another trainer may be using it)
            and (mesh is None or parameters.network.mesh in (None, mesh))
        ):
            self.network = parameters.network
            self.parameters = parameters
        else:
            self.network = CompiledNetwork(self.topology)
            if parameters is not None:
                # Same cost graph extended with evaluators/extra layers is
                # fine (the extras are param-free); parameters built for a
                # DIFFERENT network are not — catch it here instead of a
                # shape/KeyError mid-step.
                stale = [
                    n for n in parameters.params if n not in self.topology.layers
                ]
                if stale:
                    raise ValueError(
                        f"parameters were created for a different topology: "
                        f"param layers {stale} do not exist in this trainer's "
                        f"network"
                    )
            self.parameters = parameters or create_from_network(self.network, seed)
        assert update_equation is not None, "update_equation (an Optimizer) is required"
        self.optimizer = update_equation
        self.mesh = mesh if mesh is not None else get_default_mesh()
        self._metrics_fn = self._build_metrics_fn()
        from paddle_tpu.parallel.sharding import has_model_sharding, shard_params

        # mesh-aware layers (ring attention) trace against the trainer's
        # mesh, scoped to THIS network — no process-global publishing, so
        # two trainers with different meshes stay isolated.  A meshless
        # trainer reusing a meshed network ADOPTS that mesh rather than
        # clobbering it with None.
        if self.mesh is not None:
            self.network.mesh = self.mesh
        elif self.network.mesh is not None:
            self.mesh = self.network.mesh
        self._model_sharded = has_model_sharding(
            self.network, self.parameters.params, self.mesh
        )
        if self._model_sharded:
            # Row/column-shard the flagged tables over the model axis before
            # optimizer state is created so its slots inherit the placement.
            self.parameters.params = shard_params(
                self.network, self.parameters.params, self.mesh
            )
        # Static pruning hooks: masks from initial magnitudes, applied to
        # the initial values and after every update (StaticPruningHook).
        from paddle_tpu.trainer.step import apply_prune_masks, build_prune_masks

        self._prune_masks = build_prune_masks(self.network, self.parameters.params)
        if self._prune_masks:
            self.parameters.params = apply_prune_masks(
                self.parameters.params, self._prune_masks
            )
        self._train_step = make_train_step(
            self.network, self.optimizer, self.mesh, self._metrics_fn,
            infer_param_shardings=self._model_sharded,
            prune_masks=self._prune_masks,
        )
        self._eval_step = make_eval_step(
            self.network, self.mesh, self._metrics_fn,
            infer_param_shardings=self._model_sharded,
        )
        self._opt_state = self.optimizer.init(self.parameters.params)
        self._rng = jax.random.PRNGKey(seed + 1)
        self._step_count = 0
        self._pass_cache = None  # set per train() call when caching is on
        self._pass_cache_reader = None  # the reader the cache was built for
        # Per-bucket dispatch accounting: every train/eval batch's shape
        # signature is observed here (core.compiler.CompileShapeCache), so
        # the StatSet plane carries compile hit/miss counters and a bounded-
        # shape check is one property read away.  With the bucketing feed on
        # (use_bucketing flag / DataFeeder(ladder=...)) misses stay bounded
        # by the shape-ladder size; an unbucketed variable-length feed shows
        # its per-shape recompiles here instead of as silent latency.
        from paddle_tpu.core.compiler import CompileShapeCache

        self.compile_cache = CompileShapeCache("train_step")
        self._eval_cache = CompileShapeCache("eval_step")
        # Persistent AOT executable cache (core/aot_cache.py): with the
        # aot_cache_dir flag set, every train-step/epoch-program variant
        # dispatches through a per-shape executable table backed by the
        # on-disk serialized-executable store — a warm boot deserializes
        # where a cold boot retraces.  None = today's jit dispatch path.
        from paddle_tpu.utils import flags as _flags

        aot_dir = _flags.get_flag("aot_cache_dir")
        self._aot_cache = None
        if aot_dir:
            from paddle_tpu.core.aot_cache import AOTCache

            self._aot_cache = AOTCache(aot_dir)
        self._exec_table: Dict[tuple, Any] = {}  # (kind, shape key) -> exe
        # dynamic-width (batch-wide trans) weights resolve exactly ONCE, at
        # the first batch this trainer ever sees; a later batch-size change
        # must fail loudly, never silently re-draw trained weights
        self._width_resolved = not self.network.has_dynamic_widths

    # ------------------------------------------------------------------
    def _build_metrics_fn(self):
        default = default_metrics_fn(self.topology)
        if not self.evaluators:
            return default
        from paddle_tpu.evaluator import combined_update

        ev_update = combined_update(self.evaluators)

        def metrics(outs):
            m = default(outs) if default else {}
            m.update(ev_update(outs))
            return m

        return metrics

    def _split_metrics(self, metrics):
        """(plain scalar metrics, evaluator accumulators) from a step result."""
        scalars, accums = {}, {}
        for k, v in metrics.items():
            if k.startswith("ev:"):
                accums[k] = np.asarray(v)
            elif k != "cost":
                scalars[k] = float(v)
        return scalars, accums

    def _finalize(self, accums):
        from paddle_tpu.evaluator import finalize_all

        return finalize_all(self.evaluators, accums) if self.evaluators else {}

    # ------------------------------------------------------------------
    def _make_feeder(self, feeding) -> DataFeeder:
        # data layers declaring a narrow wire dtype (data_layer(feed_dtype=
        # "uint8")) feed raw and cast+normalize on device (_feed_transform)
        from paddle_tpu.reader.feeder import feed_dtypes_of
        from paddle_tpu.utils import flags as _flags

        # bucketing feed: padded lengths come from the canonical shape
        # ladder instead of multiple-of-8 rounding, completing the contract
        # reader.bucketing packs batches for (bounded jit shapes)
        ladder = None
        if _flags.get_flag("use_bucketing"):
            if self.network.has_dynamic_widths:
                # batch-wide-trans weights pin to the FIRST batch's size and
                # any later batch-size change is a hard XLA shape error; the
                # token-budget batcher varies batch size per rung by design,
                # so the combination can only explode mid-epoch — refuse now
                raise ValueError(
                    "use_bucketing is incompatible with dynamic (batch-wide "
                    "trans) width layers: bucketed batch sizes vary per "
                    "length rung, but these weights train at exactly one "
                    "batch size.  Feed this network with paddle.batch "
                    "(fixed size, drop_last=True) instead."
                )
            from paddle_tpu.core.batch import DEFAULT_LADDER

            ladder = DEFAULT_LADDER
        return DataFeeder(
            self.topology.data_types(), feeding,
            feed_dtypes=feed_dtypes_of(self.topology),
            ladder=ladder,
        )

    # -- AOT executable cache dispatch (core/aot_cache.py) --------------
    def _aot_identity(self, kind: str, batch, n_steps=None) -> Dict[str, Any]:
        """Identity key of one compiled program variant: what program this
        is (step kind + optional scan length), over which graph (topology
        fingerprint incl. compute dtype), at which ladder rung (batch shape
        key), on which mesh, with which donation signature."""
        from paddle_tpu.core import aot_cache as _aot
        from paddle_tpu.core.batch import batch_shape_key

        return {
            "kind": kind,
            "n_steps": n_steps,
            "topology": _aot.topology_fingerprint(self.network),
            "batch": repr(batch_shape_key(batch)),
            "mesh": _aot.mesh_fingerprint(self.mesh),
            "donation": "(0,)" if kind == "epoch_program" else "(0, 1, 2)",
            "model_sharded": bool(self._model_sharded),
        }

    def _aot_meta(self) -> Dict[str, Any]:
        """Header-only key fields: mismatches make an entry STALE (retraced
        and overwritten) rather than addressing a different file — the
        hyperparameters and flags that change the compiled program without
        changing which program it logically is."""
        from paddle_tpu.core.aot_cache import optimizer_fingerprint
        from paddle_tpu.utils.flags import get_flag

        return {
            "optimizer": optimizer_fingerprint(self.optimizer),
            "sentinel": bool(get_flag("divergence_sentinel")),
            "pruned": bool(self._prune_masks),
        }

    def _run_train_step(self, params, state, opt_state, batch, rng):
        """One train-step dispatch.  Without an AOT cache this is the jit
        call; with one, each batch shape resolves once per process to a
        compiled executable — deserialized from disk when a previous boot
        compiled this rung (warm), ``lower().compile()`` + stored when not
        (cold) — and every later dispatch of the shape reuses it."""
        if self._aot_cache is None:
            return self._train_step(params, state, opt_state, batch, rng)
        from paddle_tpu.core.batch import batch_shape_key

        key = ("train_step", batch_shape_key(batch))
        exe = self._exec_table.get(key)
        if exe is None:
            exe = self._aot_cache.get_or_compile(
                self._train_step,
                (params, state, opt_state, batch, rng),
                self._aot_identity("train_step", batch),
                self._aot_meta(),
            )
            self._exec_table[key] = exe
        return exe(params, state, opt_state, batch, rng)

    def warm_compile(self, batch) -> bool:
        """Populate the AOT cache for one batch shape WITHOUT running a
        step (the ``paddle-tpu cache warm`` prewarm path: compile-or-load
        every ladder rung offline so fleet boots deserialize).  True when
        the shape was newly resolved this call."""
        assert self._aot_cache is not None, "warm_compile needs aot_cache_dir"
        from paddle_tpu.core.batch import batch_shape_key

        key = ("train_step", batch_shape_key(batch))
        if key in self._exec_table:
            return False
        rng = jax.random.PRNGKey(0)
        self._exec_table[key] = self._aot_cache.get_or_compile(
            self._train_step,
            (self.parameters.params, self.parameters.state, self._opt_state,
             batch, rng),
            self._aot_identity("train_step", batch),
            self._aot_meta(),
        )
        return True

    # -- whole-pass on-device epoch program -----------------------------
    def _dispatch_epoch_program(self, pass_cache, pass_id, params, state,
                                opt_state):
        """Run one cached epoch as ONE host dispatch (trainer/step.py
        make_epoch_program): carried-state in, per-step metrics out.
        Returns (params, state, opt_state, step_metrics) with
        ``step_metrics`` a dict of host arrays stacked [n_batches, ...] —
        one fetch, from which the caller replays the exact stepwise
        event/sentinel bookkeeping."""
        from paddle_tpu.core.batch import batch_shape_key
        from paddle_tpu.trainer.step import (
            make_epoch_program,
            make_train_carry,
            split_train_carry,
        )

        n = pass_cache.n_batches
        stacked = pass_cache.stacked()
        perm = pass_cache.epoch_perm(pass_id)
        key = ("epoch_program", n, batch_shape_key(pass_cache.sample_batch()))
        prog = self._exec_table.get(key)
        if prog is None:
            jitted = make_epoch_program(
                self.network, self.optimizer, self.mesh, self._metrics_fn,
                prune_masks=self._prune_masks,
            )
            if self._aot_cache is not None:
                carry0 = make_train_carry(params, state, opt_state, self._rng)
                prog = self._aot_cache.get_or_compile(
                    jitted, (carry0, stacked, perm),
                    self._aot_identity(
                        "epoch_program", pass_cache.sample_batch(), n_steps=n
                    ),
                    self._aot_meta(),
                )
            else:
                prog = jitted
            self._exec_table[key] = prog
            _log.info(
                "whole-pass epoch program ready: %d steps per dispatch "
                "(%s dispatch table)", n,
                "aot-cached" if self._aot_cache is not None else "jit",
            )
        carry = make_train_carry(params, state, opt_state, self._rng)
        with stat_timer("epoch_program"), _obs.span(
            "epoch_program", cat="trainer", p=pass_id, n_steps=n,
        ):
            carry, ms = prog(carry, stacked, perm)
        global_stats.incr("epoch_program/dispatches")
        global_stats.incr("epoch_program/steps", n)
        params, state, opt_state, self._rng = split_train_carry(carry)
        step_ms = {k: np.asarray(v) for k, v in ms.items()}  # one fetch
        return params, state, opt_state, step_ms

    def train(
        self,
        reader: Callable,
        num_passes: int = 1,
        event_handler: Optional[Callable] = None,
        feeding=None,
        save_dir: Optional[str] = None,
        saving_period: int = 1,
        saving_period_by_batches: Optional[int] = None,
        start_pass: int = 0,
        show_parameter_stats_period: Optional[int] = None,
        async_load_data: bool = True,
        checkpoint_dir: Optional[str] = None,
        checkpoint_period_batches: Optional[int] = None,
        resume: bool = False,
    ) -> None:
        """Pass loop with the reference trainer's checkpoint cadence: every
        `saving_period` passes (and optionally every `saving_period_by_batches`
        batches) write pass-%05d under save_dir; `start_pass` resumes numbering
        (reference: Trainer.cpp:454-488, flags saving_period /
        saving_period_by_batches / start_pass).

        async_load_data (reference TrainData(async_load_data=...) +
        DataProvider.h's double-buffer queue): run the host-side feed —
        converters, sharding, the device_put issue — on a background thread
        so batch N+1's host→device transfer overlaps step N's compute.
        JAX's async dispatch handles the device side; this hides the host
        side.  The reader runs up to 3 batches ahead of the consuming step;
        set False for inline single-thread feeding if the reader mutates
        state the training loop observes (or isn't thread-compatible).

        Device-resident pass cache (the TPU-native CACHE_PASS_IN_MEM,
        reference PyDataProvider2.cpp:69): when the reader was built from
        ``@provider(cache=CacheType.CACHE_PASS_IN_MEM)`` (the factory tags
        it) or the ``cache_pass_in_mem`` flag is on, epoch 1's staged
        batches stay on device (reader/pass_cache.py: HBM-budgeted, wire
        dtype preserved, optional ``data_echo_factor`` echo) and every
        later pass replays them with a seed-reproducible on-device shuffle
        — zero H2D traffic, no per-batch Python feed.  A pass that blows
        the HBM budget falls back to streaming with a warning.

        Fault tolerance (robustness/): with ``checkpoint_dir`` set, the
        trainer writes full-state checkpoints (params + optimizer state +
        RNG + pass/batch position) every ``checkpoint_period_batches``
        batches (None = the flag) and at every pass boundary.  The
        divergence sentinel (``divergence_sentinel`` flag, fused into the
        jitted step) skips non-finite steps on device; when it declares
        divergence (skip streak or EMA loss spike), the trainer rolls back
        to the last-good checkpoint and applies the master's ``failure_max``
        discipline to the offending data window — retry from the retained
        batches, then quarantine and continue.  SIGTERM/SIGINT trigger a
        synchronous final checkpoint + ``PREEMPTED`` marker and return
        (``self.preempted`` is True); ``resume=True`` restores the latest
        good checkpoint (walking past torn ones) and skips the interrupted
        pass's already-consumed batches, so with a deterministic streamed
        reader the resumed trajectory matches an uninterrupted run
        bit-for-bit.  (A ``cache_pass_in_mem`` run resumes from the
        checkpoint but streams its remaining passes — the interrupted
        process's device-resident capture cannot be reconstructed.)"""
        if event_handler is None:
            event_handler = lambda e: None
        import itertools
        from collections import deque
        from contextlib import nullcontext

        from paddle_tpu.reader.prefetch import prefetch
        from paddle_tpu.robustness import chaos as _chaos
        from paddle_tpu.utils import flags as _flags

        if show_parameter_stats_period is None:  # explicit 0 still disables
            show_parameter_stats_period = _flags.get_flag(
                "show_parameter_stats_period"
            )
        log_period = _flags.get_flag("log_period")
        # whole-pass on-device epoch program: cached epochs >= 2 run as ONE
        # lax.scan dispatch over the stacked pass cache (O(1) host round-
        # trips per epoch), bit-exact against the stepwise loop below
        whole_pass = _flags.get_flag("whole_pass_program")
        whole_pass_warned = False
        feeder = self._make_feeder(feeding)

        def _stage(data_batch):
            # obs: the STAGE leg of the stage/dispatch/block triple — on the
            # prefetch thread when async_load_data is on, so a merged
            # timeline shows feed overlapping compute (or failing to)
            with stat_timer("feed"), _obs.span("feed", cat="trainer"):
                fed = feeder(data_batch)
                if _chaos.fire("nan_batch"):
                    fed = _chaos.poison_batch(fed)
                return shard_batch(fed, self.mesh)

        # -- robustness plane: sentinel + rollback + preemption ----------
        self.preempted = False
        sentinel = None
        if _flags.get_flag("divergence_sentinel"):
            from paddle_tpu.robustness.sentinel import DivergenceSentinel

            sentinel = DivergenceSentinel.from_flags()
        # numerics sanitizer (analysis/num_sanitizer.py): armed via the
        # num_sanitizer flag / PADDLE_TPU_NUM_SANITIZER=1 it keeps a host
        # copy of each step's inputs and, when a step is sentinel-flagged,
        # re-executes it eqn-by-eqn to name the first non-finite-producing
        # op in a flight-recorder postmortem.  Unarmed: num_san stays
        # None and this loop is untouched (zero overhead, zero captures).
        num_san = None
        if _flags.get_flag("num_sanitizer"):
            from paddle_tpu.analysis.num_sanitizer import NumericsSanitizer

            num_san = NumericsSanitizer.for_trainer(self)
        recovery = manager = None
        if checkpoint_dir:
            from paddle_tpu import checkpoint as _ckpt
            from paddle_tpu.robustness.recovery import RecoveryCoordinator

            manager = _ckpt.CheckpointManager(checkpoint_dir)
            recovery = RecoveryCoordinator.from_flags(
                save_fn=lambda step, extra: self.save_checkpoint(
                    manager, step=step, extra=extra
                ),
                restore_fn=lambda: self._restore_latest_full(manager),
            )
            if checkpoint_period_batches is None:
                checkpoint_period_batches = _flags.get_flag(
                    "checkpoint_period_batches"
                )
            if not resume and manager.latest_step() is not None:
                _log.warning(
                    "checkpoint_dir %s already holds checkpoints from a "
                    "previous run but resume=False — a rollback could "
                    "restore stale state; use a fresh directory or resume",
                    checkpoint_dir,
                )
        elif resume:
            raise ValueError("resume=True requires checkpoint_dir")

        resume_extra = None
        skip_batches = 0
        first_pass = start_pass
        if resume:
            resume_extra = recovery.resume()
            if resume_extra is None:
                _log.warning(
                    "resume: no usable checkpoint under %s; starting fresh",
                    checkpoint_dir,
                )
            else:
                from paddle_tpu.robustness.preemption import clear_marker

                clear_marker(checkpoint_dir)
                first_pass = int(resume_extra.get("pass_id", start_pass))
                skip_batches = int(resume_extra.get("batch_id", -1)) + 1
                _log.info(
                    "resumed at step %d: pass %d, skipping %d already-"
                    "consumed batch(es)",
                    self._step_count, first_pass, skip_batches,
                )

        # epoch-aware feed switch: capture pass 1 into the device-resident
        # cache, replay it for every later pass (per-bucket batches keep
        # their own shapes, so this composes with use_bucketing).  A
        # single-pass run can never replay, so it must not pin the pass in
        # HBM — data echo still applies (it needs the batch in hand, not
        # the cache).
        pass_cache = None
        cache_requested = _flags.get_flag("cache_pass_in_mem") or bool(
            getattr(reader, "cache_pass_in_mem", False)
        )
        if cache_requested and resume_extra is not None and not (
            self._pass_cache is not None and self._pass_cache.ready
        ):
            # a resumed process cannot reconstruct the interrupted run's
            # cache: a mid-pass resume would capture only the pass's TAIL,
            # and even a pass-boundary resume would capture the wrong pass
            # (the original captured pass `first_pass` raw order and
            # replays every later pass shuffled) — stream the remaining
            # passes instead.  The trajectory still continues exactly from
            # the checkpoint, but epoch order past it is the streamed
            # reader's, not the cached replay's.
            _log.warning(
                "pass cache disabled on resume: the interrupted run's "
                "capture cannot be reconstructed mid-stream; streaming "
                "the remaining passes",
            )
            cache_requested = False
        echo_factor = (
            max(int(_flags.get_flag("data_echo_factor")), 1)
            if cache_requested
            else 1
        )
        if cache_requested:
            # the cache lives with its data source (reference
            # CACHE_PASS_IN_MEM keeps the pass for the provider's
            # lifetime): a later train() call with the SAME reader object
            # replays immediately — even its first pass pays zero H2D; a
            # different reader frees the stale pass before any re-capture
            prev = self._pass_cache
            if (
                prev is not None
                and prev.ready
                and self._pass_cache_reader is reader
            ):
                pass_cache = prev
            else:
                if prev is not None:
                    prev.drop()
                if num_passes > 1:
                    from paddle_tpu.reader.pass_cache import PassCache

                    pass_cache = PassCache.from_flags(
                        reader, seed=self._seed, echo_factor=echo_factor
                    )
        elif self._pass_cache is not None:
            # caching switched off since the last call: release the HBM
            self._pass_cache.drop()
        self._pass_cache = pass_cache
        self._pass_cache_reader = reader if pass_cache is not None else None
        if whole_pass and pass_cache is None:
            _log.warning(
                "whole_pass_program requested but no device-resident pass "
                "cache is available (needs cache_pass_in_mem or a "
                "CACHE_PASS_IN_MEM provider, num_passes > 1, and a "
                "non-resumed run); training stepwise",
            )

        def judge_step(pass_id, bid, cost, health, grad_norm, metrics, rows):
            """Per-step sentinel judging + report bookkeeping — the ONE
            copy shared by the stepwise loop and the epoch-program replay,
            so the bit-exact parity contract between the two paths cannot
            drift through a one-sided edit.  Reads the pass-local
            accumulators (pass_costs/pass_weights/pass_accums) from the
            enclosing scope; emits EndIteration; returns the sentinel
            verdict."""
            verdict = "ok"
            if sentinel is not None and health is not None:
                healthy = float(health) >= 0.5
                if healthy and grad_norm is not None:
                    global_stats.observe(
                        "robustness.grad_norm", float(grad_norm)
                    )
                verdict = sentinel.observe(cost, healthy)
            if log_period and self._step_count % log_period == 0:
                _log.info("pass %d batch %d cost %.6f", pass_id, bid, cost)
            evaluator: Dict[str, float] = {}
            if verdict == "ok":
                pass_costs.append(cost)
                pass_weights.append(rows)
                evaluator, accums = self._split_metrics(metrics)
                for k, v in accums.items():
                    pass_accums[k] = pass_accums.get(k, 0) + v
                evaluator.update(self._finalize(accums))
            event_handler(
                v2_event.EndIteration(pass_id, bid, cost, evaluator)
            )
            return verdict

        params, state = self.parameters.params, self.parameters.state
        opt_state = self._opt_state
        if recovery is not None:
            from paddle_tpu.robustness.preemption import (
                PreemptionGuard,
                write_marker,
            )

            guard = PreemptionGuard()
            if resume_extra is None and self._width_resolved:
                # rollback needs an anchor before the first batch lands —
                # otherwise an early divergence has nothing to restore.
                # (A dynamic-width network's weight shapes pin to the FIRST
                # batch; anchoring pre-resolution would restore placeholder
                # shapes into a loop that believes widths are resolved, so
                # its anchor waits for the first periodic checkpoint.)
                recovery.checkpoint(
                    self._step_count,
                    {
                        "step_count": self._step_count,
                        "pass_id": first_pass,
                        "batch_id": -1,
                    },
                )
        else:
            guard = None
        with (guard if guard is not None else nullcontext()):
          for pass_id in range(first_pass, start_pass + num_passes):
            skip = skip_batches if pass_id == first_pass else 0
            event_handler(v2_event.BeginPass(pass_id))
            if "pass" in opt_state:
                # pass_manual schedule: the optimizer reads the pass index
                # (reference PassManualLRS calcLearningRate(_, pass)); the
                # value is a traced scalar so updating it never recompiles
                import jax.numpy as jnp

                opt_state = {
                    **opt_state, "pass": jnp.asarray(pass_id, jnp.int32)
                }
            pass_costs: List[float] = []
            pass_weights: List[int] = []
            pass_accums: Dict[str, np.ndarray] = {}
            # rollback bookmarks: the pass report must not double-count a
            # retried window (truncate back to the last checkpoint's mark)
            costs_mark = 0
            accums_mark: Dict[str, np.ndarray] = {}
            use_epoch_prog = (
                whole_pass
                and pass_cache is not None
                and pass_cache.ready
                and recovery is None  # per-step rollback anchors need the
                and not skip          # host loop (and mid-pass resume too)
                and pass_cache.n_buckets == 1
                and not pass_cache.sample_shuffle
                and pass_cache.fits_stacked()
            )
            if (
                whole_pass and not use_epoch_prog and not whole_pass_warned
                and pass_cache is not None and pass_cache.ready
            ):
                whole_pass_warned = True
                reasons = []
                if recovery is not None:
                    reasons.append("checkpoint/rollback plane active")
                if skip:
                    reasons.append("mid-pass resume")
                if pass_cache.n_buckets != 1:
                    reasons.append(f"{pass_cache.n_buckets} shape buckets")
                if pass_cache.sample_shuffle:
                    reasons.append("sample_shuffle")
                if not pass_cache.fits_stacked():
                    reasons.append(
                        "stacked copy would exceed pass_cache_hbm_budget_mb"
                        " (needs 2x the cached pass)"
                    )
                _log.warning(
                    "whole_pass_program requested but replaying stepwise "
                    "(%s)", "; ".join(reasons) or "unknown",
                )
            if use_epoch_prog:
                # ONE host dispatch replays the whole cached pass on
                # device; the fetched per-step metrics then drive the SAME
                # event/sentinel bookkeeping the stepwise loop performs,
                # so trajectories and reports match it bit for bit
                params, state, opt_state, step_ms = (
                    self._dispatch_epoch_program(
                        pass_cache, pass_id, params, state, opt_state
                    )
                )
                n_steps = pass_cache.n_batches
                rows = _batch_rows(pass_cache.sample_batch())
                healths = step_ms.pop("health", None)
                grad_norms = step_ms.pop("grad_norm", None)
                for i in range(n_steps):
                    event_handler(v2_event.BeginIteration(pass_id, i))
                    self._step_count += 1
                    verdict = judge_step(
                        pass_id, i, float(step_ms["cost"][i]),
                        None if healths is None else healths[i],
                        None if grad_norms is None else grad_norms[i],
                        {k: v[i] for k, v in step_ms.items()}, rows,
                    )
                    if verdict == "diverged":
                        _log.error(
                            "divergence detected at pass %d batch %d inside "
                            "the whole-pass epoch program — no per-step "
                            "rollback in this mode (run with checkpoint_dir "
                            "for the stepwise path)", pass_id, i,
                        )
                        if sentinel is not None:
                            sentinel.reset()
                # the stepwise loop below sees an exhausted feed; the
                # shared pass-end bookkeeping runs as usual
                batches = iter(())
            elif pass_cache is not None and pass_cache.ready:
                # cached pass: device-resident replay, seed-reproducible
                # shuffle, zero H2D — the feeder/prefetcher never runs
                batches = pass_cache.epoch(pass_id)
                if skip:
                    batches = itertools.islice(batches, skip, None)
            else:
                raw = iter(reader())
                if skip:
                    # resume mid-pass: drain the already-consumed batches
                    # without staging them (the reader's own RNG stream
                    # advances exactly as the interrupted run's did)
                    for _ in range(skip):
                        next(raw, None)
                batches = (
                    prefetch(raw, _stage)
                    if async_load_data
                    else map(_stage, raw)
                )
                if pass_cache is not None and pass_cache.active:
                    batches = pass_cache.capture(batches)
                elif echo_factor > 1 and pass_id == first_pass:
                    # single-pass (or overflowed) run with data echo: train
                    # each transferred batch echo_factor times, retain none
                    batches = (
                        b for bb in batches for b in (bb,) * echo_factor
                    )
            live = iter(batches)
            replay: deque = deque()
            batch_id = skip - 1
            while True:
                if replay:
                    _, bid, batch = replay.popleft()
                    is_live = False
                else:
                    try:
                        batch = next(live)
                    except StopIteration:
                        break
                    batch_id += 1
                    bid = batch_id
                    is_live = True
                if not self._width_resolved:
                    # fc/matrix-projection weights over a whole-minibatch
                    # trans have a batch-dependent height; the FIRST batch
                    # this trainer sees pins it (resolve_dynamic_widths) —
                    # any later batch-size change hits an XLA shape error
                    # rather than silently re-drawing trained weights
                    self._width_resolved = True
                    params, chg = self.network.resolve_dynamic_widths(
                        params, batch
                    )
                    if chg:  # weight shapes moved: optimizer slots follow
                        opt_state = self.optimizer.init(params)
                event_handler(v2_event.BeginIteration(pass_id, bid))
                if self.compile_cache.observe(batch) and self._step_count:
                    # a NEW batch shape after warmup = a jit recompile; say
                    # so at debug level (the hit/miss counters aggregate in
                    # the StatSet table either way)
                    _log.debug(
                        "train batch %d brings new shape (distinct shapes "
                        "now %d)", bid, self.compile_cache.n_shapes,
                    )
                if is_live and recovery is not None:
                    recovery.record(pass_id, bid, batch)
                # obs: DISPATCH (issue the async jitted step) then BLOCK
                # (the host sync on the fetched cost scalar) — the split
                # that shows whether a slow step is compute or host-feed
                with stat_timer("train_step"), _obs.span(
                    "train_step", cat="trainer", p=pass_id, b=bid,
                ):
                    self._rng, step_rng = jax.random.split(self._rng)
                    if num_san is not None:
                        # the dispatch donates params/state/opt-state —
                        # copy the step's inputs out first or there is
                        # nothing left to re-execute when it goes bad
                        num_san.capture(
                            params, state, opt_state, batch, step_rng,
                            where=f"pass {pass_id} batch {bid}",
                        )
                    params, state, opt_state, metrics = self._run_train_step(
                        params, state, opt_state, batch, step_rng
                    )
                self._step_count += 1
                health = metrics.pop("health", None)
                grad_norm = metrics.pop("grad_norm", None)
                with _obs.span("block_fetch", cat="trainer", b=bid):
                    cost = float(metrics["cost"])
                if _chaos.fire("kill"):  # hard-preemption drill: no flush
                    _chaos.kill_self()
                if (
                    show_parameter_stats_period
                    and self._step_count % show_parameter_stats_period == 0
                ):
                    # reference TrainerInternal.cpp:83-110 per-param stats log
                    from paddle_tpu.utils.debug import (
                        format_parameter_stats,
                        parameter_stats,
                    )

                    _log.info(
                        "parameter stats @ step %d:\n%s",
                        self._step_count,
                        format_parameter_stats(parameter_stats(params)),
                    )
                # judging every step costs no extra sync here: this loop
                # fetches the cost scalar anyway (events need it) —
                # sentinel_check_interval only matters for fetch-free
                # multi-step dispatch loops (make_multi_train_step's folded
                # health/skipped_steps)
                verdict = judge_step(
                    pass_id, bid, cost, health, grad_norm, metrics,
                    _batch_rows(batch),
                )
                if num_san is not None and (
                    verdict in ("skip", "diverged")
                    or not np.isfinite(cost)
                ):
                    # name the op that went non-finite, not just the step
                    num_san.postmortem(
                        f"{verdict} at pass {pass_id} batch {bid}"
                    )
                if not is_live and not replay and recovery is not None:
                    recovery.replay_done()  # window re-applied cleanly
                if verdict == "diverged":
                    if recovery is None:
                        _log.error(
                            "divergence detected at pass %d batch %d but no "
                            "checkpoint_dir is set — cannot roll back",
                            pass_id, bid,
                        )
                        if sentinel is not None:
                            sentinel.reset()
                    else:
                        action, window = recovery.on_divergence()
                        if action != "none":
                            # restore_fn updated self.*; resync the loop's
                            # working refs and drop the undone bookkeeping
                            params = self.parameters.params
                            state = self.parameters.state
                            opt_state = self._opt_state
                            del pass_costs[costs_mark:]
                            del pass_weights[costs_mark:]
                            pass_accums = {
                                k: np.copy(v) for k, v in accums_mark.items()
                            }
                            if sentinel is not None:
                                sentinel.reset()
                            if action == "retry":
                                replay = deque(window)
                    continue
                if (
                    recovery is not None
                    and verdict == "ok"
                    and checkpoint_period_batches
                    and not recovery.replaying
                    and (sentinel is None or sentinel.steady)
                    and self._step_count % checkpoint_period_batches == 0
                ):
                    self.parameters.params, self.parameters.state = params, state
                    self._opt_state = opt_state
                    recovery.checkpoint(
                        self._step_count,
                        {
                            "step_count": self._step_count,
                            "pass_id": pass_id,
                            "batch_id": bid,
                        },
                    )
                    costs_mark = len(pass_costs)
                    accums_mark = {
                        k: np.copy(v) for k, v in pass_accums.items()
                    }
                if (
                    save_dir
                    and saving_period_by_batches
                    and (bid + 1) % saving_period_by_batches == 0
                ):
                    self.parameters.params, self.parameters.state = params, state
                    self._opt_state = opt_state
                    self.save_pass(save_dir, pass_id, batch_id=bid + 1)
                if guard is not None and guard.triggered:
                    # preemption: finish THIS step's bookkeeping, persist a
                    # synchronous final checkpoint + marker, hand back
                    self.parameters.params, self.parameters.state = params, state
                    self._opt_state = opt_state
                    extra = {
                        "step_count": self._step_count,
                        "pass_id": pass_id,
                        "batch_id": bid,
                        "preempted": True,
                    }
                    self.save_checkpoint(
                        manager, step=self._step_count, extra=extra
                    )
                    write_marker(
                        checkpoint_dir, {**extra, "signal": guard.signum}
                    )
                    self.preempted = True
                    _log.warning(
                        "preempted at pass %d batch %d (step %d): state "
                        "checkpointed under %s; restart with resume=True",
                        pass_id, bid, self._step_count, checkpoint_dir,
                    )
                    return
            # persist latest values so checkpoints/test see them
            self.parameters.params, self.parameters.state = params, state
            self._opt_state = opt_state
            pass_metrics = {
                # per-SAMPLE mean: weight each batch by its row count (batch
                # sizes vary across rungs under the bucketed feed)
                "mean_cost": float(np.average(pass_costs, weights=pass_weights))
                if pass_costs else 0.0
            }
            cc = self.compile_cache
            if cc.n_shapes > 1:
                # per-bucket dispatch table (reference prints its StatSet
                # per log period; shape traffic is the TPU-relevant stat)
                _log.info(
                    "pass %d bucket dispatch: %d distinct batch shapes, "
                    "%d compile misses / %d hits",
                    pass_id, cc.n_shapes, cc.misses, cc.hits,
                )
            pass_metrics.update(self._finalize(pass_accums))
            event_handler(v2_event.EndPass(pass_id, pass_metrics))
            if save_dir and (pass_id + 1 - start_pass) % saving_period == 0:
                self.save_pass(save_dir, pass_id)
            if recovery is not None:
                # pass boundary = a natural last-good anchor; position says
                # "start of the next pass" so resume never re-reads this one
                recovery.checkpoint(
                    self._step_count,
                    {
                        "step_count": self._step_count,
                        "pass_id": pass_id + 1,
                        "batch_id": -1,
                    },
                )
        self.parameters.params, self.parameters.state = params, state
        self._opt_state = opt_state

    # ------------------------------------------------------------------
    def elastic_model(self, decode):
        """Adapt this trainer to the elastic multi-process protocol
        (trainer/elastic.py): per-task jitted gradient contributions,
        fence-synchronized deterministic reduction, the trainer's own
        optimizer applied to the reduced update, and full-state sharded
        checkpoints.  ``decode(record_bytes) -> feed sample``."""
        from paddle_tpu.trainer.elastic import TrainerTaskModel

        return TrainerTaskModel(self, decode)

    # ------------------------------------------------------------------
    def test(
        self, reader: Callable, feeding=None, async_load_data: bool = True
    ) -> v2_event.TestResult:
        from paddle_tpu.reader.prefetch import prefetch

        feeder = self._make_feeder(feeding)
        costs: List[float] = []
        weights: List[int] = []
        sums: Dict[str, float] = {}
        accum_sums: Dict[str, np.ndarray] = {}
        n = 0.0
        stage = lambda b: shard_batch(feeder(b), self.mesh)
        batches = (
            prefetch(reader(), stage) if async_load_data
            else map(stage, reader())
        )
        for batch in batches:
            if not self._width_resolved:
                # never trained yet: the eval batch pins the dynamic widths
                # (a post-training batch-size change raises a shape error in
                # the step instead — see train())
                self._width_resolved = True
                p2, chg = self.network.resolve_dynamic_widths(
                    self.parameters.params, batch
                )
                if chg:
                    self.parameters.params = p2
                    self._opt_state = self.optimizer.init(p2)
            self._eval_cache.observe(batch)
            metrics = self._eval_step(
                self.parameters.params, self.parameters.state, batch
            )
            rows = _batch_rows(batch)
            costs.append(float(metrics["cost"]))
            weights.append(rows)
            scalars, accums = self._split_metrics(metrics)
            for k, v in scalars.items():
                sums[k] = sums.get(k, 0.0) + v * rows
            for k, v in accums.items():
                accum_sums[k] = accum_sums.get(k, 0) + v
            n += rows
        # per-sample means (batch sizes vary under the bucketed feed)
        avg = {k: v / max(n, 1) for k, v in sums.items()}
        avg.update(self._finalize(accum_sums))
        return v2_event.TestResult(
            avg,
            float(np.average(costs, weights=weights)) if costs else 0.0,
        )

    # ------------------------------------------------------------------
    def save_parameter_to_tar(self, f) -> None:
        self.parameters.to_tar(f)

    def save_pass(self, save_dir: str, pass_id: int, batch_id: Optional[int] = None) -> str:
        """Write pass-%05d/ with params.tar *and* one v1-format binary file
        per parameter (reference pass-%05d dirs, paddle/trainer/ParamUtil.cpp;
        batch checkpoints get a -batch-%d suffix like Trainer.cpp:454-465)."""
        from paddle_tpu import checkpoint as ckpt

        name = f"pass-{pass_id:05d}"
        if batch_id is not None:
            name += f"-batch-{batch_id}"
        d = os.path.join(save_dir, name)
        os.makedirs(d, exist_ok=True)
        with open(os.path.join(d, "params.tar"), "wb") as f:
            self.parameters.to_tar(f)
        ckpt.save_parameter_dir(self.parameters, d)
        return d

    def load_pass(self, save_dir: str, pass_id: int) -> None:
        """Resume parameter values from a pass dir (reference
        --init_model_path / --start_pass, Trainer.cpp:224-253)."""
        from paddle_tpu import checkpoint as ckpt

        ckpt.load_parameter_dir(
            self.parameters, os.path.join(save_dir, f"pass-{pass_id:05d}")
        )
        # Restored values land with default placement; re-apply the model-axis
        # sharding (no-op when not model-sharded) so the next step doesn't
        # recompile against replicated tables.
        self._reshard_after_restore()

    # -- full-state checkpoints (params + layer state + optimizer state) --
    def _full_state(self):
        return {
            "params": self.parameters.params,
            "state": self.parameters.state,
            "opt_state": self._opt_state,
            "rng": self._rng,
        }

    def save_checkpoint(
        self,
        manager,
        step: Optional[int] = None,
        async_: bool = False,
        extra: Optional[Dict[str, Any]] = None,
    ) -> None:
        """Write params + optimizer state + counters through a
        checkpoint.CheckpointManager (the Go-pserver-style full checkpoint,
        reference go/pserver/service.go:244-303 — sans pserver).  ``extra``
        merges into the meta's extra dict (the recovery plane stores the
        pass/batch position there)."""
        manager.save(
            step if step is not None else self._step_count,
            self._full_state(),
            extra={"step_count": self._step_count, **(extra or {})},
            async_=async_,
        )

    def _apply_restored(self, tree, extra) -> None:
        self.parameters.params = tree["params"]
        self.parameters.state = tree["state"]
        self._opt_state = tree["opt_state"]
        import jax.numpy as jnp

        self._rng = jnp.asarray(tree["rng"])
        self._step_count = int(extra.get("step_count", self._step_count))
        self._reshard_after_restore()

    def restore_checkpoint(self, manager, step: Optional[int] = None) -> bool:
        """Restore the latest (or given) checkpoint; returns False when the
        directory holds none."""
        if step is None:
            restored = manager.restore_latest(self._full_state())
            if restored is None:
                return False
            _, tree, extra = restored
        else:
            tree, extra = manager.restore(step, self._full_state())
        self._apply_restored(tree, extra)
        return True

    def _restore_latest_full(self, manager) -> Optional[Dict[str, Any]]:
        """restore_checkpoint returning the checkpoint's ``extra`` dict (the
        recovery/resume position plane) — None when nothing restorable; a
        torn/corrupt newest checkpoint falls back to the previous retained
        one inside the manager."""
        restored = manager.restore_latest(self._full_state())
        if restored is None:
            return None
        _, tree, extra = restored
        self._apply_restored(tree, extra)
        return dict(extra)

    def _reshard_after_restore(self) -> None:
        """Checkpoints come back as host arrays; re-apply the model-axis
        placement so the inferred-sharding step doesn't recompile with a
        replicated (possibly OOM-sized) table."""
        if not self._model_sharded:
            return
        from paddle_tpu.parallel.sharding import shard_params

        self.parameters.params = shard_params(
            self.network, self.parameters.params, self.mesh
        )
        param_names = set(self.parameters.params)
        self._opt_state = {
            k: shard_params(self.network, v, self.mesh)
            if isinstance(v, dict) and set(v) <= param_names
            else v
            for k, v in self._opt_state.items()
        }
