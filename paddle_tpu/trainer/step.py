"""Train/eval step builders — the replacement for the reference's
TrainerInternal::trainOneBatch + GradientMachine forward/backward + per-param
updater callback pipeline (reference: paddle/trainer/TrainerInternal.cpp:66-190).

One call = one jitted XLA computation: forward, jax.grad backward, gradient
psum across the data mesh axis (implicit via sharding), optimizer update, and
metric reduction all fuse into a single program with donated buffers, so
parameters update in place on device — no host round-trip per batch (the
reference crosses Python↔SWIG each batch, v2/trainer.py:145-161).
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from paddle_tpu.core.batch import DEFAULT_LADDER, canonicalize_batch
from paddle_tpu.core.compiler import (
    CompiledNetwork,
    CompileShapeCache,
    NetState,
    Params,
)
from paddle_tpu.optimizer import Optimizer, OptState
from paddle_tpu.parallel.mesh import DATA_AXIS


def build_prune_masks(network: CompiledNetwork, params: Params) -> Optional[Params]:
    """Static pruning masks (reference StaticPruningHook,
    ParameterUpdaterHook.cpp:39): for every layer whose ParamAttr declared a
    'pruning' hook, keep the largest (1 - sparsity_ratio) fraction of each
    parameter by INITIAL magnitude; the train step re-applies the mask after
    every update.  Returns None when nothing prunes."""
    masks: Params = {}
    for name, conf in network.topology.layers.items():
        ratio = conf.attr("prune_sparsity")
        if not ratio:
            continue
        # a layer sharing parameters by name stores them under the owner
        name = network._param_owner.get(name, name)
        if name not in params or name in masks:
            continue

        def mask_leaf(v, r=ratio):
            flat = jnp.abs(v).reshape(-1)
            k = max(int(flat.shape[0] * (1.0 - r)), 1)
            thresh = jax.lax.top_k(flat, k)[0][-1]
            return (jnp.abs(v) >= thresh).astype(v.dtype)

        # hooks attach to the WEIGHT parameter (the reference's ParamAttr is
        # per-parameter; bias has its own attr) — prune w* leaves only
        masks[name] = {
            k: (mask_leaf(v) if k.startswith("w") else jnp.ones_like(v))
            for k, v in params[name].items()
        }
    return masks or None


def apply_prune_masks(params: Params, masks: Optional[Params]) -> Params:
    if not masks:
        return params
    out = dict(params)
    for name, m in masks.items():
        out[name] = jax.tree_util.tree_map(
            lambda p, mk: p * mk.astype(p.dtype), params[name], m
        )
    return out


def _global_norm(grads) -> jnp.ndarray:
    """float32 l2 norm over every gradient leaf — one fused reduction; any
    NaN/Inf leaf makes the result non-finite, so finiteness of this single
    scalar is the whole-tree health signal."""
    leaves = jax.tree_util.tree_leaves(grads)
    if not leaves:
        return jnp.asarray(0.0, jnp.float32)
    return jnp.sqrt(
        sum(jnp.sum(jnp.square(g.astype(jnp.float32))) for g in leaves)
    )


def _sentinel_enabled(sentinel: Optional[bool]) -> bool:
    if sentinel is not None:
        return bool(sentinel)
    from paddle_tpu.utils.flags import get_flag

    return bool(get_flag("divergence_sentinel"))


def _quantized_enabled(quantized: Optional[bool]) -> bool:
    if quantized is not None:
        return bool(quantized)
    from paddle_tpu.utils.flags import get_flag

    return bool(get_flag("quantized_allreduce"))


def _train_step_body(
    network: CompiledNetwork,
    optimizer: Optimizer,
    extra_metrics=None,
    prune_masks: Optional[Params] = None,
    sentinel: Optional[bool] = None,
):
    """The un-jitted single-step computation shared by make_train_step and
    make_multi_train_step: forward, grad, optimizer update, metrics.

    sentinel (None = the ``divergence_sentinel`` flag): fuse a finiteness
    check of the loss and the gradient global-norm into the step.  The
    ``health`` flag (1.0 = finite) rides the metrics — no extra host sync —
    and an unhealthy step passes params / layer state / optimizer state
    through UNCHANGED (per-leaf select), so one NaN batch is a skipped step,
    not a corrupted run (robustness/sentinel.py is the host-side judge)."""
    guard = _sentinel_enabled(sentinel)

    def step(params, state, opt_state, batch, rng):
        def loss_fn(p):
            return network.cost(p, batch, state=state, rng=rng, train=True)

        (cost, (outs, new_state)), grads = jax.value_and_grad(
            loss_fn, has_aux=True
        )(params)
        new_params, new_opt_state = optimizer.update(grads, opt_state, params)
        new_params = apply_prune_masks(new_params, prune_masks)
        metrics = {"cost": cost}
        if guard:
            grad_norm = _global_norm(grads)
            healthy = jnp.isfinite(cost.astype(jnp.float32)) & jnp.isfinite(
                grad_norm
            )

            def keep(new, old):
                return jax.tree_util.tree_map(
                    lambda n, o: jnp.where(healthy, n, o), new, old
                )

            new_params = keep(new_params, params)
            new_state = keep(new_state, state)
            new_opt_state = keep(new_opt_state, opt_state)
            metrics["health"] = healthy.astype(jnp.float32)
            metrics["grad_norm"] = grad_norm
        if extra_metrics is not None:
            metrics.update(extra_metrics(outs))
        return new_params, new_state, new_opt_state, metrics

    return step


def make_quantized_train_step(
    network: CompiledNetwork,
    optimizer: Optimizer,
    mesh: Mesh,
    extra_metrics: Optional[
        Callable[[Dict[str, Any]], Dict[str, jnp.ndarray]]
    ] = None,
    prune_masks: Optional[Params] = None,
    sentinel: Optional[bool] = None,
):
    """The ``quantized_allreduce`` train step: same signature and metric
    surface as :func:`make_train_step`, but the data-axis gradient
    reduction is an EXPLICIT block-scaled quantized collective
    (ops/quantize.py :func:`~paddle_tpu.ops.quantize.quantized_psum`)
    instead of the implicit f32 psum XLA SPMD inserts.

    Structure: a ``shard_map`` over the (pure data-parallel) mesh computes
    per-shard gradients, then psums the int8/bf16 payload blocks AND their
    f32 scales side-by-side — the exact region shape rule N405 certifies —
    and dequantizes to the gradient mean; cost pmeans at f32; per-row
    layer outputs reassemble across the data axis so ``extra_metrics``
    still sees the whole batch.  The optimizer update, prune masks and the
    divergence sentinel run on the reduced (replicated) gradients exactly
    as in the baseline body, so everything downstream of the allreduce is
    shared.

    Payload dtype / block size / stochastic rounding come from the
    ``quantize_*`` flags at build time."""
    import numpy as np

    from paddle_tpu.ops.quantize import quantized_psum
    from paddle_tpu.parallel.mesh import shard_map
    from paddle_tpu.utils.flags import get_flag

    if mesh.shape.get("model", 1) != 1:
        raise ValueError(
            "quantized_allreduce needs a pure data-parallel mesh "
            f"(model axis is {mesh.shape.get('model')}); quantize only "
            "the data-axis gradient reduction"
        )
    guard = _sentinel_enabled(sentinel)
    payload_dtype = jnp.dtype(str(get_flag("quantize_payload_dtype")))
    block = int(get_flag("quantize_block_size"))
    stochastic = bool(get_flag("quantize_stochastic_rounding"))
    # collapse to a 1-axis data mesh over the same devices in the same
    # order: shard_map wants every mesh axis named in its specs
    qmesh = Mesh(np.array(mesh.devices).reshape(-1), (DATA_AXIS,))

    def shard_grads(params, state, batch, rng):
        def loss_fn(p):
            return network.cost(p, batch, state=state, rng=rng, train=True)

        (cost, (outs, new_state)), grads = jax.value_and_grad(
            loss_fn, has_aux=True
        )(params)
        grads = quantized_psum(
            grads, DATA_AXIS, block=block, payload_dtype=payload_dtype,
            stochastic=stochastic, rng=(rng if stochastic else None),
            mean=True,
        )
        cost = jax.lax.pmean(cost.astype(jnp.float32), DATA_AXIS)
        return grads, cost, new_state, outs

    smapped = shard_map(
        shard_grads, mesh=qmesh,
        in_specs=(P(), P(), P(DATA_AXIS), P()),
        out_specs=(P(), P(), P(), P(DATA_AXIS)),
        check_vma=False,  # per-shard state/dropout outs: replication is by
        # construction of the deterministic update, not provable statically
    )

    def step(params, state, opt_state, batch, rng):
        grads, cost, new_state, outs = smapped(params, state, batch, rng)
        new_params, new_opt_state = optimizer.update(grads, opt_state, params)
        new_params = apply_prune_masks(new_params, prune_masks)
        metrics = {"cost": cost}
        if guard:
            grad_norm = _global_norm(grads)
            healthy = jnp.isfinite(cost) & jnp.isfinite(grad_norm)

            def keep(new, old):
                return jax.tree_util.tree_map(
                    lambda n, o: jnp.where(healthy, n, o), new, old
                )

            new_params = keep(new_params, params)
            new_state = keep(new_state, state)
            new_opt_state = keep(new_opt_state, opt_state)
            metrics["health"] = healthy.astype(jnp.float32)
            metrics["grad_norm"] = grad_norm
        if extra_metrics is not None:
            metrics.update(extra_metrics(outs))
        return new_params, new_state, new_opt_state, metrics

    repl = NamedSharding(qmesh, P())
    batch_sh = NamedSharding(qmesh, P(DATA_AXIS))
    return jax.jit(
        step,
        donate_argnums=(0, 1, 2),
        in_shardings=(repl, repl, repl, batch_sh, repl),
        out_shardings=(repl, repl, repl, repl),
    )


def make_train_step(
    network: CompiledNetwork,
    optimizer: Optimizer,
    mesh: Optional[Mesh] = None,
    extra_metrics: Optional[
        Callable[[Dict[str, Any]], Dict[str, jnp.ndarray]]
    ] = None,
    infer_param_shardings: bool = False,
    prune_masks: Optional[Params] = None,
    sentinel: Optional[bool] = None,
    quantized: Optional[bool] = None,
):
    """Returns jitted
    (params, state, opt_state, batch, rng) ->
        (params, state, opt_state, metrics).

    With infer_param_shardings=True the params/opt_state shardings follow the
    argument placement (use parallel.sharding.shard_params first) so
    model-axis-sharded tables stay sharded through the update; otherwise
    params are pinned replicated.  sentinel: see _train_step_body.

    quantized (None = the ``quantized_allreduce`` flag): with a data-
    parallel mesh, route the gradient reduction through the block-scaled
    quantized collective (:func:`make_quantized_train_step`).  OFF is the
    byte-for-byte historical path — no graph change whatsoever.  Without
    a mesh there is no cross-device reduction to quantize and the flag is
    a no-op."""
    if (
        _quantized_enabled(quantized)
        and mesh is not None
        and not infer_param_shardings
    ):
        return make_quantized_train_step(
            network, optimizer, mesh, extra_metrics,
            prune_masks=prune_masks, sentinel=sentinel,
        )
    step = _train_step_body(
        network, optimizer, extra_metrics, prune_masks, sentinel=sentinel
    )

    if mesh is None or infer_param_shardings:
        # No mesh, or sharding flows from the arguments (batch via
        # shard_batch, params via shard_params); XLA SPMD inserts the
        # psum/all-gathers.
        return jax.jit(step, donate_argnums=(0, 1, 2))

    repl = NamedSharding(mesh, P())
    batch_sh = NamedSharding(mesh, P(DATA_AXIS))
    return jax.jit(
        step,
        donate_argnums=(0, 1, 2),
        in_shardings=(repl, repl, repl, batch_sh, repl),
        out_shardings=(repl, repl, repl, repl),
    )


def make_multi_train_step(
    network: CompiledNetwork,
    optimizer: Optimizer,
    n_steps: int,
    mesh: Optional[Mesh] = None,
    extra_metrics: Optional[
        Callable[[Dict[str, Any]], Dict[str, jnp.ndarray]]
    ] = None,
    prune_masks: Optional[Params] = None,
    sentinel: Optional[bool] = None,
):
    """``n_steps`` train steps in ONE dispatch: lax.scan of the single-step
    body over batches stacked on a leading [n_steps, ...] axis.

    Returns jitted (params, state, opt_state, stacked_batches, rng) ->
    (params, state, opt_state, last-step metrics).

    Why: every dispatch crosses the host->device boundary once; on a
    tunneled/remote device (or any setup where dispatch latency rivals step
    time — the smallnet/LSTM benches measure ~6 ms of fixed per-call cost)
    the loop measures the transport, not the chip.  Folding K steps
    amortizes that cost K-fold, which is also how a production input
    pipeline behaves locally (async dispatch keeps the device queue full).
    The reference's TrainerBenchmark loop has no such boundary — its
    trainOneBatch is a C++ call.

    With the sentinel on, each scanned step skips independently on device;
    the returned metrics fold the whole dispatch: ``health`` is the MIN over
    the K steps and ``skipped_steps`` counts the dropped ones, so a fetch
    every K dispatches still sees every skip."""
    step = _train_step_body(
        network, optimizer, extra_metrics, prune_masks, sentinel=sentinel
    )

    def multi(params, state, opt_state, batches, rng):
        rngs = jax.random.split(rng, n_steps)

        def body(carry, xs):
            p, s, o = carry
            b, r = xs
            p, s, o, m = step(p, s, o, b, r)
            return (p, s, o), m

        (p, s, o), ms = jax.lax.scan(
            body, (params, state, opt_state), (batches, rngs)
        )
        out = jax.tree_util.tree_map(lambda x: x[-1], ms)
        if "health" in ms:
            out["health"] = jnp.min(ms["health"])
            out["skipped_steps"] = jnp.sum(1.0 - ms["health"])
        return p, s, o, out

    if mesh is None:
        return jax.jit(multi, donate_argnums=(0, 1, 2))
    repl = NamedSharding(mesh, P())
    batch_sh = NamedSharding(mesh, P(None, DATA_AXIS))
    return jax.jit(
        multi,
        donate_argnums=(0, 1, 2),
        in_shardings=(repl, repl, repl, batch_sh, repl),
        out_shardings=(repl, repl, repl, repl),
    )


def make_train_carry(params, state, opt_state, rng):
    """The explicit carried-state pytree of the whole-pass epoch program:
    params, layer state, optimizer state, the RNG chain, the divergence-
    sentinel health scalars, and the on-device metric accumulators as ONE
    tree — the step/feed/sentinel interface the serving plane and elastic
    residency also consume.  ``health_min``/``skipped`` fold the sentinel
    across the epoch; ``cost_sum``/``ok_steps`` accumulate the healthy-step
    cost so a fetch-free multi-epoch driver still has a running mean."""
    import jax.numpy as jnp

    return {
        "params": params,
        "state": state,
        "opt_state": opt_state,
        "rng": rng,
        "health_min": jnp.asarray(1.0, jnp.float32),
        "skipped": jnp.asarray(0.0, jnp.float32),
        "cost_sum": jnp.asarray(0.0, jnp.float32),
        "ok_steps": jnp.asarray(0.0, jnp.float32),
    }


def split_train_carry(carry):
    """(params, state, opt_state, rng) back out of an epoch-program carry."""
    return carry["params"], carry["state"], carry["opt_state"], carry["rng"]


def make_epoch_program(
    network: CompiledNetwork,
    optimizer: Optimizer,
    mesh: Optional[Mesh] = None,
    extra_metrics: Optional[
        Callable[[Dict[str, Any]], Dict[str, jnp.ndarray]]
    ] = None,
    prune_masks: Optional[Params] = None,
    sentinel: Optional[bool] = None,
):
    """A WHOLE training pass as one jitted on-device program:
    ``(carry, stacked_batches, perm) -> (carry, per_step_metrics)``.

    The epoch loop moves inside the XLA computation (the TF paper's
    keep-the-iteration-loop-in-the-runtime argument; arXiv:1605.08695
    §4.4): ``stacked_batches`` is the device-resident pass cache stacked on
    a leading [N, ...] axis in CAPTURE order (built once, reused every
    epoch), ``perm`` is this epoch's shuffle permutation, and the gather +
    ``lax.scan`` of the shared step body replace O(steps/K) host dispatches
    with exactly ONE per epoch.

    Bit-exact parity with the stepwise SGD loop is a contract, not an
    accident: the carry chains ``rng, step_rng = jax.random.split(rng)``
    per step — the same split sequence SGD.train performs on the host — so
    params, metrics, and the sentinel's skip decisions match the stepwise
    path bit for bit (tests/test_epoch_program.py).  Per-step metrics come
    back stacked [N, ...] so the host replays its event/bookkeeping loop
    from one fetch.

    Only the carry is donated: the stacked batches ARE the pass cache —
    donating them would free HBM the next epoch replays from."""
    step = _train_step_body(
        network, optimizer, extra_metrics, prune_masks, sentinel=sentinel
    )

    def epoch(carry, batches, perm):
        batches = jax.tree_util.tree_map(lambda x: x[perm], batches)

        def body(c, b):
            rng, step_rng = jax.random.split(c["rng"])
            p, s, o, m = step(
                c["params"], c["state"], c["opt_state"], b, step_rng
            )
            h = m.get("health", jnp.asarray(1.0, jnp.float32))
            new_c = {
                "params": p,
                "state": s,
                "opt_state": o,
                "rng": rng,
                "health_min": jnp.minimum(c["health_min"], h),
                "skipped": c["skipped"] + (1.0 - h),
                "cost_sum": c["cost_sum"]
                + jnp.where(h >= 0.5, m["cost"].astype(jnp.float32), 0.0),
                "ok_steps": c["ok_steps"] + h,
            }
            return new_c, m

        return jax.lax.scan(body, carry, batches)

    if mesh is None:
        return jax.jit(epoch, donate_argnums=(0,))
    repl = NamedSharding(mesh, P())
    batch_sh = NamedSharding(mesh, P(None, DATA_AXIS))
    return jax.jit(
        epoch,
        donate_argnums=(0,),
        in_shardings=(repl, batch_sh, repl),
        out_shardings=(repl, repl),
    )


def make_bucketed_train_step(
    network: CompiledNetwork,
    optimizer: Optimizer,
    mesh: Optional[Mesh] = None,
    extra_metrics: Optional[
        Callable[[Dict[str, Any]], Dict[str, jnp.ndarray]]
    ] = None,
    infer_param_shardings: bool = False,
    prune_masks: Optional[Params] = None,
    ladder=DEFAULT_LADDER,
    cache: Optional[CompileShapeCache] = None,
):
    """A train step that enforces the bucket-shape contract at the dispatch
    boundary: every incoming batch is canonicalized to the shape ladder
    (core.batch.canonicalize_batch) BEFORE it reaches jax.jit, so the
    executable cache is keyed per ladder rung — bounded recompiles however
    lengths are distributed — and every dispatch is accounted against a
    :class:`~paddle_tpu.core.compiler.CompileShapeCache` (hit/miss counters
    in the StatSet plane).

    Returns ``(step, cache)``; ``step`` has the make_train_step signature.
    Feeds that already ladder their shapes (DataFeeder(ladder=...)) pay only
    the shape check; anything else — hand-built batches, exotic readers —
    gets padded up to the nearest rung here."""
    inner = make_train_step(
        network, optimizer, mesh, extra_metrics,
        infer_param_shardings=infer_param_shardings,
        prune_masks=prune_masks,
    )
    if cache is None:
        cache = CompileShapeCache("train_step")

    def step(params, state, opt_state, batch, rng):
        batch = canonicalize_batch(batch, ladder)
        cache.observe(batch)
        return inner(params, state, opt_state, batch, rng)

    return step, cache


def make_grad_step(
    network: CompiledNetwork,
    mesh: Optional[Mesh] = None,
    infer_param_shardings: bool = False,
):
    """Returns jitted ``(params, state, batch, rng) -> (grads, cost)`` —
    the gradient HALF of the train step, with no optimizer update fused in.

    This is the unit of work of the elastic multi-process trainer
    (trainer/elastic.py): each leased data-shard task contributes one
    deterministic gradient tree, the fleet reduces the contributions in
    task-id order at the pass fence, and every process applies the SAME
    reduced update — so the result is bit-identical however tasks were
    distributed, which is what lets a killed worker's shards requeue to
    survivors without perturbing the trajectory.  Layer-state updates (BN
    statistics etc.) from the forward pass are intentionally dropped:
    pass-synchronous reduction has no per-step state stream to thread."""

    def gstep(params, state, batch, rng):
        def loss_fn(p):
            return network.cost(p, batch, state=state, rng=rng, train=True)

        (cost, _aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
        return grads, cost

    if mesh is None or infer_param_shardings:
        return jax.jit(gstep)
    repl = NamedSharding(mesh, P())
    batch_sh = NamedSharding(mesh, P(DATA_AXIS))
    return jax.jit(
        gstep,
        in_shardings=(repl, repl, batch_sh, repl),
        out_shardings=repl,
    )


def make_eval_step(
    network: CompiledNetwork,
    mesh: Optional[Mesh] = None,
    extra_metrics: Optional[
        Callable[[Dict[str, Any]], Dict[str, jnp.ndarray]]
    ] = None,
    infer_param_shardings: bool = False,
):
    """(params, state, batch) -> metrics (test-time, no dropout/BN update)."""

    def step(params, state, batch):
        cost, (outs, _) = network.cost(params, batch, state=state, train=False)
        metrics = {"cost": cost}
        if extra_metrics is not None:
            metrics.update(extra_metrics(outs))
        return metrics

    if mesh is None or infer_param_shardings:
        return jax.jit(step)
    repl = NamedSharding(mesh, P())
    batch_sh = NamedSharding(mesh, P(DATA_AXIS))
    return jax.jit(
        step, in_shardings=(repl, repl, batch_sh), out_shardings=repl
    )


def make_forward_fn(network: CompiledNetwork, output_names=None):
    """Inference forward returning selected layer outputs (the capi /
    Inference equivalent, reference paddle/capi/gradient_machine.h:60)."""

    @functools.partial(jax.jit, static_argnames=("train",))
    def fwd(params, state, batch, train=False):
        outs, _ = network.apply(params, batch, state=state, train=train)
        names = output_names or network.topology.output_names
        return {n: outs[n].data for n in names}

    return fwd
