"""MNIST (reference: python/paddle/v2/dataset/mnist.py) — yields
(image[784] float in [-1,1], label int).  Loads the real IDX files from the
cache dir when present; otherwise serves a deterministic synthetic set with
class-dependent structure (so LeNet demonstrably learns on it)."""

from __future__ import annotations

import gzip
import os
import struct

import numpy as np

from paddle_tpu.dataset import common

SYNTH_TRAIN = 4096
SYNTH_TEST = 512


def _load_idx_images(path: str) -> np.ndarray:
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
        assert magic == 2051
        data = np.frombuffer(f.read(), dtype=np.uint8).reshape(n, rows * cols)
    return data.astype(np.float32) / 127.5 - 1.0


def _load_idx_labels(path: str) -> np.ndarray:
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        magic, n = struct.unpack(">II", f.read(8))
        assert magic == 2049
        return np.frombuffer(f.read(), dtype=np.uint8).astype(np.int64)


def _real_files(prefix: str):
    img = common.data_path("mnist", f"{prefix}-images-idx3-ubyte.gz")
    lbl = common.data_path("mnist", f"{prefix}-labels-idx1-ubyte.gz")
    if os.path.exists(img) and os.path.exists(lbl):
        return img, lbl
    return None


def _synthetic(n: int, seed: int):
    """Class-structured synthetic digits: each class k gets a fixed random
    prototype; samples are prototype + noise.  Linearly separable enough to
    validate end-to-end learning."""
    protos = (
        np.random.RandomState(1234).uniform(-1, 1, size=(10, 784)).astype(np.float32)
    )
    rng = np.random.RandomState(seed)
    labels = rng.randint(0, 10, size=n).astype(np.int64)
    imgs = protos[labels] + 0.3 * rng.randn(n, 784).astype(np.float32)
    return np.clip(imgs, -1, 1), labels


def _reader(imgs: np.ndarray, labels: np.ndarray):
    def reader():
        for i in range(imgs.shape[0]):
            yield imgs[i], int(labels[i])

    return reader


def train():
    files = _real_files("train")
    if files:
        return _reader(_load_idx_images(files[0]), _load_idx_labels(files[1]))
    return _reader(*_synthetic(SYNTH_TRAIN, seed=7))


def test():
    files = _real_files("t10k")
    if files:
        return _reader(_load_idx_images(files[0]), _load_idx_labels(files[1]))
    return _reader(*_synthetic(SYNTH_TEST, seed=11))
def convert(path):
    """Export to recordio shards for the master (reference mnist.py:118)."""
    common.convert(path, train(), 1000, "mnist_train")
    common.convert(path, test(), 1000, "mnist_test")
