"""Dataset cache helpers (reference: python/paddle/v2/dataset/common.py)."""

from __future__ import annotations

import os

DATA_HOME = os.environ.get(
    "PADDLE_TPU_DATA", os.path.expanduser("~/.cache/paddle_tpu/dataset")
)


def data_path(*parts: str) -> str:
    return os.path.join(DATA_HOME, *parts)


def exists(*parts: str) -> bool:
    return os.path.exists(data_path(*parts))


def synth_two_class_docs(
    n: int,
    vocab: int,
    seed: int,
    min_len: int,
    max_len: int,
    signal: float = 0.8,
    word_fmt: str = "w{}",
):
    """Deterministic two-class word corpus: positive docs draw from the low
    half of the vocab, negative from the high half, with (1-signal) crossover
    noise — separable enough for a text classifier to learn.  Shared by the
    imdb/sentiment synthetic fallbacks."""
    import numpy as np

    rng = np.random.RandomState(seed)
    docs = []
    for _ in range(n):
        label = int(rng.randint(2))
        lo, hi = (0, vocab // 2) if label else (vocab // 2, vocab)
        length = int(rng.randint(min_len, max_len))
        ids = np.where(
            rng.rand(length) < signal,
            rng.randint(lo, hi, size=length),
            rng.randint(0, vocab, size=length),
        )
        docs.append(([word_fmt.format(int(i)) for i in ids], label))
    return docs


def dict_from_freq(freq, cutoff: int = 0):
    """word → id from a frequency table, most frequent first (deterministic
    tie-break on the word)."""
    if cutoff:
        freq = {w: c for w, c in freq.items() if c > cutoff}
    ordered = sorted(freq.items(), key=lambda x: (-x[1], x[0]))
    return {w: i for i, (w, _) in enumerate(ordered)}


def build_word_dict(docs, cutoff: int = 0):
    """word → id from an iterable of token lists (see dict_from_freq)."""
    freq = {}
    for words in docs:
        for w in words:
            freq[w] = freq.get(w, 0) + 1
    return dict_from_freq(freq, cutoff)


def convert(output_path, reader, line_count, name_prefix, seed: int = 0):
    """Export any reader to recordio shards the elastic master serves
    (reference python/paddle/v2/dataset/common.py:187 ``convert``; every
    dataset module exposes a ``convert(path)`` built on it).

    Samples are pickled one-per-record into ``<output_path>/<name_prefix>-
    %05d`` shard files, ``line_count`` samples per shard, each shard
    shuffled before writing (the reference's max_lines_to_shuffle).  Feed
    the shards to ``master.Service.set_dataset([pattern])`` and read them
    back through ``reader.creator.cloud_reader`` (or ``recordio_local``
    without a master).

    Returns the list of shard paths written."""
    import pickle
    import random

    from paddle_tpu.io import recordio

    if line_count < 1:
        raise ValueError(f"line_count must be >= 1, got {line_count}")
    os.makedirs(output_path, exist_ok=True)
    rng = random.Random(seed)
    paths = []

    def write_shard(samples):
        path = os.path.join(output_path, f"{name_prefix}-{len(paths):05d}")
        rng.shuffle(samples)
        recordio.write_records(
            path, (pickle.dumps(s, protocol=pickle.HIGHEST_PROTOCOL)
                   for s in samples),
        )
        paths.append(path)

    buf = []
    for sample in reader():
        buf.append(sample)
        if len(buf) >= line_count:
            write_shard(buf)
            buf = []
    if buf or not paths:  # an empty reader still writes one (empty) shard
        write_shard(buf)
    return paths
