"""Dataset cache helpers (reference: python/paddle/v2/dataset/common.py)."""

from __future__ import annotations

import hashlib
import logging
import os
import random
import time
from typing import Callable, Optional

_log = logging.getLogger("paddle_tpu.dataset")

DATA_HOME = os.environ.get(
    "PADDLE_TPU_DATA", os.path.expanduser("~/.cache/paddle_tpu/dataset")
)


def data_path(*parts: str) -> str:
    return os.path.join(DATA_HOME, *parts)


def exists(*parts: str) -> bool:
    return os.path.exists(data_path(*parts))


def md5file(path: str) -> str:
    h = hashlib.md5()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def _urllib_fetch(url: str, dest: str) -> None:
    """Stream ``url`` into ``dest`` (the default fetcher; tests inject a
    fake via ``download(fetch_fn=...)``)."""
    from urllib.request import urlopen

    with urlopen(url, timeout=60) as r, open(dest, "wb") as f:
        for chunk in iter(lambda: r.read(1 << 20), b""):
            f.write(chunk)


def download(
    url: str,
    module: str,
    md5sum: Optional[str] = None,
    save_name: Optional[str] = None,
    max_retries: int = 5,
    backoff: float = 0.5,
    fetch_fn: Optional[Callable[[str, str], None]] = None,
    rng: Optional[random.Random] = None,
    sleep: Callable[[float], None] = time.sleep,
) -> str:
    """Fetch ``url`` into the dataset cache with bounded retry (reference
    python/paddle/v2/dataset/common.py:37 ``download`` — which dies on the
    first flaky HTTP read; this one doesn't).

    Robustness contract:

    * **bounded retry with exponential backoff + jitter** — up to
      ``max_retries`` attempts, sleeping ``backoff * 2**attempt`` seconds
      plus up to 25% jitter between them (the jitter keeps a fleet of
      trainers from re-hammering a recovering mirror in lockstep);
    * **partial-file cleanup** — every attempt writes to a ``.part`` file
      that is removed on failure and atomically renamed into place only
      after the (optional) md5 check passes, so a torn download can never
      be mistaken for the dataset by the next run;
    * an md5 mismatch counts as a failed attempt (truncated-but-complete
      HTTP bodies exist), and a cached file that matches short-circuits.

    Returns the cached file path."""
    if max_retries < 1:
        raise ValueError(f"max_retries must be >= 1, got {max_retries}")
    fetch = fetch_fn or _urllib_fetch
    jitter = rng or random.Random()
    dirname = data_path(module)
    os.makedirs(dirname, exist_ok=True)
    dest = os.path.join(dirname, save_name or url.split("/")[-1])
    if os.path.exists(dest) and (md5sum is None or md5file(dest) == md5sum):
        return dest
    part = dest + ".part"
    last_err: Optional[Exception] = None
    for attempt in range(max_retries):
        if attempt:
            delay = backoff * (2 ** (attempt - 1))
            delay *= 1.0 + 0.25 * jitter.random()
            _log.warning(
                "download %s failed (%s); retry %d/%d in %.2fs",
                url, last_err, attempt, max_retries - 1, delay,
            )
            sleep(delay)
        try:
            fetch(url, part)
            if md5sum is not None and md5file(part) != md5sum:
                raise IOError(
                    f"md5 mismatch for {url} (torn or tampered download)"
                )
            os.replace(part, dest)
            return dest
        except Exception as exc:  # noqa: BLE001 — retry any fetch failure
            last_err = exc
            try:
                os.remove(part)  # never leave a torn .part behind
            except OSError:
                pass
    raise IOError(
        f"download {url} failed after {max_retries} attempt(s): {last_err}"
    )


def synth_two_class_docs(
    n: int,
    vocab: int,
    seed: int,
    min_len: int,
    max_len: int,
    signal: float = 0.8,
    word_fmt: str = "w{}",
):
    """Deterministic two-class word corpus: positive docs draw from the low
    half of the vocab, negative from the high half, with (1-signal) crossover
    noise — separable enough for a text classifier to learn.  Shared by the
    imdb/sentiment synthetic fallbacks."""
    import numpy as np

    rng = np.random.RandomState(seed)
    docs = []
    for _ in range(n):
        label = int(rng.randint(2))
        lo, hi = (0, vocab // 2) if label else (vocab // 2, vocab)
        length = int(rng.randint(min_len, max_len))
        ids = np.where(
            rng.rand(length) < signal,
            rng.randint(lo, hi, size=length),
            rng.randint(0, vocab, size=length),
        )
        docs.append(([word_fmt.format(int(i)) for i in ids], label))
    return docs


def dict_from_freq(freq, cutoff: int = 0):
    """word → id from a frequency table, most frequent first (deterministic
    tie-break on the word)."""
    if cutoff:
        freq = {w: c for w, c in freq.items() if c > cutoff}
    ordered = sorted(freq.items(), key=lambda x: (-x[1], x[0]))
    return {w: i for i, (w, _) in enumerate(ordered)}


def build_word_dict(docs, cutoff: int = 0):
    """word → id from an iterable of token lists (see dict_from_freq)."""
    freq = {}
    for words in docs:
        for w in words:
            freq[w] = freq.get(w, 0) + 1
    return dict_from_freq(freq, cutoff)


def convert(output_path, reader, line_count, name_prefix, seed: int = 0):
    """Export any reader to recordio shards the elastic master serves
    (reference python/paddle/v2/dataset/common.py:187 ``convert``; every
    dataset module exposes a ``convert(path)`` built on it).

    Samples are pickled one-per-record into ``<output_path>/<name_prefix>-
    %05d`` shard files, ``line_count`` samples per shard, each shard
    shuffled before writing (the reference's max_lines_to_shuffle).  Feed
    the shards to ``master.Service.set_dataset([pattern])`` and read them
    back through ``reader.creator.cloud_reader`` (or ``recordio_local``
    without a master).

    Returns the list of shard paths written."""
    import pickle
    import random

    from paddle_tpu.io import recordio

    if line_count < 1:
        raise ValueError(f"line_count must be >= 1, got {line_count}")
    os.makedirs(output_path, exist_ok=True)
    rng = random.Random(seed)
    paths = []

    def write_shard(samples):
        path = os.path.join(output_path, f"{name_prefix}-{len(paths):05d}")
        rng.shuffle(samples)
        recordio.write_records(
            path, (pickle.dumps(s, protocol=pickle.HIGHEST_PROTOCOL)
                   for s in samples),
        )
        paths.append(path)

    buf = []
    for sample in reader():
        buf.append(sample)
        if len(buf) >= line_count:
            write_shard(buf)
            buf = []
    if buf or not paths:  # an empty reader still writes one (empty) shard
        write_shard(buf)
    return paths
