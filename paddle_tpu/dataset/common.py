"""Dataset cache helpers (reference: python/paddle/v2/dataset/common.py)."""

from __future__ import annotations

import os

DATA_HOME = os.environ.get(
    "PADDLE_TPU_DATA", os.path.expanduser("~/.cache/paddle_tpu/dataset")
)


def data_path(*parts: str) -> str:
    return os.path.join(DATA_HOME, *parts)


def exists(*parts: str) -> bool:
    return os.path.exists(data_path(*parts))
