"""PTB language-model corpus (reference: python/paddle/v2/dataset/imikolov.py).
NGRAM mode yields n-tuples of word ids; SEQ mode yields ([<s> ids </s>],).
Real simple-examples tarball from cache when present, else a deterministic
synthetic Markov-chain corpus (bigram structure so an n-gram LM learns)."""

from __future__ import annotations

import os
import tarfile

import numpy as np

from paddle_tpu.dataset import common

__all__ = ["train", "test", "build_dict", "DataType"]

_ARCHIVE = "simple-examples.tgz"
_VOCAB = 500
_SYNTH_SENTS_TRAIN = 1200
_SYNTH_SENTS_TEST = 200


class DataType:
    NGRAM = 1
    SEQ = 2


def _have_real() -> bool:
    return os.path.exists(common.data_path("imikolov", _ARCHIVE))


def _real_sentences(filename: str):
    path = common.data_path("imikolov", _ARCHIVE)
    with tarfile.open(path) as tarf:
        for member in tarf.getmembers():
            if member.name.endswith(filename):
                for line in tarf.extractfile(member):
                    yield line.decode().strip().split()


def _synth_sentences(n: int, seed: int):
    """First-order Markov chain over the synthetic vocab: word w transitions
    to one of 4 fixed successors with high probability."""
    rng_fixed = np.random.RandomState(77)
    successors = rng_fixed.randint(0, _VOCAB, size=(_VOCAB, 4))
    rng = np.random.RandomState(seed)
    for _ in range(n):
        length = int(rng.randint(4, 20))
        w = int(rng.randint(_VOCAB))
        sent = [w]
        for _ in range(length - 1):
            if rng.rand() < 0.85:
                w = int(successors[w, rng.randint(4)])
            else:
                w = int(rng.randint(_VOCAB))
            sent.append(w)
        yield [f"w{i}" for i in sent]


def word_count(sents, word_freq=None):
    word_freq = word_freq if word_freq is not None else {}
    for sent in sents:
        for w in sent:
            word_freq[w] = word_freq.get(w, 0) + 1
        word_freq["<s>"] = word_freq.get("<s>", 0) + 1
        word_freq["<e>"] = word_freq.get("<e>", 0) + 1
    return word_freq


def build_dict(min_word_freq: int = 50):
    if _have_real():
        freq = word_count(_real_sentences("ptb.train.txt"))
        freq.pop("<unk>", None)
        word_idx = common.dict_from_freq(freq, cutoff=min_word_freq)
    else:
        freq = word_count(_synth_sentences(_SYNTH_SENTS_TRAIN, seed=31))
        word_idx = common.dict_from_freq(freq)
    word_idx["<unk>"] = len(word_idx)
    return word_idx


def _reader(word_idx, n: int, data_type: int, train_split: bool):
    unk = word_idx["<unk>"]

    def sents():
        if _have_real():
            fname = "ptb.train.txt" if train_split else "ptb.valid.txt"
            yield from _real_sentences(fname)
        elif train_split:
            yield from _synth_sentences(_SYNTH_SENTS_TRAIN, seed=31)
        else:
            yield from _synth_sentences(_SYNTH_SENTS_TEST, seed=37)

    def reader():
        for sent in sents():
            if data_type == DataType.NGRAM:
                assert n > -1, "Invalid gram length"
                ids = (
                    [word_idx["<s>"]]
                    + [word_idx.get(w, unk) for w in sent]
                    + [word_idx["<e>"]]
                )
                if len(ids) >= n:
                    for i in range(n, len(ids) + 1):
                        yield tuple(ids[i - n : i])
            elif data_type == DataType.SEQ:
                ids = [word_idx.get(w, unk) for w in sent]
                src = [word_idx["<s>"]] + ids
                trg = ids + [word_idx["<e>"]]
                yield src, trg
            else:
                raise AssertionError("Unknown data type")

    return reader


def train(word_idx, n, data_type=DataType.NGRAM):
    return _reader(word_idx, n, data_type, train_split=True)


def test(word_idx, n, data_type=DataType.NGRAM):
    return _reader(word_idx, n, data_type, train_split=False)
def convert(path):
    """Export to recordio shards for the master (reference imikolov.py)."""
    n = 5
    word_idx = build_dict()
    common.convert(path, train(word_idx, n), 1000, "imikolov_train")
    common.convert(path, test(word_idx, n), 1000, "imikolov_test")
