"""IMDB sentiment (reference: python/paddle/v2/dataset/imdb.py) — yields
([word ids], label∈{0,1}).  Real aclImdb tarball from cache when present;
otherwise a deterministic synthetic corpus whose positive/negative classes use
disjoint-leaning word distributions (learnable)."""

from __future__ import annotations

import os
import re
import string
import tarfile

import numpy as np

from paddle_tpu.dataset import common

__all__ = ["build_dict", "word_dict", "train", "test"]

_VOCAB = 2000
_SYNTH_TRAIN = 1500
_SYNTH_TEST = 300
_ARCHIVE = "aclImdb_v1.tar.gz"


def tokenize(text: str):
    return text.lower().translate(
        str.maketrans("", "", string.punctuation)
    ).split()


def _iter_archive(pattern: str):
    path = common.data_path("imdb", _ARCHIVE)
    with tarfile.open(path) as tarf:
        tf = tarf.next()
        while tf is not None:
            if bool(pattern.match(tf.name)):
                yield tokenize(tarf.extractfile(tf).read().decode("latin-1"))
            tf = tarf.next()


def _synth_docs(n: int, seed: int):
    return common.synth_two_class_docs(
        n, _VOCAB, seed, min_len=8, max_len=40, signal=0.8
    )


def _have_real() -> bool:
    return os.path.exists(common.data_path("imdb", _ARCHIVE))


def build_dict(pattern=None, cutoff: int = 150):
    """word → id, most frequent first; '<unk>' is the last id."""
    if _have_real():
        pat = re.compile(pattern or r"aclImdb/train/.*\.txt$")
        word_idx = common.build_word_dict(_iter_archive(pat), cutoff=cutoff)
    else:
        word_idx = common.build_word_dict(
            doc for doc, _ in _synth_docs(_SYNTH_TRAIN, seed=21)
        )
    word_idx["<unk>"] = len(word_idx)
    return word_idx


def word_dict():
    return build_dict()


def _reader(word_idx, train_split: bool, n: int, seed: int):
    unk = word_idx["<unk>"]

    def reader():
        if _have_real():
            part = "train" if train_split else "test"
            for label, sub in ((1, "pos"), (0, "neg")):
                pat = re.compile(rf"aclImdb/{part}/{sub}/.*\.txt$")
                for doc in _iter_archive(pat):
                    yield [word_idx.get(w, unk) for w in doc], label
        else:
            for doc, label in _synth_docs(n, seed):
                yield [word_idx.get(w, unk) for w in doc], label

    return reader


def train(word_idx):
    return _reader(word_idx, True, _SYNTH_TRAIN, seed=21)


def test(word_idx):
    return _reader(word_idx, False, _SYNTH_TEST, seed=23)
def convert(path):
    """Export to recordio shards for the master (reference imdb.py)."""
    w = word_dict()
    common.convert(path, train(w), 1000, "imdb_train")
    common.convert(path, test(w), 1000, "imdb_test")
