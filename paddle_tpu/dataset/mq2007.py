"""MQ2007 learning-to-rank (reference: python/paddle/v2/dataset/mq2007.py).
Formats: pointwise → (score, feature[46]); pairwise → (d_high[46], d_low[46]);
listwise → (labels list, features list).  Real LETOR text files from cache
when present, else deterministic synthetic queries whose relevance is a linear
function of the features (learnable by a ranker)."""

from __future__ import annotations

import os

import numpy as np

from paddle_tpu.dataset import common

__all__ = ["train", "test"]

FEATURE_DIM = 46
_SYNTH_QUERIES_TRAIN = 120
_SYNTH_QUERIES_TEST = 30
_DOCS_PER_QUERY = 8


class Query:
    def __init__(self, query_id, relevance_score, feature_vector):
        self.query_id = query_id
        self.relevance_score = relevance_score
        self.feature_vector = feature_vector


class QueryList:
    def __init__(self, querylist=None):
        self.querylist = querylist or []

    def __iter__(self):
        return iter(self.querylist)

    def __len__(self):
        return len(self.querylist)

    def __getitem__(self, i):
        return self.querylist[i]

    def _correct_ranking_(self):
        self.querylist.sort(key=lambda x: x.relevance_score, reverse=True)

    def append(self, query):
        self.querylist.append(query)


def _parse_line(line: str) -> Query:
    fields = line.strip().split()
    score = int(fields[0])
    qid = int(fields[1].split(":")[1])
    feat = np.full(FEATURE_DIM, -1.0, dtype=np.float32)
    for tok in fields[2:]:
        if ":" not in tok or tok.startswith("#"):
            break
        k, v = tok.split(":")
        if k.isdigit():
            feat[int(k) - 1] = float(v)
    return Query(qid, score, feat)


def load_from_text(filepath: str):
    querylists = {}
    with open(filepath) as f:
        for line in f:
            if not line.strip():
                continue
            q = _parse_line(line)
            querylists.setdefault(q.query_id, QueryList()).append(q)
    return list(querylists.values())


def _synth_querylists(n_queries: int, seed: int):
    w = np.random.RandomState(91).randn(FEATURE_DIM).astype(np.float32)
    rng = np.random.RandomState(seed)
    out = []
    for qid in range(n_queries):
        ql = QueryList()
        for _ in range(_DOCS_PER_QUERY):
            feat = rng.rand(FEATURE_DIM).astype(np.float32)
            raw = float(feat @ w)
            score = int(np.clip(np.floor((raw + 2) / 1.5), 0, 2))
            ql.append(Query(qid, score, feat))
        out.append(ql)
    return out


def gen_point(querylist: QueryList):
    for q in querylist:
        yield float(q.relevance_score), q.feature_vector


def gen_pair(querylist: QueryList):
    querylist._correct_ranking_()
    for i, hi in enumerate(querylist):
        for lo in querylist[i + 1 :]:
            if hi.relevance_score > lo.relevance_score:
                yield 1.0, hi.feature_vector, lo.feature_vector


def gen_list(querylist: QueryList):
    querylist._correct_ranking_()
    labels = [float(q.relevance_score) for q in querylist]
    features = [q.feature_vector for q in querylist]
    yield labels, features


def _reader(split: str, fmt: str):
    path = common.data_path("MQ2007", f"{split}.txt")
    if not os.path.exists(path):
        # LETOR distributes per-fold files; accept Fold1 layout too.
        fold = common.data_path("MQ2007", "Fold1", f"{split}.txt")
        if os.path.exists(fold):
            path = fold

    def reader():
        if os.path.exists(path):
            qls = load_from_text(path)
        elif split == "train":
            qls = _synth_querylists(_SYNTH_QUERIES_TRAIN, seed=93)
        else:
            qls = _synth_querylists(_SYNTH_QUERIES_TEST, seed=97)
        gen = {"pointwise": gen_point, "pairwise": gen_pair, "listwise": gen_list}[fmt]
        for ql in qls:
            yield from gen(ql)

    return reader


def train(format: str = "pairwise"):
    return _reader("train", format)


def test(format: str = "pairwise"):
    return _reader("test", format)
