"""MovieLens-1M (reference: python/paddle/v2/dataset/movielens.py) — each
sample is ``user.value() + movie.value() + [rating]``:
[user_id, gender_id, age_id, job_id, movie_id, [category_ids], [title_ids],
score].  Real ml-1m zip from cache when present, else deterministic synthetic
meta where rating correlates with (user bucket, movie category) affinity."""

from __future__ import annotations

import os
import re
import zipfile

import numpy as np

from paddle_tpu.dataset import common

__all__ = [
    "train",
    "test",
    "get_movie_title_dict",
    "max_movie_id",
    "max_user_id",
    "max_job_id",
    "movie_categories",
    "age_table",
    "user_info",
    "movie_info",
    "MovieInfo",
    "UserInfo",
]

age_table = [1, 18, 25, 35, 45, 50, 56]

_N_USERS = 120
_N_MOVIES = 80
_N_JOBS = 21
_CATEGORIES = [
    "Action", "Adventure", "Animation", "Children's", "Comedy", "Crime",
    "Documentary", "Drama", "Fantasy", "Film-Noir", "Horror", "Musical",
    "Mystery", "Romance", "Sci-Fi", "Thriller", "War", "Western",
]
_TITLE_WORDS = 60
_RATINGS = 4000


class MovieInfo:
    def __init__(self, index, categories, title):
        self.index = int(index)
        self.categories = categories
        self.title = title

    def value(self):
        return [
            self.index,
            [CATEGORIES_DICT[c] for c in self.categories],
            [MOVIE_TITLE_DICT[w.lower()] for w in self.title.split()],
        ]

    def __repr__(self):
        return (
            f"<MovieInfo id({self.index}), title({self.title}), "
            f"categories({self.categories})>"
        )


class UserInfo:
    def __init__(self, index, gender, age, job_id):
        self.index = int(index)
        self.is_male = gender == "M"
        self.age = age_table.index(int(age))
        self.job_id = int(job_id)

    def value(self):
        return [self.index, 0 if self.is_male else 1, self.age, self.job_id]

    def __repr__(self):
        return (
            f"<UserInfo id({self.index}), gender({'M' if self.is_male else 'F'}), "
            f"age({age_table[self.age]}), job({self.job_id})>"
        )


CATEGORIES_DICT = {c: i for i, c in enumerate(_CATEGORIES)}
MOVIE_TITLE_DICT = {f"t{i}": i for i in range(_TITLE_WORDS)}

_meta = None


def _have_real() -> bool:
    return os.path.exists(common.data_path("movielens", "ml-1m.zip"))


def _load_real():
    movies, users = {}, {}
    title_words = {}
    pattern = re.compile(r"^(.*)\((\d+)\)$")
    path = common.data_path("movielens", "ml-1m.zip")
    ratings = []
    with zipfile.ZipFile(path) as package:
        for info in package.infolist():
            if info.filename.endswith("movies.dat"):
                with package.open(info) as f:
                    for line in f:
                        mid, title, cats = line.decode("latin-1").strip().split("::")
                        title = pattern.match(title).group(1).strip()
                        for w in title.split():
                            title_words.setdefault(w.lower(), len(title_words))
                        movies[int(mid)] = (title, cats.split("|"))
            elif info.filename.endswith("users.dat"):
                with package.open(info) as f:
                    for line in f:
                        uid, gender, age, job, _ = line.decode("latin-1").strip().split("::")
                        users[int(uid)] = UserInfo(uid, gender, age, job)
            elif info.filename.endswith("ratings.dat"):
                with package.open(info) as f:
                    for line in f:
                        uid, mid, rating, _ = line.decode("latin-1").strip().split("::")
                        ratings.append((int(uid), int(mid), float(rating)))
    global MOVIE_TITLE_DICT
    MOVIE_TITLE_DICT = title_words
    movie_objs = {
        mid: MovieInfo(mid, cats, title) for mid, (title, cats) in movies.items()
    }
    return users, movie_objs, ratings


def _synth_meta():
    rng = np.random.RandomState(71)
    users = {}
    for uid in range(1, _N_USERS + 1):
        users[uid] = UserInfo(
            uid,
            "M" if rng.rand() < 0.5 else "F",
            age_table[int(rng.randint(len(age_table)))],
            int(rng.randint(_N_JOBS)),
        )
    movies = {}
    for mid in range(1, _N_MOVIES + 1):
        cats = list(
            np.array(_CATEGORIES)[
                rng.choice(len(_CATEGORIES), size=int(rng.randint(1, 4)), replace=False)
            ]
        )
        n_title = int(rng.randint(1, 4))
        title = " ".join(f"t{int(i)}" for i in rng.randint(_TITLE_WORDS, size=n_title))
        movies[mid] = MovieInfo(mid, cats, title)
    # affinity: user-job x first-category preference drives the score
    affinity = rng.rand(_N_JOBS, len(_CATEGORIES)) * 4 + 1
    ratings = []
    for _ in range(_RATINGS):
        uid = int(rng.randint(1, _N_USERS + 1))
        mid = int(rng.randint(1, _N_MOVIES + 1))
        cat = CATEGORIES_DICT[movies[mid].categories[0]]
        base = affinity[users[uid].job_id, cat]
        score = float(np.clip(round(base + rng.randn() * 0.5), 1, 5))
        ratings.append((uid, mid, score))
    return users, movies, ratings


def _get_meta():
    global _meta
    if _meta is None:
        _meta = _load_real() if _have_real() else _synth_meta()
    return _meta


def _reader(is_test: bool, test_ratio: float = 0.1, rand_seed: int = 0):
    def reader():
        users, movies, ratings = _get_meta()
        rng = np.random.RandomState(rand_seed)
        for uid, mid, score in ratings:
            if (rng.rand() < test_ratio) == is_test:
                usr = users[uid]
                mov = movies[mid]
                yield usr.value() + mov.value() + [score]

    return reader


def train():
    return _reader(is_test=False)


def test():
    return _reader(is_test=True)


def get_movie_title_dict():
    _get_meta()
    return MOVIE_TITLE_DICT


def max_movie_id():
    return max(m.index for m in _get_meta()[1].values())


def max_user_id():
    return max(u.index for u in _get_meta()[0].values())


def max_job_id():
    return max(u.job_id for u in _get_meta()[0].values())


def movie_categories():
    return CATEGORIES_DICT


def user_info():
    return _get_meta()[0]


def movie_info():
    return _get_meta()[1]
def convert(path):
    """Export to recordio shards for the master (reference movielens.py)."""
    common.convert(path, train(), 1000, "movielens_train")
    common.convert(path, test(), 1000, "movielens_test")
