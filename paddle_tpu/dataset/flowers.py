"""Oxford 102 Flowers (reference: python/paddle/v2/dataset/flowers.py) —
yields (image[3*H*W] float in [0,1], label∈[0,102)).  Synthetic
class-structured images at 64x64 when the real archives are absent."""

from __future__ import annotations

import os

import numpy as np

from paddle_tpu.dataset import common

__all__ = ["train", "test", "valid"]

CLASSES = 102
SIZE = 64
DIM = 3 * SIZE * SIZE
_SYNTH = {"train": 612, "test": 204, "valid": 102}


def _have_real() -> bool:
    if not all(
        common.exists("flowers", f)
        for f in ("102flowers.tgz", "imagelabels.mat", "setid.mat")
    ):
        return False
    try:  # real decoding needs optional deps
        import scipy.io  # noqa: F401
        from PIL import Image  # noqa: F401
    except ImportError:
        return False
    return True


def _synthetic(split: str):
    protos = (
        np.random.RandomState(101)
        .uniform(0, 1, size=(CLASSES, DIM))
        .astype(np.float32)
    )
    seed = {"train": 103, "test": 107, "valid": 109}[split]
    rng = np.random.RandomState(seed)
    n = _SYNTH[split]
    labels = rng.randint(0, CLASSES, size=n)
    imgs = np.clip(protos[labels] + 0.1 * rng.randn(n, DIM), 0, 1).astype(
        np.float32
    )
    return imgs, labels


def _real_reader(split: str):
    # Real pipeline needs image decoding (jpeg) — iterate the tgz lazily.
    import tarfile

    try:
        from PIL import Image  # optional dependency
    except ImportError as exc:  # pragma: no cover
        raise RuntimeError(
            "real flowers data needs PIL; use the synthetic fallback"
        ) from exc
    import io

    import scipy.io as sio

    labels = sio.loadmat(common.data_path("flowers", "imagelabels.mat"))["labels"][0]
    setids = sio.loadmat(common.data_path("flowers", "setid.mat"))
    key = {"train": "trnid", "test": "tstid", "valid": "valid"}[split]
    indexes = set(int(i) for i in setids[key][0])

    def reader():
        with tarfile.open(common.data_path("flowers", "102flowers.tgz")) as tf:
            for member in tf.getmembers():
                if not member.name.endswith(".jpg"):
                    continue
                idx = int(member.name[-9:-4])
                if idx not in indexes:
                    continue
                img = Image.open(io.BytesIO(tf.extractfile(member).read()))
                img = img.convert("RGB").resize((SIZE, SIZE))
                arr = np.asarray(img, dtype=np.float32) / 255.0
                yield arr.transpose(2, 0, 1).reshape(-1), int(labels[idx - 1]) - 1

    return reader


def _reader(split: str):
    if _have_real():
        return _real_reader(split)
    imgs, labels = _synthetic(split)

    def reader():
        for i in range(imgs.shape[0]):
            yield imgs[i], int(labels[i])

    return reader


def train():
    return _reader("train")


def test():
    return _reader("test")


def valid():
    return _reader("valid")
