"""UCI housing regression (reference: python/paddle/v2/dataset/uci_housing.py)
— yields (features[13] float, [price] float). Synthetic linear task fallback."""

from __future__ import annotations

import os

import numpy as np

from paddle_tpu.dataset import common

feature_names = [
    "CRIM", "ZN", "INDUS", "CHAS", "NOX", "RM", "AGE",
    "DIS", "RAD", "TAX", "PTRATIO", "B", "LSTAT",
]

SYNTH_N = 506


def _load_real():
    path = common.data_path("uci_housing", "housing.data")
    if not os.path.exists(path):
        return None
    data = np.loadtxt(path).astype(np.float32)
    x, y = data[:, :13], data[:, 13:]
    x = (x - x.mean(0)) / (x.std(0) + 1e-8)
    return x, y


def _synthetic(seed=3):
    rng = np.random.RandomState(seed)
    w = rng.randn(13, 1).astype(np.float32)
    x = rng.randn(SYNTH_N, 13).astype(np.float32)
    y = x @ w + 0.1 * rng.randn(SYNTH_N, 1).astype(np.float32)
    return x, y


def _reader(x, y, lo, hi):
    def reader():
        for i in range(lo, hi):
            yield x[i], y[i]

    return reader


def train():
    d = _load_real() or _synthetic()
    n = int(d[0].shape[0] * 0.8)
    return _reader(d[0], d[1], 0, n)


def test():
    d = _load_real() or _synthetic()
    n = int(d[0].shape[0] * 0.8)
    return _reader(d[0], d[1], n, d[0].shape[0])
def convert(path):
    """Export to recordio shards for the master (reference uci_housing.py)."""
    common.convert(path, train(), 1000, "uci_housing_train")
    common.convert(path, test(), 1000, "uci_housing_test")
