"""PASCAL VOC2012 segmentation (reference: python/paddle/v2/dataset/voc2012.py)
— yields (image[3,H,W] float in [0,1], label_map[H,W] int∈[0,21)).  Synthetic
blob-structured scenes at 64x64 when the real VOCtrainval archive is absent."""

from __future__ import annotations

import os

import numpy as np

from paddle_tpu.dataset import common

__all__ = ["train", "test", "val"]

CLASSES = 21  # background + 20 object classes
SIZE = 64
_SYNTH = {"train": 160, "test": 40, "val": 40}


def _have_real() -> bool:
    return os.path.exists(
        common.data_path("VOC2012", "VOCtrainval_11-May-2012.tar")
    )


def _synthetic(split: str):
    """Each image: uniform background plus one rectangle of a random class,
    with the class determining the rectangle's colour."""
    seed = {"train": 113, "test": 127, "val": 131}[split]
    rng = np.random.RandomState(seed)
    palette = np.random.RandomState(137).rand(CLASSES, 3).astype(np.float32)
    for _ in range(_SYNTH[split]):
        cls = int(rng.randint(1, CLASSES))
        img = np.full((3, SIZE, SIZE), 0.2, np.float32)
        img += 0.05 * rng.randn(3, SIZE, SIZE).astype(np.float32)
        label = np.zeros((SIZE, SIZE), np.int64)
        x0, y0 = rng.randint(0, SIZE // 2, size=2)
        w, h = rng.randint(SIZE // 4, SIZE // 2, size=2)
        label[y0 : y0 + h, x0 : x0 + w] = cls
        img[:, y0 : y0 + h, x0 : x0 + w] = palette[cls][:, None, None]
        yield np.clip(img, 0, 1), label


def _real_reader(split: str):
    import io
    import tarfile

    from PIL import Image  # optional dependency

    archive = common.data_path("VOC2012", "VOCtrainval_11-May-2012.tar")
    seg_dir = "VOCdevkit/VOC2012/SegmentationClass/"
    img_dir = "VOCdevkit/VOC2012/JPEGImages/"
    list_file = f"VOCdevkit/VOC2012/ImageSets/Segmentation/{split}.txt"

    def reader():
        with tarfile.open(archive) as tf:
            names = tf.extractfile(list_file).read().decode().split()
            for name in names:
                img = Image.open(
                    io.BytesIO(tf.extractfile(img_dir + name + ".jpg").read())
                ).convert("RGB")
                seg = Image.open(
                    io.BytesIO(tf.extractfile(seg_dir + name + ".png").read())
                )
                arr = np.asarray(img, dtype=np.float32).transpose(2, 0, 1) / 255.0
                lab = np.asarray(seg, dtype=np.int64)
                # VOC marks void/boundary pixels as 255 — remap to background
                # so labels stay in [0, CLASSES) for 21-class losses.
                lab = np.where(lab == 255, 0, lab)
                yield arr, lab

    return reader


def _reader(split: str):
    if _have_real():
        # VOC's real test annotations are withheld; serve val for test()
        # rather than trainval (which would overlap the training images).
        real_split = {"train": "train", "val": "val", "test": "val"}[split]
        return _real_reader(real_split)

    def reader():
        yield from _synthetic(split)

    return reader


def train():
    return _reader("train")


def test():
    return _reader("test")


def val():
    return _reader("val")
