"""CoNLL-05 semantic role labeling (reference:
python/paddle/v2/dataset/conll05.py) — yields the 9-slot SRL sample
(word_ids, ctx_n2, ctx_n1, ctx_0, ctx_p1, ctx_p2, pred_ids, mark, label_ids)
where ctx_* are the words around the predicate broadcast over the sentence,
mark is the 0/1 predicate-position indicator, labels are IOB ids.

Real data path: test.wsj.words.gz + test.wsj.props.gz (CoNLL bracket format)
plus wordDict.txt / verbDict.txt / targetDict.txt / emb in the cache dir —
the same five files the reference downloads.  Deterministic synthetic corpus
otherwise."""

from __future__ import annotations

import gzip
import os

import numpy as np

from paddle_tpu.dataset import common

__all__ = ["get_dict", "get_embedding", "test"]

_SYNTH_SENTS = 300
_WORDS = 200
_VERBS = 20
_LABELS = [
    "O",
    "B-A0",
    "I-A0",
    "B-A1",
    "I-A1",
    "B-V",
    "B-A2",
    "I-A2",
    "B-AM-TMP",
]
UNK_IDX = 0


def _have_real() -> bool:
    return all(
        common.exists("conll05st", f)
        for f in (
            "test.wsj.words.gz",
            "test.wsj.props.gz",
            "wordDict.txt",
            "verbDict.txt",
            "targetDict.txt",
        )
    )


def load_dict(path: str):
    d = {}
    with open(path) as f:
        for i, line in enumerate(f):
            d[line.strip()] = i
    return d


def get_dict():
    """(word_dict, verb_dict, label_dict)."""
    if _have_real():
        return (
            load_dict(common.data_path("conll05st", "wordDict.txt")),
            load_dict(common.data_path("conll05st", "verbDict.txt")),
            load_dict(common.data_path("conll05st", "targetDict.txt")),
        )
    word_dict = {"<unk>": UNK_IDX}
    for i in range(_WORDS):
        word_dict[f"w{i}"] = len(word_dict)
    verb_dict = {f"v{i}": i for i in range(_VERBS)}
    label_dict = {lab: i for i, lab in enumerate(_LABELS)}
    return word_dict, verb_dict, label_dict


def get_embedding():
    """Embedding table aligned with word_dict (reference: the downloaded
    'emb' text matrix); deterministic random table in synthetic mode."""
    word_dict, _, _ = get_dict()
    emb_path = common.data_path("conll05st", "emb")
    if os.path.exists(emb_path):
        return np.loadtxt(emb_path, dtype=np.float32)
    rng = np.random.RandomState(55)
    return rng.randn(len(word_dict), 32).astype(np.float32)


def _bracket_to_iob(tags):
    """CoNLL props bracket column → IOB labels: '(A0*' opens A0, '*)' closes
    the open span, '*' continues (reference conll05.py corpus_reader)."""
    labels = []
    cur = None
    for tag in tags:
        tag = tag.strip()
        if tag.startswith("("):
            cur = tag[1:].split("*")[0]
            labels.append("B-" + cur)
            if tag.endswith(")"):
                cur = None
        elif cur is not None:
            labels.append("I-" + cur)
            if tag.endswith(")"):
                cur = None
        else:
            labels.append("O")
    return labels


def corpus_reader(words_path: str, props_path: str):
    """Yields (words, pred_pos, verb_lemma, iob_labels) — one sample per
    predicate column of each sentence."""

    def flush(words, lemmas, columns):
        for col_idx in range(len(columns[0]) if columns else 0):
            tags = [row[col_idx] for row in columns]
            labels = _bracket_to_iob(tags)
            pred_positions = [i for i, lab in enumerate(labels) if lab == "B-V"]
            pred_pos = pred_positions[0] if pred_positions else 0
            yield words, pred_pos, lemmas[pred_pos], labels

    def reader():
        with gzip.open(words_path, "rt") as wf, gzip.open(props_path, "rt") as pf:
            words, lemmas, columns = [], [], []
            for wline, pline in zip(wf, pf):
                wline, pline = wline.strip(), pline.strip()
                if not wline:
                    yield from flush(words, lemmas, columns)
                    words, lemmas, columns = [], [], []
                    continue
                words.append(wline.split()[0])
                pfields = pline.split()
                lemmas.append(pfields[0])
                columns.append(pfields[1:])
            # files without a trailing blank line still flush the last block
            yield from flush(words, lemmas, columns)

    return reader


def _synth_corpus():
    """Sentences with one predicate; tokens near the predicate get argument
    labels (structured enough for a tagger to learn)."""
    rng = np.random.RandomState(61)
    for _ in range(_SYNTH_SENTS):
        length = int(rng.randint(5, 18))
        words = [f"w{int(i)}" for i in rng.randint(_WORDS, size=length)]
        pred_pos = int(rng.randint(length))
        verb = f"v{int(rng.randint(_VERBS))}"
        labels = ["O"] * length
        labels[pred_pos] = "B-V"
        if pred_pos > 0:
            labels[pred_pos - 1] = "B-A0"
        if pred_pos > 1:
            labels[pred_pos - 2] = "I-A0"
        if pred_pos < length - 1:
            labels[pred_pos + 1] = "B-A1"
        if pred_pos < length - 2:
            labels[pred_pos + 2] = "I-A1"
        yield words, pred_pos, verb, labels


def reader_creator(corpus=None):
    word_dict, verb_dict, label_dict = get_dict()

    def reader():
        for words, pred_pos, verb, labels in (corpus or _synth_corpus)():
            length = len(words)

            def ctx(off):
                i = min(max(pred_pos + off, 0), length - 1)
                return word_dict.get(words[i], UNK_IDX)

            word_ids = [word_dict.get(w, UNK_IDX) for w in words]
            pred_id = verb_dict.get(verb, 0)
            mark = [1 if i == pred_pos else 0 for i in range(length)]
            label_ids = [label_dict.get(lab, label_dict.get("O", 0)) for lab in labels]
            yield (
                word_ids,
                [ctx(-2)] * length,
                [ctx(-1)] * length,
                [ctx(0)] * length,
                [ctx(1)] * length,
                [ctx(2)] * length,
                [pred_id] * length,
                mark,
                label_ids,
            )

    return reader


def test():
    if _have_real():
        return reader_creator(
            corpus_reader(
                common.data_path("conll05st", "test.wsj.words.gz"),
                common.data_path("conll05st", "test.wsj.props.gz"),
            )
        )
    return reader_creator()
def convert(path):
    """Export to recordio shards for the master (reference conll05.py; only
    the test split is publicly redistributable, so it stands in for both)."""
    common.convert(path, test(), 1000, "conl105_train")
    common.convert(path, test(), 1000, "conl105_test")
