"""WMT14 fr→en (reference: python/paddle/v2/dataset/wmt14.py) — yields
(src_ids, trg_ids_with_<s>, trg_ids_next_with_<e>).  Dict ids 0/1/2 are
<s>/<e>/<unk> as in the reference.  Real wmt14 tarball from cache when
present; otherwise a deterministic synthetic parallel corpus where the target
is a learnable transform (reversal + vocab offset) of the source."""

from __future__ import annotations

import os
import tarfile

import numpy as np

from paddle_tpu.dataset import common

__all__ = ["train", "test", "build_dict", "get_dict"]

START = "<s>"
END = "<e>"
UNK = "<unk>"
UNK_IDX = 2

_ARCHIVE = "wmt14.tgz"
_SYNTH_TRAIN = 800
_SYNTH_TEST = 150
_SYNTH_WORDS = 300  # true synthetic vocab (ids 3..)


def _have_real() -> bool:
    return os.path.exists(common.data_path("wmt14", _ARCHIVE))


def _synth_pairs(n: int, seed: int):
    rng = np.random.RandomState(seed)
    for _ in range(n):
        length = int(rng.randint(3, 15))
        src = rng.randint(_SYNTH_WORDS, size=length)
        trg = (src[::-1] + 7) % _SYNTH_WORDS
        yield (
            [f"f{i}" for i in src],
            [f"e{i}" for i in trg],
        )


def _synth_dicts(dict_size: int):
    src_dict = {START: 0, END: 1, UNK: 2}
    trg_dict = {START: 0, END: 1, UNK: 2}
    for i in range(min(_SYNTH_WORDS, dict_size - 3)):
        src_dict[f"f{i}"] = 3 + i
        trg_dict[f"e{i}"] = 3 + i
    return src_dict, trg_dict


def _real_dicts(dict_size: int):
    path = common.data_path("wmt14", _ARCHIVE)
    src_dict, trg_dict = {}, {}
    with tarfile.open(path) as tf:
        for member in tf.getmembers():
            which = None
            if member.name.endswith("src.dict"):
                which = src_dict
            elif member.name.endswith("trg.dict"):
                which = trg_dict
            if which is not None:
                for i, line in enumerate(tf.extractfile(member)):
                    if i >= dict_size:
                        break
                    which[line.decode().strip()] = i
    return src_dict, trg_dict


def _real_pairs(file_sub: str):
    path = common.data_path("wmt14", _ARCHIVE)
    with tarfile.open(path) as tf:
        for member in tf.getmembers():
            if file_sub in member.name and member.isfile():
                for line in tf.extractfile(member):
                    fields = line.decode().strip().split("\t")
                    if len(fields) == 2:
                        yield fields[0].split(), fields[1].split()


def build_dict(dict_size: int):
    if _have_real():
        return _real_dicts(dict_size)
    return _synth_dicts(dict_size)


def _reader(dict_size: int, train_split: bool):
    src_dict, trg_dict = build_dict(dict_size)

    def pairs():
        if _have_real():
            yield from _real_pairs("train/" if train_split else "test/")
        elif train_split:
            yield from _synth_pairs(_SYNTH_TRAIN, seed=41)
        else:
            yield from _synth_pairs(_SYNTH_TEST, seed=43)

    def reader():
        for src_words, trg_words in pairs():
            src_ids = [src_dict.get(w, UNK_IDX) for w in src_words]
            trg = [trg_dict.get(w, UNK_IDX) for w in trg_words]
            trg_ids = [trg_dict[START]] + trg
            trg_ids_next = trg + [trg_dict[END]]
            yield src_ids, trg_ids, trg_ids_next

    return reader


def train(dict_size: int):
    return _reader(dict_size, train_split=True)


def test(dict_size: int):
    return _reader(dict_size, train_split=False)


def get_dict(dict_size: int, reverse: bool = True):
    src_dict, trg_dict = build_dict(dict_size)
    if reverse:
        src_dict = {v: k for k, v in src_dict.items()}
        trg_dict = {v: k for k, v in trg_dict.items()}
    return src_dict, trg_dict
def convert(path):
    """Export to recordio shards for the master (reference wmt14.py)."""
    dict_size = 30000
    common.convert(path, train(dict_size), 1000, "wmt14_train")
    common.convert(path, test(dict_size), 1000, "wmt14_test")
