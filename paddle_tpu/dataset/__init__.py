"""Datasets — the ``paddle.v2.dataset`` surface (reference:
python/paddle/v2/dataset/: mnist, cifar, imdb, imikolov, movielens, conll05,
uci_housing, wmt14, flowers, voc2012, sentiment, mq2007).

This environment has zero egress, so each dataset module prefers a local
cache dir (PADDLE_TPU_DATA, same role as the reference's ~/.cache/paddle
common.py) and otherwise falls back to a deterministic synthetic generator
with the real schema — keeping every demo runnable end-to-end.
"""

from paddle_tpu.dataset import (  # noqa: F401
    cifar,
    conll05,
    flowers,
    imdb,
    imikolov,
    mnist,
    movielens,
    mq2007,
    sentiment,
    uci_housing,
    voc2012,
    wmt14,
)

__all__ = [
    "mnist",
    "cifar",
    "imdb",
    "imikolov",
    "movielens",
    "conll05",
    "uci_housing",
    "wmt14",
    "flowers",
    "voc2012",
    "sentiment",
    "mq2007",
]
