"""CIFAR-10/100 (reference: python/paddle/v2/dataset/cifar.py) — yields
(image[3072] float in [0,1], label int).  Loads real pickled batches from the
cache dir when present (cifar-10-batches-py / cifar-100-python); otherwise
serves deterministic class-structured synthetic data."""

from __future__ import annotations

import os
import pickle
import tarfile

import numpy as np

from paddle_tpu.dataset import common

__all__ = ["train100", "test100", "train10", "test10"]

SYNTH_TRAIN = 2048
SYNTH_TEST = 256
DIM = 3 * 32 * 32


def _iter_archive(path: str, sub_name: str):
    with tarfile.open(path, mode="r") as f:
        names = [n for n in f.getnames() if sub_name in n]
        for name in names:
            batch = pickle.load(f.extractfile(name), encoding="latin1")  # wire: allow[A206] upstream CIFAR distribution IS pickle; the archive is md5-verified by dataset.common.download before any byte is read
            data = batch["data"]
            labels = batch.get("labels") or batch.get("fine_labels")
            for sample, label in zip(data, labels):
                yield (sample.astype(np.float32) / 255.0, int(label))


def _synthetic(n: int, classes: int, seed: int):
    protos = (
        np.random.RandomState(99)
        .uniform(0, 1, size=(classes, DIM))
        .astype(np.float32)
    )
    rng = np.random.RandomState(seed)
    labels = rng.randint(0, classes, size=n)
    imgs = np.clip(protos[labels] + 0.15 * rng.randn(n, DIM), 0, 1)
    return imgs.astype(np.float32), labels


def _reader(archive: str, sub_name: str, classes: int, n: int, seed: int):
    path = common.data_path("cifar", archive)

    def reader():
        if os.path.exists(path):
            yield from _iter_archive(path, sub_name)
        else:
            imgs, labels = _synthetic(n, classes, seed)
            for i in range(n):
                yield imgs[i], int(labels[i])

    return reader


def train10():
    return _reader("cifar-10-python.tar.gz", "data_batch", 10, SYNTH_TRAIN, 3)


def test10():
    return _reader("cifar-10-python.tar.gz", "test_batch", 10, SYNTH_TEST, 5)


def train100():
    return _reader("cifar-100-python.tar.gz", "train", 100, SYNTH_TRAIN, 7)


def test100():
    return _reader("cifar-100-python.tar.gz", "test", 100, SYNTH_TEST, 9)
def convert(path):
    """Export to recordio shards for the master (reference cifar.py:132)."""
    common.convert(path, train100(), 1000, "cifar_train100")
    common.convert(path, test100(), 1000, "cifar_test100")
    common.convert(path, train10(), 1000, "cifar_train10")
    common.convert(path, test10(), 1000, "cifar_test10")
