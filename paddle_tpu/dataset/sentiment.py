"""Movie-review sentiment (reference: python/paddle/v2/dataset/sentiment.py,
NLTK movie_reviews corpus) — yields ([word ids], label∈{0,1}).  Synthetic
class-structured corpus when the real corpus is absent from cache."""

from __future__ import annotations

import os
import tarfile

import numpy as np

from paddle_tpu.dataset import common

__all__ = ["train", "test", "get_word_dict"]

NUM_TRAINING_INSTANCES = 1600
NUM_TOTAL_INSTANCES = 2000
_VOCAB = 1500


def _have_real() -> bool:
    return os.path.exists(common.data_path("sentiment", "movie_reviews.tar.gz"))


def _real_docs():
    path = common.data_path("sentiment", "movie_reviews.tar.gz")
    with tarfile.open(path) as tf:
        for member in tf.getmembers():
            if not member.isfile():
                continue
            label = 1 if "/pos/" in member.name else 0
            words = tf.extractfile(member).read().decode("latin-1").lower().split()
            yield words, label


def _synth_docs():
    return common.synth_two_class_docs(
        NUM_TOTAL_INSTANCES, _VOCAB, seed=81, min_len=10, max_len=50, signal=0.75
    )


_word_dict = None
_data = None


def _load():
    global _word_dict, _data
    if _data is not None:
        return
    docs = list(_real_docs()) if _have_real() else _synth_docs()
    _word_dict = common.build_word_dict(words for words, _ in docs)
    # interleave pos/neg as the reference's sort_files does before the split
    rng = np.random.RandomState(83)
    order = rng.permutation(len(docs))
    _data = [
        ([_word_dict[w] for w in docs[i][0]], docs[i][1]) for i in order
    ]


def get_word_dict():
    _load()
    return _word_dict


def train():
    _load()

    def reader():
        for sample in _data[:NUM_TRAINING_INSTANCES]:
            yield sample

    return reader


def test():
    _load()

    def reader():
        for sample in _data[NUM_TRAINING_INSTANCES:]:
            yield sample

    return reader
def convert(path):
    """Export to recordio shards for the master (reference sentiment.py)."""
    common.convert(path, train(), 1000, "sentiment_train")
    common.convert(path, test(), 1000, "sentiment_test")
