"""Length-bucketed batching + token-budget packing.

Variable-length sequence workloads (NMT, text classification) waste most of
their compute when every batch is padded to the global max length: the
masked-out tail rows still ride through every GEMM and every scan step.  The
classic fix is a bucketing input pipeline (TensorFlow's bucket_by_sequence_
length, arXiv:1605.08695); on TPU the extra constraint is that XLA compiles
one executable per batch shape, so bucket shapes must come from a small
canonical ladder or the jit cache grows without bound.

This module supplies the feed half of that contract (the shape half lives in
``core.batch``: :data:`~paddle_tpu.core.batch.DEFAULT_LADDER`,
:func:`~paddle_tpu.core.batch.ladder_len`):

* :func:`sort_within_window` — length-sorted shuffle-window bucketing: pull a
  window of samples from an (already shuffled) stream and re-emit it in
  length order, so nearby samples have similar lengths without giving up
  stochasticity beyond the window.
* :func:`token_budget_batch` — the batcher: group samples into minibatches
  whose PADDED token count (batch_size × ladder rung) stays ~constant, i.e.
  batch size scales inversely with bucket length.  Every emitted full batch
  has the canonical size for its rung, so distinct batch shapes across an
  epoch are bounded by the ladder — exactly one (B, T) per rung when every
  sequence slot shares the sample's length; slots with uncorrelated lengths
  each round to their own rung, multiplying the realized combinations
  (bucket on the dominant slot via ``key``/``slots`` if that matters).

Both are ordinary reader decorators (``reader() -> iterable``) composable
with ``paddle.reader.shuffle`` etc.; ``token_budget_batch`` replaces
``paddle.batch`` for variable-length data and its output feeds the same
:class:`~paddle_tpu.reader.feeder.DataFeeder` (give the feeder the same
``ladder=`` so padded array shapes land on the rung the batcher packed for).
"""

from __future__ import annotations

import random as _random
from typing import Any, Callable, Iterable, List, Optional, Sequence

import numpy as np

from paddle_tpu.core.batch import DEFAULT_LADDER, ladder_len

Reader = Callable[[], Iterable[Any]]


def sample_len(sample: Any, slots: Optional[Sequence[int]] = None) -> int:
    """Token length of a sample tuple: the max length over its sequence-like
    fields (lists/tuples and 1-D+ ndarrays); scalars count as 1.

    This is the right default for id-sequence workloads (every slot of an
    NMT triple or a text-cls pair is a token list).  Samples that mix
    sequence slots with wide DENSE vector slots (a flat image next to a
    caption) must say which fields carry length — pass ``slots`` (field
    indices) here or a custom ``key=`` to the decorators."""
    if not isinstance(sample, (tuple, list)):
        return 1
    n = 1
    for i, field in enumerate(sample):
        if slots is not None and i not in slots:
            continue
        if isinstance(field, np.ndarray):
            if field.ndim >= 1:
                n = max(n, int(field.shape[0]))
        elif isinstance(field, (list, tuple)):
            n = max(n, len(field))
    return n


def sort_within_window(
    reader: Reader,
    window: int = 2048,
    key: Callable[[Any], int] = sample_len,
) -> Reader:
    """Re-emit each ``window`` of samples in (stable) length-sorted order.

    Upstream shuffling decides WHICH samples share a window; the sort only
    reorders inside it, so training order stays stochastic at the window
    scale while neighbours become length-homogeneous for the batcher."""

    def sorted_reader():
        buf: List[Any] = []
        for sample in reader():
            buf.append(sample)
            if len(buf) >= window:
                buf.sort(key=key)
                yield from buf
                buf = []
        if buf:
            buf.sort(key=key)
            yield from buf

    return sorted_reader


def bucket_batch_size(
    rung: int,
    token_budget: int,
    batch_multiple: int = 8,
    max_batch: Optional[int] = None,
) -> int:
    """Canonical examples-per-batch for a ladder rung: the largest multiple
    of ``batch_multiple`` whose padded token count fits the budget (at least
    1).  One deterministic size per rung keeps the (B, T) shape set bounded
    by the ladder size."""
    cap = max(token_budget // rung, 1)
    if cap >= batch_multiple:
        cap -= cap % batch_multiple
    if max_batch is not None:
        cap = min(cap, max_batch)
    return max(cap, 1)


def token_budget_batch(
    reader: Reader,
    token_budget: Optional[int] = None,
    batch_size: Optional[int] = None,
    key: Callable[[Any], int] = sample_len,
    ladder: Sequence[int] = DEFAULT_LADDER,
    window: int = 2048,
    batch_multiple: int = 8,
    max_batch: Optional[int] = None,
    shuffle_batches: bool = True,
    seed: int = 0,
    drop_last: bool = False,
) -> Reader:
    """Group a variable-length sample reader into length-bucketed minibatches
    that fill a ~constant PADDED-token budget per step.

    Each sample joins the bucket of ``ladder_len(key(sample))``; a bucket
    flushes a batch whenever it holds :func:`bucket_batch_size` samples for
    its rung.  Within each ``window`` of consumed samples, ready batches are
    emitted in seeded-shuffled order so the stream doesn't degenerate into
    long same-length runs.  Residual samples carry over between windows; at
    end of stream the partial per-rung remainders are emitted too (shapes
    beyond the canonical set, at most one per rung per epoch) unless
    ``drop_last``.

    ``token_budget=None`` derives the budget from ``batch_size`` × the
    tallest rung seen in the first window — i.e. the padded token count the
    UNBUCKETED pipeline would have spent per step, so switching bucketing on
    keeps per-step compute comparable while making nearly all of it valid.

    Feed the emitted batches through a ``DataFeeder(..., ladder=ladder)`` so
    the padded array shapes land exactly on the rung each batch was packed
    for."""
    if token_budget is None and batch_size is None:
        raise ValueError("token_budget_batch needs token_budget or batch_size")

    # a derived budget is pinned on the FIRST pass and reused by every later
    # reader() restart: re-deriving per pass under a shuffled upstream would
    # drift the budget (different first-window max), change every rung's
    # canonical batch size, and recompile every bucket each pass — exactly
    # the instability the bounded-shapes contract exists to prevent
    derived = [token_budget]

    def batched_reader():
        rng = _random.Random(seed)
        budget = derived[0]
        buckets: dict = {}
        ready: List[List[Any]] = []
        pending: List[Any] = []  # first-window holdback while budget derives

        def place(sample) -> None:
            rung = ladder_len(key(sample), ladder)
            buckets.setdefault(rung, []).append(sample)
            cap = bucket_batch_size(rung, budget, batch_multiple, max_batch)
            if len(buckets[rung]) >= cap:
                ready.append(buckets.pop(rung))

        def flush_ready():
            if shuffle_batches:
                rng.shuffle(ready)
            yield from ready
            ready.clear()

        seen = 0
        for sample in reader():
            seen += 1
            if budget is None:
                pending.append(sample)
                if len(pending) >= window:
                    budget = derived[0] = batch_size * max(
                        ladder_len(key(s), ladder) for s in pending
                    )
                    for s in pending:
                        place(s)
                    pending.clear()
                    yield from flush_ready()
                continue
            place(sample)
            if seen % window == 0:
                yield from flush_ready()
        if budget is None and pending:  # short stream: derive from all of it
            budget = derived[0] = batch_size * max(
                ladder_len(key(s), ladder) for s in pending
            )
            for s in pending:
                place(s)
            pending.clear()
        yield from flush_ready()
        if not drop_last:
            leftovers = [buckets[r] for r in sorted(buckets) if buckets[r]]
            if shuffle_batches:
                rng.shuffle(leftovers)
            yield from leftovers

    from paddle_tpu.reader.pass_cache import copy_cache_tags

    # carry the @provider(cache=CACHE_PASS_IN_MEM) tags through to the
    # trainer; cached replay is per-bucket-shape aware (pass_cache.py)
    return copy_cache_tags(reader, batched_reader)
