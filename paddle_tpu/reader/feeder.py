"""DataFeeder — python samples → padded static-shape device batches.

Replaces the reference's DataProviderConverter (reference:
paddle/py_paddle/dataprovider_converter.py:247) which packed samples into
CSR Arguments.  TPU-native contract instead: every sequence slot is padded to
a *bucketed* max length (rounded up to a multiple of ``seq_multiple``) so jit
sees a small, bounded set of shapes; lengths ride alongside as int32 vectors
(SeqTensor).  Sparse slots are densified to multi-hot rows (gather-sharded
embedding inputs use INDEX slots instead, which stay ids).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

from paddle_tpu.core.batch import SeqTensor, ladder_len
from paddle_tpu.core.data_types import InputType, SeqLevel, SlotKind


def _round_up(n: int, m: int) -> int:
    return ((n + m - 1) // m) * m


# Above this vocabulary size a sparse_binary slot feeds as PADDED IDS
# (sentinel = dim) instead of a dense multi-hot row: the multi-hot form is
# O(B·T·vocab) memory — fatal for the reference's 1.45M-word LTR configs —
# while the id form is O(B·T·max_nnz) and consumers gather-sum touched rows
# (layers/base.py gather_sum_rows; the reference's SparseRowMatrix regime).
SPARSE_IDS_THRESHOLD = 65536


def _ids_form(itype: InputType) -> bool:
    return (
        itype.kind == SlotKind.SPARSE_BINARY
        and itype.dim > SPARSE_IDS_THRESHOLD
    )


def feed_dtypes_of(topology) -> Dict[str, str]:
    """{slot: wire dtype} for data layers declaring a narrow feed dtype
    (``data_layer(feed_dtype="uint8")``) — shared by the trainer and the
    inference face so train and infer see identical device-side values."""
    return {
        name: conf.attr("feed_dtype")
        for name, conf in topology.data_layers().items()
        if conf.attr("feed_dtype")
    }


class DataFeeder:
    """feeding: [(slot_name, InputType)] in sample-tuple order, or a dict
    {slot_name: index_in_sample} combined with `data_types`."""

    def __init__(
        self,
        data_types: Sequence[Tuple[str, InputType]],
        feeding: Optional[Union[Dict[str, int], Sequence[str]]] = None,
        seq_multiple: int = 8,
        min_seq_len: int = 8,
        dtype=np.float32,
        feed_dtypes: Optional[Dict[str, Any]] = None,
        ladder: Optional[Sequence[int]] = None,
    ):
        """feed_dtypes: per-slot WIRE dtype override for dense slots (e.g.
        {"image": np.uint8}) — the batch crosses host->device at 1/4 the
        bytes and the jitted step casts + normalizes on device (the data
        layer's feed_scale/feed_shift attrs; reference DataProvider ships
        bytes the same way, mnist_bin_part is uint8 on disk).

        ladder: canonical sequence-length rungs (core.batch.DEFAULT_LADDER)
        replacing the multiple-of-``seq_multiple`` rounding — the feed half
        of the bucket-shape contract: padded lengths come from a small
        geometric set, so the jitted step's shape cache stays bounded over
        any length distribution (pair with reader.bucketing batches that
        fill a token budget per rung)."""
        self.data_types = list(data_types)
        self.feed_dtypes = dict(feed_dtypes or {})
        if feeding is None:
            self.index = {name: i for i, (name, _) in enumerate(self.data_types)}
        elif isinstance(feeding, dict):
            self.index = dict(feeding)
        else:
            self.index = {name: i for i, name in enumerate(feeding)}
        self.seq_multiple = seq_multiple
        self.min_seq_len = min_seq_len
        self.ladder = tuple(ladder) if ladder else None
        self.dtype = dtype

    # ------------------------------------------------------------------
    def __call__(self, batch_data: List[Any]) -> Dict[str, SeqTensor]:
        return self.convert(batch_data)

    def convert(self, batch_data: List[Any]) -> Dict[str, SeqTensor]:
        n_slots = max(self.index.values()) + 1 if self.index else 0
        for sample in batch_data[:1]:
            if not isinstance(sample, (tuple, list)) or len(sample) < n_slots:
                raise ValueError(
                    f"each sample must be a tuple of {n_slots} slot(s) "
                    f"({[n for n, _ in self.data_types]}); got "
                    f"{type(sample).__name__}. Did you forget to wrap the "
                    f"reader with paddle.batch(reader, batch_size)?"
                )
        out: Dict[str, SeqTensor] = {}
        for name, itype in self.data_types:
            col = [sample[self.index[name]] for sample in batch_data]
            out[name] = self._convert_slot(
                col, itype, self.feed_dtypes.get(name, self.dtype)
            )
        return out

    # ------------------------------------------------------------------
    def _bucket_len(self, max_len: int) -> int:
        if self.ladder:
            return ladder_len(max(max_len, self.min_seq_len), self.ladder)
        return max(_round_up(max_len, self.seq_multiple), self.min_seq_len)

    def _convert_slot(
        self, col: List[Any], itype: InputType, dtype=None
    ) -> SeqTensor:
        dtype = self.dtype if dtype is None else dtype
        if itype.seq == SeqLevel.NONE:
            return self._convert_plain(col, itype, dtype)
        if itype.seq == SeqLevel.SEQ:
            return self._convert_seq(col, itype, dtype)
        return self._convert_sub_seq(col, itype, dtype)

    def _convert_plain(self, col, itype: InputType, dtype=None) -> SeqTensor:
        dtype = self.dtype if dtype is None else dtype
        b = len(col)
        if itype.kind == SlotKind.DENSE:
            arr = np.asarray(col, dtype=dtype).reshape(b, itype.dim)
            return SeqTensor(arr)
        if itype.kind == SlotKind.INDEX:
            return SeqTensor(np.asarray(col, dtype=np.int32).reshape(b))
        if _ids_form(itype):
            nnz = max(
                _round_up(max((len(ids) for ids in col), default=1), 8), 8
            )
            arr = np.full((b, nnz), itype.dim, dtype=np.int32)  # sentinel pad
            for i, ids in enumerate(col):
                arr[i, : len(ids)] = np.asarray(ids, dtype=np.int32)
            return SeqTensor(arr, sparse_ids=True)
        # sparse -> dense multi-hot
        arr = np.zeros((b, itype.dim), dtype=self.dtype)
        for i, ids in enumerate(col):
            if itype.kind == SlotKind.SPARSE_BINARY:
                arr[i, np.asarray(ids, dtype=np.int64)] = 1.0
            else:
                idx, vals = zip(*ids) if ids else ((), ())
                arr[i, np.asarray(idx, dtype=np.int64)] = np.asarray(vals, self.dtype)
        return SeqTensor(arr)

    def _convert_seq(self, col, itype: InputType, dtype=None) -> SeqTensor:
        dtype = self.dtype if dtype is None else dtype
        b = len(col)
        lengths = np.asarray([len(s) for s in col], dtype=np.int32)
        t = self._bucket_len(int(lengths.max()) if b else 1)
        if itype.kind == SlotKind.INDEX:
            arr = np.zeros((b, t), dtype=np.int32)
            for i, s in enumerate(col):
                arr[i, : len(s)] = np.asarray(s, dtype=np.int32)
            return SeqTensor(arr, lengths)
        if itype.kind == SlotKind.DENSE:
            arr = np.zeros((b, t, itype.dim), dtype=dtype)
            for i, s in enumerate(col):
                if len(s):
                    arr[i, : len(s)] = np.asarray(s, dtype=dtype)
            return SeqTensor(arr, lengths)
        if _ids_form(itype):
            nnz = max(
                _round_up(
                    max((len(ids) for s in col for ids in s), default=1), 8
                ),
                8,
            )
            arr = np.full((b, t, nnz), itype.dim, dtype=np.int32)
            for i, s in enumerate(col):
                for j, ids in enumerate(s):
                    arr[i, j, : len(ids)] = np.asarray(ids, dtype=np.int32)
            return SeqTensor(arr, lengths, sparse_ids=True)
        # sparse sequence -> [B, T, dim] multi-hot
        arr = np.zeros((b, t, itype.dim), dtype=self.dtype)
        for i, s in enumerate(col):
            for j, ids in enumerate(s):
                if itype.kind == SlotKind.SPARSE_BINARY:
                    arr[i, j, np.asarray(ids, dtype=np.int64)] = 1.0
                else:
                    idx, vals = zip(*ids) if ids else ((), ())
                    arr[i, j, np.asarray(idx, dtype=np.int64)] = np.asarray(
                        vals, self.dtype
                    )
        return SeqTensor(arr, lengths)

    def _convert_sub_seq(self, col, itype: InputType, dtype=None) -> SeqTensor:
        """Nested sequences: each sample is a list of subsequences.  Reference
        packs these as two-level CSR (Argument.h:84-93,
        subSequenceStartPositions); TPU-native form is a doubly padded
        [B, S, T, ...] block plus n_sub[B] and sub_lengths[B, S] so nested
        recurrence stays static-shape under jit."""
        dtype = self.dtype if dtype is None else dtype
        b = len(col)
        n_sub = np.asarray([len(s) for s in col], dtype=np.int32)
        raw_s = int(n_sub.max()) if b else 1
        if self.ladder:
            # the S axis is a compiled extent too: ladder it so nested
            # batches keep the bounded-shape contract — on the shallow
            # 4-based sub-ladder, since subsequence counts are usually
            # small and the 16-based time ladder would pad them 4-8x
            from paddle_tpu.core.batch import DEFAULT_SUB_LADDER

            s_max = ladder_len(max(raw_s, 1), DEFAULT_SUB_LADDER)
        else:
            s_max = max(_round_up(raw_s, 4), 4)
        sub_lengths = np.zeros((b, s_max), dtype=np.int32)
        max_t = 1
        for i, sample in enumerate(col):
            for j, sub in enumerate(sample):
                sub_lengths[i, j] = len(sub)
                max_t = max(max_t, len(sub))
        t = self._bucket_len(max_t)
        if itype.kind == SlotKind.INDEX:
            arr = np.zeros((b, s_max, t), dtype=np.int32)
            for i, sample in enumerate(col):
                for j, sub in enumerate(sample):
                    arr[i, j, : len(sub)] = np.asarray(sub, dtype=np.int32)
            return SeqTensor(arr, n_sub, sub_lengths)
        if _ids_form(itype):
            nnz = max(
                _round_up(
                    max(
                        (len(ids) for s in col for sub in s for ids in sub),
                        default=1,
                    ),
                    8,
                ),
                8,
            )
            arr = np.full((b, s_max, t, nnz), itype.dim, dtype=np.int32)
            for i, sample in enumerate(col):
                for j, sub in enumerate(sample):
                    for k, ids in enumerate(sub):
                        arr[i, j, k, : len(ids)] = np.asarray(ids, np.int32)
            return SeqTensor(arr, n_sub, sub_lengths, sparse_ids=True)
        arr = np.zeros((b, s_max, t, itype.dim), dtype=dtype)
        for i, sample in enumerate(col):
            for j, sub in enumerate(sample):
                if itype.kind == SlotKind.DENSE:
                    if len(sub):
                        arr[i, j, : len(sub)] = np.asarray(sub, dtype=dtype)
                else:
                    for k, ids in enumerate(sub):
                        if itype.kind == SlotKind.SPARSE_BINARY:
                            arr[i, j, k, np.asarray(ids, dtype=np.int64)] = 1.0
                        else:
                            idx, vals = zip(*ids) if ids else ((), ())
                            arr[i, j, k, np.asarray(idx, dtype=np.int64)] = (
                                np.asarray(vals, self.dtype)
                            )
        return SeqTensor(arr, n_sub, sub_lengths)
