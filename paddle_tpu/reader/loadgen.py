"""Open-loop load generator — arrival-clock request injection.

Closed-loop load tests (submit, wait, submit) measure the server's best
case: the client throttles itself to the service rate and queueing delay
never appears.  The serving comparison methodology (the Gemma-on-TPU
paper, arXiv:2605.25645) uses OPEN-LOOP load instead: arrivals follow a
fixed stochastic process independent of completions, so an overloaded
server shows up as growing queueing delay in the latency percentiles
rather than as a silently reduced offered rate.  This module is that
arrival clock for the serving plane's bench/chaos drills (bench.py
``bench_serving``, tests/test_serving_e2e.py) and the production-gate
scenario harness (robustness/scenarios.py).

Determinism: inter-arrival gaps precompute from a seeded RNG at
construction, so a drill replays the identical arrival schedule; ``clock``
and ``sleep`` are injectable (the C306 discipline — tests drive virtual
time, production uses the wall clock).
"""

from __future__ import annotations

import time
from typing import Any, Callable, List, Optional

import numpy as np

__all__ = ["OpenLoopLoadGen", "PrefixMixer"]


class PrefixMixer:
    """Seeded shared-prefix traffic shaper for synthetic sources — the
    workload half of the copy-on-write prefix cache (serving/engine.py):
    production request streams repeat system prompts and conversation
    heads, so the bench/scenario traffic must too, or the cache's hit
    path never executes under load.

    A pool of ``pool_size`` prefixes (each ``prefix_tokens`` long) is
    drawn once from ``seed``; :meth:`source` then makes the i-th request's
    source ids — with probability ``prefix_frac`` a pool prefix (chosen
    round-robin so every pool entry warms) followed by a fresh random
    tail, otherwise a fully fresh source.  FULL-duplicate prompts (tail
    length 0) arise with ``dup_frac``, exercising the exact-prompt hit
    path; everything is deterministic in (seed, i).

    Vocab ids draw from [2, vocab) — 0/1 stay reserved for BOS/EOS,
    matching the serving CLI's synthetic sources."""

    def __init__(
        self,
        vocab: int,
        *,
        pool_size: int = 4,
        prefix_frac: float = 0.5,
        prefix_tokens: int = 12,
        tail_tokens: int = 8,
        dup_frac: float = 0.25,
        seed: int = 0,
        sessions: int = 0,
    ):
        if not 0.0 <= prefix_frac <= 1.0:
            raise ValueError("prefix_frac must be in [0, 1]")
        if not 0.0 <= dup_frac <= 1.0:
            raise ValueError("dup_frac must be in [0, 1]")
        if pool_size < 1:
            raise ValueError("pool_size must be >= 1")
        self.vocab = int(vocab)
        self.pool_size = int(pool_size)
        self.prefix_frac = float(prefix_frac)
        self.dup_frac = float(dup_frac)
        rng = np.random.RandomState(seed)
        self.pool: List[List[int]] = [
            rng.randint(2, vocab, size=prefix_tokens).tolist()
            for _ in range(self.pool_size)
        ]
        self._tail_tokens = int(tail_tokens)
        self._seed = int(seed)
        self.sessions = int(sessions)

    def source(self, i: int) -> List[int]:
        """Source ids of the i-th request — deterministic in (seed, i),
        independent of call order (each request derives its own RNG), so
        a replayed drill offers the identical prompt stream."""
        rng = np.random.RandomState((self._seed * 1_000_003 + i) & 0x7FFFFFFF)
        if rng.random_sample() >= self.prefix_frac:
            n = 1 + rng.randint(
                self._tail_tokens + len(self.pool[0])
            )
            return rng.randint(2, self.vocab, size=n).tolist()
        prefix = self.pool[i % self.pool_size]
        if rng.random_sample() < self.dup_frac:
            return list(prefix)  # exact repeat: the full-prompt hit path
        tail = rng.randint(
            2, self.vocab, size=1 + rng.randint(self._tail_tokens)
        ).tolist()
        return list(prefix) + tail

    def session_of(self, i: int) -> Optional[str]:
        """Session id of the i-th request — deterministic in (seed, i),
        or None when ``sessions`` is 0 (the default: session-less
        traffic).  A prefix-bearing request's session follows its POOL
        entry, so every request sharing a prompt head shares a session —
        exactly the correlation the fleet router's session-affinity
        routing keys on (shared-prefix traffic concentrates on the
        engine whose cache already holds the blocks); fresh-source
        requests spread round-robin over the session space."""
        if self.sessions <= 0:
            return None
        rng = np.random.RandomState((self._seed * 1_000_003 + i) & 0x7FFFFFFF)
        if rng.random_sample() >= self.prefix_frac:
            return f"sess{i % self.sessions}"
        return f"sess{(i % self.pool_size) % self.sessions}"


class OpenLoopLoadGen:
    """Submit ``n_requests`` at ``rate_rps`` on an open-loop arrival clock.

    ``make_request(i)`` builds the i-th request object;
    :meth:`run`\\ ``(submit)`` blocks the calling thread, sleeping until
    each precomputed arrival time and then calling ``submit(request)``
    regardless of how many earlier requests have completed.

    ``process``: ``"poisson"`` (exponential gaps — bursty, the realistic
    default), ``"uniform"`` (evenly spaced — the reproducible floor), or
    ``"burst"`` (Poisson bursts riding a quiet base rate — the two-state
    modulated Poisson process that makes tail-latency SLOs earn their
    keep: long-run mean stays ``rate_rps``, but ``burst_factor``-times
    that rate arrives during burst episodes covering ``burst_fraction``
    of the schedule's span).

    ``deadline_s``: when set, every built request is stamped with this
    per-request end-to-end deadline (``request.deadline_s``) before
    submission — the SLO input the scheduler's admission shedding reads.

    ``session_of``: optional ``i -> session id`` callable (e.g.
    :meth:`PrefixMixer.session_of`); a non-None id is stamped on the
    built request (``request.session_id``) before submission — the
    fleet router's affinity-routing key.

    ``priority_of``: optional ``i -> priority class`` callable; a
    non-None value is stamped (``request.priority``) before submission
    — the per-class admission input (serving/scheduler.py).

    Stamping never CLOBBERS a value the built request already carries:
    a request whose deadline/session/priority was derived from a
    recorded trace (robustness/traces.py replay) keeps the recorded
    values — the replayed day must reproduce the recorded affinity
    keys, not re-derive them from a live RNG.
    """

    def __init__(
        self,
        rate_rps: float,
        n_requests: int,
        make_request: Callable[[int], Any],
        *,
        process: str = "poisson",
        seed: int = 0,
        deadline_s: Optional[float] = None,
        session_of: Optional[Callable[[int], Optional[str]]] = None,
        priority_of: Optional[Callable[[int], Optional[int]]] = None,
        burst_factor: float = 3.0,
        burst_fraction: float = 0.2,
        clock=time.perf_counter,
        sleep=time.sleep,
    ):
        if rate_rps <= 0:
            raise ValueError("rate_rps must be > 0")
        if process not in ("poisson", "uniform", "burst"):
            raise ValueError(f"unknown arrival process {process!r}")
        self.rate_rps = float(rate_rps)
        self.n_requests = int(n_requests)
        self.make_request = make_request
        self.deadline_s = deadline_s
        self.session_of = session_of
        self.priority_of = priority_of
        self._clock = clock
        self._sleep = sleep
        rng = np.random.RandomState(seed)
        if process == "poisson":
            gaps = rng.exponential(1.0 / rate_rps, size=self.n_requests)
        elif process == "uniform":
            gaps = np.full(self.n_requests, 1.0 / rate_rps)
        else:
            gaps = self._burst_gaps(rng, burst_factor, burst_fraction)
        # arrival offsets from t0; the first request arrives after one gap
        self.arrivals: List[float] = list(np.cumsum(gaps))

    def _burst_gaps(self, rng, burst_factor: float, burst_fraction: float):
        """Two-state modulated Poisson gaps: each arrival draws its gap at
        the burst rate (``burst_factor * rate_rps``) or the quiet rate,
        with state residency exponential in TIME so bursts cover
        ``burst_fraction`` of the span and the long-run mean rate solves
        back to ``rate_rps`` exactly."""
        if burst_factor <= 1.0:
            raise ValueError("burst_factor must be > 1")
        if not 0.0 < burst_fraction < 1.0:
            raise ValueError("burst_fraction must be in (0, 1)")
        burst_rate = burst_factor * self.rate_rps
        # mean = f*burst + (1-f)*quiet  =>  quiet carries the remainder
        quiet_rate = (
            self.rate_rps * (1.0 - burst_fraction * burst_factor)
            / (1.0 - burst_fraction)
        )
        if quiet_rate <= 0:
            raise ValueError(
                f"burst_factor {burst_factor} x burst_fraction "
                f"{burst_fraction} leaves no quiet-rate remainder; lower one"
            )
        # state episodes long enough to hold several arrivals each (the
        # point of a burst is queue build-up, not a lone early packet)
        mean_quiet_s = 8.0 / quiet_rate
        mean_burst_s = mean_quiet_s * burst_fraction / (1.0 - burst_fraction)
        gaps = np.empty(self.n_requests)
        in_burst = False
        state_left = rng.exponential(mean_quiet_s)
        for i in range(self.n_requests):
            g = rng.exponential(
                1.0 / (burst_rate if in_burst else quiet_rate)
            )
            gaps[i] = g
            state_left -= g
            if state_left <= 0:
                in_burst = not in_burst
                state_left = rng.exponential(
                    mean_burst_s if in_burst else mean_quiet_s
                )
        return gaps

    @property
    def offered_duration_s(self) -> float:
        """Span of the arrival schedule (last arrival offset)."""
        return self.arrivals[-1] if self.arrivals else 0.0

    def run(
        self,
        submit: Callable[[Any], Any],
        stop: Optional[Callable[[], bool]] = None,
    ) -> List[Any]:
        """Blocking open-loop injection; returns the submitted requests.
        ``stop()`` is polled before each arrival — a graceful drain (the
        `paddle-tpu serve` SIGTERM path) truncates the schedule instead of
        offering load to a server that stopped admitting."""
        submitted: List[Any] = []
        t0 = self._clock()
        for i, at in enumerate(self.arrivals):
            # bounded-poll sleep toward the arrival time: stays responsive
            # if a virtual clock jumps, never parks unbounded (C306)
            while True:
                if stop is not None and stop():
                    return submitted
                delay = (t0 + at) - self._clock()
                if delay <= 0:
                    break
                self._sleep(min(delay, 0.05))
            req = self.make_request(i)
            # stamp-if-absent: a request already carrying a deadline/
            # session/priority (a trace-replay factory derived them from
            # the RECORD) keeps it — the live RNG must not re-derive
            # affinity keys a recorded day already fixed
            if (self.deadline_s is not None
                    and getattr(req, "deadline_s", None) is None):
                req.deadline_s = self.deadline_s
            if (self.session_of is not None
                    and getattr(req, "session_id", None) is None):
                sid = self.session_of(i)
                if sid is not None:
                    req.session_id = sid
            if self.priority_of is not None:
                pri = self.priority_of(i)
                if pri is not None:
                    req.priority = int(pri)
            submitted.append(submit(req))
        return submitted
