"""Open-loop load generator — arrival-clock request injection.

Closed-loop load tests (submit, wait, submit) measure the server's best
case: the client throttles itself to the service rate and queueing delay
never appears.  The serving comparison methodology (the Gemma-on-TPU
paper, arXiv:2605.25645) uses OPEN-LOOP load instead: arrivals follow a
fixed stochastic process independent of completions, so an overloaded
server shows up as growing queueing delay in the latency percentiles
rather than as a silently reduced offered rate.  This module is that
arrival clock for the serving plane's bench/chaos drills (bench.py
``bench_serving``, tests/test_serving_e2e.py).

Determinism: inter-arrival gaps precompute from a seeded RNG at
construction, so a drill replays the identical arrival schedule; ``clock``
and ``sleep`` are injectable (the C306 discipline — tests drive virtual
time, production uses the wall clock).
"""

from __future__ import annotations

import time
from typing import Any, Callable, List, Optional

import numpy as np

__all__ = ["OpenLoopLoadGen"]


class OpenLoopLoadGen:
    """Submit ``n_requests`` at ``rate_rps`` on an open-loop arrival clock.

    ``make_request(i)`` builds the i-th request object;
    :meth:`run`\\ ``(submit)`` blocks the calling thread, sleeping until
    each precomputed arrival time and then calling ``submit(request)``
    regardless of how many earlier requests have completed.

    ``process``: ``"poisson"`` (exponential gaps — bursty, the realistic
    default) or ``"uniform"`` (evenly spaced — the reproducible floor).
    """

    def __init__(
        self,
        rate_rps: float,
        n_requests: int,
        make_request: Callable[[int], Any],
        *,
        process: str = "poisson",
        seed: int = 0,
        clock=time.perf_counter,
        sleep=time.sleep,
    ):
        if rate_rps <= 0:
            raise ValueError("rate_rps must be > 0")
        if process not in ("poisson", "uniform"):
            raise ValueError(f"unknown arrival process {process!r}")
        self.rate_rps = float(rate_rps)
        self.n_requests = int(n_requests)
        self.make_request = make_request
        self._clock = clock
        self._sleep = sleep
        rng = np.random.RandomState(seed)
        if process == "poisson":
            gaps = rng.exponential(1.0 / rate_rps, size=self.n_requests)
        else:
            gaps = np.full(self.n_requests, 1.0 / rate_rps)
        # arrival offsets from t0; the first request arrives after one gap
        self.arrivals: List[float] = list(np.cumsum(gaps))

    @property
    def offered_duration_s(self) -> float:
        """Span of the arrival schedule (last arrival offset)."""
        return self.arrivals[-1] if self.arrivals else 0.0

    def run(self, submit: Callable[[Any], Any]) -> List[Any]:
        """Blocking open-loop injection; returns the submitted requests."""
        submitted: List[Any] = []
        t0 = self._clock()
        for i, at in enumerate(self.arrivals):
            # bounded-poll sleep toward the arrival time: stays responsive
            # if a virtual clock jumps, never parks unbounded (C306)
            while True:
                delay = (t0 + at) - self._clock()
                if delay <= 0:
                    break
                self._sleep(min(delay, 0.05))
            submitted.append(submit(self.make_request(i)))
        return submitted
