from paddle_tpu.reader.decorator import (  # noqa: F401
    buffered,
    cache,
    chain,
    compose,
    firstn,
    map_readers,
    shuffle,
    xmap_readers,
)
from paddle_tpu.reader.feeder import DataFeeder  # noqa: F401
