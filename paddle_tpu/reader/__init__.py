from paddle_tpu.reader import bucketing  # noqa: F401
from paddle_tpu.reader.bucketing import (  # noqa: F401
    sort_within_window,
    token_budget_batch,
)
from paddle_tpu.reader.decorator import (  # noqa: F401
    buffered,
    cache,
    chain,
    compose,
    firstn,
    map_readers,
    shuffle,
    xmap_readers,
)
from paddle_tpu.reader.feeder import DataFeeder  # noqa: F401
from paddle_tpu.reader.loadgen import OpenLoopLoadGen, PrefixMixer  # noqa: F401
from paddle_tpu.reader.pass_cache import PassCache  # noqa: F401
