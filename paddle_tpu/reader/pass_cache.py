"""Device-resident pass cache + data echo — the TPU-native CACHE_PASS_IN_MEM.

The reference keeps pass 1's decoded samples in host RAM so later passes skip
the Python generator (``CacheType.CACHE_PASS_IN_MEM``, reference
paddle/gserver/dataproviders/PyDataProvider2.cpp:69).  On TPU the scarce
resource is not the generator but the host→device wire: the environment's
serial H2D ceiling caps the ResNet-50 pipeline at ~1/6 of what the compute
path sustains.  So the TPU-native cache keeps the decoded pass ON DEVICE:

* **Capture (epoch 1)** — every staged batch (DataFeeder output after
  ``shard_batch``/``device_put``, i.e. the *wire form*: uint8 pixels when the
  data layer declares ``feed_dtype="uint8"``, ~1 byte/px of HBM; normalize
  stays fused in the jitted step) is recorded by reference.  Nothing is
  copied — the batch the step consumes IS the cache entry (the train step
  never donates its batch argument).
* **HBM budget** — every batch is accounted (``nbytes`` over the pytree)
  against ``hbm_budget_bytes``.  Overflow ⇒ drop all held references, log a
  warning, and fall back to streaming for the rest of training; nothing
  breaks, the first epoch just stays the only feed mode.  Sizing rule:
  ``budget ≥ n_samples × bytes_per_sample(wire form)`` — e.g. uint8
  224×224×3 ImageNet is ~150 KB/image, so 4 GiB holds ~28k images; CIFAR-10
  (50k × 3 KB) fits in ~154 MB.
* **Data echo (epoch 1)** — ``echo_factor=k`` trains each transferred batch
  k times back-to-back during capture, so even the H2D-bound first epoch
  amortizes its transfers k-fold (the "data echoing" trick; see the input-
  pipeline-bottleneck discussion in the TensorFlow paper §data prefetching).
* **Replay (epoch ≥ 2)** — batches are re-yielded in an order drawn from
  ``jax.random.permutation`` keyed by ``fold_in(PRNGKey(seed), pass_id)``:
  reproducible from the pass seed, zero H2D traffic, no per-batch Python
  feed path.  ``sample_shuffle=True`` additionally permutes rows *within*
  each batch on device (a gather — every slot of a batch shares one
  permutation so samples stay aligned across slots).
* **Per-bucket composition** — batches of different shapes (the
  ``use_bucketing`` ladder feed) coexist: each cache entry keeps its own
  shape, and the shuffle permutes across ALL buckets, so a cached bucketed
  epoch interleaves rungs exactly like a streamed shuffled one.  Bucket
  stats ride in :meth:`summary`.

Numerics are pinned: a cached epoch replays the identical device arrays the
streamed epoch trained on, so with ``shuffle=False`` the trained parameters
are bit-identical to streaming the same batches (tests/test_pass_cache.py).
"""

from __future__ import annotations

import logging
from typing import Any, Dict, Iterable, Iterator, List, Optional

import numpy as np

_log = logging.getLogger("paddle_tpu.pass_cache")

__all__ = ["PassCache", "batch_nbytes", "copy_cache_tags"]


def batch_nbytes(batch) -> int:
    """HBM bytes ONE DEVICE holds for a staged batch (the budget is
    per-device HBM): a batch sharded over the data axis counts its largest
    per-device shard, a replicated or single-device array counts its full
    bytes, and host/numpy leaves count globally (they land whole on a
    device when fed)."""
    import jax

    total = 0
    for leaf in jax.tree_util.tree_leaves(batch):
        shards = getattr(leaf, "addressable_shards", None)
        if shards:
            per_dev: Dict[Any, int] = {}
            for s in shards:
                nb = int(getattr(s.data, "nbytes", 0))
                per_dev[s.device] = per_dev.get(s.device, 0) + nb
            total += max(per_dev.values(), default=0)
            continue
        size = getattr(leaf, "size", None)
        itemsize = getattr(getattr(leaf, "dtype", None), "itemsize", None)
        if size is not None and itemsize is not None:
            total += int(size) * int(itemsize)
    return total


def copy_cache_tags(src, dst):
    """Propagate the @provider CACHE_PASS_IN_MEM tags from a reader to a
    wrapper around it (paddle.batch, token_budget_batch, any future reader
    decorator) — one place to extend when a new tag appears."""
    if getattr(src, "cache_pass_in_mem", False):
        dst.cache_pass_in_mem = True
        dst.cache_pass_shuffle = getattr(src, "cache_pass_shuffle", True)
    return dst


def _permute_rows(batch, perm):
    """Apply ONE row permutation to every slot of a batch (data, lengths,
    sub_lengths all gather the same axis-0 order, so a sample's slots stay
    aligned).  Runs on device — perm is a device array."""
    import jax

    return jax.tree_util.tree_map(lambda x: x[perm], batch)


class PassCache:
    """Capture a pass of staged device batches during epoch 1, replay it
    device-resident (shuffled, reproducibly) for every later epoch.

    Parameters
    ----------
    hbm_budget_bytes:
        Cap on cached bytes; ``None`` = unbounded.  Exceeding it logs a
        warning, frees everything held, and disables the cache (streaming
        fallback) — never an error.
    echo_factor:
        Train each epoch-1 batch this many times (data echo).  1 = off.
    seed:
        Pass-shuffle seed; epoch order is ``jax.random.permutation`` keyed
        by ``fold_in(PRNGKey(seed), pass_id)``.
    shuffle:
        Permute batch replay order per epoch.  ``False`` replays capture
        order — the bit-parity mode.
    sample_shuffle:
        Also permute rows within each batch on device during replay.  Off by
        default: across-shard gathers turn into collectives on a multi-chip
        mesh, and batch-order shuffle already decorrelates epochs.
    """

    def __init__(
        self,
        hbm_budget_bytes: Optional[int] = None,
        echo_factor: int = 1,
        seed: int = 0,
        shuffle: bool = True,
        sample_shuffle: bool = False,
    ):
        self.budget = hbm_budget_bytes
        self.echo_factor = max(int(echo_factor), 1)
        self.seed = int(seed)
        self.shuffle = shuffle
        self.sample_shuffle = sample_shuffle
        self.active = True  # False after an overflow fallback
        self.ready = False  # True after a completed capture epoch
        self.nbytes = 0
        self._batches: List[Any] = []
        self._bucket_counts: Dict[tuple, int] = {}
        self._stacked = None  # capture-order stack (whole-pass program)

    @classmethod
    def from_flags(cls, reader=None, seed: Optional[int] = None,
                   echo_factor: Optional[int] = None,
                   shuffle: Optional[bool] = None) -> "PassCache":
        """The one flag→cache construction shared by every feed path
        (SGD.train, the CLI time job): budget from
        ``pass_cache_hbm_budget_mb``; seed from the ``seed`` flag unless
        the caller pins its own (the trainer passes its seed param); echo
        from ``data_echo_factor`` (overridable); shuffle from the reader's
        ``cache_pass_shuffle`` tag (a should_shuffle=False provider must
        replay in capture order)."""
        from paddle_tpu.utils import flags as _flags

        if echo_factor is None:
            echo_factor = _flags.get_flag("data_echo_factor")
        if shuffle is None:
            shuffle = bool(getattr(reader, "cache_pass_shuffle", True))
        return cls(
            hbm_budget_bytes=_flags.get_flag("pass_cache_hbm_budget_mb")
            << 20,
            echo_factor=echo_factor,
            seed=_flags.get_flag("seed") if seed is None else seed,
            shuffle=shuffle,
        )

    # -- capture ---------------------------------------------------------
    @property
    def n_batches(self) -> int:
        return len(self._batches)

    @property
    def n_buckets(self) -> int:
        return len(self._bucket_counts)

    def observe(self, batch) -> None:
        """Account + hold one staged batch; overflow disables the cache."""
        if not self.active or self.ready:
            return
        nb = batch_nbytes(batch)
        if self.budget is not None and self.nbytes + nb > self.budget:
            _log.warning(
                "pass cache over HBM budget (%d + %d > %d bytes after %d "
                "batches); falling back to streaming — every epoch will pay "
                "the host feed.  Raise pass_cache_hbm_budget_mb if the pass "
                "should fit (sizing: n_samples x bytes/sample wire form).",
                self.nbytes, nb, self.budget, self.n_batches,
            )
            self.drop()
            return
        from paddle_tpu.core.batch import batch_shape_key

        self.nbytes += nb
        self._batches.append(batch)
        key = batch_shape_key(batch) if isinstance(batch, dict) else ()
        self._bucket_counts[key] = self._bucket_counts.get(key, 0) + 1

    def capture(self, batches: Iterable) -> Iterator:
        """Wrap the epoch-1 staged-batch stream: observes each batch into
        the cache, applies data echo, and seals the cache when the epoch
        completes (an abandoned epoch never seals — a partial pass must not
        masquerade as the full one)."""
        if self.active and not self.ready and self._batches:
            # a previous capture epoch was abandoned mid-pass; restart the
            # accounting so the cache never holds a mixed partial pass
            self._batches = []
            self._bucket_counts = {}
            self.nbytes = 0
        for batch in batches:
            self.observe(batch)
            yield batch
            # echo even when the cache overflowed: echo amortizes the H2D
            # transfer of the batch in hand, which needs no cache
            for _ in range(self.echo_factor - 1):
                yield batch
        self.seal()

    def drop(self) -> None:
        """Release every held batch and disable caching (streaming mode)."""
        self.active = False
        self.ready = False
        self._batches = []
        self._bucket_counts = {}
        self._stacked = None
        self.nbytes = 0

    def seal(self) -> None:
        """Mark the captured pass complete; replay becomes available."""
        if not self.active or not self._batches:
            return
        self.ready = True
        _log.info(
            "pass cache sealed: %d batches (%d shape bucket(s)), %.1f MB "
            "device-resident; epochs >= 2 replay with zero H2D traffic",
            self.n_batches, self.n_buckets, self.nbytes / 1e6,
        )

    # -- replay ----------------------------------------------------------
    def _epoch_key(self, pass_id: int):
        import jax

        return jax.random.fold_in(jax.random.PRNGKey(self.seed), pass_id)

    def epoch_order(self, pass_id: int) -> List[int]:
        """Replay order for one epoch — an on-device
        ``jax.random.permutation`` over batch indices, fetched as ints (a
        few bytes of D2H; the data plane itself never moves)."""
        n = self.n_batches
        if not self.shuffle or n <= 1:
            return list(range(n))
        import jax

        perm = jax.random.permutation(self._epoch_key(pass_id), n)
        return [int(i) for i in np.asarray(perm)]

    def epoch(self, pass_id: int) -> Iterator:
        """Yield the cached pass for ``pass_id``, shuffled reproducibly."""
        assert self.ready, "pass cache not sealed; nothing to replay"
        if not self.sample_shuffle:
            for i in self.epoch_order(pass_id):
                yield self._batches[i]
            return
        import jax

        key = self._epoch_key(pass_id)
        for j, i in enumerate(self.epoch_order(pass_id)):
            b = self._batches[i]
            rows = _row_count(b)
            perm = jax.random.permutation(
                jax.random.fold_in(key, j + 1), rows
            )
            yield _permute_rows(b, perm)

    def stream(self, start_pass: int = 1) -> Iterator:
        """Endless cached replay: epoch(start_pass), epoch(start_pass+1), …
        — the steady-state feed of a cached training/timing loop."""
        assert self.ready, "pass cache not sealed; nothing to replay"
        p = start_pass
        while True:
            yield from self.epoch(p)
            p += 1

    def sample_batch(self):
        """One cached batch (capture order), for shape-keying the compiled
        programs that will consume this pass."""
        assert self.ready, "pass cache not sealed"
        return self._batches[0]

    def fits_stacked(self) -> bool:
        """Whether holding the stacked capture-order copy IN ADDITION to
        the per-batch cache fits the HBM budget — the whole-pass program
        costs a second copy of the pass, and a pass captured just under
        the budget must not silently double past it (the feed switch falls
        back to stepwise replay instead)."""
        return self.budget is None or 2 * self.nbytes <= self.budget

    def stacked(self):
        """The cached pass stacked on a leading [N, ...] axis in CAPTURE
        order — built once, held for the cache's lifetime, and reused by
        every epoch of the whole-pass program (the per-epoch shuffle rides
        as a permutation argument INSIDE the program, so replaying an
        epoch is one dispatch, not a restack).  Single-bucket only; costs
        one extra copy of the pass in HBM — callers gate on
        :meth:`fits_stacked` (SGD's feed switch does)."""
        assert self.ready, "pass cache not sealed; nothing to stack"
        assert self.n_buckets <= 1, (
            "stacked() needs a single shape bucket; this cache holds "
            f"{self.n_buckets} (use epoch() for bucketed replay)"
        )
        if self._stacked is None:
            import jax
            import jax.numpy as jnp

            self._stacked = jax.tree_util.tree_map(
                lambda *xs: jnp.stack(xs), *self._batches
            )
        return self._stacked

    def epoch_perm(self, pass_id: int):
        """This epoch's replay order as a device int32 vector — the
        permutation argument of the whole-pass epoch program."""
        import jax.numpy as jnp

        return jnp.asarray(self.epoch_order(pass_id), jnp.int32)

    def stacked_pass(self, pass_id: int):
        """The whole cached pass stacked on a leading [N, ...] axis in this
        epoch's shuffled order — ready for ``make_multi_train_step`` so a
        full cached epoch (or several, concatenated) runs in ONE dispatch.
        Requires a single shape bucket (stacking is shape-homogeneous; the
        bucketed feed replays via :meth:`epoch` instead)."""
        import jax

        perm = self.epoch_perm(pass_id)
        return jax.tree_util.tree_map(lambda x: x[perm], self.stacked())

    # -- introspection ---------------------------------------------------
    def summary(self) -> Dict[str, Any]:
        return {
            "active": self.active,
            "ready": self.ready,
            "n_batches": self.n_batches,
            "n_buckets": self.n_buckets,
            "mb": round(self.nbytes / 1e6, 2),
            "echo_factor": self.echo_factor,
            "budget_mb": (
                round(self.budget / 1e6, 2) if self.budget is not None else None
            ),
        }


def _row_count(batch) -> int:
    import jax

    for leaf in jax.tree_util.tree_leaves(batch):
        shape = getattr(leaf, "shape", None)
        if shape:
            return int(shape[0])
    return 1
