"""Async data plane: a background feed thread + bounded device queue.

The reference's ``DataProvider`` owns an async double-buffer queue so the
next batch is converted and staged while the trainer computes
(paddle/gserver/dataproviders/DataProvider.h DoubleBuffer, and
PyDataProvider2.cpp's background load thread).  The TPU-native equivalent:
``DevicePrefetcher`` runs the host-side feed — python converters, sharding,
``jax.device_put`` — on a worker thread, so batch N+1's host→device transfer
overlaps step N's device compute.  JAX dispatch is already asynchronous; the
piece that would otherwise serialize on the main thread is exactly this
host-side conversion + transfer issue, which the worker hides.

Queue depth 2 = the reference's double buffer: one batch in flight on the
device path, one staged.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Callable, Iterable, Iterator, Optional

__all__ = ["DevicePrefetcher", "prefetch"]


class _Failure:
    def __init__(self, exc: BaseException):
        self.exc = exc


_DONE = object()


class DevicePrefetcher:
    """Iterate ``prepare(item)`` for each item of ``source``, with the
    prepare calls running ahead on a background thread.

    ``prepare`` does the host-side feed work (DataFeeder conversion +
    shard_batch/device_put); the returned batches come out in order.
    ``wait_s`` accumulates main-thread time spent blocked on the queue —
    ~0 means the data plane fully hides behind compute; large means the
    reader/transfer is the bottleneck (the number the bench reports).
    """

    def __init__(
        self,
        source: Iterable,
        prepare: Optional[Callable] = None,
        depth: int = 2,
    ):
        self._q: "queue.Queue" = queue.Queue(maxsize=max(1, depth))
        self._prepare = prepare if prepare is not None else (lambda x: x)
        self._source = source
        self._stop = threading.Event()
        self._terminal = None  # sticky: _DONE or _Failure once seen
        self.wait_s = 0.0
        self._thread = threading.Thread(
            target=self._run, name="paddle-feed", daemon=True
        )
        self._thread.start()

    # -- worker ----------------------------------------------------------
    def _put(self, item) -> bool:
        """Bounded put that stays responsive to close(); False = stopping."""
        while not self._stop.is_set():
            try:
                self._q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def _run(self) -> None:
        try:
            for item in self._source:
                if self._stop.is_set() or not self._put(self._prepare(item)):
                    return
        except BaseException as e:  # re-raised on the consuming thread
            self._put(_Failure(e))
        else:
            self._put(_DONE)

    # -- consumer --------------------------------------------------------
    def __iter__(self) -> Iterator:
        return self

    def __next__(self):
        # terminal states are sticky: the worker is gone, so a consumer that
        # keeps calling next() (retry loops, second iteration) must keep
        # getting StopIteration / the error instead of blocking forever
        if self._terminal is not None:
            if self._terminal is _DONE:
                raise StopIteration
            raise self._terminal.exc
        t0 = time.perf_counter()
        got = self._q.get()
        self.wait_s += time.perf_counter() - t0
        if got is _DONE:
            self._terminal = got
            raise StopIteration
        if isinstance(got, _Failure):
            self._terminal = got
            raise got.exc
        return got

    def close(self) -> None:
        """Stop the worker (early loop exit); safe to call repeatedly."""
        self._stop.set()
        while True:  # unblock a worker stuck in put()
            try:
                self._q.get_nowait()
            except queue.Empty:
                break
        self._thread.join(timeout=5.0)

    def __enter__(self) -> "DevicePrefetcher":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def prefetch(source: Iterable, prepare: Optional[Callable] = None, depth: int = 2):
    """Generator face over DevicePrefetcher with guaranteed worker teardown
    even when the consumer abandons the loop early."""
    pf = DevicePrefetcher(source, prepare, depth)
    try:
        for item in pf:
            yield item
    finally:
        pf.close()
