"""Reader decorators — same surface as ``paddle.v2.reader`` (reference:
python/paddle/v2/reader/decorator.py).  A *reader creator* is a zero-arg
callable returning an iterable of samples; decorators wrap creators.
"""

from __future__ import annotations

import itertools
import queue
import random as _random
import threading
from typing import Any, Callable, Iterable, List

from paddle_tpu.utils.queues import bounded_put

Reader = Callable[[], Iterable[Any]]


def map_readers(func, *readers: Reader) -> Reader:
    """Apply func element-wise over zipped readers (decorator.py:30)."""

    def reader():
        rs = [r() for r in readers]
        for vals in zip(*rs):
            yield func(*vals)

    return reader


def shuffle(reader: Reader, buf_size: int, rng=None) -> Reader:
    """Buffered shuffle (decorator.py:60).

    ``rng`` is the shuffling stream (anything with ``.shuffle``, e.g.
    ``random.Random(seed)``); None uses the process-global ``random``
    stream, which ``paddle.init(seed=...)`` seeds — pass an explicit rng
    for order reproducible independent of other global-stream consumers
    (self-lint rule A203)."""
    stream = rng if rng is not None else _random

    def shuffled():
        buf: List[Any] = []
        for e in reader():
            buf.append(e)
            if len(buf) >= buf_size:
                stream.shuffle(buf)
                for b in buf:
                    yield b
                buf = []
        if buf:
            stream.shuffle(buf)
            for b in buf:
                yield b

    return shuffled


def chain(*readers: Reader) -> Reader:
    """Concatenate readers (decorator.py:90)."""

    def chained():
        for r in readers:
            for e in r():
                yield e

    return chained


class ComposeNotAligned(ValueError):
    pass


def compose(*readers: Reader, check_alignment: bool = True) -> Reader:
    """Zip readers into flat tuples (decorator.py:118)."""

    def _flatten(item):
        if isinstance(item, tuple):
            return item
        return (item,)

    def composed():
        rs = [r() for r in readers]
        if check_alignment:
            for items in itertools.zip_longest(*rs):
                if any(i is None for i in items):
                    raise ComposeNotAligned(
                        "readers of compose() have different lengths"
                    )
                yield sum((_flatten(i) for i in items), ())
        else:
            for items in zip(*rs):
                yield sum((_flatten(i) for i in items), ())

    return composed


def buffered(reader: Reader, size: int) -> Reader:
    """Background-thread prefetch queue (decorator.py:160) — the host-side
    double-buffering that replaces the reference DataProvider's async load
    thread (paddle/gserver/dataproviders/DataProvider.h DoubleBuffer).

    Teardown contract: abandoning the iteration early (``break``, GC,
    ``.close()`` on the generator) stops and JOINS the fill thread — the
    worker's puts are bounded polls against a stop flag, so it can never
    stay parked forever on a full queue (the leak class the lock
    sanitizer's thread_report drills check for).  A reader that raises on
    the fill thread re-raises on the CONSUMING thread (the DevicePrefetcher
    discipline) instead of silently truncating the stream."""

    class _End:
        pass

    def buffered_reader():
        q: queue.Queue = queue.Queue(maxsize=size)
        stop = threading.Event()
        error: List[BaseException] = []

        def fill():
            try:
                for d in reader():
                    if not bounded_put(q, d, stop.is_set):
                        return
            except BaseException as e:  # re-raised by the consumer
                error.append(e)
            finally:
                bounded_put(q, _End, stop.is_set)

        t = threading.Thread(
            target=fill, name="paddle-buffered-fill", daemon=True
        )
        t.start()
        try:
            while True:
                e = q.get()
                if e is _End:
                    if error:
                        raise error[0]
                    return
                yield e
        finally:
            stop.set()
            while True:  # wake a worker parked on a full queue
                try:
                    q.get_nowait()
                except queue.Empty:
                    break
            t.join(timeout=5.0)

    return buffered_reader


def firstn(reader: Reader, n: int) -> Reader:
    def firstn_reader():
        for i, item in enumerate(reader()):
            if i >= n:
                return
            yield item

    return firstn_reader


def cache(reader: Reader) -> Reader:
    """Materialize once in memory, replay after (the CACHE_PASS_IN_MEM mode of
    PyDataProvider2, reference PyDataProvider2.cpp:69)."""
    holder: List[Any] = []
    done = [False]

    def cached():
        if done[0]:
            for e in holder:
                yield e
            return
        for e in reader():
            holder.append(e)
            yield e
        done[0] = True

    return cached


def xmap_readers(mapper, reader: Reader, process_num: int, buffer_size: int, order: bool = False) -> Reader:
    """Parallel map over a thread pool (decorator.py:230).

    Same teardown contract as :func:`buffered`: a consumer that abandons
    the loop early stops, wakes, and joins the feed + worker threads —
    every queue op in the pool is a bounded poll against the stop flag.
    A mapper (or source reader) that raises re-raises on the CONSUMING
    thread: the dying thread still delivers its end sentinel, so the
    consumer drains, learns the error, and tears the pool down instead of
    blocking forever on a stream that will never finish."""

    class _End:
        pass

    def xreader():
        in_q: queue.Queue = queue.Queue(buffer_size)
        out_q: queue.Queue = queue.Queue(buffer_size)
        stop = threading.Event()
        errors: List[BaseException] = []

        def _get(q: queue.Queue):
            while not stop.is_set():
                try:
                    return q.get(timeout=0.1)
                except queue.Empty:
                    continue
            return _End

        def feed():
            try:
                for i, sample in enumerate(reader()):
                    if not bounded_put(in_q, (i, sample), stop.is_set):
                        return
            except BaseException as e:  # surfaced by the consumer
                errors.append(e)
            finally:
                # always hand every worker its sentinel — a dead feed must
                # not strand the pool waiting on in_q
                for _ in range(process_num):
                    if not bounded_put(in_q, _End, stop.is_set):
                        return

        def work():
            try:
                while True:
                    item = _get(in_q)
                    if item is _End:
                        return
                    i, sample = item
                    if not bounded_put(out_q, (i, mapper(sample)),
                                       stop.is_set):
                        return
            except BaseException as e:  # surfaced by the consumer
                errors.append(e)
            finally:
                bounded_put(out_q, _End, stop.is_set)

        threads = [threading.Thread(
            target=feed, name="paddle-xmap-feed", daemon=True
        )]
        threads.extend(
            threading.Thread(
                target=work, name=f"paddle-xmap-work-{n}", daemon=True
            )
            for n in range(process_num)
        )
        for t in threads:
            t.start()

        try:
            finished = 0
            pending = {}
            next_i = 0
            while finished < process_num:
                item = out_q.get()
                if item is _End:
                    finished += 1
                    continue
                if not order:
                    yield item[1]
                else:
                    pending[item[0]] = item[1]
                    while next_i in pending:
                        yield pending.pop(next_i)
                        next_i += 1
            if errors:
                raise errors[0]
            if order:
                for i in sorted(pending):
                    yield pending[i]
        finally:
            stop.set()
            for q in (in_q, out_q):
                while True:  # wake workers parked on full queues
                    try:
                        q.get_nowait()
                    except queue.Empty:
                        break
            for t in threads:
                t.join(timeout=5.0)

    return xreader
