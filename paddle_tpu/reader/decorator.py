"""Reader decorators — same surface as ``paddle.v2.reader`` (reference:
python/paddle/v2/reader/decorator.py).  A *reader creator* is a zero-arg
callable returning an iterable of samples; decorators wrap creators.
"""

from __future__ import annotations

import itertools
import queue
import random as _random
import threading
from typing import Any, Callable, Iterable, List

Reader = Callable[[], Iterable[Any]]


def map_readers(func, *readers: Reader) -> Reader:
    """Apply func element-wise over zipped readers (decorator.py:30)."""

    def reader():
        rs = [r() for r in readers]
        for vals in zip(*rs):
            yield func(*vals)

    return reader


def shuffle(reader: Reader, buf_size: int, rng=None) -> Reader:
    """Buffered shuffle (decorator.py:60).

    ``rng`` is the shuffling stream (anything with ``.shuffle``, e.g.
    ``random.Random(seed)``); None uses the process-global ``random``
    stream, which ``paddle.init(seed=...)`` seeds — pass an explicit rng
    for order reproducible independent of other global-stream consumers
    (self-lint rule A203)."""
    stream = rng if rng is not None else _random

    def shuffled():
        buf: List[Any] = []
        for e in reader():
            buf.append(e)
            if len(buf) >= buf_size:
                stream.shuffle(buf)
                for b in buf:
                    yield b
                buf = []
        if buf:
            stream.shuffle(buf)
            for b in buf:
                yield b

    return shuffled


def chain(*readers: Reader) -> Reader:
    """Concatenate readers (decorator.py:90)."""

    def chained():
        for r in readers:
            for e in r():
                yield e

    return chained


class ComposeNotAligned(ValueError):
    pass


def compose(*readers: Reader, check_alignment: bool = True) -> Reader:
    """Zip readers into flat tuples (decorator.py:118)."""

    def _flatten(item):
        if isinstance(item, tuple):
            return item
        return (item,)

    def composed():
        rs = [r() for r in readers]
        if check_alignment:
            for items in itertools.zip_longest(*rs):
                if any(i is None for i in items):
                    raise ComposeNotAligned(
                        "readers of compose() have different lengths"
                    )
                yield sum((_flatten(i) for i in items), ())
        else:
            for items in zip(*rs):
                yield sum((_flatten(i) for i in items), ())

    return composed


def buffered(reader: Reader, size: int) -> Reader:
    """Background-thread prefetch queue (decorator.py:160) — the host-side
    double-buffering that replaces the reference DataProvider's async load
    thread (paddle/gserver/dataproviders/DataProvider.h DoubleBuffer)."""

    class _End:
        pass

    def buffered_reader():
        q: queue.Queue = queue.Queue(maxsize=size)

        def fill():
            try:
                for d in reader():
                    q.put(d)
            finally:
                q.put(_End)

        t = threading.Thread(target=fill, daemon=True)
        t.start()
        while True:
            e = q.get()
            if e is _End:
                return
            yield e

    return buffered_reader


def firstn(reader: Reader, n: int) -> Reader:
    def firstn_reader():
        for i, item in enumerate(reader()):
            if i >= n:
                return
            yield item

    return firstn_reader


def cache(reader: Reader) -> Reader:
    """Materialize once in memory, replay after (the CACHE_PASS_IN_MEM mode of
    PyDataProvider2, reference PyDataProvider2.cpp:69)."""
    holder: List[Any] = []
    done = [False]

    def cached():
        if done[0]:
            for e in holder:
                yield e
            return
        for e in reader():
            holder.append(e)
            yield e
        done[0] = True

    return cached


def xmap_readers(mapper, reader: Reader, process_num: int, buffer_size: int, order: bool = False) -> Reader:
    """Parallel map over a thread pool (decorator.py:230)."""

    class _End:
        pass

    def xreader():
        in_q: queue.Queue = queue.Queue(buffer_size)
        out_q: queue.Queue = queue.Queue(buffer_size)

        def feed():
            for i, sample in enumerate(reader()):
                in_q.put((i, sample))
            for _ in range(process_num):
                in_q.put(_End)

        def work():
            while True:
                item = in_q.get()
                if item is _End:
                    out_q.put(_End)
                    return
                i, sample = item
                out_q.put((i, mapper(sample)))

        threading.Thread(target=feed, daemon=True).start()
        workers = [threading.Thread(target=work, daemon=True) for _ in range(process_num)]
        for w in workers:
            w.start()

        finished = 0
        pending = {}
        next_i = 0
        while finished < process_num:
            item = out_q.get()
            if item is _End:
                finished += 1
                continue
            if not order:
                yield item[1]
            else:
                pending[item[0]] = item[1]
                while next_i in pending:
                    yield pending.pop(next_i)
                    next_i += 1
        if order:
            for i in sorted(pending):
                yield pending[i]

    return xreader
