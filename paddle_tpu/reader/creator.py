"""Reader creators (reference: python/paddle/v2/reader/creator.py) — build
readers from arrays, text files, and recordio shards, locally or through the
elastic master.

The reference's ``cloud_reader`` spoke to the Go master via etcd endpoints;
here the master is ``paddle_tpu.master`` (in-process Service or a
``(host, port)`` Server address) and records come back over its lease/ack
protocol — same at-least-once semantics, no etcd dependency.
"""

from __future__ import annotations

import glob as _glob
import pickle
from typing import Sequence


def np_array(x):
    """A reader yielding the rows of a numpy array (reference creator.np_array)."""

    def reader():
        yield from x

    return reader


def text_file(path: str):
    """A reader yielding stripped lines of a text file (reference
    creator.text_file)."""

    def reader():
        with open(path) as f:
            for line in f:
                yield line.rstrip("\n")

    return reader


def _expand(paths: Sequence[str]):
    if isinstance(paths, str):
        paths = [paths]
    out = []
    for p in paths:
        hits = sorted(_glob.glob(p))
        out.extend(hits if hits else [p])
    return out


def recordio_local(paths, buf_size: int = 100, pickled: bool = True):
    """A reader over local recordio shard files (glob patterns supported) —
    reference creator.recordio_local.  ``pickled=True`` unpickles each
    record (the dataset.common.convert format); False yields raw bytes."""
    from paddle_tpu.io import recordio
    from paddle_tpu.reader.decorator import buffered

    def reader():
        for path in _expand(paths):
            with recordio.Reader(path) as r:
                while True:
                    rec = r.next()
                    if rec is None:
                        break
                    yield pickle.loads(rec) if pickled else rec  # wire: allow[A206] operator-written recordio dataset (common.convert pickled these samples to local disk); v2 reader-API parity, never a network peer's bytes

    return buffered(reader, buf_size)


def cloud_reader(paths, master, buf_size: int = 64, pickled: bool = True):
    """A reader that leases tasks from the elastic master (reference
    creator.cloud_reader over the Go master client, creator.py:87).

    ``master`` is a ``paddle_tpu.master.Service`` (in-process) or a
    ``(host, port)`` address of a ``master.Server``.  The shard set is
    registered once; each reader pass drains the master's task queue with
    consume-then-ack leases, so concurrent trainers split the shards and a
    crashed trainer's tasks re-serve."""
    from paddle_tpu.master import Client
    from paddle_tpu.reader.decorator import buffered

    client = Client(master)
    client.set_dataset(_expand(paths))

    def reader():
        while True:
            rec = client.next_record()
            if rec is None:
                return
            yield pickle.loads(rec) if pickled else rec  # wire: allow[A206] records are the operator's own common.convert output streamed back opaquely by the master; the RPC envelope around them rides the safe codec

    return buffered(reader, buf_size)
