"""ResNet family — the model_zoo ResNet (reference:
v1_api_demo/model_zoo/resnet/resnet.py, built from the same conv/batch_norm/
addto DSL primitives; benchmark/paddle/image drivers are the perf baseline).

Bottleneck blocks as in the reference: conv_bn_layer chains with an addto
shortcut.  Everything stays NHWC 4D between layers so XLA keeps the conv
chain fused and MXU-tiled.
"""

from __future__ import annotations

from typing import Optional

import paddle_tpu as paddle
from paddle_tpu.core.topology import LayerOutput


def conv_bn(
    input: LayerOutput,
    ch_out: int,
    filter_size: int,
    stride: int,
    padding: int,
    active_type=None,
    ch_in: Optional[int] = None,
) -> LayerOutput:
    tmp = paddle.layer.img_conv(
        input,
        filter_size=filter_size,
        num_filters=ch_out,
        num_channels=ch_in,
        stride=stride,
        padding=padding,
        act=paddle.activation.Linear(),
        bias_attr=False,
    )
    return paddle.layer.batch_norm(tmp, act=active_type or paddle.activation.Relu())


def shortcut(input: LayerOutput, ch_out: int, stride: int) -> LayerOutput:
    ch_in = input.conf.attrs.get("channels") or input.conf.attrs.get("in_c")
    if ch_in != ch_out or stride != 1:
        return conv_bn(input, ch_out, 1, stride, 0, paddle.activation.Linear())
    return input


def bottleneck_block(input: LayerOutput, ch_out: int, stride: int) -> LayerOutput:
    short = shortcut(input, ch_out * 4, stride)
    conv1 = conv_bn(input, ch_out, 1, stride, 0)
    conv2 = conv_bn(conv1, ch_out, 3, 1, 1)
    conv3 = conv_bn(conv2, ch_out * 4, 1, 1, 0, paddle.activation.Linear())
    return paddle.layer.addto(
        [short, conv3], act=paddle.activation.Relu(), bias_attr=False
    )


def basic_block(input: LayerOutput, ch_out: int, stride: int) -> LayerOutput:
    short = shortcut(input, ch_out, stride)
    conv1 = conv_bn(input, ch_out, 3, stride, 1)
    conv2 = conv_bn(conv1, ch_out, 3, 1, 1, paddle.activation.Linear())
    return paddle.layer.addto(
        [short, conv2], act=paddle.activation.Relu(), bias_attr=False
    )


def layer_warp(block_fn, input, ch_out, count, stride):
    out = block_fn(input, ch_out, stride)
    for _ in range(count - 1):
        out = block_fn(out, ch_out, 1)
    return out


_DEPTH_CFG = {
    18: (basic_block, [2, 2, 2, 2]),
    34: (basic_block, [3, 4, 6, 3]),
    50: (bottleneck_block, [3, 4, 6, 3]),
    101: (bottleneck_block, [3, 4, 23, 3]),
    152: (bottleneck_block, [3, 8, 36, 3]),
}


def resnet(
    img: LayerOutput,
    depth: int = 50,
    class_num: int = 1000,
    img_size: int = 224,
    num_channels: int = 3,
) -> LayerOutput:
    """reference resnet.py deep_res_net; returns softmax predictions."""
    block_fn, counts = _DEPTH_CFG[depth]
    conv1 = conv_bn(
        img, 64, filter_size=7, stride=2, padding=3, ch_in=num_channels
    )
    pool1 = paddle.layer.img_pool(conv1, pool_size=3, stride=2, padding=1)
    res1 = layer_warp(block_fn, pool1, 64, counts[0], 1)
    res2 = layer_warp(block_fn, res1, 128, counts[1], 2)
    res3 = layer_warp(block_fn, res2, 256, counts[2], 2)
    res4 = layer_warp(block_fn, res3, 512, counts[3], 2)
    final_hw = res4.conf.attrs["out_h"]
    pool2 = paddle.layer.img_pool(
        res4, pool_size=final_hw, stride=1, pool_type=paddle.pooling.Avg()
    )
    return paddle.layer.fc(pool2, size=class_num, act=paddle.activation.Softmax())


def resnet_cost(
    depth: int = 50, class_num: int = 1000, img_size: int = 224, num_channels: int = 3
):
    img = paddle.layer.data(
        "image",
        paddle.data_type.dense_vector(img_size * img_size * num_channels),
        height=img_size,
        width=img_size,
    )
    label = paddle.layer.data("label", paddle.data_type.integer_value(class_num))
    predict = resnet(img, depth, class_num, img_size, num_channels)
    cost = paddle.layer.classification_cost(input=predict, label=label)
    return cost, predict
