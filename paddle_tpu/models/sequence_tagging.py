"""Sequence-tagging NER demo — the v1_api_demo/sequence_tagging topology
family (linear_crf.py / rnn_crf.py) rebuilt TPU-first.

The reference's high-dimensional sparse-feature path (sparse_binary_vector
slots + sparse remote parameter updates through the pserver) becomes a
sparse-sharded embedding: `ParamAttr(sparse_update=True)` row-shards the
table over the mesh MODEL axis and the gather rides XLA collectives
(parallel/sharding.py) — the test_CompareSparse.cpp contract (sparse must
converge like dense) is covered by tests/test_sparse_sharding.py and
exercised end-to-end here through the CRF tagger."""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

import paddle_tpu as paddle
from paddle_tpu.core.topology import LayerOutput

L = paddle.layer
A = paddle.activation


def ner_crf_cost(
    vocab: int,
    num_labels: int,
    word_dim: int = 32,
    hidden_dim: int = 32,
    sparse_update: bool = True,
    shard_axis: Optional[str] = None,
) -> Tuple[LayerOutput, LayerOutput]:
    """Bi-directional RNN + linear-chain CRF tagger (rnn_crf.py shape).
    Returns (crf cost, crf_decoding output).  Data slots: `word` id
    sequence, `label` id sequence."""
    word = L.data("word", paddle.data_type.integer_value_sequence(vocab))
    label = L.data("label", paddle.data_type.integer_value_sequence(num_labels))
    emb = L.embedding(
        word,
        size=word_dim,
        param_attr=paddle.attr.ParamAttr(sparse_update=sparse_update),
        name="word_emb",
    )
    fc_attr = (
        paddle.attr.ExtraAttr(shard_axis=shard_axis) if shard_axis else None
    )
    fwd = L.recurrent(
        L.fc(emb, size=hidden_dim, act=A.Linear(), name="proj_f", layer_attr=fc_attr),
        act=A.Tanh(),
        name="rnn_f",
    )
    bwd = L.recurrent(
        L.fc(emb, size=hidden_dim, act=A.Linear(), name="proj_b", layer_attr=fc_attr),
        act=A.Tanh(),
        reverse=True,
        name="rnn_b",
    )
    feat = L.fc(
        L.concat([fwd, bwd]), size=num_labels, act=A.Linear(), name="crf_input"
    )
    # crf + crf_decoding share the transition weights by parameter name,
    # exactly like the reference configs (linear_crf.py ParamAttr("crfw"))
    crfw = paddle.attr.ParamAttr(name="crfw")
    cost = L.crf(
        input=feat, label=label, size=num_labels, param_attr=crfw, name="crf_cost"
    )
    decode = L.crf_decoding(
        input=feat, size=num_labels, param_attr=crfw, name="crf_decode"
    )
    return cost, decode


def synthetic_tag_reader(
    vocab: int, num_labels: int, n: int = 128, seed: int = 0
):
    """Synthetic NER-ish data: each word id deterministically maps to a tag
    (id % num_labels), so the tagger is learnable from the embedding alone."""
    def reader():
        rng = np.random.RandomState(seed)
        for _ in range(n):
            length = int(rng.randint(3, 10))
            words = rng.randint(0, vocab, size=length)
            tags = words % num_labels
            yield list(words), list(tags)

    return reader
