"""Transformer-base MT — BASELINE.json configs #5 ("new config", no
reference implementation; it stresses the graph→HLO lowering the way the
reference's paddle/framework OpDesc path would have).

Pre-LN encoder-decoder (Vaswani-style dims via `transformer_base`), built
from the layer DSL: multi_head_attention / layer_norm / pos_encoding
(layers/attention.py) + per-timestep fc for the FFN, residuals via addto.
Training computes per-step softmax CE over the target vocabulary with
padding masked (same convention as models/seq2seq.py).

TPU notes: the whole model is matmuls + fused elementwise chains — XLA
tiles every attention/FFN matmul onto the MXU; bf16 mixed precision applies
per-layer with f32 softmax/LN statistics (see layers/attention.py).
"""

from __future__ import annotations

from typing import Tuple

import paddle_tpu as paddle
from paddle_tpu.core.topology import LayerOutput

L = paddle.layer
A = paddle.activation


def _ffn(x: LayerOutput, d_model: int, d_ff: int, name: str,
         moe_experts: int = 0) -> LayerOutput:
    if moe_experts:
        # sparse FFN: top-1-routed experts, sharded over the mesh model
        # axis (expert parallelism — layers/moe.py)
        return L.moe_layer(
            x, expert_hidden=d_ff, num_experts=moe_experts, size=d_model,
            layer_attr=paddle.attr.ExtraAttr(shard_axis="model"),
            name=f"{name}_moe",
        )
    h = L.fc(x, size=d_ff, act=A.Relu(), name=f"{name}_ff1")
    return L.fc(h, size=d_model, act=A.Identity(), name=f"{name}_ff2")


def _encoder_layer(x, d_model, n_heads, d_ff, name, sp_axis=None,
                   moe_experts=0):
    att = L.multi_head_attention(
        L.layer_norm(x, name=f"{name}_ln1"), n_heads=n_heads,
        seq_parallel_axis=sp_axis, name=f"{name}_att"
    )
    x = L.addto([x, att], act=A.Identity(), bias_attr=False, name=f"{name}_res1")
    ff = _ffn(L.layer_norm(x, name=f"{name}_ln2"), d_model, d_ff, name,
              moe_experts)
    return L.addto([x, ff], act=A.Identity(), bias_attr=False, name=f"{name}_res2")


def _decoder_layer(x, enc, d_model, n_heads, d_ff, name, sp_axis=None):
    self_att = L.multi_head_attention(
        L.layer_norm(x, name=f"{name}_ln1"),
        n_heads=n_heads,
        causal=True,
        seq_parallel_axis=sp_axis,
        name=f"{name}_self",
    )
    x = L.addto([x, self_att], act=A.Identity(), bias_attr=False, name=f"{name}_res1")
    cross = L.multi_head_attention(
        L.layer_norm(x, name=f"{name}_ln2"),
        key_value=enc,
        n_heads=n_heads,
        name=f"{name}_cross",
    )
    x = L.addto([x, cross], act=A.Identity(), bias_attr=False, name=f"{name}_res2")
    ff = _ffn(L.layer_norm(x, name=f"{name}_ln3"), d_model, d_ff, name)
    return L.addto([x, ff], act=A.Identity(), bias_attr=False, name=f"{name}_res3")


def transformer_cost(
    src_vocab: int,
    trg_vocab: int,
    d_model: int = 512,
    n_heads: int = 8,
    n_layers: int = 6,
    d_ff: int = 2048,
    seq_parallel_axis=None,
    moe_experts: int = 0,
) -> Tuple[LayerOutput, LayerOutput]:
    """Training topology.  Data slots: src_word ids, trg_word ids (bos-led
    decoder input), trg_next ids (shifted targets) — same slot convention as
    models/seq2seq.py so the NMT readers interchange.  moe_experts>0 swaps
    the encoder FFNs for expert-parallel MoE blocks."""
    src = L.data("src_word", paddle.data_type.integer_value_sequence(src_vocab))
    trg = L.data("trg_word", paddle.data_type.integer_value_sequence(trg_vocab))
    lbl = L.data("trg_next", paddle.data_type.integer_value_sequence(trg_vocab))

    scale = float(d_model) ** 0.5
    x = L.pos_encoding(
        L.embedding(src, size=d_model, name="src_emb"), emb_scale=scale
    )
    for i in range(n_layers):
        x = _encoder_layer(x, d_model, n_heads, d_ff, f"enc{i}",
                           seq_parallel_axis, moe_experts)
    enc = L.layer_norm(x, name="enc_ln")

    y = L.pos_encoding(
        L.embedding(trg, size=d_model, name="trg_emb"), emb_scale=scale
    )
    for i in range(n_layers):
        y = _decoder_layer(y, enc, d_model, n_heads, d_ff, f"dec{i}", seq_parallel_axis)
    dec = L.layer_norm(y, name="dec_ln")

    logits = L.fc(dec, size=trg_vocab, act=A.Softmax(), name="dec_out")
    cost = L.classification_cost(input=logits, label=lbl, name="mt_cost")
    return cost, logits


def transformer_base(src_vocab: int, trg_vocab: int):
    """The Transformer-base configuration (d_model 512, 8 heads, 6+6 layers,
    FFN 2048)."""
    return transformer_cost(src_vocab, trg_vocab, 512, 8, 6, 2048)
