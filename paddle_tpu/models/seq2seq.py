"""Seq2seq NMT with attention — the north-star seq2seq config (BASELINE.json;
the reference era's demo/seqToseq text_generation topology: bi-GRU encoder +
attention GRU decoder, built here from the same recurrent_group/
simple_attention DSL the reference uses: trainer_config_helpers
networks.py simple_attention, layers.py recurrent_group).

Training: one jitted graph, per-step softmax CE over target vocab with
padding masked.  Generation: the decoder step sub-network is re-used as the
body of a jitted beam/greedy scan (ops/beam.py) — beam search runs on-device,
unlike the reference's host-side RecurrentGradientMachine beamSearch.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.core.batch import SeqTensor
from paddle_tpu.core.compiler import CompiledNetwork
from paddle_tpu.core.topology import LayerOutput, Topology

L = paddle.layer
A = paddle.activation


def make_fused_step(w, enc, ep, emask, *, gate_act, act, att_act):
    """``step(ids [N], h [N,H]) -> (logp [N,V], h_t [N,H])`` over the fused
    attention-GRU decode chain — THE per-token numerical contract every
    decode face shares: the one-shot beam/greedy path here, and the
    serving plane's paged beam program (serving/engine.py) gathers its
    ``enc``/``ep`` through the page table and calls this same builder.
    One closure, one chain, bit-identity by construction.

    ``ep`` must already carry the folded state-projection bias (sp_b adds
    at prefill time, never per step); ``w`` is the
    :meth:`Seq2SeqGenerator.fused_decode_weights` bundle."""
    from paddle_tpu.ops.rnn import attention_gru_step

    def step(ids, h):
        xg = jnp.take(w["emb_w"], ids, axis=0) @ w["w_emb"]
        if w["xg_bias"] is not None:
            xg = xg + w["xg_bias"]
        h_t = attention_gru_step(
            xg, h, enc, ep, emask, w["w1"], w["v"], w["w_ctx"], w["w_c"],
            gate_act=gate_act, act=act, att_act=att_act,
        )
        logits = h_t @ w["head_w"]
        if w["head_b"] is not None:
            logits = logits + w["head_b"]
        prob = jax.nn.softmax(logits, axis=-1)
        return jnp.log(jnp.maximum(prob, 1e-9)), h_t

    return step


def encoder_net(
    src_word: LayerOutput, word_dim: int, hidden_dim: int
) -> Tuple[LayerOutput, LayerOutput]:
    """Bi-GRU encoder; returns (encoded_seq [B,S,2H], encoded_proj)."""
    emb = L.embedding(src_word, size=word_dim, name="src_emb")
    # simple_gru2: the FUSED grumemory form (one lax.scan) — same math as
    # simple_gru's recurrent_group, but the fast path for the NMT benchmark
    fwd = paddle.networks.simple_gru2(emb, size=hidden_dim, name="enc_fw")
    bwd = paddle.networks.simple_gru2(
        emb, size=hidden_dim, reverse=True, name="enc_bw"
    )
    enc = L.concat([fwd, bwd], name="enc")
    enc_proj = L.fc(
        enc, size=hidden_dim, act=A.Identity(), bias_attr=False, name="enc_proj"
    )
    return enc, enc_proj


def decoder_step_builder(hidden_dim: int, trg_vocab: int, boot: LayerOutput):
    """Returns the recurrent_group step fn used for BOTH training and
    generation — identical weights, mirroring the reference's shared
    SubModelConfig.  `boot` is an OUTER layer captured by closure (reference
    memory boot_layer semantics)."""

    def step(trg_emb_t, enc_seq, enc_p):
        state = L.memory("dec_state", hidden_dim, boot_layer=boot)
        context = paddle.networks.simple_attention(
            encoded_sequence=enc_seq,
            encoded_proj=enc_p,
            decoder_state=state,
            name="att",
        )
        inputs = L.fc(
            [context, trg_emb_t],
            size=hidden_dim * 3,
            act=A.Identity(),
            bias_attr=False,
            name="dec_in_proj",
        )
        gru = L.gru_step(inputs, state, size=hidden_dim, name="dec_state")
        out = L.fc(gru, size=trg_vocab, act=A.Softmax(), name="dec_out")
        return out

    return step


def _encoder_and_boot(src_vocab: int, word_dim: int, hidden_dim: int):
    """Shared source-side block: training and generation topologies MUST
    build these layers identically (same names, same auto-name consumption)
    for the tar parameter round-trip to map weights."""
    src = L.data("src_word", paddle.data_type.integer_value_sequence(src_vocab))
    enc, enc_proj = encoder_net(src, word_dim, hidden_dim)
    boot = L.fc(
        L.first_seq(enc, name="enc_first"),
        size=hidden_dim,
        act=A.Tanh(),
        name="dec_boot",
    )
    return enc, enc_proj, boot


def seq2seq_cost(
    src_vocab: int,
    trg_vocab: int,
    word_dim: int = 128,
    hidden_dim: int = 256,
) -> Tuple[LayerOutput, LayerOutput]:
    """Training topology.  Data slots: src_word ids, trg_word ids (bos-led),
    trg_next ids (the shifted targets)."""
    enc, enc_proj, boot = _encoder_and_boot(src_vocab, word_dim, hidden_dim)
    trg = L.data("trg_word", paddle.data_type.integer_value_sequence(trg_vocab))
    lbl = L.data("trg_next", paddle.data_type.integer_value_sequence(trg_vocab))
    trg_emb = L.embedding(trg, size=word_dim, name="trg_emb")

    step = decoder_step_builder(hidden_dim, trg_vocab, boot)
    dec = L.recurrent_group(
        step,
        [
            trg_emb,
            L.StaticInput(enc, is_seq=True),
            L.StaticInput(enc_proj, is_seq=True),
        ],
        name="decoder",
    )
    cost = L.classification_cost(input=dec, label=lbl, name="nmt_cost")
    return cost, dec


def seq2seq_generation(
    src_vocab: int,
    trg_vocab: int,
    word_dim: int = 128,
    hidden_dim: int = 256,
    bos_id: int = 0,
    eos_id: int = 1,
    beam_size: int = 4,
    max_length: int = 32,
) -> LayerOutput:
    """Generation topology over the SAME step function and layer names as
    :func:`seq2seq_cost`, with the target sequence replaced by a
    GeneratedInput beam (reference demo/seqToseq gen config:
    gen_trans_file + beam_search in seqToseq_net.py).  Because the beam
    layer shares the training group's name ("decoder"), trained parameters
    load via the tar round-trip; copy the target embedding with
    ``gen_params.set("decoder.@gen_emb.w", trained.get("trg_emb.w"))``.

    Build with the same auto-name state as the training topology (e.g. call
    ``paddle_tpu.core.topology.reset_auto_names()`` before each build) so
    the step's internal auto-named layers line up."""
    enc, enc_proj, boot = _encoder_and_boot(src_vocab, word_dim, hidden_dim)
    step = decoder_step_builder(hidden_dim, trg_vocab, boot)
    return L.beam_search(
        step,
        input=[
            L.GeneratedInput(trg_vocab, word_dim),
            L.StaticInput(enc, is_seq=True),
            L.StaticInput(enc_proj, is_seq=True),
        ],
        bos_id=bos_id,
        eos_id=eos_id,
        beam_size=beam_size,
        max_length=max_length,
        name="decoder",
    )


def _subgraph(topo: Topology, names) -> Topology:
    """Rebuild a LayerOutput graph for `names` from an existing Topology and
    return the pruned Topology over just their ancestors."""
    cache = {}

    def build(n: str) -> LayerOutput:
        if n not in cache:
            conf = topo.get(n)
            cache[n] = LayerOutput(conf, [build(p) for p in conf.inputs])
        return cache[n]

    return Topology([build(n) for n in names])


class Seq2SeqGenerator:
    """On-device generation over a trained seq2seq net (capi-style inference
    surface; reference: paddle/gserver/.../RecurrentGradientMachine
    generation mode + demo seqToseq gen configs)."""

    def __init__(
        self,
        parameters: "paddle.parameters.Parameters",
        src_vocab: int,
        trg_vocab: int,
        word_dim: int = 128,
        hidden_dim: int = 256,
        bos_id: int = 0,
        eos_id: int = 1,
        max_length: int = 32,
        beam_size: int = 4,
        candidate_adjust_fn=None,
        drop_fn=None,
        norm_fn=None,
    ):
        self.params = parameters
        self.net = parameters.network
        self.topo = self.net.topology
        self.hidden_dim = hidden_dim
        self.trg_vocab = trg_vocab
        self.bos_id = bos_id
        self.eos_id = eos_id
        self.max_length = max_length
        self.beam_size = beam_size
        # user beam-search control hooks (ops/beam.py module docstring;
        # reference RecurrentGradientMachine.h:70-120 callbacks)
        self.candidate_adjust_fn = candidate_adjust_fn
        self.drop_fn = drop_fn
        self.norm_fn = norm_fn

        dec_conf = self.topo.get("decoder")
        self._sub_topo = dec_conf.attrs["_sub_topology"]
        self._subnet = CompiledNetwork(self._sub_topo)
        self._scan_names = dec_conf.attrs["_scan_placeholders"]
        self._static_info = dec_conf.attrs["_static_placeholders"]
        self._memories = dec_conf.attrs["_memories"]
        # Fused decode stepping: when the decoder step matches the
        # attention-GRU idiom (the same matcher the training scan uses,
        # layers/attention.py), each beam step runs the fused chain
        # (ops/rnn.attention_gru_step) + the vocab head directly instead of
        # interpreting the sub-network layer by layer — in particular the
        # [B*K, S]-row expand+fc state projection collapses to one
        # [B*K, H] GEMM per step.  Structural mismatch -> generic stepping.
        self._match = None
        if len(self._memories) == 1:
            from paddle_tpu.layers.attention import match_attention_gru_step

            m = match_attention_gru_step(
                self._sub_topo.layers,
                self._memories[0],
                set(self._scan_names),
                {p for p, is_seq in self._static_info if is_seq},
            )
            head = self._sub_topo.layers.get("dec_out")
            if (
                m is not None
                and len(m.scan_slots) == 1
                and m.scan_slots[0][1] == self._scan_names[0]
                and head is not None
                and head.type == "fc"
                and head.act == "softmax"
                and head.drop_rate == 0.0
                and tuple(head.inputs) == (m.gru,)
            ):
                self._match = m
        # Pruned encoder-only graph: generation must not pay for the training
        # decoder scan + softmax + cost (and must not require dummy trg slots).
        self._enc_net = CompiledNetwork(
            _subgraph(self.topo, ["enc", "enc_proj", "dec_boot"])
        )

    # -- encoder forward up to the decoder's static inputs ---------------
    def _encode(self, batch, gp):
        outs, _ = self._enc_net.apply(
            gp, batch, state=self.params.state, train=False
        )
        return outs

    def fused_decode_weights(self, gp):
        """Device-ready weight bundle of the fused attention-GRU decode
        step, or None when the decoder step did not match the fused idiom.
        Shared by the beam/greedy stepping here AND the serving plane's
        block-paged decode step (serving/engine.py) — one extraction, one
        numerical contract.  ``gp`` must already be materialized
        (``self.net.materialize_shared``)."""
        if self._match is None:
            return None
        mt = self._match
        sub_params = gp["decoder"]
        lp = lambda n: self._subnet.layer_params(sub_params, n)
        p_in = lp(mt.in_proj)
        p_gru = lp(mt.gru)
        p_sp = lp(mt.state_proj)
        p_head = lp("dec_out")
        bias = sum(p["b"] for p in (p_in, p_gru) if "b" in p)
        return {
            "emb_w": gp["trg_emb"]["w"],
            "w_emb": p_in[f"w{mt.scan_slots[0][0]}"],
            # target-side gate bias (in_proj + gru biases folded); None when
            # both layers are bias-free
            "xg_bias": None if isinstance(bias, int) else bias,
            "w1": jnp.concatenate([p_sp["w0"], p_gru["w_h"]], axis=1),
            "v": lp(mt.scores)["w0"][:, 0],
            "w_ctx": p_in[f"w{mt.ctx_slot}"],
            "w_c": p_gru["w_c"],
            "head_w": p_head["w0"],
            "head_b": p_head.get("b"),
            # state-projection bias folds into the prefill-time score keys
            # (ep = enc_proj + sp_b), NOT into the per-step chain
            "sp_b": p_sp.get("b"),
        }

    def decode_weight_bytes(self, gp=None) -> int:
        """Resident bytes of the fused decode bundle at full precision —
        the f32 baseline of the serving weight-only-int8 capacity math
        (ops.quantize.weight_bundle_bytes measures the quantized side)."""
        if gp is None:
            gp = self.net.materialize_shared(self.params.params)
        w = self.fused_decode_weights(gp)
        if w is None:
            return 0
        from paddle_tpu.ops.quantize import weight_bundle_bytes

        return weight_bundle_bytes(w)

    def _step_fn(self, statics, gp):
        """Build step_fn(ids, carry) for beam/greedy: embeds ids with the
        trained trg_emb table, runs the decoder sub-network once — through
        the fused attention-GRU step when the topology matched."""
        from paddle_tpu.utils.flags import get_flag

        emb_w = gp["trg_emb"]["w"]
        sub_params = gp["decoder"]
        m0 = self._memories[0] if self._memories else None

        if self._match is not None and get_flag("fused_attention_gru"):
            mt = self._match
            w = self.fused_decode_weights(gp)
            enc_t = statics[mt.enc_name]
            ep = statics[mt.ep_name].data
            if w["sp_b"] is not None:
                ep = ep + w["sp_b"]
            emask = enc_t.mask(bool) if enc_t.lengths is not None else None
            fused = make_fused_step(
                w, enc_t.data, ep, emask,
                gate_act=mt.gate_act, act=mt.act, att_act=mt.att_act,
            )

            def step_fn(ids, carry):
                logp, h_t = fused(ids, carry[m0.name])
                return logp, {m0.name: h_t}

            return step_fn

        def step_fn(ids, carry):
            sub_batch = dict(statics)
            emb = jnp.take(emb_w, ids, axis=0)
            sub_batch[self._scan_names[0]] = SeqTensor(emb)
            for m in self._memories:
                sub_batch[m.name] = SeqTensor(carry[m.name])
            outs, _ = self._subnet.apply(sub_params, sub_batch, train=False)
            new_carry = {m.name: outs[m.attrs["link"]].data for m in self._memories}
            prob = outs["dec_out"].data
            return jnp.log(jnp.maximum(prob, 1e-9)), new_carry

        return step_fn

    def _prepare(self, batch, params=None):
        # materialize once per batch: the pruned encoder net and the decoder
        # sub-network were compiled without the full net's sharing maps, so
        # shared keys (tied embeddings, ...) must be grafted back before
        # either reads params by layer name.  `params` lets a jitted caller
        # pass the weights as an ARGUMENT — jitting a closure over
        # self.params would bake every weight into the jaxpr as a constant
        # (trace-lint rule T102: no donation, re-shipped per compile).
        gp = self.net.materialize_shared(
            self.params.params if params is None else params
        )
        outs = self._encode(batch, gp)
        statics = {}
        static_layers = ["enc", "enc_proj"]
        for (pname, is_seq), lname in zip(self._static_info, static_layers):
            val = outs[lname]
            statics[pname] = val if is_seq else SeqTensor(val.data)
        boot = outs["dec_boot"].data
        carry = {m.name: boot for m in self._memories}
        b = boot.shape[0]
        return statics, carry, b, gp

    def generate(self, batch, beam_size: Optional[int] = None, *, params=None):
        """Beam-search decode; returns (sequences [B,K,T], scores [B,K]).

        ``params`` (default: the constructor's Parameters) exists for jitted
        callers: ``jax.jit(lambda p, bt: gen.generate(bt, params=p))`` keeps
        the weights as executable arguments instead of trace-time constants
        (trace-lint T102)."""
        from paddle_tpu.ops.beam import beam_search

        k = beam_size or self.beam_size
        statics, carry, b, gp = self._prepare(batch, params)
        # static tensors must be expanded to B*K rows inside beam_search —
        # it repeats carry but statics stay per-row: expand here.
        statics_k = {
            n: SeqTensor(
                jnp.repeat(t.data, k, axis=0),
                None if t.lengths is None else jnp.repeat(t.lengths, k, axis=0),
            )
            for n, t in statics.items()
        }
        return beam_search(
            self._step_fn(statics_k, gp),
            carry,
            batch_size=b,
            beam_size=k,
            vocab_size=self.trg_vocab,
            bos_id=self.bos_id,
            eos_id=self.eos_id,
            max_len=self.max_length,
            candidate_adjust_fn=self.candidate_adjust_fn,
            drop_fn=self.drop_fn,
            norm_fn=self.norm_fn,
        )

    def generate_greedy(
        self, batch, *, params=None,
        max_new_tokens: Optional[int] = None, early_exit: bool = True,
    ):
        """Greedy decode; returns ([B, L] ids, [B] lengths) with
        ``L = min(max_length, max_new_tokens)``.

        ``max_new_tokens`` caps the decode per CALL (the constructor's
        ``max_length`` stays the compiled ceiling); ``early_exit`` stops
        stepping once every row has emitted EOS instead of always running
        the full unroll.  Both are BIT-IDENTICAL to the full run truncated:
        finished rows only ever re-emit EOS, and the early-exit buffer is
        EOS-filled, so the [B, L] output arrays match exactly
        (tests/test_seq2seq.py pins this)."""
        from paddle_tpu.ops.beam import greedy_search

        statics, carry, b, gp = self._prepare(batch, params)
        return greedy_search(
            self._step_fn(statics, gp),
            carry,
            batch_size=b,
            bos_id=self.bos_id,
            eos_id=self.eos_id,
            max_len=self.max_length,
            max_new_tokens=max_new_tokens,
            early_exit=early_exit,
        )
