"""VAE demo — v1_api_demo/vae parity, TPU-first.

The reference builds encoder/decoder as v1 configs and implements the
reparameterization + ELBO arithmetic in its trainer script (vae_conf.py /
vae_train.py).  Here the encoder and decoder are CompiledNetworks and the
whole ELBO step — encode, reparameterize with a jax PRNG, decode, MSE
reconstruction + analytic gaussian KL, gradients for BOTH networks — is one
jitted function."""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

import paddle_tpu as paddle
from paddle_tpu.core.batch import SeqTensor
from paddle_tpu.core.compiler import CompiledNetwork
from paddle_tpu.core.topology import Topology, reset_auto_names

L = paddle.layer
A = paddle.activation


def encoder_net(data_dim: int, latent_dim: int, hidden: int = 64):
    x = L.data("x", paddle.data_type.dense_vector(data_dim))
    h = L.fc(x, size=hidden, act=A.Relu(), name="e_h1")
    mu = L.fc(h, size=latent_dim, act=A.Identity(), name="e_mu")
    logvar = L.fc(h, size=latent_dim, act=A.Identity(), name="e_logvar")
    return mu, logvar


def decoder_net(latent_dim: int, data_dim: int, hidden: int = 64):
    z = L.data("z", paddle.data_type.dense_vector(latent_dim))
    h = L.fc(z, size=hidden, act=A.Relu(), name="d_h1")
    return L.fc(h, size=data_dim, act=A.Identity(), name="d_out")


class VAETrainer:
    def __init__(
        self,
        data_dim: int,
        latent_dim: int = 4,
        hidden: int = 64,
        lr: float = 1e-3,
        kl_weight: float = 1.0,
        seed: int = 0,
    ):
        self.latent_dim = latent_dim
        reset_auto_names()
        mu, logvar = encoder_net(data_dim, latent_dim, hidden)
        self.enc = CompiledNetwork(Topology([mu, logvar]))
        self.mu_name, self.lv_name = mu.name, logvar.name
        dec_out = decoder_net(latent_dim, data_dim, hidden)
        self.dec = CompiledNetwork(Topology([dec_out]))
        self.dec_out = dec_out.name

        k = jax.random.PRNGKey(seed)
        ke, kd = jax.random.split(k)
        enc_params, _ = self.enc.init(ke)
        dec_params, _ = self.dec.init(kd)
        self.params = {"enc": enc_params, "dec": dec_params}
        self.opt = paddle.optimizer.Adam(learning_rate=lr)
        self.opt_state = self.opt.init(self.params)

        def decode(dec_params, z):
            outs, _ = self.dec.apply(dec_params, {"z": SeqTensor(z)}, train=True)
            return outs[self.dec_out].data

        @jax.jit
        def step(params, opt_state, x, rng):
            def loss(p):
                outs, _ = self.enc.apply(p["enc"], {"x": SeqTensor(x)}, train=True)
                mu_v = outs[self.mu_name].data
                lv_v = outs[self.lv_name].data
                eps = jax.random.normal(rng, mu_v.shape)
                z = mu_v + eps * jnp.exp(0.5 * lv_v)  # reparameterization
                recon = decode(p["dec"], z)
                rec = jnp.mean(jnp.sum((recon - x) ** 2, axis=-1))
                kl = -0.5 * jnp.mean(
                    jnp.sum(1 + lv_v - mu_v**2 - jnp.exp(lv_v), axis=-1)
                )
                return rec + kl_weight * kl

            l, grads = jax.value_and_grad(loss)(params)
            params, opt_state = self.opt.update(grads, opt_state, params)
            return params, opt_state, l

        self._step = step
        self._decode = jax.jit(decode)
        self._rng = jax.random.PRNGKey(seed + 1)

    def train_batch(self, x: np.ndarray) -> float:
        self._rng, r = jax.random.split(self._rng)
        self.params, self.opt_state, l = self._step(
            self.params, self.opt_state, jnp.asarray(x, jnp.float32), r
        )
        return float(l)

    def sample(self, n: int) -> np.ndarray:
        self._rng, r = jax.random.split(self._rng)
        z = jax.random.normal(r, (n, self.latent_dim))
        return np.asarray(self._decode(self.params["dec"], z))

    def reconstruct(self, x: np.ndarray) -> np.ndarray:
        outs, _ = self.enc.apply(
            self.params["enc"], {"x": SeqTensor(jnp.asarray(x))}, train=False
        )
        return np.asarray(self._decode(self.params["dec"], outs[self.mu_name].data))
