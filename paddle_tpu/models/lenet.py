"""LeNet for MNIST — the v1_api_demo/mnist topology (reference:
v1_api_demo/mnist/mnist_conv.py style: conv-pool ×2 + fc + softmax)."""

from __future__ import annotations

import paddle_tpu as paddle
from paddle_tpu.core.topology import LayerOutput


def lenet(img: LayerOutput, class_num: int = 10) -> LayerOutput:
    conv1 = paddle.layer.img_conv(
        img, filter_size=5, num_filters=20, num_channels=1, padding=2,
        act=paddle.activation.Relu(),
    )
    pool1 = paddle.layer.img_pool(conv1, pool_size=2, stride=2)
    conv2 = paddle.layer.img_conv(
        pool1, filter_size=5, num_filters=50, padding=2,
        act=paddle.activation.Relu(),
    )
    pool2 = paddle.layer.img_pool(conv2, pool_size=2, stride=2)
    fc1 = paddle.layer.fc(pool2, size=500, act=paddle.activation.Relu())
    return paddle.layer.fc(fc1, size=class_num, act=paddle.activation.Softmax())


def lenet_cost(class_num: int = 10):
    img = paddle.layer.data("pixel", paddle.data_type.dense_vector(784))
    label = paddle.layer.data("label", paddle.data_type.integer_value(class_num))
    predict = lenet(img, class_num)
    cost = paddle.layer.classification_cost(input=predict, label=label)
    return cost, predict
