"""GAN demo — v1_api_demo/gan parity, TPU-first.

The reference trains two separate proto-configured networks (gan_conf.py
generator/discriminator sub-configs sharing parameter names) with handwritten
alternating v1 trainer calls.  Here the generator and discriminator are two
CompiledNetworks and each alternating phase is ONE jitted step: the
discriminator step differentiates only d_params (generator frozen via
closure), the generator step differentiates only g_params through the
discriminator — the freeze/unfreeze bookkeeping of the reference
(gan_trainer.py prepare_generator_data_batch / is_generator_training) becomes
plain functional argument structure."""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

import paddle_tpu as paddle
from paddle_tpu.core.batch import SeqTensor
from paddle_tpu.core.compiler import CompiledNetwork
from paddle_tpu.core.topology import Topology, reset_auto_names

L = paddle.layer
A = paddle.activation


def generator_net(noise_dim: int, data_dim: int, hidden: int = 64):
    z = L.data("z", paddle.data_type.dense_vector(noise_dim))
    h = L.fc(z, size=hidden, act=A.Relu(), name="g_h1")
    h = L.fc(h, size=hidden, act=A.Relu(), name="g_h2")
    return L.fc(h, size=data_dim, act=A.Identity(), name="g_out")


def discriminator_net(data_dim: int, hidden: int = 64):
    x = L.data("x", paddle.data_type.dense_vector(data_dim))
    h = L.fc(x, size=hidden, act=A.Relu(), name="d_h1")
    h = L.fc(h, size=hidden, act=A.Relu(), name="d_h2")
    return L.fc(h, size=1, act=A.Sigmoid(), name="d_out")


class GANTrainer:
    """Alternating GAN training: d_step maximizes log D(x) + log(1-D(G(z))),
    g_step maximizes log D(G(z)) (the non-saturating generator loss)."""

    def __init__(
        self,
        noise_dim: int,
        data_dim: int,
        hidden: int = 64,
        g_lr: float = 1e-3,
        d_lr: float = 1e-3,
        seed: int = 0,
    ):
        self.noise_dim = noise_dim
        reset_auto_names()
        g_out = generator_net(noise_dim, data_dim, hidden)
        self.g_net = CompiledNetwork(Topology([g_out]))
        self.g_out = g_out.name
        d_out = discriminator_net(data_dim, hidden)
        self.d_net = CompiledNetwork(Topology([d_out]))
        self.d_out = d_out.name

        k = jax.random.PRNGKey(seed)
        kg, kd = jax.random.split(k)
        self.g_params, _ = self.g_net.init(kg)
        self.d_params, _ = self.d_net.init(kd)
        self.g_opt = paddle.optimizer.Adam(learning_rate=g_lr, beta1=0.5)
        self.d_opt = paddle.optimizer.Adam(learning_rate=d_lr, beta1=0.5)
        self.g_opt_state = self.g_opt.init(self.g_params)
        self.d_opt_state = self.d_opt.init(self.d_params)

        def d_prob(d_params, x):
            outs, _ = self.d_net.apply(d_params, {"x": SeqTensor(x)}, train=True)
            return jnp.clip(outs[self.d_out].data[:, 0], 1e-6, 1 - 1e-6)

        def generate(g_params, z):
            outs, _ = self.g_net.apply(g_params, {"z": SeqTensor(z)}, train=True)
            return outs[self.g_out].data

        @jax.jit
        def d_step(d_params, d_opt_state, g_params, real, z):
            def loss(dp):
                fake = generate(g_params, z)  # generator frozen
                p_real = d_prob(dp, real)
                p_fake = d_prob(dp, fake)
                return -jnp.mean(jnp.log(p_real) + jnp.log(1.0 - p_fake))

            l, grads = jax.value_and_grad(loss)(d_params)
            d_params, d_opt_state = self.d_opt.update(grads, d_opt_state, d_params)
            return d_params, d_opt_state, l

        @jax.jit
        def g_step(g_params, g_opt_state, d_params, z):
            def loss(gp):
                fake = generate(gp, z)
                return -jnp.mean(jnp.log(d_prob(d_params, fake)))  # D frozen

            l, grads = jax.value_and_grad(loss)(g_params)
            g_params, g_opt_state = self.g_opt.update(grads, g_opt_state, g_params)
            return g_params, g_opt_state, l

        self._d_step, self._g_step = d_step, g_step
        self._generate = jax.jit(generate)

    # ------------------------------------------------------------------
    def train_batch(self, real: np.ndarray, rng: np.random.RandomState):
        b = real.shape[0]
        z = jnp.asarray(rng.randn(b, self.noise_dim), jnp.float32)
        self.d_params, self.d_opt_state, d_loss = self._d_step(
            self.d_params, self.d_opt_state, self.g_params, jnp.asarray(real), z
        )
        z2 = jnp.asarray(rng.randn(b, self.noise_dim), jnp.float32)
        self.g_params, self.g_opt_state, g_loss = self._g_step(
            self.g_params, self.g_opt_state, self.d_params, z2
        )
        return float(d_loss), float(g_loss)

    def sample(self, n: int, rng: np.random.RandomState) -> np.ndarray:
        z = jnp.asarray(rng.randn(n, self.noise_dim), jnp.float32)
        return np.asarray(self._generate(self.g_params, z))
