"""Parameter / layer extra attributes — the ``paddle.v2.attr`` surface
(reference: python/paddle/trainer_config_helpers/attrs.py ParameterAttribute,
ExtraLayerAttribute)."""

from __future__ import annotations

import dataclasses
from typing import Optional


@dataclasses.dataclass
class ParamAttr:
    """Per-parameter attributes (reference ParameterAttribute, attrs.py:52).
    learning_rate/decay multipliers feed the optimizer's per-param scaling;
    initial_std overrides the default 1/sqrt(fan_in)."""

    name: Optional[str] = None
    initial_std: Optional[float] = None
    initial_mean: Optional[float] = None
    # v1 uniform-init bounds (accepted for config compatibility)
    initial_min: Optional[float] = None
    initial_max: Optional[float] = None
    learning_rate: float = 1.0
    l2_rate: Optional[float] = None
    l1_rate: Optional[float] = None
    is_static: bool = False
    sparse_update: bool = False
    # update hooks (reference ParameterUpdaterHook.cpp): a HookAttribute or
    # list of them; only the static 'pruning' hook has behavior here
    update_hooks: Optional[object] = None


@dataclasses.dataclass
class ExtraAttr:
    """Extra layer attributes (reference ExtraLayerAttribute, attrs.py:390)."""

    drop_rate: float = 0.0
    # Clip the gradient flowing back into this layer's output to
    # [-t, t] (reference error_clipping_threshold, Layer.cpp backwardActivation)
    error_clipping_threshold: float = 0.0
    # Mesh-axis hint replacing the reference's per-layer `device`.
    shard_axis: Optional[str] = None
    # v1 per-layer device id — accepted for config compatibility, ignored
    # (placement is mesh-driven on TPU).
    device: Optional[int] = None


@dataclasses.dataclass
class HookAttribute:
    """Parameter update hook declaration (reference HookAttr /
    ParameterUpdaterHookConfig).  type='pruning' keeps the largest
    (1 - sparsity_ratio) fraction of each parameter by initial magnitude and
    zeroes the rest after every update (StaticPruningHook,
    ParameterUpdaterHook.cpp:39)."""

    type: str = "pruning"
    sparsity_ratio: float = 0.6


HookAttr = HookAttribute
ParameterAttribute = ParamAttr
ExtraLayerAttribute = ExtraAttr
