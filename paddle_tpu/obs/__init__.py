"""Unified observability plane (ISSUE 13) — tracing, flight recorder,
metrics export.

Three faces over the four concurrent planes (trainer / elastic fleet /
HA master / serving scheduler):

* :mod:`~paddle_tpu.obs.tracer` — low-overhead span tracer: per-thread
  bounded rings of begin/end/instant events (monotonic clock,
  injectable), Chrome-trace-event JSON export that opens directly in
  Perfetto, process trace context (trace id + pid + role) and explicit
  correlation ids (``req``/``task``/``rpc``) so one request's lifecycle
  lines up across processes.  The ring doubles as an always-on flight
  recorder: SIGUSR1, firing chaos points, the divergence sentinel, and
  the serving crash guard dump ``flight-<pid>.json`` postmortems.
* :mod:`~paddle_tpu.obs.merge` — ``paddle-tpu trace merge``: zip the
  per-process trace files of a launcher/scenario run into ONE timeline,
  clock-skew aligned via the RPC plane's request/response pairs.
* :mod:`~paddle_tpu.obs.metrics` — periodic StatSet→Prometheus-text
  snapshots (file and/or localhost HTTP) with first-class gauges for
  the PR-12 SLO variables (queue depth, pages in use, EWMA predicted
  wait, served/shed/rejected/timeout ledger).

This package is deliberately jax-free and import-light: master.py and
the numpy elastic plane instrument through it without pulling jax
(device-profile nesting is injected by utils/profiler when active).
"""

from __future__ import annotations

import json
import logging
import os
from typing import Any, Optional

from paddle_tpu.obs.tracer import (  # noqa: F401
    Tracer,
    flight_dump,
    instant,
    next_rpc_id,
    span,
    tracer,
)

__all__ = [
    "Tracer",
    "tracer",
    "span",
    "instant",
    "flight_dump",
    "next_rpc_id",
    "write_stats_json",
    "merge",
    "metrics",
]

_log = logging.getLogger("paddle_tpu.obs")

_LAZY = {"merge", "metrics"}


def __getattr__(name: str):  # PEP 562: keep the http/glob machinery lazy
    if name in _LAZY:
        import importlib

        mod = importlib.import_module(f"paddle_tpu.obs.{name}")
        globals()[name] = mod
        return mod
    raise AttributeError(f"module 'paddle_tpu.obs' has no attribute {name!r}")


def write_stats_json(path: str, record: Any, append: bool = False) -> bool:
    """The ONE ``--stats-out`` writer every CLI face shares (previously
    three divergent copies in cli.py x2 and trainer/elastic.py).

    ``append=False`` writes one JSON document atomically (tmp + replace —
    a reader never sees a torn file); ``append=True`` appends one JSON
    line (the per-leadership-assumption log of ``paddle-tpu master``).
    The stats line is ADVISORY everywhere: an unwritable path logs one
    uniform warning and returns False instead of crashing the process
    that just finished real work (a fleet sharing one bad ``--stats-out``
    argv must not crash-loop)."""
    try:
        line = json.dumps(record)
        if append:
            with open(path, "a") as f:
                f.write(line + "\n")
        else:
            tmp = f"{path}.{os.getpid()}.tmp"
            with open(tmp, "w") as f:
                f.write(line + "\n")
            os.replace(tmp, path)
        return True
    except (OSError, TypeError, ValueError) as exc:
        _log.warning("stats-out %s unwritable: %s", path, exc)
        print(f"stats-out {path} unwritable: {exc}", flush=True)
        return False
