"""Span tracer — the cross-process timeline recorder of the obs plane.

The reference visualizes a distributed step end-to-end (the TensorFlow
paper treats the timeline as a first-class system component, arXiv:
1605.08695 §5; the reference's own host plane is Stat.h/REGISTER_TIMER
printed per log_period).  ``StatSet`` aggregates *how much* time each
host phase costs; this module records *what happened when, in which
process, to which request* — the question every scenario drill and
failover postmortem actually asks.

Design:

* **Per-thread bounded ring buffers** of begin/end/instant events.  One
  ``deque(maxlen=ring_events)`` per thread, appended under one short
  lock hold (~micro-seconds against the milliseconds-scale dispatches it
  instruments); memory is bounded by ``threads x ring_events`` events —
  the recorder can stay armed in production forever (the flight
  recorder).
* **Monotonic clock only** (injectable for tests).  Wall clock must
  never stamp a span — NTP steps would fold spans backward in time;
  the self-lint rule A205 (analysis/ast_rules.py) enforces this for
  every ``obs/`` module.  One wall-clock *anchor* pair is recorded at
  init (pragma'd) purely so the merger can coarse-align processes that
  share no RPC edge.
* **Chrome-trace-event JSON** (``dump``): the per-process file opens
  directly in Perfetto / chrome://tracing.  Events carry ``ph`` (B/E/i),
  ``ts`` (µs), ``pid``, ``tid``, ``name``, ``cat`` (the plane: trainer /
  serving / master / rpc / elastic) and ``args`` — correlation ids
  (``req`` for a serving request, ``task`` for an elastic task, ``rpc``
  for an RPC exchange) ride in ``args`` so one request's
  submit→queued→admit→prefill→decode→deliver spans line up across
  processes after ``paddle-tpu trace merge``.
* **Trace context**: trace id (inherited from ``PADDLE_TPU_TRACE_ID`` so
  a launcher's whole process tree shares one), pid, and a process
  ``role`` (trainer / worker / master / serve) stamped by each CLI
  entry point.
* **jax.profiler nesting**: when a device profile is active,
  ``utils.profiler.profile`` installs ``jax.profiler.TraceAnnotation``
  as the annotation factory, so every host span also appears on the XLA
  timeline under the same name (host and device share a vocabulary).
  The factory is *injected* — this module never imports jax (master.py
  and the numpy elastic plane must stay jax-free).
* **Flight recorder**: recording is on by default (``flight_recorder``
  flag) at bounded memory; :func:`flight_dump` writes the last events
  to ``flight-<pid>.json`` — wired to SIGUSR1, every firing chaos point
  (robustness/chaos.py), the divergence sentinel, and the serving
  scheduler's crash guard, so a kill -9 fleet drill leaves postmortem
  timelines from the survivors.
"""

from __future__ import annotations

import atexit
import collections
import contextlib
import itertools
import json
import logging
import os
import tempfile
import threading
import time
from typing import Any, Dict, Iterator, List, Optional

from paddle_tpu.analysis.lock_sanitizer import make_lock

__all__ = [
    "Tracer",
    "tracer",
    "span",
    "instant",
    "next_rpc_id",
    "flight_dump",
]

_log = logging.getLogger("paddle_tpu.obs")

_US = 1e6

# process-wide RPC correlation counter (rpc ids must be unique per process;
# the pid prefix makes them unique per RUN, so the merger can pair one
# client call with one server handling across trace files)
_rpc_counter = itertools.count()


def next_rpc_id() -> str:
    return f"{os.getpid()}-{next(_rpc_counter)}"


class Tracer:
    """Process-wide span recorder.  One instance (the module singleton
    ``tracer``) serves every plane; tests build private instances with an
    injected clock."""

    def __init__(self, clock=time.monotonic, ring_events: Optional[int] = None):
        from paddle_tpu.utils import flags as _flags

        self._clock = clock  # monotonic by contract (rule A205)
        self._lock = make_lock("obs-tracer")
        # tid -> deque of (ph, ts_us, name, cat, args); guarded by _lock
        self._rings: Dict[int, collections.deque] = {}
        self._thread_names: Dict[int, str] = {}  # guarded by _lock
        self._ring_events = int(
            ring_events if ring_events is not None
            else _flags.get_flag("trace_ring_events")
        )
        self._recording = bool(_flags.get_flag("flight_recorder"))
        self._annotation_factory = None  # injected by utils.profiler
        self.role = "proc"
        self.pid = os.getpid()
        self.trace_id = os.environ.get(
            "PADDLE_TPU_TRACE_ID", f"t{self.pid:x}"
        )
        self._export_dir: Optional[str] = None
        self._atexit_registered = False
        # one wall-clock anchor so `trace merge` can coarse-align processes
        # that share no RPC edge; NEVER used to stamp a span (A205)
        self._anchor_mono_us = self._clock() * _US
        self._anchor_wall_us = time.time() * _US  # obs: allow-wall-clock one-time merge anchor, never a span timestamp

    # -- arming ----------------------------------------------------------
    @property
    def recording(self) -> bool:
        return self._recording

    def set_recording(self, on: bool) -> None:
        """Arm/disarm the ring recorder (the bench's A/B lever).  Off =
        every emit is one attribute read."""
        self._recording = bool(on)

    @property
    def exporting(self) -> bool:
        return self._export_dir is not None

    @property
    def export_dir(self) -> Optional[str]:
        return self._export_dir

    def set_annotation_factory(self, factory) -> None:
        """Install a context-manager factory (``jax.profiler.
        TraceAnnotation`` while a device profile is active) that every
        span nests under — host and XLA timelines then share names."""
        self._annotation_factory = factory

    def configure(
        self,
        role: Optional[str] = None,
        trace_dir: Optional[str] = None,
        trace_id: Optional[str] = None,
        install_sigusr1: bool = True,
    ) -> None:
        """Adopt the process trace context.  Called once by each CLI entry
        point (train → trainer, worker, master, serve); ``trace_dir``
        defaults to the ``trace_dir`` flag (env
        ``PADDLE_TPU_TRACE_DIR`` reaches subprocesses), and a non-empty
        dir arms EXPORT: the process dumps its Chrome-trace file there at
        exit (atexit — a kill -9 leaves only the flight recorder).

        The recorder flags are RE-READ here: the singleton froze
        ``flight_recorder``/``trace_ring_events`` at first import, so a
        ``set_flag`` between import and the CLI entry (the same runtime
        pattern ``trace_dir`` supports) takes effect now.  A changed ring
        size applies to rings created from here on."""
        from paddle_tpu.utils import flags as _flags

        self._recording = bool(_flags.get_flag("flight_recorder"))
        self._ring_events = int(_flags.get_flag("trace_ring_events"))
        if role is not None:
            self.role = role
        if trace_id is not None:
            self.trace_id = trace_id
        if trace_dir is None:
            trace_dir = _flags.get_flag("trace_dir")
        if trace_dir:
            self._export_dir = trace_dir
            if not self._atexit_registered:
                self._atexit_registered = True
                atexit.register(self._atexit_dump)
        if install_sigusr1:
            self._install_sigusr1()

    def _install_sigusr1(self) -> None:
        import signal

        def _handler(signum, frame):
            # the handler runs on the MAIN thread between bytecodes — if
            # the signal lands inside _emit's lock hold (any hot-path
            # span), dumping synchronously would self-deadlock on the
            # non-reentrant tracer lock.  A side thread takes the lock
            # only once the interrupted frame releases it.
            threading.Thread(
                target=self.flight_dump, args=("SIGUSR1",),
                name="paddle-obs-flight", daemon=True,
            ).start()

        try:
            if signal.getsignal(signal.SIGUSR1) in (
                signal.SIG_DFL, signal.SIG_IGN,
            ):
                signal.signal(signal.SIGUSR1, _handler)
        except (ValueError, AttributeError, OSError):
            # not the main thread, or a platform without SIGUSR1
            pass

    def _atexit_dump(self) -> None:
        try:
            self.dump()
        except Exception:  # noqa: BLE001 — exit path must never raise
            _log.exception("trace export at exit failed")

    # -- recording -------------------------------------------------------
    def _emit(self, ph: str, name: str, cat: str,
              args: Optional[Dict[str, Any]]) -> None:
        if not self._recording:
            return
        ts_us = self._clock() * _US
        tid = threading.get_ident()
        with self._lock:
            ring = self._rings.get(tid)
            if ring is None:
                ring = collections.deque(maxlen=self._ring_events)
                self._rings[tid] = ring
                self._thread_names[tid] = threading.current_thread().name
            ring.append((ph, ts_us, name, cat, args))

    def instant(self, name: str, cat: str = "host", **args: Any) -> None:
        """One point-in-time event (ph 'i') — lifecycle transitions
        (submit / shed / fence-release) that have no duration."""
        self._emit("i", name, cat, args or None)

    def begin(self, name: str, cat: str = "host", **args: Any) -> None:
        self._emit("B", name, cat, args or None)

    def end(self, name: str, cat: str = "host") -> None:
        self._emit("E", name, cat, None)

    @contextlib.contextmanager
    def span(self, name: str, cat: str = "host", **args: Any) -> Iterator[None]:
        """Scoped begin/end pair.  Disarmed cost: one attribute read and a
        generator frame — cheap enough to leave on hot paths."""
        if not self._recording:
            yield
            return
        ann = self._annotation_factory
        ctx = ann(name) if ann is not None else None
        self._emit("B", name, cat, args or None)
        if ctx is not None:
            ctx.__enter__()
        try:
            yield
        finally:
            if ctx is not None:
                ctx.__exit__(None, None, None)
            self._emit("E", name, cat, None)

    def reset(self) -> None:
        with self._lock:
            self._rings.clear()
            self._thread_names.clear()

    # -- export ----------------------------------------------------------
    def _snapshot(self):
        with self._lock:
            rings = {tid: list(ring) for tid, ring in self._rings.items()}
            names = dict(self._thread_names)
        return rings, names

    def events(self) -> List[Dict[str, Any]]:
        """Chrome-trace-event dicts of everything currently in the rings,
        time-sorted, metadata (process/thread names) first."""
        rings, names = self._snapshot()
        evs: List[Dict[str, Any]] = []
        for tid, ring in rings.items():
            for ph, ts_us, name, cat, args in ring:
                ev: Dict[str, Any] = {
                    "ph": ph,
                    "ts": round(ts_us, 3),
                    "pid": self.pid,
                    "tid": tid,
                    "name": name,
                    "cat": cat,
                }
                if args:
                    ev["args"] = dict(args)
                evs.append(ev)
        evs.sort(key=lambda e: e["ts"])
        meta = [{
            "ph": "M", "name": "process_name", "pid": self.pid, "tid": 0,
            "ts": 0,
            "args": {"name": f"{self.role} (pid {self.pid})"},
        }]
        meta.extend({
            "ph": "M", "name": "thread_name", "pid": self.pid, "tid": tid,
            "ts": 0, "args": {"name": names.get(tid, str(tid))},
        } for tid in sorted(rings))
        return meta + evs

    def trace_object(self, reason: Optional[str] = None) -> Dict[str, Any]:
        obj: Dict[str, Any] = {
            "traceEvents": self.events(),
            "otherData": {
                "trace_id": self.trace_id,
                "role": self.role,
                "pid": self.pid,
                "clock_anchor": {
                    "mono_us": self._anchor_mono_us,
                    "wall_us": self._anchor_wall_us,
                },
            },
        }
        if reason is not None:
            obj["otherData"]["reason"] = reason
        return obj

    def dump(self, path: Optional[str] = None) -> Optional[str]:
        """Write this process's Chrome-trace JSON.  Default path:
        ``<trace_dir>/trace-<role>-<pid>.json``; None (nothing written)
        when neither a path nor an export dir is armed."""
        if path is None:
            if self._export_dir is None:
                return None
            path = os.path.join(
                self._export_dir, f"trace-{self.role}-{self.pid}.json"
            )
        obj = self.trace_object()
        try:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            tmp = f"{path}.{self.pid}.tmp"
            with open(tmp, "w") as f:
                # default=str: an exotic span arg (numpy scalar, path
                # object) degrades to its repr instead of losing the dump
                json.dump(obj, f, default=str)
            os.replace(tmp, path)
        except OSError as exc:
            _log.warning("trace dump %s unwritable: %s", path, exc)
            return None
        return path

    def flight_dump(self, reason: str,
                    extra: Optional[Dict[str, Any]] = None) -> Optional[str]:
        """Postmortem: the ring buffers' last events to
        ``flight-<pid>.json`` (under the ``trace_dir`` flag when set,
        else the system temp dir — never the working directory).  Safe
        from signal handlers and except blocks; never raises.  ``extra``
        merges into ``otherData`` — the numerics sanitizer rides its
        first-non-finite-eqn postmortem here (``otherData.numerics``)."""
        try:
            from paddle_tpu.utils import flags as _flags

            d = (
                self._export_dir
                or _flags.get_flag("trace_dir")
                or tempfile.gettempdir()
            )
            path = os.path.join(d, f"flight-{self.pid}.json")
            obj = self.trace_object(reason=reason)
            if extra:
                obj["otherData"].update(extra)
            os.makedirs(d, exist_ok=True)
            with open(path, "w") as f:
                json.dump(obj, f, default=str)
            _log.warning(
                "flight recorder: dumped %d event(s) to %s (%s)",
                sum(1 for e in obj["traceEvents"] if e["ph"] != "M"),
                path, reason,
            )
            return path
        except Exception:  # noqa: BLE001 — a postmortem must never crash
            _log.exception("flight dump failed (%s)", reason)
            return None


# the process singleton + module-level conveniences every plane imports
tracer = Tracer()
span = tracer.span
instant = tracer.instant
flight_dump = tracer.flight_dump
