"""Merge per-process trace files into ONE clock-skew-aligned timeline.

A launcher/scenario run leaves one ``trace-<role>-<pid>.json`` per
process (obs/tracer.py).  Each file's timestamps come from that
process's own monotonic clock — arbitrary epoch, so the files cannot be
concatenated raw.  Two alignment sources, coarse to fine:

1. **Wall anchors** — every trace records one (monotonic, wall) clock
   pair at tracer init; mapping each process onto the wall clock aligns
   to NTP precision (good enough for processes that never talk).
2. **RPC pairs** — the master RPC plane stamps every exchange with a
   correlation id on BOTH sides: the client span ``rpc_call:<method>``
   (dial→reply, args.rpc) and the server span ``rpc:<method>``
   (recv→send, same args.rpc).  The server's handling midpoint must sit
   at the client's exchange midpoint (the classic NTP offset estimate);
   the median residual over all pairs between two processes refines
   their relative offset to dispatch precision.  Offsets propagate over
   the RPC-pair graph by BFS from the reference process, so a worker
   that only ever talked to the master still aligns against a serving
   process on the master's side.

The merged file is a normal Chrome-trace JSON (open in Perfetto):
every event keeps its own pid/tid, timestamps are rebased onto the
reference process's clock, and ``otherData.offsets_us`` records the
per-process corrections applied.
"""

from __future__ import annotations

import glob
import json
import os
from statistics import median as _median
from typing import Any, Dict, List, Optional, Tuple

__all__ = ["load_trace", "merge_traces", "merge_dir", "validate_trace"]

_REQUIRED_KEYS = ("ph", "ts", "pid", "tid", "name")


def load_trace(path: str) -> Dict[str, Any]:
    with open(path) as f:
        obj = json.load(f)
    if isinstance(obj, list):  # bare-array Chrome trace form
        obj = {"traceEvents": obj, "otherData": {}}
    if "traceEvents" not in obj:
        raise ValueError(f"{path}: not a Chrome-trace file (no traceEvents)")
    return obj


def validate_trace(obj: Dict[str, Any]) -> List[str]:
    """Schema problems of one trace object (empty list = valid):
    required keys on every event, well-formed args, balanced B/E pairing
    per (pid, tid) with matching names."""
    problems: List[str] = []
    evs = obj.get("traceEvents")
    if not isinstance(evs, list):
        return ["traceEvents is not a list"]
    stacks: Dict[Tuple[Any, Any], List[str]] = {}
    for i, ev in enumerate(evs):
        if not isinstance(ev, dict):
            problems.append(f"event {i}: not an object")
            continue
        for k in _REQUIRED_KEYS:
            if k not in ev:
                problems.append(f"event {i}: missing key {k!r}")
        if "args" in ev and not isinstance(ev["args"], dict):
            problems.append(f"event {i}: args is not an object")
        ph = ev.get("ph")
        key = (ev.get("pid"), ev.get("tid"))
        if ph == "B":
            stacks.setdefault(key, []).append(ev.get("name"))
        elif ph == "E":
            stack = stacks.setdefault(key, [])
            if not stack:
                # an E with no open B is the expected ring-wrap artifact
                # (the deque dropped its B with the oldest events) — ANY
                # orphan-E-on-empty-stack is explainable that way, so it
                # is never an error; only a LIFO violation below is
                continue
            if stack[-1] != ev.get("name"):
                problems.append(
                    f"event {i}: E {ev.get('name')!r} closes B "
                    f"{stack[-1]!r} on pid/tid {key}"
                )
            stack.pop()
    # Bs left open at the end are expected too: a dump can happen MID-SPAN
    # (the flight recorder fires inside spans by design).  The enforced
    # pairing invariant is the LIFO name discipline of the retained pairs.
    return problems


def _span_mids(evs: List[dict], prefix: str) -> Dict[str, float]:
    """{rpc_id: midpoint_ts} of every completed ``prefix``* span carrying
    an args.rpc correlation id, per the file's OWN clock."""
    open_: Dict[Tuple[Any, str], Tuple[float, Optional[str]]] = {}
    mids: Dict[str, float] = {}
    for ev in evs:
        name = ev.get("name", "")
        if not name.startswith(prefix):
            continue
        key = (ev.get("tid"), name)
        if ev.get("ph") == "B":
            open_[key] = (ev["ts"], (ev.get("args") or {}).get("rpc"))
        elif ev.get("ph") == "E" and key in open_:
            t0, rpc = open_.pop(key)
            if rpc is not None:
                mids[rpc] = (t0 + ev["ts"]) / 2.0
    return mids


def merge_traces(objs: List[Dict[str, Any]],
                 reference_pid: Optional[int] = None) -> Dict[str, Any]:
    """One merged trace object from per-process trace objects.  The
    reference process (default: the one with the most events) keeps its
    clock; every other process is shifted by the RPC-pair offset when an
    RPC path to the reference exists, else by the wall anchors."""
    if not objs:
        raise ValueError("nothing to merge")
    by_pid: Dict[int, Dict[str, Any]] = {}
    for obj in objs:
        other = obj.get("otherData", {})
        pid = other.get("pid")
        if pid is None:  # infer from the first real event
            pids = [e.get("pid") for e in obj["traceEvents"] if "pid" in e]
            pid = pids[0] if pids else len(by_pid)
        by_pid[int(pid)] = obj
    pids = sorted(by_pid)
    if reference_pid is None:
        reference_pid = max(
            pids, key=lambda p: (len(by_pid[p]["traceEvents"]), -p)
        )

    # wall-anchor deltas: ts + dw maps onto the wall clock
    dw: Dict[int, float] = {}
    for pid, obj in by_pid.items():
        anchor = obj.get("otherData", {}).get("clock_anchor") or {}
        if "wall_us" in anchor and "mono_us" in anchor:
            dw[pid] = anchor["wall_us"] - anchor["mono_us"]

    # RPC pair edges: offset o means t_server ~ t_client + o (both local)
    client_mids = {
        pid: _span_mids(obj["traceEvents"], "rpc_call:")
        for pid, obj in by_pid.items()
    }
    server_mids = {
        pid: _span_mids(obj["traceEvents"], "rpc:")
        for pid, obj in by_pid.items()
    }
    edges: Dict[Tuple[int, int], List[float]] = {}
    for cp in pids:
        for sp in pids:
            if cp == sp:
                continue
            common = set(client_mids[cp]) & set(server_mids[sp])
            if common:
                edges.setdefault((cp, sp), []).extend(
                    server_mids[sp][r] - client_mids[cp][r] for r in common
                )

    # BFS the pair graph from the reference, assigning per-process deltas
    # (ts + delta = reference clock); wall anchors fill the gaps
    delta: Dict[int, float] = {reference_pid: 0.0}
    frontier = [reference_pid]
    while frontier:
        nxt: List[int] = []
        for p in frontier:
            for (cp, sp), offs in edges.items():
                o = _median(offs)
                if cp == p and sp not in delta:
                    # t_ref = t_cp + delta[cp]; t_sp - o ~ t_cp
                    delta[sp] = delta[p] - o
                    nxt.append(sp)
                elif sp == p and cp not in delta:
                    delta[cp] = delta[p] + o
                    nxt.append(cp)
        frontier = nxt
    for pid in pids:
        if pid not in delta:
            if pid in dw and reference_pid in dw:
                delta[pid] = dw[pid] - dw[reference_pid]
            else:
                delta[pid] = 0.0

    merged: List[dict] = []
    for pid in pids:
        d = delta[pid]
        for ev in by_pid[pid]["traceEvents"]:
            ev = dict(ev)
            if ev.get("ph") != "M":
                ev["ts"] = round(ev["ts"] + d, 3)
            merged.append(ev)
    merged.sort(key=lambda e: (e.get("ph") != "M", e.get("ts", 0)))
    trace_ids = {
        by_pid[p].get("otherData", {}).get("trace_id") for p in pids
    } - {None}
    return {
        "traceEvents": merged,
        "otherData": {
            "trace_id": sorted(trace_ids)[0] if trace_ids else None,
            "merged_pids": pids,
            "reference_pid": reference_pid,
            "offsets_us": {str(p): round(delta[p], 3) for p in pids},
            "rpc_pair_edges": {
                f"{cp}->{sp}": len(offs)
                for (cp, sp), offs in sorted(edges.items())
            },
            "roles": {
                str(p): by_pid[p].get("otherData", {}).get("role")
                for p in pids
            },
        },
    }


def merge_dir(trace_dir: str, out_path: Optional[str] = None,
              pattern: str = "trace-*.json") -> Tuple[Dict[str, Any], str]:
    """Merge every per-process trace file under ``trace_dir``; write the
    result to ``out_path`` (default ``<trace_dir>/merged.json``).
    Returns (merged object, written path)."""
    paths = sorted(glob.glob(os.path.join(trace_dir, pattern)))
    if not paths:
        raise FileNotFoundError(
            f"no {pattern} files under {trace_dir} — did the run set the "
            "trace_dir flag (PADDLE_TPU_TRACE_DIR)?"
        )
    merged = merge_traces([load_trace(p) for p in paths])
    merged["otherData"]["merged_from"] = [os.path.basename(p) for p in paths]
    if out_path is None:
        out_path = os.path.join(trace_dir, "merged.json")
    with open(out_path, "w") as f:
        json.dump(merged, f)
    return merged, out_path
