"""Metrics export — StatSet + SLO gauges in Prometheus text format.

The reference prints its ``StatSet`` table per ``log_period``
(TrainerInternal.cpp:443); that surface stays, but a table scraped from
a log is not a production metrics plane.  This module renders the same
aggregates — plus live **gauges** for the PR-12 SLO variables the
production gate asserts on (serving queue depth, pages in use, EWMA
predicted queue wait, the served/shed/rejected/timeout ledger) — in the
Prometheus text exposition format, periodically snapshotted to a file
(atomic replace) and/or served on a localhost HTTP endpoint.  The gated
quantities become observable LIVE, not only in the post-run summary.

Gauges are callbacks: a plane that owns an SLO variable registers
``register_gauge(name, fn, help)`` (the serving scheduler does this on
construction and unregisters on close); the exporter polls them at
render time and skips any that raise — a crashing gauge must never take
the exporter down.
"""

from __future__ import annotations

import logging
import os
import threading
from typing import Callable, Dict, List, Optional, Tuple

from paddle_tpu.analysis.lock_sanitizer import THREAD_PREFIX, make_lock

__all__ = [
    "register_gauge",
    "unregister_gauge",
    "render_prometheus",
    "MetricsExporter",
]

_log = logging.getLogger("paddle_tpu.obs")


def _series_key(name: str, labels) -> str:
    """The exposition line's series id: ``family`` bare, or
    ``family{k="v",...}`` with labels sorted (one canonical key per
    label set, so register/unregister pairs always meet)."""
    if not labels:
        return name
    inner = ",".join(
        f'{k}="{_escape(str(v))}"' for k, v in sorted(labels.items())
    )
    return f"{name}{{{inner}}}"


class _GaugeRegistry:
    """Process-wide named gauge callbacks (guarded; reads snapshot)."""

    def __init__(self) -> None:
        self._lock = make_lock("obs-gauges")
        # series key -> (fn, help, family name)
        self._gauges: Dict[str, Tuple[Callable[[], float], str, str]] = {}

    def register(self, name: str, fn: Callable[[], float],
                 help_: str = "", labels=None) -> None:
        """Latest registration wins (a newer scheduler instance takes the
        name over); keep the returned ``fn`` to unregister safely.
        ``labels`` (dict) makes a LABELED series of the ``name`` family —
        the fleet router registers one series per engine
        (``engine="..."``); HELP/TYPE render once per family."""
        with self._lock:
            self._gauges[_series_key(name, labels)] = (fn, help_, name)

    def unregister(self, name: str, fn: Optional[Callable] = None,
                   labels=None) -> None:
        """Remove a gauge — but only if ``fn`` (when given) is still the
        registered callback: a closed older instance must not tear down
        the gauge a newer instance re-registered under the same name."""
        key = _series_key(name, labels)
        with self._lock:
            if fn is not None and self._gauges.get(key, (None,))[0] is not fn:
                return
            self._gauges.pop(key, None)

    def snapshot(self) -> Dict[str, Tuple[Callable[[], float], str, str]]:
        with self._lock:
            return dict(self._gauges)


_registry = _GaugeRegistry()
register_gauge = _registry.register
unregister_gauge = _registry.unregister


def _escape(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


# StatSet counters -> first-class serving ledger statuses (the disjoint
# categories serving.status_counts reports)
_LEDGER = (
    ("served", "serving/completed"),
    ("shed", "serving/shed"),
    ("rejected", "serving/rejected"),
    ("timeout", "serving/timeout"),
)

# the router-tier ledger (serving/router.py increments fleet/<status>):
# same disjoint categories, distinct StatSet names — a process hosting
# BOTH a router and an engine reports each tier's counts once
_FLEET_LEDGER = (
    ("served", "fleet/served"),
    ("shed", "fleet/shed"),
    ("rejected", "fleet/rejected"),
    ("timeout", "fleet/timeout"),
)


def render_prometheus(stats=None) -> str:
    """The full exposition: registered gauges, the serving ledger, and
    the generic StatSet aggregates (count/total/avg/max per stat, stat
    name as a label — names like ``lock_held/<x>`` stay intact)."""
    if stats is None:
        from paddle_tpu.utils.timers import global_stats as stats
    summary = stats.summary()
    lines: List[str] = []

    seen_families = set()
    for key, (fn, help_, family) in sorted(_registry.snapshot().items()):
        try:
            value = float(fn())
        except Exception:  # noqa: BLE001 — a dead gauge must not kill export
            continue
        if family not in seen_families:
            # HELP/TYPE once per FAMILY: labeled series (the router's
            # per-engine gauges) share one header like any exporter's
            seen_families.add(family)
            if help_:
                lines.append(f"# HELP {family} {help_}")
            lines.append(f"# TYPE {family} gauge")
        lines.append(f"{key} {value}")

    lines.append(
        "# HELP paddle_tpu_serving_requests_total finalized serving "
        "requests by disjoint terminal status"
    )
    lines.append("# TYPE paddle_tpu_serving_requests_total counter")
    for status, stat in _LEDGER:
        count = summary.get(stat, {}).get("count", 0)
        lines.append(
            f'paddle_tpu_serving_requests_total{{status="{status}"}} '
            f"{int(count)}"
        )
    # the per-class ledger: the scheduler increments
    # serving/class/<class>/<status> on every finalization (ALL
    # statuses, served included) — rendered as class-labeled series of
    # the same family, labels sorted (class before status) like every
    # series key this module emits
    for name in sorted(summary):
        parts = name.split("/")
        if (len(parts) == 4 and parts[0] == "serving"
                and parts[1] == "class"):
            cls, status = parts[2], parts[3]
            lines.append(
                "paddle_tpu_serving_requests_total"
                f'{{class="{_escape(cls)}",status="{_escape(status)}"}} '
                f"{int(summary[name]['count'])}"
            )

    lines.append(
        "# HELP paddle_tpu_fleet_requests_total requests finalized by the "
        "fleet router, by disjoint terminal status (serving/router.py — "
        "distinct from the per-engine serving ledger so an in-process "
        "fleet never double-counts)"
    )
    lines.append("# TYPE paddle_tpu_fleet_requests_total counter")
    for status, stat in _FLEET_LEDGER:
        count = summary.get(stat, {}).get("count", 0)
        lines.append(
            f'paddle_tpu_fleet_requests_total{{status="{status}"}} '
            f"{int(count)}"
        )

    lines.append(
        "# HELP paddle_tpu_stat_count StatSet event count per stat "
        "(utils/timers.py — the REGISTER_TIMER plane)"
    )
    lines.append("# TYPE paddle_tpu_stat_count counter")
    for name in sorted(summary):
        lines.append(
            f'paddle_tpu_stat_count{{name="{_escape(name)}"}} '
            f"{int(summary[name]['count'])}"
        )
    for field, kind in (("total", "counter"), ("avg", "gauge"),
                        ("max", "gauge")):
        lines.append(f"# TYPE paddle_tpu_stat_{field} {kind}")
        for name in sorted(summary):
            lines.append(
                f'paddle_tpu_stat_{field}{{name="{_escape(name)}"}} '
                f"{summary[name][field]}"
            )
    return "\n".join(lines) + "\n"


class MetricsExporter:
    """Periodic exposition writer + optional localhost HTTP endpoint.

    ``path``: write the exposition there every ``period_s`` seconds
    (tmp + atomic replace — a scraper never reads a torn file);
    ``port``: also serve GET /metrics on 127.0.0.1 (0 picks a free
    port, exposed as ``self.port``).  Defaults come from the
    ``metrics_out`` / ``metrics_port`` / ``metrics_period_s`` flags.
    ``close()`` stops the writer thread and the HTTP server."""

    def __init__(
        self,
        path: Optional[str] = None,
        port: Optional[int] = None,
        period_s: Optional[float] = None,
        stats=None,
    ):
        from paddle_tpu.utils import flags as _flags

        self._stats = stats
        self.path = path if path is not None else _flags.get_flag(
            "metrics_out"
        )
        self.period_s = float(
            period_s if period_s is not None
            else _flags.get_flag("metrics_period_s")
        )
        # an EXPLICIT port=0 argument means "pick a free port" (tests);
        # the metrics_port flag's default 0 means "no endpoint"; an
        # explicit NEGATIVE port forces the endpoint OFF even when the
        # flag/env would arm it (the CLI's `--metrics-port 0` contract)
        explicit_port = port is not None
        if port is None:
            port = _flags.get_flag("metrics_port")
        if explicit_port and int(port) < 0:
            explicit_port, port = False, 0
        self._stop = threading.Event()
        self._httpd = None
        self._http_thread = None
        self._writer_thread = None
        self.port: Optional[int] = None
        if int(port) > 0 or (explicit_port and int(port) == 0):
            self._start_http(int(port))
        if self.path:
            self._writer_thread = threading.Thread(
                target=self._write_loop,
                name=THREAD_PREFIX + "obs-metrics",
                daemon=True,
            )
            self._writer_thread.start()

    # -- file sink -------------------------------------------------------
    def write_once(self) -> bool:
        if not self.path:
            return False
        text = render_prometheus(self._stats)
        try:
            tmp = f"{self.path}.{os.getpid()}.tmp"
            with open(tmp, "w") as f:
                f.write(text)
            os.replace(tmp, self.path)
            return True
        except OSError as exc:
            _log.warning("metrics_out %s unwritable: %s", self.path, exc)
            return False

    def _write_loop(self) -> None:
        while not self._stop.wait(self.period_s):  # bounded: stop-aware
            self.write_once()
        self.write_once()  # final snapshot on close

    # -- http sink -------------------------------------------------------
    def _start_http(self, port: int) -> None:
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        exporter = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 — BaseHTTPRequestHandler API
                if self.path.split("?", 1)[0] not in ("/metrics", "/"):
                    self.send_error(404)
                    return
                body = render_prometheus(exporter._stats).encode()
                self.send_response(200)
                self.send_header(
                    "Content-Type", "text/plain; version=0.0.4"
                )
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, fmt, *args):  # scrapes are not log news
                pass

        self._httpd = ThreadingHTTPServer(("127.0.0.1", port), Handler)
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._http_thread = threading.Thread(
            target=self._httpd.serve_forever,
            name=THREAD_PREFIX + "obs-http",
            daemon=True,
        )
        self._http_thread.start()

    def close(self) -> None:
        self._stop.set()
        if self._writer_thread is not None:
            self._writer_thread.join(timeout=5)
            self._writer_thread = None
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._http_thread is not None:
            self._http_thread.join(timeout=5)
            self._http_thread = None

    def __enter__(self) -> "MetricsExporter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
