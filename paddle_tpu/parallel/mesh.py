"""Device mesh & sharding — the replacement for the reference's entire
distribution stack: the intra-node thread-ring of MultiGradientMachine
(reference: paddle/gserver/gradientmachines/MultiGradientMachine.h:44-120) and
the inter-node parameter servers (reference: paddle/pserver/ParameterServer2.h,
go/pserver).

Design: one global `jax.sharding.Mesh` with named axes

    data   — data parallelism (batch axis).  Gradient psum rides ICI
             AllReduce; there is no parameter server to push/pull.
    model  — tensor/model parallelism for wide layers & sharded embeddings
             (replaces ParallelNeuralNetwork per-layer device placement and
             the row-sharded sparse tables on pservers).

Parameters/optimizer state are replicated over `data` (or sharded over
`model` when a layer opts in); batches are sharded over `data` on the leading
axis.  XLA inserts the collectives.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
MODEL_AXIS = "model"


def shard_map(*args, **kwargs):
    """``jax.shard_map`` across jax versions: the top-level name (jax >=
    0.5) with a fallback to ``jax.experimental.shard_map`` — call sites
    (ring attention, the GPipe pipeline, the allreduce bench) stay one
    spelling."""
    fn = getattr(jax, "shard_map", None)
    if fn is None:  # older jax: experimental namespace only
        from jax.experimental.shard_map import shard_map as fn
    if "check_vma" in kwargs:
        # the replication-check kwarg was renamed check_rep -> check_vma;
        # mid-window jax exposes the top-level name but still takes
        # check_rep, so translate by the actual signature, not the lookup
        # path
        try:
            import inspect

            sig_params = inspect.signature(fn).parameters
        except (TypeError, ValueError):
            sig_params = {"check_vma": None}
        if "check_vma" not in sig_params and "check_rep" in sig_params:
            kwargs["check_rep"] = kwargs.pop("check_vma")
    return fn(*args, **kwargs)


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    data: int = -1  # -1 = all remaining devices
    model: int = 1


# ---------------------------------------------------------------------------
# Multi-process process group — jax.distributed with a coordination-service
# fallback shim (the shard_map-shim pattern: one spelling across jax
# versions/backends).
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class ProcessGroup:
    """Membership of a multi-process run.

    backend:
      * ``"jax-distributed"`` — a real jax.distributed runtime was formed;
        jax.devices() is the GLOBAL device set and in-program collectives
        cross processes over ICI/DCN.
      * ``"shim"`` — the dev-container fallback (older jax, or CPU-only
        platforms where jax.distributed cannot form a backend): each
        process keeps its local devices and cross-process reduction rides
        the master coordination service instead (trainer/elastic.py's
        pass-fence + task-result reduce).
      * ``"single"`` — no multi-process environment configured.
    """

    num_processes: int = 1
    process_id: int = 0
    coordinator: Optional[str] = None
    backend: str = "single"

    def __bool__(self) -> bool:  # truthy == genuinely multi-process
        return self.num_processes > 1


_process_group = ProcessGroup()


def init_process_group(
    coordinator: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    use_jax_distributed: Optional[bool] = None,
) -> ProcessGroup:
    """Join (or declare) the process group.  Arguments default from the
    launcher environment (``PADDLE_TPU_COORDINATOR`` etc.).

    ``use_jax_distributed``: None (default) consults the
    ``PADDLE_TPU_DIST_BACKEND=jax`` environment switch — on TPU pods the
    real runtime is what you want, but on the CPU dev container calling
    ``jax.distributed.initialize`` would hang against a coordinator that
    can never form a device backend, so the shim is the default there."""
    global _process_group
    import os

    from paddle_tpu import launcher as _launcher

    coordinator = coordinator or os.environ.get(_launcher.ENV_COORD)
    if num_processes is None:
        num_processes = int(os.environ.get(_launcher.ENV_NPROC, "0") or 0)
    if process_id is None:
        process_id = int(os.environ.get(_launcher.ENV_PROC_ID, "0") or 0)
    if not coordinator or num_processes <= 1:
        _process_group = ProcessGroup()
        return _process_group
    if use_jax_distributed is None:
        use_jax_distributed = (
            os.environ.get("PADDLE_TPU_DIST_BACKEND", "") == "jax"
        )
    backend = "shim"
    if use_jax_distributed:
        import logging

        import jax

        try:
            jax.distributed.initialize(
                coordinator_address=coordinator,
                num_processes=num_processes,
                process_id=process_id,
            )
            backend = "jax-distributed"
        except (AttributeError, NotImplementedError, RuntimeError, ValueError) as exc:
            # older jax / no distributable backend: fall back to the shim —
            # but the operator EXPLICITLY asked for the real runtime, so
            # say loudly that they are not getting it (a silent shim on a
            # pod means N unsynchronized replicas, not one job)
            logging.getLogger("paddle_tpu.parallel").warning(
                "PADDLE_TPU_DIST_BACKEND=jax requested but "
                "jax.distributed.initialize failed (%s: %s); falling back "
                "to the coordination-service shim — in-program collectives "
                "will NOT cross processes", type(exc).__name__, exc,
            )
            backend = "shim"
    _process_group = ProcessGroup(
        num_processes=num_processes,
        process_id=process_id,
        coordinator=coordinator,
        backend=backend,
    )
    return _process_group


def current_process_group() -> ProcessGroup:
    return _process_group


_default_mesh: Optional[Mesh] = None


def make_mesh(
    data: int = -1,
    model: int = 1,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    devs = list(devices if devices is not None else jax.devices())
    n = len(devs)
    if data == -1:
        assert n % model == 0, f"{n} devices not divisible by model={model}"
        data = n // model
    assert data * model == n, f"mesh {data}x{model} != {n} devices"
    arr = np.array(devs).reshape(data, model)
    return Mesh(arr, (DATA_AXIS, MODEL_AXIS))


def set_default_mesh(mesh: Optional[Mesh]) -> None:
    global _default_mesh
    _default_mesh = mesh


def get_default_mesh() -> Optional[Mesh]:
    return _default_mesh


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Shard the leading (batch) axis across the data axis."""
    return NamedSharding(mesh, P(DATA_AXIS))


def shard_batch(batch, mesh: Optional[Mesh]):
    """Place a Batch pytree so every leaf's leading axis is split over the
    data axis (the feeder guarantees batch % data-size == 0)."""
    if mesh is None:
        return batch
    sh = batch_sharding(mesh)
    return jax.tree_util.tree_map(lambda x: jax.device_put(x, sh), batch)
