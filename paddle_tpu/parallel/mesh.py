"""Device mesh & sharding — the replacement for the reference's entire
distribution stack: the intra-node thread-ring of MultiGradientMachine
(reference: paddle/gserver/gradientmachines/MultiGradientMachine.h:44-120) and
the inter-node parameter servers (reference: paddle/pserver/ParameterServer2.h,
go/pserver).

Design: one global `jax.sharding.Mesh` with named axes

    data   — data parallelism (batch axis).  Gradient psum rides ICI
             AllReduce; there is no parameter server to push/pull.
    model  — tensor/model parallelism for wide layers & sharded embeddings
             (replaces ParallelNeuralNetwork per-layer device placement and
             the row-sharded sparse tables on pservers).

Parameters/optimizer state are replicated over `data` (or sharded over
`model` when a layer opts in); batches are sharded over `data` on the leading
axis.  XLA inserts the collectives.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
MODEL_AXIS = "model"


def shard_map(*args, **kwargs):
    """``jax.shard_map`` across jax versions: the top-level name (jax >=
    0.5) with a fallback to ``jax.experimental.shard_map`` — call sites
    (ring attention, the GPipe pipeline, the allreduce bench) stay one
    spelling."""
    fn = getattr(jax, "shard_map", None)
    if fn is None:  # older jax: experimental namespace only
        from jax.experimental.shard_map import shard_map as fn
    if "check_vma" in kwargs:
        # the replication-check kwarg was renamed check_rep -> check_vma;
        # mid-window jax exposes the top-level name but still takes
        # check_rep, so translate by the actual signature, not the lookup
        # path
        try:
            import inspect

            sig_params = inspect.signature(fn).parameters
        except (TypeError, ValueError):
            sig_params = {"check_vma": None}
        if "check_vma" not in sig_params and "check_rep" in sig_params:
            kwargs["check_rep"] = kwargs.pop("check_vma")
    return fn(*args, **kwargs)


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    data: int = -1  # -1 = all remaining devices
    model: int = 1


_default_mesh: Optional[Mesh] = None


def make_mesh(
    data: int = -1,
    model: int = 1,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    devs = list(devices if devices is not None else jax.devices())
    n = len(devs)
    if data == -1:
        assert n % model == 0, f"{n} devices not divisible by model={model}"
        data = n // model
    assert data * model == n, f"mesh {data}x{model} != {n} devices"
    arr = np.array(devs).reshape(data, model)
    return Mesh(arr, (DATA_AXIS, MODEL_AXIS))


def set_default_mesh(mesh: Optional[Mesh]) -> None:
    global _default_mesh
    _default_mesh = mesh


def get_default_mesh() -> Optional[Mesh]:
    return _default_mesh


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Shard the leading (batch) axis across the data axis."""
    return NamedSharding(mesh, P(DATA_AXIS))


def shard_batch(batch, mesh: Optional[Mesh]):
    """Place a Batch pytree so every leaf's leading axis is split over the
    data axis (the feeder guarantees batch % data-size == 0)."""
    if mesh is None:
        return batch
    sh = batch_sharding(mesh)
    return jax.tree_util.tree_map(lambda x: jax.device_put(x, sh), batch)
