"""Parameter sharding rules — the replacement for the reference's row-sharded
sparse parameter servers and per-layer device placement.

The reference shards embedding tables by row across pservers and has trainers
prefetch only the touched rows (reference: paddle/math/SparseRowMatrix.h:204
SparsePrefetchRowCpuMatrix, pserver/ParameterServer2.h:510 getParameterSparse,
trainer/RemoteParameterUpdater.h:265 SparseRemoteParameterUpdater).  On TPU
there is no parameter server: the table lives sharded over the mesh `model`
axis and `jnp.take` under SPMD makes XLA emit the gather + collectives — the
"prefetch" is an ICI all-gather of exactly the touched rows' partitions,
fused into the step.

Rules (derived from layer configs, applied to the params pytree):

  * ``embedding`` with ``ParamAttr(sparse_update=True)`` or
    ``shard_axis='model'``  →  table rows sharded: P('model', None)
  * ``fc``/``selective_fc`` with ``shard_axis='model'``  →  column-parallel:
    w P(None, 'model'), bias P('model') (replaces ParallelNeuralNetwork's
    per-layer `device` attr, reference ParallelNeuralNetwork.h:34)
  * everything else replicated: P()
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from paddle_tpu.parallel.mesh import MODEL_AXIS

Params = Dict[str, Dict[str, Any]]


def _spec_for(conf, param_name: str, value) -> P:
    ndim = getattr(value, "ndim", 0)
    sharded = bool(conf.attr("sparse_update")) or conf.shard_axis == MODEL_AXIS
    if not sharded:
        return P()
    if conf.type == "embedding":
        # row-sharded vocab table
        return P(MODEL_AXIS, *([None] * (ndim - 1))) if ndim >= 1 else P()
    if conf.type in ("fc", "selective_fc"):
        if param_name.startswith("w") and ndim == 2:
            return P(None, MODEL_AXIS)  # column-parallel
        if param_name == "b" and ndim == 1:
            return P(MODEL_AXIS)
    if conf.type == "moe" and param_name != "router":
        # expert parallelism: every expert-major [E, ...] tensor splits its
        # expert axis across the model axis (layers/moe.py)
        return P(MODEL_AXIS, *([None] * (ndim - 1))) if ndim >= 1 else P()
    return P()


def param_shardings(network, params: Params, mesh: Mesh) -> Params:
    """NamedSharding pytree matching `params`."""
    specs: Params = {}
    for lname, pdict in params.items():
        conf = network.topology.get(lname)
        specs[lname] = {
            k: NamedSharding(mesh, _spec_for(conf, k, v)) for k, v in pdict.items()
        }
    return specs


def shard_params(network, params: Params, mesh: Optional[Mesh]) -> Params:
    """Place every parameter according to the layer rules (replicated unless
    a rule shards it).  Idempotent; call once after init or restore."""
    if mesh is None:
        return params
    specs = param_shardings(network, params, mesh)
    return {
        lname: {k: jax.device_put(v, specs[lname][k]) for k, v in pdict.items()}
        for lname, pdict in params.items()
    }


def has_model_sharding(network, params: Params, mesh: Optional[Mesh]) -> bool:
    """True when any rule actually shards over a >1-sized model axis."""
    if mesh is None or mesh.shape.get(MODEL_AXIS, 1) <= 1:
        return False
    for lname, pdict in params.items():
        conf = network.topology.get(lname)
        for k, v in pdict.items():
            if _spec_for(conf, k, v) != P():
                return True
    return False
