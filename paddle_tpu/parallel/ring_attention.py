"""Ring attention — exact sequence/context parallelism for long sequences.

The reference scales long sequences by CPU-side sequence re-batching inside
RecurrentGradientMachine; it has no attention-era context parallelism.  The
TPU-native design shards the SEQUENCE axis across a mesh axis and computes
exact attention by rotating key/value blocks around the ring with
``jax.lax.ppermute`` while accumulating an online (streaming) softmax —
attention memory per chip drops from O(T²) to O(T·T/n) and activations to
O(T/n), with the k/v transfer overlapping compute on ICI
(Liu et al., Ring Attention; the public long-context recipe).

``ring_attention`` is the shard_map-level primitive (q/k/v already sharded
[B, T/n, H, dh] per device); ``sequence_parallel_attention`` wraps it in
shard_map over a mesh for global [B, T, H, dh] arrays.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

def ring_attention(
    q: jnp.ndarray,  # [B, Tq_loc, H, dh]  (this device's query block)
    k: jnp.ndarray,  # [B, Tk_loc, H, dh]  (this device's key block)
    v: jnp.ndarray,  # [B, Tk_loc, H, dh]
    axis_name: str,
    lengths: Optional[jnp.ndarray] = None,  # [B] GLOBAL valid key count
    causal: bool = False,
    scale: Optional[float] = None,
) -> jnp.ndarray:
    """Exact attention over the ring; call inside shard_map with the
    sequence axis sharded over `axis_name`.  Returns [B, Tq_loc, H, dh].

    Each of the n ring steps computes this device's queries against ONE
    rotated k/v block and folds it into a streaming softmax (running max m,
    normalizer l, accumulator o) — numerically identical to softmax over
    the full row, never materializing the [T, T] matrix."""
    n = jax.lax.psum(1, axis_name)
    my = jax.lax.axis_index(axis_name)
    b, t_loc, h, dh = q.shape
    scale = scale if scale is not None else 1.0 / math.sqrt(dh)
    q_pos = my * t_loc + jnp.arange(t_loc)  # global positions of my queries

    o = jnp.zeros((b, h, t_loc, dh), jnp.float32)
    m = jnp.full((b, h, t_loc), -jnp.inf, jnp.float32)
    l = jnp.zeros((b, h, t_loc), jnp.float32)
    perm = [(j, (j + 1) % n) for j in range(n)]

    for step in range(n):  # n is static under shard_map tracing
        src = (my - step) % n  # whose block we hold this step
        k_pos = src * k.shape[1] + jnp.arange(k.shape[1])
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
        if lengths is not None:
            s = jnp.where(
                (k_pos[None, :] < lengths[:, None])[:, None, None, :], s, -jnp.inf
            )
        if causal:
            s = jnp.where(
                (k_pos[None, :] <= q_pos[:, None])[None, None], s, -jnp.inf
            )
        blk_max = jnp.max(s, axis=-1)
        m_new = jnp.maximum(m, blk_max)
        # fully-masked rows keep m=-inf; shift by 0 there to avoid nan
        shift = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
        p = jnp.exp(s - shift[..., None])
        p = jnp.where(jnp.isfinite(s), p, 0.0)  # masked keys contribute 0
        corr = jnp.where(jnp.isfinite(m), jnp.exp(m - shift), 0.0)
        o = o * corr[..., None] + jnp.einsum(
            "bhqk,bkhd->bhqd", p, v.astype(jnp.float32)
        )
        l = l * corr + jnp.sum(p, axis=-1)
        m = m_new
        if step != n - 1:
            k = jax.lax.ppermute(k, axis_name, perm)
            v = jax.lax.ppermute(v, axis_name, perm)

    out = o / jnp.maximum(l, 1e-20)[..., None]  # [B, H, Tq, dh]
    return jnp.swapaxes(out, 1, 2).astype(q.dtype)  # [B, Tq, H, dh]


def sequence_parallel_attention(
    q: jnp.ndarray,  # [B, T, H, dh] global
    k: jnp.ndarray,
    v: jnp.ndarray,
    mesh: Mesh,
    axis_name: str,
    lengths: Optional[jnp.ndarray] = None,
    causal: bool = False,
) -> jnp.ndarray:
    """shard_map wrapper: shards T over `axis_name`, runs the ring, returns
    the global [B, T, H, dh] result (sharded the same way under jit)."""
    t = q.shape[1]
    n = mesh.shape[axis_name]
    assert t % n == 0, f"sequence length {t} not divisible by ring size {n}"
    spec = P(None, axis_name, None, None)
    in_specs = (spec, spec, spec) + ((P(None),) if lengths is not None else ())
    fn = functools.partial(ring_attention, axis_name=axis_name, causal=causal)

    if lengths is not None:
        def mapped(q_, k_, v_, len_):
            return fn(q_, k_, v_, lengths=len_)
    else:
        def mapped(q_, k_, v_):
            return fn(q_, k_, v_)

    from paddle_tpu.parallel.mesh import shard_map as _shard_map

    shmapped = _shard_map(mapped, mesh=mesh, in_specs=in_specs, out_specs=spec)
    args = (q, k, v) + ((lengths,) if lengths is not None else ())
    return shmapped(*args)
