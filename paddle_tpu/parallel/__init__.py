from paddle_tpu.parallel.mesh import (  # noqa: F401
    MeshConfig,
    batch_sharding,
    get_default_mesh,
    make_mesh,
    replicated,
    set_default_mesh,
    shard_batch,
)
