"""Pipeline parallelism over a mesh axis — GPipe-style microbatch
pipelining expressed as a ``shard_map`` collective-permute loop.

The 2017 reference's closest notion is ParallelNeuralNetwork's per-layer
`device` placement (reference: paddle/gserver/gradientmachines/
ParallelNeuralNetwork.h:34) — whole layers pinned to devices with
activations copied between them.  The TPU-native form: S equal-shape
stages live one per device slice along a mesh axis; M microbatches stream
through; each tick every stage computes its current microbatch and
``ppermute``s the activation to the next stage over ICI.  The classic
GPipe bubble is (S-1)/(M+S-1); everything is static-shape and jittable,
and ``jax.grad`` differentiates straight through the permutes (the
backward pipeline falls out of the transpose of ppermute).

Stages must be shape-preserving ([mb, D] -> [mb, D]) — the equal-width
transformer-block regime pipelining exists for.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from paddle_tpu.parallel.mesh import MODEL_AXIS


def stack_stage_params(per_stage_params) -> Any:
    """[S] list of identically-shaped stage param pytrees -> one pytree with
    a leading S axis (what pipeline_apply shards over the pipe axis)."""
    return jax.tree_util.tree_map(
        lambda *leaves: jnp.stack(leaves), *per_stage_params
    )


def split_microbatches(x: jnp.ndarray, num_microbatches: int) -> jnp.ndarray:
    """[B, ...] -> [M, B/M, ...]."""
    b = x.shape[0]
    assert b % num_microbatches == 0, (
        f"batch {b} not divisible by {num_microbatches} microbatches"
    )
    return x.reshape((num_microbatches, b // num_microbatches) + x.shape[1:])


def pipeline_apply(
    stage_fn: Callable[[Any, jnp.ndarray], jnp.ndarray],
    stage_params: Any,
    microbatches: jnp.ndarray,
    mesh: Mesh,
    axis: str = MODEL_AXIS,
) -> jnp.ndarray:
    """Run every microbatch through all S pipeline stages.

    stage_params: pytree whose leaves have leading axis S == mesh.shape[axis]
    (see stack_stage_params); microbatches: [M, mb, D] (split_microbatches).
    Returns [M, mb, D] outputs, replicated across the pipe axis.
    """
    s_total = mesh.shape[axis]
    m_total = microbatches.shape[0]
    perm = [(i, (i + 1) % s_total) for i in range(s_total)]

    def spmd(params_slice, mbs):
        my_params = jax.tree_util.tree_map(lambda v: v[0], params_slice)
        s = jax.lax.axis_index(axis)
        mb_shape = mbs.shape[1:]
        x_cur = jnp.zeros(mb_shape, mbs.dtype)
        outputs = jnp.zeros_like(mbs)

        def tick(t, carry):
            x_cur, outputs = carry
            first_in = jax.lax.dynamic_index_in_dim(
                mbs, jnp.clip(t, 0, m_total - 1), axis=0, keepdims=False
            )
            xin = jnp.where(s == 0, first_in, x_cur)
            y = stage_fn(my_params, xin)
            out_idx = jnp.clip(t - (s_total - 1), 0, m_total - 1)
            write = jnp.logical_and(s == s_total - 1, t >= s_total - 1)
            outputs = jnp.where(
                write,
                jax.lax.dynamic_update_index_in_dim(outputs, y, out_idx, 0),
                outputs,
            )
            x_next = jax.lax.ppermute(y, axis, perm)
            return x_next, outputs

        _, outputs = jax.lax.fori_loop(
            0, m_total + s_total - 1, tick, (x_cur, outputs)
        )
        # only the last stage holds real outputs: zero the rest and psum to
        # replicate the result across the pipe axis
        outputs = jnp.where(s == s_total - 1, outputs, jnp.zeros_like(outputs))
        return jax.lax.psum(outputs, axis)

    in_specs = (
        jax.tree_util.tree_map(lambda _: P(axis), stage_params),
        P(),
    )
    from paddle_tpu.parallel.mesh import shard_map as _shard_map

    fn = _shard_map(
        spmd, mesh=mesh, in_specs=in_specs, out_specs=P(), check_vma=False
    )
    return fn(stage_params, microbatches)
