"""Optimizers — TPU-native rebuild of the reference optimizer registry
(reference: paddle/parameter/FirstOrderOptimizer.h:23-331,
TrainingAlgorithmOp.cu fused kernels, OptimizerConfig.proto, and the
``paddle.v2.optimizer`` surface python/paddle/v2/optimizer.py).

Each optimizer is an optax-style pure pair (init, update) over the parameter
pytree; the whole update runs inside the jitted train step, so XLA fuses it —
the moral equivalent of the reference's hand-fused TrainingAlgorithmOp.cu
kernels for free.  Learning-rate schedules mirror
paddle/parameter/LearningRateScheduler.cpp:43-115.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

OptState = Dict[str, Any]
Schedule = Callable[[jnp.ndarray], jnp.ndarray]  # step -> lr multiplier


# ---------------------------------------------------------------------------
# learning-rate schedules (LearningRateScheduler.cpp)
# ---------------------------------------------------------------------------


def constant_schedule() -> Schedule:
    return lambda step: jnp.asarray(1.0, jnp.float32)


def poly_schedule(a: float, b: float) -> Schedule:
    """lr * (1 + gamma*t)^-power — reference "poly" with a=gamma, b=power."""
    return lambda step: jnp.power(1.0 + a * step, -b)


def caffe_poly_schedule(a: float, b: float, max_steps: float) -> Schedule:
    return lambda step: jnp.power(1.0 - jnp.minimum(step, max_steps) / max_steps, b)


def exp_schedule(a: float, b: float) -> Schedule:
    """lr * a^(t/b) — reference "exp"."""
    return lambda step: jnp.power(a, step / b)


def discexp_schedule(a: float, b: float) -> Schedule:
    """lr * a^floor(t/b) — reference "discexp"."""
    return lambda step: jnp.power(a, jnp.floor(step / b))


def linear_schedule(a: float, b: float) -> Schedule:
    """max(lr - a*t, b) — reference "linear"."""
    return lambda step: jnp.maximum(1.0 - a * step, b)


def manual_schedule(boundaries, multipliers) -> Schedule:
    """Piecewise-constant (reference "manual"/"pass_manual", ManualLRS):
    the first segment with counter <= boundaries[i] selects multipliers[i];
    past the last boundary the last multiplier holds
    (LearningRateScheduler.cpp ManualLRS::calc)."""
    bs = jnp.asarray(boundaries, jnp.float32)
    ms = jnp.asarray(multipliers, jnp.float32)

    def fn(step):
        # count of boundaries strictly below: num <= segments_[i] keeps
        # segment i, matching the reference's closed upper bound
        idx = jnp.sum((step > bs).astype(jnp.int32))
        return ms[jnp.minimum(idx, ms.shape[0] - 1)]

    return fn


def parse_lr_args(args: str):
    """The reference's ``learning_rate_args`` boundary string
    ``'seg0:rate0,seg1:rate1,...'`` (LearningRateScheduler.cpp ManualLRS
    ctor) -> (segments, rates)."""
    segments, rates = [], []
    for piece in (args or "").split(","):
        piece = piece.strip()
        if not piece:
            continue
        seg, sep, rate = piece.partition(":")
        if not sep:
            raise ValueError(
                f"wrong format for learning_rate_args {args!r}: expected "
                "'seg0:rate0,seg1:rate1,...'"
            )
        segments.append(float(seg))
        rates.append(float(rate))
    if not segments:
        raise ValueError(
            f"learning_rate_schedule 'manual'/'pass_manual' needs a "
            f"non-empty learning_rate_args; got {args!r}"
        )
    return segments, rates


def make_schedule(
    name: str,
    a: float = 0.0,
    b: float = 0.0,
    max_steps: float = 0.0,
    args: str = "",
) -> Schedule:
    if name in ("constant", "fixed", ""):
        return constant_schedule()
    if name == "poly":
        return poly_schedule(a, b)
    if name == "caffe_poly":
        return caffe_poly_schedule(a, b, max_steps)
    if name == "exp":
        return exp_schedule(a, b)
    if name == "discexp":
        return discexp_schedule(a, b)
    if name == "linear":
        return linear_schedule(a, b)
    if name in ("manual", "pass_manual"):
        return manual_schedule(*parse_lr_args(args))
    raise ValueError(f"unknown learning_rate_schedule {name!r}")


def schedule_counter_unit(name: str) -> str:
    """What counter the reference feeds this schedule: "pass" for
    pass_manual (calcLearningRate uses the pass index), "samples" for
    manual (numSamplesProcessed), "step" otherwise (this framework's
    schedules are expressed in update steps; v1 configs convert their
    sample-based decay args via batch_size in make_optimizer)."""
    if name == "pass_manual":
        return "pass"
    if name == "manual":
        return "samples"
    return "step"


# ---------------------------------------------------------------------------
# regularization (paddle/parameter/Regularizer.cpp) & clipping
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class L2Regularization:
    rate: float


@dataclasses.dataclass(frozen=True)
class L1Regularization:
    rate: float


# ---------------------------------------------------------------------------
# optimizer base
# ---------------------------------------------------------------------------


def _zeros_like_tree(params):
    return jax.tree_util.tree_map(jnp.zeros_like, params)


class Optimizer:
    """Base: subclasses implement slot init + the per-leaf update rule.

    The v2 surface keywords match python/paddle/v2/optimizer.py:
    learning_rate, learning_rate_decay_a/b, learning_rate_schedule,
    regularization, gradient_clipping_threshold, model_average.
    """

    def __init__(
        self,
        learning_rate: float = 0.001,
        learning_rate_schedule: str = "constant",
        learning_rate_decay_a: float = 0.0,
        learning_rate_decay_b: float = 0.0,
        learning_rate_max_steps: float = 1.0,
        learning_rate_args: str = "",
        regularization: Optional[Any] = None,
        gradient_clipping_threshold: float = 0.0,
        model_average: Optional["ModelAverage"] = None,
        samples_per_step: float = 1.0,
    ):
        self.learning_rate = learning_rate
        # the schedule itself is a closure; keep its constructor args as
        # primitives so the optimizer's identity is fingerprintable (the
        # AOT executable cache keys compiled steps by it — two optimizers
        # that bake different schedule constants must never share an entry)
        self._schedule_args = (
            learning_rate_schedule,
            learning_rate_decay_a,
            learning_rate_decay_b,
            learning_rate_max_steps,
            learning_rate_args,
        )
        self.schedule = make_schedule(
            learning_rate_schedule,
            learning_rate_decay_a,
            learning_rate_decay_b,
            learning_rate_max_steps,
            learning_rate_args,
        )
        # "manual" boundaries count SAMPLES (reference numSamplesProcessed);
        # samples_per_step (the batch size, set by v1_compat.make_optimizer)
        # converts the step counter.  "pass_manual" counts passes: the
        # trainer publishes the pass index into opt_state["pass"].
        self.schedule_unit = schedule_counter_unit(learning_rate_schedule)
        self.samples_per_step = float(samples_per_step)
        self.regularization = regularization
        self.clip = gradient_clipping_threshold
        self.model_average = model_average

    # -- slots ---------------------------------------------------------
    def init_slots(self, params) -> Dict[str, Any]:
        return {}

    def init(self, params) -> OptState:
        state: OptState = {"step": jnp.zeros((), jnp.int32)}
        if self.schedule_unit == "pass":
            state["pass"] = jnp.zeros((), jnp.int32)
        state.update(self.init_slots(params))
        if self.model_average is not None:
            state["avg"] = jax.tree_util.tree_map(lambda p: p.astype(jnp.float32), params)
            state["avg_count"] = jnp.zeros((), jnp.float32)
        return state

    # -- update --------------------------------------------------------
    def rule(self, g, p, lr, state_leaves, step):
        """Per-leaf update; returns (delta, new_state_leaves)."""
        raise NotImplementedError

    def slot_names(self) -> Tuple[str, ...]:
        return ()

    def update(self, grads, state: OptState, params) -> Tuple[Any, OptState]:
        step = state["step"]
        if self.schedule_unit == "pass":
            counter = state["pass"].astype(jnp.float32)
        elif self.schedule_unit == "samples":
            # the reference bumps numSamplesProcessed BEFORE computing the
            # rate (ParameterUpdater.h startBatch/finishBatch order), so the
            # first update already sees num = batchSize
            counter = (step.astype(jnp.float32) + 1.0) * self.samples_per_step
        else:
            counter = step.astype(jnp.float32)
        lr = self.learning_rate * self.schedule(counter)

        # global gradient clipping by value threshold (reference
        # gradient_clipping_threshold clips elementwise per parameter).
        if self.clip > 0:
            grads = jax.tree_util.tree_map(
                lambda g: jnp.clip(g, -self.clip, self.clip), grads
            )

        # decoupled-style L2: reference folds decay into the gradient
        # (Regularizer applied before the update rule).
        if isinstance(self.regularization, L2Regularization) and self.regularization.rate:
            rate = self.regularization.rate
            grads = jax.tree_util.tree_map(lambda g, p: g + rate * p, grads, params)

        names = self.slot_names()
        slot_trees = [state[n] for n in names]

        leaves_g, treedef = jax.tree_util.tree_flatten(grads)
        leaves_p = treedef.flatten_up_to(params)
        leaves_slots = [treedef.flatten_up_to(s) for s in slot_trees]

        new_p_leaves = []
        new_slot_leaves = [[] for _ in names]
        for i, (g, p) in enumerate(zip(leaves_g, leaves_p)):
            sl = tuple(s[i] for s in leaves_slots)
            new_p, new_sl = self.rule(g, p, lr, sl, step)
            new_p_leaves.append(new_p)
            for j, v in enumerate(new_sl):
                new_slot_leaves[j].append(v)

        new_params = jax.tree_util.tree_unflatten(treedef, new_p_leaves)
        new_state = dict(state)
        new_state["step"] = step + 1
        for j, n in enumerate(names):
            new_state[n] = jax.tree_util.tree_unflatten(treedef, new_slot_leaves[j])

        # L1: proximal shrink after the step (reference applyL1).
        if isinstance(self.regularization, L1Regularization) and self.regularization.rate:
            lam = lr * self.regularization.rate
            new_params = jax.tree_util.tree_map(
                lambda p: jnp.sign(p) * jnp.maximum(jnp.abs(p) - lam, 0.0), new_params
            )

        if self.model_average is not None:
            window = self.model_average.average_window
            new_state["avg"] = jax.tree_util.tree_map(
                lambda a, p: (1.0 - window) * a + window * p, state["avg"], new_params
            )
            new_state["avg_count"] = state["avg_count"] + 1.0

        return new_params, new_state


@dataclasses.dataclass(frozen=True)
class ModelAverage:
    """reference AverageOptimizer (parameter averaging for eval),
    paddle/parameter/AverageOptimizer.cpp.  Exponential window here."""

    average_window: float = 0.01


# ---------------------------------------------------------------------------
# concrete optimizers
# ---------------------------------------------------------------------------


class Momentum(Optimizer):
    """SGD with (optionally Nesterov) momentum — SgdOptimizer/
    sgd_optimizer.cc."""

    def __init__(self, momentum: float = 0.0, nesterov: bool = False, **kw):
        super().__init__(**kw)
        self.momentum = momentum
        self.nesterov = nesterov

    def slot_names(self):
        return ("m",) if self.momentum else ()

    def init_slots(self, params):
        return {"m": _zeros_like_tree(params)} if self.momentum else {}

    def rule(self, g, p, lr, slots, step):
        if not self.momentum:
            return p - lr * g, ()
        (m,) = slots
        m = self.momentum * m - lr * g
        if self.nesterov:
            delta = self.momentum * m - lr * g
        else:
            delta = m
        return p + delta, (m,)


SGD = Momentum


class SparseMomentum(Momentum):
    """reference SparseMomentumParameterOptimizer
    (paddle/parameter/FirstOrderOptimizer.h:52): momentum whose lazy
    alpha/beta bookkeeping lets the CPU pserver touch only the rows a sparse
    gradient hit.  The algorithm it computes is plain momentum — the
    laziness is a host-memory optimization with no TPU analogue (the dense
    vectorized update is the fast path here, and sparse tables shard over
    the mesh instead: parallel/sharding.py) — so this subclass IS Momentum,
    kept as a distinct type for v1 config compatibility."""

    def __init__(self, momentum: float = 0.9, **kw):
        super().__init__(momentum=momentum, **kw)


class AdaGrad(Optimizer):
    """AdagradParameterOptimizer (FirstOrderOptimizer.h:44)."""

    def __init__(self, epsilon: float = 1e-6, **kw):
        super().__init__(**kw)
        self.epsilon = epsilon

    def slot_names(self):
        return ("accum",)

    def init_slots(self, params):
        return {"accum": _zeros_like_tree(params)}

    def rule(self, g, p, lr, slots, step):
        (acc,) = slots
        acc = acc + jnp.square(g)
        return p - lr * g / (jnp.sqrt(acc) + self.epsilon), (acc,)


class AdaDelta(Optimizer):
    """AdaDeltaParameterOptimizer (FirstOrderOptimizer.h:82): rho/epsilon."""

    def __init__(self, rho: float = 0.95, epsilon: float = 1e-6, **kw):
        super().__init__(**kw)
        self.rho = rho
        self.epsilon = epsilon

    def slot_names(self):
        return ("accum_g", "accum_x")

    def init_slots(self, params):
        return {"accum_g": _zeros_like_tree(params), "accum_x": _zeros_like_tree(params)}

    def rule(self, g, p, lr, slots, step):
        eg, ex = slots
        eg = self.rho * eg + (1 - self.rho) * jnp.square(g)
        dx = -jnp.sqrt((ex + self.epsilon) / (eg + self.epsilon)) * g
        ex = self.rho * ex + (1 - self.rho) * jnp.square(dx)
        return p + lr * dx, (eg, ex)


class RMSProp(Optimizer):
    """RMSPropParameterOptimizer (FirstOrderOptimizer.h:124): maintains both
    E[g^2] and E[g] (centered variant, as the reference does)."""

    def __init__(self, rho: float = 0.95, epsilon: float = 1e-6, **kw):
        super().__init__(**kw)
        self.rho = rho
        self.epsilon = epsilon

    def slot_names(self):
        return ("ms", "mg")

    def init_slots(self, params):
        return {"ms": _zeros_like_tree(params), "mg": _zeros_like_tree(params)}

    def rule(self, g, p, lr, slots, step):
        ms, mg = slots
        ms = self.rho * ms + (1 - self.rho) * jnp.square(g)
        mg = self.rho * mg + (1 - self.rho) * g
        return (
            p - lr * g / jnp.sqrt(ms - jnp.square(mg) + self.epsilon),
            (ms, mg),
        )


class DecayedAdaGrad(Optimizer):
    """DecayedAdagradParameterOptimizer (FirstOrderOptimizer.h:166)."""

    def __init__(self, rho: float = 0.95, epsilon: float = 1e-6, **kw):
        super().__init__(**kw)
        self.rho = rho
        self.epsilon = epsilon

    def slot_names(self):
        return ("accum",)

    def init_slots(self, params):
        return {"accum": _zeros_like_tree(params)}

    def rule(self, g, p, lr, slots, step):
        (acc,) = slots
        acc = self.rho * acc + (1 - self.rho) * jnp.square(g)
        return p - lr * g / jnp.sqrt(acc + self.epsilon), (acc,)


class Adam(Optimizer):
    """AdamParameterOptimizer (FirstOrderOptimizer.h:205) with bias
    correction, matching adam_optimizer.cc."""

    def __init__(
        self, beta1: float = 0.9, beta2: float = 0.999, epsilon: float = 1e-8, **kw
    ):
        super().__init__(**kw)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon

    def slot_names(self):
        return ("m", "v")

    def init_slots(self, params):
        return {"m": _zeros_like_tree(params), "v": _zeros_like_tree(params)}

    def rule(self, g, p, lr, slots, step):
        m, v = slots
        t = step.astype(jnp.float32) + 1.0
        m = self.beta1 * m + (1 - self.beta1) * g
        v = self.beta2 * v + (1 - self.beta2) * jnp.square(g)
        # the floor keeps the bias-correction denominator provably
        # positive (N403); for any t >= 1 it is >= 1-beta >> 1e-16, so
        # the max is bit-identical to the unguarded form
        mhat = m / jnp.maximum(1 - jnp.power(self.beta1, t), 1e-16)
        vhat = v / jnp.maximum(1 - jnp.power(self.beta2, t), 1e-16)
        return p - lr * mhat / (jnp.sqrt(vhat) + self.epsilon), (m, v)


class AdaMax(Optimizer):
    """AdamaxParameterOptimizer (FirstOrderOptimizer.h:255)."""

    def __init__(self, beta1: float = 0.9, beta2: float = 0.999, **kw):
        super().__init__(**kw)
        self.beta1 = beta1
        self.beta2 = beta2

    def slot_names(self):
        return ("m", "u")

    def init_slots(self, params):
        return {"m": _zeros_like_tree(params), "u": _zeros_like_tree(params)}

    def rule(self, g, p, lr, slots, step):
        m, u = slots
        t = step.astype(jnp.float32) + 1.0
        m = self.beta1 * m + (1 - self.beta1) * g
        u = jnp.maximum(self.beta2 * u, jnp.abs(g))
        # same N403 floor as Adam: bit-identical for t >= 1
        corr = jnp.maximum(1 - jnp.power(self.beta1, t), 1e-16)
        return p - (lr / corr) * m / (u + 1e-12), (m, u)
