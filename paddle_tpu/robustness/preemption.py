"""Preemption-safe shutdown and resume.

TPU slices are preemptible: the scheduler sends SIGTERM and the job has a
grace window to get its state out.  The reference survives this at the
pserver tier (a restarted shard reloads its CRC-checked optimizer-state
checkpoint and training resumes, go/pserver/service.go:244-303); here the
whole jit-visible state is one checkpoint, so the story is:

  signal → finish the in-flight step → synchronous full-state checkpoint
  (params + optimizer state + RNG + pass/batch position) → ``PREEMPTED``
  marker → exit.         (trainer/sgd.py checks the guard once per batch)

  restart with ``--resume`` → restore the latest good checkpoint → skip the
  already-consumed batches of the interrupted pass → the trajectory
  continues exactly where it stopped (bit-for-bit vs an uninterrupted run
  when the reader is deterministic — tests/test_chaos_e2e.py proves it
  with a kill -9).

``PreemptionGuard`` is a context manager that installs chained signal
handlers: the FIRST signal sets a flag the training loop polls (the
non-blocking health-signal model of arXiv:1605.08695 §4.4 — no mid-step
interruption, no torn device state); a SECOND signal falls through to the
previously-installed handler, so a stuck run can still be killed with two
Ctrl-Cs.

The SERVING plane rides the same guard (`paddle-tpu serve`, cli.py): the
first SIGTERM triggers ``ServingScheduler.drain()`` — stop admitting,
finish every in-flight request, exit 0 — instead of a checkpoint; the
second-signal escape hatch is identical (tests/test_scenarios_e2e.py
drills both).
"""

from __future__ import annotations

import json
import logging
import os
import signal
import threading
from typing import Any, Dict, Optional

__all__ = [
    "PreemptionGuard",
    "MARKER_NAME",
    "write_marker",
    "read_marker",
    "clear_marker",
]

_log = logging.getLogger("paddle_tpu.robustness")

MARKER_NAME = "PREEMPTED"


class PreemptionGuard:
    def __init__(self, signals=(signal.SIGTERM, signal.SIGINT)):
        self.signals = tuple(signals)
        self._event = threading.Event()
        self._prev: Dict[int, Any] = {}
        self._installed = False
        self.signum: Optional[int] = None

    # ------------------------------------------------------------------
    def _handler(self, signum, frame):
        if self._event.is_set():
            # second signal: the operator means it — chain to the previous
            # handler (default SIGTERM terminates; SIGINT raises
            # KeyboardInterrupt) instead of absorbing it
            prev = self._prev.get(signum)
            if callable(prev):
                prev(signum, frame)
                return
            signal.signal(signum, prev or signal.SIG_DFL)
            os.kill(os.getpid(), signum)
            return
        self.signum = signum
        self._event.set()
        _log.warning(
            "signal %d: preemption requested — will checkpoint after the "
            "in-flight step and exit (repeat the signal to force)", signum,
        )

    def __enter__(self) -> "PreemptionGuard":
        try:
            for s in self.signals:
                self._prev[s] = signal.signal(s, self._handler)
            self._installed = True
        except ValueError:
            # signal.signal only works on the main thread; a trainer driven
            # from a worker thread keeps running without preemption capture
            _log.debug("not on main thread; preemption guard inactive")
        return self

    def __exit__(self, *exc) -> None:
        if self._installed:
            for s, prev in self._prev.items():
                signal.signal(s, prev)
            self._installed = False
        return None

    @property
    def triggered(self) -> bool:
        return self._event.is_set()


# ---------------------------------------------------------------------------
# PREEMPTED marker (lives beside the checkpoints)
# ---------------------------------------------------------------------------

def _marker_path(directory: str) -> str:
    return os.path.join(directory, MARKER_NAME)


def write_marker(directory: str, info: Dict[str, Any]) -> str:
    os.makedirs(directory, exist_ok=True)
    tmp = _marker_path(directory) + ".tmp"
    with open(tmp, "w") as f:
        json.dump(info, f)
    os.replace(tmp, _marker_path(directory))
    return _marker_path(directory)


def read_marker(directory: str) -> Optional[Dict[str, Any]]:
    try:
        with open(_marker_path(directory)) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def clear_marker(directory: str) -> None:
    try:
        os.remove(_marker_path(directory))
    except OSError:
        pass
