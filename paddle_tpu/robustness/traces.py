"""Request-lifecycle trace record/replay — the ``.ptt`` format.

Every resilience number before this plane was proven against *synthetic*
traffic: the open-loop loadgen draws arrivals/prompts/sessions from a
seeded RNG, so "the workload" exists only as (generator code, seed).
This module makes a served workload itself the durable artifact — the
TF-paper's treatment of inputs as replayable data (arXiv:1605.08695
§4.4) applied to the serving plane's request lifecycles:

* **Record** (``paddle-tpu serve --record-trace day.ptt``, router tier
  too): every submitted request appends one framed record — arrival
  offset on the run's own clock, request id, the FULL source token ids
  (bit-determinism beats compactness here), ``max_new_tokens``,
  deadline, session id, priority class — and every cancel appends a
  cancel record.  The writer is append-only and CRC-framed so a crash
  mid-run leaves a *detectably* torn file, never a silently short one.
* **Replay** (``paddle-tpu serve --replay day.ptt``,
  :class:`TraceReplayLoadGen`): the recorded day re-offers against a
  changed build **bit-deterministically** — requests are built purely
  from the records (prompts, sessions, deadlines, priorities all come
  from the trace, never a live RNG — the affinity keys a fleet router
  derives are therefore identical, tests/test_traces.py pins this) and
  arrivals follow the recorded offsets on a virtual arrival clock.
  The replay-vs-live drift gate lives in robustness/scenarios.py
  (``trace_replay_drift``) and is committed as SCENARIO_r20.json.

Format (text, one record per line, canonical JSON so that
read → re-serialize is **byte-identical** — the roundtrip contract):

.. code-block:: text

    #ptt1 {"meta":{...},"version":1}
    {"dl":0.25,"ev":"req","id":"r0","mnt":8,"o":0.0131,...}|9f3c2a01
    {"ev":"cancel","id":"r0","o":0.2,"reason":"client gave up"}|55aa0102
    #ptt-end {"crc":"c0ffee12","n":2}

Every record line carries its own crc32 suffix; the footer carries the
record count and the rolling crc over all record lines.  A missing
footer (torn write), a count/crc mismatch, or a corrupt line raises
:class:`TraceError` with structured fields — a truncated trace is a
*diagnosed* artifact, not a shorter workload.
"""

from __future__ import annotations

import json
import math
import os
import time
import zlib
from typing import Any, Callable, Dict, List, Optional

__all__ = [
    "TRACE_VERSION",
    "TraceError",
    "TraceWriter",
    "Trace",
    "read_trace",
    "serialize_trace",
    "arrival_stats",
    "TraceReplayLoadGen",
]

TRACE_VERSION = 1

_HEADER_TAG = "#ptt1 "
_FOOTER_TAG = "#ptt-end "

# canonical record schema: every request record carries ALL of these keys
# (None where absent) so serialization is shape-stable across writers
_REQ_KEYS = ("dl", "ev", "id", "mnt", "o", "prio", "sess", "src")


class TraceError(ValueError):
    """Structured trace-format rejection: ``path``/``line_no``/``reason``
    name exactly what is wrong (torn footer, crc mismatch, bad record)
    so a replay harness can report the artifact, not a stack trace."""

    def __init__(self, reason: str, *, path: Optional[str] = None,
                 line_no: Optional[int] = None):
        self.reason = reason
        self.path = path
        self.line_no = line_no
        where = path or "<trace>"
        at = f", line {line_no}" if line_no is not None else ""
        super().__init__(f"{where}{at}: {reason}")


def _dump(obj: Any) -> str:
    """The ONE canonical JSON serialization (sorted keys, no spaces):
    byte-identical re-serialization falls out of parse→_dump."""
    return json.dumps(obj, sort_keys=True, separators=(",", ":"),
                      allow_nan=False)


def _frame(payload: str) -> str:
    return f"{payload}|{zlib.crc32(payload.encode()):08x}"


def serialize_trace(records: List[Dict[str, Any]],
                    meta: Optional[Dict[str, Any]] = None) -> str:
    """Serialize ``records`` (+``meta``) to the full ``.ptt`` text —
    the writer and the roundtrip test share this one code path."""
    head = _HEADER_TAG + _dump(
        {"meta": meta or {}, "version": TRACE_VERSION}
    )
    lines = [head]
    rolling = 0
    for rec in records:
        line = _frame(_dump(rec))
        rolling = zlib.crc32((line + "\n").encode(), rolling)
        lines.append(line)
    foot = _FOOTER_TAG + _dump({"crc": f"{rolling:08x}", "n": len(records)})
    lines.append(foot)
    return "\n".join(lines) + "\n"


class TraceWriter:
    """Append-only ``.ptt`` writer.  Offsets default to the writer's own
    clock relative to its first record (the live-capture path); explicit
    ``offset_s`` makes deterministic traces (tests, converters).  The
    footer lands in :meth:`close` — an unclosed (crashed) writer leaves
    a file :func:`read_trace` rejects as torn, by design."""

    def __init__(self, path: str, meta: Optional[Dict[str, Any]] = None,
                 *, clock=time.perf_counter):
        self.path = str(path)
        self._clock = clock
        self._t0: Optional[float] = None
        self._n = 0
        self._rolling = 0
        self._closed = False
        self._f = open(self.path, "w")
        self._f.write(_HEADER_TAG + _dump(
            {"meta": meta or {}, "version": TRACE_VERSION}
        ) + "\n")
        self._f.flush()

    def _offset(self, offset_s: Optional[float]) -> float:
        if offset_s is not None:
            return float(offset_s)
        now = self._clock()
        if self._t0 is None:
            self._t0 = now
        return now - self._t0

    def _write(self, rec: Dict[str, Any]) -> None:
        if self._closed:
            raise TraceError("write on a closed TraceWriter",
                             path=self.path)
        line = _frame(_dump(rec))
        self._rolling = zlib.crc32((line + "\n").encode(), self._rolling)
        self._n += 1
        self._f.write(line + "\n")
        self._f.flush()

    def record_request(self, request, offset_s: Optional[float] = None,
                       ) -> Dict[str, Any]:
        """Append one request record from a serving ``Request``-shaped
        object (``src_ids``/``max_new_tokens``/``deadline_s``/
        ``session_id``/``priority`` duck-typed)."""
        rec = {
            "ev": "req",
            "o": round(self._offset(offset_s), 6),
            "id": str(request.req_id),
            "src": [int(t) for t in request.src_ids],
            "mnt": (int(request.max_new_tokens)
                    if request.max_new_tokens is not None else None),
            "dl": (float(request.deadline_s)
                   if request.deadline_s is not None else None),
            "sess": (str(request.session_id)
                     if getattr(request, "session_id", None) is not None
                     else None),
            "prio": int(getattr(request, "priority", 1)),
        }
        self._write(rec)
        return rec

    def record_cancel(self, req_id: str, offset_s: Optional[float] = None,
                      reason: str = "") -> Dict[str, Any]:
        rec = {
            "ev": "cancel",
            "o": round(self._offset(offset_s), 6),
            "id": str(req_id),
            "reason": str(reason),
        }
        self._write(rec)
        return rec

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._f.write(_FOOTER_TAG + _dump(
            {"crc": f"{self._rolling:08x}", "n": self._n}
        ) + "\n")
        self._f.flush()
        os.fsync(self._f.fileno())
        self._f.close()

    def __enter__(self) -> "TraceWriter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class Trace:
    """A parsed trace: ``meta`` + ordered ``records``.
    :meth:`serialize` re-emits the byte-identical file text."""

    def __init__(self, meta: Dict[str, Any],
                 records: List[Dict[str, Any]],
                 path: Optional[str] = None):
        self.meta = meta
        self.records = records
        self.path = path

    def requests(self) -> List[Dict[str, Any]]:
        return [r for r in self.records if r.get("ev") == "req"]

    def cancels(self) -> List[Dict[str, Any]]:
        return [r for r in self.records if r.get("ev") == "cancel"]

    def serialize(self) -> str:
        return serialize_trace(self.records, self.meta)

    def arrival_stats(self) -> Dict[str, Any]:
        return arrival_stats(self)

    def __len__(self) -> int:
        return len(self.records)


def _parse_line(raw: str, path: str, line_no: int) -> Dict[str, Any]:
    payload, sep, crc = raw.rpartition("|")
    if not sep:
        raise TraceError("record line has no crc frame",
                         path=path, line_no=line_no)
    if f"{zlib.crc32(payload.encode()):08x}" != crc:
        raise TraceError(
            f"record crc mismatch (stored {crc!r})",
            path=path, line_no=line_no,
        )
    try:
        rec = json.loads(payload)
    except ValueError as exc:
        raise TraceError(f"record is not valid JSON: {exc}",
                         path=path, line_no=line_no) from None
    if not isinstance(rec, dict) or "ev" not in rec or "o" not in rec:
        raise TraceError("record missing ev/o fields",
                         path=path, line_no=line_no)
    return rec


def read_trace(path: str) -> Trace:
    """Parse + validate a ``.ptt`` file.  Raises :class:`TraceError`
    on a torn/truncated/corrupt file — the replay contract is
    all-or-nothing, a partial workload is not a workload."""
    try:
        with open(path) as f:
            text = f.read()
    except OSError as exc:
        raise TraceError(f"unreadable: {exc}", path=path) from None
    lines = text.splitlines()
    if not lines or not lines[0].startswith(_HEADER_TAG):
        raise TraceError("missing #ptt1 header (not a trace file)",
                         path=path, line_no=1)
    try:
        head = json.loads(lines[0][len(_HEADER_TAG):])
    except ValueError as exc:
        raise TraceError(f"header is not valid JSON: {exc}",
                         path=path, line_no=1) from None
    if head.get("version") != TRACE_VERSION:
        raise TraceError(
            f"unsupported trace version {head.get('version')!r} "
            f"(this reader speaks {TRACE_VERSION})",
            path=path, line_no=1,
        )
    if not text.endswith("\n"):
        raise TraceError("torn trace: last line has no newline "
                         "(crash mid-record)", path=path,
                         line_no=len(lines))
    if len(lines) < 2 or not lines[-1].startswith(_FOOTER_TAG):
        raise TraceError(
            "torn trace: missing #ptt-end footer (writer never closed)",
            path=path, line_no=len(lines),
        )
    try:
        foot = json.loads(lines[-1][len(_FOOTER_TAG):])
    except ValueError as exc:
        raise TraceError(f"footer is not valid JSON: {exc}",
                         path=path, line_no=len(lines)) from None
    body = lines[1:-1]
    records: List[Dict[str, Any]] = []
    rolling = 0
    for i, raw in enumerate(body):
        rec = _parse_line(raw, path, i + 2)
        rolling = zlib.crc32((raw + "\n").encode(), rolling)
        records.append(rec)
    if foot.get("n") != len(records):
        raise TraceError(
            f"truncated trace: footer declares {foot.get('n')} records, "
            f"file holds {len(records)}",
            path=path, line_no=len(lines),
        )
    if foot.get("crc") != f"{rolling:08x}":
        raise TraceError(
            f"trace body crc mismatch (footer {foot.get('crc')!r})",
            path=path, line_no=len(lines),
        )
    last = -math.inf
    for i, rec in enumerate(records):
        if float(rec["o"]) < last - 1e-9:
            raise TraceError(
                f"arrival offsets not monotonic at record {i}",
                path=path, line_no=i + 2,
            )
        last = float(rec["o"])
    return Trace(head.get("meta", {}), records, path=path)


def arrival_stats(trace: Trace) -> Dict[str, Any]:
    """Arrival-process reconstruction from a recorded trace: count,
    span, mean rate and the inter-arrival coefficient of variation —
    the statistic that separates the loadgen's processes (uniform
    CV→0, Poisson CV→1, burst CV>1), so a recorded workload's process
    is checkable without the generator that made it."""
    offs = [float(r["o"]) for r in trace.requests()]
    n = len(offs)
    if n < 2:
        return {"n": n, "span_s": 0.0, "rate_rps": 0.0, "cv": 0.0,
                "gap_mean_s": 0.0, "gap_std_s": 0.0}
    gaps = [b - a for a, b in zip(offs, offs[1:])]
    mean = sum(gaps) / len(gaps)
    var = sum((g - mean) ** 2 for g in gaps) / len(gaps)
    std = math.sqrt(var)
    span = offs[-1] - offs[0]
    return {
        "n": n,
        "span_s": span,
        "rate_rps": (n - 1) / span if span > 0 else 0.0,
        "cv": std / mean if mean > 0 else 0.0,
        "gap_mean_s": mean,
        "gap_std_s": std,
    }


def _default_factory(rec: Dict[str, Any]):
    """Build a serving ``Request`` purely from a trace record — prompts,
    session, deadline, priority ALL come from the record (never a live
    RNG): the replayed day reproduces the same affinity keys."""
    from paddle_tpu.serving.scheduler import Request

    return Request(
        list(rec["src"]),
        rec.get("mnt"),
        req_id=str(rec["id"]),
        deadline_s=rec.get("dl"),
        session_id=rec.get("sess"),
        priority=int(rec.get("prio", 1)),
    )


class TraceReplayLoadGen:
    """Open-loop replay of a recorded trace: arrivals follow the
    recorded offsets on a fresh virtual arrival clock (``speedup``
    compresses/stretches them uniformly); requests are built by
    ``request_factory(record)`` (default: a serving ``Request`` built
    purely from the record).  Mirrors ``OpenLoopLoadGen.run`` —
    ``submit(request)`` per arrival, bounded-poll sleeps (C306),
    ``stop()`` truncation — plus ``cancel(req_id, reason)`` callbacks
    at the recorded cancel offsets."""

    def __init__(
        self,
        trace: Trace,
        *,
        request_factory: Optional[
            Callable[[Dict[str, Any]], Any]] = None,
        speedup: float = 1.0,
        clock=time.perf_counter,
        sleep=time.sleep,
    ):
        if speedup <= 0:
            raise ValueError("speedup must be > 0")
        self.trace = trace
        self.request_factory = (
            request_factory if request_factory is not None
            else _default_factory
        )
        self.speedup = float(speedup)
        self._clock = clock
        self._sleep = sleep

    @property
    def offered_duration_s(self) -> float:
        recs = self.trace.records
        return float(recs[-1]["o"]) / self.speedup if recs else 0.0

    def run(
        self,
        submit: Callable[[Any], Any],
        stop: Optional[Callable[[], bool]] = None,
        cancel: Optional[Callable[[str, str], Any]] = None,
    ) -> List[Any]:
        submitted: List[Any] = []
        t0 = self._clock()
        for rec in self.trace.records:
            at = float(rec["o"]) / self.speedup
            while True:
                if stop is not None and stop():
                    return submitted
                delay = (t0 + at) - self._clock()
                if delay <= 0:
                    break
                self._sleep(min(delay, 0.05))
            if rec["ev"] == "req":
                submitted.append(submit(self.request_factory(rec)))
            elif rec["ev"] == "cancel" and cancel is not None:
                cancel(str(rec["id"]), rec.get("reason", ""))
        return submitted
