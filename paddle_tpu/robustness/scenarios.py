"""Production-gate scenario harness — mixed traffic, SLOs, chaos under load.

bench.py measures throughput of single workloads; production is mixed
traffic with tail-latency SLOs and faults that arrive WHILE the system is
busy.  This module composes the existing planes — the serving scheduler
(PR 11 + the SLO admission/shedding of this round), the open-loop load
generator (reader/loadgen.py), the elastic master/worker fleet (PRs 6-7)
and the chaos fault points (robustness/chaos.py) — into named, seeded,
diffable scenarios, each returning one flat JSON-able metrics dict in the
Gemma-on-TPU serving vocabulary (arXiv:2605.25645): p50/p95/p99 latency,
goodput under an SLO, shed/reject/timeout counts, and
recovery-time-after-fault.

Fast scenarios (``FAST_SCENARIOS`` — `make scenarios`, sanitizer-armed,
seconds each, in-process):

* ``overload``         — the shed-not-collapse gate: measure the serving
  plane's saturation rate, then offer 1x and 2x that rate open-loop with
  per-request deadlines; at 2x the goodput (completed within SLO) must
  hold >= 80% of the 1x goodput and the p99 of served requests must stay
  inside the SLO — overload degrades to the feasible subset instead of
  collapsing into universal timeouts.
* ``burst_overload``   — the same gate under the ``burst`` arrival
  process (Poisson bursts on a quiet base rate).
* ``nan_request_under_load`` / ``slow_client_under_load`` — the serving
  chaos points fired mid-traffic, reporting recovery time after the
  fault (first completion past the fault) and that ONLY the poisoned
  request fails.
* ``mixed_train_serve`` — train and serve concurrently in one process:
  a deterministic training loop (trainer/elastic.NumpyLinearModel) runs
  beside the serving plane under load with ``nan_request`` fired
  mid-traffic; training must stay bit-identical to a solo run and the
  serving SLO must hold.
* ``partition_under_load`` — the hostile-network gate (robustness/
  netem.py + master_wire.py): a REAL RPC training loop (journaled master
  Service + Server over localhost, an ElasticWorker on a wire-codec
  Client) runs beside live serving traffic while netem corrupts a frame
  (the codec must reject it — counter asserted) and then severs the link
  mid-pass (``net_partition``); gates: the worker rides the partition
  through its bounded-retry window, recovery-time-after-partition is
  reported and bounded, final params are bit-identical to an unfaulted
  reference leg, the surviving journal lints clean, and the co-located
  serving SLO holds.
* ``trace_replay_drift`` — the scenario-realism gate (robustness/
  traces.py): record a mixed two-class overload window to a ``.ptt``
  trace while serving it live, replay the trace bit-deterministically
  against a fresh scheduler, and gate replay-vs-live drift plus the
  per-class SLO contract (the interactive class holds goodput, the
  batch class sheds first at 2x saturation — committed as
  SCENARIO_r20.json).

Slow scenarios (``SLOW_SCENARIOS`` — tests/test_scenarios_e2e.py,
`make chaos`; real process fleets):

* ``fleet_kill_worker`` / ``fleet_kill_master`` — a live train+serve
  mix: an elastic fleet trains over the HA master plane while the parent
  process serves open-loop traffic; ``kill_worker`` SIGKILLs a worker
  holding a shard lease, ``kill_master`` SIGKILLs the LEADER mid-pass
  (the standby takes over warm from the journal).  Reported: recovery
  time after the fault, zero-recompute accounting, bit-identity of the
  final training parameters vs an unfaulted reference, and the serving
  status mix (only shed/timed-out requests may fail).
* ``fleet_serving`` — the serving-FLEET kill drill (ISSUE 18): N real
  ``paddle-tpu serve --register`` engine processes behind the affinity
  router (serving/router.py), open-loop deadline traffic through the
  fleet client, SIGKILL one engine mid-window.  Gates: the corpse is
  pruned by lease expiry (recovery time bounded), traffic re-routes to
  survivors with goodput holding, the disjoint fleet ledger sums to the
  offered count, and the routing journal finalizes every request id
  exactly once — zero double-serves.
* ``fleet_rolling_restart`` — drain+replace EVERY engine under live
  traffic: replacement registers first, the old engine drains via the
  router's drain protocol and exits 0 on SIGTERM; the fleet never drops
  below N-1 live engines and no request dies to the restart.

`paddle-tpu scenario` runs any of these from the command line; bench.py
``bench_scenarios`` puts the fast gates under the regression guard
(SCENARIO_r12.json).
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import threading
import time
from typing import Any, Dict, List, Optional

import numpy as np

__all__ = [
    "FAST_SCENARIOS",
    "SLOW_SCENARIOS",
    "run_scenario",
    "scenario_overload",
    "scenario_chaos_under_load",
    "scenario_mixed_train_serve",
    "scenario_partition_under_load",
    "scenario_trace_replay_drift",
    "fleet_reference",
    "run_fleet_chaos",
    "run_fleet_serving",
    "run_fleet_rolling_restart",
    "make_serving_engine",
]

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# tiny-flagship serving shape: big enough that decode is a real dispatch
# chain with ~tens-of-ms per-request service time (so wall-clock SLOs and
# queueing are meaningful, not noise), small enough that a scenario runs
# in seconds on the CPU container
_V, _E, _H, _MAXLEN = 60, 32, 64, 32


def _pct(xs: List[float], p: float) -> Optional[float]:
    from paddle_tpu.serving import percentile

    return percentile(xs, p)


def _ms(x: Optional[float]) -> Optional[float]:
    return None if x is None else round(x * 1e3, 3)


def make_serving_engine(seed: int = 0, max_slots: int = 2,
                        hbm_budget_mb: int = 2,
                        prefill_chunk_tokens: int = 0,
                        block_steps: int = 1):
    """A prewarmed tiny-flagship serving engine (the bench's cache-warm
    discipline: every slot/page rung the scenarios realize is compiled
    before any measured window, so EWMAs and percentiles see dispatch,
    not XLA)."""
    import paddle_tpu as paddle
    from paddle_tpu.core.topology import reset_auto_names
    from paddle_tpu.models.seq2seq import Seq2SeqGenerator, seq2seq_cost
    from paddle_tpu.serving import Request, ServingEngine

    reset_auto_names()
    cost, _ = seq2seq_cost(_V, _V, word_dim=_E, hidden_dim=_H)
    params = paddle.parameters.create(cost, seed=seed)
    gen = Seq2SeqGenerator(
        params, _V, _V, word_dim=_E, hidden_dim=_H,
        bos_id=0, eos_id=1, max_length=_MAXLEN,
    )
    eng = ServingEngine(
        gen, max_slots=max_slots, hbm_budget_mb=hbm_budget_mb,
        max_new_tokens=_MAXLEN, block_steps=block_steps,
        prefill_chunk_tokens=prefill_chunk_tokens,
    )
    rungs, g = [], 1
    while g < max_slots:
        rungs.append(g)
        g *= 2
    rungs.append(max_slots)
    for gsz in rungs:
        for src_len in (5, 20):
            eng.admit([Request([2] * src_len) for _ in range(gsz)])
            while eng.n_live or eng.n_prefilling:
                eng.step()
    return eng


def _srcs(seed: int, n: int, lo: int = 3, hi: int = 24) -> List[List[int]]:
    rng = np.random.RandomState(seed)
    return [
        rng.randint(2, _V, size=rng.randint(lo, hi)).tolist()
        for _ in range(n)
    ]


def _status_counts(reqs) -> Dict[str, int]:
    from paddle_tpu.serving import status_counts

    return status_counts(reqs)


def _serve_window(engine, srcs, offered_rps: Optional[float], slo_s: float,
                  seed: int, process: str = "poisson",
                  queue_limit: Optional[int] = None,
                  callback=None) -> Dict[str, Any]:
    """One measured serving window: calibrate the scheduler's EWMA, offer
    the sources open-loop (or all at once when ``offered_rps`` is None),
    wait everything out, and report the SLO ledger."""
    from paddle_tpu.reader.loadgen import OpenLoopLoadGen
    from paddle_tpu.serving import Request, ServingScheduler

    reqs = [Request(s, callback=callback) for s in srcs]
    with ServingScheduler(engine, queue_limit=queue_limit) as sched:
        for s in srcs[:3]:  # EWMA calibration, outside the window
            sched.generate(s, timeout=60.0)
        t0 = time.perf_counter()
        if offered_rps is None:
            for r in reqs:
                r.deadline_s = slo_s if slo_s > 0 else None
                sched.submit(r)
        else:
            OpenLoopLoadGen(
                offered_rps, len(reqs), lambda i: reqs[i], seed=seed,
                process=process,
                deadline_s=slo_s if slo_s > 0 else None,
            ).run(sched.submit)
        for r in reqs:
            if not r.wait(300):
                raise RuntimeError(f"request {r.req_id} never finalized")
        wall = time.perf_counter() - t0
    served = [r for r in reqs if r.status == "served"]
    lat = [r.t_done - r.t_submit for r in served]
    in_slo = [x for x in lat if slo_s <= 0 or x <= slo_s]
    service = [
        r.t_done - r.t_admit for r in served if r.t_admit is not None
    ]
    return {
        "n_offered": len(reqs),
        "offered_rps": None if offered_rps is None else round(offered_rps, 2),
        "wall_s": round(wall, 3),
        "statuses": _status_counts(reqs),
        "goodput_rps": round(len(in_slo) / wall, 3) if wall > 0 else None,
        "goodput_frac": round(len(in_slo) / len(reqs), 4),
        "p50_ms": _ms(_pct(lat, 0.50)),
        "p95_ms": _ms(_pct(lat, 0.95)),
        "p99_ms": _ms(_pct(lat, 0.99)),
        "mean_service_ms": _ms(float(np.mean(service)) if service else None),
        "p95_service_ms": _ms(_pct(service, 0.95)),
        "_requests": reqs,
    }


def _resolve_slo_s(slo_ms: Optional[float], wave: Dict[str, Any]) -> float:
    """The scenario SLO: explicit, the ``scenario_slo_ms`` flag, or 2.5x
    the saturation wave's p95 SERVICE time floored at 50 ms — wide enough
    that an unloaded request is always feasible (the 1x goodput base is
    honest), tight enough that 2x queueing must shed."""
    from paddle_tpu.utils import flags as _flags

    if slo_ms is None:
        slo_ms = _flags.get_flag("scenario_slo_ms")
    if slo_ms and slo_ms > 0:
        return float(slo_ms) / 1e3
    base = (wave.get("p95_service_ms")
            or 4.0 * (wave.get("mean_service_ms") or 10.0)) / 1e3
    return max(0.05, 2.5 * base)


def scenario_overload(slo_ms: Optional[float] = None, n_requests: int = 128,
                      seed: int = 0, process: str = "poisson",
                      engine=None) -> Dict[str, Any]:
    """The shed-not-collapse gate: goodput at 2x saturation must hold
    >= 80% of goodput at saturation, and served p99 must stay inside the
    SLO — asserted here, reported as booleans for the bench guard."""
    engine = engine if engine is not None else make_serving_engine(seed)
    # saturation: an all-at-once wave calibrates per-request service time
    # under full slot occupancy; capacity derives ANALYTICALLY as
    # slots / mean-service (the wave's raw wall clock is too noisy on a
    # shared 2-core box to gate on — service time averages the noise out)
    wave = _serve_window(
        engine, _srcs(seed, n_requests), None, 0.0, seed
    )
    saturation_rps = engine.max_slots / (wave["mean_service_ms"] / 1e3)
    slo_s = _resolve_slo_s(slo_ms, wave)
    at_1x = _serve_window(
        engine, _srcs(seed + 1, n_requests), saturation_rps, slo_s,
        seed + 1, process=process,
    )
    at_2x = _serve_window(
        engine, _srcs(seed + 2, 2 * n_requests), 2.0 * saturation_rps,
        slo_s, seed + 2, process=process,
    )
    g1, g2 = at_1x["goodput_rps"], at_2x["goodput_rps"]
    p99 = at_2x["p99_ms"]
    gate_goodput = bool(g1 and g2 and g2 >= 0.8 * g1)
    # served requests may cross the deadline by at most ~one dispatch (the
    # deadline sweep cancels at loop granularity): 10% tolerance
    gate_p99 = bool(p99 is not None and p99 <= slo_s * 1e3 * 1.1)
    out = {
        "scenario": "overload" if process == "poisson" else "burst_overload",
        "arrival": process,
        "slo_ms": round(slo_s * 1e3, 3),
        "saturation_rps": round(saturation_rps, 2),
        "saturation": {k: v for k, v in wave.items() if k != "_requests"},
        "at_1x": {k: v for k, v in at_1x.items() if k != "_requests"},
        "at_2x": {k: v for k, v in at_2x.items() if k != "_requests"},
        "goodput_2x_over_1x": round(g2 / g1, 4) if g1 and g2 else None,
        "gate_goodput_2x_ge_80pct": gate_goodput,
        "gate_p99_within_slo": gate_p99,
        "passed": gate_goodput and gate_p99,
    }
    return out


def scenario_chaos_under_load(point: str = "nan_request",
                              occurrence: int = 5,
                              slo_ms: Optional[float] = None,
                              n_requests: int = 48, seed: int = 0,
                              engine=None) -> Dict[str, Any]:
    """Fire a serving chaos point UNDER live open-loop traffic and report
    recovery-time-after-fault: the gap between the fault consultation and
    the next completed request.  Only the poisoned request may fail (for
    ``nan_request``); a frozen client callback may stall nothing but
    delivery (``serve_slow_client``)."""
    from paddle_tpu.robustness import chaos

    if point not in ("nan_request", "serve_slow_client"):
        raise ValueError(f"not a serving chaos point: {point!r}")
    if occurrence <= 3:
        # the window's 3 EWMA-calibration submits consume the first 3
        # consultations; an earlier occurrence would poison calibration
        raise ValueError("occurrence must be > 3 (calibration offset)")
    engine = engine if engine is not None else make_serving_engine(seed)
    wave = _serve_window(engine, _srcs(seed, 24), None, 0.0, seed)
    saturation_rps = wave["n_offered"] / wave["wall_s"]
    slo_s = _resolve_slo_s(slo_ms, wave)
    delivered: List[Any] = []
    os.environ.setdefault("PADDLE_TPU_CHAOS_HANG_SECS", "2")
    chaos.arm(f"{point}@{occurrence}")
    try:
        win = _serve_window(
            engine, _srcs(seed + 3, n_requests), 0.7 * saturation_rps,
            slo_s, seed + 3,
            # serve_slow_client freezes a client CALLBACK: the drill needs
            # real callbacks on the delivery thread to have one to freeze
            callback=delivered.append,
        )
    finally:
        chaos.disarm()
    reqs = win.pop("_requests")
    failed = [r for r in reqs if r.status not in ("served", "shed", "timeout")]
    # recovery: first completion after the fault's victim was finalized
    # (nan_request) / after the hang began (slow client freezes delivery,
    # so wait()-completion timestamps keep flowing — recovery ~ 0)
    if point == "nan_request":
        victims = [r for r in reqs if r.error and "non-integral" in r.error]
        t_fault = victims[0].t_done if victims else None
    else:
        victims = []
        t_fault = min((r.t_done for r in reqs if r.t_done), default=None)
    recovery_s = None
    if t_fault is not None:
        after = [
            r.t_done - t_fault for r in reqs
            if r.status == "served" and r.t_done is not None
            and r.t_done >= t_fault
        ]
        recovery_s = min(after) if after else None
    ok = (
        (point != "nan_request" or len(victims) == 1)
        and all(r.status in ("served", "shed", "timeout") or r in victims
                for r in reqs)
        and not [r for r in failed if r not in victims]
    )
    return {
        # match the registry key (serve_slow_client registers as
        # slow_client_under_load)
        "scenario": (
            "slow_client_under_load" if point == "serve_slow_client"
            else f"{point}_under_load"
        ),
        "chaos_point": f"{point}@{occurrence}",
        "slo_ms": round(slo_s * 1e3, 3),
        **{k: v for k, v in win.items()},
        "n_chaos_victims": len(victims),
        "recovery_after_fault_ms": _ms(recovery_s),
        "passed": bool(ok),
    }


def _train_linear(n_steps: int, dim: int = 8, seed: int = 1,
                  out: Optional[dict] = None) -> dict:
    """Deterministic in-process training loop (the jax-free elastic-plane
    model): the mixed-traffic scenario's training half.  Returns final
    params + steps/s; bit-identical across runs by construction — any
    divergence under co-located serving is a real isolation bug."""
    from paddle_tpu.trainer.elastic import NumpyLinearModel

    rng = np.random.RandomState(seed)
    w_true = rng.randn(dim).astype(np.float32)
    records = []
    for _ in range(64):
        x = rng.randn(dim).astype(np.float32)
        records.append(
            np.concatenate([x, [np.float32(x @ w_true)]])
            .astype(np.float32).tobytes()
        )
    from paddle_tpu import obs as _obs

    model = NumpyLinearModel(dim, lr=0.2)
    t0 = time.perf_counter()
    for step in range(n_steps):
        lo = (step * 8) % len(records)
        with _obs.span("train_step", cat="trainer", b=step):
            grads, _cost, _n = model.task_grad(
                records[lo:lo + 8], pass_id=0, task_id=step
            )
            model.apply(grads)
    wall = time.perf_counter() - t0
    res = {
        "w": model.w.copy(), "b": model.b.copy(),
        "steps_per_s": n_steps / wall if wall > 0 else None,
    }
    if out is not None:
        out.update(res)
    return res


def _traced_fleet_leg(seed: int) -> Optional[Dict[str, Any]]:
    """Only when span EXPORT is armed (``paddle-tpu scenario --trace``):
    run a one-worker elastic mini-pass over an in-process HA master so
    the merged timeline spans >= 2 PROCESSES and carries the master RPC
    plane — the parent contributes serving + master spans (the Server
    handles the worker's RPCs here), the worker subprocess contributes
    its lease→compute→ack spans, and the RPC request/response pairs give
    `trace merge` its clock-skew anchors.  Runs BEFORE the measured
    serving windows, so the SLO gates never pay its CPU."""
    from paddle_tpu import obs

    if not obs.tracer.exporting:
        return None
    import tempfile

    from paddle_tpu.master_ha import HAMaster

    d = tempfile.mkdtemp(prefix="paddle-tpu-trace-fleet-")
    data = os.path.join(d, "data.rio")
    _write_linear_dataset(data, n=24, seed=seed)
    ha = HAMaster(os.path.join(d, "ha"), [data], owner_id="trace-master",
                  **_MASTER_KW)
    ha.start()
    try:
        if not ha.wait_leader(30):
            raise RuntimeError("trace-leg master never took leadership")
        rcs, errs, stats, _ = _collect_workers(
            d, 1, _spawn_workers(d, 1, 1), timeout=120
        )
        if rcs != [0]:
            raise RuntimeError(f"trace-leg worker failed: {rcs} {errs}")
    finally:
        ha.stop()
    return {
        "worker_rc": rcs[0],
        "tasks_done": stats.get(0, {}).get("tasks_done"),
    }


def scenario_mixed_train_serve(slo_ms: Optional[float] = None,
                               n_requests: int = 48, train_steps: int = 400,
                               seed: int = 0,
                               engine=None) -> Dict[str, Any]:
    """Train and serve concurrently in ONE process: the training loop runs
    on a side thread while the serving plane takes open-loop traffic with
    ``nan_request`` fired mid-stream.  Gates: training params bit-equal
    to the solo run (zero divergence), only the poisoned request fails,
    goodput holds.  Under ``--trace`` a one-worker fleet leg runs first
    (:func:`_traced_fleet_leg`) so the merged timeline is genuinely
    cross-process."""
    from paddle_tpu.robustness import chaos

    traced_fleet = _traced_fleet_leg(seed)
    engine = engine if engine is not None else make_serving_engine(seed)
    solo = _train_linear(train_steps)
    wave = _serve_window(engine, _srcs(seed, 24), None, 0.0, seed)
    saturation_rps = wave["n_offered"] / wave["wall_s"]
    slo_s = _resolve_slo_s(slo_ms, wave)
    mixed: dict = {}
    trainer = threading.Thread(
        target=_train_linear, args=(train_steps,),
        kwargs={"out": mixed}, name="scenario-train", daemon=True,
    )
    chaos.arm("nan_request@7")
    try:
        trainer.start()
        win = _serve_window(
            engine, _srcs(seed + 4, n_requests), 0.7 * saturation_rps,
            slo_s, seed + 4,
        )
        trainer.join(60.0)
    finally:
        chaos.disarm()
    reqs = win.pop("_requests")
    poisoned = [r for r in reqs if r.error and "non-integral" in r.error]
    train_identical = (
        not trainer.is_alive()
        and np.array_equal(mixed.get("w"), solo["w"])
        and np.array_equal(mixed.get("b"), solo["b"])
    )
    serve_ok = len(poisoned) == 1 and all(
        r.status in ("served", "shed", "timeout") for r in reqs
        if r not in poisoned
    )
    out_trace = (
        {} if traced_fleet is None else {"traced_fleet": traced_fleet}
    )
    return {
        "scenario": "mixed_train_serve",
        "slo_ms": round(slo_s * 1e3, 3),
        **win,
        **out_trace,
        "train_steps": train_steps,
        "train_steps_per_s_solo": round(solo["steps_per_s"], 1),
        "train_steps_per_s_mixed": (
            round(mixed["steps_per_s"], 1) if mixed.get("steps_per_s")
            else None
        ),
        "train_bit_identical_to_solo": bool(train_identical),
        "passed": bool(train_identical and serve_ok),
    }


class _AckStamper:
    """Wraps a master client surface, stamping the wall-clock time of
    every SUCCESSFUL task_finished ack — the observable the partition
    drill measures recovery from (first ack landed after the link came
    back).  Everything else delegates untouched."""

    def __init__(self, inner):
        self._inner = inner
        self.ack_times: List[float] = []

    def __getattr__(self, name):
        fn = getattr(self._inner, name)
        if name != "task_finished":
            return fn

        def stamped(*args):
            out = fn(*args)
            if out:
                self.ack_times.append(time.time())
            return out

        return stamped


def _rpc_training_leg(workdir: str, seed: int, passes: int = 2,
                      out: Optional[dict] = None) -> dict:
    """One REAL-RPC training run, in-process: a journaled master Service
    served over localhost, driven by an ElasticWorker whose every call
    rides the master_wire codec (and, when netem chaos is armed, the
    fault-injecting transport).  Deterministic by the elastic protocol,
    so two legs over the same dataset are bit-identical — faulted or
    not."""
    from paddle_tpu.master import Client, Server, Service
    from paddle_tpu.trainer.elastic import ElasticWorker, NumpyLinearModel

    os.makedirs(workdir, exist_ok=True)
    data = os.path.join(workdir, "data.rio")
    _write_linear_dataset(data, n=48, seed=seed)
    svc = Service(
        snapshot_path=os.path.join(workdir, "master_state.json"),
        chunks_per_task=2, timeout_s=8.0, worker_timeout_s=10.0,
        auto_rotate=False, journal=True,
    )
    srv = Server(svc)
    client = Client(srv.address, call_timeout_s=0.75, reconnect_tries=4,
                    reconnect_backoff=0.1)
    stamper = _AckStamper(client)
    model = NumpyLinearModel(_DIM, lr=0.2)
    worker = ElasticWorker(stamper, "w0", model, min_workers=1,
                           rpc_retry_window_s=60.0)
    res: dict = {}
    try:
        client.set_dataset([data])
        summary = worker.run(passes)
        res = {
            "params": model.state(),
            "tasks_done": summary["tasks_done"],
            "pass_costs": summary["pass_costs"],
            "ack_times": list(stamper.ack_times),
            "master_stats": svc.stats(),
        }
    finally:
        try:
            client.close()
        except Exception:  # noqa: BLE001 — the link may be partitioned
            pass
        srv.close()
        jf = None
        try:
            with open(os.path.join(workdir, "master_state.json")) as f:
                jf = json.load(f).get("journal_file")
        except (OSError, ValueError):
            pass
        res["journal_path"] = (
            os.path.join(workdir, jf) if jf else None
        )
        if out is not None:
            out.update(res)
    return res


def scenario_partition_under_load(slo_ms: Optional[float] = None,
                                  n_requests: int = 48, seed: int = 0,
                                  engine=None) -> Dict[str, Any]:
    """The hostile-network gate: corrupt-frame rejection + a mid-pass
    link partition under live mixed train+serve traffic.

    Arms ``net_corrupt@2`` (one early client frame bit-flips in flight —
    the master_wire CRC must reject it server-side, counted, and the
    client's bounded retry must ride it) and ``net_partition@12`` (the
    link goes DOWN for ~1.2s as the 12th egress message leaves, mid-pass)
    on the CLIENT role only, while the serving plane takes open-loop
    deadline traffic in the same process.  Gates: the worker completes
    every pass through its retry window, final training params are
    BIT-IDENTICAL to an unfaulted reference leg, the codec reject counter
    is > 0, recovery-time-after-partition is bounded, the surviving
    journal lints clean, and only shed/timeout serving failures occur."""
    import tempfile

    from paddle_tpu import master_journal as _mj
    from paddle_tpu import master_wire as _wire
    from paddle_tpu.robustness import chaos, netem

    engine = engine if engine is not None else make_serving_engine(seed)
    d = tempfile.mkdtemp(prefix="paddle-tpu-partition-")
    # unfaulted reference leg FIRST (chaos unarmed): the bit-identity
    # target — itself over the real wire codec, same dataset, same seeds
    ref = _rpc_training_leg(os.path.join(d, "reference"), seed)
    wave = _serve_window(engine, _srcs(seed, 24), None, 0.0, seed)
    saturation_rps = wave["n_offered"] / wave["wall_s"]
    slo_s = _resolve_slo_s(slo_ms, wave)

    partition_secs = 1.2
    env_prev = {
        k: os.environ.get(k)
        for k in ("PADDLE_TPU_NETEM_ROLE", "PADDLE_TPU_NETEM_PARTITION_SECS",
                  "PADDLE_TPU_NETEM_DIRECTION")
    }
    os.environ["PADDLE_TPU_NETEM_ROLE"] = "client"
    os.environ["PADDLE_TPU_NETEM_PARTITION_SECS"] = str(partition_secs)
    os.environ["PADDLE_TPU_NETEM_DIRECTION"] = "both"
    _wire.counters.reset()
    netem.reset()
    chaos.arm("net_corrupt@2,net_partition@12")
    faulted: dict = {}
    trainer = threading.Thread(
        target=_rpc_training_leg,
        args=(os.path.join(d, "faulted"), seed),
        kwargs={"out": faulted}, name="scenario-partition-train",
        daemon=True,
    )
    # the serving schedule is sized to OUTLAST the faulted training leg
    # and truncates the moment it exits (run_fleet_chaos discipline), so
    # live deadline traffic genuinely spans the corrupt frame AND the
    # partition window — faults-at-rest prove nothing
    from paddle_tpu.reader.loadgen import OpenLoopLoadGen
    from paddle_tpu.serving import Request, ServingScheduler

    reqs: List[Any] = []
    all_srcs = _srcs(seed + 4, n_requests)

    def _mk(i):
        r = Request(all_srcs[i % len(all_srcs)])
        reqs.append(r)
        return r

    try:
        trainer.start()
        t0 = time.perf_counter()
        t_traffic0 = time.time()
        with ServingScheduler(engine) as sched:
            OpenLoopLoadGen(
                min(0.5 * saturation_rps, 60.0), 20 * n_requests, _mk,
                seed=seed + 4, deadline_s=slo_s,
            ).run(sched.submit, stop=lambda: not trainer.is_alive())
            t_traffic1 = time.time()
            trainer.join(120.0)
            for r in reqs:
                if not r.wait(300):
                    raise RuntimeError(
                        f"request {r.req_id} never finalized"
                    )
        wall = time.perf_counter() - t0
    finally:
        chaos.disarm()
        for k, v in env_prev.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    served = [r for r in reqs if r.status == "served"]
    lat = [r.t_done - r.t_submit for r in served]
    in_slo = [x for x in lat if x <= slo_s]
    win = {
        "n_offered": len(reqs),
        "wall_s": round(wall, 3),
        "statuses": _status_counts(reqs),
        "goodput_frac": (
            round(len(in_slo) / len(reqs), 4) if reqs else None
        ),
        "p50_ms": _ms(_pct(lat, 0.50)),
        "p95_ms": _ms(_pct(lat, 0.95)),
        "p99_ms": _ms(_pct(lat, 0.99)),
    }
    wire_counts = _wire.counters.snapshot()
    netem_counts = netem.counters.snapshot()
    t_part = netem.last_partition_start()
    recovery_s = None
    if t_part > 0:
        after = [t - t_part for t in faulted.get("ack_times", ())
                 if t >= t_part]
        recovery_s = min(after) if after else None
    train_identical = (
        not trainer.is_alive()
        and faulted.get("params") is not None
        and all(
            np.array_equal(faulted["params"][k], ref["params"][k])
            for k in ref["params"]
        )
        and len(faulted.get("pass_costs", ())) == len(ref["pass_costs"])
    )
    jpath = faulted.get("journal_path")
    journal_findings = (
        _mj.verify_journal(jpath) if jpath and os.path.exists(jpath)
        else [{"rule": "J001", "severity": "error",
               "message": "no surviving journal generation"}]
    )
    serve_ok = all(
        r.status in ("served", "shed", "timeout") for r in reqs
    )
    rejects = wire_counts.get("server_rejected_frames", 0)
    gates = {
        "gate_train_bit_identical": bool(train_identical),
        "gate_codec_rejected_corrupt_frame": rejects > 0,
        "gate_partition_fired": t_part > 0,
        # faults-at-rest prove nothing: the open-loop schedule must have
        # been live on BOTH sides of the partition onset
        "gate_traffic_spanned_partition": bool(
            t_part > 0 and t_traffic0 < t_part < t_traffic1
        ),
        "gate_recovered_after_partition": (
            recovery_s is not None and recovery_s < 10.0
        ),
        "gate_journal_lints_clean": not journal_findings,
        "gate_serving_only_shed_or_timeout": bool(serve_ok),
    }
    netem.reset()
    return {
        "scenario": "partition_under_load",
        "chaos_point": "net_corrupt@2,net_partition@12",
        "partition_secs": partition_secs,
        "slo_ms": round(slo_s * 1e3, 3),
        **win,
        "train_tasks_done": faulted.get("tasks_done"),
        "train_params_bit_identical": bool(train_identical),
        "recovery_after_partition_ms": _ms(recovery_s),
        "wire": wire_counts,
        "netem": netem_counts,
        "journal_findings": [f["message"] for f in journal_findings][:5],
        **gates,
        "passed": all(gates.values()),
    }


def _class_ledger(reqs, slo_s: float) -> Dict[str, Dict[str, Any]]:
    """Per-priority-class SLO ledger: offered/served/in-SLO/failed counts
    and goodput per ``class_label`` — the observable the per-class
    admission gate asserts on (high classes keep goodput while low
    classes shed first at overload)."""
    out: Dict[str, Dict[str, Any]] = {}
    for r in reqs:
        c = getattr(r, "class_label", "p1")
        d = out.setdefault(
            c, {"offered": 0, "served": 0, "in_slo": 0, "failed": 0}
        )
        d["offered"] += 1
        if r.status == "served":
            d["served"] += 1
            lat = (
                r.t_done - r.t_submit
                if r.t_done is not None and r.t_submit is not None
                else None
            )
            if lat is not None and (slo_s <= 0 or lat <= slo_s):
                d["in_slo"] += 1
        else:
            d["failed"] += 1
    for d in out.values():
        d["goodput_frac"] = round(d["in_slo"] / d["offered"], 4)
        d["failed_frac"] = round(d["failed"] / d["offered"], 4)
    return out


def scenario_trace_replay_drift(slo_ms: Optional[float] = None,
                                n_requests: int = 72, seed: int = 0,
                                engine=None,
                                trace_path: Optional[str] = None,
                                ) -> Dict[str, Any]:
    """The scenario-realism gate (robustness/traces.py): RECORD a mixed
    two-class overload window to a ``.ptt`` trace while serving it live,
    then REPLAY the trace against a fresh scheduler and gate the drift.

    The live window offers 2x the calibrated saturation rate with
    PrefixMixer sessions and two priority classes (p0 interactive every
    4th request, p2 batch otherwise); every submitted request is
    appended to the trace.  The replay rebuilds every request purely
    from the records (prompts, sessions, deadlines, priorities — never a
    live RNG) on the recorded arrival offsets.  Gates: the replayed
    offer is BIT-IDENTICAL to the live one (same src ids, sessions,
    classes, deadlines, in order), replay-vs-live p99 and goodput drift
    stay inside tolerance (wide — the 2-core container is noisy; the
    gate catches replays that collapse, not scheduler jitter), the high
    class beats both the aggregate and the batch class (nonzero) in
    BOTH windows, and the low class sheds first at 2x saturation in
    BOTH windows."""
    import tempfile

    from paddle_tpu.reader.loadgen import OpenLoopLoadGen, PrefixMixer
    from paddle_tpu.robustness import traces as _traces
    from paddle_tpu.serving import Request, ServingScheduler

    engine = engine if engine is not None else make_serving_engine(seed)
    wave = _serve_window(engine, _srcs(seed, 24), None, 0.0, seed)
    saturation_rps = engine.max_slots / (wave["mean_service_ms"] / 1e3)
    slo_s = _resolve_slo_s(slo_ms, wave)
    if trace_path is None:
        trace_path = os.path.join(
            tempfile.mkdtemp(prefix="paddle-tpu-trace-"), "window.ptt"
        )
    mixer = PrefixMixer(_V, pool_size=3, prefix_frac=0.5, seed=seed,
                        sessions=4)
    # policy: the batch class sheds EARLIER (slack > 1 inflates its
    # predicted-wait margin), the interactive class holds on LONGER
    shed_slack = {0: 0.7, 2: 1.5}

    def _ledger(reqs, wall):
        served = [r for r in reqs if r.status == "served"]
        lat = [r.t_done - r.t_submit for r in served]
        in_slo = [x for x in lat if x <= slo_s]
        return {
            "n_offered": len(reqs),
            "wall_s": round(wall, 3),
            "statuses": _status_counts(reqs),
            "goodput_frac": round(len(in_slo) / len(reqs), 4),
            "p50_ms": _ms(_pct(lat, 0.50)),
            "p99_ms": _ms(_pct(lat, 0.99)),
            "classes": _class_ledger(reqs, slo_s),
        }

    # --- live window, recorded ------------------------------------------
    live_reqs: List[Any] = []

    def mk(i):
        r = Request(
            mixer.source(i), req_id=f"trace-{seed}-{i}",
            session_id=mixer.session_of(i),
        )
        live_reqs.append(r)
        return r

    writer = _traces.TraceWriter(trace_path, meta={
        "scenario": "trace_replay_drift", "seed": seed,
        "slo_ms": round(slo_s * 1e3, 3),
    })
    with ServingScheduler(engine, class_shed_slack=shed_slack) as sched:
        for s in _srcs(seed, 3):
            sched.generate(s, timeout=60.0)
        t0 = time.perf_counter()
        OpenLoopLoadGen(
            2.0 * saturation_rps, n_requests, mk, seed=seed + 1,
            deadline_s=slo_s,
            priority_of=lambda i: 0 if i % 4 == 0 else 2,
        ).run(lambda r: (writer.record_request(r), sched.submit(r))[-1])
        for r in live_reqs:
            if not r.wait(300):
                raise RuntimeError(f"request {r.req_id} never finalized")
        live_wall = time.perf_counter() - t0
    writer.close()

    # --- replay from the artifact ---------------------------------------
    trace = _traces.read_trace(trace_path)
    replay_reqs: List[Any] = []

    def factory(rec):
        r = Request(
            list(rec["src"]), rec.get("mnt"), req_id=str(rec["id"]),
            deadline_s=rec.get("dl"), session_id=rec.get("sess"),
            priority=int(rec.get("prio", 1)),
        )
        replay_reqs.append(r)
        return r

    with ServingScheduler(engine, class_shed_slack=shed_slack) as sched:
        for s in _srcs(seed, 3):
            sched.generate(s, timeout=60.0)
        t0 = time.perf_counter()
        _traces.TraceReplayLoadGen(trace, request_factory=factory).run(
            sched.submit
        )
        for r in replay_reqs:
            if not r.wait(300):
                raise RuntimeError(f"request {r.req_id} never finalized")
        replay_wall = time.perf_counter() - t0

    live = _ledger(live_reqs, live_wall)
    replay = _ledger(replay_reqs, replay_wall)
    identical_offer = (
        len(replay_reqs) == len(live_reqs)
        and all(
            a.src_ids == b.src_ids
            and a.session_id == b.session_id
            and a.priority == b.priority
            and a.deadline_s == b.deadline_s
            for a, b in zip(live_reqs, replay_reqs)
        )
    )
    g_live, g_rep = live["goodput_frac"], replay["goodput_frac"]
    p99_live, p99_rep = live["p99_ms"], replay["p99_ms"]
    hi_live = live["classes"].get("p0", {})
    lo_live = live["classes"].get("p2", {})
    hi_rep = replay["classes"].get("p0", {})
    lo_rep = replay["classes"].get("p2", {})
    gates = {
        "gate_offer_bit_identical": bool(identical_offer),
        "gate_goodput_drift": bool(abs(g_rep - g_live) <= 0.35),
        "gate_p99_drift": bool(
            p99_live is not None and p99_rep is not None
            and p99_rep <= 3.0 * p99_live + 250.0
        ),
        # RELATIVE on purpose (like the overload scenario's 2x/1x ratio):
        # an absolute floor dies under the lock sanitizer's per-lock
        # overhead, where effective capacity lands far below the wave-
        # calibrated saturation.  The interactive class must beat both
        # the window aggregate and the batch class — and hold NONZERO
        # goodput (a collapsed replay fails here) — in both windows.
        "gate_high_class_goodput": bool(
            hi_live.get("goodput_frac", 0.0)
            >= max(g_live, lo_live.get("goodput_frac", 0.0)) - 1e-9
            and hi_live.get("goodput_frac", 0.0) > 0.0
            and hi_rep.get("goodput_frac", 0.0)
            >= max(g_rep, lo_rep.get("goodput_frac", 0.0)) - 1e-9
            and hi_rep.get("goodput_frac", 0.0) > 0.0
        ),
        # the BATCH class carries the overload: its failure fraction must
        # be at least the interactive class's in both windows
        "gate_low_class_sheds_first": bool(
            hi_live.get("failed_frac", 1.0)
            <= lo_live.get("failed_frac", 0.0) + 1e-9
            and hi_rep.get("failed_frac", 1.0)
            <= lo_rep.get("failed_frac", 0.0) + 1e-9
        ),
    }
    return {
        "scenario": "trace_replay_drift",
        "slo_ms": round(slo_s * 1e3, 3),
        "offered_rps": round(2.0 * saturation_rps, 2),
        "trace_path": trace_path,
        "trace_records": len(trace),
        "arrival": {
            k: round(float(v), 4)
            for k, v in trace.arrival_stats().items()
        },
        "live": live,
        "replay": replay,
        **gates,
        "passed": all(gates.values()),
    }


# ---------------------------------------------------------------------------
# fleet scenarios — real process groups (slow; tests/test_scenarios_e2e.py)
# ---------------------------------------------------------------------------

_DIM = 8
_TASKS_PER_PASS = 12  # 96 records / 4 per chunk = 24 chunks at 2/task
# wide lease on purpose: a scheduling stall on a loaded 2-core box must
# never let the standby depose a HEALTHY leader mid-drill (see
# tests/test_master_failover_e2e.py for the full rationale)
_MASTER_KW = dict(chunks_per_task=2, timeout_s=30.0, worker_timeout_s=10.0,
                  auto_rotate=False, lease_timeout=6.0)


def _fleet_env() -> dict:
    return dict(
        os.environ, JAX_PLATFORMS="cpu", OMP_NUM_THREADS="1",
        OPENBLAS_NUM_THREADS="1", MKL_NUM_THREADS="1",
        PYTHONPATH=_REPO + os.pathsep + os.environ.get("PYTHONPATH", ""),
    )


def _write_linear_dataset(path: str, n: int = 96, seed: int = 0) -> None:
    from paddle_tpu.io import recordio

    rng = np.random.RandomState(seed)
    w_true = rng.randn(_DIM).astype(np.float32)
    recs = []
    for _ in range(n):
        x = rng.randn(_DIM).astype(np.float32)
        recs.append(
            np.concatenate([x, [np.float32(x @ w_true)]])
            .astype(np.float32).tobytes()
        )
    recordio.write_records(path, iter(recs), max_chunk_records=4)


def _spawn_workers(d: str, n: int, passes: int, chaos_env=None):
    procs = []
    for i in range(n):
        env = _fleet_env()
        if chaos_env and i in chaos_env:
            env.update(chaos_env[i])
        procs.append(subprocess.Popen(
            [sys.executable, "-m", "paddle_tpu.trainer.elastic",
             "--dir", os.path.join(d, "ha"), "--worker-id", f"w{i}",
             "--num-passes", str(passes), "--model", "numpy",
             "--model-arg", f"dim={_DIM}", "--model-arg", "lr=0.2",
             "--min-workers", str(n),
             "--checkpoint-dir", os.path.join(d, "ck"),
             "--stats-out", os.path.join(d, "stats-{worker}.json")],
            env=env, stdout=subprocess.DEVNULL, stderr=subprocess.PIPE,
        ))
    return procs


def _collect_workers(d: str, n: int, procs, timeout: float = 240.0):
    """communicate() drains stderr WHILE waiting — a never-read PIPE blocks
    a chatty worker at ~64KB and would deadlock the drill."""
    from paddle_tpu.checkpoint import CheckpointManager
    from paddle_tpu.trainer.elastic import NumpyLinearModel

    rcs, errs = [], {}
    for i, p in enumerate(procs):
        _out, err = p.communicate(timeout=timeout)
        rcs.append(p.returncode)
        errs[i] = err.decode()[-2000:]
    stats = {}
    for i in range(n):
        sp = os.path.join(d, f"stats-w{i}.json")
        if os.path.exists(sp):
            with open(sp) as f:
                stats[i] = json.load(f)
    restored = CheckpointManager(os.path.join(d, "ck")).restore_latest(
        NumpyLinearModel(_DIM).state()
    )
    return rcs, errs, stats, restored


def fleet_reference(workdir: str, n_workers: int = 4,
                    passes: int = 2) -> Dict[str, Any]:
    """Unfaulted reference fleet: the bit-identity target every fleet
    chaos drill diffs its final training parameters against."""
    from paddle_tpu.master_ha import HAMaster

    d = os.path.abspath(workdir)
    os.makedirs(d, exist_ok=True)
    data = os.path.join(d, "data.rio")
    _write_linear_dataset(data)
    ha = HAMaster(os.path.join(d, "ha"), [data], owner_id="ref",
                  **_MASTER_KW)
    ha.start()
    try:
        if not ha.wait_leader(30):
            raise RuntimeError("reference master never took leadership")
        rcs, errs, stats, restored = _collect_workers(
            d, n_workers, _spawn_workers(d, n_workers, passes)
        )
        master_stats = ha.service.stats() if ha.service else None
    finally:
        ha.stop()
    if rcs != [0] * n_workers or restored is None:
        raise RuntimeError(f"reference fleet failed: rcs={rcs} errs={errs}")
    return {
        "params": restored[1],
        "total_acks": sum(s["tasks_done"] for s in stats.values()),
        "master_stats": master_stats,
        "n_workers": n_workers,
        "passes": passes,
    }


def _load_chaos_report(path: str) -> Optional[Dict[str, Any]]:
    """The victim's chaos arming-audit report (robustness/chaos.py writes
    it at process exit when ``PADDLE_TPU_CHAOS_REPORT`` names a path) —
    None when the process died before atexit ran (SIGKILL: expected) or
    never wrote one."""
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def run_fleet_chaos(workdir: str, kill: str = "kill_master",
                    reference: Optional[Dict[str, Any]] = None,
                    n_workers: int = 4, passes: int = 2,
                    slo_ms: Optional[float] = None, seed: int = 0,
                    serve_requests: int = 64,
                    engine=None) -> Dict[str, Any]:
    """The headline drill: a live train+serve mix with a fault fired under
    load.  An elastic fleet trains over the HA master plane; the PARENT
    process serves open-loop traffic with deadlines the whole time;
    ``kill`` selects the fault (``kill_worker``: SIGKILL a worker as it
    takes its 1st task, holding a shard lease; ``kill_master``: SIGKILL
    the subprocess LEADER at its 8th ack, the in-process standby takes
    over warm).  Returns the serving ledger, training accounting,
    recovery time after the fault, and bit-identity vs ``reference``."""
    from paddle_tpu.master_ha import HAMaster, discover_endpoint
    from paddle_tpu.reader.loadgen import OpenLoopLoadGen
    from paddle_tpu.serving import Request, ServingScheduler

    if kill not in ("kill_worker", "kill_master"):
        raise ValueError(f"unknown fleet fault {kill!r}")
    d = os.path.abspath(workdir)
    os.makedirs(d, exist_ok=True)
    if reference is None:
        reference = fleet_reference(
            os.path.join(d, "reference"), n_workers, passes
        )
    drill = os.path.join(d, kill)
    os.makedirs(drill, exist_ok=True)
    chaos_report = os.path.join(drill, "chaos-report.json")
    data = os.path.join(drill, "data.rio")
    _write_linear_dataset(data)
    hadir = os.path.join(drill, "ha")

    # serving plane prewarmed BEFORE the fleet spawns: the measured window
    # must pay dispatch under contention, not XLA under contention
    engine = engine if engine is not None else make_serving_engine(seed)
    wave = _serve_window(engine, _srcs(seed, 24), None, 0.0, seed)
    saturation_rps = wave["n_offered"] / wave["wall_s"]
    slo_s = _resolve_slo_s(slo_ms, wave)

    leader = None
    standby = None
    chaos_env = None
    t_kill = None
    takeover = None
    procs: list = []
    try:
        if kill == "kill_master":
            leader = subprocess.Popen(
                [sys.executable, "-m", "paddle_tpu", "master",
                 "--dir", hadir, "--patterns", data,
                 "--chunks-per-task", "2", "--timeout-s", "30",
                 "--worker-timeout-s", "10", "--lease-timeout", "6",
                 "--chaos", "kill_master@8"],
                env=dict(_fleet_env(),
                         PADDLE_TPU_CHAOS_REPORT=chaos_report),
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT, text=True,
            )
            deadline = time.time() + 60
            while discover_endpoint(hadir) is None:
                if leader.poll() is not None:
                    raise RuntimeError(
                        "leader died early: "
                        + leader.stdout.read()[-2000:]
                    )
                if time.time() > deadline:
                    raise RuntimeError("no leader endpoint appeared")
                time.sleep(0.1)  # lock: allow[C306] supervises a REAL subprocess leader: wall-clock by design, driven end-to-end by the fleet drills
            standby = HAMaster(hadir, [data], owner_id="standby",
                               **_MASTER_KW)
            standby.start()
            deadline = time.time() + 20
            while standby._replica is None:  # warm: replica before workers
                if time.time() > deadline:
                    raise RuntimeError("standby never built a replica")
                time.sleep(0.05)  # lock: allow[C306] waits on the live HA thread's journal tail: wall-clock by design in a process-fleet drill
        else:
            standby = HAMaster(hadir, [data], owner_id="drill",
                               **_MASTER_KW)
            standby.start()
            if not standby.wait_leader(30):
                raise RuntimeError("drill master never took leadership")
            chaos_env = {1: {"PADDLE_TPU_CHAOS": "kill_worker@1",
                             "PADDLE_TPU_CHAOS_REPORT": chaos_report}}

        procs = _spawn_workers(drill, n_workers, passes, chaos_env)

        # a side thread watches the fault's victim process and stamps the
        # kill time the moment SIGKILL lands
        victim = leader if kill == "kill_master" else procs[1]
        kill_stamp: Dict[str, float] = {}

        def _watch_kill():
            while victim.poll() is None:
                time.sleep(0.01)  # lock: allow[C306] stamps the wall-clock moment SIGKILL lands on a real process — the recovery metric's zero point
            kill_stamp["t"] = time.time()

        watcher = threading.Thread(
            target=_watch_kill, name="scenario-kill-watch", daemon=True
        )
        watcher.start()

        # the serve window runs on THIS thread while the fleet trains: one
        # process group, mixed traffic, fault incoming.  The schedule is
        # sized to outlast the fleet and truncated the moment every worker
        # exits (traffic stays live across the whole faulted span)
        reqs: List[Any] = []
        t0 = time.perf_counter()
        with ServingScheduler(engine) as sched:
            for s in _srcs(seed + 6, 3):
                sched.generate(s, timeout=60.0)
            span_s = 120.0
            all_srcs = _srcs(seed + 5, serve_requests)

            def mk(i):
                r = Request(all_srcs[i % len(all_srcs)])
                reqs.append(r)
                return r

            OpenLoopLoadGen(
                max(serve_requests / span_s, 2.0), 10 * serve_requests, mk,
                seed=seed + 5, deadline_s=slo_s,
            ).run(
                sched.submit,
                stop=lambda: all(p.poll() is not None for p in procs),
            )
            for r in reqs:
                if not r.wait(300):
                    raise RuntimeError(f"request {r.req_id} never finalized")
        serve_wall = time.perf_counter() - t0

        watcher.join(180.0)
        if "t" not in kill_stamp:
            raise RuntimeError(f"{kill} chaos never fired")
        t_kill = kill_stamp["t"]
        if victim.returncode != -signal.SIGKILL:
            if victim.returncode == 0:
                # the armed process finished CLEAN: SIGKILL never landed,
                # so the armed point was never consulted.  The victim's
                # exit report (robustness/chaos.py arming audit, written
                # because PADDLE_TPU_CHAOS_REPORT was set) proves it —
                # and an armed-but-never-consulted fault point is a drill
                # FAILURE (the kill coverage silently became a no-op: the
                # drill "passed" without ever exercising the fault), not
                # a scheduling flake to retry away.
                raise RuntimeError(
                    f"{kill} armed but never fired: victim exited 0; "
                    f"chaos report: {_load_chaos_report(chaos_report)!r}"
                )
            raise RuntimeError(
                f"{kill} victim exited {victim.returncode}, not SIGKILL"
            )

        rcs, errs, stats, restored = _collect_workers(
            drill, n_workers, procs
        )
        t_done = time.time()
        if kill == "kill_master":
            if rcs != [0] * n_workers:
                raise RuntimeError(
                    f"fleet did not ride through the bounce: {rcs} {errs}"
                )
            if not standby.is_leader.is_set():
                raise RuntimeError("standby never took over")
            takeover = dict(standby.last_takeover or {})
            recovery_s = takeover["t_leader"] - t_kill
        else:
            if rcs[1] != -signal.SIGKILL:
                raise RuntimeError(f"victim exited {rcs[1]}, not SIGKILL")
            if sorted(c for i, c in enumerate(rcs) if i != 1) != [0] * (n_workers - 1):
                raise RuntimeError(f"survivors failed: {rcs} {errs}")
            # the master requeues the dead worker's lease after one shard
            # timeout; recovery = kill -> fleet completion (upper bound)
            recovery_s = t_done - t_kill
        master_stats = standby.service.stats() if standby.service else None
    finally:
        if standby is not None:
            standby.stop()
        if leader is not None and leader.poll() is None:
            leader.kill()
        if leader is not None:
            leader.communicate()
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.communicate()

    total_acks = sum(s["tasks_done"] for s in stats.values())
    params = restored[1] if restored is not None else None
    bit_identical = params is not None and all(
        np.array_equal(params[k], reference["params"][k])
        for k in ("w", "b")
    )
    served = [r for r in reqs if r.status == "served"]
    lat = [r.t_done - r.t_submit for r in served]
    fail_bad = [
        r for r in reqs if r.status not in ("served", "shed", "timeout")
    ]
    expected_acks = _TASKS_PER_PASS * passes
    zero_recompute = total_acks == expected_acks
    out = {
        "scenario": f"fleet_{kill}",
        "chaos_point": (
            "kill_master@8" if kill == "kill_master" else "kill_worker@1"
        ),
        "n_workers": n_workers,
        "passes": passes,
        "slo_ms": round(slo_s * 1e3, 3),
        "serve": {
            "n_offered": len(reqs),
            "offered_rps": round(len(reqs) / serve_wall, 2)
            if serve_wall > 0 else None,
            "saturation_rps": round(saturation_rps, 2),
            "wall_s": round(serve_wall, 3),
            "statuses": _status_counts(reqs),
            "goodput_frac": round(
                sum(1 for x in lat if x <= slo_s) / len(reqs), 4
            ),
            "p50_ms": _ms(_pct(lat, 0.50)),
            "p95_ms": _ms(_pct(lat, 0.95)),
            "p99_ms": _ms(_pct(lat, 0.99)),
        },
        "recovery_after_fault_s": round(recovery_s, 3),
        # SIGKILL skips atexit, so the victim's arming-audit report being
        # ABSENT here is the expected post-kill state — a present report
        # with zero consultations is the failure raised above
        "chaos_report_after_kill": _load_chaos_report(chaos_report),
        "total_task_acks": total_acks,
        "expected_task_acks": expected_acks,
        "zero_recomputed_tasks": bool(zero_recompute),
        "master_fail_events": (
            master_stats["fail_events"] if master_stats else None
        ),
        "train_params_bit_identical": bool(bit_identical),
        "only_shed_or_timeout_failed": not fail_bad,
        "passed": bool(
            bit_identical and not fail_bad
            and (zero_recompute if kill == "kill_master" else True)
        ),
    }
    if takeover is not None:
        out["takeover"] = {
            k: takeover.get(k) for k in ("warm", "replayed_records",
                                         "takeover_s")
        }
    return out


# ---------------------------------------------------------------------------
# serving fleet drills (serving/router.py): an in-process router frontend
# over REAL `paddle-tpu serve --register` engine subprocesses
# ---------------------------------------------------------------------------

_ENGINE_SLOTS = 2


def _spawn_engine(engine_id: str, router_addr, seed: int = 0, extra=()):
    """One fleet engine subprocess (`paddle-tpu serve --register`) on the
    tiny flagship, BLAS pinned to one thread (the _fleet_env discipline:
    N engines on a small container must not fight over OpenMP pools).
    ``extra``: additional CLI args (bench_fleet_serving passes
    ``--prefix-cache`` for the affinity A/B)."""
    return subprocess.Popen(
        [sys.executable, "-m", "paddle_tpu", "serve",
         "--register", f"{router_addr[0]}:{router_addr[1]}",
         "--engine-id", engine_id,
         "--max-slots", str(_ENGINE_SLOTS), "--hbm-budget-mb", "2",
         "--src-vocab", str(_V), "--trg-vocab", str(_V),
         "--word-dim", str(_E), "--hidden-dim", str(_H),
         "--max-length", str(_MAXLEN), "--seed", str(seed),
         "--drain-timeout-s", "60"] + list(extra),
        env=_fleet_env(), stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        text=True,
    )


def _wait_engines(router, n: int, timeout_s: float = 120.0,
                  procs=()) -> None:
    deadline = time.time() + timeout_s
    while len(router.live_engines()) < n:
        for p in procs:
            if p.poll() is not None:
                _out, err = p.communicate()
                raise RuntimeError(
                    f"engine died before registering (rc {p.returncode}): "
                    f"{err[-2000:]}"
                )
        if time.time() > deadline:
            raise RuntimeError(
                f"only {len(router.live_engines())}/{n} engines registered "
                f"in {timeout_s}s"
            )
        time.sleep(0.05)  # lock: allow[C306] fleet-assembly poll over real subprocesses: wall-clock by design


def _prewarm_fleet(router) -> None:
    """Compile every engine's serve path BEFORE the measured window (the
    bench prewarm discipline, one tier up): each engine's slot rungs are
    exercised through its own data plane, so the drill's latencies and
    EWMAs measure dispatch under routing, not XLA."""
    from paddle_tpu import master as _master
    from paddle_tpu.serving.router import ENGINE_METHODS

    engines = router.fleet_stats()["engines"]
    for k, (eid, view) in enumerate(sorted(engines.items())):
        addr = tuple(view["address"])
        for j, src_len in enumerate((5, 20)):
            # rung 2 as well: two concurrent requests batch on the engine
            def _one(i, n=src_len, a=addr):
                c = _master.Client(a, methods=ENGINE_METHODS,
                                   call_timeout_s=180.0, reconnect_tries=2)
                try:
                    c.serve(f"warm-{a[1]}-{n}-{i}", [2] * n, 4, None, None,
                            None)
                finally:
                    c.close()
            ts = [threading.Thread(target=_one, args=(i,),
                                   name="scenario-fleet-warm", daemon=True)
                  for i in range(_ENGINE_SLOTS)]
            for t in ts:
                t.start()
            for t in ts:
                t.join(240.0)


def _journal_double_serves(journal_path: str) -> int:
    """Count request ids finalized MORE than once in the routing journal —
    the on-disk proof of the zero-double-serve contract."""
    done: Dict[str, int] = {}
    with open(journal_path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if rec.get("t") == "done" and rec.get("req"):
                done[rec["req"]] = done.get(rec["req"], 0) + 1
    return sum(1 for c in done.values() if c > 1)


def run_fleet_serving(workdir: str, n_engines: int = 2,
                      n_requests: int = 36, rate_rps: float = 6.0,
                      slo_ms: Optional[float] = None,
                      seed: int = 0) -> Dict[str, Any]:
    """The serving-fleet kill drill (ISSUE 18): N real engine processes
    behind the affinity router, open-loop deadline traffic, then SIGKILL
    one engine mid-window.  Gates: the dead engine is pruned via lease
    expiry (recovery time bounded), traffic re-routes to the survivors
    (goodput holds — only shed/timeout may fail), the disjoint fleet
    ledger sums to the offered count, and the routing journal finalizes
    every request id EXACTLY once (zero double-serves)."""
    from paddle_tpu.reader.loadgen import OpenLoopLoadGen, PrefixMixer
    from paddle_tpu.serving import FleetClient, Request, Router

    d = os.path.abspath(workdir)
    os.makedirs(d, exist_ok=True)
    journal = os.path.join(d, "journal.jsonl")
    slo_s = (float(slo_ms) / 1e3) if slo_ms and slo_ms > 0 else 30.0
    lease_s = 1.5
    router = Router(
        address=("127.0.0.1", 0), journal_path=journal,
        lease_timeout_s=lease_s, stats_poll_s=0.1,
    )
    procs = []
    reqs: List[Any] = []
    kill_stamp: Dict[str, float] = {}
    try:
        procs = [
            _spawn_engine(f"eng{i}", router.address, seed)
            for i in range(n_engines)
        ]
        _wait_engines(router, n_engines, procs=procs)
        _prewarm_fleet(router)

        mixer = PrefixMixer(_V, pool_size=3, prefix_frac=0.5, seed=seed,
                            sessions=4)

        def mk(i):
            r = Request(
                mixer.source(i), 8, req_id=f"flt-{seed}-{i}",
                session_id=mixer.session_of(i),
            )
            reqs.append(r)
            return r

        victim = procs[0]

        def _kill_mid_window():
            # fire roughly a third into the arrival schedule
            time.sleep((n_requests / rate_rps) / 3.0)
            kill_stamp["t"] = time.time()
            victim.kill()

        killer = threading.Thread(target=_kill_mid_window,
                                  name="scenario-fleet-kill", daemon=True)
        fc = FleetClient(router.address)
        t0 = time.perf_counter()
        try:
            killer.start()
            OpenLoopLoadGen(
                rate_rps, n_requests, mk, seed=seed, deadline_s=slo_s,
            ).run(fc.submit)
            for r in reqs:
                if not r.wait(300):
                    raise RuntimeError(f"request {r.req_id} never finalized")
        finally:
            fc.close()
            killer.join(60.0)
        wall = time.perf_counter() - t0

        # the lease plane prunes the corpse; recovery = SIGKILL -> pruned
        deadline = time.time() + 4 * lease_s + 5.0
        while "eng0" in router.live_engines():
            if time.time() > deadline:
                raise RuntimeError("killed engine never pruned")
            time.sleep(0.02)  # lock: allow[C306] watches a REAL lease expire: wall-clock by design
        recovery_s = time.time() - kill_stamp["t"]
        victim.communicate(timeout=60)
        fleet = router.fleet_stats()
    finally:
        for p in procs:
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)
        survivor_rcs = []
        for p in procs:
            if p.stdout is not None and not p.stdout.closed:
                try:
                    p.communicate(timeout=90)
                except subprocess.TimeoutExpired:
                    p.kill()
                    p.communicate()
            survivor_rcs.append(p.returncode)
        router.close()

    statuses = _status_counts(reqs)
    served = [r for r in reqs if r.status == "served"]
    lat = [r.t_done - r.t_submit for r in served]
    in_slo = [x for x in lat if x <= slo_s]
    fail_bad = [
        r for r in reqs if r.status not in ("served", "shed", "timeout")
    ]
    double_serves = _journal_double_serves(journal)
    ledger_total = sum(fleet["ledger"].values())
    return {
        "scenario": "fleet_serving",
        "n_engines": n_engines,
        "slo_ms": round(slo_s * 1e3, 3),
        "n_offered": len(reqs),
        "offered_rps": round(rate_rps, 2),
        "wall_s": round(wall, 3),
        "statuses": statuses,
        "goodput_frac": round(len(in_slo) / len(reqs), 4) if reqs else None,
        "p50_ms": _ms(_pct(lat, 0.50)),
        "p95_ms": _ms(_pct(lat, 0.95)),
        "p99_ms": _ms(_pct(lat, 0.99)),
        "recovery_after_kill_s": round(recovery_s, 3),
        "reroutes": fleet["reroutes"],
        "duplicates_discarded": fleet["duplicates_discarded"],
        "double_served": double_serves,
        "ledger": fleet["ledger"],
        "ledger_disjoint": ledger_total
        == fleet["ledger"]["served"] + fleet["ledger"]["shed"]
        + fleet["ledger"]["rejected"] + fleet["ledger"]["timeout"]
        + fleet["ledger"]["closed"],
        "survivor_rcs": survivor_rcs[1:],
        "passed": bool(
            not fail_bad
            and double_serves == 0
            and recovery_s <= 4 * lease_s + 5.0
            and len(in_slo) / len(reqs) >= 0.5
            and all(rc == 0 for rc in survivor_rcs[1:])
        ),
    }


def run_fleet_rolling_restart(workdir: str, n_engines: int = 2,
                              n_requests: int = 30, rate_rps: float = 4.0,
                              slo_ms: Optional[float] = None,
                              seed: int = 0) -> Dict[str, Any]:
    """The rolling-restart drill (ISSUE 18): drain+replace EVERY engine
    under live open-loop traffic — replacement registers first, then the
    old engine drains via the router's drain protocol and exits on
    SIGTERM.  Gates: every drain clean and every retired engine exits 0,
    the fleet never drops below N-1 live engines, and no request dies to
    the restart (only served/shed/timeout terminal states)."""
    from paddle_tpu.reader.loadgen import OpenLoopLoadGen, PrefixMixer
    from paddle_tpu.serving import FleetClient, Request, Router

    d = os.path.abspath(workdir)
    os.makedirs(d, exist_ok=True)
    journal = os.path.join(d, "journal.jsonl")
    router = Router(
        address=("127.0.0.1", 0), journal_path=journal,
        lease_timeout_s=2.0, stats_poll_s=0.1,
    )
    procs: Dict[str, Any] = {}
    reqs: List[Any] = []
    min_live = [n_engines]
    stop_sampling = threading.Event()

    def _sample_live():
        while not stop_sampling.is_set():
            min_live[0] = min(min_live[0], len(router.live_engines()))
            time.sleep(0.05)  # lock: allow[C306] samples REAL fleet membership over a wall-clock drill window

    sampler = threading.Thread(target=_sample_live,
                               name="scenario-fleet-sample", daemon=True)
    drains: Dict[str, Any] = {}
    rcs: Dict[str, int] = {}
    try:
        for i in range(n_engines):
            procs[f"eng{i}"] = _spawn_engine(f"eng{i}", router.address, seed)
        _wait_engines(router, n_engines, procs=list(procs.values()))
        _prewarm_fleet(router)
        sampler.start()

        mixer = PrefixMixer(_V, pool_size=3, prefix_frac=0.5, seed=seed,
                            sessions=4)

        def mk(i):
            r = Request(
                mixer.source(i), 8, req_id=f"roll-{seed}-{i}",
                session_id=mixer.session_of(i),
            )
            reqs.append(r)
            return r

        fc = FleetClient(router.address)
        gen_done = threading.Event()
        gen_err: List[BaseException] = []

        def _offer():
            try:
                OpenLoopLoadGen(rate_rps, n_requests, mk, seed=seed).run(
                    fc.submit
                )
            except BaseException as e:  # noqa: BLE001 — reported by the join below
                gen_err.append(e)
            finally:
                gen_done.set()

        offerer = threading.Thread(target=_offer,
                                   name="scenario-fleet-offer", daemon=True)
        t0 = time.perf_counter()
        try:
            offerer.start()
            for i in range(n_engines):
                old = f"eng{i}"
                new = f"eng{n_engines + i}"
                # replacement FIRST: the fleet grows to N+1, drains to N,
                # and never dips below N-1 even transiently
                procs[new] = _spawn_engine(new, router.address, seed)
                _wait_engines(router, n_engines + 1,
                              procs=[procs[new]])
                clean = router.drain_engine(old, timeout_s=90.0)
                drains[old] = clean
                p = procs[old]
                if p.poll() is None:
                    p.send_signal(signal.SIGTERM)
                try:
                    p.communicate(timeout=90)
                except subprocess.TimeoutExpired:
                    p.kill()
                    p.communicate()
                rcs[old] = p.returncode
            offerer.join(300.0)
            if gen_err:
                raise gen_err[0]
            for r in reqs:
                if not r.wait(300):
                    raise RuntimeError(f"request {r.req_id} never finalized")
        finally:
            fc.close()
        wall = time.perf_counter() - t0
        fleet = router.fleet_stats()
    finally:
        stop_sampling.set()
        for p in procs.values():
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)
        for name, p in procs.items():
            if name in rcs:
                continue
            try:
                p.communicate(timeout=90)
            except subprocess.TimeoutExpired:
                p.kill()
                p.communicate()
        router.close()

    statuses = _status_counts(reqs)
    fail_bad = [
        r for r in reqs if r.status not in ("served", "shed", "timeout")
    ]
    double_serves = _journal_double_serves(journal)
    return {
        "scenario": "fleet_rolling_restart",
        "n_engines": n_engines,
        "rotations": n_engines,
        "n_offered": len(reqs),
        "wall_s": round(wall, 3),
        "statuses": statuses,
        "drains_clean": drains,
        "retired_rcs": rcs,
        "min_live_engines": min_live[0],
        "double_served": double_serves,
        "reroutes": fleet["reroutes"],
        "ledger": fleet["ledger"],
        "passed": bool(
            not fail_bad
            and double_serves == 0
            and all(drains.values())
            and all(rc == 0 for rc in rcs.values())
            and min_live[0] >= n_engines - 1
            and statuses["served"] >= 1
        ),
    }


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------

FAST_SCENARIOS = {
    "overload": lambda **kw: scenario_overload(**kw),
    "burst_overload": lambda **kw: scenario_overload(process="burst", **kw),
    "nan_request_under_load": lambda **kw: scenario_chaos_under_load(
        point="nan_request", **kw
    ),
    "slow_client_under_load": lambda **kw: scenario_chaos_under_load(
        point="serve_slow_client", **kw
    ),
    "mixed_train_serve": lambda **kw: scenario_mixed_train_serve(**kw),
    "partition_under_load": lambda **kw: scenario_partition_under_load(**kw),
    "trace_replay_drift": lambda **kw: scenario_trace_replay_drift(**kw),
}

SLOW_SCENARIOS = {
    "fleet_kill_worker": lambda workdir, **kw: run_fleet_chaos(
        workdir, kill="kill_worker", **kw
    ),
    "fleet_kill_master": lambda workdir, **kw: run_fleet_chaos(
        workdir, kill="kill_master", **kw
    ),
    "fleet_serving": lambda workdir, **kw: run_fleet_serving(
        workdir, **kw
    ),
    "fleet_rolling_restart": lambda workdir, **kw: run_fleet_rolling_restart(
        workdir, **kw
    ),
}


def run_scenario(name: str, **kw) -> Dict[str, Any]:
    if name in FAST_SCENARIOS:
        return FAST_SCENARIOS[name](**kw)
    if name in SLOW_SCENARIOS:
        return SLOW_SCENARIOS[name](**kw)
    raise KeyError(
        f"unknown scenario {name!r}; known: "
        f"{sorted(FAST_SCENARIOS) + sorted(SLOW_SCENARIOS)}"
    )
