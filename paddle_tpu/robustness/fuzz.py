"""Coverage-guided chaos-composition fuzzer — seeded fault cocktails.

Every chaos drill before this plane exercised ONE fault shape at a time
(a netem partition, a SIGKILL, a NaN request) against a hand-written
scenario.  Production outages are compositions: a burst arrival wave
lands WHILE the network duplicates frames and a worker stalls.  This
module samples seeded compositions from the existing fault vocabulary
(robustness/chaos.py points x robustness/netem.py link faults x the
open-loop arrival processes), runs each against the invariant set every
plane already promises, and ddmin-shrinks any violation to a minimal
replayable spec — the interleave explorer's shrink/replay contract
(analysis/interleave.py) applied to whole-process fault cocktails.

A composition is a list of declarative **axis items** (each one
independently removable, which is what makes ddmin meaningful):

* ``arrival`` — the offered-load shape: process (poisson / uniform /
  burst) and a rate factor over the engine's calibrated saturation
  (2.0 = overload; the shed-not-collapse regime).
* ``serve_chaos`` — a serving fault point (``nan_request`` /
  ``serve_slow_client``) armed at a sampled occurrence mid-window.
* ``netem`` — a link fault (``net_delay`` / ``net_drop`` / ``net_dup``
  / ``net_corrupt`` / ``net_partition``) on the CLIENT role of a real
  RPC training leg (scenarios._rpc_training_leg) that runs on a side
  thread WHILE the serving window is live.
* ``train_chaos`` — ``worker_hang`` (a bounded stall) on that leg.
* ``checkpoint`` — ``torn_checkpoint`` at save 1 or 2 of a two-save
  CheckpointManager cycle (restore must fall back, never load garbage).

Invariants checked after every composition (violations are strings —
the spec's ``violations`` field):

* every offered request reaches a TERMINAL status and the status ledger
  sums disjointly to the offered count;
* the faulted training leg's final params are BIT-IDENTICAL to an
  unfaulted reference leg and its journal lints clean;
* a torn checkpoint is never restored — ``restore_latest`` falls back
  to the intact step;
* zero leaked framework threads and zero leaked KV pages after
  teardown;
* every ARMED point was actually consulted (the arming audit —
  ``chaos.consult_report``): a composition that never drives its fault
  site proved nothing, and silently proving nothing is itself a bug.

``planted="ledger_skew"`` plants a detectable bookkeeping bug (the
served count over-reports by one, but only under an overload arrival
item) — the canary `make chaos` uses to prove the detect -> shrink ->
replay pipeline end-to-end: the batch must flag it, ddmin must shrink
the composition to the single overload item, and ``--replay`` of the
shrunk spec must reproduce it (exit 0 iff reproduced).
"""

from __future__ import annotations

import json
import os
import random
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

__all__ = [
    "FUZZ_SPEC_VERSION",
    "sample_composition",
    "run_composition",
    "shrink_items",
    "fuzz_batch",
    "replay_fuzz_spec",
    "load_spec",
    "save_spec",
]

FUZZ_SPEC_VERSION = 1

_SERVE_POINTS = ("nan_request", "serve_slow_client")
_NETEM_POINTS = ("net_delay", "net_drop", "net_dup", "net_corrupt",
                 "net_partition")
_RATE_FACTORS = (0.5, 1.0, 2.0)
_PROCESSES = ("poisson", "uniform", "burst")

# per-process caches: the engine's calibrated saturation rate and the
# unfaulted reference training leg (both deterministic, both expensive —
# a 25-composition batch pays each exactly once)
_saturation_cache: Dict[int, float] = {}
_train_ref: Dict[str, Any] = {}


# ---------------------------------------------------------------------------
# sampling
# ---------------------------------------------------------------------------

def sample_composition(rng: random.Random) -> List[Dict[str, Any]]:
    """One seeded composition: the arrival axis always, each fault axis
    with its own probability.  Axis items are plain dicts — declarative,
    JSON-able, independently removable (the ddmin unit)."""
    items: List[Dict[str, Any]] = [{
        "axis": "arrival",
        "process": rng.choice(_PROCESSES),
        "rate_factor": rng.choice(_RATE_FACTORS),
    }]
    if rng.random() < 0.5:
        items.append({
            "axis": "serve_chaos",
            "point": rng.choice(_SERVE_POINTS),
            "occurrence": rng.randint(2, 8),
        })
    if rng.random() < 0.4:
        point = rng.choice(_NETEM_POINTS)
        item = {
            "axis": "netem",
            "point": point,
            "occurrence": rng.randint(2, 10),
        }
        if point == "net_partition":
            item["partition_secs"] = round(rng.uniform(0.5, 1.2), 2)
        items.append(item)
    if rng.random() < 0.3:
        items.append({
            "axis": "train_chaos",
            "point": "worker_hang",
            "occurrence": rng.randint(1, 2),
            "hang_secs": round(rng.uniform(0.5, 1.5), 2),
        })
    if rng.random() < 0.3:
        items.append({
            "axis": "checkpoint",
            "point": "torn_checkpoint",
            "occurrence": rng.randint(1, 2),
        })
    return items


# ---------------------------------------------------------------------------
# the composition runner
# ---------------------------------------------------------------------------

def _saturation_rps(engine, seed: int = 0) -> float:
    """Calibrate (once per engine) the analytical saturation rate the
    rate factors scale — the overload scenario's discipline."""
    key = id(engine)
    if key not in _saturation_cache:
        from paddle_tpu.robustness.scenarios import _serve_window, _srcs

        wave = _serve_window(engine, _srcs(seed, 16), None, 0.0, seed)
        _saturation_cache[key] = (
            engine.max_slots / (wave["mean_service_ms"] / 1e3)
        )
    return _saturation_cache[key]


def _reference_leg(workdir: str) -> Dict[str, Any]:
    """The unfaulted RPC training leg every faulted leg diffs against
    (bit-identity) — computed once per process, chaos disarmed."""
    if not _train_ref:
        from paddle_tpu.robustness.scenarios import _rpc_training_leg

        _train_ref.update(
            _rpc_training_leg(os.path.join(workdir, "reference"), seed=0)
        )
    return _train_ref


def _items_by_axis(items: Sequence[Dict[str, Any]]) -> Dict[str, Dict]:
    """Last item per axis wins (a shrunk spec never holds duplicates;
    a hand-edited one gets deterministic behavior)."""
    out: Dict[str, Dict] = {}
    for it in items:
        out[str(it.get("axis"))] = dict(it)
    return out


def _new_framework_threads(baseline: set) -> List[str]:
    from paddle_tpu.analysis.lock_sanitizer import THREAD_PREFIX

    return sorted(
        t.name for t in threading.enumerate()
        if t.name.startswith(THREAD_PREFIX) and t.name not in baseline
        and t.is_alive()
    )


def run_composition(items: Sequence[Dict[str, Any]], *,
                    engine=None, workdir: Optional[str] = None,
                    planted: Optional[str] = None,
                    n_requests: int = 16) -> Dict[str, Any]:
    """Run one composition and check the invariant set.  Returns
    ``{violations, observed}`` — empty ``violations`` means every plane
    kept its promise under this cocktail.  Deterministic given (items,
    engine state): the same spec replays to the same verdict, which is
    what makes ddmin-shrunk specs regression tests."""
    import numpy as np

    from paddle_tpu import master_journal as _mj
    from paddle_tpu import master_wire as _wire
    from paddle_tpu.reader.loadgen import OpenLoopLoadGen
    from paddle_tpu.robustness import chaos, netem
    from paddle_tpu.robustness.scenarios import (
        _rpc_training_leg,
        _srcs,
        make_serving_engine,
    )
    from paddle_tpu.serving import Request, ServingScheduler, status_counts
    from paddle_tpu.serving.scheduler import TERMINAL_STATUSES

    import tempfile

    if workdir is None:
        workdir = tempfile.mkdtemp(prefix="paddle-tpu-fuzz-")
    os.makedirs(workdir, exist_ok=True)
    engine = engine if engine is not None else make_serving_engine(0)
    axes = _items_by_axis(items)
    violations: List[str] = []
    observed: Dict[str, Any] = {}
    baseline_threads = {t.name for t in threading.enumerate()}

    # --- arm the whole cocktail at once (a composition is CONCURRENT) ---
    arrival = axes.get("arrival", {})
    rate_factor = float(arrival.get("rate_factor", 0.5))
    process = str(arrival.get("process", "uniform"))
    spec_parts: List[str] = []
    hang_secs = None
    for axis in ("serve_chaos", "netem", "train_chaos", "checkpoint"):
        it = axes.get(axis)
        if it:
            spec_parts.append(f"{it['point']}@{int(it['occurrence'])}")
            if "hang_secs" in it:
                hang_secs = float(it["hang_secs"])
    want_train = "netem" in axes or "train_chaos" in axes

    env_keys = ("PADDLE_TPU_CHAOS_HANG_SECS", "PADDLE_TPU_NETEM_ROLE",
                "PADDLE_TPU_NETEM_PARTITION_SECS")
    env_prev = {k: os.environ.get(k) for k in env_keys}
    os.environ["PADDLE_TPU_CHAOS_HANG_SECS"] = str(hang_secs or 1.0)
    if "netem" in axes:
        os.environ["PADDLE_TPU_NETEM_ROLE"] = "client"
        os.environ["PADDLE_TPU_NETEM_PARTITION_SECS"] = str(
            axes["netem"].get("partition_secs", 1.0)
        )
    saturation = _saturation_rps(engine)
    if want_train:
        _reference_leg(workdir)  # built with chaos DISARMED
    _wire.counters.reset()
    netem.reset()
    chaos.arm(",".join(spec_parts))
    faulted: dict = {}
    trainer = None
    try:
        if want_train:
            trainer = threading.Thread(
                target=_rpc_training_leg,
                args=(os.path.join(workdir, "faulted"),),
                kwargs={"seed": 0, "out": faulted},
                name="fuzz-train", daemon=True,
            )
            trainer.start()

        # --- the serving window (no calibration submits: occurrence 1
        # of a serving point must be reachable by a shrunk spec) --------
        reqs: List[Any] = []
        delivered: List[Any] = []
        all_srcs = _srcs(7, n_requests)

        def mk(i):
            # real callbacks: serve_slow_client freezes a client CALLBACK,
            # so the delivery thread needs one to freeze
            r = Request(all_srcs[i % len(all_srcs)],
                        callback=delivered.append)
            reqs.append(r)
            return r

        with ServingScheduler(engine) as sched:
            OpenLoopLoadGen(
                max(rate_factor * saturation, 1.0), n_requests, mk,
                seed=11, process=process, deadline_s=0.4,
            ).run(sched.submit)
            for r in reqs:
                if not r.wait(120):
                    violations.append(f"request_never_finalized:{r.req_id}")
        if trainer is not None:
            trainer.join(180.0)
            if trainer.is_alive():
                violations.append("train_leg_hung")

        # --- checkpoint axis: two saves, torn at the sampled one -------
        if "checkpoint" in axes:
            from paddle_tpu.checkpoint import CheckpointManager

            ckdir = os.path.join(
                workdir, f"ck-{int(time.time() * 1e6) & 0xFFFFFF}"
            )
            mgr = CheckpointManager(ckdir)
            states = {
                1: {"w": np.full(4, 1.0, np.float32)},
                2: {"w": np.full(4, 2.0, np.float32)},
            }
            for step, tree in states.items():
                mgr.save(step, tree)
            got = mgr.restore_latest({"w": np.zeros(4, np.float32)})
            if got is None:
                violations.append("checkpoint_restore_none")
            else:
                step, tree, _extra = got
                want = states.get(step)
                if want is None or not np.array_equal(tree["w"], want["w"]):
                    violations.append(
                        f"torn_checkpoint_restored_garbage:step={step}"
                    )
                torn = int(axes["checkpoint"]["occurrence"])
                if step == torn and torn in states:
                    violations.append(
                        f"restored_the_torn_step:step={step}"
                    )
                observed["checkpoint_restored_step"] = step

        # --- invariants -------------------------------------------------
        counts = status_counts(reqs)
        if planted == "ledger_skew" and rate_factor >= 2.0:
            # the planted canary bug: the served ledger over-reports by
            # one under overload — detectable, shrinkable, replayable
            counts["served"] += 1
        bad_status = [
            f"non_terminal_status:{r.req_id}:{r.status}"
            for r in reqs if r.status not in TERMINAL_STATUSES
        ]
        violations.extend(bad_status)
        if not bad_status and sum(counts.values()) != len(reqs):
            violations.append(
                f"ledger_sum_mismatch:offered={len(reqs)}"
                f":sum={sum(counts.values())}"
            )
        observed["statuses"] = counts
        observed["n_offered"] = len(reqs)

        if want_train:
            ref = _train_ref
            params = faulted.get("params")
            if params is None:
                violations.append("train_leg_no_params")
            elif not all(
                np.array_equal(params[k], ref["params"][k])
                for k in ref["params"]
            ):
                violations.append("train_params_diverged")
            jpath = faulted.get("journal_path")
            if jpath and os.path.exists(jpath):
                for f in _mj.verify_journal(jpath):
                    violations.append(
                        f"journal_lint:{f.get('rule')}:{f.get('message')}"
                    )
            else:
                violations.append("no_surviving_journal")
            observed["train_tasks_done"] = faulted.get("tasks_done")

        # the arming audit: an armed-but-never-consulted point means the
        # composition never drove its fault site — it proved nothing
        report = chaos.consult_report()
        observed["chaos_report"] = report
        for point, rec in report.items():
            if rec["consultations"] == 0:
                violations.append(f"armed_never_consulted:{point}")
    finally:
        chaos.disarm()
        netem.reset()
        for k, v in env_prev.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v

    if engine.pages.n_used != 0:
        violations.append(f"leaked_pages:{engine.pages.n_used}")
    deadline = time.time() + 3.0
    leaked = _new_framework_threads(baseline_threads)
    while leaked and time.time() < deadline:
        time.sleep(0.05)  # lock: allow[C306] teardown grace for exiting scheduler threads in a real drill
        leaked = _new_framework_threads(baseline_threads)
    if leaked:
        violations.append(f"leaked_threads:{','.join(leaked)}")
    return {"violations": violations, "observed": observed}


# ---------------------------------------------------------------------------
# shrink + batch + replay (the interleave explorer's contract)
# ---------------------------------------------------------------------------

def shrink_items(items: Sequence[Dict[str, Any]],
                 fails: Callable[[Sequence[Dict[str, Any]]], bool],
                 max_rounds: int = 64) -> List[Dict[str, Any]]:
    """ddmin over axis items: the smallest sub-list that still violates
    (complement testing with chunk halving, then a greedy single-item
    pass — analysis/interleave.py ``shrink_events`` over a different
    event type)."""
    current = list(items)
    if not fails(current):
        return current  # not reproducible: return as-is, caller decides
    n = 2
    rounds = 0
    while len(current) >= 2 and rounds < max_rounds:
        rounds += 1
        chunk = max(1, len(current) // n)
        reduced = False
        for i in range(0, len(current), chunk):
            cand = current[:i] + current[i + chunk:]
            if cand and fails(cand):
                current = cand
                n = max(n - 1, 2)
                reduced = True
                break
        if not reduced:
            if chunk == 1:
                break
            n = min(n * 2, len(current))
    i = 0
    while i < len(current) and rounds < max_rounds * 2:
        rounds += 1
        cand = current[:i] + current[i + 1:]
        if cand and fails(cand):
            current = cand
        else:
            i += 1
    return current


def _spec(seed: Optional[int], index: Optional[int],
          items: List[Dict[str, Any]], planted: Optional[str],
          violations: List[str]) -> Dict[str, Any]:
    return {
        "version": FUZZ_SPEC_VERSION,
        "kind": "chaos-fuzz",
        "seed": seed,
        "index": index,
        "items": items,
        "planted": planted,
        "violations": violations,
    }


def fuzz_batch(count: int = 25, seed: int = 0, *, engine=None,
               workdir: Optional[str] = None,
               planted: Optional[str] = None, shrink: bool = True,
               n_requests: int = 16,
               log: Optional[Callable[[str], None]] = None,
               ) -> Dict[str, Any]:
    """Run ``count`` seeded compositions (composition ``i`` samples from
    ``random.Random(f"{seed}:{i}")`` — any batch subset replays
    independently, the explorer's seeding discipline).  Stops at the
    first violation; with ``shrink`` the composition is ddmin-minimized
    and returned as a replayable spec."""
    from paddle_tpu.robustness.scenarios import make_serving_engine

    engine = engine if engine is not None else make_serving_engine(0)

    def _run(items):
        return run_composition(items, engine=engine, workdir=workdir,
                               planted=planted, n_requests=n_requests)

    for i in range(int(count)):
        items = sample_composition(random.Random(f"{seed}:{i}"))
        out = _run(items)
        if log is not None:
            log(
                f"composition {i}: "
                f"{'+'.join(it['axis'] for it in items)} -> "
                f"{len(out['violations'])} violation(s)"
            )
        if out["violations"]:
            if shrink:
                items = shrink_items(
                    items, lambda cand: bool(_run(cand)["violations"])
                )
                out = _run(items)
            return {
                "violation_found": True,
                "compositions_run": i + 1,
                "spec": _spec(seed, i, list(items), planted,
                              out["violations"]),
            }
    return {"violation_found": False, "compositions_run": int(count),
            "spec": None}


def replay_fuzz_spec(spec: Dict[str, Any], *, engine=None,
                     workdir: Optional[str] = None) -> Dict[str, Any]:
    """Re-run a shrunk violation spec (``paddle-tpu fuzz --replay``).
    Returns ``{violations, reproduced}`` — ``reproduced`` means the
    replay violated again, the regression-test contract (the CLI exits
    0 iff reproduced)."""
    if spec.get("kind") != "chaos-fuzz":
        raise ValueError(
            f"not a chaos-fuzz spec (kind={spec.get('kind')!r})"
        )
    if spec.get("version") != FUZZ_SPEC_VERSION:
        raise ValueError(
            f"unsupported fuzz spec version {spec.get('version')!r}"
        )
    out = run_composition(
        spec.get("items", ()), engine=engine, workdir=workdir,
        planted=spec.get("planted"),
    )
    return {
        "violations": out["violations"],
        "observed": out["observed"],
        "reproduced": bool(out["violations"]),
    }


def save_spec(spec: Dict[str, Any], path: str) -> None:
    tmp = f"{path}.{os.getpid()}.tmp"
    with open(tmp, "w") as f:
        f.write(json.dumps(spec, indent=1, sort_keys=True) + "\n")
    os.replace(tmp, path)


def load_spec(path: str) -> Dict[str, Any]:
    with open(path) as f:
        return json.load(f)
