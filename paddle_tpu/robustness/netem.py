"""Fault-injecting transport wrapper — the network itself as a chaos point.

The chaos harness (robustness/chaos.py) can kill a process but could not
touch a MESSAGE: production networks delay, drop, duplicate, reorder,
corrupt and partition, and the reference pserver's LightNetwork layer
treats all of that as routine input (retry/timeout over epoll/RDMA,
paddle/pserver/SocketChannel.cpp).  This module arms those faults on the
master RPC plane: :func:`maybe_wrap` wraps a ``multiprocessing.connection``
Connection in a :class:`FaultyConnection` whenever a ``net_*`` chaos point
is armed, so Server/Client/HAClient — and every subprocess fleet that
inherits ``PADDLE_TPU_CHAOS`` through its environment — transparently ride
a hostile network.

Fault points (armed via the ``--chaos`` spec / ``PADDLE_TPU_CHAOS``; the
``@occurrence`` counts egress messages per point, process-wide)::

    net_delay      hold the message for NETEM_DELAY_MS (+ uniform jitter
                   of NETEM_JITTER_MS) before sending
    net_drop       silently discard the message (the peer's deadline path
                   must detect and retry)
    net_dup        send the message TWICE (at-least-once delivery drill:
                   the server must dedupe, the client must discard the
                   stale duplicate reply by sequence number)
    net_reorder    hold the message back and release it AFTER the next one
    net_corrupt    flip a byte inside the wire frame (the CRC must reject;
                   the payload must never deserialize)
    net_drip       bandwidth emulation: sleep len/NETEM_DRIP_KBPS before
                   the message leaves (a 64 KB/s trickle makes a multi-MB
                   payload a multi-second stall)
    net_partition  from the firing consultation on, the link is DOWN for
                   NETEM_PARTITION_SECS in the configured DIRECTION —
                   egress dropped (``send``), ingress discarded (``recv``),
                   or both.  One-sided arming (only one process carries the
                   chaos env) + a single direction = a genuinely ASYMMETRIC
                   partition: requests arrive, replies vanish.

Environment knobs (the ``PADDLE_TPU_CHAOS_HANG_SECS`` convention)::

    PADDLE_TPU_NETEM_DELAY_MS        per-message delay (default 50)
    PADDLE_TPU_NETEM_JITTER_MS       uniform jitter on top (default 0)
    PADDLE_TPU_NETEM_PARTITION_SECS  partition duration (default 2)
    PADDLE_TPU_NETEM_DIRECTION       send | recv | both (default both;
                                     partitions only — per-message faults
                                     inject on egress, the tc-netem model)
    PADDLE_TPU_NETEM_DRIP_KBPS       drip bandwidth (default 64)
    PADDLE_TPU_NETEM_ROLE            client | server | both (default both):
                                     which side of a connection injects —
                                     lets one process drill "responses
                                     lost" vs "requests lost"

Faults are injected ABOVE the transport's own message framing (the frame
bytes are mutated/dropped/replayed whole), so a corrupt message is exactly
what media rot or a buggy middlebox produces: an intact delivery whose
CONTENT is damaged — the master_wire CRC's job.  Partition state is
process-global (a host loses its link, not one socket): a client that
times out, hangs up, and re-dials stays partitioned on the fresh
connection until the window elapses.

Unarmed cost is zero: :func:`maybe_wrap` returns the raw connection
untouched unless a ``net_*`` point is armed at wrap time.
"""

from __future__ import annotations

import logging
import os
import time
from typing import Optional

import numpy as np

from paddle_tpu.analysis.lock_sanitizer import make_lock
from paddle_tpu.master_wire import _Counters
from paddle_tpu.robustness import chaos as _chaos

__all__ = [
    "NETEM_POINTS",
    "FaultyConnection",
    "maybe_wrap",
    "active_points",
    "counters",
    "last_partition_start",
    "reset",
]

_log = logging.getLogger("paddle_tpu.robustness.netem")

NETEM_POINTS = frozenset({
    "net_delay", "net_drop", "net_dup", "net_reorder", "net_corrupt",
    "net_drip", "net_partition",
})

# process-global link state: one partition covers every wrapped connection
# (and every FUTURE connection — a re-dial does not heal a dead link)
_state_lock = make_lock("netem.state")
_partition_until = 0.0
_partition_started = 0.0  # wall-clock stamp drills measure recovery from


def reset() -> None:
    """Clear link state + counters (test/drill teardown)."""
    global _partition_until, _partition_started
    with _state_lock:
        _partition_until = 0.0
        _partition_started = 0.0
    counters.reset()


def last_partition_start() -> float:
    """Wall-clock time the most recent partition began (0.0 = never) —
    the zero point of a drill's recovery-after-partition metric."""
    with _state_lock:
        return _partition_started


def _start_partition(duration_s: float, clock) -> None:
    global _partition_until, _partition_started
    with _state_lock:
        _partition_until = clock() + duration_s
        _partition_started = time.time()
    _log.warning("netem: partition begins for %.2fs", duration_s)


def _partition_active(clock) -> bool:
    with _state_lock:
        return clock() < _partition_until


def active_points() -> frozenset:
    """The armed ``net_*`` subset of the chaos spec."""
    return _chaos.armed_points() & NETEM_POINTS


# the same thread-safe counter table the wire codec uses (one
# implementation; master_wire only imports lock_sanitizer, so no cycle)
counters = _Counters("netem.counters")


def _env_f(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


class FaultyConnection:
    """One wrapped Connection.  Per-message faults inject on EGRESS
    (``send_bytes``) — the tc-netem qdisc model — and the process-global
    partition gates both directions per ``PADDLE_TPU_NETEM_DIRECTION``.

    The wrapper is used under the same single-threaded-per-connection
    discipline as the raw Connection (the server's per-conn handler
    thread; the client's ``_conn_lock``), so per-connection fault state
    (the reorder stash) needs no lock of its own."""

    def __init__(self, conn, role: str, clock=time.monotonic,
                 sleep=time.sleep, seed: Optional[int] = None):
        self._conn = conn
        self._role = role
        self._clock = clock
        self._sleep = sleep
        self._rng = np.random.RandomState(
            int(os.environ.get("PADDLE_TPU_NETEM_SEED", "0"))
            if seed is None else seed
        )
        self._delay_s = _env_f("PADDLE_TPU_NETEM_DELAY_MS", 50.0) / 1e3
        self._jitter_s = _env_f("PADDLE_TPU_NETEM_JITTER_MS", 0.0) / 1e3
        self._partition_s = _env_f("PADDLE_TPU_NETEM_PARTITION_SECS", 2.0)
        self._drip_bps = _env_f("PADDLE_TPU_NETEM_DRIP_KBPS", 64.0) * 1024.0
        self._direction = os.environ.get("PADDLE_TPU_NETEM_DIRECTION", "both")
        self._reorder_stash: Optional[bytes] = None

    # -- direction / partition gates -------------------------------------
    def _partitioned(self, direction: str) -> bool:
        if not _partition_active(self._clock):
            return False
        return self._direction in ("both", direction)

    def _consult_partition(self) -> None:
        """Consulted on EGRESS only (the ``@occurrence`` grammar counts
        messages leaving this process); the ingress paths merely OBSERVE
        the link state the egress consultation established."""
        if _chaos.fire("net_partition") and not _partition_active(self._clock):
            _start_partition(self._partition_s, self._clock)

    # -- egress ----------------------------------------------------------
    def send_bytes(self, data: bytes) -> None:
        self._consult_partition()
        if self._partitioned("send"):
            counters.incr("partition_dropped")
            return  # the link ate it; the peer's deadline path finds out
        if _chaos.fire("net_drop"):
            counters.incr("dropped")
            return
        if _chaos.fire("net_delay"):
            counters.incr("delayed")
            self._sleep(
                self._delay_s + self._jitter_s * float(self._rng.rand())
            )
        if _chaos.fire("net_drip"):
            counters.incr("dripped")
            self._sleep(len(data) / max(self._drip_bps, 1.0))
        if _chaos.fire("net_corrupt"):
            counters.incr("corrupted")
            data = self._flip_byte(data)
        if _chaos.fire("net_reorder") and self._reorder_stash is None:
            counters.incr("reordered")
            self._reorder_stash = bytes(data)
            return  # held back; released after the NEXT message
        self._conn.send_bytes(data)
        if self._reorder_stash is not None:
            stash, self._reorder_stash = self._reorder_stash, None
            self._conn.send_bytes(stash)
        if _chaos.fire("net_dup"):
            counters.incr("duplicated")
            self._conn.send_bytes(data)

    def _flip_byte(self, data: bytes) -> bytes:
        if not data:
            return data
        buf = bytearray(data)
        # aim past the 12-byte wire header when the frame allows: payload
        # rot is the classic case (the CRC catches header rot identically)
        lo = 12 if len(buf) > 13 else 0
        i = int(self._rng.randint(lo, len(buf)))
        buf[i] ^= 0xFF
        return bytes(buf)

    # -- ingress ---------------------------------------------------------
    def _discard_arrivals(self, maxlength: Optional[int]) -> None:
        """Messages that land while the ingress is partitioned were lost
        on the real link: read and drop them so a heal never delivers
        stale traffic."""
        while self._conn.poll(0):
            try:
                self._conn.recv_bytes(maxlength)
            except OSError:
                return  # oversize/closed: the transport already tore it down
            counters.incr("partition_discarded")

    def recv_bytes(self, maxlength: Optional[int] = None) -> bytes:
        while self._partitioned("recv"):
            self._discard_arrivals(maxlength)
            self._sleep(0.02)
        return self._conn.recv_bytes(maxlength)

    def poll(self, timeout: float = 0.0) -> bool:
        deadline = self._clock() + max(timeout or 0.0, 0.0)
        while self._partitioned("recv"):
            self._discard_arrivals(None)
            if self._clock() >= deadline:
                return False
            self._sleep(min(0.02, max(deadline - self._clock(), 0.001)))
        return self._conn.poll(max(deadline - self._clock(), 0.0))

    # -- passthrough -----------------------------------------------------
    def __getattr__(self, name: str):
        # close / fileno / closed / send — everything unfaulted delegates
        return getattr(self._conn, name)


def maybe_wrap(conn, role: str):
    """Wrap ``conn`` when any ``net_*`` chaos point is armed for this
    process AND ``PADDLE_TPU_NETEM_ROLE`` covers ``role`` ("client" dials,
    "server" accepts).  Unarmed: returns ``conn`` untouched — zero cost."""
    if not active_points():
        return conn
    want = os.environ.get("PADDLE_TPU_NETEM_ROLE", "both")
    if want not in ("both", role):
        return conn
    return FaultyConnection(conn, role)
