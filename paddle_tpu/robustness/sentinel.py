"""Divergence sentinel — the host half of the fused health check.

The device half lives in the jitted train step (trainer/step.py): one scalar
``health`` flag (loss AND gradient global-norm both finite) computed inside
the same XLA program as the step, and a per-leaf select that keeps params /
optimizer state / layer state untouched when the flag is down — a non-finite
batch is *skipped*, never applied.  The flag rides the step's metric outputs,
so observing it costs no extra device round-trip: the training loop already
fetches the cost scalar each iteration, and fetch-free loops (multi-step
scan dispatch) fold it across the scan and check every K dispatches.

This class is the judgment layer over those observations:

* **skip accounting** — every down flag bumps ``robustness.skipped_steps``
  (StatSet); ``skip_limit`` consecutive skips declare divergence (the data
  window is poisoned beyond what per-step skipping can absorb).
* **EMA loss-spike detection** — finite but exploding losses never trip the
  finiteness flag; an exponential moving average of the healthy cost plus a
  spike factor catches them: ``patience`` consecutive observations above
  ``spike_factor x EMA`` declare divergence (TensorFlow's user-level
  health-check model, arXiv:1605.08695 §4.4 — the non-blocking signal that
  triggers user-level recovery).

Verdicts: ``"ok"`` | ``"skip"`` (step was dropped on device) |
``"diverged"`` (roll back — see robustness.recovery).
"""

from __future__ import annotations

import logging
import math
from typing import Optional

from paddle_tpu.utils.timers import global_stats

__all__ = ["DivergenceSentinel"]

_log = logging.getLogger("paddle_tpu.robustness")


class DivergenceSentinel:
    def __init__(
        self,
        skip_limit: int = 3,
        ema_decay: float = 0.98,
        spike_factor: float = 4.0,
        spike_patience: int = 3,
        warmup_steps: int = 20,
        min_spike_cost: float = 1e-3,
        stats=None,
    ):
        """warmup_steps: observations before the EMA is trusted (early
        training legitimately moves fast).  min_spike_cost: absolute floor
        under which no cost counts as a spike (a jitter from 1e-6 to 4e-6
        is convergence noise, not divergence)."""
        self.skip_limit = max(int(skip_limit), 1)
        self.ema_decay = float(ema_decay)
        self.spike_factor = float(spike_factor)
        self.spike_patience = max(int(spike_patience), 1)
        self.warmup_steps = int(warmup_steps)
        self.min_spike_cost = float(min_spike_cost)
        self._stats = stats if stats is not None else global_stats
        self.reset()
        # lifetime counters survive reset() — reset clears the *judgment*
        # state after a rollback, not the run's history
        self.total_skipped = 0
        self.total_spikes = 0

    # ------------------------------------------------------------------
    def reset(self) -> None:
        """Forget judgment state (EMA, streaks) — called after a rollback so
        the restored trajectory is judged fresh, not against the diverged
        run's statistics."""
        self.ema: Optional[float] = None
        self._n_obs = 0
        self._skip_streak = 0
        self._spike_streak = 0

    # ------------------------------------------------------------------
    @property
    def steady(self) -> bool:
        """No skip/spike streak in flight — safe to call this state
        'last-good' (the recovery plane refuses to anchor a checkpoint on a
        trajectory that is mid-incident)."""
        return self._skip_streak == 0 and self._spike_streak == 0

    def observe(self, cost: float, healthy: bool) -> str:
        """Fold one step's fetched (cost, health-flag) pair; returns the
        verdict for THIS step."""
        self._n_obs += 1
        if not healthy:
            self._skip_streak += 1
            self.total_skipped += 1
            self._stats.incr("robustness.skipped_steps")
            _log.warning(
                "sentinel: non-finite step skipped on device "
                "(streak %d/%d)", self._skip_streak, self.skip_limit,
            )
            if self._skip_streak >= self.skip_limit:
                return self._diverged(f"skip streak {self._skip_streak}")
            return "skip"
        self._skip_streak = 0
        if not math.isfinite(cost):
            # healthy flag up but fetched cost non-finite: only possible
            # when the sentinel's device half is disabled — treat as a skip
            # that DID apply (no select protected the params)
            self.total_skipped += 1
            self._stats.incr("robustness.skipped_steps")
            return self._diverged("non-finite cost with device half off")
        if (
            self.ema is not None
            and self._n_obs > self.warmup_steps
            and cost > self.min_spike_cost
            and cost > self.spike_factor * self.ema
        ):
            self._spike_streak += 1
            self.total_spikes += 1
            self._stats.incr("robustness.loss_spikes")
            _log.warning(
                "sentinel: loss spike %.6g vs EMA %.6g "
                "(streak %d/%d)", cost, self.ema,
                self._spike_streak, self.spike_patience,
            )
            if self._spike_streak >= self.spike_patience:
                return self._diverged(
                    f"loss spike streak {self._spike_streak}"
                )
            # a spiking cost must not drag the EMA up toward itself —
            # the baseline stays the pre-spike trajectory
            return "ok"
        self._spike_streak = 0
        self.ema = (
            cost
            if self.ema is None
            else self.ema_decay * self.ema + (1.0 - self.ema_decay) * cost
        )
        self._stats.observe("robustness.loss_ema", self.ema)
        return "ok"

    def _diverged(self, why: str) -> str:
        """Declare divergence; the flight recorder (obs plane) dumps the
        last N span events first — the postmortem shows which batches and
        dispatches led into the incident before rollback erases the
        in-memory evidence."""
        from paddle_tpu import obs as _obs

        _obs.flight_dump(f"sentinel-divergence: {why}")
        return "diverged"

    # ------------------------------------------------------------------
    @classmethod
    def from_flags(cls, stats=None) -> "DivergenceSentinel":
        from paddle_tpu.utils import flags as _flags

        return cls(
            skip_limit=_flags.get_flag("sentinel_skip_limit"),
            ema_decay=_flags.get_flag("sentinel_ema_decay"),
            spike_factor=_flags.get_flag("sentinel_spike_factor"),
            spike_patience=_flags.get_flag("sentinel_spike_patience"),
            stats=stats,
        )
