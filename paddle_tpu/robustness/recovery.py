"""Auto-rollback — last-good checkpoints + the master's ``failure_max``
discipline applied to data windows.

The Go master never lets one bad task kill a job: a failing task is retried,
and a task failing more than ``failure_max`` times is discarded and the job
moves on (reference go/master/service.go:308-336 processFailedTask).  This
module is the same policy one level up, applied to *training state*: the
unit of failure is the **data window** — every batch applied since the last
good checkpoint — and the recovery loop is

    diverged  →  restore last-good full state (params + optimizer state +
                 RNG + counters, checkpoint.CheckpointManager)
              →  retry the window (its batches were retained on device)
              →  after ``failure_max`` failures of the SAME window,
                 quarantine it: drop its batches and continue with the
                 stream (``robustness.quarantined_batches``).

The coordinator owns the window buffer, the per-window failure counts, and
the checkpoint cadence bookkeeping; the training driver (trainer/sgd.py)
owns the loop and calls in.
"""

from __future__ import annotations

import logging
from typing import Any, Callable, Dict, List, Optional, Tuple

from paddle_tpu.utils.timers import global_stats

__all__ = ["RecoveryCoordinator"]

_log = logging.getLogger("paddle_tpu.robustness")


class RecoveryCoordinator:
    """Glue between the training loop and a checkpoint.CheckpointManager.

    save_fn(step, extra)  — write a full-state checkpoint (SGD.save_checkpoint
                            bound with the position dict as ``extra``).
    restore_fn()          — restore the latest good checkpoint into the
                            trainer; returns its ``extra`` dict or None when
                            the directory holds no usable checkpoint.
    """

    def __init__(
        self,
        save_fn: Callable[[int, Dict[str, Any]], None],
        restore_fn: Callable[[], Optional[Dict[str, Any]]],
        failure_max: int = 3,
        max_window_batches: int = 256,
        stats=None,
    ):
        self._save = save_fn
        self._restore = restore_fn
        self.failure_max = max(int(failure_max), 1)
        self.max_window_batches = max(int(max_window_batches), 1)
        self._stats = stats if stats is not None else global_stats
        # the current window: batches applied since the last checkpoint
        self._window: List[Tuple[int, int, Any]] = []  # (pass, batch, staged)
        self._window_start: Optional[Tuple[int, int]] = None
        self._window_replayable = True
        self._window_count = 0  # recorded batches incl. past the cap
        # step of the checkpoint that OPENED the current window: a restore
        # landing anywhere else means the anchor was lost (torn newest
        # checkpoint fell back further) and the window is not contiguous
        # with the restored state
        self._anchor_step: Optional[int] = None
        # failure counts per window identity (its start position) — the
        # reference's Task.Epoch, keyed by data range instead of task id
        self._failures: Dict[Tuple[int, int], int] = {}
        self.rollbacks = 0
        self.quarantined = 0
        self.replaying = False

    # -- window bookkeeping ---------------------------------------------
    def record(self, pass_id: int, batch_id: int, staged_batch: Any) -> None:
        """A LIVE batch is about to be applied: retain it for replay.
        Replayed batches are already in the window — don't re-record them."""
        if self._window_start is None:
            self._window_start = (pass_id, batch_id)
        self._window_count += 1
        if not self._window_replayable:
            return
        if len(self._window) >= self.max_window_batches:
            # unbounded retention would pin the whole pass in device memory;
            # past the cap the window can still be *quarantined* (restore +
            # skip forward) but no longer retried batch-for-batch
            _log.warning(
                "recovery: window exceeds %d batches; dropping replay "
                "buffer (divergence now quarantines without retry — "
                "lower checkpoint_period_batches to keep retries)",
                self.max_window_batches,
            )
            self._window.clear()
            self._window_replayable = False
            return
        self._window.append((pass_id, batch_id, staged_batch))

    def checkpoint(self, step: int, extra: Dict[str, Any]) -> None:
        """State at ``extra``'s position is good: persist it and open a new
        window.  Suppressed while a replay is in flight — the window buffer
        must keep meaning 'everything applied since the last checkpoint'."""
        if self.replaying:
            return
        self._save(step, extra)
        self._anchor_step = step
        self._open_new_window()

    def _open_new_window(self) -> None:
        self._window = []
        self._window_start = None
        self._window_replayable = True
        self._window_count = 0

    @property
    def window_len(self) -> int:
        return len(self._window)

    # -- the failure path ------------------------------------------------
    def on_divergence(self) -> Tuple[str, List[Tuple[int, int, Any]]]:
        """Roll back to last-good and rule on the offending window.

        Returns ``("retry", batches)`` — state was restored, re-apply these
        (pass_id, batch_id, staged) tuples before touching the live stream;
        ``("quarantine", [])`` — state was restored, the window is dropped,
        continue with the live stream; ``("none", [])`` — no checkpoint to
        restore (recovery disabled mid-air), continue as-is."""
        extra = self._restore()
        if extra is None:
            _log.error(
                "recovery: divergence with no restorable checkpoint — "
                "continuing without rollback"
            )
            return "none", []
        self.rollbacks += 1
        self._stats.incr("robustness.rollbacks")
        key = self._window_start or (-1, -1)
        self._failures[key] = self._failures.get(key, 0) + 1
        failures = self._failures[key]
        restored_step = int(extra.get("step_count", -1))
        anchor_lost = (
            self._anchor_step is not None
            and restored_step != self._anchor_step
        )
        if anchor_lost:
            # restore_latest fell back PAST the checkpoint that opened this
            # window (torn/corrupt newest): the retained batches are not
            # contiguous with the restored state, so replaying them would
            # silently skip the gap — quarantine instead and continue with
            # the live stream from an older-but-consistent state
            _log.error(
                "recovery: window %s's anchor checkpoint (step %s) is "
                "unrestorable; rolled back to step %d — the window is not "
                "contiguous with the restored state and is QUARANTINED "
                "(%d batches between the checkpoints are also skipped)",
                key, self._anchor_step, restored_step, self._window_count,
            )
            self._anchor_step = restored_step
        if anchor_lost or failures >= self.failure_max or not self._window_replayable:
            n = self._window_count
            self.quarantined += n
            self._stats.incr("robustness.quarantined_batches", n)
            if not anchor_lost:
                _log.error(
                    "recovery: window %s failed %d time(s) — QUARANTINED "
                    "(%d batch(es) dropped%s), training continues past it",
                    key, failures, n,
                    "" if self._window_replayable else ", unreplayable",
                )
            self._open_new_window()
            self.replaying = False
            return "quarantine", []
        _log.warning(
            "recovery: rolled back to last-good (failure %d/%d of window "
            "%s) — retrying %d retained batch(es)",
            failures, self.failure_max, key, len(self._window),
        )
        self.replaying = True
        return "retry", list(self._window)

    def replay_done(self) -> None:
        self.replaying = False

    # -- resume -----------------------------------------------------------
    def resume(self) -> Optional[Dict[str, Any]]:
        """Restore the latest good checkpoint (torn/corrupt ones are walked
        past by the manager); returns its position extra, or None."""
        extra = self._restore()
        if extra is not None:
            self._anchor_step = int(extra.get("step_count", 0))
            self._open_new_window()
        return extra

    @classmethod
    def from_flags(cls, save_fn, restore_fn, stats=None) -> "RecoveryCoordinator":
        from paddle_tpu.utils import flags as _flags

        return cls(
            save_fn,
            restore_fn,
            failure_max=_flags.get_flag("failure_max"),
            stats=stats,
        )
