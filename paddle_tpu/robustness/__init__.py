"""Fault-tolerant training: guard + recovery + chaos.

The reference stack survives failures at every tier — the Go master requeues
timed-out tasks and discards poison tasks after ``failure_max`` retries
(go/master/service.go:80-459), the Go pserver checkpoints optimizer state
with CRC so a restarted shard resumes (go/pserver/service.go:244-303).  This
package closes the same loop for the TPU-native trainer, where there is no
pserver and the whole jit-visible state pytree is the unit of recovery:

* :mod:`~paddle_tpu.robustness.sentinel` — device-fused finiteness flag +
  host-side EMA loss-spike judgment (divergence detection).
* :mod:`~paddle_tpu.robustness.recovery` — rollback to last-good full-state
  checkpoints with the master's failure_max retry/quarantine discipline
  applied to data windows.
* :mod:`~paddle_tpu.robustness.preemption` — SIGTERM/SIGINT → synchronous
  final checkpoint + ``PREEMPTED`` marker; ``--resume`` restores mid-pass.
* :mod:`~paddle_tpu.robustness.chaos` — named fault points (NaN batch, torn
  checkpoint write, SIGKILL at step N, stale HA lease) armed by flag/env,
  proving the above against real injected failures.
"""

from paddle_tpu.robustness import chaos  # noqa: F401
from paddle_tpu.robustness.preemption import (  # noqa: F401
    MARKER_NAME,
    PreemptionGuard,
    clear_marker,
    read_marker,
    write_marker,
)
from paddle_tpu.robustness.recovery import RecoveryCoordinator  # noqa: F401
from paddle_tpu.robustness.sentinel import DivergenceSentinel  # noqa: F401

__all__ = [
    "chaos",
    "DivergenceSentinel",
    "RecoveryCoordinator",
    "PreemptionGuard",
    "MARKER_NAME",
    "write_marker",
    "read_marker",
    "clear_marker",
]
