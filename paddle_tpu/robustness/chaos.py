"""Chaos harness — named fault points for fault-injection testing.

The reference proves its fault tolerance the hard way: the Go master/pserver
tests kill real processes and the trainer requeues/recovers
(go/master/service_internal_test.go, the failure_max discipline of
go/master/service.go:308).  This module is the injection side of that story
for the TPU-native stack: production code consults cheap, normally-inert
fault points, and a test (or an operator running a game-day drill) arms them
through one spec string.

Spec grammar (flag ``chaos`` or env ``PADDLE_TPU_CHAOS``)::

    point[@occurrence][,point[@occurrence]...]

    nan_batch@5        poison the 5th staged training batch with NaN
    torn_checkpoint@2  truncate the 2nd checkpoint's state.npz after write
    kill@12            SIGKILL the process right after train step 12
    stale_lease@3      the HA leader's 3rd lease renewal silently no-ops
    kill_worker@2      SIGKILL an elastic worker as it takes its 2nd task
                       (mid-pass, HOLDING a shard lease: arm on worker k of
                       N via its environment — the kill-one-of-N drill)
    worker_hang@2      an elastic worker freezes (GC pause / NFS stall) on
                       its 2nd task for PADDLE_TPU_CHAOS_HANG_SECS (default
                       20s): registry + shard leases expire underneath it
                       and it must rejoin as a late worker
    kill_master@8      SIGKILL the leader master as the 8th task_finished
                       ack reaches it, BEFORE the transition executes —
                       mid-pass, with journaled state on disk: the standby
                       must take over warm (bounded journal replay, zero
                       recomputed tasks) and absorb the worker's retried
                       ack (arm on the leader candidate's environment)
    nan_request@3      poison the 3rd request submitted to the serving
                       scheduler (a NaN token rides the source ids): the
                       admission validator must REJECT it with an error
                       result — it must never reach the shared decode
                       batch or stall the sequences already in flight
    serve_slow_client@2  the 2nd delivered result's client callback
                       freezes for PADDLE_TPU_CHAOS_HANG_SECS: only the
                       delivery thread stalls — Request.wait() and the
                       decode loop must keep running (slow-consumer
                       isolation drill)
    net_delay / net_drop / net_dup / net_reorder / net_corrupt /
    net_drip / net_partition@N
                       the hostile-network plane (robustness/netem.py):
                       when any net_* point is armed, Server/Client
                       connections wrap in a fault-injecting transport —
                       the occurrence counts EGRESS MESSAGES process-wide,
                       so net_partition@10 severs the link (for
                       PADDLE_TPU_NETEM_PARTITION_SECS, in the
                       PADDLE_TPU_NETEM_DIRECTION) as the 10th message
                       leaves, and net_corrupt@3 bit-flips the 3rd frame
                       (the master_wire CRC must reject it)

Every point can also fire *under live mixed traffic*: the scenario
harness (robustness/scenarios.py, ``paddle-tpu scenario``) arms
``nan_request``/``serve_slow_client`` mid-open-loop-load and
``kill_worker``/``kill_master`` under a training fleet that is serving
concurrently, and reports recovery-time-after-fault — faults-at-rest and
faults-under-load are different drills, and production only ever sees
the second kind.

``@occurrence`` counts *consultations* of that point (1-based); omitting it
means "every time".  Each armed point fires at most once per occurrence —
``fire()`` is exact-match, not ">=", so ``kill@12`` kills exactly at the
12th consultation and a resumed process (whose counter restarts) can be
armed differently via the environment.

Fault points are zero-cost when unarmed: ``fire()`` is a dict lookup on an
empty dict.
"""

from __future__ import annotations

import logging
import os
from typing import Dict, Optional

import numpy as np

__all__ = [
    "arm",
    "disarm",
    "fire",
    "active_spec",
    "armed_points",
    "consult_report",
    "write_report",
    "poison_batch",
    "tear_file",
    "KNOWN_POINTS",
]

_log = logging.getLogger("paddle_tpu.robustness.chaos")

_ENV = "PADDLE_TPU_CHAOS"

# the documented fault surface; arming an unknown point raises so a typo'd
# drill never silently tests nothing
KNOWN_POINTS = frozenset(
    {"nan_batch", "torn_checkpoint", "kill", "stale_lease",
     "kill_worker", "worker_hang", "kill_master",
     "nan_request", "serve_slow_client",
     # the hostile-network plane (robustness/netem.py consults these on
     # every egress message of a wrapped RPC connection)
     "net_delay", "net_drop", "net_dup", "net_reorder", "net_corrupt",
     "net_drip", "net_partition"}
)

# point -> occurrence to fire at (None = every consultation)
_armed: Dict[str, Optional[int]] = {}
# point -> how many times it has been consulted
_counts: Dict[str, int] = {}
# point -> how many times it actually FIRED (the arming-audit ledger:
# an armed point with zero fires at process exit is a drill that
# silently tested nothing — exactly the skew that forced
# fleet_kill_worker's blind auto-retry loop before PR 20)
_fired: Dict[str, int] = {}
# points that already dumped a flight-recorder postmortem (an unoccurrenced
# point fires every consultation; one postmortem per arming is the record)
_flight_dumped: set = set()
_env_loaded = False
_atexit_hooked = False

# a drill parent that SIGKILLs (or expects) its child reads the child's
# consultation report from this file: SIGKILL skips atexit, so a report
# that EXISTS proves the child exited normally — armed-but-unfired in a
# normally-exited victim is the drill failure the audit exists to catch
_REPORT_ENV = "PADDLE_TPU_CHAOS_REPORT"


def _parse(spec: str) -> Dict[str, Optional[int]]:
    out: Dict[str, Optional[int]] = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        name, _, occ = part.partition("@")
        if name not in KNOWN_POINTS:
            raise ValueError(
                f"unknown chaos point {name!r}; known: {sorted(KNOWN_POINTS)}"
            )
        out[name] = int(occ) if occ else None
    return out


def arm(spec: str) -> None:
    """Arm fault points from a spec string (replaces any previous arming).
    Unknown point names raise HERE — a typo'd drill fails at arming, it
    never runs silently testing nothing."""
    global _env_loaded
    _env_loaded = True  # an explicit arm overrides the environment
    _armed.clear()
    _counts.clear()
    _fired.clear()
    _flight_dumped.clear()
    _armed.update(_parse(spec))
    if _armed:
        _log.warning("chaos armed: %s", spec)
        _hook_exit_report()


def disarm() -> None:
    global _env_loaded
    _armed.clear()
    _counts.clear()
    _fired.clear()
    _flight_dumped.clear()
    _env_loaded = True  # stay disarmed even if the env var is set


def active_spec() -> str:
    _load_env()
    return ",".join(
        f"{k}@{v}" if v is not None else k for k, v in sorted(_armed.items())
    )


def armed_points() -> frozenset:
    """The set of currently armed point names (after resolving the
    environment) — robustness/netem.py keys its zero-cost-when-unarmed
    wrap decision on this."""
    _load_env()
    return frozenset(_armed)


def _load_env() -> None:
    """Lazily pick up the ``chaos`` flag once (the flags plane resolves the
    PADDLE_TPU_CHAOS environment variable itself) — subprocess tests arm the
    child through its environment without touching its code."""
    global _env_loaded
    if _env_loaded:
        return
    _env_loaded = True
    try:
        from paddle_tpu.utils import flags as _flags

        spec = _flags.get_flag("chaos")
    except KeyError:  # flag plane not loaded (stripped deployment)
        spec = os.environ.get(_ENV)
    if spec:
        _armed.update(_parse(spec))
        _log.warning("chaos armed from %s: %s", _ENV, spec)
        _hook_exit_report()


def fire(point: str) -> bool:
    """Consult a fault point.  Returns True when the point should inject its
    fault at this consultation.  Unarmed points cost one dict lookup."""
    _load_env()
    if point not in _armed:
        return False
    _counts[point] = _counts.get(point, 0) + 1
    occ = _armed[point]
    hit = occ is None or _counts[point] == occ
    if hit:
        _fired[point] = _fired.get(point, 0) + 1
        _log.warning(
            "chaos point %r firing (consultation %d)", point, _counts[point]
        )
        # flight recorder (obs plane): a firing fault point dumps the last
        # N span events BEFORE the fault lands — kill@N's SIGKILL follows
        # this consultation immediately, so the postmortem timeline is the
        # only record the dead process leaves.  Once per arming: an
        # unoccurrenced point fires every consultation.
        if point not in _flight_dumped:
            _flight_dumped.add(point)
            from paddle_tpu import obs as _obs

            _obs.flight_dump(f"chaos:{point}@{_counts[point]}")
    return hit


# ---------------------------------------------------------------------------
# Arming audit: every armed point accounts for itself at process exit
# ---------------------------------------------------------------------------

def consult_report() -> Dict[str, dict]:
    """Per-armed-point accounting: ``{point: {occurrence, consultations,
    fired}}``.  An armed point with ``consultations == 0`` means the
    code path the drill meant to fault NEVER RAN — the silent skew that
    makes a green drill meaningless; the scenario harness treats it as
    a test failure (robustness/scenarios.py)."""
    _load_env()
    return {
        point: {
            "occurrence": occ,
            "consultations": _counts.get(point, 0),
            "fired": _fired.get(point, 0),
        }
        for point, occ in sorted(_armed.items())
    }


def write_report(path: str) -> Dict[str, dict]:
    """Write :func:`consult_report` as one JSON document (atomic
    replace) — the cross-process face of the audit: a drill parent
    points the child at a path via ``PADDLE_TPU_CHAOS_REPORT`` and
    reads what the child actually consulted after it exits."""
    import json

    report = consult_report()
    tmp = f"{path}.{os.getpid()}.tmp"
    with open(tmp, "w") as f:
        f.write(json.dumps(report, sort_keys=True) + "\n")
    os.replace(tmp, path)
    return report


def _exit_report() -> None:
    """atexit: count fired/unfired armed points into the StatSet plane
    (``chaos/fired`` / ``chaos/unfired`` + per-point counters — the
    stats-out line every CLI summary prints), log one audit line, and
    write the report file when ``PADDLE_TPU_CHAOS_REPORT`` names one.
    A SIGKILL'd process never gets here — an ABSENT report after a
    kill-point drill is the expected signature of a successful kill."""
    if not _armed:
        return
    report = consult_report()
    try:
        from paddle_tpu.utils.timers import global_stats

        for point, rec in report.items():
            if rec["fired"]:
                global_stats.incr("chaos/fired")
                global_stats.incr(f"chaos/fired/{point}")
            else:
                global_stats.incr("chaos/unfired")
                global_stats.incr(f"chaos/unfired/{point}")
    except Exception:  # noqa: BLE001 — exit reporting must never raise
        pass
    unfired = sorted(p for p, rec in report.items() if not rec["fired"])
    _log.warning(
        "chaos exit report: %s%s",
        ",".join(
            f"{p}@{rec['occurrence']}:consulted={rec['consultations']}"
            f":fired={rec['fired']}"
            if rec["occurrence"] is not None else
            f"{p}:consulted={rec['consultations']}:fired={rec['fired']}"
            for p, rec in report.items()
        ),
        f" UNFIRED={unfired}" if unfired else "",
    )
    path = os.environ.get(_REPORT_ENV)
    if path:
        try:
            write_report(path)
        except OSError:
            _log.exception("chaos report %s unwritable", path)


def _hook_exit_report() -> None:
    global _atexit_hooked
    if _atexit_hooked:
        return
    _atexit_hooked = True
    import atexit

    atexit.register(_exit_report)


# ---------------------------------------------------------------------------
# Injection helpers (the code each point runs when it fires)
# ---------------------------------------------------------------------------

def poison_batch(batch):
    """NaN-poison the first floating-point slot of a feed batch (host side,
    pre-device_put) — the inject-NaN-batch fault.  Returns the batch."""
    for key in batch:
        t = batch[key]
        data = t.data if hasattr(t, "data") else t
        arr = np.asarray(data)
        if np.issubdtype(arr.dtype, np.floating):
            arr = arr.copy()
            arr.reshape(-1)[0] = np.nan
            if hasattr(t, "data"):
                t.data = arr
            else:
                batch[key] = arr
            _log.warning("chaos: poisoned batch slot %r with NaN", key)
            return batch
    _log.warning("chaos: nan_batch fired but batch has no float slot")
    return batch


def tear_file(path: str, keep_fraction: float = 0.5) -> None:
    """Truncate a file in place — the torn/partial checkpoint write fault
    (a crash mid-write leaves exactly this on disk)."""
    size = os.path.getsize(path)
    with open(path, "r+b") as f:
        f.truncate(max(int(size * keep_fraction), 1))
    _log.warning("chaos: tore %s to %d/%d bytes",
                 path, max(int(size * keep_fraction), 1), size)


def kill_self() -> None:
    """SIGKILL this process — no handlers, no atexit, no flush (the
    preemption-without-warning fault)."""
    import signal

    _log.warning("chaos: SIGKILL self (pid %d)", os.getpid())
    os.kill(os.getpid(), signal.SIGKILL)


def hang(seconds: Optional[float] = None) -> None:
    """Freeze the caller — the stalled-but-alive worker fault (a GC pause
    or NFS stall long enough that every lease it holds expires).  Duration
    comes from ``PADDLE_TPU_CHAOS_HANG_SECS`` unless given."""
    import time

    if seconds is None:
        seconds = float(os.environ.get("PADDLE_TPU_CHAOS_HANG_SECS", "20"))
    _log.warning("chaos: hanging pid %d for %.1fs", os.getpid(), seconds)
    time.sleep(seconds)
