"""Block-paged decode-state cache — fixed-size HBM blocks + page table.

Ragged in-flight sequences must share ONE compiled decode step; the state
that is ragged per sequence (the attended-over encoder memory — this
model family's "KV cache") therefore lives in fixed-size blocks of a big
HBM pool, and each live sequence owns a row of page ids (its page-table
row).  The compiled step gathers ``pool[page_table]`` — physical layout is
an argument, never a shape — so admitting or retiring a sequence changes
page-table CONTENTS, not compiled shapes (the Ragged Paged Attention
design, arXiv:2604.15464, on XLA gather/scatter instead of a custom
kernel).

Budget discipline is the PR-3 pass-cache rule (reader/pass_cache.py):
capacity is derived up front from an explicit per-device HBM budget, every
allocation is accounted in bytes, and exhaustion is a *refused admission*
(the request waits in queue), never an OOM.  Block 0..n-1 are real; one
extra SCRATCH block absorbs the writes/gathers of padded (dead) rows so
ladder padding never corrupts live state.

Counters ride the StatSet plane: ``serving/pages_alloc``,
``serving/pages_free``, ``serving/alloc_refused``.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

__all__ = ["BlockPagedCache"]


class BlockPagedCache:
    """Host-side allocator + device pool layout for block-paged state.

    ``feature_dims`` maps pool name -> per-token feature width (the NMT
    engine stores two pools: ``enc`` [block, 2H] attention values and
    ``ep`` [block, H] projected score keys).  The device arrays themselves
    are owned by the engine (they are donated through jit every prefill);
    this class owns the free list, the budget math and the page-table
    bookkeeping.

    Sizing rule (README "Serving"): with f32 pools,
    ``bytes_per_block = block_tokens * sum(feature_dims) * 4`` and
    ``n_blocks = budget_bytes // bytes_per_block``; a request of S source
    tokens needs ``ceil(S / block_tokens)`` blocks while in flight.
    """

    def __init__(
        self,
        block_tokens: int,
        feature_dims: Dict[str, int],
        hbm_budget_bytes: Optional[int] = None,
        n_blocks: Optional[int] = None,
        dtype_bytes: int = 4,
        stats=None,
    ):
        from paddle_tpu.utils.timers import global_stats

        if block_tokens < 1:
            raise ValueError("block_tokens must be >= 1")
        self.block_tokens = int(block_tokens)
        self.feature_dims = dict(feature_dims)
        self.bytes_per_block = (
            self.block_tokens * sum(self.feature_dims.values()) * dtype_bytes
        )
        if n_blocks is None:
            if hbm_budget_bytes is None:
                raise ValueError("need hbm_budget_bytes or n_blocks")
            n_blocks = int(hbm_budget_bytes) // self.bytes_per_block
        if n_blocks < 1:
            raise ValueError(
                f"HBM budget {hbm_budget_bytes} holds zero "
                f"{self.bytes_per_block}-byte blocks; raise "
                "serving_hbm_budget_mb or shrink block_tokens"
            )
        self.n_blocks = int(n_blocks)
        self._stats = stats if stats is not None else global_stats
        # LIFO free list: recently freed (still-warm) blocks re-allocate
        # first.  Block ids are stable ints in [0, n_blocks); the shadow
        # set keeps the per-retire double-free check O(1).
        self._free: List[int] = list(range(self.n_blocks - 1, -1, -1))
        self._free_set = set(self._free)

    # -- scratch ---------------------------------------------------------
    @property
    def scratch(self) -> int:
        """The extra pool row (index ``n_blocks``) every padded page id
        points at; its contents are garbage by design and every consumer
        masks it out."""
        return self.n_blocks

    @property
    def pool_rows(self) -> int:
        """Rows each device pool must have: real blocks + the scratch row."""
        return self.n_blocks + 1

    # -- budget ----------------------------------------------------------
    @property
    def n_free(self) -> int:
        return len(self._free)

    @property
    def n_used(self) -> int:
        return self.n_blocks - len(self._free)

    @property
    def used_bytes(self) -> int:
        return self.n_used * self.bytes_per_block

    def pages_for_tokens(self, n_tokens: int) -> int:
        """Blocks a sequence of ``n_tokens`` source tokens occupies."""
        return max(1, -(-int(n_tokens) // self.block_tokens))

    # -- alloc / free ----------------------------------------------------
    def alloc(self, n_pages: int) -> Optional[List[int]]:
        """``n_pages`` block ids, or None when the budget can't cover them
        (admission control: the caller keeps the request queued)."""
        if n_pages > len(self._free):
            self._stats.incr("serving/alloc_refused")
            return None
        pages = [self._free.pop() for _ in range(n_pages)]
        self._free_set.difference_update(pages)
        self._stats.incr("serving/pages_alloc", n_pages)
        return pages

    def free(self, pages: Sequence[int]) -> None:
        for p in pages:
            if not (0 <= p < self.n_blocks):
                raise ValueError(f"freeing foreign block id {p}")
            if p in self._free_set:
                raise ValueError(f"double free of block {p}")
        self._free.extend(pages)
        self._free_set.update(pages)
        self._stats.incr("serving/pages_free", len(pages))

    def summary(self) -> Dict[str, int]:
        return {
            "n_blocks": self.n_blocks,
            "block_tokens": self.block_tokens,
            "bytes_per_block": self.bytes_per_block,
            "n_free": self.n_free,
            "used_bytes": self.used_bytes,
        }
