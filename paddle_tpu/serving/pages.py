"""Block-paged decode-state cache — fixed-size HBM blocks + page table.

Ragged in-flight sequences must share ONE compiled decode step; the state
that is ragged per sequence (the attended-over encoder memory — this
model family's "KV cache") therefore lives in fixed-size blocks of a big
HBM pool, and each live sequence owns a row of page ids (its page-table
row).  The compiled step gathers ``pool[page_table]`` — physical layout is
an argument, never a shape — so admitting or retiring a sequence changes
page-table CONTENTS, not compiled shapes (the Ragged Paged Attention
design, arXiv:2604.15464, on XLA gather/scatter instead of a custom
kernel).

Budget discipline is the PR-3 pass-cache rule (reader/pass_cache.py):
capacity is derived up front from an explicit per-device HBM budget, every
allocation is accounted in bytes, and exhaustion is a *refused admission*
(the request waits in queue), never an OOM.  Block 0..n-1 are real; one
extra SCRATCH block absorbs the writes/gathers of padded (dead) rows so
ladder padding never corrupts live state.

**Copy-on-write prefix sharing (PR 17)** replaces the free/shadow-set
discipline with per-block REFCOUNTS — the ragged-paged-attention
blueprint's shared-prefix blocks as first-class citizens:

* :meth:`alloc` hands out blocks at refcount 1;
* :meth:`share` maps an already-populated block into another page table
  (refcount +1) — N sessions over one warmed prefix hold ONE copy;
* :meth:`release` drops a reference; a block frees only at refcount 0.
  ``retain=True`` parks a refcount-0 block in the RETAINED pool instead
  of the free list: still populated, instantly revivable by a later
  ``share`` (the prefix cache's warm blocks), evicted LRU-first when
  ``alloc`` outgrows the free list — the same ``serving_hbm_budget_mb``
  covers live and retained blocks, retained capacity is free capacity;
* :meth:`cow` gives a writer private copies of any block it shares with
  another reader BEFORE the write (the caller copies the pool rows the
  returned (src, dst) pairs name) — a decode/prefill write can never
  mutate bytes another sequence is attending over.

``free`` remains as the non-retaining release spelling (the PR-10 call
surface).  Counters ride the StatSet plane: ``serving/pages_alloc``,
``serving/pages_free``, ``serving/alloc_refused``, plus the sharing
plane's ``serving/pages_shared``, ``serving/pages_evicted`` and
``serving/pages_cow``.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Callable, Dict, List, Optional, Sequence, Tuple

__all__ = ["BlockPagedCache"]


class BlockPagedCache:
    """Host-side allocator + device pool layout for block-paged state.

    ``feature_dims`` maps pool name -> per-token feature width (the NMT
    engine stores two pools: ``enc`` [block, 2H] attention values and
    ``ep`` [block, H] projected score keys).  The device arrays themselves
    are owned by the engine (they are donated through jit every prefill);
    this class owns the refcounts, the budget math and the page-table
    bookkeeping.

    Sizing rule (README "Serving"): with f32 pools,
    ``bytes_per_block = block_tokens * sum(feature_dims) * 4`` and
    ``n_blocks = budget_bytes // bytes_per_block``; a request of S source
    tokens needs ``ceil(S / block_tokens)`` blocks while in flight —
    shared blocks count ONCE no matter how many page tables map them.

    ``on_evict(block_id)`` (assignable) fires when :meth:`alloc` reclaims
    a retained refcount-0 block — the prefix cache invalidates the entry
    whose bytes just died.
    """

    def __init__(
        self,
        block_tokens: int,
        feature_dims: Dict[str, int],
        hbm_budget_bytes: Optional[int] = None,
        n_blocks: Optional[int] = None,
        dtype_bytes: int = 4,
        stats=None,
    ):
        from paddle_tpu.utils.timers import global_stats

        if block_tokens < 1:
            raise ValueError("block_tokens must be >= 1")
        self.block_tokens = int(block_tokens)
        self.feature_dims = dict(feature_dims)
        self.bytes_per_block = (
            self.block_tokens * sum(self.feature_dims.values()) * dtype_bytes
        )
        if n_blocks is None:
            if hbm_budget_bytes is None:
                raise ValueError("need hbm_budget_bytes or n_blocks")
            n_blocks = int(hbm_budget_bytes) // self.bytes_per_block
        if n_blocks < 1:
            raise ValueError(
                f"HBM budget {hbm_budget_bytes} holds zero "
                f"{self.bytes_per_block}-byte blocks; raise "
                "serving_hbm_budget_mb or shrink block_tokens"
            )
        self.n_blocks = int(n_blocks)
        self._stats = stats if stats is not None else global_stats
        # LIFO free list: recently freed (still-warm) blocks re-allocate
        # first.  Block ids are stable ints in [0, n_blocks); _ref[b] counts
        # the page tables mapping block b (0 = free or retained).
        self._free: List[int] = list(range(self.n_blocks - 1, -1, -1))
        self._ref: List[int] = [0] * self.n_blocks
        # refcount-0 blocks whose bytes are still warm (prefix-cache
        # entries): insertion order IS the LRU order — oldest first out
        self._retained: "OrderedDict[int, None]" = OrderedDict()
        self.on_evict: Optional[Callable[[int], None]] = None

    # -- scratch ---------------------------------------------------------
    @property
    def scratch(self) -> int:
        """The extra pool row (index ``n_blocks``) every padded page id
        points at; its contents are garbage by design and every consumer
        masks it out."""
        return self.n_blocks

    @property
    def pool_rows(self) -> int:
        """Rows each device pool must have: real blocks + the scratch row."""
        return self.n_blocks + 1

    # -- budget ----------------------------------------------------------
    @property
    def n_free(self) -> int:
        return len(self._free)

    @property
    def n_retained(self) -> int:
        """Refcount-0 blocks kept warm for the prefix cache (reclaimable)."""
        return len(self._retained)

    @property
    def n_used(self) -> int:
        """Blocks some page table maps (refcount >= 1).  Retained blocks
        are NOT used: they are evictable capacity, and the SLO gauge
        ``pages_in_use`` must return to 0 when the plane drains even with
        a warm prefix cache."""
        return self.n_blocks - len(self._free) - len(self._retained)

    @property
    def n_shared(self) -> int:
        """Blocks mapped by MORE than one page table right now."""
        return sum(1 for r in self._ref if r >= 2)

    @property
    def used_bytes(self) -> int:
        return self.n_used * self.bytes_per_block

    def refcount(self, block: int) -> int:
        return self._ref[block]

    def pages_for_tokens(self, n_tokens: int) -> int:
        """Blocks a sequence of ``n_tokens`` source tokens occupies."""
        return max(1, -(-int(n_tokens) // self.block_tokens))

    # -- alloc / share / release -----------------------------------------
    def alloc(self, n_pages: int) -> Optional[List[int]]:
        """``n_pages`` block ids at refcount 1, or None when the budget
        can't cover them (admission control: the caller keeps the request
        queued).  The free list drains first; then retained refcount-0
        blocks are EVICTED oldest-first (LRU) — ``on_evict`` fires per
        reclaimed block so the prefix cache drops the dead entry."""
        if n_pages > len(self._free) + len(self._retained):
            self._stats.incr("serving/alloc_refused")
            return None
        pages = []
        for _ in range(n_pages):
            if self._free:
                p = self._free.pop()
            else:
                p, _ = self._retained.popitem(last=False)  # LRU-oldest
                self._stats.incr("serving/pages_evicted")
                if self.on_evict is not None:
                    self.on_evict(p)
            self._ref[p] = 1
            pages.append(p)
        self._stats.incr("serving/pages_alloc", n_pages)
        return pages

    def share(self, pages: Sequence[int]) -> None:
        """Map already-populated blocks into ANOTHER page table: refcount
        +1 each; a retained block revives (leaves the LRU pool).  Sharing
        a free block is a bug — its bytes are undefined — and raises."""
        for p in pages:
            if not (0 <= p < self.n_blocks):
                raise ValueError(f"sharing foreign block id {p}")
            if self._ref[p] == 0 and p not in self._retained:
                raise ValueError(
                    f"sharing free block {p} (undefined contents)"
                )
        for p in pages:
            if self._ref[p] == 0:
                self._retained.pop(p)
            self._ref[p] += 1
        self._stats.incr("serving/pages_shared", len(pages))

    def release(self, pages: Sequence[int], retain: bool = False) -> None:
        """Drop one reference per block; a block frees only at refcount 0.
        ``retain=True`` parks refcount-0 blocks in the warm LRU pool
        (most-recently-released = last out) instead of the free list.
        Releasing a block no table maps (double release / foreign id)
        raises — the double-free discipline, now refcount-exact."""
        for p in pages:
            if not (0 <= p < self.n_blocks):
                raise ValueError(f"freeing foreign block id {p}")
            if self._ref[p] == 0:
                raise ValueError(
                    f"double free of block {p} (refcount already 0)"
                )
        for p in pages:
            self._ref[p] -= 1
            if self._ref[p] == 0:
                if retain:
                    self._retained[p] = None  # appended = most recent
                else:
                    self._free.append(p)
        self._stats.incr("serving/pages_free", len(pages))

    def free(self, pages: Sequence[int]) -> None:
        """The non-retaining release (the PR-10 call surface)."""
        self.release(pages, retain=False)

    def cow(self, pages: Sequence[int]
            ) -> Tuple[Optional[List[int]], List[Tuple[int, int]]]:
        """Copy-on-write: private replacements for every block of
        ``pages`` that another page table also maps (refcount >= 2).
        Returns ``(new_pages, copies)`` — ``new_pages`` is the caller's
        page list with shared blocks swapped for fresh refcount-1 blocks,
        ``copies`` the (src, dst) pairs whose POOL ROWS the caller must
        copy BEFORE writing (the copy half of copy-on-write; this class
        never touches device memory).  ``(None, [])`` when the budget
        can't cover the copies (the write waits, exactly like a refused
        admission).  Exclusively-owned pages come back unchanged."""
        shared = [p for p in pages if self._ref[p] >= 2]
        if not shared:
            return list(pages), []
        fresh = self.alloc(len(shared))
        if fresh is None:
            return None, []
        repl = dict(zip(shared, fresh))
        for p in shared:
            self._ref[p] -= 1  # >= 2 on entry, so never reaches 0 here
        self._stats.incr("serving/pages_cow", len(shared))
        return [repl.get(p, p) for p in pages], [
            (p, repl[p]) for p in shared
        ]

    def summary(self) -> Dict[str, int]:
        return {
            "n_blocks": self.n_blocks,
            "block_tokens": self.block_tokens,
            "bytes_per_block": self.bytes_per_block,
            "n_free": self.n_free,
            "n_retained": self.n_retained,
            "n_shared": self.n_shared,
            "used_bytes": self.used_bytes,
        }
